"""CI smoke: the ``--shards`` fleet serving mode against a REAL
server process on a simulated 8-device mesh.

Boots ``python -m gyeeta_tpu serve --shards 8`` (per-shard ingest
loops + per-shard WAL subdirs + once-per-tick collective roll-up)
under ``XLA_FLAGS=--xla_force_host_platform_device_count=8``, feeds
wire traffic from TWO agents whose sticky hids hash to different
shards, then asserts the MERGED fleet view end-to-end:

- svcstate and topk rows are non-empty and carry BOTH agents' hosts
  (the cross-shard merge actually merged);
- the stock NM edge (sim/nodeweb.py) and the REST gateway render the
  same requests byte-equal (same snapshot tick);
- the per-shard WAL subdirs exist and hold both agents' chunks on
  their layout shards;
- per-shard fold-rate gauges ride the exposition.

Run by ci.sh; standalone: ``JAX_PLATFORMS=cpu python _multichip_smoke.py``.
"""

from __future__ import annotations

import asyncio
import json
import os
import socket
import subprocess
import sys
import tempfile
import time

HERE = os.path.dirname(os.path.abspath(__file__))
N_SHARDS = 8


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def _spawn_server(port: int, tmp: str):
    env = dict(
        os.environ, JAX_PLATFORMS="cpu", GYT_PLATFORM="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count="
                  f"{N_SHARDS}",
        # fresh per-run compile cache: RELOADING a cached shard_map
        # executable is broken on the 0.4.x jaxlib line (see
        # tests/conftest.py) — an always-cold scoped dir never reloads
        JAX_COMPILATION_CACHE_DIR=os.path.join(tmp, "xla_cache"),
        # small mesh geometry: smoke compiles must stay in CI budget
        GYT_N_HOSTS="16", GYT_SVC_CAPACITY="256",
        GYT_TASK_CAPACITY="256", GYT_CONN_BATCH="256",
        GYT_RESP_BATCH="512", GYT_LISTENER_BATCH="64", GYT_FOLD_K="2",
        GYT_DEP_PAIR_CAPACITY="2048", GYT_DEP_EDGE_CAPACITY="1024")
    cmd = [sys.executable, "-m", "gyeeta_tpu", "serve",
           "--host", "127.0.0.1", "--port", str(port),
           "--shards", str(N_SHARDS),
           "--journal-dir", os.path.join(tmp, "wal"),
           "--hostmap", os.path.join(tmp, "hostmap.json"),
           "--tick-interval", "1.0",
           "--handshake-timeout", "5", "--idle-timeout", "600",
           "--stats-interval", "60", "--log-level", "WARNING"]
    return subprocess.Popen(cmd, cwd=HERE, env=env)


async def _wait_ready(port: int, proc, timeout: float = 600.0) -> None:
    from gyeeta_tpu.net.agent import QueryClient
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            raise SystemExit(
                f"server exited early (rc={proc.returncode})")
        try:
            qc = QueryClient(connect_timeout=2.0, request_timeout=30.0)
            await qc.connect("127.0.0.1", port)
            await qc.query({"subsys": "serverstatus"})
            await qc.close()
            return
        except Exception:
            await asyncio.sleep(1.0)
    raise SystemExit("sharded server never became ready")


async def _rest_query(gh, gp, req: dict) -> tuple:
    reader, writer = await asyncio.open_connection(gh, gp)
    body = json.dumps(req).encode()
    writer.write(
        b"POST /query HTTP/1.1\r\nHost: s\r\nConnection: close\r\n"
        + f"Content-Length: {len(body)}\r\n\r\n".encode() + body)
    await writer.drain()
    raw = await reader.read(-1)
    writer.close()
    head, _, rbody = raw.partition(b"\r\n\r\n")
    assert b" 200 " in head.splitlines()[0], head
    return rbody, json.loads(rbody)


async def scenario(port: int, proc, tmp: str) -> None:
    from gyeeta_tpu.net.agent import NetAgent, QueryClient
    from gyeeta_tpu.net.webgw import WebGateway
    from gyeeta_tpu.sim.nodeweb import NodeWebSim

    await _wait_ready(port, proc)
    host = "127.0.0.1"

    # two agents → two sticky hids (0, 1) → different layout shards.
    # Generous dial deadline: the serving loop stalls for minutes while
    # the first tick compiles the mesh programs in a cold process.
    agents = [NetAgent(machine_id=0x5111 + i, seed=3 + i, n_svcs=3,
                       connect_timeout=420.0)
              for i in range(2)]
    hids = []
    for a in agents:
        hids.append(await a.connect(host, port))
        await a.send_sweep(n_conn=192, n_resp=256)
    assert len(set(h % N_SHARDS for h in hids)) == 2, hids

    # wait for a data-carrying merged snapshot on the serving edge
    qc = QueryClient(connect_timeout=5.0, request_timeout=60.0)
    await qc.connect(host, port)
    deadline = time.monotonic() + 600.0
    while time.monotonic() < deadline:
        for a in agents:
            await a.send_sweep(n_conn=64, n_resp=64)
        out = await qc.query({"subsys": "svcstate", "maxrecs": 100})
        hosts_seen = {r["hostid"] for r in out.get("recs", [])}
        if out.get("nrecs", 0) >= 6 and len(hosts_seen) >= 2:
            break
        await asyncio.sleep(1.0)
    else:
        raise SystemExit("merged svcstate never carried both shards")
    assert {float(h) for h in hids} <= hosts_seen, (hids, hosts_seen)

    # NM vs REST byte-equality on the MERGED view (same snapshot tick)
    gw = WebGateway(host, port)
    gh, gp = await gw.start()
    nw = NodeWebSim(hostname="ci-multichip")
    hs = await nw.connect(host, port)
    assert hs["error_code"] == 0, hs
    for subsys in ("svcstate", "topk"):
        ok = False
        for _ in range(12):      # ticks advance under us: align+retry
            nm = await nw.query_web(subsys, maxrecs=50)
            rest_raw, rest = await _rest_query(
                gh, gp, {"subsys": subsys, "maxrecs": 50})
            if nm.get("snaptick") == rest.get("snaptick"):
                assert nm["nrecs"] > 0, f"{subsys}: empty over NM"
                assert json.dumps(nm).encode() == rest_raw, \
                    f"{subsys}: NM vs REST bytes differ"
                ok = True
                break
            await asyncio.sleep(0.3)
        if not ok:
            raise SystemExit(
                f"{subsys}: never aligned NM/REST on one snapshot")

    # per-shard WAL subdirs hold each agent's chunks on its shard
    from gyeeta_tpu.utils import journal as J
    subdirs = J.sharded_subdirs(os.path.join(tmp, "wal"))
    assert len(subdirs) == N_SHARDS, subdirs
    seen_shards = set()
    for s, d in enumerate(subdirs):
        for _seg, _off, _t, hid, _tick, _cid, _chunk in J.read_sealed(
                d, None, None):
            assert hid % N_SHARDS == s, (hid, s)
            seen_shards.add(s)
    assert {h % N_SHARDS for h in hids} <= seen_shards, \
        (hids, seen_shards)

    # per-shard fold gauges + roll-up timing ride the exposition
    _raw, met = await _rest_query(gh, gp, {"subsys": "metrics"})
    text = met["text"]
    assert "gyt_rollup_seconds" in text, "no roll-up timing gauge"
    assert 'gyt_shard_fold_ev_per_sec{shard="0"}' in text, \
        "no per-shard fold gauges"

    await nw.close()
    await gw.stop()
    await qc.close()
    for a in agents:
        await a.close()
    print("multichip smoke: OK — --shards 8 serve, merged "
          f"svcstate ({out['nrecs']} rows, hosts {sorted(hosts_seen)}), "
          "NM/REST byte-equal svcstate+topk, per-shard WAL routed, "
          "per-shard gauges exposed", file=sys.stderr)


def main() -> int:
    tmp = tempfile.mkdtemp(prefix="gyt_multichip_smoke_")
    port = _free_port()
    proc = _spawn_server(port, tmp)
    try:
        asyncio.run(scenario(port, proc, tmp))
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=60)
        except subprocess.TimeoutExpired:
            proc.kill()
        import shutil
        shutil.rmtree(tmp, ignore_errors=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
