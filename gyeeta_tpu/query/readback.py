"""Point-in-time readbacks of live AggState (the ``web_curr_*`` analogue).

Each snapshot function is a single jitted device computation returning a
dense column dict over service rows (or hosts / flows); the host then
filters/serializes. This is the freshness-critical path of the north star
(<1s p99 query freshness): no DB, no RCU walk — a readback of sketch
tensors (ref: live-path triads ``server/gy_mnodehandle.cc:798``).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from gyeeta_tpu.engine import table
from gyeeta_tpu.engine.aggstate import (
    AggState, EngineCfg, CTR_BYTES_SENT, CTR_BYTES_RCVD, CTR_NCONN_CLOSED,
    CTR_DUR_SUM_US,
)
from gyeeta_tpu.sketch import countmin, hyperloglog as hll, loghist, \
    tdigest, topk, windows

DEFAULT_QS = (0.25, 0.5, 0.95, 0.99)


@partial(jax.jit, static_argnums=(0, 2))
def svc_snapshot(cfg: EngineCfg, st: AggState, level: int = 0):
    """Per-service live snapshot at a window level (0=5min, 1=5d, 2=all).

    Returns dense (S,) columns; row validity in ``live``. Quantiles from the
    windowed loghist (the bulk path); all-time digest quantiles alongside
    (the high-accuracy path).
    """
    live = table.live_mask(st.tbl)
    resp_hist = windows.read(st.resp_win, level)
    ctr = windows.read(st.ctr_win, level)
    qs = jnp.asarray(DEFAULT_QS, jnp.float32)
    resp_q_us = loghist.quantiles(resp_hist, cfg.resp_spec, qs)
    td_q_us = tdigest.quantiles_entities(st.svc_td, qs)
    nresp = loghist.counts_total(resp_hist)
    elapsed = jnp.maximum(st.resp_win.tick.astype(jnp.float32), 1.0)
    if level < len(cfg.levels):
        lv = cfg.levels[level] if level >= 0 else None
        span_ticks = 1.0 if lv is None else float(lv.stride_ticks * lv.nslots)
        # before the window fills, the data only covers `elapsed` ticks —
        # dividing by the full span would underreport rates until then
        span_sec = jnp.minimum(elapsed, span_ticks) * 5.0
    else:
        # all-time: elapsed base ticks × 5 s (dynamic, min one tick)
        span_sec = elapsed * 5.0
    return {
        "glob_id_hi": st.tbl.key_hi,
        "glob_id_lo": st.tbl.key_lo,
        "live": live,
        "nresp": nresp,
        "qps": nresp / span_sec,
        "resp_p25_us": resp_q_us[:, 0],
        "resp_p50_us": resp_q_us[:, 1],
        "resp_p95_us": resp_q_us[:, 2],
        "resp_p99_us": resp_q_us[:, 3],
        "td_p50_us": td_q_us[:, 1],
        "td_p95_us": td_q_us[:, 2],
        "td_p99_us": td_q_us[:, 3],
        "bytes_sent": ctr[:, CTR_BYTES_SENT],
        "bytes_rcvd": ctr[:, CTR_BYTES_RCVD],
        "nconn_closed": ctr[:, CTR_NCONN_CLOSED],
        "mean_conn_dur_us": ctr[:, CTR_DUR_SUM_US]
        / jnp.maximum(ctr[:, CTR_NCONN_CLOSED], 1.0),
        "distinct_clients": hll.estimate(st.svc_hll),
        "stats": st.svc_stats,
    }


# ------------------------------------------------ grouped svcstate readback
# The monolithic svcstate_snapshot reads EVERY window's (S, B)
# histograms per call — ~2 s at the 65k north-star geometry on one CPU
# core (VERDICT r4 weak #4). Queries rarely reference every group, so
# the query path reads column GROUPS on demand (cached per state
# version) and computes projection-only groups over just the result
# rows. svcstate_snapshot stays for whole-fleet consumers (history
# snapshots at capacity, scale artifacts).

_QS3 = (0.5, 0.95, 0.99)


@partial(jax.jit, static_argnums=(0,))
def svcstate_base(cfg: EngineCfg, st: AggState):
    """Cheap gauges: ids, liveness, classification, stats panel — no
    histogram/HLL sweeps."""
    return {
        "glob_id_hi": st.tbl.key_hi,
        "glob_id_lo": st.tbl.key_lo,
        "live": table.live_mask(st.tbl),
        "state": st.svc_state,
        "issue": st.svc_issue,
        "hostid": st.svc_host,
        "stats": st.svc_stats,
    }


@partial(jax.jit, static_argnums=(0,))
def svcstate_vol(cfg: EngineCfg, st: AggState):
    """Query volume from the current 5s slab (one (S, B) pass)."""
    from gyeeta_tpu.ingest.decode import STAT_NQRYS

    nqrys = jnp.maximum(loghist.counts_total(st.resp_win.cur),
                        st.svc_stats[:, STAT_NQRYS])
    return {"nqry5s": nqrys, "qps5s": nqrys / 5.0}


@partial(jax.jit, static_argnums=(0,))
def svcstate_cli(cfg: EngineCfg, st: AggState):
    return {"nclients": hll.estimate(st.svc_hll)}


@partial(jax.jit, static_argnums=(0, 2))
def svcstate_qlevel(cfg: EngineCfg, st: AggState, level: int):
    """Latency columns for ONE window level (full capacity)."""
    qs = jnp.asarray(_QS3, jnp.float32)
    h = windows.read(st.resp_win, level)
    q = loghist.quantiles(h, cfg.resp_spec, qs)
    if level == -1:
        return {"resp5s_us": loghist.mean(h, cfg.resp_spec),
                "p95resp5s_us": q[:, 1], "p99resp5s_us": q[:, 2]}
    if level == 0:
        return {"p95resp5m_us": q[:, 1]}
    return {"p50resp5d_us": q[:, 0], "p95resp5d_us": q[:, 1]}


@partial(jax.jit, static_argnums=(0, 3))
def svcstate_qlevel_rows(cfg: EngineCfg, st: AggState, idx, level: int):
    """Latency columns for one level over just rows ``idx`` — the
    row-sliced projection path: the window total is gathered BEFORE
    the (ring + cur) add, so cost scales with len(idx), not capacity.
    ``idx`` is a padded fixed-size int32 array (see api._pad_idx)."""
    qs = jnp.asarray(_QS3, jnp.float32)
    if level == -1:
        h = st.resp_win.cur[idx]
    elif level < len(st.resp_win.totals):
        h = st.resp_win.totals[level][idx] + st.resp_win.cur[idx]
    else:
        h = st.resp_win.alltime[idx] + st.resp_win.cur[idx]
    q = loghist.quantiles(h, cfg.resp_spec, qs)
    if level == -1:
        return {"resp5s_us": loghist.mean(h, cfg.resp_spec),
                "p95resp5s_us": q[:, 1], "p99resp5s_us": q[:, 2]}
    if level == 0:
        return {"p95resp5m_us": q[:, 1]}
    return {"p50resp5d_us": q[:, 0], "p95resp5d_us": q[:, 1]}


@partial(jax.jit, static_argnums=(0,))
def svcstate_vol_rows(cfg: EngineCfg, st: AggState, idx):
    from gyeeta_tpu.ingest.decode import STAT_NQRYS

    nqrys = jnp.maximum(loghist.counts_total(st.resp_win.cur[idx]),
                        st.svc_stats[idx, STAT_NQRYS])
    return {"nqry5s": nqrys, "qps5s": nqrys / 5.0}


@partial(jax.jit, static_argnums=(0,))
def svcstate_cli_rows(cfg: EngineCfg, st: AggState, idx):
    return {"nclients": hll.estimate(
        st.svc_hll._replace(regs=st.svc_hll.regs[idx]))}


@partial(jax.jit, static_argnums=(0,))
def svcstate_snapshot(cfg: EngineCfg, st: AggState):
    """The svcstate-subsystem readback: current 5s window + gauges + the
    semantic classification — the ``web_curr_svcstate`` analogue
    (``server/gy_mnodehandle.cc``), one device program for the fleet."""
    spec = cfg.resp_spec
    qs = jnp.asarray((0.5, 0.95, 0.99), jnp.float32)
    h5 = st.resp_win.cur
    h5m = windows.read(st.resp_win, 0)
    h5d = windows.read(st.resp_win, 1)
    q5 = loghist.quantiles(h5, spec, qs)
    q5m = loghist.quantiles(h5m, spec, qs)
    q5d = loghist.quantiles(h5d, spec, qs)
    from gyeeta_tpu.ingest.decode import STAT_NQRYS
    nqrys = jnp.maximum(loghist.counts_total(h5),
                        st.svc_stats[:, STAT_NQRYS])
    return {
        "glob_id_hi": st.tbl.key_hi,
        "glob_id_lo": st.tbl.key_lo,
        "live": table.live_mask(st.tbl),
        "nqry5s": nqrys,
        "qps5s": nqrys / 5.0,
        "resp5s_us": loghist.mean(h5, spec),
        "p95resp5s_us": q5[:, 1],
        "p99resp5s_us": q5[:, 2],
        "p95resp5m_us": q5m[:, 1],
        "p50resp5d_us": q5d[:, 0],
        "p95resp5d_us": q5d[:, 1],
        "state": st.svc_state,
        "issue": st.svc_issue,
        "hostid": st.svc_host,
        "nclients": hll.estimate(st.svc_hll),
        "stats": st.svc_stats,
    }


@partial(jax.jit, static_argnums=(0, 2))
def flow_snapshot(cfg: EngineCfg, st: AggState, k: int = 64):
    """Heavy-hitter flows by bytes + global distinct-endpoint estimate."""
    f_hi, f_lo, f_bytes = topk.query(st.flow_topk, k)
    return {
        "flow_hi": f_hi,
        "flow_lo": f_lo,
        "flow_bytes": f_bytes,
        "evicted_bytes": st.flow_topk.evicted,
        "distinct_flows": hll.estimate(st.glob_hll),
        "total_bytes": countmin.total(st.cms),
    }


@partial(jax.jit, static_argnums=(0,))
def host_snapshot(cfg: EngineCfg, st: AggState):
    return {"panel": st.host_panel}


@partial(jax.jit, static_argnums=(0,))
def task_snapshot(cfg: EngineCfg, st: AggState):
    """Per-process-group live snapshot (the ``web_curr_aggrtaskstate``
    analogue): gauges + agent classification + learned CPU baseline."""
    cpu_p95 = loghist.quantiles(
        st.task_cpu_hist, cfg.taskcpu_spec,
        jnp.asarray([0.95], jnp.float32))[:, 0]
    return {
        "key_hi": st.task_tbl.key_hi,
        "key_lo": st.task_tbl.key_lo,
        "live": table.live_mask(st.task_tbl),
        "stats": st.task_stats,
        "state": st.task_state,
        "issue": st.task_issue,
        "hostid": st.task_host,
        "comm_hi": st.task_comm_hi,
        "comm_lo": st.task_comm_lo,
        "rel_hi": st.task_rel_hi,
        "rel_lo": st.task_rel_lo,
        "cpu_p95": cpu_p95,
    }


@jax.jit
def dep_edges_snapshot(dep):
    """Dependency-edge columns (svcdependency): one device readback, no
    clustering work (that is :func:`dep_mesh_snapshot`)."""
    from gyeeta_tpu.parallel import depgraph as dg

    es = dg.edges_local(dep)
    return {
        "e_live": table.live_mask(es.tbl),
        "e_cli_hi": es.cli_hi, "e_cli_lo": es.cli_lo,
        "e_cli_svc": es.cli_svc,
        "e_ser_hi": es.ser_hi, "e_ser_lo": es.ser_lo,
        "e_nconn": es.nconn, "e_bytes": es.byts,
    }


@partial(jax.jit, static_argnums=(1,))
def dep_mesh_snapshot(dep, n_iters: int = 16):
    """Mesh-cluster labels over the svc→svc edges (svcmesh): the
    ``coalesce_svc_mesh_clusters`` readout
    (``server/gy_shconnhdlr.cc:5198``). The node table holds up to two
    distinct endpoints per edge, so it is sized 2× the edge slab."""
    from gyeeta_tpu.parallel import depgraph as dg

    es = dg.edges_local(dep)
    node_capacity = 2 * es.nconn.shape[0]
    ntbl, labels, sizes = dg.mesh_clusters(es, node_capacity, n_iters)
    return {
        "n_hi": ntbl.key_hi, "n_lo": ntbl.key_lo,
        "n_mask": table.live_mask(ntbl),
        "n_label": labels, "n_size": sizes,
    }


@partial(jax.jit, static_argnums=(0,))
def trace_snapshot(cfg: EngineCfg, st: AggState):
    """Per-(svc, api) live snapshot: counters + latency percentiles
    (the ``web_curr_tracereq`` analogue; north-star config #5)."""
    qs = jnp.asarray((0.5, 0.95, 0.99), jnp.float32)
    q = loghist.quantiles(st.api_resp_hist, cfg.apiresp_spec, qs)
    return {
        "live": table.live_mask(st.api_tbl),
        "svc_hi": st.api_svc_hi, "svc_lo": st.api_svc_lo,
        "api_hi": st.api_id_hi, "api_lo": st.api_id_lo,
        "proto": st.api_proto,
        "ctr": st.api_ctr,
        "p50_us": q[:, 0], "p95_us": q[:, 1], "p99_us": q[:, 2],
        "hostid": st.api_host,
    }


def svc_rows_to_host(cfg: EngineCfg, snap: dict) -> list[dict]:
    """Device snapshot → list of per-service dicts (live rows only).

    One device→host transfer per column (hoisted), then pure-python row
    assembly — this is on the <1s-freshness query path.
    """
    host = {k: np.asarray(v) for k, v in snap.items()}
    live = host["live"]
    idx = np.nonzero(live)[0]
    gid = (host["glob_id_hi"].astype(np.uint64) << np.uint64(32)) \
        | host["glob_id_lo"].astype(np.uint64)
    scalar_cols = [k for k, v in host.items()
                   if k not in ("glob_id_hi", "glob_id_lo", "live", "stats")
                   and v.ndim == 1]
    out = []
    for i in idx:
        row = {"glob_id": int(gid[i])}
        for k in scalar_cols:
            row[k] = float(host[k][i])
        out.append(row)
    return out
