"""Row-keyed response deltas: push per-tick CHANGES, not re-renders.

A subscribed dashboard (``net/subs.py``) holds the last full response
it was delivered. When ``snaptick`` advances, the pushing tier renders
the query once, diffs the new response against the previous version
row-by-row, and ships only the difference — thousands of dashboards
cost one render + one diff per tick instead of thousands of polls,
and the wire carries rows that CHANGED, not the whole table (the same
"carry the mergeable delta, not the stream" move the ingest edge made
in PR 11).

The contract is **byte-exact reassembly**: applying the event stream
client-side rebuilds a response whose ``json.dumps`` equals the fresh
full render's, byte for byte, at every tick (property-tested in
``tests/test_delta.py``). That forces the format to carry complete
ordering and envelope information:

- ``order``  — the full row-key sequence of the new response (row
  ORDER is part of the response: sort columns move rows every tick);
- ``upsert`` — only the rows that are new or changed, keyed;
- ``env``    — every non-``recs`` envelope field (``nrecs``,
  ``ntotal``, ``snaptick``, …) verbatim;
- ``ekeys``  — the envelope's key order (dict order is part of the
  serialized bytes);
- ``kf``     — the key fields this delta keyed rows by. Identity
  fields are preferred (``svcid``/``hostid``/…: a row that changes
  VALUES still matches its old self, so only its new version ships);
  when a response has no identity fields — or two distinct rows
  collide on them — the delta falls back to whole-row keying
  (``kf="*"``), which is always correct: colliding keys are then
  byte-identical rows, so reassembly cannot pick a wrong one.

Deletes are implicit: a key absent from ``order`` is gone. When the
serialized delta would not beat the full body (churn-heavy ticks), the
pusher sends a ``full`` resync event instead — the ``full=`` escape —
so the wire never pays MORE than polling would.

Events are plain JSON dicts (one ``json.dumps`` away from both the
SSE ``data:`` line and the GYT binary subscription frame):

- ``{"t": "full",  "snaptick": T, "resp": {...}}``
- ``{"t": "delta", "snaptick": T, "base": P, "kf": [...], "order":
  [...], "upsert": {...}, "env": {...}, "ekeys": [...]}``
- ``{"t": "ack",   "snaptick": T}``  (reconnect at the current tick:
  nothing to send yet)

Continuous queries (``query/cq.py``) add three MEMBERSHIP kinds over
the same wire — a standing predicate's match set moving, not a panel
re-ordering. Rows sort by membership key on reassembly (membership is
a set; no ``order`` vector), and the same base-chain rule applies:

- ``{"t": "enter",  "snaptick": T, "base": P, "kf": [...],
  "rows": {key: row, ...}}``   (rows newly matching the predicate)
- ``{"t": "change", "snaptick": T, "base": P, "kf": [...],
  "rows": {key: row, ...}}``   (members whose row bytes changed)
- ``{"t": "leave",  "snaptick": T, "base": P, "kf": [...],
  "keys": [key, ...]}``        (rows that stopped matching / vanished)
"""

from __future__ import annotations

import json
from typing import Optional

# identity-field preference order: stable across ticks, cheap to key.
# Deliberately excludes rank-like fields (a row that moves rank is the
# SAME row) and every value column.
_KEY_FIELDS = ("svcid", "taskid", "cliid", "hostid", "id", "metric",
               "shard", "name", "hostname")


class ResyncRequired(ValueError):
    """A delta arrived whose base version the applier does not hold —
    the subscriber must be resynced with a full event."""


def _dumps(obj) -> str:
    return json.dumps(obj)


def _key_fields_of(rows: list) -> list:
    for r in rows:
        return [f for f in _KEY_FIELDS if f in r]
    return []


def _key_of(row: dict, kf) -> str:
    if kf == "*":
        return json.dumps(row, sort_keys=True, separators=(",", ":"),
                          default=str)
    return json.dumps([row.get(f) for f in kf], separators=(",", ":"),
                      default=str)


def _keyed(rows: list, kf):
    """rows → {key: row}; None on a REAL collision (same key, different
    row). Identical duplicate rows may share a key safely — either copy
    reassembles to the same bytes."""
    out = {}
    for r in rows:
        k = _key_of(r, kf)
        prev = out.get(k)
        if prev is not None and prev != r:
            return None
        out[k] = r
    return out


def full_event(resp: dict) -> dict:
    return {"t": "full", "snaptick": resp.get("snaptick"),
            "resp": resp}


def ack_event(snaptick) -> dict:
    return {"t": "ack", "snaptick": snaptick}


def compute_event(prev: Optional[dict], curr: dict,
                  max_ratio: float = 1.0) -> tuple[dict, int, int]:
    """Diff two full responses → ``(event, event_bytes, full_bytes)``.

    ``prev=None`` (a fresh subscriber) always yields a full event.
    A delta that serializes to ≥ ``max_ratio`` × the full body is
    replaced by a full resync event — the ``full=`` escape."""
    full_bytes = len(_dumps(curr).encode())
    if prev is None:
        ev = full_event(curr)
        return ev, len(_dumps(ev).encode()), full_bytes
    prev_recs = prev.get("recs") or []
    curr_recs = curr.get("recs") or []
    kf = _key_fields_of(curr_recs) or _key_fields_of(prev_recs) or "*"
    prev_map = _keyed(prev_recs, kf)
    curr_map = _keyed(curr_recs, kf)
    if prev_map is None or curr_map is None:
        kf = "*"
        prev_map = _keyed(prev_recs, kf)
        curr_map = _keyed(curr_recs, kf)
    order = [_key_of(r, kf) for r in curr_recs]
    upsert = {k: r for k, r in zip(order, curr_recs)
              if prev_map.get(k) != r}
    ev = {"t": "delta", "snaptick": curr.get("snaptick"),
          "base": prev.get("snaptick"), "kf": kf, "order": order,
          "upsert": upsert,
          "env": {k: v for k, v in curr.items() if k != "recs"},
          "ekeys": list(curr.keys())}
    ev_bytes = len(_dumps(ev).encode())
    if ev_bytes >= max_ratio * full_bytes:
        ev = full_event(curr)
        return ev, len(_dumps(ev).encode()), full_bytes
    return ev, ev_bytes, full_bytes


def apply_event(prev: Optional[dict], event: dict) -> dict:
    """Apply one subscription event client-side → the full response.

    ``full`` replaces wholesale; ``ack`` returns ``prev`` unchanged;
    ``delta`` requires ``prev`` at the delta's ``base`` snaptick —
    anything else raises :class:`ResyncRequired` (the subscriber asks
    again with its last-seen snaptick, or just re-subscribes)."""
    t = event.get("t")
    if t == "full":
        return event["resp"]
    if t == "ack":
        if prev is None:
            raise ResyncRequired("ack with no held version")
        return prev
    if t in ("enter", "change", "leave"):
        return _apply_membership(prev, event)
    if t != "delta":
        raise ValueError(f"unknown subscription event {t!r}")
    if prev is None:
        raise ResyncRequired("delta with no held version")
    if prev.get("snaptick") != event.get("base"):
        raise ResyncRequired(
            f"delta base {event.get('base')} != held "
            f"{prev.get('snaptick')}")
    kf = event["kf"]
    prev_map = _keyed(prev.get("recs") or [], kf) or {}
    upsert = event["upsert"]
    rows = []
    for k in event["order"]:
        r = upsert.get(k, prev_map.get(k))
        if r is None:
            raise ResyncRequired(f"delta references unknown row {k!r}")
        rows.append(r)
    out = {}
    env = event["env"]
    for k in event["ekeys"]:
        out[k] = rows if k == "recs" else env[k]
    return out


def _apply_membership(prev: Optional[dict], event: dict) -> dict:
    """Apply one continuous-query membership event (``enter`` /
    ``change`` / ``leave``) to the held membership response. Same
    base-version contract as ``delta``; reassembled ``recs`` sort by
    membership key and the envelope keeps the held response's key
    order, so chained application stays byte-exact against the hub's
    canonical rendering (``cq.cq_response``)."""
    t = event["t"]
    if prev is None:
        raise ResyncRequired(f"{t} with no held version")
    if prev.get("snaptick") != event.get("base"):
        raise ResyncRequired(
            f"{t} base {event.get('base')} != held "
            f"{prev.get('snaptick')}")
    kf = event.get("kf", prev.get("kf", "*"))
    members = _keyed(prev.get("recs") or [], kf)
    if members is None:
        raise ResyncRequired("held membership rows collide on key")
    if t == "leave":
        for k in event["keys"]:
            if k not in members:
                raise ResyncRequired(f"leave of unknown member {k!r}")
            del members[k]
    else:
        for k, r in event["rows"].items():
            if t == "change" and k not in members:
                raise ResyncRequired(f"change of unknown member {k!r}")
            members[k] = r
    recs = [members[k] for k in sorted(members)]
    out = {}
    for k, v in prev.items():
        if k == "recs":
            out[k] = recs
        elif k == "snaptick":
            out[k] = event.get("snaptick")
        elif k == "nrecs":
            out[k] = len(recs)
        else:
            out[k] = v
    return out
