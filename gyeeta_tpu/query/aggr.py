"""Aggregation queries: groupby + sum/avg/min/max/count/pNN.

The reference builds aggregated SQL per subsystem (``get_select_aggr_query``
+ custom groupby, ``common/gy_query_common.cc:736-754``; per-subsystem
``web_db_aggr_*`` handlers, ``server/gy_mnodehandle.cc:1083``). Here one
aggregation engine serves both execution paths:

- **live**: the filtered columnar snapshot is grouped host-side (numpy per
  group) — the live path is already one device readback, aggregation is
  arithmetic on its columns;
- **historical**: exact-translatable queries push SUM/AVG/MIN/MAX/COUNT +
  GROUP BY down into partition SQL; percentile ops or inexact filters fall
  back to fetching the filtered rows and running the *same* numpy
  aggregator — one semantics, two speeds (the dual-execution discipline of
  ``common/gy_query_criteria.h`` extended to aggregation).

Spec syntax (JSON): ``{"aggr": ["avg(qps5s)", "p95(p95resp5s) as p",
"count(*)"], "groupby": ["hostid"], "step": 300}`` — ``step`` (historical
only) buckets time into N-second groups, the reference's downsampling
interval.
"""

from __future__ import annotations

import collections
import re
from typing import NamedTuple, Optional

import numpy as np

from gyeeta_tpu.query import fieldmaps

_SPEC_RE = re.compile(
    r"^\s*(sum|avg|min|max|count|p(\d{1,2}(?:\.\d+)?))"
    r"\(\s*(\*|\w+)\s*\)"
    r"(?:\s+as\s+(\w+))?\s*$", re.IGNORECASE)

# ops with a direct sqlite form (percentiles are numpy-only)
_SQL_OPS = {"sum": "SUM", "avg": "AVG", "min": "MIN", "max": "MAX",
            "count": "COUNT"}


class AggrSpec(NamedTuple):
    op: str                  # sum|avg|min|max|count|pNN
    field: str               # json field name, or "*" (count only)
    alias: str               # output column name
    pct: Optional[float] = None


def parse_aggr(spec: str, subsys: str) -> AggrSpec:
    m = _SPEC_RE.match(spec)
    if not m:
        raise ValueError(
            f"bad aggregation {spec!r}; want op(field) [as alias] with op "
            f"in sum/avg/min/max/count/pNN")
    op, pct, field, alias = m.groups()
    op = op.lower()
    fmap = fieldmaps.field_map(subsys)
    if field == "*":
        if not op.startswith("count"):
            raise ValueError(f"{spec!r}: only count(*) may use '*'")
    else:
        fd = fmap.get(field)
        if fd is None:
            raise ValueError(f"unknown field {field!r} in {spec!r}")
        if op != "count" and fd.kind not in ("num", "bool"):
            raise ValueError(
                f"{spec!r}: cannot {op} over non-numeric field {field!r}")
    pctv = float(pct) if pct else None
    if pctv is not None:
        op = "pct"
    return AggrSpec(op=op, field=field,
                    alias=alias or spec.strip().replace(" ", ""),
                    pct=pctv)


def parse_groupby(groupby, subsys: str) -> tuple:
    fmap = fieldmaps.field_map(subsys)
    out = []
    for g in groupby or ():
        if g == "time":          # historical step-bucket pseudo-column
            out.append(g)
            continue
        if g not in fmap:
            raise ValueError(f"unknown groupby field {g!r}")
        out.append(g)
    return tuple(out)


def _apply(spec: AggrSpec, vals: np.ndarray) -> float:
    if spec.op == "count":
        return float(len(vals))
    if len(vals) == 0:
        return 0.0
    v = vals.astype(np.float64)
    if spec.op == "sum":
        return float(np.sum(v))
    if spec.op == "avg":
        return float(np.mean(v))
    if spec.op == "min":
        return float(np.min(v))
    if spec.op == "max":
        return float(np.max(v))
    if spec.op == "pct":
        return float(np.percentile(v, spec.pct))
    raise AssertionError(spec.op)


def aggregate_rows(rows: list, specs: list, groupby: tuple) -> list:
    """Group + aggregate row dicts (shared by live & history fallback).

    ``rows`` values are presentation-domain (enum strings etc.); groupby
    labels pass through as-is, aggregated fields must be numeric.
    """
    groups = collections.defaultdict(list)
    for r in rows:
        key = tuple(r.get(g) for g in groupby)
        groups[key].append(r)
    if not groups and not groupby:
        # global aggregate over zero rows still yields one row (SQL
        # aggregate-without-GROUP-BY semantics; _apply gives the zeros)
        groups[()] = []
    out = []
    for key, members in groups.items():
        rec = dict(zip(groupby, key))
        for s in specs:
            if s.field == "*":
                rec[s.alias] = float(len(members))
                continue
            vals = np.array([m[s.field] for m in members
                             if m.get(s.field) is not None], np.float64)
            rec[s.alias] = _apply(s, vals)
        out.append(rec)
    return out


def aggregate_columns(cols: dict, idx: np.ndarray, specs: list,
                      groupby: tuple, fmap: dict) -> list:
    """Columnar group-aggregate over selected row indices (live path)."""
    if groupby:
        keycols = [np.asarray(cols[fmap[g].col])[idx] for g in groupby]
        keys = list(zip(*[k.tolist() for k in keycols])) \
            if keycols else [()] * len(idx)
    else:
        keys = [()] * len(idx)
    groups = collections.defaultdict(list)
    for pos, k in enumerate(keys):
        groups[k].append(pos)
    if not groups and not groupby:
        # one zero row for a global aggregate over zero matches — the SQL
        # path and aggregate_rows agree on this shape
        groups[()] = []
    out = []
    for key, members in groups.items():
        rec = {}
        for g, kv in zip(groupby, key):
            fd = fmap[g]
            rec[g] = fd.to_json(kv) if fd.to_json else kv
        sel = idx[np.asarray(members, np.int64)]
        for s in specs:
            if s.field == "*":
                rec[s.alias] = float(len(sel))
                continue
            vals = np.asarray(cols[fmap[s.field].col])[sel]
            rec[s.alias] = _apply(s, vals.astype(np.float64))
        out.append(rec)
    return out


def sql_pushdown(specs: list, groupby: tuple, step: Optional[float],
                 bucket_expr: Optional[str] = None):
    """(select_exprs, group_exprs) for the exact-SQL fast path, or None
    when any op needs numpy (percentiles). ``bucket_expr`` is the
    backend's floor-division time-bucket SQL (CAST truncates in sqlite
    but ROUNDS in Postgres — each store supplies the form that floors,
    matching the numpy path's ``time // step * step``)."""
    sel, grp = [], []
    for g in groupby:
        if g == "time":
            if not step:
                raise ValueError("groupby 'time' needs a 'step' seconds")
            expr = (bucket_expr or
                    "CAST(time/{step} AS INTEGER)*{step}").format(
                step=float(step))
            sel.append(f"{expr} AS time")
            grp.append(expr)
        else:
            sel.append(g)
            grp.append(g)
    for s in specs:
        if s.op not in _SQL_OPS:
            return None
        arg = "*" if s.field == "*" else s.field
        sel.append(f"{_SQL_OPS[s.op]}({arg}) AS \"{s.alias}\"")
    return sel, grp
