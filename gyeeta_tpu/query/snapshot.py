"""Snapshot-isolated query serving: per-tick immutable engine views.

Every query edge used to walk the LIVE runtime under the fold loop —
each live query called ``flush()`` (a device dispatch), and a dashboard
fleet therefore stalled the fold while the fold stalled query p99. The
reference serves queries from incrementally-maintained in-memory tables
decoupled from ingest (``server/gy_mnodehandle.cc`` web queries walk
existing maps); sPIN makes the same argument from the streaming side —
the ingest path must never absorb request-processing stalls.

:class:`EngineSnapshot` is the decoupling point: each tick publishes a
frozen view of the folded engine — the state pytree and dep graph
COPIED out of the fold's donation domain (one non-donating device
dispatch per publish; every ``state -> state`` fold donates its input,
so a snapshot that merely aliased the live buffers would dereference
deleted memory after the next dispatch), plus a snapshot-scoped
:class:`~gyeeta_tpu.utils.colcache.ColumnCache` and a result cache
keyed by the normalized request. The runtime swaps ``rt.snapshot`` —
a plain attribute store, atomic under the GIL — so queries on worker
threads keep reading snapshot N while the fold builds N+1: the classic
double buffer, paid once per tick instead of once per query.

Thread model: snapshot state/dep are immutable after publish, so device
readbacks from any number of query threads are safe (jax dispatch is
thread-safe; the buffers are never donated). Host-side registries stay
live-shared — their renders run under the runtime's registry lock and
are memoized per snapshot, so a tick's worth of dashboard traffic pays
each render once. Result-cache invalidation is by replacement: a new
tick publishes a new snapshot (fresh caches); CRUD and restore clear or
replace the current one (``on_mutation`` / re-publish).
"""

from __future__ import annotations

import collections
import threading

import numpy as np

from gyeeta_tpu.query import api

# registry-backed renders race host-side mutators that the registry
# lock does not cover (notifylog appends from the tick loop, alert
# bookkeeping during check): a concurrent structural mutation raises
# RuntimeError("... changed size/mutated during iteration") — rare at
# per-snapshot-memo frequency, so a short retry is the right tool
_AUX_RETRIES = 3

# aux views served straight from host-side registries (no device state
# anywhere in their providers) — safe to delegate to the runtime's live
# aux table under the registry lock
_REGISTRY_AUX = frozenset((
    "hostinfo", "cgroupstate", "mountstate", "netif", "alerts",
    "alertdef", "silences", "inhibits", "actions", "notifymsg",
    "svcipclust", "tags", "tracedef", "tracestatus",
))


def request_key(req: dict) -> str:
    """Normalized request hash key — the ONE shared definition in
    ``query/normalize.py``: the gateway tier's distributed edge cache
    keys with the same function, so a result rendered here serves the
    whole fleet (and a gateway-side hit proves a replica-side hit
    would have happened too)."""
    from gyeeta_tpu.query.normalize import request_key as _rk
    return _rk(req)


class EngineSnapshot:
    """One immutable published engine view (the ``columns_fn``
    contract of :func:`gyeeta_tpu.query.api.execute`, plus a
    per-snapshot result cache).

    ``state``/``dep`` are fold-domain COPIES — see the module
    docstring. ``version`` increases monotonically per publish;
    ``tick`` is the window tick the view was frozen at."""

    def __init__(self, rt, state, dep, tick: int, published_at: float,
                 version: int, result_cache_max: int = 1024):
        self.rt = rt
        self.state = state
        self.dep = dep
        self.tick = int(tick)
        self.published_at = float(published_at)
        self.version = int(version)
        from gyeeta_tpu.utils.colcache import ColumnCache
        self._cols = ColumnCache()
        self._results: collections.OrderedDict = collections.OrderedDict()
        self._results_max = int(result_cache_max)
        self._lock = threading.Lock()
        # single-flight: per-request and per-subsystem compute locks so
        # a dashboard stampede onto a FRESH snapshot collapses N
        # identical misses into ONE render (the N-1 waiters re-check
        # the cache after the holder publishes). Keyed locks form a
        # DAG (topk→svcstate/tracereq, svcsumm→svcstate, ext*→base) —
        # no cycles, no deadlock.
        self._flight: dict = {}

    def _flight_lock(self, key) -> threading.Lock:
        with self._lock:
            lk = self._flight.get(key)
            if lk is None:
                lk = self._flight[key] = threading.Lock()
            return lk

    # ------------------------------------------------------ result cache
    def query(self, req: dict) -> dict:
        """Serve one live query from this snapshot, collapsing repeated
        identical requests to one render (per-snapshot result cache:
        hits/misses land on ``gyt_query_cache_{hits,misses}_total``);
        CONCURRENT identical requests single-flight — one render, the
        rest wait for it and hit."""
        stats = self.rt.stats
        key = request_key(req)
        if self._results_max <= 0:
            stats.bump("query_cache_misses")
            return self._render(req)
        with self._lock:
            hit = self._results.get(key)
        if hit is not None:
            stats.bump("query_cache_hits")
            return hit
        with self._flight_lock(("r", key)):
            with self._lock:              # the holder may have stored
                hit = self._results.get(key)
            if hit is not None:
                stats.bump("query_cache_hits")
                return hit
            stats.bump("query_cache_misses")
            out = self._render(req)
            with self._lock:
                self._results[key] = out
                while len(self._results) > self._results_max:
                    self._results.popitem(last=False)
            return out

    def _render(self, req: dict) -> dict:
        out = api.execute(self.rt.cfg, None,
                          api.QueryOptions.from_json(req),
                          names=self.rt.names, columns_fn=self.columns)
        out["snaptick"] = self.tick
        return out

    def on_mutation(self) -> None:
        """CRUD invalidation hook: a registry/alert/tracedef mutation
        changes aux views mid-snapshot, so drop BOTH caches (device-
        backed column entries recompute from the frozen state — CRUD is
        rare enough that re-rendering beats tracking which subsystems a
        mutation touched)."""
        with self._lock:
            self._results.clear()
        self._cols.bump()

    def result_cache_len(self) -> int:
        with self._lock:
            return len(self._results)

    # ---------------------------------------------------------- columns
    def columns(self, subsys: str):
        """(cols, mask) for ``subsys`` over the frozen view — memoized
        per snapshot, so identical dashboard queries differing only in
        filter/sort/projection share one readback."""
        if "@" in subsys:
            # subsys@window (windowed alertdefs): the time-travel tier
            # reads shard FILES, not live state — safe from any thread
            base, _, win = subsys.partition("@")
            tv = getattr(self.rt, "timeview", None)
            if tv is None:
                raise ValueError("windowed alertdef needs history "
                                 "shards (hist_shard_dir)")
            return tv.window_columns_for(base, win)
        got = self._cols.peek(subsys)
        if got is not None:
            return got
        with self._flight_lock(("c", subsys)):
            return self._cols.get(subsys, lambda: self._columns(subsys))

    def _columns(self, subsys: str):
        rt = self.rt
        if subsys in _REGISTRY_AUX:
            return self._registry_columns(subsys)
        if subsys == "topk":
            return self._topk_columns()
        if subsys == "hostlist":
            return self._hostlist_columns()
        if subsys == "serverstatus":
            return self._serverstatus_columns()
        if subsys == "traceuniq":
            tcols, tlive = self.columns("tracereq")
            return api.traceuniq_from_trace(tcols, tlive)
        if subsys == "traceconn":
            return self._retry_aux(lambda: rt.traceconns.columns(
                rt.names, svc_task_ids=self._svc_task_ids()))
        if subsys in ("extactiveconn", "extclientconn", "exttracereq"):
            base = {"extactiveconn": "activeconn",
                    "extclientconn": "clientconn",
                    "exttracereq": "tracereq"}[subsys]
            idcol = "cliid" if subsys == "extclientconn" else "svcid"
            cols, live = self.columns(base)
            info_cols, _ = self._retry_aux(
                lambda: rt.svcreg.columns(rt.names))
            return api.info_join(cols, live, info_cols, idcol=idcol)
        if hasattr(rt, "_merged_columns_state"):     # ShardedRuntime
            if subsys == "shardlist":
                return self._shardlist_columns()
            return rt._merged_columns_state(subsys, self.state,
                                            self.dep, self._cols,
                                            reg=True)
        try:
            out = api.columns_for(rt.cfg, self.state, subsys,
                                  names=rt.names, dep=self.dep,
                                  svcreg=rt.svcreg)
        except KeyError:
            # a subsystem with fields but no single-node provider
            # (e.g. shardlist) fails like the live path: clean error
            raise ValueError(f"unknown subsystem {subsys!r}") from None
        if subsys == "procinfo":
            # tags mutate via CRUD; CRUD clears this snapshot's caches,
            # so joining INSIDE the memo stays consistent
            out = rt.tags.with_tags(out)
        return out

    def _registry_columns(self, subsys: str):
        return self._retry_aux(self.rt._aux[subsys])

    def _retry_aux(self, fn):
        """Run a host-side registry render under the registry lock,
        retrying the rare iteration-vs-mutation race (see module
        docstring)."""
        lock = getattr(self.rt, "_reg_lock", None)
        for attempt in range(_AUX_RETRIES):
            try:
                if lock is not None:
                    with lock:
                        return fn()
                return fn()
            except RuntimeError as e:
                if attempt + 1 == _AUX_RETRIES or (
                        "changed size" not in str(e)
                        and "mutated" not in str(e)):
                    raise
        raise AssertionError("unreachable")

    # ------------------------------------------- state-backed aux views
    def _topk_columns(self):
        """Heavy-hitter recovery over the FROZEN state (read-only
        dispatch — the shared decode+merge of ``timeview.hist_recover``
        works for both runtimes and never touches live buffers)."""
        from gyeeta_tpu.history.timeview import hist_recover
        rec = self._cols.get(
            "__hh_recover", lambda: hist_recover(self.rt, self.state))
        return api.heavy_topk_columns(
            rec["flows"], svc=self.columns("svcstate"),
            trace=self.columns("tracereq"))

    def _host_last_ticks(self) -> np.ndarray:
        rt = self.rt
        if hasattr(rt, "_shard_leaf"):               # ShardedRuntime
            return np.concatenate([
                np.asarray(rt._shard_leaf(self.state.host_last_tick, s))
                for s in range(rt.n)])
        return np.asarray(self.state.host_last_tick)

    def _hostlist_columns(self):
        last = self._host_last_ticks()
        seen = np.nonzero(last >= 0)[0]
        age = self.tick - last[seen]
        hostids, hostnames = api._host_name_cols(len(last), self.rt.names)
        cols = {
            "hostid": seen.astype(np.float64),
            "hostname": np.asarray(hostnames, object)[seen],
            "up": age <= api.DOWN_AFTER_TICKS,
            "lastseen": age.astype(np.float64),
        }
        return cols, np.ones(len(seen), bool)

    def _serverstatus_columns(self):
        from gyeeta_tpu import version as V
        rt = self.rt
        c = rt.stats.counters
        obj = lambda v: np.array([v], object)             # noqa: E731
        num = lambda v: np.array([float(v)], np.float64)  # noqa: E731
        if hasattr(rt, "_rollup"):                   # ShardedRuntime
            nsvc = float(np.asarray(rt._rollup(self.state).n_svc_live))
        else:
            nsvc = float(np.asarray(self.state.tbl.n_live))
        cols = {
            "uptime": num(rt._clock() - rt._t_started),
            "tick": num(self.tick),
            "nhosts": num(int((self._host_last_ticks() >= 0).sum())),
            "nsvc": num(nsvc),
            "connevents": num(c.get("conn_events", 0)),
            "respevents": num(c.get("resp_events", 0)),
            "queries": num(c.get("queries", 0)),
            "alertsfired": num(rt.alerts.stats.get("nfired", 0)),
            "wirever": num(V.CURR_WIRE_VERSION),
            "version": obj(V.__version__),
        }
        return cols, np.ones(1, bool)

    def _svc_task_ids(self):
        cols, live = self.columns("taskstate")
        zero = "0" * 16
        from gyeeta_tpu.query.lazycols import rows_of
        idx = np.nonzero(np.asarray(live, bool))[0]
        got = rows_of(cols, ["taskid", "relsvcid"], idx)
        return {t for t, r in zip(got["taskid"], got["relsvcid"])
                if r != zero}

    def _shardlist_columns(self):
        rt = self.rt
        rows = []
        for sidx in range(rt.n):
            st = rt._shard_state(sidx, self.state, self._cols)
            rows.append({
                "shard": float(sidx),
                "nsvc": float(np.asarray(st.tbl.n_live)),
                "nhosts": float((np.asarray(st.host_last_tick) >= 0)
                                .sum()),
                "nconn": float(np.asarray(st.n_conn)),
                "nresp": float(np.asarray(st.n_resp)),
                "ntaskrows": float(np.asarray(st.task_tbl.n_live)),
                "ndropped": float(np.asarray(st.tbl.n_drop)
                                  + np.asarray(st.task_tbl.n_drop)),
            })
        cols = {k: np.array([r[k] for r in rows], np.float64)
                for k in rows[0]}
        return cols, np.ones(rt.n, bool)
