"""Per-subsystem field maps: JSON field ↔ column ↔ type ↔ enum codec.

The tensor-era analogue of ``common/gy_json_field_maps.h`` (~40 subsystems
of ``JSON_DB_MAPPING`` tables, e.g. hoststate :785, svcstate :1102): every
queryable subsystem declares its fields once; the criteria engine and the
JSON writers are generic over these tables. JSON field names match the
reference's query API so existing Gyeeta queries port unchanged.
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Optional

import numpy as np

from gyeeta_tpu.semantic.states import CPU_ISSUE_NAMES, ISSUE_NAMES, \
    MEM_ISSUE_NAMES, STATE_NAMES, TASK_ISSUE_NAMES

SUBSYS_SVCSTATE = "svcstate"
SUBSYS_HOSTSTATE = "hoststate"
SUBSYS_CLUSTERSTATE = "clusterstate"
SUBSYS_FLOWSTATE = "flowstate"      # heavy-hitter flows (TPU-first)
SUBSYS_SVCINFO = "svcinfo"
SUBSYS_TASKSTATE = "taskstate"      # ref aggrtaskstate
# top-N process-group views (ref TASK_TOP_PROCS, gy_comm_proto.h:1415:
# top CPU / PG CPU / RSS / forks — here: preset-sorted taskstate views)
SUBSYS_TOPCPU = "topcpu"
SUBSYS_TOPPGCPU = "toppgcpu"        # ref toppgcpu (groups ARE our unit;
#                                     alias preset of topcpu)
SUBSYS_PROCINFO = "procinfo"        # ref procinfo (static group info)
SUBSYS_TOPRSS = "toprss"
SUBSYS_TOPDELAY = "topdelay"
SUBSYS_TOPFORK = "topfork"          # ref TOPFORK (top fork-rate groups)
SUBSYS_SVCDEP = "svcdependency"     # ref DEPENDS_LISTENER / svcprocmap
SUBSYS_SVCMESH = "svcmesh"          # ref svc mesh clusters (shyama)
SUBSYS_CPUMEM = "cpumem"            # ref cpumem (2s host cpu/mem state)
SUBSYS_TRACEREQ = "tracereq"        # ref tracereq (request tracing)
SUBSYS_ACTIVECONN = "activeconn"    # ref activeconn (per-svc client view)
SUBSYS_HOSTINFO = "hostinfo"        # ref hostinfo (static host inventory)
SUBSYS_SVCSUMM = "svcsumm"          # ref svcsumm (per-host summary)
SUBSYS_EXTSVCSTATE = "extsvcstate"  # ref extsvcstate (state ⋈ info)
SUBSYS_CLIENTCONN = "clientconn"    # ref clientconn (outbound view)
SUBSYS_SVCPROCMAP = "svcprocmap"    # ref svcprocmap (listener↔procs)
SUBSYS_NOTIFYMSG = "notifymsg"      # ref notifymsg
SUBSYS_HOSTLIST = "hostlist"        # ref parthalist (agents + liveness)
SUBSYS_SERVERSTATUS = "serverstatus"  # ref madhavastatus/shyamastatus
SUBSYS_TRACEDEF = "tracedef"        # ref tracedef (capture control)
SUBSYS_TRACESTATUS = "tracestatus"  # ref tracestatus
SUBSYS_TRACEUNIQ = "traceuniq"      # ref traceuniq (APIs per svc)
SUBSYS_TRACECONN = "traceconn"      # ref traceconn (traced conns)
SUBSYS_TAGS = "tags"                # ref tags (user process-group tags)
SUBSYS_MOUNTSTATE = "mountstate"    # ref MOUNT_HDLR (mount/freespace)
SUBSYS_NETIF = "netif"              # ref NET_IF_HDLR (interfaces)
SUBSYS_EXTACTIVECONN = "extactiveconn"  # ref extactiveconn (⋈ svcinfo)
SUBSYS_EXTCLIENTCONN = "extclientconn"  # ref extclientconn (⋈ svcinfo)
SUBSYS_EXTTRACEREQ = "exttracereq"  # ref exttracereq (⋈ svcinfo)
SUBSYS_SHARDLIST = "shardlist"      # mesh-native: per-shard stats (the
#                                     madhavalist analogue — one row per
#                                     shard instead of per madhava)
SUBSYS_CGROUPSTATE = "cgroupstate"  # ref cgroupstate
SUBSYS_SVCIPCLUST = "svcipclust"    # ref NAT-IP / VIP clusters
SUBSYS_TOPK = "topk"                # heavy hitters (TPU-first): exact
#                                     top-K lanes ∪ keys recovered from
#                                     the invertible sketch + dense
#                                     svc/api rankings, every row bound-
#                                     annotated (sketch/invertible.py)
SUBSYS_ALERTS = "alerts"            # ref alerts (fired alert log)
SUBSYS_ALERTDEF = "alertdef"        # ref alertdef
SUBSYS_SILENCES = "silences"        # ref silences
SUBSYS_INHIBITS = "inhibits"        # ref inhibits
SUBSYS_ACTIONS = "actions"          # ref actions (alert routing targets)


class FieldDef(NamedTuple):
    json: str                       # JSON/query field name (reference name)
    col: str                        # column key in the readback dict
    kind: str                       # "num" | "str" | "bool" | "enum"
    to_json: Optional[Callable] = None     # value → JSON value
    from_json: Optional[Callable] = None   # query literal → comparable value
    desc: str = ""


def _enum_codec(names):
    lower = [n.lower() for n in names]

    def enc(v):
        i = int(v)
        return names[i] if 0 <= i < len(names) else str(i)

    def dec(s):
        if isinstance(s, (int, float)):
            return float(s)
        try:
            return float(lower.index(str(s).lower()))
        except ValueError:
            raise ValueError(f"unknown enum literal {s!r}; one of {names}")

    return enc, dec


_state_enc, _state_dec = _enum_codec(STATE_NAMES)
_issue_enc, _issue_dec = _enum_codec(ISSUE_NAMES)
_tissue_enc, _tissue_dec = _enum_codec(TASK_ISSUE_NAMES)
_cissue_enc, _cissue_dec = _enum_codec(CPU_ISSUE_NAMES)
_missue_enc, _missue_dec = _enum_codec(MEM_ISSUE_NAMES)


def num(json, col, desc=""):
    return FieldDef(json, col, "num", desc=desc)


def boolean(json, col, desc=""):
    return FieldDef(json, col, "bool", desc=desc)


def enum(json, col, enc, dec, desc=""):
    return FieldDef(json, col, "enum", to_json=enc, from_json=dec, desc=desc)


def string(json, col, desc=""):
    return FieldDef(json, col, "str", desc=desc)


# --------------------------------------------------------------- svcstate
# ref json_db_svcstate_arr (gy_json_field_maps.h:1102); column keys are the
# keys of query.api.svc_columns()
SVCSTATE_FIELDS = (
    string("svcid", "svcid", "Service glob id (hex)"),
    string("svcname", "svcname", "Service name (interned)"),
    num("qps5s", "qps5s", "Current queries/sec"),
    num("nqry5s", "nqry5s", "Queries in last 5s window"),
    num("resp5s", "resp5s", "Mean response last 5s (msec)"),
    num("p95resp5s", "p95resp5s", "p95 response last 5s (msec)"),
    num("p95resp5m", "p95resp5m", "p95 response last 5min (msec)"),
    num("p99resp5s", "p99resp5s", "p99 response last 5s (msec)"),
    num("nconns", "nconns", "Total connections"),
    num("nactive", "nactive", "Active connections"),
    num("nprocs", "nprocs", "Listener processes"),
    num("kbin15s", "kbin15s", "Inbound KB"),
    num("kbout15s", "kbout15s", "Outbound KB"),
    num("sererr", "sererr", "Server errors"),
    num("clierr", "clierr", "Client errors"),
    num("delayus", "delayus", "Process delays usec"),
    num("cpudelus", "cpudelus", "CPU delays usec"),
    num("iodelus", "iodelus", "Block IO delays usec"),
    num("usercpu", "usercpu", "User CPU %"),
    num("syscpu", "syscpu", "System CPU %"),
    num("rssmb", "rssmb", "Resident memory MB"),
    num("nissue", "nissue", "Processes with issues"),
    enum("state", "state", _state_enc, _state_dec,
         "Service state per analysis"),
    enum("issue", "issue", _issue_enc, _issue_dec, "Issue source"),
    num("hostid", "hostid", "Owning host id"),
    num("nclients", "nclients", "Distinct client endpoints (HLL)"),
    num("p50resp5d", "p50resp5d", "p50 response 5-day window (msec)"),
    num("p95resp5d", "p95resp5d", "p95 response 5-day window (msec)"),
)

# -------------------------------------------------------------- hoststate
# ref json_db_hoststate_arr (gy_json_field_maps.h:785)
HOSTSTATE_FIELDS = (
    num("hostid", "hostid", "Host id"),
    string("hostname", "hostname", "Hostname (interned)"),
    num("nprocissue", "nprocissue", "Processes with issues"),
    num("nprocsevere", "nprocsevere", "Processes with severe issues"),
    num("nproc", "nproc", "Total processes"),
    num("nlistissue", "nlistissue", "Listeners with issues"),
    num("nlistsevere", "nlistsevere", "Listeners with severe issues"),
    num("nlisten", "nlisten", "Total listeners"),
    enum("state", "state", _state_enc, _state_dec, "Host state"),
    boolean("cpuissue", "cpuissue", "Host CPU issue"),
    boolean("memissue", "memissue", "Host memory issue"),
    boolean("severecpu", "severecpu", "Severe CPU issue"),
    boolean("severemem", "severemem", "Severe memory issue"),
)

# ----------------------------------------------------------- clusterstate
# ref MS_CLUSTER_STATE (gy_comm_proto.h:3181) / shyama aggregate
CLUSTERSTATE_FIELDS = (
    num("nhosts", "nhosts", "Hosts reporting"),
    num("nidle", "nidle", "Hosts Idle"),
    num("ngood", "ngood", "Hosts Good"),
    num("nok", "nok", "Hosts OK"),
    num("nbad", "nbad", "Hosts Bad"),
    num("nsevere", "nsevere", "Hosts Severe"),
    num("ndown", "ndown", "Hosts Down"),
    num("issuefrac", "issue_frac", "Fraction of hosts Bad/Severe"),
)

# -------------------------------------------------------------- taskstate
# ref json_db_aggrtaskstate_arr / MAGGR_TASK fields (gy_comm_proto.h:2114,
# server/gy_msocket.h MAGGR_TASK); comm resolved via the intern table
TASKSTATE_FIELDS = (
    string("taskid", "taskid", "Process-group (aggregate task) id (hex)"),
    string("comm", "comm", "Process command name"),
    string("relsvcid", "relsvcid", "Related listener (service) id (hex)"),
    num("tcpkb", "tcpkb", "TCP KB transferred in last 5s"),
    num("tcpconns", "tcpconns", "TCP connections"),
    num("cpu", "cpu", "Total CPU %% (all group processes)"),
    num("cpup95", "cpup95", "Learned p95 CPU %% baseline"),
    num("rssmb", "rssmb", "Resident memory MB"),
    num("cpudelms", "cpudelms", "CPU delay msec (taskstats)"),
    num("vmdelms", "vmdelms", "VM (swap/reclaim) delay msec"),
    num("iodelms", "iodelms", "Block IO delay msec"),
    num("ntasks", "ntasks", "Processes in the group"),
    num("nissue", "nissue", "Processes with issues"),
    num("forks", "forks", "Process forks/sec in the group"),
    enum("state", "state", _state_enc, _state_dec, "Group state"),
    enum("issue", "issue", _tissue_enc, _tissue_dec, "Issue source"),
    num("hostid", "hostid", "Owning host id"),
)

# --------------------------------------------------------------- procinfo
# ref SUBSYS_PROCINFO (aggrtaskinfotbl): the static face of a process
# group — identity, placement, service linkage
PROCINFO_FIELDS = (
    string("taskid", "taskid", "Process-group id (hex)"),
    string("comm", "comm", "Process command name"),
    string("relsvcid", "relsvcid", "Related listener (service) id (hex)"),
    string("svcname", "svcname", "Linked service name ('' if none)"),
    num("ntasks", "ntasks", "Processes in the group"),
    num("hostid", "hostid", "Owning host id"),
    string("tag", "tag", "User tag (CRUD objtype 'tag'; ref "
                         "MAGGR_TASK tagbuf_, gy_msocket.h:960)"),
)

# ------------------------------------------------------------------- tags
# ref SUBSYS_TAGS (gy_json_field_maps.h:55 — a bare enum there; the
# working feature is the per-group tag buffer): the tag registry as its
# own listing
TAGS_FIELDS = (
    string("taskid", "taskid", "Tagged process-group id (hex)"),
    string("tag", "tag", "User tag text"),
)

# ------------------------------------------------------------- mountstate
# ref MOUNT_HDLR inventory (gy_mount_disk.h:233): per-mount filesystem
# + freespace, pseudo-fs excluded agent-side
MOUNTSTATE_FIELDS = (
    num("hostid", "hostid", "Reporting host id"),
    string("mnt", "mnt", "Mount point path"),
    string("fstype", "fstype", "Filesystem type"),
    num("sizemb", "sizemb", "Filesystem size MB"),
    num("freemb", "freemb", "Free space MB (unprivileged avail)"),
    num("usedpct", "usedpct", "Space used %%"),
    num("inodepct", "inodepct", "Inodes used %%"),
    boolean("netfs", "netfs", "Network filesystem (nfs/cifs/…)"),
)

# ------------------------------------------------------------------ netif
# ref NET_IF_HDLR (gy_netif.h:708): interface inventory + rates
NETIF_FIELDS = (
    num("hostid", "hostid", "Reporting host id"),
    string("name", "name", "Interface name"),
    num("speedmbps", "speedmbps", "Link speed Mbps (-1 unknown)"),
    num("rxmbsec", "rxmbsec", "Receive MB/s"),
    num("txmbsec", "txmbsec", "Transmit MB/s"),
    num("rxerrsec", "rxerrsec", "Receive errors/s"),
    num("txerrsec", "txerrsec", "Transmit errors/s"),
    boolean("up", "up", "Operationally up"),
)

# ---------------------------------------------------------- svcdependency
# ref DEPENDS_LISTENER (common/gy_socket_stat.h:721) +
# LISTENER_DEPENDENCY_NOTIFY (gy_comm_proto.h:2333): one row per
# caller→service edge of the dependency graph
SVCDEP_FIELDS = (
    string("cliid", "cliid", "Caller entity id (hex): listener or "
           "process-group"),
    string("cliname", "cliname", "Caller name (interned)"),
    boolean("clisvc", "clisvc", "Caller is itself a service (mesh edge)"),
    string("serid", "serid", "Callee service glob id (hex)"),
    string("sername", "sername", "Callee service name"),
    num("nconn", "nconn", "Flows folded into this edge"),
    num("bytes", "bytes", "Total bytes over this edge"),
)

# -------------------------------------------------------------- svcmesh
# ref coalesce_svc_mesh_clusters (server/gy_shconnhdlr.cc:5198): one row
# per service in the svc→svc mesh, labelled by coalesced cluster
SVCMESH_FIELDS = (
    string("svcid", "svcid", "Service glob id (hex)"),
    string("svcname", "svcname", "Service name (interned)"),
    num("clusterid", "clusterid", "Cluster label (min reachable node row)"),
    num("clustersize", "clustersize", "Services in this cluster"),
)

# ---------------------------------------------------------------- cpumem
# ref json_db_cpumem_arr (the 2s CPU_MEM_STATE path, gy_comm_proto.h:2024)
CPUMEM_FIELDS = (
    num("hostid", "hostid", "Host id"),
    string("hostname", "hostname", "Hostname (interned)"),
    num("cpu", "cpu", "Total CPU %"),
    num("usercpu", "usercpu", "User CPU %"),
    num("syscpu", "syscpu", "System CPU %"),
    num("iowait", "iowait", "IO-wait %"),
    num("corecpu", "corecpu", "Hottest core CPU %"),
    num("cs", "cs", "Context switches/sec"),
    num("forks", "forks", "Forks/sec"),
    num("runq", "runq", "Runnable processes"),
    num("rsspct", "rsspct", "Resident memory %"),
    num("commitpct", "commitpct", "Committed memory %"),
    num("swapfreepct", "swapfreepct", "Swap free %"),
    num("pginout", "pginout", "Pages in+out/sec"),
    num("swapinout", "swapinout", "Swap pages in+out/sec"),
    num("allocstall", "allocstall", "Direct-reclaim stalls/sec"),
    num("oom", "oom", "OOM kills in window"),
    enum("cpustate", "cpustate", _state_enc, _state_dec,
         "CPU state per 2s analysis"),
    enum("cpuissue", "cpuissue", _cissue_enc, _cissue_dec,
         "CPU issue source"),
    enum("memstate", "memstate", _state_enc, _state_dec,
         "Memory state per 2s analysis"),
    enum("memissue", "memissue", _missue_enc, _missue_dec,
         "Memory issue source"),
)

# --------------------------------------------------------------- tracereq
# ref json_db_tracereq_arr (request-trace aggregates): one row per
# (service, normalized API signature)
from gyeeta_tpu.trace.proto import PROTO_NAMES as _PROTO_NAMES  # noqa: E402

_proto_enc, _proto_dec = _enum_codec(_PROTO_NAMES)

TRACEREQ_FIELDS = (
    string("svcid", "svcid", "Service glob id (hex)"),
    string("svcname", "svcname", "Service name (interned)"),
    string("api", "api", "Normalized API signature (interned)"),
    enum("proto", "proto", _proto_enc, _proto_dec,
         "Application protocol"),
    num("nreq", "nreq", "Transactions folded"),
    num("nerr", "nerr", "Errored transactions"),
    num("bytesin", "bytesin", "Request bytes"),
    num("bytesout", "bytesout", "Response bytes"),
    num("p50resp", "p50resp", "p50 latency (msec)"),
    num("p95resp", "p95resp", "p95 latency (msec)"),
    num("p99resp", "p99resp", "p99 latency (msec)"),
    num("hostid", "hostid", "Last reporting host"),
)

# ---------------------------------------------------------------- svcinfo
# ref json_db_svcinfo_arr: static listener metadata (announce-rate,
# host-side registry utils/svcreg.py)
SVCINFO_FIELDS = (
    string("svcid", "svcid", "Service glob id (hex)"),
    string("svcname", "svcname", "Service name (interned)"),
    string("ip", "ip", "Bind address"),
    num("port", "port", "Listen port"),
    num("tstart", "tstart", "Listener start time (epoch sec)"),
    string("comm", "comm", "Listener process comm"),
    string("cmdline", "cmdline", "Command line (interned)"),
    num("pid", "pid", "Listener pid"),
    boolean("anyip", "anyip", "Bound to ANY address"),
    boolean("ishttp", "ishttp", "Serves HTTP"),
    num("hostid", "hostid", "Owning host id"),
)

# -------------------------------------------------------------- activeconn
# ref json_db_activeconn_arr: the per-service client view of the
# dependency edges (who talks to this service, how much)
ACTIVECONN_FIELDS = (
    string("svcid", "svcid", "Service glob id (hex)"),
    string("svcname", "svcname", "Service name (interned)"),
    num("nclients", "nclients", "Distinct caller entities"),
    num("nconn", "nconn", "Flows folded"),
    num("bytes", "bytes", "Total bytes"),
    num("nsvccli", "nsvccli", "Callers that are services"),
)

# -------------------------------------------------------------- flowstate
FLOWSTATE_FIELDS = (
    string("flowid", "flowid", "Flow key (hex)"),
    num("bytes", "bytes", "Bytes transferred (top-K estimate)"),
    num("evictedbytes", "evictedbytes", "Undercount bound (evicted mass)"),
)

# ------------------------------------------------------------------- topk
# Heavy-hitter rankings as one queryable union (ROADMAP "heavy-hitter
# detection as a first-class subsystem"): per-metric ranked rows from
# the exact top-K lanes, the invertible-sketch recovery, and the dense
# svc/api slabs. Flow-row ``value`` is an UPPER bound on the true
# total (never undercounts); its overcount is ≤ ``errbound`` — exact
# lanes tighten it to est − count (truth ∈ [count, est]), recovered
# rows carry the invertible-array term (2·N/width w.p. 1−2^−depth);
# dense rows are exact slab gauges (errbound 0).
TOPK_FIELDS = (
    string("metric", "metric",
           "Ranking: bytes | conns | errrate | p99resp"),
    num("rank", "rank", "1-based rank within the metric"),
    string("id", "id", "Entity id (hex): flow key / svcid / api key"),
    string("name", "name", "Entity name ('' for raw flows)"),
    num("value", "value", "Ranked stat value"),
    num("errbound", "errbound",
        "Error bound on value (evicted mass + invertible-array term)"),
    string("source", "source",
           "Row provenance: exact | recovered | dense"),
)

# ---------------------------------------------------------------- svcsumm
# ref SUBSYS_SVCSUMM (LISTEN_SUMM_STATS, server/gy_msocket.h:841):
# per-host service summary counts
SVCSUMM_FIELDS = (
    num("hostid", "hostid", "Host id"),
    string("hostname", "hostname", "Hostname (interned)"),
    num("nsvc", "nsvc", "Services on host"),
    num("nidle", "nidle", "Idle services"),
    num("ngood", "ngood", "Good services"),
    num("nok", "nok", "OK services"),
    num("nbad", "nbad", "Bad services"),
    num("nsevere", "nsevere", "Severe services"),
    num("ndown", "ndown", "Down services"),
    num("nissue", "nissue", "Services with issues (Bad+)"),
    num("totqps", "totqps", "Total QPS across services"),
    num("totactive", "totactive", "Total active connections"),
    num("totkbin", "totkbin", "Total inbound KB"),
    num("totkbout", "totkbout", "Total outbound KB"),
)

# ------------------------------------------------------------ extsvcstate
# ref EXTSVCSTATE: svcstate joined with svcinfo (gy_mnodehandle.cc:4657)
EXTSVCSTATE_FIELDS = SVCSTATE_FIELDS + (
    string("ip", "ip", "Bind address"),
    num("port", "port", "Listen port"),
    string("comm", "comm", "Listener process comm"),
    string("cmdline", "cmdline", "Command line (interned)"),
    num("pid", "pid", "Listener pid"),
    num("tstart", "tstart", "Listener start time (epoch sec)"),
)

# ------------------------------------------------------------- clientconn
# ref SUBSYS_CLIENTCONN (remoteconn): outbound view per caller entity
CLIENTCONN_FIELDS = (
    string("cliid", "cliid", "Caller entity id (hex)"),
    string("cliname", "cliname", "Caller name (interned)"),
    boolean("clisvc", "clisvc", "Caller is itself a service"),
    num("nservers", "nservers", "Distinct services called"),
    num("nconn", "nconn", "Flows folded"),
    num("bytes", "bytes", "Total bytes"),
)

# ------------------------------------------------------------- svcprocmap
# ref LISTEN_TASKMAP_NOTIFY (gy_comm_proto.h:2813): listener ↔
# process-group mapping
SVCPROCMAP_FIELDS = (
    string("svcid", "svcid", "Service glob id (hex)"),
    string("svcname", "svcname", "Service name"),
    string("relsvcid", "relsvcid", "Related-listener group id (hex)"),
    string("taskid", "taskid", "Process-group id (hex)"),
    string("comm", "comm", "Process comm"),
    num("hostid", "hostid", "Host id"),
)

# -------------------------------------------------------------- notifymsg
# ref SUBSYS_NOTIFYMSG (notificationtbl, gy_mdb_schema.cc:101)
NOTIFYMSG_FIELDS = (
    num("time", "time", "Event time (epoch sec)"),
    string("type", "type", "info | warn | error"),
    string("source", "source", "agent | alert | server | config"),
    string("msg", "msg", "Message"),
)

# --------------------------------------------------------------- hostlist
# ref SUBSYS_PARTHALIST: registered agents + liveness
HOSTLIST_FIELDS = (
    num("hostid", "hostid", "Assigned host id"),
    string("hostname", "hostname", "Hostname (interned)"),
    boolean("up", "up", "Reported within the liveness window"),
    num("lastseen", "lastseen", "Ticks since last report (-1 never)"),
)

# ------------------------------------------------------------ serverstatus
# ref SUBSYS_MADHAVASTATUS/SHYAMASTATUS: one-row server self status
SERVERSTATUS_FIELDS = (
    num("uptime", "uptime", "Seconds since server start"),
    num("tick", "tick", "Current 5s window tick"),
    num("nhosts", "nhosts", "Hosts that have ever reported"),
    num("nsvc", "nsvc", "Live service rows"),
    num("connevents", "connevents", "Flow events ingested"),
    num("respevents", "respevents", "Response samples ingested"),
    num("queries", "queries", "Queries served"),
    num("alertsfired", "alertsfired", "Alerts notified"),
    num("wirever", "wirever", "Wire protocol version"),
    string("version", "version", "Server version"),
)

# ------------------------------------------------------------ trace defs
# ref tracedef / tracestatus subsystems (REQ_TRACE_DEF distribution,
# common/gy_trace_def.h; tracestatustbl)
TRACEDEF_FIELDS = (
    string("name", "name", "Trace definition name"),
    string("filter", "filter", "Service-selection criteria (svcinfo)"),
    num("tend", "tend", "Capture until (epoch sec; 0 = no expiry)"),
    boolean("active", "active", "Definition currently in effect"),
    num("nsvc", "nsvc", "Services currently capturing"),
)

TRACESTATUS_FIELDS = TRACEDEF_FIELDS

# ------------------------------------------------------------- traceuniq
# ref traceuniqtbl: distinct API signatures per service
TRACEUNIQ_FIELDS = (
    string("svcid", "svcid", "Service glob id (hex)"),
    string("svcname", "svcname", "Service name"),
    num("napis", "napis", "Distinct API signatures"),
    num("nreq", "nreq", "Transactions across APIs"),
    num("nerr", "nerr", "Errored transactions"),
)

# -------------------------------------------------------------- traceconn
# ref SUBSYS_TRACECONN (json_db_traceconn_arr, gy_json_field_maps.h:2670):
# the per-CONNECTION face of request tracing — who talks to the traced
# service over which connection
TRACECONN_FIELDS = (
    string("svcid", "svcid", "Traced service glob id (hex)"),
    string("name", "name", "Traced service name"),
    string("connid", "connid", "Traced connection id (hex)"),
    string("cprocid", "cprocid", "Client process-group id (hex)"),
    string("cname", "cname", "Client process comm"),
    boolean("csvc", "csvc", "Client is itself a service"),
    num("nreq", "nreq", "Requests seen on this connection"),
    num("hostid", "hostid", "Reporting host id"),
    num("idleticks", "idleticks", "Ticks since last request"),
)

# ------------------------------------------------------------- ext* joins
_EXTINFO_FIELDS = (
    string("ip", "ip", "Bind address"),
    num("port", "port", "Listen port"),
    string("comm", "comm", "Listener process comm"),
    string("cmdline", "cmdline", "Command line (interned)"),
    num("pid", "pid", "Listener pid"),
    num("tstart", "tstart", "Listener start time (epoch sec)"),
)

EXTACTIVECONN_FIELDS = ACTIVECONN_FIELDS + _EXTINFO_FIELDS
EXTCLIENTCONN_FIELDS = CLIENTCONN_FIELDS + _EXTINFO_FIELDS
EXTTRACEREQ_FIELDS = TRACEREQ_FIELDS + _EXTINFO_FIELDS

# ------------------------------------------------------------- svcipclust
# ref check_svc_nat_ip_clusters (server/gy_shconnhdlr.h:1301): services
# reached through one virtual IP = a load-balancer cluster
SVCIPCLUST_FIELDS = (
    string("vip", "vip", "Virtual (pre-NAT) ip:port dialed by clients"),
    string("dns", "dns", "Reverse-resolved VIP domain ('' pending/"
                         "unresolvable; ref gy_dns_mapping.h:46)"),
    string("svcid", "svcid", "Backend service glob id (hex)"),
    string("svcname", "svcname", "Backend service name"),
    num("nsvc", "nsvc", "Backends behind this VIP"),
)

# -------------------------------------------------------------- shardlist
SHARDLIST_FIELDS = (
    num("shard", "shard", "Mesh shard index"),
    num("nsvc", "nsvc", "Live service rows on this shard"),
    num("nhosts", "nhosts", "Hosts reporting to this shard"),
    num("nconn", "nconn", "Flow events folded on this shard"),
    num("nresp", "nresp", "Response samples folded on this shard"),
    num("ntaskrows", "ntaskrows", "Live process-group rows"),
    num("ndropped", "ndropped", "Table inserts dropped (probe exhaust)"),
)

# --------------------------------------------------------------- hostinfo
# ref json_db_hostinfo_arr (HOST_INFO_NOTIFY, gy_comm_proto.h:2843):
# static host inventory — hardware/OS/cloud metadata
HOSTINFO_FIELDS = (
    num("hostid", "hostid", "Host id"),
    string("host", "host", "Hostname (interned)"),
    num("ncpus", "ncpus", "Online CPU cores"),
    num("nnuma", "nnuma", "NUMA nodes"),
    num("rammb", "rammb", "RAM MB"),
    num("swapmb", "swapmb", "Swap MB"),
    num("boot", "boot", "Boot time (epoch sec)"),
    string("kernverstr", "kernverstr", "Kernel version"),
    string("dist", "dist", "OS distribution"),
    string("cputype", "cputype", "Processor model"),
    string("instanceid", "instanceid", "Cloud instance id"),
    string("region", "region", "Cloud region"),
    string("zone", "zone", "Cloud zone"),
    string("virt", "virt", "Virtualization (none/vm/container)"),
    string("cloud", "cloud", "Cloud provider (none/aws/gcp/azure)"),
    boolean("isk8s", "isk8s", "Kubernetes node"),
)

# ------------------------------------------------------------ cgroupstate
# ref cgroupstate subsystem (CGROUP_HANDLE stats, common/gy_cgroup_stat.h)
CGROUPSTATE_FIELDS = (
    string("cgid", "cgid", "Cgroup path hash (hex)"),
    string("dir", "dir", "Cgroup path (interned)"),
    num("hostid", "hostid", "Host id"),
    num("cpupct", "cpupct", "CPU %"),
    num("cpulimpct", "cpulimpct", "CPU limit % (<0 none)"),
    num("throttlepct", "throttlepct", "Throttled period fraction %"),
    num("rssmb", "rssmb", "Resident memory MB"),
    num("memlimmb", "memlimmb", "Memory limit MB (<0 none)"),
    num("pgmajfps", "pgmajfps", "Major page faults/sec"),
    num("nprocs", "nprocs", "Processes in cgroup"),
    boolean("isv2", "isv2", "cgroup v2 unified hierarchy"),
    enum("state", "state", _state_enc, _state_dec,
         "Cgroup pressure state"),
)

# ----------------------------------------------------------- alerts tier
# ref shyama alert subsystems (gy_json_field_maps.h SUBSYS_ALERTS /
# ALERTDEF / SILENCES / INHIBITS; ALERTMGR state, gy_alertmgr.h:948)
ALERTS_FIELDS = (
    num("tfired", "tfired", "Fire time (epoch sec)"),
    string("alertname", "alertname", "Alert definition name"),
    string("severity", "severity", "Severity"),
    string("subsys", "subsys", "Subsystem evaluated"),
    string("entity", "entity", "Entity key (svcid=… / hostid=…)"),
    string("labels", "labels", "Labels (JSON)"),
    string("annotations", "annotations", "Annotations (JSON)"),
)

ALERTDEF_FIELDS = (
    string("alertname", "alertname", "Definition name"),
    string("subsys", "subsys", "Subsystem"),
    string("filter", "filter", "Criteria filter"),
    string("severity", "severity", "Severity"),
    string("mode", "mode", "realtime | db"),
    num("numcheckfor", "numcheckfor", "Consecutive hits to fire"),
    num("repeataftersec", "repeataftersec", "Re-notify holdoff sec"),
    num("querysec", "querysec", "DB-mode period sec"),
    num("groupwaitsec", "groupwaitsec", "Group-wait sec"),
    boolean("enabled", "enabled", "Definition enabled"),
    num("nfiring", "nfiring", "Entities currently firing"),
)

SILENCES_FIELDS = (
    string("name", "name", "Silence name"),
    string("filter", "filter", "Criteria filter (empty = all)"),
    string("alertnames", "alertnames", "Alert names muted (empty = any)"),
    num("tstart", "tstart", "Active from (epoch sec)"),
    num("tend", "tend", "Active until (epoch sec)"),
    boolean("active", "active", "Currently in effect"),
)

INHIBITS_FIELDS = (
    string("name", "name", "Inhibit rule name"),
    string("srcalerts", "srcalerts", "Source alert names"),
    string("targetalerts", "targetalerts", "Suppressed alert names"),
    boolean("active", "active", "A source alert is currently firing"),
)

ACTIONS_FIELDS = (
    string("name", "name", "Action name (alertdef routing target)"),
    string("type", "type", "Delivery type (builtin/webhook/slack/"
                           "email/pagerduty)"),
    string("target", "target", "Delivery URL ('' for builtins)"),
    num("ndefs", "ndefs", "Alert definitions routing to this action"),
)

FIELDS_OF_SUBSYS = {
    SUBSYS_SVCSTATE: SVCSTATE_FIELDS,
    SUBSYS_HOSTSTATE: HOSTSTATE_FIELDS,
    SUBSYS_CLUSTERSTATE: CLUSTERSTATE_FIELDS,
    SUBSYS_FLOWSTATE: FLOWSTATE_FIELDS,
    SUBSYS_TASKSTATE: TASKSTATE_FIELDS,
    SUBSYS_TOPCPU: TASKSTATE_FIELDS,
    SUBSYS_TOPPGCPU: TASKSTATE_FIELDS,
    SUBSYS_PROCINFO: PROCINFO_FIELDS,
    SUBSYS_TOPRSS: TASKSTATE_FIELDS,
    SUBSYS_TOPDELAY: TASKSTATE_FIELDS,
    SUBSYS_TOPFORK: TASKSTATE_FIELDS,
    SUBSYS_SVCDEP: SVCDEP_FIELDS,
    SUBSYS_SVCMESH: SVCMESH_FIELDS,
    SUBSYS_CPUMEM: CPUMEM_FIELDS,
    SUBSYS_TRACEREQ: TRACEREQ_FIELDS,
    SUBSYS_SVCINFO: SVCINFO_FIELDS,
    SUBSYS_ACTIVECONN: ACTIVECONN_FIELDS,
    SUBSYS_HOSTINFO: HOSTINFO_FIELDS,
    SUBSYS_CGROUPSTATE: CGROUPSTATE_FIELDS,
    SUBSYS_SVCSUMM: SVCSUMM_FIELDS,
    SUBSYS_EXTSVCSTATE: EXTSVCSTATE_FIELDS,
    SUBSYS_CLIENTCONN: CLIENTCONN_FIELDS,
    SUBSYS_SVCPROCMAP: SVCPROCMAP_FIELDS,
    SUBSYS_NOTIFYMSG: NOTIFYMSG_FIELDS,
    SUBSYS_HOSTLIST: HOSTLIST_FIELDS,
    SUBSYS_SERVERSTATUS: SERVERSTATUS_FIELDS,
    SUBSYS_TRACEDEF: TRACEDEF_FIELDS,
    SUBSYS_TRACESTATUS: TRACESTATUS_FIELDS,
    SUBSYS_TRACEUNIQ: TRACEUNIQ_FIELDS,
    SUBSYS_TRACECONN: TRACECONN_FIELDS,
    SUBSYS_TAGS: TAGS_FIELDS,
    SUBSYS_MOUNTSTATE: MOUNTSTATE_FIELDS,
    SUBSYS_NETIF: NETIF_FIELDS,
    SUBSYS_EXTACTIVECONN: EXTACTIVECONN_FIELDS,
    SUBSYS_EXTCLIENTCONN: EXTCLIENTCONN_FIELDS,
    SUBSYS_EXTTRACEREQ: EXTTRACEREQ_FIELDS,
    SUBSYS_SHARDLIST: SHARDLIST_FIELDS,
    SUBSYS_SVCIPCLUST: SVCIPCLUST_FIELDS,
    SUBSYS_TOPK: TOPK_FIELDS,
    SUBSYS_ALERTS: ALERTS_FIELDS,
    SUBSYS_ALERTDEF: ALERTDEF_FIELDS,
    SUBSYS_SILENCES: SILENCES_FIELDS,
    SUBSYS_INHIBITS: INHIBITS_FIELDS,
    SUBSYS_ACTIONS: ACTIONS_FIELDS,
}


def check_subsys(subsys: str) -> str:
    """Validate a subsystem NAME at definition time → the name, or a
    ValueError that lists every valid subsystem. Alert/trace defs call
    this when they are CREATED so a typo'd subsys fails the CRUD
    request with an actionable message instead of surfacing as a
    fold-time evaluation error on every subsequent tick."""
    if subsys not in FIELDS_OF_SUBSYS:
        raise ValueError(f"unknown subsystem {subsys!r}; "
                         f"one of {sorted(FIELDS_OF_SUBSYS)}")
    return subsys


def field_map(subsys: str) -> dict[str, FieldDef]:
    try:
        return {f.json: f for f in FIELDS_OF_SUBSYS[subsys]}
    except KeyError:
        raise ValueError(f"unknown subsystem {subsys!r}; "
                         f"one of {sorted(FIELDS_OF_SUBSYS)}")


def row_to_json(subsys: str, row: dict) -> dict:
    """Apply enum/bool codecs for presentation (statetojson analogues)."""
    out = {}
    for f in FIELDS_OF_SUBSYS[subsys]:
        if f.col not in row:
            continue
        v = row[f.col]
        if f.kind == "enum":
            out[f.json] = f.to_json(v)
        elif f.kind == "bool":
            out[f.json] = bool(v)
        elif f.kind == "num":
            fv = float(v)
            out[f.json] = int(fv) if fv.is_integer() else round(fv, 3)
        else:
            out[f.json] = v
    return out
