"""CRUD channel shared by both runtimes (the reference's
CRUD_GENERIC_JSON / CRUD_ALERT_JSON query types,
``gy_comm_proto.h:246-258``): {"op": "add"|"delete", "objtype": ...}."""

from __future__ import annotations

CRUD_OBJS = ("alertdef", "silence", "inhibit", "tracedef",
             "action", "tag")


def crud(rt, req: dict) -> dict:
    """``rt`` provides .alerts, .tracedefs, .notifylog."""
    op = req.get("op")
    objtype = req.get("objtype")
    if objtype not in CRUD_OBJS:
        raise ValueError(f"objtype must be one of {CRUD_OBJS}")
    if op == "add":
        if objtype == "alertdef":
            rt.alerts.add_def(req)
            name = req["alertname"]
        elif objtype == "silence":
            name = rt.alerts.add_silence(req).name
        elif objtype == "inhibit":
            name = rt.alerts.add_inhibit(req).name
        elif objtype == "action":
            name = rt.alerts.add_action(req).name
        elif objtype == "tag":
            rt.tags.set(req["taskid"], req.get("tag", ""))
            name = req["taskid"]
        else:
            name = rt.tracedefs.add(req).name
        rt.notifylog.add(f"{objtype} {name!r} added", source="config")
        return {"ok": True, "objtype": objtype, "name": name}
    if op == "delete":
        name = req.get("name") or req.get("alertname") \
            or req.get("taskid")
        if not name:
            raise ValueError("delete needs a name")
        if objtype == "alertdef":
            found = rt.alerts.delete_def(name)
        elif objtype == "silence":
            found = rt.alerts.silences.pop(name, None) is not None
        elif objtype == "inhibit":
            found = rt.alerts.inhibits.pop(name, None) is not None
        elif objtype == "action":
            found = rt.alerts.delete_action(name)
        elif objtype == "tag":
            found = rt.tags.delete(req.get("taskid") or name)
        else:
            found = rt.tracedefs.delete(name)
        if found:
            rt.notifylog.add(f"{objtype} {name!r} deleted",
                             source="config")
        return {"ok": found, "objtype": objtype, "name": name}
    raise ValueError("op must be add or delete")


def multiquery(query_fn, req: dict) -> dict:
    """Run a batch of sub-queries through ``query_fn`` (one round trip;
    one bad sub-query doesn't fail the batch). Sub-queries must be
    plain queries: nesting or CRUD inside a batch is rejected — a
    16-wide batch nested N deep would fan out 16^N synchronous
    executions on the event loop."""
    subs = req["multiquery"]
    if not isinstance(subs, list) or len(subs) > 16:
        raise ValueError("multiquery: list of <=16 queries")
    out = []
    for sub in subs:
        if not isinstance(sub, dict) or "multiquery" in sub \
                or sub.get("op"):
            out.append({"error": "sub-query must be a plain query"})
            continue
        try:
            out.append(query_fn(sub))
        except Exception as e:
            out.append({"error": str(e)})
    return {"multiquery": out, "nqueries": len(out)}
