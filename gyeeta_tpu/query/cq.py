"""Continuous-query engine: standing predicates evaluated server-side.

The reference evaluates filter criteria where the stream already flows
(``gy_query_criteria`` inside madhava); our subscription tier
(``net/subs.py``) pushed whole-panel deltas and left predicate work to
every client, and the alert manager ran a SECOND predicate evaluator
over the same columns. This module is the one evaluation engine both
now share:

- **Normalization + grouping** — a standing filter canonicalizes
  through ``query/normalize.py:canonical_filter`` and groups by
  ``(subsys, canonical-criteria)``: N subscribers (or N alertdefs)
  asking a semantically-equal question cost ONE predicate pass per
  tick. That is the sPIN move (PAPERS.md): computation rides the
  stream once, amortized over every consumer.

- **Membership carried across ticks** — each group holds the row set
  currently matching its predicate. A tick advances membership from
  the panel's CHANGED rows only (the hub already diffs the panel for
  its row-keyed delta stream): unchanged rows cannot change a pure
  predicate's verdict, so per-tick predicate cost is O(churn), not
  O(panel).

- **enter / leave / change events** — first-class delta kinds
  (``query/delta.py`` applies them): ``enter`` ships rows newly
  matching, ``leave`` ships the keys of rows that stopped matching
  (or left the panel), ``change`` ships members whose row bytes moved
  while still matching. Applying a tick's event chain client-side
  rebuilds the canonical membership response byte-exactly
  (property-tested against a brute-force replay oracle in
  ``tests/test_cq.py``).

Two evaluation domains, one grouping/lifecycle core:

- **row domain** (the hub): rendered JSON rows re-enter the criteria
  engine through :func:`columns_of_rows` (enum names decode back to
  ordinals via the field map's ``from_json``);
- **column domain** (the alert manager): raw snapshot columns — the
  same arrays queries render from — keep alert rows byte-identical to
  the legacy evaluator while the per-def predicate scan collapses
  into the shared group pass (:func:`advance_entities` is the
  enter/stay/leave lifecycle step alertdefs consume: fire on enter,
  count consecutive membership, resolve on leave).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from gyeeta_tpu.query import criteria, delta as D, fieldmaps
from gyeeta_tpu.query.normalize import canonical_filter, request_key

# a continuous query's panel render: the FULL panel, one render per
# (panel, tick) shared by every criteria group standing on it (and by
# any plain subscriber of the same normalized request)
PANEL_MAXRECS = 1_000_000


def panel_request(subsys: str) -> dict:
    return {"subsys": subsys, "maxrecs": PANEL_MAXRECS}


def normalize_cq(subsys: str, filt: str) -> dict:
    """Canonical continuous-query envelope: the grouping identity.
    ``cq: true`` keeps the key disjoint from plain subscriptions of
    the same filter (they deliver different event streams)."""
    return {"subsys": subsys, "filter": canonical_filter(filt),
            "cq": True}


def group_key(subsys: str, filt: str) -> str:
    return request_key(normalize_cq(subsys, filt))


def parse_standing(subsys: str, filt: str):
    """Validate one standing filter at registration time →
    ``(canonical_filter, tree)``. Raises ``ValueError`` (or the
    criteria ``ParseError`` subclass) on an empty/unparseable filter,
    an unknown subsystem, or criteria targeting a foreign subsystem
    (which would silently match every row — same guard alertdefs
    get)."""
    fieldmaps.check_subsys(subsys)
    tree = criteria.parse(filt)
    if tree is None:
        raise ValueError("a continuous query needs a non-empty filter")
    criteria.check_filter_subsys(tree, subsys, what="continuous query")
    return canonical_filter(filt), tree


def panel_kf(subsys: str):
    """STABLE identity keying for a subsystem's membership rows: the
    delta tier's identity-field preference order restricted to the
    subsystem's field map. Computed from the schema — not per tick
    from observed rows — so hub, replay oracle, and a reconnecting
    client key identically at every tick (including empty panels)."""
    fmap = fieldmaps.field_map(subsys)
    kf = [f for f in D._KEY_FIELDS if f in fmap]    # noqa: SLF001
    return kf or "*"


def row_key(row: dict, kf) -> str:
    return D._key_of(row, kf)                       # noqa: SLF001


# ------------------------------------------------- row-domain predicate
def columns_of_rows(subsys: str, rows: list) -> dict:
    """Rendered JSON rows → the criteria engine's column domain.
    Inverse of the render direction: enum name strings decode to
    ordinals (``fd.from_json``), numeric/bool fields coerce to float64
    vectors, strings stay object arrays. Fields absent from the rows
    are absent from the columns (a criterion on one raises KeyError —
    the caller renders full panels, so this only bites projected
    responses, which continuous queries never are)."""
    cols: dict = {}
    if not rows:
        return cols
    fmap = fieldmaps.field_map(subsys)
    present = rows[0].keys()
    for jname, fd in fmap.items():
        if jname not in present:
            continue
        vals = [r.get(jname) for r in rows]
        if fd.kind == "enum":
            dec = fd.from_json
            out = np.empty(len(vals), np.float64)
            for i, v in enumerate(vals):
                try:
                    out[i] = dec(v)
                except (ValueError, TypeError):
                    out[i] = -1.0
            cols[fd.col] = out
        elif fd.kind in ("num", "bool"):
            out = np.empty(len(vals), np.float64)
            for i, v in enumerate(vals):
                try:
                    out[i] = float(v) if v is not None else 0.0
                except (ValueError, TypeError):
                    out[i] = 0.0
            cols[fd.col] = out
        else:
            cols[fd.col] = np.array(
                ["" if v is None else str(v) for v in vals], object)
    return cols


def match_mask(tree, subsys: str, rows: list,
               cols: Optional[dict] = None) -> np.ndarray:
    """One vectorized predicate pass over rendered rows → bool mask.
    Pass ``cols`` (from :func:`columns_of_rows`) to share the decode
    across the panel's criteria groups."""
    if not rows:
        return np.zeros(0, bool)
    if cols is None:
        cols = columns_of_rows(subsys, rows)
    return criteria.evaluate(tree, cols, subsys)


# ------------------------------------------------ membership lifecycle
class Membership:
    """One criteria group's row membership, carried across ticks.
    ``snaptick`` is the tick membership (or a member's row) last
    CHANGED — quiet ticks advance the stream with heartbeat acks, not
    new versions, so the version ring stores only change points."""

    __slots__ = ("subsys", "filt", "tree", "kf", "members", "snaptick")

    def __init__(self, subsys: str, filt: str, tree, kf=None,
                 members: Optional[dict] = None, snaptick=None):
        self.subsys = subsys
        self.filt = filt
        self.tree = tree
        self.kf = panel_kf(subsys) if kf is None else kf
        self.members: dict = {} if members is None else members
        self.snaptick = snaptick


def panel_diff(prev_map: dict, curr_map: dict):
    """One panel's tick step, shared by every group standing on it:
    ``(changed_keys, changed_rows, removed_keys)`` — rows new or
    byte-different since the last tick, and keys gone from the
    panel."""
    changed_keys, changed_rows = [], []
    for k, r in curr_map.items():
        if prev_map.get(k) != r:
            changed_keys.append(k)
            changed_rows.append(r)
    removed = [k for k in prev_map if k not in curr_map]
    return changed_keys, changed_rows, removed


def _sorted_dict(d: dict) -> dict:
    return {k: d[k] for k in sorted(d)}


def advance(m: Membership, changed_keys, changed_rows, match,
            removed, tick):
    """Advance one group's membership from the panel's changed rows →
    ``(enter, change, leave)`` (key-sorted dicts / key list). Mutates
    ``m.members`` and bumps ``m.snaptick`` to ``tick`` iff anything
    moved. Incremental is exact: an unchanged row keeps its predicate
    verdict (the oracle equivalence ``tests/test_cq.py`` pins)."""
    enter, change, leave = {}, {}, []
    for k, r, hit in zip(changed_keys, changed_rows, match):
        if hit:
            old = m.members.get(k)
            if old is None:
                enter[k] = r
            elif old != r:
                change[k] = r
            m.members[k] = r
        elif k in m.members:
            del m.members[k]
            leave.append(k)
    for k in removed:
        if k in m.members:
            del m.members[k]
            leave.append(k)
    leave.sort()
    enter = _sorted_dict(enter)
    change = _sorted_dict(change)
    if enter or change or leave:
        m.snaptick = tick
    return enter, change, leave


def rebuild(m: Membership, new_members: dict, tick):
    """Full (non-incremental) membership step: diff the freshly
    evaluated match set against the held one — the subscribe-time
    priming / retained-group refresh path, and the replay oracle's
    per-tick step."""
    enter = _sorted_dict({k: r for k, r in new_members.items()
                          if k not in m.members})
    change = _sorted_dict({k: r for k, r in new_members.items()
                           if k in m.members and m.members[k] != r})
    leave = sorted(k for k in m.members if k not in new_members)
    m.members = dict(new_members)
    if enter or change or leave:
        m.snaptick = tick
    return enter, change, leave


def advance_entities(members: set, hits: set):
    """Set-domain lifecycle step (the alert manager's view of the same
    engine): ``(enter, stay, leave)`` entity-key sets. A def FIRES on
    enter (after ``numcheckfor`` consecutive membership ticks — enter
    then stay), and RESOLVES on leave."""
    return hits - members, hits & members, members - hits


# ----------------------------------------------------- event envelope
def cq_response(subsys: str, filt: str, kf, snaptick,
                members: dict) -> dict:
    """The canonical membership response — what ``full`` events carry
    and what applying an event chain rebuilds byte-exactly. Rows sort
    by their membership key: deterministic without carrying an order
    vector (membership is a SET; panels keep ordering semantics)."""
    return {"subsys": subsys, "cqfilter": filt, "kf": kf,
            "snaptick": snaptick, "nrecs": len(members),
            "recs": [members[k] for k in sorted(members)]}


def response_of(m: Membership) -> dict:
    return cq_response(m.subsys, m.filt, m.kf, m.snaptick, m.members)


def members_of_response(resp: dict) -> dict:
    kf = resp.get("kf", "*")
    return {row_key(r, kf): r for r in resp.get("recs") or []}


def events_of(base, tick, kf, enter: dict, change: dict,
              leave: list) -> list:
    """One tick's membership movement → the first-class event chain
    (``leave`` → ``change`` → ``enter``, each kind only when
    non-empty). Bases chain WITHIN the tick: the first event bases on
    the group's previous version, the rest on the tick itself, so
    ``delta.apply_event`` applied in order needs no lookahead."""
    evs = []
    b = base
    if leave:
        evs.append({"t": "leave", "snaptick": tick, "base": b,
                    "kf": kf, "keys": leave})
        b = tick
    if change:
        evs.append({"t": "change", "snaptick": tick, "base": b,
                    "kf": kf, "rows": change})
        b = tick
    if enter:
        evs.append({"t": "enter", "snaptick": tick, "base": b,
                    "kf": kf, "rows": enter})
    return evs
