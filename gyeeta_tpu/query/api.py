"""Query API: QUERY_OPTIONS-style requests over live engine state.

The point-in-time path of the reference's web query engine
(``common/gy_query_common.h:24`` QUERY_OPTIONS parse →
``server/gy_mnodehandle.cc:203`` web_query_route_qtype → per-subsystem
``web_curr_*`` walks): here a request is one device readback + one columnar
criteria mask + host-side JSON row assembly. Freshness = one snapshot
latency (<1s north star); the historical path is ``gyeeta_tpu.history``.

Request shape (JSON-compatible dict, matching the Node webserver's query
envelope semantics)::

    {"subsys": "svcstate", "filter": "{ svcstate.state in 'Bad','Severe' }",
     "columns": ["svcid", "p95resp5s", "state"],    # optional projection
     "sortcol": "p95resp5s", "sortdesc": true,      # optional sort
     "maxrecs": 100}
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import numpy as np

from gyeeta_tpu.engine.aggstate import AggState, EngineCfg
from gyeeta_tpu.ingest import decode as D
from gyeeta_tpu.query import criteria, fieldmaps, readback
from gyeeta_tpu.semantic import hoststate


class QueryOptions(NamedTuple):
    subsys: str
    filter: Optional[str] = None
    columns: Optional[tuple] = None
    sortcol: Optional[str] = None
    sortdesc: bool = True
    maxrecs: int = 1000
    aggr: Optional[tuple] = None       # e.g. ("avg(qps5s)", "count(*)")
    groupby: Optional[tuple] = None    # e.g. ("hostid",)

    @classmethod
    def from_json(cls, req: dict) -> "QueryOptions":
        known = {"subsys", "filter", "columns", "sortcol", "sortdesc",
                 "maxrecs", "aggr", "groupby"}
        unknown = set(req) - known
        if unknown:
            raise ValueError(f"unknown query options: {sorted(unknown)}")
        if "subsys" not in req:
            raise ValueError("query needs 'subsys'")
        cols = req.get("columns")
        ag = req.get("aggr")
        gb = req.get("groupby")
        if isinstance(ag, str):
            ag = [ag]
        if isinstance(gb, str):
            gb = [gb]
        return cls(
            subsys=req["subsys"], filter=req.get("filter"),
            columns=tuple(cols) if cols else None,
            sortcol=req.get("sortcol"),
            sortdesc=bool(req.get("sortdesc", True)),
            maxrecs=int(req.get("maxrecs", 1000)),
            aggr=tuple(ag) if ag else None,
            groupby=tuple(gb) if gb else None,
        )


def _hex_id(hi, lo):
    gid = (hi.astype(np.uint64) << np.uint64(32)) | lo.astype(np.uint64)
    return np.array([format(int(g), "016x") for g in gid], object)


def _names_of(names, kind, hi, lo):
    """Resolve interned 64-bit ids to names (hex-id fallback)."""
    if names is None:
        return _hex_id(hi, lo)
    ids = (hi.astype(np.uint64) << np.uint64(32)) | lo.astype(np.uint64)
    return names.resolve_array(kind, ids)


def _pad_idx(idx: np.ndarray, cap: int):
    """Row indices → (padded device array, true length). Padding to the
    next power of two bounds the jit-recompile count for the row-sliced
    readbacks at log2(capacity) shapes."""
    import jax.numpy as jnp

    n = len(idx)
    p = 8
    while p < n:
        p <<= 1
    p = min(p, cap)
    out = np.zeros(p, np.int32)
    out[:n] = idx
    return jnp.asarray(out), n


_QCOLS_OF_LEVEL = {
    -1: (("resp5s", "resp5s_us"), ("p95resp5s", "p95resp5s_us"),
         ("p99resp5s", "p99resp5s_us")),
    0: (("p95resp5m", "p95resp5m_us"),),
    1: (("p50resp5d", "p50resp5d_us"), ("p95resp5d", "p95resp5d_us")),
}


def svc_columns(cfg: EngineCfg, st: AggState, names=None):
    """svcstate subsystem columns (reference JSON names' units: msec).

    Returns a :class:`~gyeeta_tpu.query.lazycols.LazyCols`: the cheap
    gauge panel is eager; the per-window latency quantiles, volume/HLL
    sweeps and string columns materialize group-at-a-time only when a
    filter/sort references them, with O(result) row-sliced loaders for
    projection (VERDICT r4 #6 — a typical query no longer reads every
    (S, B) window or formats S hex ids)."""
    from gyeeta_tpu.ingest import wire
    from gyeeta_tpu.query.lazycols import LazyCols

    base = {k: np.asarray(v)
            for k, v in readback.svcstate_base(cfg, st).items()}
    g = base["stats"]
    hi, lo = base["glob_id_hi"], base["glob_id_lo"]
    eager = {
        "nconns": g[:, D.STAT_NCONNS],
        "nactive": g[:, D.STAT_NCONNS_ACTIVE],
        "nprocs": g[:, D.STAT_NTASKS],
        "kbin15s": g[:, D.STAT_KB_IN],
        "kbout15s": g[:, D.STAT_KB_OUT],
        "sererr": g[:, D.STAT_SER_ERRORS],
        "clierr": g[:, D.STAT_CLI_ERRORS],
        "delayus": g[:, D.STAT_TASKS_DELAY_US],
        "cpudelus": g[:, D.STAT_TASKS_CPUDELAY_US],
        "iodelus": g[:, D.STAT_TASKS_BLKIODELAY_US],
        "usercpu": g[:, D.STAT_USER_CPU],
        "syscpu": g[:, D.STAT_SYS_CPU],
        "rssmb": g[:, D.STAT_RSS_MB],
        "nissue": g[:, D.STAT_NTASKS_ISSUE],
        "state": base["state"],
        "issue": base["issue"],
        "hostid": base["hostid"],
    }

    def _qcols(level, d):
        d = {k: np.asarray(v) for k, v in d.items()}
        return {col: d[src] / 1e3 for col, src in _QCOLS_OF_LEVEL[level]}

    def _qload(level):
        return lambda: _qcols(level,
                              readback.svcstate_qlevel(cfg, st, level))

    def _qrows(level):
        def load(idx):
            pidx, n = _pad_idx(idx, cfg.svc_capacity)
            d = readback.svcstate_qlevel_rows(cfg, st, pidx, level)
            return {k: v[:n] for k, v in _qcols(level, d).items()}
        return load

    def _vol_rows(idx):
        pidx, n = _pad_idx(idx, cfg.svc_capacity)
        d = readback.svcstate_vol_rows(cfg, st, pidx)
        return {k: np.asarray(v)[:n] for k, v in d.items()}

    def _cli_rows(idx):
        pidx, n = _pad_idx(idx, cfg.svc_capacity)
        d = readback.svcstate_cli_rows(cfg, st, pidx)
        return {k: np.asarray(v)[:n] for k, v in d.items()}

    group_of = {"svcid": "sid", "svcname": "sname",
                "nqry5s": "vol", "qps5s": "vol", "nclients": "cli"}
    load = {
        "sid": lambda: {"svcid": _hex_id(hi, lo)},
        "sname": lambda: {"svcname": _names_of(
            names, wire.NAME_KIND_SVC, hi, lo)},
        "vol": lambda: {k: np.asarray(v) for k, v in
                        readback.svcstate_vol(cfg, st).items()},
        "cli": lambda: {k: np.asarray(v) for k, v in
                        readback.svcstate_cli(cfg, st).items()},
    }
    load_rows = {
        "sid": lambda idx: {"svcid": _hex_id(hi[idx], lo[idx])},
        "sname": lambda idx: {"svcname": _names_of(
            names, wire.NAME_KIND_SVC, hi[idx], lo[idx])},
        "vol": _vol_rows,
        "cli": _cli_rows,
    }
    for level, pairs in _QCOLS_OF_LEVEL.items():
        key = f"q{level}"
        for col, _src in pairs:
            group_of[col] = key
        load[key] = _qload(level)
        load_rows[key] = _qrows(level)
    return LazyCols(eager, group_of, load, load_rows), base["live"]


# a host is Down after this many base ticks without a report (6 x 5s = 30s;
# ref: server marks parthas inactive on missed status pings,
# gy_comm_proto.h:974 PARTHA_STATUS + conn timeouts gy_mconnhdlr.h:79)
DOWN_AFTER_TICKS = 6


def host_columns(cfg: EngineCfg, st: AggState, names=None) -> dict:
    panel = np.asarray(st.host_panel)
    last = np.asarray(st.host_last_tick)
    now = int(np.asarray(st.resp_win.tick))
    reported = last >= 0
    down = reported & (now - last > DOWN_AFTER_TICKS)
    states = hoststate.classify_hosts(
        ntask_issue=panel[:, D.HOST_NTASKS_ISSUE],
        ntask_severe=panel[:, D.HOST_NTASKS_SEVERE],
        nlisten_issue=panel[:, D.HOST_NLISTEN_ISSUE],
        nlisten_severe=panel[:, D.HOST_NLISTEN_SEVERE],
        cpu_issue=panel[:, D.HOST_CPU_ISSUE] > 0,
        mem_issue=panel[:, D.HOST_MEM_ISSUE] > 0,
        severe_cpu=panel[:, D.HOST_SEVERE_CPU] > 0,
        severe_mem=panel[:, D.HOST_SEVERE_MEM] > 0)
    from gyeeta_tpu.semantic.states import STATE_DOWN
    states = np.where(down, STATE_DOWN, states)
    hostids, hostnames = _host_name_cols(panel.shape[0], names)
    cols = {
        "hostid": hostids,
        "hostname": hostnames,
        "nprocissue": panel[:, D.HOST_NTASKS_ISSUE],
        "nprocsevere": panel[:, D.HOST_NTASKS_SEVERE],
        "nproc": panel[:, D.HOST_NTASKS],
        "nlistissue": panel[:, D.HOST_NLISTEN_ISSUE],
        "nlistsevere": panel[:, D.HOST_NLISTEN_SEVERE],
        "nlisten": panel[:, D.HOST_NLISTEN],
        "state": states,
        "cpuissue": panel[:, D.HOST_CPU_ISSUE],
        "memissue": panel[:, D.HOST_MEM_ISSUE],
        "severecpu": panel[:, D.HOST_SEVERE_CPU],
        "severemem": panel[:, D.HOST_SEVERE_MEM],
    }
    return cols, reported


def task_columns(cfg: EngineCfg, st: AggState, names=None) -> dict:
    """taskstate subsystem columns (ref MAGGR_TASK / aggrtaskstate)."""
    snap = {k: np.asarray(v)
            for k, v in readback.task_snapshot(cfg, st).items()}
    g = snap["stats"]
    cols = _task_identity_cols(snap, names)
    cols |= {
        "tcpkb": g[:, D.TASK_TCP_KB],
        "tcpconns": g[:, D.TASK_TCP_CONNS],
        "cpu": g[:, D.TASK_CPU_PCT],
        "cpup95": snap["cpu_p95"],
        "rssmb": g[:, D.TASK_RSS_MB],
        "cpudelms": g[:, D.TASK_CPU_DELAY_MS],
        "vmdelms": g[:, D.TASK_VM_DELAY_MS],
        "iodelms": g[:, D.TASK_BLKIO_DELAY_MS],
        "ntasks": g[:, D.TASK_NTASKS],
        "nissue": g[:, D.TASK_NTASKS_ISSUE],
        "forks": g[:, D.TASK_FORKS_SEC],
        "state": snap["state"],
        "issue": snap["issue"],
        "hostid": snap["hostid"],
    }
    return cols, snap["live"]


def flow_columns(cfg: EngineCfg, st: AggState, k: int = 128,
                 names=None) -> dict:
    snap = {kk: np.asarray(v)
            for kk, v in readback.flow_snapshot(cfg, st, k).items()}
    valid = snap["flow_bytes"] > 0
    cols = {
        "flowid": _hex_id(snap["flow_hi"], snap["flow_lo"]),
        "bytes": snap["flow_bytes"],
        "evictedbytes": np.full(len(valid), float(snap["evicted_bytes"])),
    }
    return cols, valid


# rows emitted per topk metric before maxrecs/filters apply — the
# union view stays bounded no matter the slab geometry (the reference
# caps its TOP_N walks the same way, gy_comm_proto.h:1415)
TOPK_PER_METRIC = 64


def heavy_topk_columns(flow_rows, svc=None, trace=None,
                       per_metric: int = TOPK_PER_METRIC):
    """The ``topk`` subsystem's union columns — shared by Runtime and
    ShardedRuntime so the three query edges render identical rows.

    ``flow_rows``: pre-merged heavy flows as ``(id_hex, value,
    errbound, source)`` tuples sorted heaviest-first (exact top-K lanes
    ∪ invertible-sketch recoveries — see ``Runtime.heavy_recover``).
    ``svc``/``trace``: the subsystem's (cols, live) snapshots for the
    dense rankings (top services by conns / error rate, top APIs by
    p99). Every row carries its error bound: exact lanes undercount by
    ≤ errbound, recovered rows are upper bounds overcounting by ≤
    errbound, dense rows are exact (0).
    """
    metric, rank, ids, names_, value, errb, source = \
        [], [], [], [], [], [], []

    def emit(m, rows):
        for i, (rid, rname, val, eb, src) in enumerate(
                rows[:per_metric]):
            metric.append(m)
            rank.append(float(i + 1))
            ids.append(rid)
            names_.append(rname)
            value.append(float(val))
            errb.append(float(eb))
            source.append(src)

    emit("bytes", [(rid, "", val, eb, src)
                   for rid, val, eb, src in flow_rows])

    def dense(cols, live, valcol, idcol, namecol, valfn=None):
        from gyeeta_tpu.query.lazycols import rows_of

        idx = np.nonzero(np.asarray(live, bool))[0]
        if len(idx) == 0:
            return []
        vals = (valfn(cols, idx) if valfn is not None
                else np.asarray(cols[valcol], np.float64)[idx])
        order = np.argsort(vals, kind="stable")[::-1]
        keep = order[: per_metric]
        keep = keep[vals[keep] > 0]
        # id/name projection over just the kept rows (LazyCols row
        # path — the string groups never format at slab width here)
        got = rows_of(cols, [idcol, namecol], idx[keep])
        rows = [(got[idcol][j], got[namecol][j], vals[keep[j]], 0.0,
                 "dense") for j in range(len(keep))]
        # deterministic rank on TIED values: value desc, id asc — the
        # kept window renders bit-identically whether the rows came
        # from one slab or a mesh's concatenated shard slabs (lane
        # order differs; the ranking must not)
        rows.sort(key=lambda r: (-r[2], r[0]))
        return rows

    if svc is not None:
        scols, slive = svc
        emit("conns", dense(scols, slive, "nconns", "svcid", "svcname"))

        def errrate(cols, idx):
            err = np.asarray(cols["sererr"], np.float64)[idx]
            nq = np.asarray(cols["nqry5s"], np.float64)[idx]
            return err / np.maximum(nq, 1.0)

        emit("errrate", dense(scols, slive, None, "svcid", "svcname",
                              valfn=errrate))
    if trace is not None:
        from gyeeta_tpu.query.lazycols import rows_of

        tcols, tlive = trace
        idx = np.nonzero(np.asarray(tlive, bool))[0]
        rows = []
        if len(idx):
            p99 = np.asarray(tcols["p99resp"], np.float64)[idx]
            keep = np.argsort(p99, kind="stable")[::-1][: per_metric]
            keep = keep[p99[keep] > 0]
            got = rows_of(tcols, ["svcid", "svcname", "api"], idx[keep])
            rows = [(got["svcid"][j],
                     f"{got['svcname'][j]}:{got['api'][j]}",
                     p99[keep[j]], 0.0, "dense")
                    for j in range(len(keep))]
            rows.sort(key=lambda r: (-r[2], r[0], r[1]))
        emit("p99resp", rows)

    n = len(metric)
    obj = lambda vals: _obj_col(vals)  # noqa: E731
    cols = {
        "metric": obj(metric), "rank": np.asarray(rank, np.float64),
        "id": obj(ids), "name": obj(names_),
        "value": np.asarray(value, np.float64),
        "errbound": np.asarray(errb, np.float64),
        "source": obj(source),
    }
    return cols, np.ones(n, bool)


def _obj_col(vals) -> np.ndarray:
    out = np.empty(len(vals), object)
    out[:] = [str(v) for v in vals]
    return out


def _host_name_cols(n: int, names):
    """(hostids, hostnames) shared by every host-axis subsystem."""
    from gyeeta_tpu.ingest import wire

    hostids = np.arange(n)
    if names is None:
        hostnames = np.array([str(h) for h in hostids], object)
    else:
        hostnames = np.array(
            [names.lookup(wire.NAME_KIND_HOST, h) or str(h)
             for h in hostids], object)
    return hostids, hostnames


def cpumem_columns(cfg: EngineCfg, st: AggState, names=None) -> dict:
    """cpumem subsystem: raw 2s gauges + server-side classification."""
    vals = np.asarray(st.host_cm)
    last = np.asarray(st.cm_last_tick)
    reported = last >= 0
    hostids, hostnames = _host_name_cols(vals.shape[0], names)
    cols = {
        "hostid": hostids,
        "hostname": hostnames,
        "cpu": vals[:, D.CM_CPU_PCT],
        "usercpu": vals[:, D.CM_USERCPU_PCT],
        "syscpu": vals[:, D.CM_SYSCPU_PCT],
        "iowait": vals[:, D.CM_IOWAIT_PCT],
        "corecpu": vals[:, D.CM_MAX_CORE_CPU_PCT],
        "cs": vals[:, D.CM_CS_SEC],
        "forks": vals[:, D.CM_FORKS_SEC],
        "runq": vals[:, D.CM_PROCS_RUNNING],
        "rsspct": vals[:, D.CM_RSS_PCT],
        "commitpct": vals[:, D.CM_COMMIT_PCT],
        "swapfreepct": vals[:, D.CM_SWAP_FREE_PCT],
        "pginout": vals[:, D.CM_PG_INOUT_SEC],
        "swapinout": vals[:, D.CM_SWAP_INOUT_SEC],
        "allocstall": vals[:, D.CM_ALLOCSTALL_SEC],
        "oom": vals[:, D.CM_OOM_KILLS],
        "cpustate": np.asarray(st.cm_cpu_state),
        "cpuissue": np.asarray(st.cm_cpu_issue),
        "memstate": np.asarray(st.cm_mem_state),
        "memissue": np.asarray(st.cm_mem_issue),
    }
    return cols, reported


def trace_columns(cfg: EngineCfg, st: AggState, names=None) -> dict:
    """tracereq subsystem: per-(service, API) latency aggregates."""
    from gyeeta_tpu.engine import step as S
    from gyeeta_tpu.ingest import wire

    snap = {k: np.asarray(v)
            for k, v in readback.trace_snapshot(cfg, st).items()}
    ctr = snap["ctr"]
    cols = {
        "svcid": _hex_id(snap["svc_hi"], snap["svc_lo"]),
        "svcname": _names_of(names, wire.NAME_KIND_SVC,
                             snap["svc_hi"], snap["svc_lo"]),
        "api": _names_of(names, wire.NAME_KIND_API,
                         snap["api_hi"], snap["api_lo"]),
        "proto": snap["proto"],
        "nreq": ctr[:, S.APIC_NREQ],
        "nerr": ctr[:, S.APIC_NERR],
        "bytesin": ctr[:, S.APIC_BYTES_IN],
        "bytesout": ctr[:, S.APIC_BYTES_OUT],
        "p50resp": snap["p50_us"] / 1e3,
        "p95resp": snap["p95_us"] / 1e3,
        "p99resp": snap["p99_us"] / 1e3,
        "hostid": snap["hostid"],
    }
    return cols, snap["live"]


def cluster_columns(cfg: EngineCfg, st: AggState, names=None) -> dict:
    hcols, reported = host_columns(cfg, st)
    c = hoststate.cluster_state(np.asarray(hcols["state"]), valid=reported)
    cols = {k: np.array([float(v)]) for k, v in c.items()}
    return cols, np.ones(1, bool)


def task_comm_names_from(names, key, comm, live, task_hi, task_lo):
    """Resolve process-group ids → comm names given task-slab arrays
    (key/comm as u64, live mask) — shared by the single-node provider and
    the sharded runtime's gathered slabs."""
    from gyeeta_tpu.ingest import wire

    comm_of = dict(zip(key[live].tolist(), comm[live].tolist()))
    want = ((task_hi.astype(np.uint64) << np.uint64(32))
            | task_lo.astype(np.uint64))
    comm_ids = np.array([comm_of.get(int(t), 0) for t in want], np.uint64)
    if names is None:
        return _hex_id(task_hi, task_lo)
    resolved = names.resolve_array(wire.NAME_KIND_COMM, comm_ids)
    fallback = _hex_id(task_hi, task_lo)
    return np.where(comm_ids != 0, resolved, fallback)


def _task_slab_arrays(st: AggState):
    key = (np.asarray(st.task_tbl.key_hi).astype(np.uint64)
           << np.uint64(32)) | np.asarray(st.task_tbl.key_lo)
    comm = (np.asarray(st.task_comm_hi).astype(np.uint64)
            << np.uint64(32)) | np.asarray(st.task_comm_lo)
    live = np.asarray(
        (st.task_tbl.key_hi != np.uint32(0xFFFFFFFF))
        | (st.task_tbl.key_lo != np.uint32(0xFFFFFFFF)))
    return key, comm, live


def _task_comm_names(st: AggState, names, task_hi, task_lo):
    """Resolve process-group ids → comm names via the live task slab (the
    reference resolves DEPENDS entries through MAGGR_TASK)."""
    key, comm, live = _task_slab_arrays(st)
    return task_comm_names_from(names, key, comm, live, task_hi, task_lo)


def dep_columns(cfg: EngineCfg, st: AggState, names=None,
                dep=None) -> dict:
    """svcdependency subsystem: one row per (caller → service) edge."""
    from gyeeta_tpu.ingest import wire

    if dep is None:
        raise ValueError("svcdependency needs a dependency graph "
                         "(runtime not configured with one)")
    snap = {k: np.asarray(v)
            for k, v in readback.dep_edges_snapshot(dep).items()}
    cli_svc = snap["e_cli_svc"]
    # caller name: listener name for svc→svc edges, comm (via the task
    # slab) for task→svc edges
    svc_names = _names_of(names, wire.NAME_KIND_SVC,
                          snap["e_cli_hi"], snap["e_cli_lo"])
    task_names = _task_comm_names(st, names, snap["e_cli_hi"],
                                  snap["e_cli_lo"])
    cols = {
        "cliid": _hex_id(snap["e_cli_hi"], snap["e_cli_lo"]),
        "cliname": np.where(cli_svc, svc_names, task_names),
        "clisvc": cli_svc,
        "serid": _hex_id(snap["e_ser_hi"], snap["e_ser_lo"]),
        "sername": _names_of(names, wire.NAME_KIND_SVC,
                             snap["e_ser_hi"], snap["e_ser_lo"]),
        "nconn": snap["e_nconn"],
        "bytes": snap["e_bytes"],
    }
    return cols, snap["e_live"]


def mesh_columns(cfg: EngineCfg, st: AggState, names=None,
                 dep=None) -> dict:
    """svcmesh subsystem: one row per service in the dependency mesh,
    labelled with its coalesced cluster (ref svc mesh clusters,
    ``server/gy_shconnhdlr.h:1301``)."""
    from gyeeta_tpu.ingest import wire

    if dep is None:
        raise ValueError("svcmesh needs a dependency graph")
    snap = {k: np.asarray(v)
            for k, v in readback.dep_mesh_snapshot(dep).items()}
    cols = {
        "svcid": _hex_id(snap["n_hi"], snap["n_lo"]),
        "svcname": _names_of(names, wire.NAME_KIND_SVC,
                             snap["n_hi"], snap["n_lo"]),
        "clusterid": snap["n_label"],
        "clustersize": snap["n_size"],
    }
    return cols, snap["n_mask"]


_COLUMNS_OF = {
    fieldmaps.SUBSYS_SVCSTATE: svc_columns,
    fieldmaps.SUBSYS_HOSTSTATE: host_columns,
    fieldmaps.SUBSYS_CLUSTERSTATE: cluster_columns,
    fieldmaps.SUBSYS_FLOWSTATE: flow_columns,
    fieldmaps.SUBSYS_TASKSTATE: task_columns,
    fieldmaps.SUBSYS_TOPCPU: task_columns,
    fieldmaps.SUBSYS_TOPRSS: task_columns,
    fieldmaps.SUBSYS_TOPDELAY: task_columns,
    fieldmaps.SUBSYS_TOPFORK: task_columns,
    fieldmaps.SUBSYS_CPUMEM: cpumem_columns,
    fieldmaps.SUBSYS_TRACEREQ: trace_columns,
}

def _group_edges(snap: dict, end: str):
    """Group live dep edges by one endpoint (``cli`` or ``ser``) →
    (hi, lo, inv, segsum, live_idx). One np.unique over the packed
    64-bit ids + np.add.at segment sums — shared by the activeconn
    (group by server) and clientconn (group by caller) views."""
    live = np.nonzero(snap["e_live"])[0]
    ids64 = ((snap[f"e_{end}_hi"][live].astype(np.uint64) << np.uint64(32))
             | snap[f"e_{end}_lo"][live].astype(np.uint64))
    ids, inv = np.unique(ids64, return_inverse=True)
    n = len(ids)

    def segsum(vals):
        out = np.zeros(n, np.float64)
        np.add.at(out, inv, vals.astype(np.float64))
        return out

    hi = (ids >> np.uint64(32)).astype(np.uint32)
    lo = (ids & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    return hi, lo, inv, segsum, live


def activeconn_from_edges(snap: dict, names=None):
    """Group a dep-edge column snapshot by server service (shared by the
    single-node and sharded activeconn providers)."""
    from gyeeta_tpu.ingest import wire

    hi, lo, inv, segsum, live = _group_edges(snap, "ser")
    cols = {
        "svcid": _hex_id(hi, lo),
        "svcname": _names_of(names, wire.NAME_KIND_SVC, hi, lo),
        "nclients": segsum(np.ones(len(live))),
        "nconn": segsum(snap["e_nconn"][live]),
        "bytes": segsum(snap["e_bytes"][live]),
        "nsvccli": segsum(snap["e_cli_svc"][live]),
    }
    return cols, np.ones(len(hi), bool)


def activeconn_columns(cfg: EngineCfg, st: AggState, names=None,
                       dep=None) -> dict:
    """activeconn subsystem: per-service caller rollup of the dep edges
    (ref activeconn/clientconn views over DEPENDS maps)."""
    if dep is None:
        raise ValueError("activeconn needs a dependency graph")
    snap = {k: np.asarray(v)
            for k, v in readback.dep_edges_snapshot(dep).items()}
    return activeconn_from_edges(snap, names)


def svcinfo_columns(cfg: EngineCfg, st: AggState, names=None,
                    svcreg=None) -> dict:
    """svcinfo subsystem: host-side listener-metadata registry."""
    if svcreg is None:
        raise ValueError("svcinfo needs the listener-info registry")
    return svcreg.columns(names)


def clientconn_from_edges(snap: dict, names=None, task_names_fn=None):
    """Group dep edges by CALLER (the clientconn view: what does this
    process-group / service call, ref remoteconn/clientconn tables).

    ``task_names_fn(hi, lo) -> names`` resolves task-group callers
    (single-node: the local task slab; sharded: gathered slabs)."""
    from gyeeta_tpu.ingest import wire

    hi, lo, inv, segsum, live = _group_edges(snap, "cli")
    is_svc = np.zeros(len(hi), bool)
    np.maximum.at(is_svc, inv, snap["e_cli_svc"][live].astype(bool))
    svc_names = _names_of(names, wire.NAME_KIND_SVC, hi, lo)
    task_names = (task_names_fn(hi, lo) if task_names_fn is not None
                  else _hex_id(hi, lo))
    cols = {
        "cliid": _hex_id(hi, lo),
        "cliname": np.where(is_svc, svc_names, task_names),
        "clisvc": is_svc,
        "nservers": segsum(np.ones(len(live))),
        "nconn": segsum(snap["e_nconn"][live]),
        "bytes": segsum(snap["e_bytes"][live]),
    }
    return cols, np.ones(len(hi), bool)


def clientconn_columns(cfg: EngineCfg, st: AggState, names=None,
                       dep=None) -> dict:
    if dep is None:
        raise ValueError("clientconn needs a dependency graph")
    snap = {k: np.asarray(v)
            for k, v in readback.dep_edges_snapshot(dep).items()}
    return clientconn_from_edges(
        snap, names, lambda hi, lo: _task_comm_names(st, names, hi, lo))


def svcsumm_from_svc(cols, live, names=None):
    """Group svcstate columns by host → svcsumm columns. Takes the
    ALREADY-MERGED columns so single-node and sharded paths summarize
    identically (grouping per shard would fragment hosts whose services
    land on several shards)."""
    from gyeeta_tpu.ingest import wire
    from gyeeta_tpu.semantic import states as S

    idx = np.nonzero(live)[0]
    hosts = np.asarray(cols["hostid"])[idx].astype(np.int64)
    ids, inv = np.unique(hosts, return_inverse=True)
    n = len(ids)

    def segsum(vals):
        out = np.zeros(n, np.float64)
        np.add.at(out, inv, np.asarray(vals, np.float64))
        return out

    state = np.asarray(cols["state"])[idx]
    if names is not None:
        hostnames = names.resolve_array(
            wire.NAME_KIND_HOST, ids.astype(np.uint64))
    else:
        hostnames = np.array([str(i) for i in ids], object)
    out = {
        "hostid": ids.astype(np.float64),
        "hostname": hostnames,
        "nsvc": segsum(np.ones(len(idx))),
        "nidle": segsum(state == S.STATE_IDLE),
        "ngood": segsum(state == S.STATE_GOOD),
        "nok": segsum(state == S.STATE_OK),
        "nbad": segsum(state == S.STATE_BAD),
        "nsevere": segsum(state == S.STATE_SEVERE),
        "ndown": segsum(state == S.STATE_DOWN),
        "nissue": segsum(state >= S.STATE_BAD),
        "totqps": segsum(np.asarray(cols["qps5s"])[idx]),
        "totactive": segsum(np.asarray(cols["nactive"])[idx]),
        "totkbin": segsum(np.asarray(cols["kbin15s"])[idx]),
        "totkbout": segsum(np.asarray(cols["kbout15s"])[idx]),
    }
    return out, np.ones(n, bool)


def svcsumm_columns(cfg: EngineCfg, st: AggState, names=None):
    """svcsumm subsystem: per-host service-state summary (the
    LISTEN_SUMM_STATS rollup, ``server/gy_msocket.h:841``)."""
    cols, live = svc_columns(cfg, st, names=names)
    return svcsumm_from_svc(cols, live, names)


_EXT_JOIN_KEYS = (("ip", ""), ("port", 0.0), ("comm", ""),
                  ("cmdline", ""), ("pid", 0.0), ("tstart", 0.0))


def info_join(cols, live, info_cols, idcol="svcid",
              keys=_EXT_JOIN_KEYS):
    """Left-join svcinfo metadata columns onto any column set whose
    ``idcol`` holds service glob-id hex strings — the "extended"
    subsystem mechanic (state ⋈ info, ``gy_mnodehandle.cc:4657``).
    Rows without announced metadata keep defaults."""
    from gyeeta_tpu.query.lazycols import LazyCols
    n = len(cols[idcol])
    # ext views are full-width joins: a lazy column set must
    # materialize everything (dict() alone would copy only the
    # already-loaded groups)
    joined = cols.full() if isinstance(cols, LazyCols) else dict(cols)
    out = {}
    for key, default in keys:
        col = np.empty(n, object if isinstance(default, str)
                       else np.float64)
        col[:] = default
        out[key] = col
    if info_cols:
        pos_of = {sid: j for j, sid in enumerate(info_cols["svcid"])}
        for i in np.nonzero(np.asarray(live, bool))[0]:
            j = pos_of.get(cols[idcol][i])
            if j is not None:
                for key, _ in keys:
                    out[key][i] = info_cols[key][j]
    joined.update(out)
    return joined, live


def extsvc_join(cols, live, info_cols):
    """Join svcstate columns with svcinfo columns on svcid (shared by
    single-node and sharded extsvcstate providers)."""
    return info_join(cols, live, info_cols)


def traceuniq_from_trace(tcols, tlive):
    """Group per-(svc, api) trace columns by service → traceuniq
    columns (ref traceuniqtbl). Shared by both runtimes."""
    idx = np.nonzero(np.asarray(tlive, bool))[0]
    svc = np.asarray(tcols["svcid"])[idx]
    ids, inv = np.unique(svc, return_inverse=True)
    n = len(ids)

    def segsum(vals):
        out = np.zeros(n, np.float64)
        np.add.at(out, inv, np.asarray(vals, np.float64))
        return out

    name_of = {}
    for j, i in enumerate(idx):
        name_of.setdefault(svc[j], tcols["svcname"][i])
    cols = {
        "svcid": ids.astype(object),
        "svcname": np.array([name_of[s] for s in ids], object),
        "napis": segsum(np.ones(len(idx))),
        "nreq": segsum(np.asarray(tcols["nreq"])[idx]),
        "nerr": segsum(np.asarray(tcols["nerr"])[idx]),
    }
    return cols, np.ones(n, bool)


def extsvcstate_columns(cfg: EngineCfg, st: AggState, names=None,
                        svcreg=None):
    """extsvcstate: svcstate ⋈ svcinfo on svcid (the reference's
    "extended" subsystems join state+info records,
    ``server/gy_mnodehandle.cc:4657``). State rows without announced
    metadata still appear, with empty info columns."""
    cols, live = svc_columns(cfg, st, names=names)
    info_cols, _ = (svcreg.columns(names) if svcreg is not None
                    else ({}, None))
    return extsvc_join(cols, live, info_cols)


def svcprocmap_columns(cfg: EngineCfg, st: AggState, names=None,
                       svcreg=None):
    """svcprocmap: listener ↔ process-group mapping via the shared
    related_listen_id (ref LISTEN_TASKMAP_NOTIFY,
    ``gy_comm_proto.h:2813``)."""
    tcols, tlive = task_columns(cfg, st, names=names)
    info_cols, _ = (svcreg.columns(names) if svcreg is not None
                    else (None, None))
    return svcprocmap_join(tcols, tlive, info_cols)


def svcprocmap_join(tcols, tlive, info_cols):
    """Join task columns with svcinfo on related_listen_id (shared by
    single-node and sharded providers — pass MERGED task columns)."""
    rows = {"svcid": [], "svcname": [], "relsvcid": [], "taskid": [],
            "comm": [], "hostid": []}
    if info_cols is not None and len(tcols["taskid"]):
        by_rel: dict[str, list[int]] = {}
        for i in np.nonzero(tlive)[0]:
            by_rel.setdefault(tcols["relsvcid"][i], []).append(i)
        for j, rel in enumerate(info_cols["relsvcid"]):
            for i in by_rel.get(rel, ()):
                rows["svcid"].append(info_cols["svcid"][j])
                rows["svcname"].append(info_cols["svcname"][j])
                rows["relsvcid"].append(rel)
                rows["taskid"].append(tcols["taskid"][i])
                rows["comm"].append(tcols["comm"][i])
                rows["hostid"].append(float(tcols["hostid"][i]))
    n = len(rows["svcid"])
    cols = {}
    for k, vals in rows.items():
        if k == "hostid":
            cols[k] = np.array(vals, np.float64)
        else:
            col = np.empty(n, object)
            col[:] = vals
            cols[k] = col
    return cols, np.ones(n, bool)


def _task_identity_cols(snap, names):
    """Shared identity columns over a task snapshot (taskid/comm/
    relsvcid rendering in ONE place for taskstate + procinfo)."""
    from gyeeta_tpu.ingest import wire

    return {
        "taskid": _hex_id(snap["key_hi"], snap["key_lo"]),
        "comm": _names_of(names, wire.NAME_KIND_COMM,
                          snap["comm_hi"], snap["comm_lo"]),
        "relsvcid": _hex_id(snap["rel_hi"], snap["rel_lo"]),
    }


def procinfo_columns(cfg: EngineCfg, st: AggState, names=None):
    """procinfo: the static face of the process-group slab (identity,
    placement, service linkage — ref aggrtaskinfotbl). Built straight
    from the task snapshot: the related-listener ids exist as (hi, lo)
    arrays there — no hex round trip."""
    from gyeeta_tpu.ingest import wire

    snap = {k: np.asarray(v)
            for k, v in readback.task_snapshot(cfg, st).items()}
    rel_ids = ((snap["rel_hi"].astype(np.uint64) << np.uint64(32))
               | snap["rel_lo"].astype(np.uint64))
    if names is not None:
        svcnames = names.resolve_array(wire.NAME_KIND_SVC, rel_ids,
                                       fallback_hex=False)
    else:
        svcnames = np.full(len(rel_ids), "", object)
    cols = _task_identity_cols(snap, names)
    cols.update({
        "svcname": np.where(rel_ids == 0, "", svcnames),
        "ntasks": snap["stats"][:, D.TASK_NTASKS],
        "hostid": snap["hostid"],
    })
    return cols, snap["live"]


# svcsumm derives from svc_columns (defined below the map literal)
_COLUMNS_OF[fieldmaps.SUBSYS_SVCSUMM] = svcsumm_columns
_COLUMNS_OF[fieldmaps.SUBSYS_PROCINFO] = procinfo_columns
_COLUMNS_OF[fieldmaps.SUBSYS_TOPPGCPU] = task_columns

# subsystems whose columns come from the dependency graph, not AggState
_DEP_COLUMNS_OF = {
    fieldmaps.SUBSYS_SVCDEP: dep_columns,
    fieldmaps.SUBSYS_SVCMESH: mesh_columns,
    fieldmaps.SUBSYS_ACTIVECONN: activeconn_columns,
    fieldmaps.SUBSYS_CLIENTCONN: clientconn_columns,
}

# subsystems backed by the host-side listener-metadata registry
_SVCREG_COLUMNS_OF = {
    fieldmaps.SUBSYS_SVCINFO: svcinfo_columns,
    fieldmaps.SUBSYS_EXTSVCSTATE: extsvcstate_columns,
    fieldmaps.SUBSYS_SVCPROCMAP: svcprocmap_columns,
}

# top-N views: preset sort + limit over taskstate columns
# (ref TASK_TOP_PROCS top-15 CPU / top-8 RSS, gy_comm_proto.h:1415)
_TOP_PRESETS = {
    fieldmaps.SUBSYS_TOPCPU: ("cpu", 15),
    fieldmaps.SUBSYS_TOPPGCPU: ("cpu", 10),   # ref top-10 PG CPU
    fieldmaps.SUBSYS_TOPRSS: ("rssmb", 8),
    fieldmaps.SUBSYS_TOPDELAY: ("cpudelms", 15),
    fieldmaps.SUBSYS_TOPFORK: ("forks", 15),
}


def columns_for(cfg: EngineCfg, st: AggState, subsys: str, names=None,
                dep=None, svcreg=None, aux=None):
    """Resolve a subsystem to its (cols, base_mask) column source —
    the ONE dispatch over aux providers ≻ host-side registries ≻
    dep-graph views ≻ device-slab readbacks. Shared by query execution
    and realtime alertdef evaluation so a subsystem added to one is
    automatically visible to the other."""
    if aux is not None and subsys in aux:
        return aux[subsys]()
    if subsys in _SVCREG_COLUMNS_OF:
        return _SVCREG_COLUMNS_OF[subsys](cfg, st, names=names,
                                          svcreg=svcreg)
    if subsys in _DEP_COLUMNS_OF:
        return _DEP_COLUMNS_OF[subsys](cfg, st, names=names, dep=dep)
    return _COLUMNS_OF[subsys](cfg, st, names=names)


# process-local subsystems answered by the runtime itself (no engine
# columns): self-metrics readback + Prometheus exposition. Shared by
# Runtime and ShardedRuntime so the two surfaces cannot drift.
LOCAL_SUBSYS = ("selfstats", "metrics")


def local_response(rt, req: dict, snapshot=None):
    """Answer a process-local subsystem for a runtime-like object
    (``.stats``/``.alerts``, optional ``.spans`` ring, and
    ``.engine_health()`` for the batched device readback), or None
    when ``req`` targets an engine subsystem.

    ``snapshot`` (an ``EngineSnapshot``) selects the snapshot-serving
    path: the scrape renders the gauges the last tick's health pass
    already refreshed instead of touching live device state — a
    /metrics scrape fleet can no longer stall the fold."""
    subsys = req.get("subsys")
    if subsys == "selfstats":
        from gyeeta_tpu.utils.selfstats import selfstats_response
        return selfstats_response(rt.stats, rt.alerts,
                                  spans=getattr(rt, "spans", None))
    if subsys == "metrics":
        from gyeeta_tpu.obs import prom
        if snapshot is None:
            # strong path: fold staged records + refresh the engine-
            # health gauges so the scrape sees current device state
            # (one batched transfer)
            rt.flush()
            rt.engine_health()
        else:
            # snapshot path: no flush, no device readback — refresh
            # only the snapshot-freshness gauges (the tracked-staleness
            # surface: alert when age exceeds ~3x the tick interval)
            rt.stats.gauge("snapshot_age_seconds", max(
                0.0, rt._clock() - snapshot.published_at))
            rt.stats.gauge("snapshot_tick", float(snapshot.tick))
        return prom.metrics_response(rt.stats, rt.alerts)
    return None


def execute(cfg: EngineCfg, st: AggState, opts: QueryOptions,
            names=None, dep=None, columns_fn=None, svcreg=None,
            aux=None) -> dict:
    """Run one point-in-time query → {"recs": [...], "nrecs": N}.

    ``columns_fn(subsys) -> (cols, base_mask)`` overrides the column
    source — the sharded runtime injects gathered/merged columns here so
    filter/sort/aggregation/projection run identically on one shard or a
    whole mesh (the multi-madhava scatter the Node webserver performs,
    ``server/gy_mnodehandle.cc:203``).

    ``aux`` maps extra subsystem names to zero-arg column providers —
    host-side registries (hostinfo, cgroupstate) and alert-manager views
    (alerts/alertdef/silences/inhibits) plug in here without this module
    importing them.
    """
    if opts.subsys not in fieldmaps.FIELDS_OF_SUBSYS:
        raise ValueError(f"unknown subsystem {opts.subsys!r}")
    if columns_fn is None and not any(
            opts.subsys in m for m in (_COLUMNS_OF, _DEP_COLUMNS_OF,
                                       _SVCREG_COLUMNS_OF, aux or {})):
        raise ValueError(f"unknown subsystem {opts.subsys!r}")
    preset = _TOP_PRESETS.get(opts.subsys)
    if preset is not None and opts.sortcol is None and not opts.aggr:
        opts = opts._replace(sortcol=preset[0],
                             maxrecs=min(opts.maxrecs, preset[1]))
    if columns_fn is not None:
        cols, base_mask = columns_fn(opts.subsys)
    else:
        cols, base_mask = columns_for(cfg, st, opts.subsys, names=names,
                                      dep=dep, svcreg=svcreg, aux=aux)
    tree = criteria.parse(opts.filter) if opts.filter else None
    mask = base_mask & criteria.evaluate(tree, cols, opts.subsys)
    idx = np.nonzero(mask)[0]

    if opts.aggr:
        from gyeeta_tpu.query import aggr as A

        if opts.groupby and "time" in opts.groupby:
            raise ValueError("groupby 'time' is historical-only")
        specs = [A.parse_aggr(s, opts.subsys) for s in opts.aggr]
        gb = A.parse_groupby(opts.groupby, opts.subsys)
        fmap = fieldmaps.field_map(opts.subsys)
        recs = A.aggregate_columns(cols, idx, specs, gb, fmap)
        if opts.sortcol:
            if opts.sortcol not in (tuple(s.alias for s in specs) + gb):
                raise ValueError(
                    f"sortcol {opts.sortcol!r} must be a groupby field "
                    f"or aggregation alias")
            recs.sort(key=lambda r: r[opts.sortcol],
                      reverse=opts.sortdesc)
        return {"recs": recs[: opts.maxrecs], "nrecs":
                min(len(recs), opts.maxrecs), "ngroups": len(recs)}

    if opts.sortcol:
        fmap = fieldmaps.field_map(opts.subsys)
        fd = fmap.get(opts.sortcol)
        if fd is None:
            raise ValueError(f"unknown sort column {opts.sortcol!r}")
        key = np.asarray(cols[fd.col])[idx]
        order = np.argsort(key, kind="stable")
        idx = idx[order[::-1] if opts.sortdesc else order]
    idx = idx[: opts.maxrecs]

    fmap = fieldmaps.field_map(opts.subsys)
    want = opts.columns or tuple(fmap)
    unknown = [c for c in want if c not in fmap]
    if unknown:
        raise ValueError(f"unknown columns {unknown}")
    # late materialization: project only the RESULT rows — lazy column
    # groups (svcstate quantiles, hex ids, name resolution) compute
    # over len(idx) rows, not capacity (VERDICT r4 #6)
    from gyeeta_tpu.query.lazycols import LazyCols
    colnames = [fmap[c].col for c in want if fmap[c].col in cols]
    if isinstance(cols, LazyCols):
        sliced = cols.rows_many(colnames, idx)
        recs = [fieldmaps.row_to_json(
            opts.subsys, {c: sliced[c][j] for c in colnames})
            for j in range(len(idx))]
    else:
        recs = [fieldmaps.row_to_json(
            opts.subsys, {c: cols[c][i] for c in colnames})
            for i in idx]
    return {"recs": recs, "nrecs": len(recs),
            "ntotal": int(base_mask.sum())}


def query_json(cfg: EngineCfg, st: AggState, req: dict,
               names=None, dep=None, svcreg=None, aux=None) -> dict:
    """JSON-envelope entry point (the NM-conn QUERY_CMD analogue)."""
    return execute(cfg, st, QueryOptions.from_json(req), names=names,
                   dep=dep, svcreg=svcreg, aux=aux)
