"""Query tier: device readbacks, criteria filters, field maps, JSON.

The analogue of the madhava web-query engine (``server/gy_mnodehandle.cc``
``web_query_*`` triads + ``common/gy_query_criteria.h`` filters): pointintime
queries are pure device readbacks of sketch state; filters compile to boolean
masks over readback columns; output is Gyeeta-shaped JSON.
"""

import importlib


def __getattr__(name):
    # readback pulls the engine (and with it jax); the thin-client
    # half of this package (normalize/delta/criteria/fieldmaps) must
    # stay importable without initializing an accelerator backend —
    # the fabric gateway (net/gateway.py) runs on boxes with no TPU
    if name == "readback":
        return importlib.import_module("gyeeta_tpu.query.readback")
    raise AttributeError(name)
