"""Query tier: device readbacks, criteria filters, field maps, JSON.

The analogue of the madhava web-query engine (``server/gy_mnodehandle.cc``
``web_query_*`` triads + ``common/gy_query_criteria.h`` filters): pointintime
queries are pure device readbacks of sketch state; filters compile to boolean
masks over readback columns; output is Gyeeta-shaped JSON.
"""

from gyeeta_tpu.query import readback  # noqa: F401
