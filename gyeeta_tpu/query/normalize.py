"""Shared request normalization: ONE canonical form for cache keying.

Two caches key on "the same query": the per-snapshot result cache
(``query/snapshot.py``, PR 9) and the gateway tier's distributed
(snaptick, request-hash) edge cache (``net/gateway.py``). They MUST
key identically, or a result rendered once on a serve replica misses
at the gateway (and vice versa) and the fleet pays the render twice.
This module is that single definition; both tiers import it.

Normalization is strictly semantics-preserving for the LIVE query
envelope (the only envelope either cache ever sees — CRUD, multiquery
and historical requests bypass both caches):

- key order never matters (the key is key-sorted canonical JSON);
- ``None`` values drop (absent and null are the same request);
- defaulted fields drop (``maxrecs`` at the :class:`QueryOptions`
  default, ``sortdesc=True``, ``consistency="snapshot"`` — the serving
  edge default);
- ``sortdesc`` without a ``sortcol`` drops entirely (it has no effect);
- single-string ``aggr``/``groupby``/``columns`` coerce to lists, and
  numeric strings for ``maxrecs`` coerce to int;
- filters canonicalize through the criteria parser: equivalent filter
  strings (whitespace, comparator aliases like ``==``/``~=``, numeric
  literal spellings like ``1`` vs ``1.0``) render to one canonical
  string. An unparseable filter keeps its raw text (the query will
  fail identically wherever it lands, so keying it raw is harmless).
"""

from __future__ import annotations

import json

# QueryOptions defaults (query/api.py) — a request carrying exactly
# these says nothing the bare request doesn't
_DEFAULTS = {"maxrecs": 1000, "sortdesc": True,
             "consistency": "snapshot"}

# envelope fields that make a request uncacheable / non-live — the
# callers gate on these before keying, but normalize() must still
# pass them through untouched so a key is never LOSSY
_PASSTHROUGH = ("at", "window", "tstart", "tend", "op", "multiquery")


def canonical_filter(s: str) -> str:
    """One canonical rendering per equivalence class of filter strings
    (modulo the criteria grammar). Unparseable input returns as-is."""
    from gyeeta_tpu.query import criteria

    try:
        tree = criteria.parse(s)
    except Exception:           # noqa: BLE001 — keyed raw, fails alike
        return s
    return _render_tree(tree)


def _render_val(v) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, float):
        return str(int(v)) if v == int(v) and abs(v) < 1e15 else repr(v)
    esc = str(v).replace("\\", "\\\\").replace("'", "\\'")
    return f"'{esc}'"


def _render_tree(node) -> str:
    from gyeeta_tpu.query.criteria import BoolNode, Criterion

    if isinstance(node, Criterion):
        vals = ",".join(_render_val(v) for v in node.values)
        return f"{{ {node.subsys}.{node.field} {node.op} {vals} }}"
    assert isinstance(node, BoolNode)
    if node.op == "not":
        return f"not {_render_tree(node.children[0])}"
    inner = f" {node.op} ".join(_render_tree(c) for c in node.children)
    return f"( {inner} )"


def normalize_request(req: dict) -> dict:
    """Canonical form of one live-query envelope (see module doc)."""
    out = {}
    for k in sorted(req):
        v = req[k]
        if v is None:
            continue
        if k == "maxrecs":
            try:
                v = int(v)
            except (TypeError, ValueError):
                pass
        elif k == "sortdesc":
            v = bool(v)
        elif k in ("aggr", "groupby", "columns"):
            v = [v] if isinstance(v, str) else list(v)
        elif k == "filter" and isinstance(v, str):
            v = canonical_filter(v)
        if _DEFAULTS.get(k, _SENTINEL) == v:
            continue
        out[k] = v
    if "sortcol" not in out:
        out.pop("sortdesc", None)
    return out


_SENTINEL = object()


def request_key(req: dict) -> str:
    """Normalized request hash key: key-sorted canonical JSON of the
    normalized envelope. Two dashboards asking the same question in a
    different spelling collapse to one render — on EVERY cache tier."""
    return json.dumps(normalize_request(req), sort_keys=True,
                      separators=(",", ":"), default=str)
