"""Criteria/filter engine: Gyeeta filter strings → vectorized masks.

Grammar-compatible with the reference's filter language
(``common/gy_query_criteria.h:56-84`` comparators; boolean nesting via
``gy_boolparse``): leaf criteria are ``{ subsys.field op value }``, composed
with ``and`` / ``or`` / ``not`` and parentheses, e.g.::

    ( { svcstate.state in 'Bad','Severe' } and { svcstate.qps5s > 100 } )
      or { svcstate.sererr > 0 }

Differences from the reference (deliberate):
- evaluation is **columnar**: one numpy/jnp vector op per criterion over the
  whole readback snapshot, instead of a per-row expression walk — the
  in-memory analogue of the reference's dual "in-memory eval" path;
- ``like`` uses Python ``re`` (the reference uses RE2);
- the DNF expansion step (boolstuff) is unnecessary — the tree evaluates
  directly with short-circuit-free vector ops.

Supported comparators: = == != < <= > >= substr notsubstr like notlike
in notin bit2 bit3 (~ ~= =~ !~ aliases).
"""

from __future__ import annotations

import re
from typing import NamedTuple, Optional

import numpy as np

from gyeeta_tpu.query import fieldmaps


class Criterion(NamedTuple):
    subsys: str
    field: str
    op: str
    values: tuple          # parsed literals (1 for scalar ops, n for in)


class BoolNode(NamedTuple):
    op: str                # "and" | "or" | "not"
    children: tuple


class ParseError(ValueError):
    pass


_COMP_ALIASES = {"==": "=", "~": "like", "~=": "like", "=~": "like",
                 "!~": "notlike"}
_COMPARATORS = ("<=", ">=", "!=", "==", "=~", "~=", "!~", "=", "<", ">",
                "~", "substr", "notsubstr", "like", "notlike", "in",
                "notin", "bit2", "bit3")

_TOKEN_RE = re.compile(r"""
    \s*(?:
      (?P<lbrace>\{) | (?P<rbrace>\}) |
      (?P<lparen>\() | (?P<rparen>\)) |
      (?P<comma>,) |
      (?P<str>'(?:[^'\\]|\\.)*') |
      (?P<num>-?\d+\.?\d*(?:[eE][+-]?\d+)?) |
      (?P<op><=|>=|!=|==|=~|~=|!~|[=<>~]) |
      (?P<word>[A-Za-z_][A-Za-z0-9_.]*)
    )""", re.VERBOSE)


def _tokenize(s: str):
    out, pos = [], 0
    while pos < len(s):
        m = _TOKEN_RE.match(s, pos)
        if not m or m.end() == pos:
            if s[pos:].strip() == "":
                break
            raise ParseError(f"bad token at {s[pos:pos+20]!r}")
        pos = m.end()
        kind = m.lastgroup
        out.append((kind, m.group(kind)))
    return out


class _Parser:
    def __init__(self, tokens):
        self.toks = tokens
        self.i = 0

    def peek(self):
        return self.toks[self.i] if self.i < len(self.toks) else (None, None)

    def next(self):
        t = self.peek()
        self.i += 1
        return t

    def expect(self, kind):
        k, v = self.next()
        if k != kind:
            raise ParseError(f"expected {kind}, got {k}:{v!r}")
        return v

    # expr := and_expr ('or' and_expr)*
    def expr(self):
        left = self.and_expr()
        while self.peek() == ("word", "or"):
            self.next()
            left = BoolNode("or", (left, self.and_expr()))
        return left

    def and_expr(self):
        left = self.unary()
        while self.peek() == ("word", "and"):
            self.next()
            left = BoolNode("and", (left, self.unary()))
        return left

    def unary(self):
        if self.peek() == ("word", "not"):
            self.next()
            return BoolNode("not", (self.unary(),))
        k, _ = self.peek()
        if k == "lparen":
            self.next()
            e = self.expr()
            self.expect("rparen")
            return e
        if k == "lbrace":
            return self.criterion()
        raise ParseError(f"unexpected token {self.peek()!r}")

    def criterion(self):
        self.expect("lbrace")
        path = self.expect("word")
        if "." not in path:
            raise ParseError(f"criterion field must be subsys.field: {path}")
        subsys, field = path.split(".", 1)
        k, v = self.next()
        if k == "op":
            op = v
        elif k == "word" and v in _COMPARATORS:
            op = v
        else:
            raise ParseError(f"expected comparator, got {v!r}")
        op = _COMP_ALIASES.get(op, op)
        vals = [self._literal()]
        while self.peek()[0] == "comma":
            self.next()
            vals.append(self._literal())
        self.expect("rbrace")
        if len(vals) > 1 and op not in ("in", "notin"):
            raise ParseError(
                f"comparator {op!r} takes one value; use 'in' for lists")
        if subsys not in fieldmaps.FIELDS_OF_SUBSYS:
            raise ParseError(
                f"unknown subsystem {subsys!r}; "
                f"one of {sorted(fieldmaps.FIELDS_OF_SUBSYS)}")
        if field not in fieldmaps.field_map(subsys):
            raise ParseError(f"unknown field {subsys}.{field}")
        return Criterion(subsys, field, op, tuple(vals))

    def _literal(self):
        k, v = self.next()
        if k == "str":
            return re.sub(r"\\(.)", r"\1", v[1:-1])
        if k == "num":
            return float(v)
        if k == "word" and v.lower() in ("true", "false"):
            return v.lower() == "true"
        raise ParseError(f"expected literal, got {v!r}")


def parse(s: str):
    """Filter string → expression tree (Criterion / BoolNode)."""
    toks = _tokenize(s)
    if not toks:
        return None
    p = _Parser(toks)
    tree = p.expr()
    if p.i != len(toks):
        raise ParseError(f"trailing tokens: {p.toks[p.i:]}")
    return tree


def subsystems_of(tree) -> set:
    if tree is None:
        return set()
    if isinstance(tree, Criterion):
        return {tree.subsys}
    return set().union(*(subsystems_of(c) for c in tree.children))


def check_filter_subsys(tree, subsys: str, what: str = "filter") -> None:
    """Definition-time guard: every criterion in ``tree`` must target
    ``subsys``. Evaluation treats foreign-subsystem criteria as
    all-pass (the CRIT_SKIP join semantics queries want), which turns a
    typo'd/mismatched subsys in an alertdef filter into a def that
    silently matches EVERY row — and that only surfaces at the first
    fold-time check. Fail it where the definition is created instead.
    """
    foreign = subsystems_of(tree) - {subsys}
    if foreign:
        raise ValueError(
            f"{what} criteria reference subsystem"
            f"{'s' if len(foreign) > 1 else ''} {sorted(foreign)} but "
            f"the definition targets {subsys!r}; foreign criteria are "
            f"skipped (all-pass) at evaluation, so this definition "
            f"would match every row")


def _eval_criterion(c: Criterion, columns: dict, subsys: str, n: int):
    if c.subsys != subsys:
        # criteria for other subsystems pass (multi-subsystem filters are
        # resolved by the caller joining masks — ref CRIT_SKIP semantics)
        return np.ones(n, bool)
    fmap = fieldmaps.field_map(c.subsys)
    fd = fmap.get(c.field)
    if fd is None:
        raise ParseError(f"unknown field {c.subsys}.{c.field}")
    col = columns[fd.col]
    vals = c.values
    if fd.kind == "enum":
        vals = tuple(fd.from_json(v) for v in vals)
    v0 = vals[0]
    if fd.kind in ("num", "enum", "bool"):
        col = np.asarray(col, np.float64)
        if fd.kind == "bool" and isinstance(v0, bool):
            v0 = float(v0)
            vals = tuple(float(x) for x in vals)
        if c.op == "=":
            return col == v0
        if c.op == "!=":
            return col != v0
        if c.op == "<":
            return col < v0
        if c.op == "<=":
            return col <= v0
        if c.op == ">":
            return col > v0
        if c.op == ">=":
            return col >= v0
        if c.op == "bit2":
            return (col.astype(np.int64) & int(v0)) != 0
        if c.op == "bit3":
            return (col.astype(np.int64) & int(v0)) == int(v0)
        if c.op == "in":
            return np.isin(col, np.asarray(vals, np.float64))
        if c.op == "notin":
            return ~np.isin(col, np.asarray(vals, np.float64))
        raise ParseError(f"comparator {c.op} invalid for numeric "
                         f"field {c.field}")
    # string columns: object/str arrays
    col = np.asarray(col, object)
    sv = [str(x) for x in vals]
    if c.op == "=":
        return np.array([x == sv[0] for x in col], bool)
    if c.op == "!=":
        return np.array([x != sv[0] for x in col], bool)
    if c.op == "substr":
        return np.array([sv[0] in x for x in col], bool)
    if c.op == "notsubstr":
        return np.array([sv[0] not in x for x in col], bool)
    if c.op in ("like", "notlike"):
        rx = re.compile(sv[0])
        hit = np.array([bool(rx.search(x)) for x in col], bool)
        return hit if c.op == "like" else ~hit
    if c.op == "in":
        s = set(sv)
        return np.array([x in s for x in col], bool)
    if c.op == "notin":
        s = set(sv)
        return np.array([x not in s for x in col], bool)
    raise ParseError(f"comparator {c.op} invalid for string field {c.field}")


def evaluate(tree, columns: dict, subsys: str) -> np.ndarray:
    """Expression tree → (N,) bool mask over the snapshot columns."""
    n = len(next(iter(columns.values())))
    if tree is None:
        return np.ones(n, bool)
    if isinstance(tree, Criterion):
        return _eval_criterion(tree, columns, subsys, n)
    if tree.op == "not":
        return ~evaluate(tree.children[0], columns, subsys)
    masks = [evaluate(c, columns, subsys) for c in tree.children]
    if tree.op == "and":
        out = masks[0]
        for m in masks[1:]:
            out = out & m
        return out
    out = masks[0]
    for m in masks[1:]:
        out = out | m
    return out
