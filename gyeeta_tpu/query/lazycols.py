"""Lazy column sets: group-at-a-time readback + O(result) projection.

The r4 scale sweep showed the svcstate snapshot costing ~2 s at the
65k-service geometry (VERDICT r4 weak #4): one monolithic jit read
EVERY window's (S, B) histograms, the HLL registers, and then Python
formatted hex ids / resolved names for ALL S rows — per query, for
whatever subset the query actually touched.

``LazyCols`` keeps the plain-dict contract that ``execute``/criteria/
aggregation already use, but materializes column GROUPS on first
access, and offers :meth:`rows_many` so projection of the final
``maxrecs`` result rows touches O(result) — the expensive 5min/5day
window sums and the per-row string formatting never run at capacity
unless a filter/sort actually references them. The reference gets the
same effect from incrementally-maintained in-memory tables queried
per-request (``server/gy_mnodehandle.cc`` web queries walk existing
maps; they don't recompute the fleet).
"""

from __future__ import annotations

from typing import Callable, Optional

import numpy as np

# above this result width, per-row loaders lose to the full vector
# path — fall back to materializing the group
_ROWS_FULL_CUTOFF = 4096


class LazyCols(dict):
    """dict of columns; unmaterialized ones load group-at-a-time.

    ``eager``      — columns available immediately.
    ``group_of``   — column name → group key.
    ``load``       — group key → ``fn() -> {col: array}`` (full width).
    ``load_rows``  — group key → ``fn(idx) -> {col: array}`` over just
                     the given row indices (optional per group).
    """

    def __init__(self, eager: dict, group_of: dict,
                 load: dict, load_rows: Optional[dict] = None):
        super().__init__(eager)
        self._group_of = group_of
        self._load = load
        self._load_rows = load_rows or {}
        self._loaded: set = set()

    # -------------------------------------------------- dict protocol
    def __missing__(self, key):
        g = self._group_of.get(key)
        if g is None:
            raise KeyError(key)
        self._materialize(g)
        return dict.__getitem__(self, key)

    def __contains__(self, key) -> bool:
        return dict.__contains__(self, key) or key in self._group_of

    def _materialize(self, g: str) -> None:
        if g in self._loaded:
            return
        for c, v in self._load[g]().items():
            dict.__setitem__(self, c, v)
        self._loaded.add(g)

    def full(self) -> dict:
        """Materialize every group → plain dict (full-width joins)."""
        for g in self._load:
            self._materialize(g)
        return dict(self)

    # ------------------------------------------------ row projection
    def rows_many(self, colnames, idx: np.ndarray) -> dict:
        """→ {col: values over rows ``idx``}, computing unmaterialized
        groups only over those rows when a row loader exists."""
        out: dict = {}
        want_by_group: dict = {}
        for c in colnames:
            if dict.__contains__(self, c):
                out[c] = np.asarray(dict.__getitem__(self, c))[idx]
            else:
                want_by_group.setdefault(self._group_of[c], []).append(c)
        for g, cs in want_by_group.items():
            lr = self._load_rows.get(g)
            if lr is None or len(idx) > _ROWS_FULL_CUTOFF:
                self._materialize(g)
                for c in cs:
                    out[c] = np.asarray(dict.__getitem__(self, c))[idx]
            else:
                got = lr(idx)
                for c in cs:
                    out[c] = np.asarray(got[c])
        return out


def merge_lazy(parts, widths=None) -> "LazyCols":
    """Concatenate per-shard LazyCols into one lazy merged set.

    Eager columns concatenate now; each lazy group concatenates on
    first reference — so a sharded filter/sort query still reads only
    the groups it names. Row loaders DO survive the merge: merged
    result indices split by shard offset and route to each part's own
    row loader, so projection of ``maxrecs`` rows stays O(result) on
    the mesh too (the sharded half of VERDICT r4 #6).

    ``widths`` (per-part row counts) is required when the parts carry
    no eager columns to derive it from."""
    eager_keys = list(dict.keys(parts[0]))
    eager = {k: np.concatenate([np.asarray(dict.__getitem__(p, k))
                                for p in parts]) for k in eager_keys}
    if widths is None:
        if not eager_keys:
            raise ValueError(
                "merge_lazy needs explicit widths when parts have no "
                "eager columns (zero offsets would misroute every "
                "row-loader index)")
        widths = [len(dict.__getitem__(p, eager_keys[0]))
                  for p in parts]
    offsets = np.concatenate([[0], np.cumsum(widths)])
    cols_of_group: dict = {}
    for c, g in parts[0]._group_of.items():
        cols_of_group.setdefault(g, []).append(c)

    def _concat_group(g):
        def load():
            ds = [p._load[g]() for p in parts]
            return {c: np.concatenate([np.asarray(d[c]) for d in ds])
                    for c in ds[0]}
        return load

    def _rows_group(g):
        def load(idx):
            idx = np.asarray(idx, np.int64)
            if len(idx) == 0:
                # delegate so empty columns keep their REAL dtypes
                # (string groups are object arrays, not float64)
                return parts[0].rows_many(cols_of_group[g], idx)
            shard = np.searchsorted(offsets, idx, "right") - 1
            out: dict = {}
            for s in np.unique(shard):
                at = np.nonzero(shard == s)[0]
                got = parts[s].rows_many(cols_of_group[g],
                                         idx[at] - offsets[s])
                for c, v in got.items():
                    col = out.get(c)
                    if col is None:
                        col = np.empty(len(idx), np.asarray(v).dtype)
                        out[c] = col
                    col[at] = v
            return out
        return load

    return LazyCols(eager, dict(parts[0]._group_of),
                    {g: _concat_group(g) for g in parts[0]._load},
                    {g: _rows_group(g) for g in parts[0]._load})


def rows_of(cols, colnames, idx: np.ndarray) -> dict:
    """Uniform projection helper: LazyCols row path, or plain slicing."""
    if isinstance(cols, LazyCols):
        return cols.rows_many(colnames, idx)
    return {c: np.asarray(cols[c])[idx] for c in colnames}
