"""Version gate (ref: partha/sversion.cc, server/mversion.cc — registration
version gating per common/gy_comm_proto.h:55-56)."""

__version__ = "0.1.0"

# Minimum wire-format version this build accepts from agents/simulators.
MIN_WIRE_VERSION = 3   # v2: AGGR_TASK_DT grew forks_sec (TOPFORK);
CURR_WIRE_VERSION = 5  # v3: REQ_TRACE_DT grew conn_id/cli ids
#                        (TRACECONN) — older record layouts cannot be
#                        decoded, so the registration gate must reject
#                        older producers outright.
#                        v4: durable-ingest additions only (SWEEP_SEQ
#                        marks, COMM_THROTTLE control, REGISTER_RESP
#                        last_seq tail) — no existing layout changed,
#                        so v3 producers stay accepted (MIN stays 3);
#                        v3 peers skip the new subtype/control frames.
#                        v5: edge pre-aggregation (NOTIFY_SKETCH_DELTA
#                        + the REGISTER_RESP preagg advert tail) —
#                        additive again: v3/v4 servers skip the new
#                        subtype COUNTED, v3/v4 agents ignore the
#                        advert tail and stay raw (MIN stays 3)
