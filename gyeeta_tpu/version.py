"""Version gate (ref: partha/sversion.cc, server/mversion.cc — registration
version gating per common/gy_comm_proto.h:55-56)."""

__version__ = "0.1.0"

# Minimum wire-format version this build accepts from agents/simulators.
MIN_WIRE_VERSION = 2   # v2: AGGR_TASK_DT grew forks_sec (TOPFORK) — a
CURR_WIRE_VERSION = 2  # v1 task record layout cannot be decoded
