"""gyt-server: the deployable aggregation-server daemon.

The process-hardening tier the reference builds in ``common/gy_init_proc``
(+ madhava's ``main()``): config layering, structured startup logging,
SIGTERM/SIGINT graceful shutdown (drain staged slabs, final checkpoint),
SIGHUP hot-reload of runtime knobs, and a periodic self-stats report.
Run as ``python -m gyeeta_tpu --port 10038 --config gyt.json``.

Single-controller design: one asyncio loop owns the Runtime; the TPU
pipeline is the concurrency (no forked child processes — the reference's
parent/child split guards a multi-threaded C++ address space, which this
architecture does not have).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import logging
import os
import signal
from typing import Optional

from gyeeta_tpu.net.server import GytServer
from gyeeta_tpu.runtime import Runtime
from gyeeta_tpu.utils import config as C

log = logging.getLogger("gyeeta_tpu.daemon")


class _StagingCompactLoop:
    """Compaction-region replay loop over a ship staging directory.

    Segments land in ``staging`` via net/segship.py and are replayed by
    the STOCK compactors (journal_dir mode) exactly as if local.  The
    layout (flat vs shard_NN/) is discovered from what actually lands,
    so the daemon can boot on an empty staging dir before the first
    segment arrives — construction of the compactor is deferred to the
    first pass that finds segments (ParallelCompactor refuses an empty
    or flat dir at construction, and its proc count must be clamped to
    the shard count the shipper reveals)."""

    def __init__(self, cfg, opts, staging: str, shard_dir: str,
                 procs: int = 0, stats=None):
        self.cfg = cfg
        self.opts = opts
        self.staging = staging
        self.shard_dir = shard_dir
        self.procs = int(procs or 0)
        self.stats = stats
        self.compactor = None
        self._stop = None           # threading.Event, set in start()
        self._thread = None

    def _ensure(self):
        if self.compactor is not None:
            return self.compactor
        from gyeeta_tpu.utils import journal as J
        subs = J.sharded_subdirs(self.staging)
        if subs and self.procs >= 1:
            from gyeeta_tpu.history.compactproc import ParallelCompactor
            self.compactor = ParallelCompactor(
                self.cfg, self.opts, min(self.procs, len(subs)),
                journal_dir=self.staging, shard_dir=self.shard_dir,
                stats=self.stats)
        elif subs or J.dir_segments(self.staging):
            from gyeeta_tpu.history.compactor import Compactor
            self.compactor = Compactor(self.cfg, self.opts,
                                       journal_dir=self.staging,
                                       shard_dir=self.shard_dir,
                                       stats=self.stats)
        return self.compactor

    def pass_once(self) -> None:
        c = self._ensure()
        if c is None:
            return                  # nothing landed yet
        c.compact_once()

    def floors(self):
        """Per-shard compacted floors for SegmentReceiver.sweep_below:
        a staged segment below its floor is fully represented in the
        parted store and safe to delete locally (the ship ledger keeps
        answering "done" for it)."""
        c = self.compactor
        if c is None:
            return None
        try:
            pos = c.store.position()
        except Exception:           # noqa: BLE001 — sweep is best-effort
            return None
        if not pos:
            return None
        from gyeeta_tpu.utils import journal as J
        return J.floors_of(pos)

    def start(self) -> None:
        import threading
        self._stop = threading.Event()
        interval = max(float(self.opts.hist_compact_interval_s), 0.2)

        def _loop():
            while not self._stop.wait(interval):
                try:
                    self.pass_once()
                except Exception:   # noqa: BLE001 — keep the loop alive
                    if self.stats is not None:
                        self.stats.bump("compact_errors")
                    log.exception("staging compaction pass failed")

        self._thread = threading.Thread(target=_loop, daemon=True,
                                        name="gyt-staging-compact")
        self._thread.start()

    def stop(self) -> None:
        if self._stop is not None:
            self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30.0)
            self._thread = None

    def final_pass(self) -> None:
        """Stop the loop and run one last replay so a clean stop leaves
        the parted store current with everything already landed."""
        self.stop()
        try:
            self.pass_once()
        except Exception:           # noqa: BLE001 — never block shutdown
            log.exception("final staging compaction pass failed")
        if self.compactor is not None:
            self.compactor.close()


class Daemon:
    def __init__(self, args: argparse.Namespace):
        self.args = args
        cfg = C.load_engine_cfg(args.config)
        opts = C.load_runtime_opts(
            args.config,
            **({"history_db": args.history_db} if args.history_db else {}),
            **({"checkpoint_dir": args.checkpoint_dir}
               if args.checkpoint_dir else {}),
            **({"journal_dir": args.journal_dir}
               if getattr(args, "journal_dir", None) else {}),
            **({"journal_fsync_ms": args.journal_fsync_ms}
               if getattr(args, "journal_fsync_ms", None) is not None
               else {}),
            **({"journal_fsync_kb": args.journal_fsync_kb}
               if getattr(args, "journal_fsync_kb", None) is not None
               else {}),
            **({"journal_segment_mb": args.journal_segment_mb}
               if getattr(args, "journal_segment_mb", None) is not None
               else {}),
            **({"hist_shard_dir": args.shard_dir}
               if getattr(args, "shard_dir", None) else {}),
            **({"hist_window_ticks": args.hist_window_ticks}
               if getattr(args, "hist_window_ticks", None) is not None
               else {}),
            **({"hist_compact_interval_s": args.compact_interval}
               if getattr(args, "compact_interval", None) is not None
               else {}))
        # a crash mid-`checkpoint.save` leaves .tmp.npz staging files
        # behind; without a start-time sweep they accumulate forever
        if opts.checkpoint_dir:
            from gyeeta_tpu.utils import checkpoint as _ck
            n = _ck.sweep_stale_tmp(opts.checkpoint_dir)
            if n:
                log.info("swept %d stale .tmp.npz staging file(s)", n)
        self.rt = _make_runtime(args, cfg, opts)
        if args.restore:
            extra = self.rt.restore(args.restore)
            log.info("restored checkpoint %s (tick %s)", args.restore,
                     extra.get("tick"))
            _replay_wal(self.rt, extra)
        elif getattr(args, "restore_latest", False):
            if restore_latest_checkpoint(
                    self.rt, opts.checkpoint_dir) is None:
                log.info("no usable checkpoint (cold start)")
        self.srv = GytServer(self.rt, host=args.host, port=args.port,
                             tick_interval=args.tick_interval,
                             hostmap_path=args.hostmap,
                             record_path=args.record,
                             feed_pipeline=getattr(
                                 args, "feed_pipeline", False),
                             handshake_timeout=getattr(
                                 args, "handshake_timeout", 10.0),
                             idle_timeout=getattr(
                                 args, "idle_timeout", None),
                             write_timeout=getattr(
                                 args, "write_timeout", 10.0),
                             frame_error_budget=getattr(
                                 args, "frame_error_budget", 8),
                             throttle_hold_ms=getattr(
                                 args, "throttle_hold_ms", 1500),
                             throttle_lag_s=getattr(
                                 args, "throttle_lag_s", 0.75),
                             throttle_pending_mb=getattr(
                                 args, "throttle_pending_mb", 32.0),
                             throttle_ring_frac=getattr(
                                 args, "throttle_ring_frac", 0.75),
                             query_workers=getattr(
                                 args, "query_workers", None),
                             query_queue_max=getattr(
                                 args, "query_queue_max", None),
                             query_snapshot=(
                                 False if getattr(args, "query_strong",
                                                  False) else None),
                             shard_ingest=getattr(args, "shards", 0) > 1,
                             shard_queue_mb=getattr(
                                 args, "shard_queue_mb", 8.0),
                             ingest_procs=getattr(
                                 args, "ingest_procs", 1) or 1,
                             sub_persist=getattr(
                                 args, "sub_persist", None),
                             relay_port=getattr(
                                 args, "relay_port", None))
        self._hot = C.HotReload(args.config, opts) if args.config else None
        # history compaction daemon: sealed WAL segments → columnar
        # snapshot shards (the time-travel tier's writer). Runs only
        # with BOTH a journal (the source) and a shard dir (the sink).
        self.compactor = None
        # remote compaction region pieces (OPERATIONS.md "Remote
        # compaction region"): receiver + staging replay loop on the
        # compaction side, shipper thread on the source side
        self._ship_loop = None
        self.ship_recv = None
        self.shipper = None
        self._ship_thread = None
        if opts.hist_shard_dir and getattr(args, "ship_staging", None):
            # compaction-region mode: the WAL source is the SHIP
            # STAGING dir (segments landed by net/segship.py), not
            # this process's own journal — replayed by the stock
            # compactors exactly as if local
            self._ship_loop = _StagingCompactLoop(
                self.rt.cfg, opts, args.ship_staging,
                opts.hist_shard_dir,
                procs=getattr(args, "compact_procs", 0),
                stats=self.rt.stats)
        elif opts.hist_shard_dir and self.rt.journal is not None:
            if getattr(args, "compact_procs", 0) >= 1:
                # distributed compaction: N replay worker processes
                # over disjoint WAL shard groups (parted store layout)
                from gyeeta_tpu.history.compactproc import \
                    ParallelCompactor
                self.compactor = ParallelCompactor(
                    self.rt.cfg, opts, args.compact_procs,
                    journal=self.rt.journal, stats=self.rt.stats)
            else:
                from gyeeta_tpu.history.compactor import Compactor
                self.compactor = Compactor(self.rt.cfg, opts,
                                           journal=self.rt.journal,
                                           stats=self.rt.stats)
        elif opts.hist_shard_dir:
            log.warning("--shard-dir set without --journal-dir: the "
                        "WAL is the history source — time-travel "
                        "queries will serve existing shards only")
        self.stop_event = asyncio.Event()

    async def run(self) -> None:
        host, port = await self.srv.start()
        log.info("gyt-server listening on %s:%d (svc_capacity=%d, "
                 "n_hosts=%d); protocol edges: GYT agent/query, "
                 "stock partha (PS/PM), stock node webserver (NM)",
                 host, port, self.rt.cfg.svc_capacity,
                 self.rt.cfg.n_hosts)
        # crash forensics + liveness watchdog (component row 8: the
        # reference's fatal-signal backtraces + scheduler watchdogs)
        from gyeeta_tpu.utils import crashguard
        if self.rt.opts.checkpoint_dir:
            os.makedirs(self.rt.opts.checkpoint_dir, exist_ok=True)
            crash_path = f"{self.rt.opts.checkpoint_dir}/gyt_crash.log"
        else:
            crash_path = "/tmp/gyt_crash.log"
        crashguard.enable_crash_dumps(crash_path)
        watchdog = None
        if self.args.tick_interval:
            watchdog = crashguard.TickWatchdog(
                stall_after_s=max(12 * self.args.tick_interval, 30.0),
                on_stall=lambda gap: self.rt.notifylog.add(
                    f"serving loop stalled for {gap:.0f}s "
                    f"(stacks in {crash_path})", ntype="error",
                    source="selfmon"))
            watchdog.beat()
            watchdog.start()
            self.srv.watchdog = watchdog
        if self.compactor is not None:
            self.compactor.start()
            log.info("history compactor: window=%d ticks, every %.0fs "
                     "-> %s", self.rt.opts.hist_window_ticks,
                     self.rt.opts.hist_compact_interval_s,
                     self.rt.opts.hist_shard_dir)
        if getattr(self.args, "ship_staging", None) \
                and getattr(self.args, "ship_port", None) is not None:
            from gyeeta_tpu.net.segship import SegmentReceiver
            self.ship_recv = SegmentReceiver(
                self.args.ship_staging, stats=self.rt.stats,
                host=self.args.ship_listen_host,
                port=self.args.ship_port,
                floors_fn=(self._ship_loop.floors
                           if self._ship_loop is not None else None),
                notifylog=self.rt.notifylog)
            sh, sp = await self.ship_recv.start()
            # machine-parsable bind line for harnesses scripting
            # ephemeral ports (the relay's RELAY_LISTEN idiom)
            print(f"SHIP_LISTEN {sh} {sp}", flush=True)
        if self._ship_loop is not None:
            self._ship_loop.start()
            log.info("staging compactor over %s every %.0fs -> %s",
                     self.args.ship_staging,
                     self.rt.opts.hist_compact_interval_s,
                     self.rt.opts.hist_shard_dir)
        if getattr(self.args, "ship_to", None) \
                and self.rt.journal is not None:
            import threading

            from gyeeta_tpu.history.shipper import SegmentShipper
            th, _, tp = self.args.ship_to.rpartition(":")
            self.shipper = SegmentShipper({
                "target": (th or "127.0.0.1", int(tp)),
                "shipper_id": getattr(self.args, "ship_id", None),
                "journal": self.rt.journal, "stats": self.rt.stats})
            self._ship_thread = threading.Thread(
                target=self.shipper.run, daemon=True,
                name="gyt-shipper")
            self._ship_thread.start()
            log.info("segment shipper -> %s (id=%s)",
                     self.args.ship_to, self.shipper.shipper_id)
        elif getattr(self.args, "ship_to", None):
            log.warning("--ship-to without --journal-dir: nothing to "
                        "ship (the WAL is the shipped source)")
        stats_task = asyncio.create_task(self._stats_loop())
        try:
            await self.stop_event.wait()
        finally:
            if watchdog is not None:
                watchdog.stop()
            stats_task.cancel()
            await self.shutdown()

    async def _stats_loop(self) -> None:
        while True:
            await asyncio.sleep(self.args.stats_interval)
            d = self.rt.stats.delta()
            if d:
                log.info("stats %s", json.dumps(d, default=str))
            # a silently-degraded native extension must be visible
            # without a query client: the per-interval fallback decode
            # rate rides the cadence log at WARNING (satellite of the
            # obs tier; the one-time import warning can scroll away)
            if d.get("ref_fallback_decoded"):
                log.warning(
                    "native decode FALLBACK active: %d events decoded "
                    "in pure Python this interval (counter "
                    "ref_fallback_decoded; rebuild with `python -m "
                    "gyeeta_tpu.ingest.native.build`)",
                    d["ref_fallback_decoded"])
            # engine device-health gauges (refreshed each tick by the
            # batched readback) — the print_stats() cadence analogue;
            # the durable-ingest gauges (journal fsync lag = the RPO
            # bound, unsynced WAL bytes, throttle state) ride the same
            # line: one glance covers device AND disk pressure
            eng = {k: v for k, v in self.rt.stats.gauges.items()
                   if k.startswith(("engine_", "journal_",
                                    "throttle_state"))}
            # fused fold-path cadence: device dispatches + staging-slab
            # buffer flips + digest flushes this interval (the fold
            # half of the overlap win; gyt_fold_dispatches_total etc
            # ride /metrics from the same counters)
            for k in ("fold_dispatches", "stage_slab_flips",
                      "td_partial_flushes"):
                if d.get(k):
                    eng[k + "_delta"] = d[k]
            if eng:
                log.info("health %s", json.dumps(eng, default=str,
                                                 sort_keys=True))
            # NM query-edge cadence line: live node conns + per-verb
            # rates this interval (only when the edge is in use)
            nm = {k: v for k, v in d.items() if k.startswith("nm_")}
            if self.srv._nm_conns_live or nm:
                nm["conns_live"] = self.srv._nm_conns_live
                log.info("nm %s", json.dumps(nm, default=str,
                                             sort_keys=True))
            if self._hot:
                new = self._hot.poll()
                if new is not self.rt.opts:
                    self.rt.opts = new
                    log.info("hot-reloaded runtime knobs")

    async def shutdown(self) -> None:
        """Graceful stop: stop accepting, drain staged folds, final
        checkpoint recording the fsynced journal position, then drop
        the WAL segments that checkpoint supersedes (the SIGTERM path
        of the reference's init proc). A clean shutdown therefore
        leaves an EMPTY WAL window: the respawn replays zero chunks."""
        log.info("shutting down: draining staged slabs")
        if self.shipper is not None:
            # stop BEFORE the journal closes; the ship floor it
            # registered stays in force for the final truncation, so
            # a not-yet-landed segment survives this shutdown
            self.shipper.stop()
            if self._ship_thread is not None:
                self._ship_thread.join(timeout=10.0)
        if self._ship_loop is not None:
            # final staging pass so a clean stop leaves the parted
            # store current with everything already landed
            self._ship_loop.final_pass()
        if self.ship_recv is not None:
            await self.ship_recv.stop()
        if self.compactor is not None:
            # final pass BEFORE the journal closes: seal + compact the
            # shutdown window so a clean stop leaves history current
            try:
                self.compactor.compact_once(seal=True)
            except Exception:     # noqa: BLE001 — never block shutdown
                log.exception("final compaction pass failed")
            self.compactor.close()
        await self.srv.stop()          # closes rt (journal fsync+close)
        self.rt.flush()
        if self.rt.opts.checkpoint_dir:
            from gyeeta_tpu.utils import checkpoint as ckpt
            from gyeeta_tpu.utils import journal as J
            tick = self.rt._tick_no
            extra = J.checkpoint_extra(self.rt, tick)
            path = ckpt.save(
                f"{self.rt.opts.checkpoint_dir}/gyt_final_{tick:08d}.npz",
                self.rt.cfg, self.rt.state, extra=extra)
            J.post_checkpoint_truncate(self.rt, extra)
            log.info("final checkpoint: %s", path)
        log.info("bye")

    def handle_signal(self, sig: int) -> None:
        if sig == signal.SIGHUP:
            # hot-reload when a config file backs the knobs; a stray
            # HUP (logrotate, tty hangup) must never stop the server
            if self._hot:
                new = self._hot.poll()
                if new is not self.rt.opts:
                    self.rt.opts = new
                    log.info("SIGHUP: hot-reloaded runtime knobs")
            else:
                log.info("SIGHUP ignored (no --config)")
            return
        log.info("signal %d: stopping", sig)
        self.stop_event.set()


def _make_runtime(args, cfg, opts):
    """The ``--shards N`` fleet mode: a :class:`ShardedRuntime` over an
    N-device mesh (the production shape — per-shard fused folds, one
    collective roll-up per tick, per-shard WAL subdirs), else the flat
    single-device Runtime. On a CPU host the mesh devices are forced
    via ``xla_force_host_platform_device_count`` — set BEFORE the first
    jax backend init, which is why this helper owns runtime
    construction."""
    shards = int(getattr(args, "shards", 0) or 0)
    if shards <= 1:
        return Runtime(cfg, opts)
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={shards}"
        ).strip()
    import jax

    from gyeeta_tpu.parallel.mesh import make_mesh
    from gyeeta_tpu.parallel.shardedrt import ShardedRuntime
    ndev = len(jax.devices())
    if ndev < shards:
        raise SystemExit(
            f"--shards {shards} needs {shards} devices, backend has "
            f"{ndev} (a CPU host must not initialize jax before the "
            f"device-count flag is set — check for early jax use)")
    log.info("sharded runtime: %d-shard mesh (%d devices available), "
             "per-shard WAL %s", shards, ndev,
             "on" if opts.journal_dir else "off")
    return ShardedRuntime(cfg, make_mesh(shards), opts)


def checkpoint_candidates(ckpt_dir: Optional[str]) -> list:
    """Complete checkpoint files, newest first. Excludes the .tmp.npz
    a crash mid-``ckpt.save`` leaves behind (atomic-rename staging) —
    restoring one would crash-loop a supervised restart forever."""
    import pathlib
    if not ckpt_dir:
        return []
    d = pathlib.Path(ckpt_dir)
    if not d.is_dir():
        return []
    cands = [p for p in d.glob("gyt_*.npz")
             if not p.name.endswith(".tmp.npz")]
    return [str(p) for p in sorted(
        cands, key=lambda p: p.stat().st_mtime, reverse=True)]


def latest_checkpoint(ckpt_dir: Optional[str]):
    """Newest complete checkpoint file in the dir, or None."""
    cands = checkpoint_candidates(ckpt_dir)
    return cands[0] if cands else None


def _replay_wal(rt, extra: Optional[dict]) -> dict:
    """Recovery phase 2: re-fold write-ahead-journal chunks from the
    checkpoint's recorded position (``extra["wal"]``; a cold start
    replays the whole journal) through the normal decode/fold path.
    No-op without a journal. Returns the replay report."""
    if getattr(rt, "journal", None) is None:
        return {"chunks": 0, "records": 0}
    pos = (extra or {}).get("wal")
    rep = rt.replay_journal(tuple(pos) if pos else None)
    if rep["chunks"]:
        log.info("WAL replay: %d chunk(s) / %d record(s) re-folded "
                 "(from %s)", rep["chunks"], rep["records"],
                 "checkpoint position" if pos else "journal start")
    else:
        log.info("WAL replay: empty window (clean shutdown or no "
                 "post-checkpoint traffic)")
    return rep


def restore_latest_checkpoint(rt, ckpt_dir: Optional[str]):
    """The ``--restore-latest`` respawn path: walk checkpoints newest→
    oldest and restore the first usable one into ``rt``, then replay
    the write-ahead journal from that checkpoint's recorded position
    (when ``rt`` has one — the crash-window recovery that bounds data
    loss to the last fsync). A truncated / corrupt / cfg-mismatched
    newest file (torn by a crash mid-write) must NEVER crash-loop a
    supervised restart — it logs and falls through to the next-older
    candidate. Returns the restored path, or None (cold start; a cold
    start with a non-empty journal still replays it)."""
    for cand in checkpoint_candidates(ckpt_dir):
        try:
            extra = rt.restore(cand)
            log.info("restored checkpoint %s (tick %s)", cand,
                     extra.get("tick"))
            _replay_wal(rt, extra)
            return cand
        except Exception as e:  # noqa: BLE001 — corrupt / mismatched
            log.warning("checkpoint %s unusable (%s) — trying older",
                        cand, e)
    _replay_wal(rt, None)
    return None


def parse_args(argv: Optional[list] = None) -> argparse.Namespace:
    ap = argparse.ArgumentParser(
        prog="gyeeta_tpu",
        description="TPU-native fleet observability aggregation server")
    ap.add_argument("--config", help="JSON config ({engine:…, runtime:…})")
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=10038)
    ap.add_argument("--history-db",
                    help="history store: a sqlite path, or a "
                    "postgresql:// DSN for the durable Postgres tier "
                    "(needs psycopg in the image)")
    ap.add_argument("--checkpoint-dir")
    ap.add_argument("--restore", help="checkpoint .npz to restore")
    ap.add_argument("--restore-latest", action="store_true",
                    help="restore the newest checkpoint in "
                    "--checkpoint-dir when one exists (the respawn "
                    "path: a supervised restart resumes state)")
    ap.add_argument("--hostmap", help="machine-id→host-id placement file")
    ap.add_argument("--record", help="tee ingested wire bytes to this "
                    "capture file (replay with `gyeeta_tpu replay`)")
    ap.add_argument("--tick-interval", type=float, default=5.0)
    # fleet-scale sharded serving (OPERATIONS.md "Fleet-scale
    # deployment"): per-shard ingest loops + fused per-shard folds +
    # one collective roll-up per tick on an N-device mesh
    ap.add_argument("--shards", type=int, default=0,
                    help="run the sharded mesh runtime over N devices "
                    "(hosts hash to shards by sticky hid; per-shard "
                    "WAL subdirs under --journal-dir; 0/1 = flat "
                    "single-device runtime)")
    ap.add_argument("--shard-queue-mb", type=float, default=8.0,
                    help="per-shard ingest queue byte bound before "
                    "counted oldest-first drops (--shards mode)")
    # multi-process ingest edge (net/ingestproc.py; OPERATIONS.md
    # "Multi-process deployment"): N worker processes own wire
    # validation + deframe/decode + per-shard WAL append off the fold
    # GIL and publish decoded slabs over shared-memory rings
    ap.add_argument("--ingest-procs", type=int, default=1,
                    help="ingest worker processes (sticky shard "
                    "groups; needs --shards >= N; 1 = today's "
                    "in-process edge, zero behavior change)")
    ap.add_argument("--feed-pipeline", action="store_true",
                    help="deframe/decode on a worker thread (the "
                    "reference's L1/L2 split; useful on multi-core "
                    "hosts — the native decoders release the GIL)")
    ap.add_argument("--relay-port", type=int, default=None,
                    help="accept REMOTE ingest relay uplinks on this "
                    "port (net/relay.py: the shm-ring ledger over "
                    "TCP — published == consumed + counted drops "
                    "across machines; 0 = ephemeral)")
    ap.add_argument("--stats-interval", type=float, default=60.0)
    # conn-hardening deadlines (net/server.py; every reap lands on a
    # labeled gyt_conn_timeouts_total counter in /metrics)
    ap.add_argument("--handshake-timeout", type=float, default=10.0,
                    help="seconds a conn may take to complete "
                    "registration (slow-loris reap)")
    ap.add_argument("--idle-timeout", type=float, default=None,
                    help="seconds of silence before an established "
                    "conn is reaped (default: 12x tick interval, "
                    "min 30s; 0 disables)")
    ap.add_argument("--write-timeout", type=float, default=10.0,
                    help="seconds a control push may block on a "
                    "non-draining agent conn")
    ap.add_argument("--frame-error-budget", type=int, default=8,
                    help="recoverable frame-level errors per query "
                    "conn before it is closed")
    # snapshot-isolated query serving (query/snapshot.py, net/qexec.py;
    # OPERATIONS.md "Query serving"): live queries read the last
    # published per-tick engine view on a bounded off-loop worker pool
    ap.add_argument("--query-workers", type=int, default=None,
                    help="query worker-pool width (default "
                    "GYT_QUERY_WORKERS or 4)")
    ap.add_argument("--query-queue-max", type=int, default=None,
                    help="max in-flight queries before shedding with "
                    "a counted overload error (default "
                    "GYT_QUERY_QUEUE_MAX or 128)")
    ap.add_argument("--sub-persist",
                    help="append-only file persisting the streaming-"
                    "subscription version ring (net/subs.py): a "
                    "restarted server resumes reconnecting "
                    "subscribers with deltas instead of full resyncs "
                    "(single-replica deployments; gateways have "
                    "their own --sub-persist)")
    ap.add_argument("--query-strong", action="store_true",
                    help="serve every query inline with strong "
                    "consistency (the pre-snapshot behavior; also "
                    "GYT_QUERY_SNAPSHOT=0)")
    # durable-ingest tier: write-ahead journal + admission control
    # (utils/journal.py; OPERATIONS.md "Durability & recovery")
    ap.add_argument("--journal-dir",
                    help="write-ahead event journal directory: every "
                    "accepted event chunk is appended pre-fold and "
                    "replayed on --restore-latest, bounding data loss "
                    "to the last group fsync (unset = journaling off)")
    ap.add_argument("--journal-fsync-ms", type=float, default=None,
                    help="group-fsync time cadence in ms (the RPO "
                    "bound; default 50)")
    ap.add_argument("--journal-fsync-kb", type=int, default=None,
                    help="group-fsync byte cadence in KiB (default "
                    "1024; whichever cadence trips first syncs)")
    ap.add_argument("--journal-segment-mb", type=int, default=None,
                    help="journal segment rotation size in MiB "
                    "(default 64)")
    ap.add_argument("--throttle-hold-ms", type=int, default=1500,
                    help="admission control: how long a COMM_THROTTLE "
                    "tells agents to hold feeds in their spool when "
                    "ingest pressure trips (0 disables the controller)")
    ap.add_argument("--throttle-lag-s", type=float, default=0.75,
                    help="journal fsync lag that trips the trace-feed "
                    "throttle")
    ap.add_argument("--throttle-pending-mb", type=float, default=32.0,
                    help="unsynced WAL bytes that trip the trace-feed "
                    "throttle")
    ap.add_argument("--throttle-ring-frac", type=float, default=0.75,
                    help="ingest worker-ring occupancy fraction that "
                    "trips the trace-feed throttle (multi-process "
                    "ingest; >=0.95 holds every sweep — throttle "
                    "before the drop-oldest rings shed)")
    # time-travel history tier: WAL compaction → columnar snapshot
    # shards + at=/window= queries (OPERATIONS.md "History & time
    # travel"; GYT_HIST_* env knobs cover the rest)
    ap.add_argument("--shard-dir",
                    help="snapshot-shard directory: enables the "
                    "time-travel query tier; with --journal-dir a "
                    "compaction daemon rolls sealed WAL segments into "
                    "per-window columnar shards")
    ap.add_argument("--hist-window-ticks", type=int, default=None,
                    help="raw shard window in 5s ticks (default 12 = "
                    "1m time-travel resolution)")
    ap.add_argument("--compact-interval", type=float, default=None,
                    help="compaction daemon cadence in seconds "
                    "(default 30)")
    ap.add_argument("--compact-procs", type=int, default=0,
                    help="N>=1: distributed compaction — N replay "
                    "worker PROCESSES over disjoint WAL shard groups "
                    "into a parted shard store (needs --shards; N <= "
                    "shard count). 0 (default) = the in-process "
                    "single-runtime compactor")
    # remote compaction region (history/shipper.py + net/segship.py;
    # OPERATIONS.md "Remote compaction region"): sealed WAL segments
    # ship content-hashed to a peer region's staging dir, where the
    # stock compactors replay them exactly as if local
    ap.add_argument("--ship-to", metavar="HOST:PORT",
                    help="ship this server's sealed WAL segments to a "
                    "remote compaction region's segment receiver "
                    "(needs --journal-dir; the ship truncate floor "
                    "pins unshipped segments against checkpoint "
                    "truncation)")
    ap.add_argument("--ship-id", default=None,
                    help="stable shipper identity for --ship-to "
                    "(provenance key; default ship-<hostname>)")
    ap.add_argument("--ship-staging",
                    help="run the COMPACTION-REGION side: accept "
                    "shipped segments into this staging dir (with "
                    "--ship-port) and/or compact it into --shard-dir "
                    "(with --compact-procs)")
    ap.add_argument("--ship-port", type=int, default=None,
                    help="listen port for shipper uplinks into "
                    "--ship-staging (0 = ephemeral; prints "
                    "SHIP_LISTEN host port)")
    ap.add_argument("--ship-listen-host", default="0.0.0.0")
    ap.add_argument("--log-level", default="INFO")
    return ap.parse_args(argv)


def main(argv: Optional[list] = None) -> None:
    args = parse_args(argv)
    logging.basicConfig(
        level=getattr(logging, args.log_level.upper()),
        format="%(asctime)s %(levelname)s %(name)s %(message)s")

    async def amain():
        d = Daemon(args)
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGTERM, signal.SIGINT, signal.SIGHUP):
            loop.add_signal_handler(sig, d.handle_signal, sig)
        await d.run()

    asyncio.run(amain())


if __name__ == "__main__":
    main()
