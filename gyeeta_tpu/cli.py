"""Command-line entry points: serve / query / agent / replay / obs.

``python -m gyeeta_tpu serve …``   — the aggregation-server daemon
``python -m gyeeta_tpu query …``   — one-shot JSON query/CRUD client
``python -m gyeeta_tpu agent …``   — a (sim or collecting) host agent
``python -m gyeeta_tpu replay …``  — play a wire capture into a server
``python -m gyeeta_tpu obs top``   — live self-monitor (counters,
engine health, stage timings, recent pipeline spans); ``obs metrics``
dumps the raw Prometheus exposition
``python -m gyeeta_tpu nm probe``  — stock node-webserver (NM conn)
wire probe: handshake + per-subsystem QUERY_WEB_JSON + optional
alertdef CRUD round trip (``--crud``); ``nm query`` sends one raw body
``python -m gyeeta_tpu chaos``     — deterministic fault-injection TCP
proxy between agents and the server (corrupt/truncate/disconnect/stall
+ latency/re-split/kill windows; ``sim/chaos.py``)
``python -m gyeeta_tpu compact``   — offline WAL→shard compaction for
the time-travel history tier (``compact list`` prints the manifest)

The reference splits these across binaries (gymadhava/gyshyama,
partha, node webserver clients); one Python entry point with
subcommands covers the same operational surface.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys

# operator platform pin: GYT_PLATFORM=cpu forces the CPU backend
# BEFORE any jax import (the JAX_PLATFORMS env var alone is overridden
# by site configs on some hosts — e.g. the axon sitecustomize pins
# jax_platforms — and a wedged accelerator tunnel then blocks startup
# forever with no error)
_plat = os.environ.get("GYT_PLATFORM")
if _plat:
    import jax
    jax.config.update("jax_platforms", _plat)


def _cmd_query(argv) -> None:
    ap = argparse.ArgumentParser(prog="gyeeta_tpu query")
    ap.add_argument("request", help="JSON query/CRUD body, or '-' for "
                    "stdin")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=10038)
    ap.add_argument("--timeout", type=float, default=60.0,
                    help="per-request deadline (seconds)")
    args = ap.parse_args(argv)
    body = sys.stdin.read() if args.request == "-" else args.request
    req = json.loads(body)

    async def run():
        from gyeeta_tpu.net.agent import QueryClient
        qc = QueryClient(request_timeout=args.timeout)
        await qc.connect(args.host, args.port)
        out = await qc.query(req)
        await qc.close()
        json.dump(out, sys.stdout, default=str)
        sys.stdout.write("\n")

    asyncio.run(run())


def _cmd_agent(argv) -> None:
    ap = argparse.ArgumentParser(prog="gyeeta_tpu agent")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=10038)
    ap.add_argument("--collect", action="store_true",
                    help="measure THIS host's /proc //sys instead of "
                    "simulating host/cgroup telemetry")
    ap.add_argument("--real", action="store_true",
                    help="observe THIS host's real TCP connections and "
                    "listeners (sock_diag sweep) instead of simulated "
                    "flows; implies --collect semantics for flows only")
    ap.add_argument("--livecap", action="store_true",
                    help="with --real: when the server enables tracing "
                    "for a listener (REQ_TRACE_SET), capture its "
                    "port's live traffic via AF_PACKET and stream "
                    "parsed transactions (needs CAP_NET_RAW; degrades "
                    "cleanly without)")
    ap.add_argument("--cap-ifname", default="lo",
                    help="interface for --livecap captures")
    ap.add_argument("--n-agents", type=int, default=1)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--interval", type=float, default=5.0)
    ap.add_argument("--n-conn", type=int, default=256)
    ap.add_argument("--n-resp", type=int, default=512)
    # supervision knobs (NetAgent.run_forever): the agent process NEVER
    # exits on a dropped/refused conn — it backs off, keeps producing
    # sweeps into a bounded spool, and resends on reconnect
    ap.add_argument("--backoff-base", type=float, default=0.5,
                    help="first reconnect delay (doubles per failure)")
    ap.add_argument("--backoff-cap", type=float, default=30.0,
                    help="max reconnect delay")
    ap.add_argument("--connect-timeout", type=float, default=15.0,
                    help="dial deadline per connect attempt")
    ap.add_argument("--spool-mb", type=float, default=8.0,
                    help="outage sweep-spool bound (MB, drop-oldest)")
    args = ap.parse_args(argv)

    async def run():
        from gyeeta_tpu.net.agent import NetAgent
        agents = [NetAgent(seed=args.seed + i, collect=args.collect,
                           real=args.real, livecap=args.livecap,
                           cap_ifname=args.cap_ifname,
                           connect_timeout=args.connect_timeout,
                           spool_max_bytes=int(args.spool_mb * 2**20))
                  for i in range(args.n_agents)]
        print(f"supervising {len(agents)} agent(s) -> "
              f"{args.host}:{args.port}", file=sys.stderr)
        await asyncio.gather(*(
            a.run_forever(args.host, args.port,
                          interval=args.interval, n_conn=args.n_conn,
                          n_resp=args.n_resp,
                          backoff_base=args.backoff_base,
                          backoff_cap=args.backoff_cap)
            for a in agents))

    asyncio.run(run())


def _cmd_chaos(argv) -> None:
    ap = argparse.ArgumentParser(
        prog="gyeeta_tpu chaos",
        description="deterministic fault-injection TCP proxy: point "
        "agents at --listen-port, upstream at the real server; faults "
        "are seeded + byte-offset keyed (reproducible)")
    ap.add_argument("--listen-host", default="127.0.0.1")
    ap.add_argument("--listen-port", type=int, default=10039)
    ap.add_argument("--upstream-host", default="127.0.0.1")
    ap.add_argument("--upstream-port", type=int, default=10038)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--faults", default="",
                    help="comma list of corrupt,truncate,disconnect,"
                    "stall (empty = pass-through)")
    ap.add_argument("--mean-fault-kb", type=int, default=256,
                    help="mean bytes between injected faults (KB)")
    ap.add_argument("--stall-s", type=float, default=1.0)
    ap.add_argument("--latency-ms", type=float, default=0.0)
    ap.add_argument("--jitter-ms", type=float, default=0.0)
    ap.add_argument("--resplit", type=int, default=0,
                    help="re-split forwarded chunks to at most this "
                    "many bytes (0 = off)")
    ap.add_argument("--kill-at", type=float, default=0.0,
                    help="seconds after start to open a server-kill "
                    "window (drop + refuse all conns)")
    ap.add_argument("--kill-for", type=float, default=0.0,
                    help="kill-window duration (0 = no window)")
    ap.add_argument("--wedge-at", type=float, default=0.0,
                    help="seconds after start to open a WEDGE window "
                    "(stop forwarding both directions, conns stay "
                    "open — the stalled-not-dead upstream)")
    ap.add_argument("--wedge-for", type=float, default=0.0,
                    help="wedge-window duration (0 = no window)")
    ap.add_argument("--fault-both", action="store_true",
                    help="also fault the server->client direction "
                    "(responses / subscription pushes)")
    ap.add_argument("--latency-c2s-ms", type=float, default=None,
                    help="asymmetric latency, client->server "
                    "direction (overrides --latency-ms)")
    ap.add_argument("--latency-s2c-ms", type=float, default=None,
                    help="asymmetric latency, server->client "
                    "direction (overrides --latency-ms)")
    ap.add_argument("--partition-at", type=float, default=0.0,
                    help="seconds after start to open a PARTITION "
                    "window (both directions dropped, conns held)")
    ap.add_argument("--partition-for", type=float, default=0.0,
                    help="partition-window duration (0 = no window)")
    ap.add_argument("--report-interval", type=float, default=10.0)
    args = ap.parse_args(argv)

    from gyeeta_tpu.sim.chaos import run_proxy
    asyncio.run(run_proxy(args))


def _cmd_replay(argv) -> None:
    ap = argparse.ArgumentParser(prog="gyeeta_tpu replay")
    ap.add_argument("capture", help="GYTREC capture file")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=10038)
    ap.add_argument("--speed", type=float, default=0.0,
                    help="0 = full speed; 1 = recorded pace")
    ap.add_argument("--host-offset", type=int, default=0)
    args = ap.parse_args(argv)

    async def run():
        from gyeeta_tpu import version
        from gyeeta_tpu.ingest import wire
        from gyeeta_tpu.net.agent import register
        from gyeeta_tpu.utils import hashing as H
        from gyeeta_tpu.utils import replay
        _, writer, status, _hid = await register(
            args.host, args.port,
            H.hash_bytes_np(b"gyt-replayer"), wire.CONN_EVENT,
            version.CURR_WIRE_VERSION)
        if status != wire.REG_OK:
            raise SystemExit(f"registration failed: {status}")
        # stream on the event loop with a drain per chunk: captures can
        # be many GB, so transport backpressure must gate the file read,
        # and a dropped conn must fail loudly, not buffer into the void
        from gyeeta_tpu.utils.selfstats import Stats
        stats = Stats()
        n = 0
        try:
            for delay, chunk in replay.paced_chunks(
                    args.capture, args.speed, args.host_offset,
                    stats=stats):
                if delay > 0:
                    await asyncio.sleep(delay)
                writer.write(chunk)
                await writer.drain()
                n += len(chunk)
        except (ConnectionError, OSError) as e:
            raise SystemExit(f"server dropped the conn after {n} bytes: "
                             f"{e}")
        writer.close()
        torn = int(stats.counters.get("replay_torn_tail", 0))
        print(f"replayed {n} bytes"
              + (" (capture tail torn — final partial chunk skipped)"
                 if torn else ""), file=sys.stderr)

    asyncio.run(run())


def _cmd_obs(argv) -> None:
    ap = argparse.ArgumentParser(
        prog="gyeeta_tpu obs",
        description="self-observability clients: 'top' renders the "
        "live selfstats/health/span surface; 'metrics' dumps the "
        "Prometheus exposition text")
    ap.add_argument("what", choices=("top", "metrics"))
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=10038)
    ap.add_argument("--interval", type=float, default=2.0,
                    help="top refresh cadence (seconds)")
    ap.add_argument("--once", action="store_true",
                    help="top: render one frame and exit")
    args = ap.parse_args(argv)

    async def run():
        from gyeeta_tpu.net.agent import QueryClient
        from gyeeta_tpu.obs import format_top
        qc = QueryClient()
        await qc.connect(args.host, args.port)
        try:
            if args.what == "metrics":
                out = await qc.query({"subsys": "metrics"})
                sys.stdout.write(out.get("text", ""))
                return
            prev, prev_t = None, 0.0
            while True:
                import time as _time
                ss = await qc.query({"subsys": "selfstats"})
                now = _time.time()
                frame = format_top(
                    ss, prev, (now - prev_t) if prev is not None else 0.0)
                if not args.once:
                    sys.stdout.write("\x1b[H\x1b[2J")   # clear screen
                sys.stdout.write(frame)
                sys.stdout.flush()
                if args.once:
                    return
                prev, prev_t = ss.get("counters", {}), now
                await asyncio.sleep(args.interval)
        finally:
            await qc.close()

    asyncio.run(run())


def _cmd_nm(argv) -> None:
    ap = argparse.ArgumentParser(
        prog="gyeeta_tpu nm",
        description="stock node-webserver (NM conn) clients: 'probe' "
        "runs the NM_CONNECT handshake plus one QUERY_WEB_JSON per "
        "subsystem and reports wire-level health; 'query' sends one "
        "raw QUERY_WEB_JSON/CRUD body over an NM conn")
    ap.add_argument("what", choices=("probe", "query"))
    ap.add_argument("request", nargs="?",
                    help="query: JSON body ({'qtype':..,'options':..} "
                    "or native {'subsys':..}), or '-' for stdin")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=10038)
    ap.add_argument("--subsys", default="serverstatus,hoststate,"
                    "svcstate,taskstate,alertdef",
                    help="probe: comma-separated subsystems to query")
    ap.add_argument("--crud", action="store_true",
                    help="probe: also run an alertdef create→list→"
                    "delete CRUD round trip")
    args = ap.parse_args(argv)

    async def run():
        from gyeeta_tpu.sim.nodeweb import NMError, NodeWebSim
        nw = NodeWebSim(hostname="nm-probe")
        hs = await nw.connect(args.host, args.port)
        try:
            if args.what == "query":
                body = sys.stdin.read() if args.request == "-" \
                    else (args.request or "{}")
                req = json.loads(body)
                if req.get("op"):
                    out = await nw.crud_alert(req) \
                        if req.get("objtype") in ("alertdef", "silence",
                                                  "inhibit", "action") \
                        else await nw.crud_generic(req)
                else:
                    out = await nw.request(
                        2, req if "qtype" in req else
                        {"qtype": req.pop("subsys"), "options": req})
                json.dump(out, sys.stdout, default=str)
                sys.stdout.write("\n")
                return
            print(f"nm probe: connected — madhava "
                  f"{hs['madhava_name']!r} id {hs['madhava_id']:#x} "
                  f"version {hs['madhava_version']:#08x}",
                  file=sys.stderr)
            failed = 0
            for sub in args.subsys.split(","):
                sub = sub.strip()
                try:
                    # strong: the probe checks the LIVE wire+engine
                    # path end to end (the snapshot default would
                    # serve a possibly-empty boot-time view)
                    out = await nw.query_web(sub, maxrecs=1,
                                             consistency="strong")
                    print(f"  {sub:<14} ok  nrecs={out.get('nrecs')}",
                          file=sys.stderr)
                except NMError as e:
                    failed += 1
                    print(f"  {sub:<14} ERR {e}", file=sys.stderr)
            if args.crud:
                name = "nm-probe-def"
                await nw.crud_alert({
                    "op": "add", "objtype": "alertdef",
                    "alertname": name, "subsys": "svcstate",
                    "filter": "{ svcstate.state in 'Severe' }"})
                lst = await nw.query_web("alertdef")
                ok = any(r.get("alertname") == name
                         for r in lst.get("recs", []))
                await nw.crud_alert({"op": "delete",
                                     "objtype": "alertdef",
                                     "name": name})
                print(f"  alertdef CRUD round-trip "
                      f"{'ok' if ok else 'FAILED'}", file=sys.stderr)
                failed += 0 if ok else 1
            if failed:
                raise SystemExit(f"nm probe: {failed} check(s) failed")
            print("nm probe: OK", file=sys.stderr)
        finally:
            await nw.close()

    asyncio.run(run())


def _cmd_compact(argv) -> None:
    ap = argparse.ArgumentParser(
        prog="gyeeta_tpu compact",
        description="offline WAL compaction: re-fold a journal dir "
        "through the engine and emit columnar snapshot shards "
        "(history/compactor.py) — the batch form of the serve "
        "daemon's in-process compactor. 'list' prints the shard "
        "manifest of a shard dir.")
    ap.add_argument("what", nargs="?", default="run",
                    choices=("run", "list"))
    ap.add_argument("--journal-dir", help="WAL source (run)")
    ap.add_argument("--shard-dir", required=True)
    ap.add_argument("--config", help="JSON config ({engine:…, "
                    "runtime:…}) — geometry MUST match the serving "
                    "process that wrote the WAL")
    ap.add_argument("--window-ticks", type=int, default=None)
    ap.add_argument("--upto-tick", type=int, default=None,
                    help="also tick past the last chunk's stamp (only "
                    "sound when the producer is stopped)")
    ap.add_argument("--procs", type=int, default=0,
                    help="N>=1: parallel compaction — N replay worker "
                    "processes over disjoint WAL shard groups into a "
                    "parted shard store (needs a sharded WAL; N <= "
                    "shard count). 0 = single replay runtime")
    args = ap.parse_args(argv)

    from gyeeta_tpu.utils import config as C
    if args.what == "list":
        from gyeeta_tpu.history.shards import open_shard_store
        store = open_shard_store(args.shard_dir)
        out = {"pos": store.position(), "tick": store.tick(),
               "shards": store.shards()}
        # shipped-store provenance: when the WAL source is a ship
        # staging dir, its content-hash ledger says which region
        # produced every segment (shipper id, instance token, epoch,
        # blake2b) — the operator's "who made this window" answer
        if args.journal_dir:
            import pathlib

            from gyeeta_tpu.net.segship import LEDGER_NAME
            lp = pathlib.Path(args.journal_dir) / LEDGER_NAME
            if lp.exists():
                segs = []
                for raw in lp.read_bytes().splitlines(keepends=True):
                    if not raw.endswith(b"\n"):
                        break              # torn tail: incomplete fact
                    try:
                        e = json.loads(raw)
                    except ValueError:
                        break
                    if e.get("meta") or "k" not in e:
                        continue
                    src = e.get("src") or {}
                    segs.append({
                        "segment": e["k"], "status": e.get("status"),
                        "hash": e.get("hash"),
                        "records": e.get("nrec"),
                        "bytes": e.get("size"),
                        "src_shipper": src.get("shipper"),
                        "src_epoch": src.get("epoch"),
                        "src_token": src.get("token"),
                        "src_host": src.get("host")})
                out["shipped_segments"] = segs
        json.dump(out, sys.stdout, indent=2)
        sys.stdout.write("\n")
        return
    if not args.journal_dir:
        raise SystemExit("compact run needs --journal-dir")
    cfg = C.load_engine_cfg(args.config)
    opts = C.load_runtime_opts(
        args.config, hist_shard_dir=args.shard_dir,
        **({"hist_window_ticks": args.window_ticks}
           if args.window_ticks is not None else {}))
    from gyeeta_tpu.utils.selfstats import Stats
    if args.procs >= 1:
        from gyeeta_tpu.history.compactproc import ParallelCompactor
        c = ParallelCompactor(cfg, opts, args.procs,
                              journal_dir=args.journal_dir,
                              shard_dir=args.shard_dir, stats=Stats())
    else:
        from gyeeta_tpu.history.compactor import Compactor
        c = Compactor(cfg, opts, journal_dir=args.journal_dir,
                      shard_dir=args.shard_dir, stats=Stats())
    try:
        rep = c.compact_once(upto_tick=args.upto_tick)
    finally:
        c.close()
    json.dump(rep, sys.stdout)
    sys.stdout.write("\n")


def _cmd_web(argv) -> None:
    ap = argparse.ArgumentParser(prog="gyeeta_tpu web")
    ap.add_argument("--host", default="127.0.0.1",
                    help="upstream gyt-server address")
    ap.add_argument("--port", type=int, default=10038)
    # loopback by default: the gateway is UNAUTHENTICATED query + CRUD
    # — exposing it wider is an explicit operator decision (put auth in
    # front, like the reference's Node tier expects)
    ap.add_argument("--listen-host", default="127.0.0.1")
    ap.add_argument("--listen-port", type=int, default=10080)
    args = ap.parse_args(argv)

    async def run():
        from gyeeta_tpu.net.webgw import WebGateway
        gw = WebGateway(args.host, args.port, host=args.listen_host,
                        port=args.listen_port)
        h, p = await gw.start()
        print(f"web gateway on http://{h}:{p} -> gyt "
              f"{args.host}:{args.port}", file=sys.stderr)
        await asyncio.Event().wait()

    asyncio.run(run())


def _cmd_relay(argv) -> None:
    """Remote ingest relay (net/relay.py): runs the full ingest edge
    on THIS host — agents register and stream here — and ships decoded
    batches to the serve process's --relay-port over one exact-ledger
    TCP uplink (published == consumed + counted drops, across
    machines, across relay restarts)."""
    from gyeeta_tpu.net.relay import relay_main
    relay_main(argv)


def _cmd_ship(argv) -> None:
    """Source-region segment shipper (history/shipper.py): sealed WAL
    segments stream to a remote compaction region's staging receiver,
    content-hashed and resumable, with the ship truncate floor
    pinning unshipped segments against checkpoint truncation."""
    from gyeeta_tpu.history.shipper import ship_main
    ship_main(argv)


def _cmd_shiprecv(argv) -> None:
    """Compaction-region staging receiver (net/segship.py): sealed
    segments land here hash-verified + crash-consistent; point
    `compact --procs N` (or serve --compact-procs with --ship-staging)
    at the staging dir to replay them exactly as if local."""
    from gyeeta_tpu.net.segship import recv_main
    recv_main(argv)


def _cmd_gateway(argv) -> None:
    ap = argparse.ArgumentParser(prog="gyeeta_tpu gateway")
    ap.add_argument("--upstream", action="append", default=[],
                    metavar="HOST:PORT",
                    help="serve replica to fan out to (repeatable; "
                    ">=2 makes the cache worth the hop)")
    ap.add_argument("--hub-from", action="append", default=[],
                    metavar="HOST:PORT", dest="hub_from",
                    help="run as a cross-region HUB: subscribe to a "
                    "PEER GATEWAY's delta stream instead of polling "
                    "serve replicas — the whole remote region rides "
                    "one delta stream per distinct query (repeatable "
                    "for failover across the home region's gateways)")
    ap.add_argument("--peer", action="append", default=[],
                    metavar="HOST:PORT",
                    help="another gateway instance to exchange cached "
                    "results with (repeatable)")
    # loopback by default, same reasoning as the web gateway: the
    # fabric edge is UNAUTHENTICATED query + subscribe
    ap.add_argument("--listen-host", default="127.0.0.1")
    ap.add_argument("--listen-port", type=int, default=10090)
    ap.add_argument("--poll-s", type=float, default=None,
                    help="snaptick watch cadence per upstream "
                    "(default GYT_GW_POLL_S or 0.5)")
    # fault-domain knobs (OPERATIONS.md "Failure domains &
    # degradation"): circuit breaker, hedged reads, subscription
    # continuation across restarts
    ap.add_argument("--gw-down-after", type=int, default=None,
                    help="consecutive failures before an upstream is "
                    "marked down (circuit breaker; default "
                    "GYT_GW_DOWN_AFTER or 3 — never one bad poll)")
    ap.add_argument("--hedge-ms", type=float, default=None,
                    help="latency budget past which a render hedges "
                    "to the next-healthiest replica (default "
                    "GYT_GW_HEDGE_MS or 75; 0 disables)")
    ap.add_argument("--sub-persist", default=None,
                    help="append-only file persisting the "
                    "subscription version ring: a restarted gateway "
                    "resumes reconnecting subscribers with DELTAS "
                    "instead of full resyncs (default "
                    "GYT_GW_SUB_PERSIST or off)")
    ap.add_argument("--advertise", default=None,
                    help="the host:port PEERS dial this gateway on "
                    "(rendezvous key ownership; default the listen "
                    "address)")
    args = ap.parse_args(argv)

    def hp(s):
        h, _, p = s.rpartition(":")
        return (h or "127.0.0.1", int(p))

    if not args.upstream and not args.hub_from:
        ap.error("need --upstream (region-local) or --hub-from "
                 "(cross-region hub)")

    async def run():
        from gyeeta_tpu.net.gateway import FabricGateway
        gw = FabricGateway([hp(u) for u in args.upstream]
                           or [hp(u) for u in args.hub_from],
                           host=args.listen_host,
                           port=args.listen_port,
                           peers=[hp(p) for p in args.peer],
                           poll_s=args.poll_s,
                           down_after=args.gw_down_after,
                           hedge_ms=args.hedge_ms,
                           sub_persist=args.sub_persist,
                           advertise=args.advertise,
                           hub=bool(args.hub_from))
        h, p = await gw.start()
        mode = "HUB <-" if args.hub_from else "->"
        print(f"fabric gateway on {h}:{p} (REST + GYT + NM) {mode} "
              f"{len(gw.upstreams)} upstream(s), "
              f"{len(gw.peers)} peer(s)", file=sys.stderr)
        await asyncio.Event().wait()

    asyncio.run(run())


def main(argv=None) -> None:
    argv = sys.argv[1:] if argv is None else argv
    if argv and argv[0] in ("query", "agent", "replay", "web", "obs",
                            "nm", "chaos", "compact", "gateway",
                            "relay", "ship", "shiprecv"):
        return {"query": _cmd_query, "agent": _cmd_agent,
                "replay": _cmd_replay, "web": _cmd_web,
                "obs": _cmd_obs, "nm": _cmd_nm,
                "chaos": _cmd_chaos, "gateway": _cmd_gateway,
                "relay": _cmd_relay, "ship": _cmd_ship,
                "shiprecv": _cmd_shiprecv,
                "compact": _cmd_compact}[argv[0]](argv[1:])
    if argv and argv[0] == "serve":
        argv = argv[1:]
    from gyeeta_tpu.server_main import main as serve_main
    serve_main(argv)


if __name__ == "__main__":
    main()
