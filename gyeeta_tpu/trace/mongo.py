"""MongoDB wire-protocol transaction parser.

The Mongo analogue of the reference's ``common/gy_mongo_proto.{h,cc}``
(OP_MSG and legacy OP_QUERY parse, request/response pairing, error
detection from the reply document) — rebuilt as an incremental state
machine over the two directed byte streams of one connection.

API signature is ``<command> <collection>`` (e.g. ``find orders``,
``insert users``) extracted from the first element of the command
document: Mongo commands put the command name first and the collection
name as its value, with ``$db`` later in the doc — a shape-stable
signature without any BSON deep-walk. Responses pair by ``responseTo``
matching the request's ``requestID`` (Mongo multiplexes on one conn);
``ok: 0.0`` in the reply document marks an error transaction.
"""

from __future__ import annotations

import struct
from typing import NamedTuple, Optional

from gyeeta_tpu.trace.proto import PROTO_MONGO, Transaction

OP_REPLY = 1
OP_QUERY = 2004
OP_MSG = 2013
OP_COMPRESSED = 2012

# commands that should never appear as an API signature (conn chatter)
_ADMIN_CMDS = frozenset((
    "ismaster", "isMaster", "hello", "ping", "buildInfo", "buildinfo",
    "saslStart", "saslContinue", "getnonce", "authenticate",
))


class _Pending(NamedTuple):
    api: str
    tusec: int
    nbytes: int


def bson_first_element(doc: bytes) -> tuple[Optional[str], Optional[object]]:
    """(name, value) of the first element of a BSON document.

    value is decoded for string/double/int32/int64/bool, else None.
    Malformed docs return (None, None) — parsers must survive captures
    that start mid-stream.
    """
    els = bson_elements(doc, limit=1)
    return els[0] if els else (None, None)


def bson_elements(doc: bytes, limit: int = 32) -> list:
    """Shallow-walk up to ``limit`` top-level elements of a BSON doc."""
    out = []
    if len(doc) < 5:
        return out
    total = struct.unpack_from("<i", doc, 0)[0]
    if total < 5 or total > len(doc):
        total = len(doc)
    i = 4
    while i < total - 1 and len(out) < limit:
        typ = doc[i]
        i += 1
        if typ == 0:
            break
        j = doc.find(b"\x00", i)
        if j < 0:
            break
        name = doc[i:j].decode("utf-8", "replace")
        i = j + 1
        val: Optional[object] = None
        if typ == 0x01:                         # double
            if i + 8 > total:
                break
            val = struct.unpack_from("<d", doc, i)[0]
            i += 8
        elif typ == 0x02:                       # string
            if i + 4 > total:
                break
            slen = struct.unpack_from("<i", doc, i)[0]
            if slen < 1 or i + 4 + slen > total:
                break
            val = doc[i + 4: i + 4 + slen - 1].decode("utf-8", "replace")
            i += 4 + slen
        elif typ in (0x03, 0x04):               # embedded doc / array
            if i + 4 > total:
                break
            dlen = struct.unpack_from("<i", doc, i)[0]
            if dlen < 5 or i + dlen > total:
                break
            i += dlen
        elif typ == 0x05:                       # binary
            if i + 5 > total:
                break
            blen = struct.unpack_from("<i", doc, i)[0]
            i += 4 + 1 + max(blen, 0)
        elif typ == 0x07:                       # ObjectId
            i += 12
        elif typ == 0x08:                       # bool
            if i >= total:
                break
            val = bool(doc[i])
            i += 1
        elif typ in (0x09, 0x11, 0x12):         # datetime/timestamp/int64
            if i + 8 > total:
                break
            val = struct.unpack_from("<q", doc, i)[0]
            i += 8
        elif typ == 0x0A:                       # null
            pass
        elif typ == 0x10:                       # int32
            if i + 4 > total:
                break
            val = struct.unpack_from("<i", doc, i)[0]
            i += 4
        elif typ == 0x13:                       # decimal128
            i += 16
        else:                                   # unknown type: stop walking
            break
        out.append((name, val))
        if i > total:
            break
    return out


def _api_from_command(doc: bytes) -> Optional[str]:
    name, val = bson_first_element(doc)
    if not name or name.startswith("$") or name in _ADMIN_CMDS:
        return None
    if isinstance(val, str) and val and len(val) <= 120:
        return f"{name} {val}"
    return name


class MongoParser:
    """Request/response pairing for one Mongo connection.

    ``feed_request`` / ``feed_response`` accept arbitrary chunk
    boundaries. Responses match requests via the header's ``responseTo``
    field; unmatched responses (server push, exhausted cursors) are
    dropped. OP_COMPRESSED payloads can't be inspected — the transaction
    still pairs and times, with api ``compressed``.
    """

    # never buffer more than this awaiting a frame's completion; larger
    # messages (bulk inserts, cursor batches) are length-skipped without
    # buffering — their command doc is in the first bytes anyway
    MAX_BUFFER = 1 << 20

    def __init__(self, max_queue: int = 64):
        self._req_buf = b""
        self._resp_buf = b""
        self._req_skip = 0          # bytes of an oversized frame to discard
        self._resp_skip = 0
        self._pending: dict[int, _Pending] = {}
        self._max_queue = max_queue
        self.transactions: list[Transaction] = []

    # ------------------------------------------------------------- frames
    def _walk(self, buf: bytes, skip: int, cb) -> tuple[bytes, int]:
        """Invoke ``cb(header, body)`` per complete frame; return the
        (unconsumed tail, remaining skip) for partial-frame resume. A
        nonsense length field means we joined mid-stream: drop the
        buffer and resync at the next capture gap. Frames larger than
        MAX_BUFFER are parsed from their first MAX_BUFFER bytes and the
        remainder is skipped without buffering."""
        if skip:
            take = min(skip, len(buf))
            buf = buf[take:]
            skip -= take
            if skip:
                return b"", skip
        i = 0
        while len(buf) - i >= 16:
            mlen, reqid, respto, op = struct.unpack_from("<iiii", buf, i)
            if mlen < 16 or mlen > 48_000_000:
                return b"", 0
            if mlen > self.MAX_BUFFER:
                if len(buf) - i < 16 + 4096:    # want the command head
                    break
                cb((mlen, reqid, respto, op), buf[i + 16: i + 16 + 4096])
                if len(buf) - i >= mlen:        # whole frame already here
                    i += mlen
                    continue
                return b"", mlen - (len(buf) - i)
            if len(buf) - i < mlen:
                break
            cb((mlen, reqid, respto, op), buf[i + 16: i + mlen])
            i += mlen
        return buf[i:], 0

    # --------------------------------------------------------------- feed
    def feed_request(self, data: bytes, tusec: int) -> None:
        def on_frame(hdr, body):
            mlen, reqid, _respto, op = hdr
            api: Optional[str] = None
            if op == OP_MSG and len(body) >= 5:
                # flagBits(4) then sections; kind-0 section = command doc
                k = 4
                if body[k] == 0:
                    api = _api_from_command(body[k + 1:])
            elif op == OP_QUERY and len(body) >= 9:
                # flags(4), fullCollectionName cstring, skip(4), ret(4), doc
                j = body.find(b"\x00", 4)
                if j > 0:
                    coll = body[4:j].decode("utf-8", "replace")
                    doc = body[j + 9:]
                    name, _ = bson_first_element(doc)
                    if coll.endswith(".$cmd"):
                        api = _api_from_command(doc)
                    elif name:
                        api = f"query {coll}"
            elif op == OP_COMPRESSED:
                api = "compressed"
            if api is not None:
                # bounded with oldest-first eviction: orphaned requests
                # (responses lost to capture gaps) must not wedge the
                # queue — insertion order IS request order
                while len(self._pending) >= self._max_queue:
                    self._pending.pop(next(iter(self._pending)))
                self._pending[reqid] = _Pending(api, tusec, mlen)

        self._req_buf, self._req_skip = self._walk(
            self._req_buf + data, self._req_skip, on_frame)

    def feed_response(self, data: bytes, tusec: int) -> None:
        def on_frame(hdr, body):
            mlen, _reqid, respto, op = hdr
            req = self._pending.pop(respto, None)
            if req is None:
                return
            is_err = False
            if op == OP_MSG and len(body) >= 5 and body[4] == 0:
                for name, val in bson_elements(body[5:], limit=16):
                    if name == "ok":
                        is_err = not bool(val)
                        break
            elif op == OP_REPLY and len(body) >= 4:
                flags = struct.unpack_from("<i", body, 0)[0]
                is_err = bool(flags & 0x2)      # QueryFailure
            self.transactions.append(Transaction(
                proto=PROTO_MONGO, api=req.api, t_req_usec=req.tusec,
                resp_usec=max(0, tusec - req.tusec),
                status=1 if is_err else 0, is_error=is_err,
                bytes_in=req.nbytes, bytes_out=mlen))

        self._resp_buf, self._resp_skip = self._walk(
            self._resp_buf + data, self._resp_skip, on_frame)

    def drain(self) -> list[Transaction]:
        out, self.transactions = self.transactions, []
        return out
