"""Passive DNS snooping: port-53 responses → IP→domain mappings.

The reference's DNS mapper captures DNS traffic and learns the domain
each IP was RESOLVED AS (``common/gy_dns_mapping.h:46``) — names a
reverse resolver can never see (CDN/anycast IPs answer PTR with
infrastructure names, or not at all). This module parses DNS response
messages (the UDP payload; works on frames from live AF_PACKET capture
or pcap files) and yields (domain, ip_text) pairs for the
:class:`~gyeeta_tpu.utils.dnsmap.DnsCache` to prime.

Wire format: RFC 1035 — 12-byte header, QD section skipped, answer
records walked with name-compression handling; only A/AAAA answers
yield mappings (CNAME chains resolve through the final address
records, which carry the QUERY name context via the answer owner)."""

from __future__ import annotations

import ipaddress
import struct

_MAX_NAME_JUMPS = 32


def _read_name(msg: bytes, off: int) -> tuple[str, int]:
    """Decode a (possibly compressed) domain name. → (name, next_off).
    next_off is the offset after the name AT THE ORIGINAL position
    (compression pointers don't advance the caller's cursor)."""
    labels = []
    jumps = 0
    end = None
    while True:
        if off >= len(msg):
            raise ValueError("truncated name")
        ln = msg[off]
        if ln == 0:
            if end is None:
                end = off + 1
            break
        if ln & 0xC0 == 0xC0:
            if off + 2 > len(msg):
                raise ValueError("truncated pointer")
            if end is None:
                end = off + 2
            ptr = struct.unpack_from("!H", msg, off)[0] & 0x3FFF
            jumps += 1
            if jumps > _MAX_NAME_JUMPS:
                raise ValueError("compression loop")
            off = ptr
            continue
        if ln & 0xC0:
            raise ValueError("bad label type")
        off += 1
        if off + ln > len(msg):
            raise ValueError("truncated label")
        labels.append(msg[off: off + ln])
        off += ln
    return b".".join(labels).decode("ascii", "replace").lower(), end


def parse_response(msg: bytes):
    """One DNS message → [(domain, ip_text)] from its A/AAAA answers.
    Non-responses and malformed messages yield []."""
    if len(msg) < 12:
        return []
    (_tid, flags, qd, an, _ns, _ar) = struct.unpack_from("!HHHHHH", msg)
    if not flags & 0x8000 or an == 0:        # queries carry no answers
        return []
    try:
        off = 12
        qname = ""
        for _ in range(qd):                  # skip the question section
            qname, off = _read_name(msg, off)
            off += 4                         # qtype + qclass
        out = []
        for _ in range(an):
            owner, off = _read_name(msg, off)
            if off + 10 > len(msg):
                break
            rtype, _rclass, _ttl, rdlen = struct.unpack_from(
                "!HHIH", msg, off)
            off += 10
            rdata = msg[off: off + rdlen]
            off += rdlen
            # CNAME answers re-point the owner; address records under a
            # CNAME chain still describe the QUERY name (what the
            # client asked for is the service identity)
            name = qname or owner
            if rtype == 1 and rdlen == 4:        # A
                out.append((name, str(ipaddress.IPv4Address(rdata))))
            elif rtype == 28 and rdlen == 16:    # AAAA
                out.append((name, str(ipaddress.IPv6Address(rdata))))
        return out
    except ValueError:
        return []


def udp_dns_payload(frame: bytes, l3: int):
    """Ethernet frame + L3 offset → the DNS message bytes when this is
    a UDP src-port-53 datagram, else None (the livecap fast filter)."""
    if len(frame) < l3 + 28:
        return None
    ver = frame[l3] >> 4
    if ver == 4:
        ihl = (frame[l3] & 0xF) * 4
        if frame[l3 + 9] != 17 or len(frame) < l3 + ihl + 8:
            return None
        udp = l3 + ihl
    elif ver == 6:
        if frame[l3 + 6] != 17 or len(frame) < l3 + 48:
            return None              # full v6 header + UDP header
        udp = l3 + 40
    else:
        return None
    sport = struct.unpack_from("!H", frame, udp)[0]
    if sport != 53:
        return None
    return frame[udp + 8:]
