"""Live AF_PACKET capture behind a privilege gate (VERDICT r4 #9).

The reference captures each service's traffic live with per-netns
AF_PACKET sockets and dynamic BPF filters, plus a cheap error-only
HTTP tier feeding ``ser_errors``
(``common/gy_svc_net_capture.h:153,232,286``,
``gy_network_capture.h``). Userspace here CAN do the same when the
process holds CAP_NET_RAW — this module opens a raw packet socket on
one interface, batches captured frames, and replays them through the
SAME reassembly/parser machinery the pcap-file path uses
(``trace/pcapfile.py``) — one tested flow engine for files and live
traffic.

Design notes (redesign, not a translation):
- **Privilege-gated, never required**: :func:`available` probes
  CAP_NET_RAW by opening-and-closing a socket; everything degrades to
  "no live capture" cleanly (the reference also runs captureless when
  the cap is missing).
- **Batch-replay, not per-packet**: frames accumulate in a bounded
  ring and parse on :meth:`drain` cadence as a synthesized pcap
  stream. Parsing cost is paid per drain (5s cadence), not per
  packet — the same batching discipline as the engine's K-slab folds.
- **Port filter = the dynamic-BPF analogue**: a host-side port set
  bounds buffered frames; the error tier is a post-parse filter
  (headers only are parsed either way, so "cheap tier" = keep only
  ``is_error`` transactions).
"""

from __future__ import annotations

import socket
import struct
import time
from typing import Optional

from gyeeta_tpu.trace import pcapfile as PF

ETH_P_ALL = 0x0003


def available(ifname: str = "lo") -> bool:
    """True when this process may open AF_PACKET sockets (CAP_NET_RAW)."""
    try:
        s = socket.socket(socket.AF_PACKET, socket.SOCK_RAW,
                          socket.htons(ETH_P_ALL))
        try:
            s.bind((ifname, 0))
        finally:
            s.close()
        return True
    except (PermissionError, OSError):
        return False


class LiveCapture:
    """One interface's live TCP capture → parsed transactions.

    ``ports`` restricts buffering to frames whose TCP src or dst port
    is in the set (both directions of a service's conversations).
    ``err_only`` keeps only error transactions at drain (the cheap
    tier). Raises PermissionError without CAP_NET_RAW — callers gate
    on :func:`available`.
    """

    def __init__(self, ifname: str = "lo",
                 ports: Optional[set] = None,
                 err_only: bool = False,
                 max_frames: int = 65536,
                 snaplen: int = 1 << 17,
                 dns_snoop: bool = False):
        # snaplen default covers full loopback/GSO frames WITH their
        # link header (14B ethernet + up to 64KiB IP > 65535): recv()
        # TRUNCATES to the buffer and a cut frame poisons the flow's
        # TCP reassembly (sequence gap) — whole-frame capture is the
        # correctness default; shrink only for err-only tiers that
        # parse headers alone
        self.ifname = ifname
        self.ports = set(ports) if ports else None
        self.err_only = err_only
        self.max_frames = max_frames
        self.snaplen = snaplen
        self.dns_snoop = dns_snoop    # harvest port-53 responses too
        self.n_dropped = 0            # ring overflow (counted, not silent)
        self.n_frames = 0
        self._frames: list[tuple[int, bytes]] = []
        self._dns: list[tuple[str, str]] = []
        # cross-drain continuity for boundary-spanning transactions
        self._carry: list[tuple[int, bytes]] = []
        self._emitted: dict = {}      # flow key -> txns already emitted
        self._pending_age: dict = {}  # flow key -> drains w/o progress
        self._sock = socket.socket(socket.AF_PACKET, socket.SOCK_RAW,
                                   socket.htons(ETH_P_ALL))
        self._sock.bind((ifname, 0))
        self._sock.setblocking(False)
        try:
            # polled on sweep cadence (seconds apart): a deep kernel
            # buffer absorbs the between-poll burst
            self._sock.setsockopt(socket.SOL_SOCKET,
                                  socket.SO_RCVBUF, 8 << 20)
        except OSError:
            pass

    # ------------------------------------------------------------ intake
    def _want(self, frame: bytes) -> bool:
        if self.ports is None:
            return True
        l3 = PF._l3_offset(PF._LINK_ETH, frame)
        if l3 is None or len(frame) < l3 + 20:
            return False
        ver = frame[l3] >> 4
        if ver == 4:
            ihl = (frame[l3] & 0xF) * 4
            if frame[l3 + 9] != 6 or len(frame) < l3 + ihl + 4:
                return False
            tcp = l3 + ihl
        elif ver == 6:
            if frame[l3 + 6] != 6 or len(frame) < l3 + 44:
                return False
            tcp = l3 + 40
        else:
            return False
        sport, dport = struct.unpack_from("!HH", frame, tcp)
        return sport in self.ports or dport in self.ports

    def poll(self, max_pkts: int = 8192) -> int:
        """Drain the socket's pending frames into the ring. Returns
        frames buffered this call. Non-blocking; call on cadence."""
        got = 0
        for _ in range(max_pkts):
            try:
                frame = self._sock.recv(self.snaplen)
            except BlockingIOError:
                break
            except OSError:
                break
            if not frame:
                continue
            if self.dns_snoop:
                from gyeeta_tpu.trace import dnssnoop
                l3 = PF._l3_offset(PF._LINK_ETH, frame)
                if l3 is not None:
                    payload = dnssnoop.udp_dns_payload(frame, l3)
                    if payload is not None:
                        self._dns.extend(dnssnoop.parse_response(payload))
                        continue
            if not self._want(frame):
                continue
            if len(self._frames) >= self.max_frames:
                self.n_dropped += 1      # bounded ring: count overflow
                continue
            self._frames.append((time.time_ns() // 1000, frame))
            got += 1
        self.n_frames += got
        return got

    # ------------------------------------------------------------- drain
    @staticmethod
    def _flow_key(frame: bytes):
        """Frame → normalized flow key (parse_pcap's key), or None."""
        l3 = PF._l3_offset(PF._LINK_ETH, frame)
        if l3 is None:
            return None
        parsed = PF._parse_ip_tcp(frame[l3:])
        if parsed is None:
            return None
        src, sport, dst, dport = parsed[:4]
        a, b = (src, sport), (dst, dport)
        return (a, b) if a <= b else (b, a)

    # retained pending flows age out after this many drains without a
    # completed transaction (half-open conns must not pin frames)
    _PENDING_MAX_DRAINS = 8
    _PENDING_MAX_FRAMES = 4096

    def drain(self, record_path: Optional[str] = None):
        """Parse buffered frames → [FlowConversation] with NEW
        transactions only. Flows whose parser still holds an
        unanswered request keep their frames across drains, so a
        transaction spanning a capture window (the slow ones — exactly
        the interesting tail) completes in a later drain instead of
        splitting. ``err_only`` filters transactions to errors;
        ``record_path`` appends the NEW frames as replayable pcap."""
        new_frames, self._frames = self._frames, []
        if record_path and new_frames:
            buf_new = PF.write_pcap(new_frames)
            with open(record_path, "ab") as f:
                # one global header per file: append records only when
                # the file already exists with content
                f.write(buf_new if f.tell() == 0 else buf_new[24:])
        frames = sorted(self._carry + new_frames)
        self._carry = []
        if not frames:
            return []
        flows = PF.parse_pcap(PF.write_pcap(frames),
                              include_pending=True)
        by_key: dict = {}
        for tus, fr in frames:
            k = self._flow_key(fr)
            if k is not None:
                by_key.setdefault(k, []).append((tus, fr))
        out = []
        seen_keys = set()
        for f in flows:
            a, b = f.cli, f.ser
            k = (a, b) if a <= b else (b, a)
            seen_keys.add(k)
            done_before = self._emitted.get(k, 0)
            new_txns = f.transactions[done_before:]
            if f.pending:
                age = self._pending_age.get(k, 0) + (0 if new_txns
                                                     else 1)
                kept = by_key.get(k, [])[-self._PENDING_MAX_FRAMES:]
                if age <= self._PENDING_MAX_DRAINS:
                    self._carry.extend(kept)
                    self._emitted[k] = len(f.transactions)
                    self._pending_age[k] = age
                else:                      # stale half-open flow
                    self._emitted.pop(k, None)
                    self._pending_age.pop(k, None)
            else:
                self._emitted.pop(k, None)
                self._pending_age.pop(k, None)
            if new_txns:
                f = f._replace(transactions=list(new_txns))
                out.append(f)
        # bookkeeping for keys that produced no flow this round
        for k in list(self._emitted):
            if k not in seen_keys:
                self._emitted.pop(k, None)
                self._pending_age.pop(k, None)
        flows = out
        if self.err_only:
            for f in flows:
                f.transactions[:] = [t for t in f.transactions
                                     if t.is_error]
            flows = [f for f in flows if f.transactions]
        return flows

    def drain_dns(self) -> list:
        """Snooped (domain, ip) pairs since the last drain — prime a
        :class:`~gyeeta_tpu.utils.dnsmap.DnsCache` with them."""
        out, self._dns = self._dns, []
        return out

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass
