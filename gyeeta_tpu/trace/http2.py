"""HTTP/2 (+gRPC) transaction parser with HPACK header decoding.

The analogue of the reference's ``common/gy_http2_proto.{h,cc}`` /
``gy_http2_proto_detail.h`` (frame walk + HPACK for method/path/status)
— rebuilt as an incremental per-connection state machine:

- frame layer: 9-byte header walk with partial-frame resume; HEADERS +
  CONTINUATION fragments accumulate until END_HEADERS;
- HPACK (RFC 7541): full instruction set — indexed, literal with/without
  /never indexing, dynamic-table size update — with a real dynamic table
  and canonical Huffman decoding (Appendix B code table);
- transaction layer: ``:method``/``:path`` open a stream's request,
  ``:status`` (plus ``grpc-status`` in trailers for gRPC) closes it;
  streams are concurrent (odd client stream ids), so pairing is by
  stream id, not FIFO.

gRPC rides on this parser for free: a gRPC call is an HTTP/2 POST whose
path *is* the API signature (``/pkg.Service/Method`` — no templating
needed) and whose error comes from ``grpc-status != 0``.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

from gyeeta_tpu.trace.proto import (
    PROTO_HTTP2, Transaction, normalize_http,
)

_PREFACE = b"PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n"

FRAME_DATA = 0x0
FRAME_HEADERS = 0x1
FRAME_RST_STREAM = 0x3
FRAME_CONTINUATION = 0x9
FLAG_END_HEADERS = 0x4
FLAG_PADDED = 0x8
FLAG_PRIORITY = 0x20

# ------------------------------------------------------------------ HPACK
# RFC 7541 Appendix A static table (index 1..61): (name, value)
STATIC_TABLE = (
    (":authority", ""), (":method", "GET"), (":method", "POST"),
    (":path", "/"), (":path", "/index.html"), (":scheme", "http"),
    (":scheme", "https"), (":status", "200"), (":status", "204"),
    (":status", "206"), (":status", "304"), (":status", "400"),
    (":status", "404"), (":status", "500"), ("accept-charset", ""),
    ("accept-encoding", "gzip, deflate"), ("accept-language", ""),
    ("accept-ranges", ""), ("accept", ""), ("access-control-allow-origin",
    ""), ("age", ""), ("allow", ""), ("authorization", ""),
    ("cache-control", ""), ("content-disposition", ""),
    ("content-encoding", ""), ("content-language", ""),
    ("content-length", ""), ("content-location", ""), ("content-range", ""),
    ("content-type", ""), ("cookie", ""), ("date", ""), ("etag", ""),
    ("expect", ""), ("expires", ""), ("from", ""), ("host", ""),
    ("if-match", ""), ("if-modified-since", ""), ("if-none-match", ""),
    ("if-range", ""), ("if-unmodified-since", ""), ("last-modified", ""),
    ("link", ""), ("location", ""), ("max-forwards", ""),
    ("proxy-authenticate", ""), ("proxy-authorization", ""), ("range", ""),
    ("referer", ""), ("refresh", ""), ("retry-after", ""), ("server", ""),
    ("set-cookie", ""), ("strict-transport-security", ""),
    ("transfer-encoding", ""), ("user-agent", ""), ("vary", ""),
    ("via", ""), ("www-authenticate", ""),
)

# RFC 7541 Appendix B Huffman code table: (code, bit_length) per symbol
# 0..255 (EOS omitted — padding uses its prefix). Data, not logic.
_HUFF = (
    (0x1ff8, 13), (0x7fffd8, 23), (0xfffffe2, 28), (0xfffffe3, 28),
    (0xfffffe4, 28), (0xfffffe5, 28), (0xfffffe6, 28), (0xfffffe7, 28),
    (0xfffffe8, 28), (0xffffea, 24), (0x3ffffffc, 30), (0xfffffe9, 28),
    (0xfffffea, 28), (0x3ffffffd, 30), (0xfffffeb, 28), (0xfffffec, 28),
    (0xfffffed, 28), (0xfffffee, 28), (0xfffffef, 28), (0xffffff0, 28),
    (0xffffff1, 28), (0xffffff2, 28), (0x3ffffffe, 30), (0xffffff3, 28),
    (0xffffff4, 28), (0xffffff5, 28), (0xffffff6, 28), (0xffffff7, 28),
    (0xffffff8, 28), (0xffffff9, 28), (0xffffffa, 28), (0xffffffb, 28),
    (0x14, 6), (0x3f8, 10), (0x3f9, 10), (0xffa, 12),
    (0x1ff9, 13), (0x15, 6), (0xf8, 8), (0x7fa, 11),
    (0x3fa, 10), (0x3fb, 10), (0xf9, 8), (0x7fb, 11),
    (0xfa, 8), (0x16, 6), (0x17, 6), (0x18, 6),
    (0x0, 5), (0x1, 5), (0x2, 5), (0x19, 6),
    (0x1a, 6), (0x1b, 6), (0x1c, 6), (0x1d, 6),
    (0x1e, 6), (0x1f, 6), (0x5c, 7), (0xfb, 8),
    (0x7ffc, 15), (0x20, 6), (0xffb, 12), (0x3fc, 10),
    (0x1ffa, 13), (0x21, 6), (0x5d, 7), (0x5e, 7),
    (0x5f, 7), (0x60, 7), (0x61, 7), (0x62, 7),
    (0x63, 7), (0x64, 7), (0x65, 7), (0x66, 7),
    (0x67, 7), (0x68, 7), (0x69, 7), (0x6a, 7),
    (0x6b, 7), (0x6c, 7), (0x6d, 7), (0x6e, 7),
    (0x6f, 7), (0x70, 7), (0x71, 7), (0x72, 7),
    (0xfc, 8), (0x73, 7), (0xfd, 8), (0x1ffb, 13),
    (0x7fff0, 19), (0x1ffc, 13), (0x3ffc, 14), (0x22, 6),
    (0x7ffd, 15), (0x3, 5), (0x23, 6), (0x4, 5),
    (0x24, 6), (0x5, 5), (0x25, 6), (0x26, 6),
    (0x27, 6), (0x6, 5), (0x74, 7), (0x75, 7),
    (0x28, 6), (0x29, 6), (0x2a, 6), (0x7, 5),
    (0x2b, 6), (0x76, 7), (0x2c, 6), (0x8, 5),
    (0x9, 5), (0x2d, 6), (0x77, 7), (0x78, 7),
    (0x79, 7), (0x7a, 7), (0x7b, 7), (0x7ffe, 15),
    (0x7fc, 11), (0x3ffd, 14), (0x1ffd, 13), (0xffffffc, 28),
    (0xfffe6, 20), (0x3fffd2, 22), (0xfffe7, 20), (0xfffe8, 20),
    (0x3fffd3, 22), (0x3fffd4, 22), (0x3fffd5, 22), (0x7fffd9, 23),
    (0x3fffd6, 22), (0x7fffda, 23), (0x7fffdb, 23), (0x7fffdc, 23),
    (0x7fffdd, 23), (0x7fffde, 23), (0xffffeb, 24), (0x7fffdf, 23),
    (0xffffec, 24), (0xffffed, 24), (0x3fffd7, 22), (0x7fffe0, 23),
    (0xffffee, 24), (0x7fffe1, 23), (0x7fffe2, 23), (0x7fffe3, 23),
    (0x7fffe4, 23), (0x1fffdc, 21), (0x3fffd8, 22), (0x7fffe5, 23),
    (0x3fffd9, 22), (0x7fffe6, 23), (0x7fffe7, 23), (0xffffef, 24),
    (0x3fffda, 22), (0x1fffdd, 21), (0xfffe9, 20), (0x3fffdb, 22),
    (0x3fffdc, 22), (0x7fffe8, 23), (0x7fffe9, 23), (0x1fffde, 21),
    (0x7fffea, 23), (0x3fffdd, 22), (0x3fffde, 22), (0xfffff0, 24),
    (0x1fffdf, 21), (0x3fffdf, 22), (0x7fffeb, 23), (0x7fffec, 23),
    (0x1fffe0, 21), (0x1fffe1, 21), (0x3fffe0, 22), (0x1fffe2, 21),
    (0x7fffed, 23), (0x3fffe1, 22), (0x7fffee, 23), (0x7fffef, 23),
    (0xfffea, 20), (0x3fffe2, 22), (0x3fffe3, 22), (0x3fffe4, 22),
    (0x7ffff0, 23), (0x3fffe5, 22), (0x3fffe6, 22), (0x7ffff1, 23),
    (0x3ffffe0, 26), (0x3ffffe1, 26), (0xfffeb, 20), (0x7fff1, 19),
    (0x3fffe7, 22), (0x7ffff2, 23), (0x3fffe8, 22), (0x1ffffec, 25),
    (0x3ffffe2, 26), (0x3ffffe3, 26), (0x3ffffe4, 26), (0x7ffffde, 27),
    (0x7ffffdf, 27), (0x3ffffe5, 26), (0xfffff1, 24), (0x1ffffed, 25),
    (0x7fff2, 19), (0x1fffe3, 21), (0x3ffffe6, 26), (0x7ffffe0, 27),
    (0x7ffffe1, 27), (0x3ffffe7, 26), (0x7ffffe2, 27), (0xfffff2, 24),
    (0x1fffe4, 21), (0x1fffe5, 21), (0x3ffffe8, 26), (0x3ffffe9, 26),
    (0xffffffd, 28), (0x7ffffe3, 27), (0x7ffffe4, 27), (0x7ffffe5, 27),
    (0xfffec, 20), (0xfffff3, 24), (0xfffed, 20), (0x1fffe6, 21),
    (0x3fffe9, 22), (0x1fffe7, 21), (0x1fffe8, 21), (0x7ffff3, 23),
    (0x3fffea, 22), (0x3fffeb, 22), (0x1ffffee, 25), (0x1ffffef, 25),
    (0xfffff4, 24), (0xfffff5, 24), (0x3ffffea, 26), (0x7ffff4, 23),
    (0x3ffffeb, 26), (0x7ffffe6, 27), (0x3ffffec, 26), (0x3ffffed, 26),
    (0x7ffffe7, 27), (0x7ffffe8, 27), (0x7ffffe9, 27), (0x7ffffea, 27),
    (0x7ffffeb, 27), (0xffffffe, 28), (0x7ffffec, 27), (0x7ffffed, 27),
    (0x7ffffee, 27), (0x7ffffef, 27), (0x7fffff0, 27), (0x3ffffee, 26),
)

_HUFF_DECODE = {(code, bits): sym for sym, (code, bits) in enumerate(_HUFF)}


def huffman_decode(data: bytes) -> bytes:
    """Canonical HPACK Huffman decode (bit-accumulator walk)."""
    out = bytearray()
    acc = 0
    nbits = 0
    for byte in data:
        acc = (acc << 8) | byte
        nbits += 8
        while nbits >= 5:
            matched = False
            # codes are 5..30 bits; try shortest first
            for blen in range(5, min(nbits, 30) + 1):
                code = (acc >> (nbits - blen)) & ((1 << blen) - 1)
                sym = _HUFF_DECODE.get((code, blen))
                if sym is not None:
                    out.append(sym)
                    nbits -= blen
                    acc &= (1 << nbits) - 1
                    matched = True
                    break
            if not matched:
                break
    # trailing bits must be a prefix of EOS (all ones) — tolerated silently
    return bytes(out)


class HpackDecoder:
    """RFC 7541 decoder with a bounded dynamic table."""

    def __init__(self, max_size: int = 4096):
        self._dyn: list[tuple[str, str]] = []
        self._max = max_size

    def _entry(self, idx: int) -> tuple[str, str]:
        if 1 <= idx <= len(STATIC_TABLE):
            return STATIC_TABLE[idx - 1]
        d = idx - len(STATIC_TABLE) - 1
        if 0 <= d < len(self._dyn):
            return self._dyn[d]
        return ("", "")

    @staticmethod
    def _int(data: bytes, i: int, prefix: int) -> tuple[int, int]:
        mask = (1 << prefix) - 1
        v = data[i] & mask
        i += 1
        if v < mask:
            return v, i
        shift = 0
        while i < len(data):
            b = data[i]
            i += 1
            v += (b & 0x7F) << shift
            shift += 7
            if not b & 0x80:
                break
        return v, i

    def _str(self, data: bytes, i: int) -> tuple[str, int]:
        if i >= len(data):
            return "", len(data)
        huff = bool(data[i] & 0x80)
        ln, i = self._int(data, i, 7)
        raw = data[i: i + ln]
        i += ln
        if huff:
            raw = huffman_decode(raw)
        return raw.decode("utf-8", "replace"), i

    def decode(self, block: bytes) -> list[tuple[str, str]]:
        out = []
        i = 0
        while i < len(block):
            b = block[i]
            if b & 0x80:                        # indexed
                idx, i = self._int(block, i, 7)
                out.append(self._entry(idx))
            elif b & 0x40:                      # literal, incremental index
                idx, i = self._int(block, i, 6)
                name = self._entry(idx)[0] if idx else None
                if name is None:
                    name, i = self._str(block, i)
                val, i = self._str(block, i)
                self._dyn.insert(0, (name, val))
                # size accounting: 32-byte overhead per RFC
                while sum(len(n) + len(v) + 32
                          for n, v in self._dyn) > self._max:
                    self._dyn.pop()
                out.append((name, val))
            elif b & 0x20:                      # dynamic table size update
                # clamp: the update rides untrusted captured bytes — a
                # huge value would disable eviction (memory DoS)
                v, i = self._int(block, i, 5)
                self._max = min(v, 65536)
                while sum(len(n) + len(v) + 32
                          for n, v in self._dyn) > self._max:
                    self._dyn.pop()
            else:                               # literal, no/never index
                idx, i = self._int(block, i, 4)
                name = self._entry(idx)[0] if idx else None
                if name is None:
                    name, i = self._str(block, i)
                val, i = self._str(block, i)
                out.append((name, val))
        return out


# ------------------------------------------------------------ transaction
class _Stream(NamedTuple):
    api: str
    tusec: int
    nbytes: int
    is_grpc: bool


class Http2Parser:
    """Per-connection HTTP/2 transaction pairing by stream id.

    ``feed_request`` consumes the client preface then client frames;
    ``feed_response`` consumes server frames. HEADERS(+CONTINUATION)
    blocks decode through per-direction HPACK contexts. A request opens
    at ``:method``/``:path``; a response closes at ``:status`` — except
    for gRPC, where HEADERS without END_STREAM is only the initial
    metadata and the trailers frame (END_STREAM) carries
    ``grpc-status``.
    """

    def __init__(self, max_streams: int = 256):
        self._req = _DirState()
        self._resp = _DirState()
        self._hp_req = HpackDecoder()
        self._hp_resp = HpackDecoder()
        self._open: dict[int, _Stream] = {}
        self._resp_status: dict[int, int] = {}
        self._max_streams = max_streams
        self._preface_seen = False
        self.transactions: list[Transaction] = []

    def feed_request(self, data: bytes, tusec: int) -> None:
        st = self._req
        st.buf += data
        if not self._preface_seen:
            if len(st.buf) < len(_PREFACE):
                if _PREFACE.startswith(st.buf):
                    return
            if st.buf.startswith(_PREFACE):
                st.buf = st.buf[len(_PREFACE):]
            self._preface_seen = True
        for ftype, flags, sid, payload in st.frames():
            self._on_req_frame(ftype, flags, sid, payload, tusec)

    def feed_response(self, data: bytes, tusec: int) -> None:
        st = self._resp
        st.buf += data
        for ftype, flags, sid, payload in st.frames():
            self._on_resp_frame(ftype, flags, sid, payload, tusec)

    # ------------------------------------------------------------- frames
    def _rst(self, sid: int) -> None:
        """RST_STREAM from either side cancels the stream: drop its
        pending state or _open fills with cancelled calls and the
        parser wedges at max_streams."""
        self._open.pop(sid, None)
        self._resp_status.pop(sid, None)

    def _on_req_frame(self, ftype, flags, sid, payload, tusec) -> None:
        if ftype == FRAME_RST_STREAM:
            return self._rst(sid)
        block = self._req.header_block(ftype, flags, sid, payload)
        if block is None:
            return
        sid, fragment, _end_stream = block
        hdrs = dict(self._hp_req.decode(fragment))
        method = hdrs.get(":method", "")
        path = hdrs.get(":path", "")
        if not method or not path:
            return
        is_grpc = hdrs.get("content-type", "").startswith(
            "application/grpc")
        # gRPC paths are exact API names; HTTP paths get templated
        api = (f"{method} {path}"[:128] if is_grpc
               else normalize_http(method.encode(), path.encode()))
        if len(self._open) < self._max_streams:
            self._open[sid] = _Stream(api, tusec, len(fragment), is_grpc)

    def _on_resp_frame(self, ftype, flags, sid, payload, tusec) -> None:
        if ftype == FRAME_RST_STREAM:
            return self._rst(sid)
        block = self._resp.header_block(ftype, flags, sid, payload)
        if block is None:
            return
        sid, fragment, end_stream = block
        hdrs = dict(self._hp_resp.decode(fragment))
        req = self._open.get(sid)
        if req is None:
            return
        status_s = hdrs.get(":status", "")
        status = int(status_s) if status_s.isdigit() else 0
        if req.is_grpc and not end_stream:
            # initial metadata; remember status, wait for trailers
            self._resp_status[sid] = status
            return
        if req.is_grpc:
            status = self._resp_status.pop(sid, status)
            g = hdrs.get("grpc-status", "0")
            is_err = g.isdigit() and int(g) != 0
        else:
            is_err = status >= 500
        self._open.pop(sid, None)
        self.transactions.append(Transaction(
            proto=PROTO_HTTP2, api=req.api, t_req_usec=req.tusec,
            resp_usec=max(0, tusec - req.tusec), status=status,
            is_error=is_err, bytes_in=req.nbytes,
            bytes_out=len(fragment)))

    def drain(self) -> list[Transaction]:
        out, self.transactions = self.transactions, []
        return out


class _DirState:
    """One direction's frame walk + HEADERS/CONTINUATION accumulation."""

    def __init__(self) -> None:
        self.buf = b""
        self._frag_sid: Optional[int] = None
        self._frag = b""
        self._frag_end_stream = False

    def frames(self):
        while len(self.buf) >= 9:
            flen = int.from_bytes(self.buf[:3], "big")
            if flen > 1 << 24:
                self.buf = b""
                return
            if len(self.buf) < 9 + flen:
                return
            ftype = self.buf[3]
            flags = self.buf[4]
            sid = int.from_bytes(self.buf[5:9], "big") & 0x7FFFFFFF
            payload = self.buf[9: 9 + flen]
            self.buf = self.buf[9 + flen:]
            yield ftype, flags, sid, payload

    def header_block(self, ftype, flags, sid, payload):
        """Accumulate HEADERS(+CONTINUATION); return
        (sid, full_fragment, end_stream) at END_HEADERS, else None."""
        if ftype == FRAME_HEADERS:
            if flags & FLAG_PADDED and payload:
                pad = payload[0]
                payload = payload[1: len(payload) - pad]
            if flags & FLAG_PRIORITY:
                payload = payload[5:]
            self._frag_sid = sid
            self._frag = payload
            self._frag_end_stream = bool(flags & 0x1)
        elif ftype == FRAME_CONTINUATION and sid == self._frag_sid:
            self._frag += payload
        else:
            return None
        if flags & FLAG_END_HEADERS:
            out = (self._frag_sid, self._frag, self._frag_end_stream)
            self._frag_sid = None
            self._frag = b""
            return out
        return None
