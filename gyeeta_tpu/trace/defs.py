"""On-demand trace definitions: which services to capture, until when.

The control half of request tracing (§3.6 of the reference): a trace
definition selects listeners by criteria and a time bound; the control
plane distributes enable/disable to the owning agents
(``REQ_TRACE_DEF`` / ``SM_REQ_TRACE_DEF_NEW`` → partha ``REQ_TRACE_SET``,
``common/gy_trace_def.h``, ``gy_comm_proto.h:3295,3377``;
``server/gy_shconnhdlr.cc:1272``). Here the server owns the registry,
re-evaluates matches each tick against live svcinfo/svcstate columns,
and pushes ``COMM_TRACE_SET`` diffs down the agents' event conns.
"""

from __future__ import annotations

import time
from typing import NamedTuple, Optional

import numpy as np

from gyeeta_tpu.query import criteria


class TraceDef(NamedTuple):
    name: str
    filter: Optional[str] = None    # criteria over svcinfo (None = all)
    tend: float = 0.0               # epoch sec; 0 = no expiry

    @classmethod
    def from_json(cls, d: dict) -> "TraceDef":
        if "name" not in d:
            raise ValueError("tracedef needs a name")
        filt = d.get("filter")
        if filt:
            tree = criteria.parse(filt)
            if tree is None:
                raise ValueError("tracedef filter must be non-empty")
        return cls(name=d["name"], filter=filt,
                   tend=float(d.get("tend", 0.0)))


class TraceDefs:
    """Registry + per-host applied-state diffing.

    ``target_svcids(columns_fn)`` evaluates every unexpired def against
    the live svcinfo columns → the set of (svcid, hostid) that should
    be capturing. ``diff_for_hosts`` turns that into per-host
    enable/disable lists relative to what was last pushed."""

    def __init__(self, clock=None):
        self.defs: dict[str, TraceDef] = {}
        self._applied: dict[int, set] = {}      # host → enabled svc ids
        self._trees: dict[str, object] = {}     # name → parsed filter
        self._nsvc: dict[str, int] = {}         # name → last match count
        self._clock = clock or time.time

    def add(self, d: dict | TraceDef) -> TraceDef:
        td = d if isinstance(d, TraceDef) else TraceDef.from_json(d)
        self.defs[td.name] = td
        self._trees[td.name] = (criteria.parse(td.filter)
                                if td.filter else None)
        return td

    def delete(self, name: str) -> bool:
        self._trees.pop(name, None)
        self._nsvc.pop(name, None)
        return self.defs.pop(name, None) is not None

    def _active_defs(self):
        now = self._clock()
        return [d for d in self.defs.values()
                if d.tend <= 0 or now < d.tend]

    def target_svcids(self, columns_fn) -> dict[int, set]:
        """→ {host_id: {svc_glob_id, ...}} that should be capturing.

        ``columns_fn('svcinfo') -> (cols, mask)`` supplies the listener
        inventory (svcid hex + hostid columns)."""
        out: dict[int, set] = {}
        defs = self._active_defs()
        if not defs:
            self._nsvc = {}
            return out
        cols, base = columns_fn("svcinfo")
        if not len(base):
            self._nsvc = {d.name: 0 for d in defs}
            return out
        for d in defs:
            mask = np.asarray(base, bool)
            tree = self._trees.get(d.name)
            if tree is not None:
                mask = mask & criteria.evaluate(tree, cols, "svcinfo")
            idx = np.nonzero(mask)[0]
            self._nsvc[d.name] = len(idx)
            for i in idx:
                hid = int(cols["hostid"][i])
                out.setdefault(hid, set()).add(
                    int(cols["svcid"][i], 16))
        return out

    def diff_for_hosts(self, targets: dict[int, set], hosts=None):
        """→ {host_id: (enable_ids, disable_ids)} vs the applied state;
        updates the applied state. Hosts with no change are absent.

        ``hosts`` restricts the diff to reachable hosts — state for an
        unreachable host must NOT be committed (its diff would be lost;
        the caller resyncs it on reconnect via ``forget_host``)."""
        out = {}
        cand = set(targets) | set(self._applied)
        if hosts is not None:
            cand &= set(hosts)
        for hid in cand:
            want = targets.get(hid, set())
            have = self._applied.get(hid, set())
            en = sorted(want - have)
            dis = sorted(have - want)
            if en or dis:
                out[hid] = (en, dis)
            if want:
                self._applied[hid] = want
            else:
                self._applied.pop(hid, None)
        return out

    def forget_host(self, host_id: int) -> None:
        """Reconnect resync: drop applied state so the next diff
        re-pushes everything (agents lose capture state on restart)."""
        self._applied.pop(host_id, None)

    def unapply(self, host_id: int, enable, disable) -> None:
        """Reverse a committed diff after its push FAILED: the agent
        never saw it, so its state is still the pre-diff one. Restoring
        that (applied − enables + disables) makes the next tick re-emit
        the same diff — including disables, which ``forget_host`` alone
        can never re-send (a host absent from both targets and applied
        produces no diff at all)."""
        have = (self._applied.get(host_id, set())
                - set(enable)) | set(disable)
        if have:
            self._applied[host_id] = have
        else:
            self._applied.pop(host_id, None)

    def columns(self):
        """(cols, mask) for the tracedef/tracestatus subsystems —
        shared by both runtimes so the column set cannot diverge."""
        rows = self.status_rows()

        def obj(k):
            out = np.empty(len(rows), object)
            out[:] = [r[k] for r in rows]
            return out

        cols = {"name": obj("name"), "filter": obj("filter"),
                "tend": np.array([float(r["tend"]) for r in rows]),
                "active": np.array([r["active"] for r in rows], bool),
                "nsvc": np.array([float(r["nsvc"]) for r in rows])}
        return cols, np.ones(len(rows), bool)

    def status_rows(self) -> list[dict]:
        now = self._clock()
        rows = []
        for d in sorted(self.defs.values(), key=lambda x: x.name):
            active = d.tend <= 0 or now < d.tend
            rows.append({"name": d.name, "filter": d.filter or "",
                         "tend": min(d.tend, 1e18), "active": active,
                         # per-def match count from the last evaluation
                         "nsvc": self._nsvc.get(d.name, 0)
                         if active else 0})
        return rows
