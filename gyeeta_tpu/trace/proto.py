"""Application-protocol transaction parsers (HTTP/1, Postgres) + detector.

The agent-side half of request tracing: raw captured byte streams in
both directions of a connection → :class:`Transaction` records (api
signature, latency, status, bytes). Mirrors what the reference's
``API_PARSE_HDLR`` does per connection (``common/gy_proto_parser.h``:
protocol detection from the first payload bytes, stream reassembly with
partial-buffer resume, request/response pairing; HTTP/1 parser
``common/gy_http_proto.cc``, Postgres parser ``common/gy_postgres_proto.h``)
— rewritten as small incremental state machines, not a port.

API signature normalization collapses per-call variability so traffic
aggregates by *shape*:

- HTTP: ``GET /users/1234/orders?x=1`` → ``GET /users/{}/orders``
  (numeric / UUID / hex / long segments templated, query string dropped);
- SQL: literals and numbers are replaced by placeholders, whitespace
  collapsed, identifier case preserved: ``SELECT * FROM t WHERE id=42``
  → ``SELECT * FROM t WHERE id=$``.

Signatures travel as interned 64-bit ids (``utils.hashing.hash_bytes_np``)
with a NAME_INTERN announcement, like every other string.
"""

from __future__ import annotations

import re
from typing import NamedTuple, Optional

PROTO_UNKNOWN = 0
PROTO_HTTP1 = 1
PROTO_POSTGRES = 2
PROTO_MONGO = 3
PROTO_HTTP2 = 4
PROTO_TLS = 5
PROTO_SYBASE = 6
PROTO_NAMES = ("unknown", "http1", "postgres", "mongo", "http2", "tls",
               "sybase")

_HTTP_METHODS = (b"GET ", b"POST ", b"PUT ", b"DELETE ", b"HEAD ",
                 b"OPTIONS ", b"PATCH ", b"TRACE ", b"CONNECT ")
_H2_PREFACE = b"PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n"
_MONGO_OPS = (2013, 2004, 2010, 2011, 1, 2001, 2002, 2005, 2006, 2007, 2012)


class Transaction(NamedTuple):
    """One parsed request/response exchange."""
    proto: int
    api: str              # normalized signature
    t_req_usec: int       # request first-byte time
    resp_usec: int        # response latency
    status: int           # HTTP status / 0 ok / 1 error for PG
    is_error: bool
    bytes_in: int         # request bytes
    bytes_out: int        # response bytes


def detect_protocol(first_bytes: bytes) -> int:
    """Classify a connection from its first client payload bytes (the
    reference sniffs the same way before attaching a parser,
    ``common/gy_proto_parser.h`` PROTO_DETECT; TLS record sniff
    ``common/gy_tls_proto.h``)."""
    if first_bytes.startswith(_H2_PREFACE[: max(4, len(first_bytes))]) and \
            len(first_bytes) >= 4:
        return PROTO_HTTP2
    if any(first_bytes.startswith(m) for m in _HTTP_METHODS):
        return PROTO_HTTP1
    if len(first_bytes) >= 5 and first_bytes[0] == 0x16 and \
            first_bytes[1] == 0x03 and first_bytes[2] <= 0x04:
        return PROTO_TLS
    if len(first_bytes) >= 8:
        # PG startup: int32 length, int32 protocol (196608 = 3.0) or
        # SSLRequest code 80877103
        ln = int.from_bytes(first_bytes[:4], "big")
        code = int.from_bytes(first_bytes[4:8], "big")
        if 8 <= ln <= 10000 and code in (196608, 80877103, 80877102):
            return PROTO_POSTGRES
    if len(first_bytes) >= 16:
        # Mongo header: msglen, requestID, responseTo, opcode — all LE
        ln = int.from_bytes(first_bytes[:4], "little")
        op = int.from_bytes(first_bytes[12:16], "little")
        if 16 <= ln <= 48_000_000 and op in _MONGO_OPS:
            return PROTO_MONGO
    if len(first_bytes) >= 8:
        # TDS: a conn opens with a LOGIN (0x02) buffer — 8-byte packet
        # header with a sane big-endian length (gy_sybase_proto.h:20)
        ptype, status = first_bytes[0], first_bytes[1]
        ln = (first_bytes[2] << 8) | first_bytes[3]
        if ptype == 0x02 and status in (0x00, 0x01) and 8 <= ln <= 4096:
            return PROTO_SYBASE
    return PROTO_UNKNOWN


# ----------------------------------------------------------- normalization
_NUMSEG = re.compile(
    rb"^(\d+|[0-9a-fA-F]{8}-[0-9a-fA-F]{4}-[0-9a-fA-F]{4}-"
    rb"[0-9a-fA-F]{4}-[0-9a-fA-F]{12}|[0-9a-fA-F]{16,})$")


def normalize_http(method: bytes, path: bytes, max_len: int = 128) -> str:
    """Route template: drop query string, template variable segments."""
    path = path.split(b"?", 1)[0].split(b"#", 1)[0]
    segs = path.split(b"/")
    out = []
    for s in segs:
        out.append(b"{}" if s and _NUMSEG.match(s) else s)
    norm = b"/".join(out) or b"/"
    sig = method.decode("latin1") + " " + norm.decode("latin1")
    return sig[:max_len]


_SQL_STR = re.compile(rb"'(?:[^']|'')*'")
_SQL_NUM = re.compile(rb"\b\d+(?:\.\d+)?\b")
_SQL_WS = re.compile(rb"\s+")


def normalize_sql(sql: bytes, max_len: int = 128) -> str:
    """SQL shape: literals → ``$``, numbers → ``$``, whitespace folded."""
    s = _SQL_STR.sub(b"$", sql)
    s = _SQL_NUM.sub(b"$", s)
    s = _SQL_WS.sub(b" ", s).strip()
    return s.decode("latin1", "replace")[:max_len]


# ------------------------------------------------------------------ HTTP/1
class _Req(NamedTuple):
    api: str
    tusec: int
    nbytes: int


class HttpParser:
    """Incremental HTTP/1.x request/response pairing for one connection.

    ``feed_request(data, tusec)`` / ``feed_response(data, tusec)`` accept
    arbitrary chunk boundaries (partial-buffer resume). Pipelined
    requests queue FIFO; each response head closes the oldest request
    (HTTP/1.1 ordering guarantee). Bodies are skipped by Content-Length;
    chunked bodies are scanned to the terminating 0-chunk.
    """

    def __init__(self, max_queue: int = 64):
        self._req_buf = b""
        self._resp_buf = b""
        self._pending: list[_Req] = []
        self._max_queue = max_queue
        self.transactions: list[Transaction] = []
        # body-skip state per direction: remaining bytes, or chunked flag
        self._req_skip = 0
        self._resp_skip = 0
        self._req_chunked = False
        self._resp_chunked = False

    # -------------------------------------------------------------- feed
    def feed_request(self, data: bytes, tusec: int) -> None:
        self._req_buf += data
        while True:
            if self._req_skip or self._req_chunked:
                if not self._skip_body("req"):
                    return
            head = self._take_head("req")
            if head is None:
                return
            line = head.split(b"\r\n", 1)[0]
            parts = line.split(b" ")
            if len(parts) >= 2 and (parts[0] + b" ") in _HTTP_METHODS:
                api = normalize_http(parts[0], parts[1])
                if len(self._pending) < self._max_queue:
                    self._pending.append(_Req(api, tusec, len(head)))
            self._arm_body_skip("req", head)

    def feed_response(self, data: bytes, tusec: int) -> None:
        self._resp_buf += data
        while True:
            if self._resp_skip or self._resp_chunked:
                if not self._skip_body("resp"):
                    return
            head = self._take_head("resp")
            if head is None:
                return
            line = head.split(b"\r\n", 1)[0]
            status = 0
            if line.startswith(b"HTTP/"):
                parts = line.split(b" ")
                if len(parts) >= 2 and parts[1].isdigit():
                    status = int(parts[1])
            if self._pending:
                req = self._pending.pop(0)
                self.transactions.append(Transaction(
                    proto=PROTO_HTTP1, api=req.api, t_req_usec=req.tusec,
                    resp_usec=max(0, tusec - req.tusec), status=status,
                    is_error=status >= 500, bytes_in=req.nbytes,
                    bytes_out=len(head)))
            self._arm_body_skip("resp", head)

    # ----------------------------------------------------------- plumbing
    def _buf(self, d):
        return self._req_buf if d == "req" else self._resp_buf

    def _setbuf(self, d, v):
        if d == "req":
            self._req_buf = v
        else:
            self._resp_buf = v

    def _take_head(self, d) -> Optional[bytes]:
        buf = self._buf(d)
        i = buf.find(b"\r\n\r\n")
        if i < 0:
            if len(buf) > 64 * 1024:      # runaway head: drop (resync)
                self._setbuf(d, b"")
            return None
        head, rest = buf[: i + 4], buf[i + 4:]
        self._setbuf(d, rest)
        return head

    def _arm_body_skip(self, d, head: bytes) -> None:
        h = head.lower()
        n = 0
        chunked = b"transfer-encoding: chunked" in h
        i = h.find(b"content-length:")
        if i >= 0:
            j = h.find(b"\r\n", i)
            try:
                n = int(h[i + 15: j].strip())
            except ValueError:
                n = 0
        if d == "req":
            self._req_skip, self._req_chunked = n, chunked
        else:
            self._resp_skip, self._resp_chunked = n, chunked

    def _skip_body(self, d) -> bool:
        """Consume body bytes; True once the body is fully skipped."""
        buf = self._buf(d)
        if d == "req":
            skip, chunked = self._req_skip, self._req_chunked
        else:
            skip, chunked = self._resp_skip, self._resp_chunked
        if not chunked:
            take = min(skip, len(buf))
            self._setbuf(d, buf[take:])
            skip -= take
            if d == "req":
                self._req_skip = skip
            else:
                self._resp_skip = skip
            return skip == 0
        # chunked: walk size lines until the 0 chunk
        while True:
            i = buf.find(b"\r\n")
            if i < 0:
                self._setbuf(d, buf)
                return False
            try:
                sz = int(buf[:i].split(b";")[0], 16)
            except ValueError:
                sz = 0
            need = i + 2 + sz + 2
            if len(buf) < need:
                self._setbuf(d, buf)
                return False
            buf = buf[need:]
            if sz == 0:
                self._setbuf(d, buf)
                if d == "req":
                    self._req_chunked = False
                else:
                    self._resp_chunked = False
                return True

    def drain(self) -> list[Transaction]:
        out, self.transactions = self.transactions, []
        return out


def transactions_to_records(txns, svc_glob_id: int, host_id: int):
    """Transactions → (REQ_TRACE record array, NAME_INTERN records).

    The agent-side encoding step: api signatures intern to 64-bit ids
    (announced once) and the fixed-width trace records carry only ids.
    """
    import numpy as np

    from gyeeta_tpu.ingest import wire
    from gyeeta_tpu.utils import hashing as H
    from gyeeta_tpu.utils.intern import InternTable

    recs = np.zeros(len(txns), wire.REQ_TRACE_DT)
    names = {}
    for i, t in enumerate(txns):
        api_id = H.hash_bytes_np(t.api.encode())
        names[api_id] = t.api
        recs[i]["svc_glob_id"] = svc_glob_id
        recs[i]["api_id"] = api_id
        recs[i]["tusec"] = t.t_req_usec
        recs[i]["resp_usec"] = min(t.resp_usec, 0xFFFFFFFF)
        recs[i]["bytes_in"] = min(t.bytes_in, 0xFFFFFFFF)
        recs[i]["bytes_out"] = min(t.bytes_out, 0xFFFFFFFF)
        recs[i]["status"] = t.status
        recs[i]["proto"] = t.proto
        recs[i]["is_error"] = t.is_error
        recs[i]["host_id"] = host_id
    name_recs = InternTable.records(
        [(wire.NAME_KIND_API, nid, s) for nid, s in names.items()])
    return recs, name_recs


# ---------------------------------------------------------------- Postgres
class PostgresParser:
    """Postgres wire-protocol transaction pairing for one connection.

    Requests: simple queries (``Q``) and extended-protocol ``P``arse
    messages (the statement text rides in both). A transaction closes at
    ReadyForQuery (``Z``) on the server side; ``E`` marks it errored.
    The startup packet (no type byte) is consumed first.
    """

    def __init__(self, max_queue: int = 64):
        self._req_buf = b""
        self._resp_buf = b""
        self._started = False
        self._pending: list[_Req] = []
        self._max_queue = max_queue
        self._err = False
        self._resp_bytes = 0
        self.transactions: list[Transaction] = []

    def feed_request(self, data: bytes, tusec: int) -> None:
        self._req_buf += data
        if not self._started:
            if len(self._req_buf) < 4:
                return
            ln = int.from_bytes(self._req_buf[:4], "big")
            if len(self._req_buf) < ln:
                return
            self._req_buf = self._req_buf[ln:]
            self._started = True
        while len(self._req_buf) >= 5:
            typ = self._req_buf[0:1]
            ln = int.from_bytes(self._req_buf[1:5], "big")
            if len(self._req_buf) < 1 + ln:
                return
            body = self._req_buf[5: 1 + ln]
            self._req_buf = self._req_buf[1 + ln:]
            if typ == b"Q":
                sql = body.rstrip(b"\x00")
                self._queue(normalize_sql(sql), tusec, 1 + ln)
            elif typ == b"P":
                # Parse: statement name \0 query \0 ...
                parts = body.split(b"\x00", 2)
                if len(parts) >= 2:
                    self._queue(normalize_sql(parts[1]), tusec, 1 + ln)

    def _queue(self, api: str, tusec: int, nbytes: int) -> None:
        if len(self._pending) < self._max_queue:
            self._pending.append(_Req(api, tusec, nbytes))

    def feed_response(self, data: bytes, tusec: int) -> None:
        self._resp_buf += data
        while len(self._resp_buf) >= 5:
            typ = self._resp_buf[0:1]
            ln = int.from_bytes(self._resp_buf[1:5], "big")
            if len(self._resp_buf) < 1 + ln:
                return
            self._resp_buf = self._resp_buf[1 + ln:]
            self._resp_bytes += 1 + ln
            if typ == b"E":
                self._err = True
            elif typ == b"Z":
                if self._pending:
                    req = self._pending.pop(0)
                    self.transactions.append(Transaction(
                        proto=PROTO_POSTGRES, api=req.api,
                        t_req_usec=req.tusec,
                        resp_usec=max(0, tusec - req.tusec),
                        status=1 if self._err else 0,
                        is_error=self._err, bytes_in=req.nbytes,
                        bytes_out=self._resp_bytes))
                self._err = False
                self._resp_bytes = 0

    def drain(self) -> list[Transaction]:
        out, self.transactions = self.transactions, []
        return out
