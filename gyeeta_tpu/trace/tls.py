"""TLS record / ClientHello parse — SNI + ALPN extraction.

The analogue of the reference's ``common/gy_tls_proto.h``: TLS traffic
can't be transaction-parsed without the SSL-capture path, but the
*handshake* is cleartext and carries two things the product uses:

- **SNI** (server_name extension): which domain the client thinks it is
  talking to — feeds the service-domain annotation the reference gets
  from ``LISTENER_DOMAIN_NOTIFY`` (``common/gy_comm_proto.h:2724``);
- **ALPN**: the application protocol (``h2``, ``http/1.1``) — feeds
  protocol detection for when decrypted payload becomes available.

``TlsParser`` fits the same feed/drain shape as the other parsers but
emits :class:`TlsInfo` (not transactions): encrypted conns surface as
connection metadata, not API calls.
"""

from __future__ import annotations

import struct
from typing import NamedTuple, Optional

REC_HANDSHAKE = 0x16
HS_CLIENT_HELLO = 0x01
EXT_SNI = 0
EXT_ALPN = 16


class TlsInfo(NamedTuple):
    sni: str          # server_name, "" if absent
    alpn: str         # first ALPN protocol offered, "" if absent
    version: int      # legacy_version from the hello (0x0303 = TLS1.2+)


def parse_client_hello(data: bytes) -> Optional[TlsInfo]:
    """Parse a ClientHello from the start of a client byte stream.

    Tolerates the hello spanning multiple TLS records (reassembles
    handshake bytes across records) and truncated input (returns None —
    callers retry with more bytes).
    """
    # 1. concatenate handshake-record payloads
    hs = bytearray()
    i = 0
    while i + 5 <= len(data) and data[i] == REC_HANDSHAKE:
        rlen = struct.unpack_from(">H", data, i + 3)[0]
        hs += data[i + 5: i + 5 + rlen]
        i += 5 + rlen
        if len(hs) >= 4:
            need = 4 + int.from_bytes(hs[1:4], "big")
            if len(hs) >= need:
                break
    if len(hs) < 4 or hs[0] != HS_CLIENT_HELLO:
        return None
    body_len = int.from_bytes(hs[1:4], "big")
    if len(hs) < 4 + body_len:
        return None
    b = bytes(hs[4: 4 + body_len])
    # 2. fixed fields: version(2) random(32) session_id ciphers compression
    if len(b) < 35:
        return None
    version = struct.unpack_from(">H", b, 0)[0]
    p = 34
    sid_len = b[p]
    p += 1 + sid_len
    if p + 2 > len(b):
        return None
    cs_len = struct.unpack_from(">H", b, p)[0]
    p += 2 + cs_len
    if p + 1 > len(b):
        return None
    comp_len = b[p]
    p += 1 + comp_len
    sni = alpn = ""
    if p + 2 <= len(b):
        ext_total = struct.unpack_from(">H", b, p)[0]
        p += 2
        end = min(p + ext_total, len(b))
        while p + 4 <= end:
            etype, elen = struct.unpack_from(">HH", b, p)
            p += 4
            ebody = b[p: p + elen]
            p += elen
            if etype == EXT_SNI and len(ebody) >= 5:
                # list_len(2) type(1)=host_name name_len(2) name
                nlen = struct.unpack_from(">H", ebody, 3)[0]
                sni = ebody[5: 5 + nlen].decode("ascii", "replace")
            elif etype == EXT_ALPN and len(ebody) >= 3:
                # list_len(2) then (len(1) proto)*
                plen = ebody[2]
                alpn = ebody[3: 3 + plen].decode("ascii", "replace")
    return TlsInfo(sni=sni, alpn=alpn, version=version)


class TlsParser:
    """feed/drain-shaped wrapper: buffers client bytes until the
    ClientHello parses (or 16KB passes — then gives up)."""

    def __init__(self) -> None:
        self._buf = b""
        self._done = False
        self.info: Optional[TlsInfo] = None

    def feed_request(self, data: bytes, tusec: int) -> None:
        if self._done:
            return
        self._buf += data
        info = parse_client_hello(self._buf)
        if info is not None:
            self.info = info
            self._done = True
            self._buf = b""
        elif len(self._buf) > 16 * 1024:
            self._done = True
            self._buf = b""

    def feed_response(self, data: bytes, tusec: int) -> None:
        pass

    def drain(self) -> list:
        return []
