"""pcap-file ingestion: captured packets → protocol parsers → traces.

The reference carries a pcap engine (``common/gy_pcap_read.h`` /
``gy_pkt_pool``-fed parsers) so captured traffic can drive the same
protocol analysis as live capture. Userspace here can't sniff, but it
CAN ingest capture FILES: this module reads classic libpcap files
(the 24-byte global header, ``a1b2c3d4`` magics, Ethernet/Linux-SLL +
IPv4/IPv6 + TCP), reassembles each TCP flow's two directions in
sequence order, classifies the application protocol from the client's
first bytes, and replays the conversation through the SAME incremental
parsers live tracing uses (``PARSER_OF_PROTO``) — one
:class:`~gyeeta_tpu.trace.proto.Transaction` list per service flow,
ready for ``transactions_to_records`` → ``Runtime.feed``.

Deliberately a TRACER, not a TCP stack: segments are ordered by
sequence number with duplicate-offset trimming (retransmits), no
window/SACK emulation — capture files of sane conversations are the
use case (the reference's parser-side reassembly makes the same
simplification, ``common/gy_proto_parser.h`` reassembly notes).
"""

from __future__ import annotations

import struct
from typing import NamedTuple, Optional

from gyeeta_tpu.trace import PARSER_OF_PROTO, detect_protocol

_MAGIC_USEC = 0xA1B2C3D4
_MAGIC_NSEC = 0xA1B23C4D

_LINK_ETH = 1
_LINK_SLL = 113
_LINK_RAW = 101


class PcapError(ValueError):
    pass


class _Seg(NamedTuple):
    seq: int
    tusec: int
    payload: bytes


def _read_global_header(buf: bytes):
    """→ (endian, nsec, linktype, offset)."""
    if len(buf) < 24:
        raise PcapError("truncated pcap global header")
    magic = struct.unpack_from("<I", buf, 0)[0]
    if magic in (_MAGIC_USEC, _MAGIC_NSEC):
        endian = "<"
    else:
        magic = struct.unpack_from(">I", buf, 0)[0]
        if magic not in (_MAGIC_USEC, _MAGIC_NSEC):
            raise PcapError("not a classic pcap file (bad magic)")
        endian = ">"
    nsec = magic == _MAGIC_NSEC
    linktype = struct.unpack_from(endian + "I", buf, 20)[0]
    return endian, nsec, linktype, 24


def _l3_offset(linktype: int, frame: bytes) -> Optional[int]:
    """Link header length (and VLAN skip) → IP header offset."""
    if linktype == _LINK_RAW:
        return 0
    if linktype == _LINK_ETH:
        if len(frame) < 14:
            return None
        etype = (frame[12] << 8) | frame[13]
        off = 14
        while etype in (0x8100, 0x88A8):       # VLAN tag(s)
            if len(frame) < off + 4:
                return None
            etype = (frame[off + 2] << 8) | frame[off + 3]
            off += 4
        return off if etype in (0x0800, 0x86DD) else None
    if linktype == _LINK_SLL:
        if len(frame) < 16:
            return None
        etype = (frame[14] << 8) | frame[15]
        return 16 if etype in (0x0800, 0x86DD) else None
    return None


def _parse_ip_tcp(pkt: bytes):
    """IP(v4/v6)+TCP headers → (src, sport, dst, dport, seq, flags,
    payload) or None for non-TCP/fragments."""
    if not pkt:
        return None
    ver = pkt[0] >> 4
    if ver == 4:
        if len(pkt) < 20:
            return None
        ihl = (pkt[0] & 0xF) * 4
        if ihl < 20 or len(pkt) < ihl:          # corrupt header length
            return None
        if pkt[9] != 6:                         # not TCP
            return None
        frag = struct.unpack_from(">H", pkt, 6)[0] & 0x1FFF
        if frag:
            return None                         # non-first fragment
        tot = struct.unpack_from(">H", pkt, 2)[0]
        src, dst = pkt[12:16], pkt[16:20]
        tcp = pkt[ihl:tot] if tot >= ihl else pkt[ihl:]
    elif ver == 6:
        if len(pkt) < 40 or pkt[6] != 6:        # next-header TCP only
            return None
        plen = struct.unpack_from(">H", pkt, 4)[0]
        src, dst = pkt[8:24], pkt[24:40]
        tcp = pkt[40:40 + plen]
    else:
        return None
    if len(tcp) < 20:
        return None
    sport, dport = struct.unpack_from(">HH", tcp, 0)
    seq = struct.unpack_from(">I", tcp, 4)[0]
    doff = (tcp[12] >> 4) * 4
    flags = tcp[13]
    return src, sport, dst, dport, seq, flags, tcp[doff:]


def _trimmed_segments(segs: list) -> list:
    """Sequence-ordered ``(tusec, chunk)`` stream with duplicate-range
    trimming (retransmits keep the first copy; capture gaps skip —
    the incremental parsers resync).

    WRAP-AWARE: the base is the first-CAPTURED segment's seq and every
    position is the 32-bit modular distance from it, so flows whose
    sequence space crosses 2^32 reassemble; anything farther than 2^30
    from base (pre-base retransmits, garbage) is dropped."""
    if not segs:
        return []
    # unwrap around the first-CAPTURED seq: signed 32-bit distance
    # handles both pre-reference reordering and a 2^32 wrap mid-flow
    ref = min(segs, key=lambda s: s.tusec).seq
    off = []
    for s in segs:
        d = (s.seq - ref) & 0xFFFFFFFF
        if d >= 1 << 31:
            d -= 1 << 32
        if abs(d) <= (1 << 30):
            off.append((d, s))
    if not off:
        return []
    base = min(d for d, _ in off)
    rel_segs = sorted(((d - base, s) for d, s in off),
                      key=lambda rs: rs[0])
    got = 0
    out = []
    for rel, s in rel_segs:
        chunk = s.payload[got - rel:] if rel < got else s.payload
        if chunk:
            out.append((s.tusec, chunk))
            got = max(got, rel + len(s.payload))
    return out


def _head(segs: list, want: int = 64) -> bytes:
    """First ``want`` stream bytes for protocol detection — accumulated
    across however many (possibly tiny) segments it takes."""
    out = b""
    for _, c in segs:
        out += c
        if len(out) >= want:
            break
    return out[:want]


def _monotonized(kind: str, segs: list) -> list:
    """[(eff_tusec, kind, chunk)] with per-direction non-decreasing
    timestamps (so a stable time-merge preserves sequence order)."""
    out = []
    t_eff = 0
    for t, c in segs:
        t_eff = max(t_eff, t)
        out.append((t_eff, kind, c))
    return out


class FlowConversation(NamedTuple):
    cli: tuple                # (addr_bytes, port)
    ser: tuple
    proto: int
    transactions: list
    pending: bool = False     # parser still holds an unanswered
    #                           request / partial buffers (the flow's
    #                           conversation spans past this capture)


def write_pcap(frames, nsec: bool = False, linktype: int = _LINK_ETH
               ) -> bytes:
    """(tusec, frame_bytes) iterable → classic little-endian pcap
    bytes (the capture round-trip, ref ``common/gy_pcap_write.cc:221``
    — here for recording live captures into replayable fixtures).
    ``parse_pcap(write_pcap(f))`` sees exactly the written frames."""
    magic = _MAGIC_NSEC if nsec else _MAGIC_USEC
    out = [struct.pack("<IHHiIII", magic, 2, 4, 0, 0, 262144, linktype)]
    mul = 1000 if nsec else 1
    for tusec, frame in frames:
        frac = (tusec % 1_000_000) * mul
        out.append(struct.pack("<IIII", tusec // 1_000_000, frac,
                               len(frame), len(frame)))
        out.append(frame)
    return b"".join(out)


def parse_pcap(buf: bytes, max_flows: int = 4096,
               include_pending: bool = False) -> list:
    """pcap bytes → [FlowConversation] (one per TCP flow with data).

    Direction: the SYN sender is the client; SYN-less flows (capture
    started mid-conversation) fall back to "lower endpoint dialed
    higher port" and protocol detection disambiguates.
    ``include_pending`` also returns transaction-less flows whose
    parser holds an unanswered request (live-capture windows retain
    their frames so boundary-spanning transactions complete later).
    """
    endian, nsec, linktype, off = _read_global_header(buf)
    div = 1000 if nsec else 1
    flows: dict = {}          # key(frozenset ends) -> {end: [segs]}
    syn_from: dict = {}
    n = len(buf)
    while off + 16 <= n:
        ts_s, ts_f, incl, _orig = struct.unpack_from(
            endian + "IIII", buf, off)
        off += 16
        if incl > n - off:
            break                               # truncated tail
        frame = buf[off: off + incl]
        off += incl
        l3 = _l3_offset(linktype, frame)
        if l3 is None:
            continue
        parsed = _parse_ip_tcp(frame[l3:])
        if parsed is None:
            continue
        src, sport, dst, dport, seq, flags, payload = parsed
        a, b = (src, sport), (dst, dport)
        key = (a, b) if a <= b else (b, a)
        st = flows.get(key)
        if st is None:
            if len(flows) >= max_flows:
                continue
            st = flows[key] = {a: [], b: []}
        if flags & 0x02 and not flags & 0x10:   # SYN (no ACK)
            syn_from[key] = a
        if payload:
            tusec = ts_s * 1_000_000 + ts_f // div
            st[a].append(_Seg(seq, tusec, payload))
    out = []
    for key, st in flows.items():
        ends = list(st)
        cli = syn_from.get(key)
        if cli is None:
            # mid-capture: guess by port (server = lower port), fixed
            # below by protocol detection if the guess is backwards
            cli = max(ends, key=lambda e: e[1])
        ser = ends[0] if ends[1] == cli else ends[1]
        req_segs = _trimmed_segments(st[cli])
        resp_segs = _trimmed_segments(st[ser])
        if not req_segs and not resp_segs:
            continue
        proto = detect_protocol(_head(req_segs))
        if proto == 0 and resp_segs:
            # the SYN-less direction guess may be backwards
            flipped = detect_protocol(_head(resp_segs))
            if flipped != 0:
                cli, ser = ser, cli
                req_segs, resp_segs = resp_segs, req_segs
                proto = flipped
        cls = PARSER_OF_PROTO.get(proto)
        if cls is None:
            continue
        parser = cls()
        # interleave the two directions by capture time, but NEVER let
        # the time merge undo per-direction sequence order: each
        # direction's timestamps are monotonized first (a reordered
        # network delivery keeps its seq position; sort is stable)
        events = sorted(_monotonized("req", req_segs)
                        + _monotonized("resp", resp_segs),
                        key=lambda e: e[0])
        for tusec, kind, chunk in events:
            if kind == "req":
                parser.feed_request(chunk, tusec)
            else:
                parser.feed_response(chunk, tusec)
        txns = parser.drain()
        pending = bool(getattr(parser, "_pending", ()))
        if txns or (include_pending and pending):
            out.append(FlowConversation(cli=cli, ser=ser, proto=proto,
                                        transactions=txns,
                                        pending=pending))
    return out
