"""Sybase/TDS 5.0 wire parser: packets → tokens → transactions.

The reference's largest protocol parser (``common/gy_sybase_proto.cc``,
5299 LoC; token/type enums ``gy_sybase_proto.h:20-100``) covers Sybase
ASE's TDS 5.0 with full row-format tracking. This implementation keeps
the same OBSERVABLE behavior — request signatures (language SQL, RPC
names, dynamic statements), request/response pairing, latency, error
detection, byte counts — with a fraction of the machinery:

- **packet layer**: every TDS buffer is 8-byte-headed (type, status,
  length BE incl. header, spid, packet#, window); a logical message is
  packets up to EOM (status bit 0x01). Arbitrary chunk boundaries
  resume (same discipline as every parser here).
- **requests**: LANG batches (type 1) carry raw SQL; NORMAL buffers
  (type 15) carry LANGUAGE (0x21) / DBRPC (0xE6/0xE8) / DYNAMIC
  (0xE7/0x62) tokens; RPC buffers (type 3) carry the proc name.
  Signatures normalize through :func:`normalize_sql` like Postgres.
- **responses** (type 4): one logical message answers one request and
  ENDS with a final DONE/DONEPROC (9 bytes: token, status u16le,
  transtate u16le, count u32le) whose MORE bit (0x0001) is clear.
  Mid-stream row/format tokens need column-state to walk precisely;
  like the reference's resync scan (``gy_sybase_proto.cc:294,412``)
  errors are detected by validated EED (0xE5) / ERROR (0xAA) token
  scans plus the DONE error bit (0x0002) — the row payloads
  themselves are opaque to the tracer.
"""

from __future__ import annotations

from typing import NamedTuple

from gyeeta_tpu.trace.proto import (PROTO_SYBASE, Transaction, _Req,
                                    normalize_sql)

# packet types (gy_sybase_proto.h:20)
TYPE_LANG = 1
TYPE_LOGIN = 2
TYPE_RPC = 3
TYPE_RESPONSE = 4
TYPE_ATTN = 6
TYPE_NORMAL = 15

# tokens (gy_sybase_proto.h:42)
TOK_LANGUAGE = 0x21
TOK_DBRPC = 0xE6
TOK_DBRPC2 = 0xE8
TOK_DYNAMIC = 0xE7
TOK_DYNAMIC2 = 0x62
TOK_EED = 0xE5
TOK_ERROR = 0xAA
TOK_DONE = 0xFD
TOK_DONEPROC = 0xFE
TOK_DONEINPROC = 0xFF

_EOM = 0x01                  # packet status: last packet of message
DONE_MORE = 0x0001
DONE_ERROR = 0x0002

_HDR = 8


class _Msg(NamedTuple):
    ptype: int
    body: bytes


def _le16(b: bytes, off: int) -> int:
    return b[off] | (b[off + 1] << 8)


def _le32(b: bytes, off: int) -> int:
    return (b[off] | (b[off + 1] << 8) | (b[off + 2] << 16)
            | (b[off + 3] << 24))


class _PacketAssembler:
    """8-byte-header packet stream → complete logical messages."""

    def __init__(self, max_msg: int = 1 << 20):
        self._buf = b""
        self._msg = b""
        self._msg_type = -1
        self._max_msg = max_msg

    def feed(self, data: bytes) -> list:
        out: list[_Msg] = []
        self._buf += data
        while len(self._buf) >= _HDR:
            ptype, status = self._buf[0], self._buf[1]
            ln = (self._buf[2] << 8) | self._buf[3]    # big-endian
            if not 1 <= ptype <= 17 or ln < _HDR:
                # implausible header: slide one byte and rescan (the
                # reference's parser resyncs the same way on framing
                # loss, gy_sybase_proto.cc:294)
                self._buf = self._buf[1:]
                self._msg = b""
                self._msg_type = -1
                continue
            if len(self._buf) < ln:
                break
            body = self._buf[_HDR:ln]
            self._buf = self._buf[ln:]
            if self._msg_type < 0:
                self._msg_type = ptype
            if len(self._msg) + len(body) <= self._max_msg:
                self._msg += body
            if status & _EOM:
                out.append(_Msg(self._msg_type, self._msg))
                self._msg = b""
                self._msg_type = -1
        return out


def _req_signature(ptype: int, body: bytes) -> str | None:
    """One request message → normalized API signature (None = not a
    client command: logins, attentions, cancels)."""
    if ptype == TYPE_LANG:
        return normalize_sql(body)
    if ptype == TYPE_RPC:
        if not body:
            return None
        nlen = body[0]
        name = body[1:1 + nlen].decode("latin1", "replace")
        return f"EXEC {name}" if name else None
    if ptype != TYPE_NORMAL:
        return None
    off = 0
    while off < len(body):
        tok = body[off]
        if tok == TOK_LANGUAGE:
            if off + 5 > len(body):
                return None
            ln = _le32(body, off + 1)
            # u32 length covers 1 status byte + text
            text = body[off + 6: off + 5 + ln]
            return normalize_sql(text)
        if tok in (TOK_DBRPC, TOK_DBRPC2):
            if off + 3 > len(body):
                return None
            ln = _le16(body, off + 1)
            seg = body[off + 3: off + 3 + ln]
            if not seg:
                return None
            nlen = seg[0]
            name = seg[1:1 + nlen].decode("latin1", "replace")
            return f"EXEC {name}" if name else None
        if tok in (TOK_DYNAMIC, TOK_DYNAMIC2):
            wide = tok == TOK_DYNAMIC2
            lsz = 4 if wide else 2
            if off + 1 + lsz > len(body):
                return None
            ln = _le32(body, off + 1) if wide else _le16(body, off + 1)
            seg = body[off + 1 + lsz: off + 1 + lsz + ln]
            if len(seg) < 3:
                return None
            idlen = seg[2]
            stmt = seg[3 + idlen:]
            if len(stmt) >= 2:            # prepare carries the text
                slen = _le16(stmt, 0)
                text = stmt[2:2 + slen]
                if text:
                    return normalize_sql(text)
            sid = seg[3:3 + idlen].decode("latin1", "replace")
            return f"DYNAMIC {sid}" if sid else None
        # non-command leading token (capabilities, options, params…):
        # skip the common length-prefixed shapes, else give up
        if tok in (0xE2, 0xE3, 0xA6, 0xEC, 0xEE):    # u16le length
            if off + 3 > len(body):
                return None
            off += 3 + _le16(body, off + 1)
            continue
        if tok in (0x63, 0x20, 0x61):                # u32le length
            if off + 5 > len(body):
                return None
            off += 5 + _le32(body, off + 1)
            continue
        return None
    return None


# response-stream tokens with a u16le length prefix the walk can skip
# (gy_sybase_proto.h token shapes): CAPABILITY, ENVCHANGE, INFO,
# PARAMFMT, ROWFMT, CONTROL, ORDERBY
_U16_TOKENS = frozenset((0xE2, 0xE3, 0xA6, 0xEC, 0xEE, 0xAE, 0xA9))
# u32le length: ROWFMT2/PARAMFMT2/ORDERBY2-class wide tokens
_U32_TOKENS = frozenset((0x63, 0x20, 0x61))
TOK_RETURNSTATUS = 0x79      # fixed: token + i32


def _scan_response(body: bytes) -> tuple:
    """→ (closed, is_error).

    STRUCTURED front walk: tokens are consumed by their declared
    shapes (DONE* 9 bytes, EED/ERROR/infra tokens length-prefixed)
    until the first unsized token (ROW/PARAMS data needs the column
    state of the 5299-LoC reference to size). Error evidence is
    accepted only from tokens reached structurally — ROW PAYLOAD
    BYTES ARE NEVER SCANNED, so 0xAA/0xE5 bytes inside result data
    cannot false-positive (the r4 heuristic scanned the whole body
    and could). Errors raised mid-rows still surface through the
    final DONE's error bit, which the server sets for errored
    commands (the tail anchor below)."""
    is_err = False
    off = 0
    n = len(body)
    while off < n:
        tok = body[off]
        if tok in (TOK_DONE, TOK_DONEPROC, TOK_DONEINPROC):
            if off + 9 > n:
                break
            if _le16(body, off + 1) & DONE_ERROR:
                is_err = True
            off += 9
            continue
        if tok in (TOK_EED, TOK_ERROR):
            if off + 3 > n:
                break
            ln = _le16(body, off + 1)
            if ln < 6:
                # a real EED/ERROR carries at least msgid+state+class;
                # shorter means the stream is not token-aligned here —
                # stop rather than fabricate a severity from the next
                # token's bytes
                break
            if tok == TOK_ERROR:
                is_err = True
            else:
                # EED: len, msgid u32, state u8, class(severity) u8
                sev = body[off + 8] if off + 9 <= n else 11
                if sev > 10:
                    is_err = True
            off += 3 + ln
            continue
        if tok == TOK_RETURNSTATUS:
            off += 5
            continue
        if tok in _U16_TOKENS:
            if off + 3 > n:
                break
            off += 3 + _le16(body, off + 1)
            continue
        if tok in _U32_TOKENS:
            if off + 5 > n:
                break
            off += 5 + _le32(body, off + 1)
            continue
        # unsized token (ROW 0xD1, PARAMS 0xD7, …): structure is lost
        # from here — stop; the tail DONE still closes the message
        break
    closed = False
    if n >= 9:
        tail_tok = body[n - 9]
        if tail_tok in (TOK_DONE, TOK_DONEPROC, TOK_DONEINPROC):
            status = _le16(body, n - 8)
            if not status & DONE_MORE:
                closed = True
            if status & DONE_ERROR:
                is_err = True
    return closed, is_err


class SybaseParser:
    """Incremental TDS 5.0 request/response pairing for one conn."""

    def __init__(self, max_queue: int = 64):
        self._req_asm = _PacketAssembler()
        self._resp_asm = _PacketAssembler()
        self._pending: list[_Req] = []
        self._max_queue = max_queue
        self._logged_in = False
        self._resp_bytes = 0
        self.transactions: list[Transaction] = []

    def feed_request(self, data: bytes, tusec: int) -> None:
        for msg in self._req_asm.feed(data):
            if msg.ptype == TYPE_LOGIN:
                self._logged_in = True
                continue
            api = _req_signature(msg.ptype, msg.body)
            if api and len(self._pending) < self._max_queue:
                self._pending.append(_Req(api, tusec,
                                          len(msg.body) + _HDR))

    def feed_response(self, data: bytes, tusec: int) -> None:
        self._resp_bytes += len(data)
        for msg in self._resp_asm.feed(data):
            if msg.ptype != TYPE_RESPONSE:
                continue
            closed, is_err = _scan_response(msg.body)
            if not closed:
                continue
            if not self._pending:
                self._resp_bytes = 0      # login ack / unsolicited
                continue
            req = self._pending.pop(0)
            self.transactions.append(Transaction(
                proto=PROTO_SYBASE, api=req.api,
                t_req_usec=req.tusec,
                resp_usec=max(0, tusec - req.tusec),
                status=1 if is_err else 0, is_error=is_err,
                bytes_in=req.nbytes, bytes_out=self._resp_bytes))
            self._resp_bytes = 0

    def drain(self) -> list[Transaction]:
        out, self.transactions = self.transactions, []
        return out
