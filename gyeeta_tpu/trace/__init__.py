"""Request tracing: protocol parsers + per-API device aggregation.

The reference's request-trace pipeline (``API_PARSE_HDLR``,
``common/gy_proto_parser.h:674``) captures request/response byte streams
in the agent, detects the application protocol, reassembles transactions
(request → response pairing), normalizes the request into an *API
signature* (HTTP route template / SQL shape), and ships
``REQ_TRACE_TRAN`` records upstream (``common/gy_comm_proto.h:3288``)
where per-service API aggregates are maintained.

Here the same split, TPU-style: parsing is host/agent-side byte work
(HTTP/1 + Postgres in ``trace/proto.py``, MongoDB in ``trace/mongo.py``,
HTTP/2+gRPC with full HPACK in ``trace/http2.py``, TLS ClientHello
SNI/ALPN in ``trace/tls.py``, plus the protocol detector), API
signatures travel as interned 64-bit ids (NAME_INTERN announcements),
and the aggregation is a device slab keyed by (service, api) folding
whole trace batches: windowed counters + per-API response-time loghist
(north-star config #5: per-API latency sketches across the fleet).
"""

from gyeeta_tpu.trace.proto import (  # noqa: F401
    PROTO_UNKNOWN, PROTO_HTTP1, PROTO_POSTGRES, PROTO_MONGO,
    PROTO_HTTP2, PROTO_TLS, PROTO_SYBASE, PROTO_NAMES,
    HttpParser, PostgresParser, detect_protocol, normalize_http,
    normalize_sql, Transaction, transactions_to_records,
)
from gyeeta_tpu.trace.tds import SybaseParser  # noqa: F401
from gyeeta_tpu.trace.http2 import (  # noqa: F401
    HpackDecoder, Http2Parser, huffman_decode,
)
from gyeeta_tpu.trace.mongo import MongoParser, bson_elements  # noqa: F401
from gyeeta_tpu.trace.tls import (  # noqa: F401
    TlsInfo, TlsParser, parse_client_hello,
)

PARSER_OF_PROTO = {
    PROTO_HTTP1: HttpParser,
    PROTO_POSTGRES: PostgresParser,
    PROTO_MONGO: MongoParser,
    PROTO_HTTP2: Http2Parser,
    PROTO_TLS: TlsParser,
    PROTO_SYBASE: SybaseParser,
}

# AFTER the registry: pcapfile consumes PARSER_OF_PROTO at import
from gyeeta_tpu.trace.pcapfile import (  # noqa: E402,F401
    FlowConversation, PcapError, parse_pcap,
)
