"""Request tracing: protocol parsers + per-API device aggregation.

The reference's request-trace pipeline (``API_PARSE_HDLR``,
``common/gy_proto_parser.h:674``) captures request/response byte streams
in the agent, detects the application protocol, reassembles transactions
(request → response pairing), normalizes the request into an *API
signature* (HTTP route template / SQL shape), and ships
``REQ_TRACE_TRAN`` records upstream (``common/gy_comm_proto.h:3288``)
where per-service API aggregates are maintained.

Here the same split, TPU-style: parsing is host/agent-side byte work
(``trace/proto.py`` — HTTP/1 and Postgres transaction parsers + the
protocol detector), API signatures travel as interned 64-bit ids
(NAME_INTERN announcements), and the aggregation is a device slab keyed
by (service, api) folding whole trace batches: windowed counters +
per-API response-time loghist (north-star config #5: per-API latency
sketches across the fleet).
"""

from gyeeta_tpu.trace.proto import (  # noqa: F401
    PROTO_UNKNOWN, PROTO_HTTP1, PROTO_POSTGRES, PROTO_NAMES,
    HttpParser, PostgresParser, detect_protocol, normalize_http,
    normalize_sql, Transaction, transactions_to_records,
)
