"""Prometheus text-format exposition of the framework self-metrics.

The process-local ``Stats`` registry (``utils/selfstats.py``) was only
reachable through the ``selfstats`` query subsystem — invisible to any
standard scraper. This renders the SAME registry as exposition format
0.0.4 text:

- counters        → ``gyt_<name>_total`` (monotone ints: event counts,
  decode-path counters, drop events, …); a counter bumped as
  ``name|k=v`` renders as the labeled sample ``gyt_name_total{k="v"}``
  — one family, one TYPE line, N label values (the NM edge's
  ``nm_queries|verb=...`` per-verb counters use this)
- gauges          → ``gyt_<name>`` (tick, drop totals, and the
  ``engine_*`` device-health gauges from ``obs/health.py``)
- timing hists    → ``gyt_stage_duration_seconds{stage=...}`` —
  geometric buckets mapped to cumulative ``le`` buckets (seconds) with
  ``_sum``/``_count``; trailing all-zero buckets are elided (+Inf
  always emitted), a valid subset per the exposition spec
- alert-manager   → ``gyt_alerts_<name>_total`` (including
  ``gyt_alerts_ncq_group_evals_total`` — criteria-group predicate
  passes: defs sharing a canonical filter share one pass)

Continuous-query rows (``net/subs.py`` hub, OPERATIONS.md
"Continuous queries"): ``gyt_cq_groups`` / ``gyt_cq_subscribers``
gauges and the ``gyt_cq_group_evals_total`` /
``gyt_cq_panel_renders_total`` / ``gyt_cq_events_total{kind=...}`` /
``gyt_cq_resyncs_total`` counter families — the amortization contract
(one predicate pass per criteria group, ≤1 render per panel per tick)
is checked off these exact rows by ``_cq_smoke.py``.

One rendering function serves every surface: ``GET /metrics`` on the
HTTP gateway and the ``metrics`` query subsystem on the binary
protocol (both runtimes route through ``query/api.py:local_response``),
so scraper and query client can never see different names.
"""

from __future__ import annotations

import re
import time

import numpy as np

from gyeeta_tpu.utils import selfstats as SS

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

_NAME_OK = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*$")
_SANITIZE = re.compile(r"[^a-zA-Z0-9_:]")


def _name(raw: str) -> str:
    n = _SANITIZE.sub("_", str(raw))
    if not _NAME_OK.match(n):
        n = "_" + n
    return n


def _num(v) -> str:
    f = float(v)
    if f == float("inf"):
        return "+Inf"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def render(stats, alerts=None) -> str:
    """``Stats`` registry → exposition text. Engine-health gauges are
    expected to already sit in ``stats.gauges`` (the runtimes fold the
    batched readback in before rendering)."""
    out: list[str] = []

    # consistent copies: the exposition renders on query worker threads
    # in snapshot mode while the serving loop keeps bumping
    counters, gauges = stats.export()

    # group counters into families: plain names stand alone; "name|k=v"
    # label-encoded names collapse into one family with labeled samples
    families: dict[str, list] = {}
    for k in counters:
        base, _, labels = k.partition("|")
        families.setdefault(base, []).append((labels, counters[k]))
    for base in sorted(families):
        n = f"gyt_{_name(base)}_total"
        out.append(f"# TYPE {n} counter")
        for labels, v in sorted(families[base]):
            lab = ""
            if labels:
                parts = [f'{_name(kk)}="{vv}"' for kk, _, vv in
                         (p.partition("=") for p in labels.split(","))]
                lab = "{" + ",".join(parts) + "}"
            out.append(f"{n}{lab} {_num(v)}")

    if alerts is not None:
        for k in sorted(alerts.stats):
            n = f"gyt_alerts_{_name(k)}_total"
            out.append(f"# TYPE {n} counter")
            out.append(f"{n} {_num(alerts.stats[k])}")

    gauges["uptime_seconds"] = time.time() - stats.t_start
    # gauges share the counters' "name|k=v" label convention (the
    # per-shard fold-rate / occupancy gauges of the mesh tier)
    gfam: dict[str, list] = {}
    for k in gauges:
        base, _, labels = k.partition("|")
        gfam.setdefault(base, []).append((labels, gauges[k]))
    for base in sorted(gfam):
        n = f"gyt_{_name(base)}"
        out.append(f"# TYPE {n} gauge")
        for labels, v in sorted(gfam[base]):
            lab = ""
            if labels:
                parts = [f'{_name(kk)}="{vv}"' for kk, _, vv in
                         (p.partition("=") for p in labels.split(","))]
                lab = "{" + ",".join(parts) + "}"
            out.append(f"{n}{lab} {_num(v)}")

    hists = stats.timing_hists()
    if hists:
        h_name = "gyt_stage_duration_seconds"
        out.append(f"# TYPE {h_name} histogram")
        for stage, counts, total_ms in hists:
            lab = _name(stage)
            cum = np.cumsum(counts)
            n = int(cum[-1])
            if n == 0:
                continue
            last = int(np.nonzero(counts)[0][-1])
            for b in range(last + 1):
                le = SS.bucket_upper_ms(b) / 1e3
                out.append(f'{h_name}_bucket{{stage="{lab}",'
                           f'le="{_num(le)}"}} {int(cum[b])}')
            out.append(f'{h_name}_bucket{{stage="{lab}",le="+Inf"}} {n}')
            out.append(f'{h_name}_sum{{stage="{lab}"}} '
                       f'{repr(total_ms / 1e3)}')
            out.append(f'{h_name}_count{{stage="{lab}"}} {n}')

    return "\n".join(out) + "\n"


def metrics_response(stats, alerts=None) -> dict:
    """The ``metrics`` query-subsystem payload: exposition text plus
    the content type the HTTP gateway must serve it under."""
    return {"text": render(stats, alerts), "content_type": CONTENT_TYPE}
