"""Pipeline span tracer: a fixed-size ring of per-stage spans.

The reference attributes hot-path cost with ``GY_HISTOGRAM`` wrappers
and prints them on a cadence; histograms answer "how slow is this
stage" but not "what did the last slow batch look like". This ring
keeps the most recent N spans of the feed pipeline — one span per
stage per feed batch (deframe → decode+fold dispatch → tick), each
carrying the batch size, the native-vs-fallback decode path, and the
wall time — so an operator can see the actual recent batches, not just
their distribution. Surfaced as ``selfstats.spans`` over the query
protocol and rendered by ``python -m gyeeta_tpu obs top``.

Overhead discipline: recording a span is two clock reads and one list
slot write — no allocation beyond the tuple, no locks (the serving
loop is single-threaded; the decode-pipeline worker never records).
Wall times measure HOST time; jitted dispatches are async, so a
"fold" span is the enqueue cost, and device time shows up in the
blocking spans (tick/flush). For true device timelines use the
``GYT_JAX_PROFILE`` knob below.
"""

from __future__ import annotations

import contextlib
import os
import time

_FIELDS = ("name", "t", "wallms", "nrec", "path")


class SpanTracer:
    """Lock-free single-writer ring buffer of (name, t, wallms, nrec,
    path) spans. ``capacity`` bounds memory forever; old spans are
    overwritten (the notifymsg-ring discipline)."""

    __slots__ = ("_buf", "_cap", "_i", "total")

    def __init__(self, capacity: int = 1024):
        self._buf: list = [None] * max(capacity, 1)
        self._cap = max(capacity, 1)
        self._i = 0
        self.total = 0          # spans ever recorded (overwrites included)

    def record(self, name: str, t: float, wallms: float,
               nrec: int = 0, path: str = "") -> None:
        self._buf[self._i] = (name, t, wallms, nrec, path)
        self._i = (self._i + 1) % self._cap
        self.total += 1

    @contextlib.contextmanager
    def span(self, name: str, nrec: int = 0, path: str = ""):
        """Record one span around a code block (host wall time)."""
        t = time.time()
        p0 = time.perf_counter()
        try:
            yield
        finally:
            self.record(name, t, (time.perf_counter() - p0) * 1e3,
                        nrec, path)

    def __len__(self) -> int:
        return min(self.total, self._cap)

    def rows(self, last: int = 128) -> list[dict]:
        """Newest-first span dicts (bounded by ``last``)."""
        n = min(len(self), last)
        out = []
        for k in range(1, n + 1):
            rec = self._buf[(self._i - k) % self._cap]
            if rec is None:          # pragma: no cover — len() guards
                break
            out.append({f: (round(v, 4) if f == "wallms" else v)
                        for f, v in zip(_FIELDS, rec)})
        return out

    def clear(self) -> None:
        self._buf = [None] * self._cap
        self._i = 0
        self.total = 0


class FoldProfiler:
    """Opt-in ``jax.profiler`` bracketing of the first N fold
    dispatches: ``GYT_JAX_PROFILE=<dir>`` arms it, and the trace
    covers folds 1..N (``GYT_JAX_PROFILE_FOLDS``, default 20) — the
    device-timeline complement to the host-side span ring. Never
    active unless the env var is set; ``close()`` stops a trace that
    didn't reach N folds (short-lived processes still get a file)."""

    def __init__(self, env=None):
        env = os.environ if env is None else env
        self.dir = env.get("GYT_JAX_PROFILE") or None
        self.n_folds = int(env.get("GYT_JAX_PROFILE_FOLDS", "20") or 20)
        self._seen = 0
        self._active = False

    @property
    def armed(self) -> bool:
        return self.dir is not None and not (
            self._seen >= self.n_folds and not self._active)

    def on_fold(self) -> None:
        """Call once per fold dispatch (hot path: two attribute reads
        when the knob is unset)."""
        if self.dir is None or self._seen >= self.n_folds:
            if self._active:        # pragma: no cover — defensive
                self._stop()
            return
        if not self._active:
            import jax
            jax.profiler.start_trace(self.dir)
            self._active = True
        self._seen += 1
        if self._seen >= self.n_folds:
            self._stop()

    def _stop(self) -> None:
        import jax
        jax.profiler.stop_trace()
        self._active = False

    def close(self) -> None:
        if self._active:
            self._stop()
