"""Self-observability tier: exposition, device health, span tracing.

Three pillars over the process-wide ``Stats`` registry
(``utils/selfstats.py``):

- ``obs/prom.py``   — Prometheus text-format exporter (``GET /metrics``
  on the HTTP gateway; ``metrics`` query subsystem on the binary
  protocol — one rendering for both, shared by both runtimes).
- ``obs/health.py`` — engine device-state health: slab occupancy,
  probe-failure/eviction counters, dep-graph fill, digest-stage
  pressure, read back as ONE batched transfer per report cadence
  (``engine/step.py:engine_health_vec``).
- ``obs/spans.py``  — ring-buffer span tracer over the feed pipeline
  (deframe → decode+fold per batch, with size and native-vs-fallback
  path) + the opt-in ``GYT_JAX_PROFILE`` device-trace bracket.

``python -m gyeeta_tpu obs top`` renders the live surface; see the
Monitoring section of OPERATIONS.md for scrape config and alerting
starting points.
"""

from __future__ import annotations

from gyeeta_tpu.obs.spans import FoldProfiler, SpanTracer  # noqa: F401


def format_top(selfstats: dict, prev_counters: dict | None = None,
               interval_s: float = 0.0, width: int = 78) -> str:
    """Render one ``obs top`` frame from a ``selfstats`` payload.

    ``prev_counters`` + ``interval_s`` turn cumulative counters into
    rates (the ``Stats.delta()`` view, computed client-side so the
    monitor never mutates server state)."""
    c = selfstats.get("counters", {})
    lines = []
    up = c.get("uptime_sec", 0)
    lines.append(f"gyt self-monitor — uptime {up}s")

    eng = {k: v for k, v in sorted(c.items())
           if str(k).startswith("engine_")}
    if eng:
        lines.append("")
        lines.append("engine health:")
        for k, v in eng.items():
            lines.append(f"  {k:<36} {v}")

    # durable-ingest surface: WAL fsync lag (the RPO bound), unsynced
    # bytes, segment footprint, replay/torn-tail counters, and the
    # admission-control state — the disk half of the health picture
    dur = {k: v for k, v in sorted(c.items())
           if str(k).startswith(("journal_", "wal_", "throttle"))}
    if dur:
        lines.append("")
        lines.append("durability / backpressure:")
        for k, v in dur.items():
            lines.append(f"  {k:<36} {v}")

    # query-serving surface: snapshot freshness, result-cache hit
    # rate, executor depth and shed counts (the 1k+ QPS dashboard
    # health picture — OPERATIONS.md "Query serving")
    qry = {k: v for k, v in sorted(c.items())
           if str(k).startswith(("query_", "queries", "snapshot"))}
    if qry:
        lines.append("")
        lines.append("query serving:")
        hits = c.get("query_cache_hits", 0)
        misses = c.get("query_cache_misses", 0)
        if hits or misses:
            qry["cache_hit_rate"] = round(hits / (hits + misses), 4)
        for k, v in qry.items():
            lines.append(f"  {k:<36} {v}")

    # query-fabric surface (gateway tier, net/gateway.py): edge-cache
    # hit tiers, fleet-wide single-render collapse, subscription fan
    # and the delta-vs-full wire ratio (OPERATIONS.md "Query fabric")
    gwm = {k: v for k, v in sorted(c.items())
           if str(k).startswith("gw_")}
    if gwm:
        lines.append("")
        lines.append("query fabric:")
        db, fb = c.get("gw_delta_bytes", 0), c.get("gw_full_bytes", 0)
        if fb:
            gwm["delta_vs_full_byte_ratio"] = round(db / fb, 4)
        # fault-domain derived rows (OPERATIONS.md "Failure domains &
        # degradation"): a hedge WIN rate near 1 means one replica is
        # consistently slow; resumes-vs-resyncs is the continuation
        # hit rate of the retained/persisted version rings
        hreq = c.get("gw_hedged_requests", 0)
        if hreq:
            gwm["hedge_win_rate"] = round(
                c.get("gw_hedged_wins", 0) / hreq, 4)
        resumes = c.get("gw_sub_resumes", 0)
        resyncs = c.get("gw_sub_resyncs", 0)
        if resumes or resyncs:
            gwm["sub_continuation_rate"] = round(
                resumes / (resumes + resyncs), 4)
        for k, v in gwm.items():
            lines.append(f"  {k:<36} {v}")

    # remote ingest relay surface (net/relay.py): per-relay published /
    # consumed / counted-dropped ledgers plus epoch and reconnect churn
    # (OPERATIONS.md "Regions & WAN deployment"). ledger_open is the
    # global invariant published − consumed − dropped summed over all
    # relays: a persistently nonzero value means records vanished
    # UNCOUNTED between the remote host and the hub — page on it.
    rly = {k: v for k, v in sorted(c.items())
           if str(k).startswith("relay_")}
    if rly:
        lines.append("")
        lines.append("remote ingest relay:")

        def _rsum(pfx: str) -> float:
            return sum(v for k, v in rly.items()
                       if str(k).startswith(pfx)
                       and isinstance(v, (int, float)))

        rly["ledger_open"] = round(
            _rsum("relay_published_records")
            - _rsum("relay_consumed_records")
            - _rsum("relay_dropped_records"), 4)
        for k, v in rly.items():
            lines.append(f"  {k:<36} {v}")

    # segment-shipping surface (history/shipper.py + net/segship.py):
    # sealed / shipped / counted-dropped SEGMENT ledgers per shipper
    # plus hash mismatches, staging sheds and heartbeat age
    # (OPERATIONS.md "Remote compaction region"). ship_open is the
    # global invariant sealed − shipped − dropped: persistently
    # nonzero and growing means sealed segments are NOT reaching the
    # compaction region — check the uplink before the source's disk
    # fills against the pinned ship floor.
    shp = {k: v for k, v in sorted(c.items())
           if str(k).startswith("ship_")}
    if shp:
        lines.append("")
        lines.append("segment shipping:")

        def _ssum(pfx: str) -> float:
            return sum(v for k, v in shp.items()
                       if str(k).startswith(pfx)
                       and isinstance(v, (int, float)))

        shp["ship_open"] = round(
            _ssum("ship_sealed_segments")
            - _ssum("ship_shipped_segments")
            - _ssum("ship_dropped_segments"), 4)
        for k, v in shp.items():
            lines.append(f"  {k:<36} {v}")

    # history tier (compactor + windowed quantiles, OPERATIONS.md
    # "Distributed compaction & windowed quantiles")
    hist = {k: v for k, v in sorted(c.items())
            if str(k).startswith(("compact_", "wd_",
                                  "windowed_quant"))}
    if hist:
        lines.append("")
        lines.append("history compaction:")
        for k, v in hist.items():
            lines.append(f"  {k:<36} {v}")

    plain = {k: v for k, v in sorted(c.items())
             if not str(k).startswith(("engine_", "journal_", "wal_",
                                       "throttle", "query_", "queries",
                                       "snapshot", "gw_", "relay_",
                                       "ship_", "compact_", "wd_",
                                       "windowed_quant"))
             and isinstance(v, (int, float))}
    lines.append("")
    hdr = f"  {'counter':<36} {'total':>12}"
    if prev_counters is not None and interval_s > 0:
        hdr += f" {'rate/s':>12}"
    lines.append("counters:")
    lines.append(hdr)
    for k, v in plain.items():
        if k == "uptime_sec":
            continue
        row = f"  {k:<36} {v:>12}"
        if prev_counters is not None and interval_s > 0:
            d = (v - prev_counters.get(k, 0)) / interval_s
            row += f" {d:>12.1f}"
        lines.append(row)

    timings = selfstats.get("timings") or []
    if timings:
        lines.append("")
        lines.append("stage timings:")
        lines.append(f"  {'stage':<20} {'count':>9} {'p50ms':>9} "
                     f"{'p95ms':>9} {'p99ms':>9} {'totalms':>11}")
        for r in timings:
            lines.append(
                f"  {r['stage']:<20} {r['count']:>9} {r['p50ms']:>9} "
                f"{r['p95ms']:>9} {r['p99ms']:>9} {r['totalms']:>11}")

    spans = selfstats.get("spans") or []
    if spans:
        lines.append("")
        lines.append("recent spans (newest first):")
        lines.append(f"  {'stage':<16} {'wallms':>9} {'nrec':>9} path")
        for s in spans[:16]:
            lines.append(f"  {s['name']:<16} {s['wallms']:>9} "
                         f"{s['nrec']:>9} {s.get('path', '')}")

    return "\n".join(ln[:width] for ln in lines) + "\n"
