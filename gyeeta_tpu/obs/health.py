"""Engine health gauges: one batched device readback → named gauges.

``engine/step.py:engine_health_vec`` packs the whole device-health
surface into one small f32 vector (sums over shards, max for stage
pressure). This module turns that vector into the operator-facing
gauge dict — occupancy ratios against the configured capacities,
probe-failure/eviction counters, dep-graph fill — that both runtimes
fold into their ``Stats`` gauges (so the gauges ride ``selfstats``,
the ``metrics`` exposition, and the serve-loop cadence log from ONE
readback per report cadence).

Occupancy counts live + tombstoned rows: a tombstone still occupies
probe positions until compaction, so it is load the open-addressing
probe sees (``engine/table.py`` load guidance: keep ≤70%).
"""

from __future__ import annotations

import numpy as np

from gyeeta_tpu.engine.step import HEALTH_KEYS


def capacities(cfg, opts, n_shards: int = 1) -> dict:
    """Total capacities backing the occupancy ratios. Every shard owns
    a full-geometry slab, so a mesh multiplies by ``n_shards``."""
    return {
        "svc": cfg.svc_capacity * n_shards,
        "task": cfg.task_capacity * n_shards,
        "api": cfg.api_capacity * n_shards,
        "td_stage": cfg.td_stage_cap,      # per-entity; max, not summed
        "dep_pair": opts.dep_pair_capacity * n_shards,
        "dep_edge": opts.dep_edge_capacity * n_shards,
        "hh": cfg.hh_depth * max(cfg.hh_width, 1) * n_shards,
    }


def gauges_from_vec(vec, caps: dict) -> dict:
    """HEALTH_KEYS-ordered vector → {gauge_name: float}.

    Names are exposition-ready (``gyt_`` prefix added by the exporter);
    ratios are rounded to 4 places (they are operator signals, not
    accounting)."""
    h = dict(zip(HEALTH_KEYS, np.asarray(vec, np.float64).tolist()))
    occ = lambda live, tomb, cap: round(  # noqa: E731
        (live + tomb) / max(cap, 1), 4)
    return {
        "engine_svc_rows_live": h["svc_live"],
        "engine_svc_occupancy_ratio": occ(h["svc_live"], h["svc_tomb"],
                                          caps["svc"]),
        "engine_svc_tombstones": h["svc_tomb"],
        "engine_svc_probe_failures": h["svc_drop"],
        "engine_task_rows_live": h["task_live"],
        "engine_task_occupancy_ratio": occ(h["task_live"],
                                           h["task_tomb"], caps["task"]),
        "engine_task_tombstones": h["task_tomb"],
        "engine_task_probe_failures": h["task_drop"],
        "engine_api_rows_live": h["api_live"],
        "engine_api_occupancy_ratio": occ(h["api_live"], h["api_tomb"],
                                          caps["api"]),
        "engine_api_tombstones": h["api_tomb"],
        "engine_api_probe_failures": h["api_drop"],
        "engine_td_stage_pressure_ratio": round(
            h["td_stage_max"] / max(caps["td_stage"], 1), 4),
        "engine_conn_folded": h["n_conn"],
        "engine_resp_folded": h["n_resp"],
        "engine_resp_unknown_svc": h["n_resp_unknown"],
        "engine_td_overflow": h["n_td_overflow"],
        "engine_dep_pair_fill_ratio": round(
            h["dep_half_live"] / max(caps["dep_pair"], 1), 4),
        "engine_dep_edge_fill_ratio": round(
            h["dep_edge_live"] / max(caps["dep_edge"], 1), 4),
        "engine_dep_probe_failures": h["dep_edge_drop"],
        "engine_dep_paired": h["dep_paired"],
        "engine_dep_expired": h["dep_expired"],
        "engine_dep_dropped": h["dep_dropped"],
        # heavy-hitter tier: the top-K undercount bound operators size
        # alerts against, invertible-bucket fill, hot-admission lanes
        "topk_evicted_mass": h["topk_evicted"],
        "engine_hh_occupancy_ratio": round(
            h["hh_occupied"] / max(caps["hh"], 1), 4),
        "engine_hh_hot_lanes": h["hh_hot_lanes"],
    }


def drops_for_pressure(gauges: dict) -> dict:
    """The cumulative drop counters ``utils/droppressure.check``
    watches, pulled from the health gauges (no extra readback)."""
    return {"svc": int(gauges["engine_svc_probe_failures"]),
            "task": int(gauges["engine_task_probe_failures"]),
            "api": int(gauges["engine_api_probe_failures"]),
            "dep": int(gauges["engine_dep_dropped"])}
