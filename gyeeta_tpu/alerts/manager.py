"""Alert manager: columnar realtime evaluation + lifecycle + routing.

One ``check()`` per 5s engine pass evaluates every enabled alertdef as a
criteria mask over its subsystem snapshot (the whole fleet in a handful of
vector ops — the tensor form of the reference's per-event RT_ALERT_VECS
walk, ``server/gy_malerts.cc:1869``), then advances per-entity lifecycle:

    pending (consecutive hits < numcheckfor) → firing → resolved

Alertdefs ARE continuous queries (ISSUE 18): each def is a standing
filter whose canonical form (``query/normalize.py:canonical_filter``)
lands it in a ``(column-source, criteria)`` group shared with every
other def asking the same question — the predicate evaluates ONCE per
group per check (``ncq_group_evals`` counts group passes; compare
against the def count), the same normalization+grouping the
subscription hub's CQ tier uses (``query/cq.py``). A def FIRES on
membership *enter* (gated by ``numcheckfor`` consecutive membership
checks) and RESOLVES on *leave* (``cq.advance_entities`` is the
lifecycle step). Column sources are rendered lazily per targeted
subsystem only — a subsystem no def targets costs nothing, and the
runtimes skip the whole pass (counted ``alert_eval_skipped``) when no
realtime def is enabled.

Silences and inhibits gate *notification*, not detection (matching the
reference: a silenced alert still tracks state, ``gy_alertmgr.cc:5117``).

Two evaluation modes (the reference's RT vs MDB alertdef split,
``server/gy_malerts.cc``): realtime defs run in ``check()`` against the
live snapshot; db-mode defs run in ``check_db()`` as periodic
criteria-SQL over the history store every ``querysec`` (db-row silences
match by alertname/time only — history rows carry presentation strings,
not live ordinals).

Notification grouping (ref ALERT_GROUP group-wait windows,
``gy_alertmgr.h:574``): a def with ``groupwaitsec > 0`` buffers its
notifications from the moment the group opens and emits them as one
batch when the wait expires (``flush_groups``, called from ``check``).
Actions are pluggable callables; "log" is built in (EMAIL/SLACK/
PAGERDUTY/WEBHOOK of ``gy_alertmgr.h:50`` register the same way;
network egress is deployment-specific).
"""

from __future__ import annotations

import collections
import time
from typing import Callable, NamedTuple, Optional

import numpy as np

from gyeeta_tpu.alerts.defs import AlertDef, Inhibit, Silence
from gyeeta_tpu.query import api, cq, criteria
from gyeeta_tpu.query.normalize import canonical_filter


class Alert(NamedTuple):
    alertname: str
    severity: str
    subsys: str
    entity: str                  # svcid / hostid / flow key
    tfired: float
    labels: dict
    annotations: dict
    row: dict                    # snapshot row at fire time


class _EntityState(NamedTuple):
    nhits: int = 0
    firing: bool = False
    tlast_notify: float = -1e18


# The entity key is the COMPOSITE of every id-grained column present:
# one key alone under-identifies rows on several subsystems (tracereq
# rows are (svcid, api); svcprocmap rows are (svcid, taskid)), and a
# coarse key collapses per-entity state — numcheckfor then advances
# once per matching row per check and distinct entities suppress each
# other through repeataftersec.
_ENTITY_KEYS = ("svcid", "taskid", "cgid", "cliid", "serid", "api",
                "flowid", "alertname", "hostid",
                # topk rows: one entity per (metric, entity id) — so
                # "new flow enters the top-10" fires once per flow, not
                # once per rank shuffle
                "metric", "id")


def _entity_key_of(subsys: str, cols: dict, i: int) -> str:
    parts = [f"{k}={cols[k][i]}" for k in _ENTITY_KEYS if k in cols]
    return ",".join(parts) if parts else f"row={i}"


def _entity_key_of_row(row: dict) -> str:
    parts = [f"{k}={row[k]}" for k in _ENTITY_KEYS
             if k in row and row[k] is not None]
    if parts:
        return ",".join(parts)
    # id-less subsystems (clusterstate): the whole subsystem is one
    # entity — per-row keys would defeat dedup/numcheckfor entirely
    return "all"


class AlertManager:
    MAX_LOG = 10_000     # bounded notification history (oldest dropped)

    def __init__(self, cfg, clock: Optional[Callable[[], float]] = None):
        self.cfg = cfg
        self.defs: dict[str, AlertDef] = {}
        self.silences: dict[str, Silence] = {}
        self.inhibits: dict[str, Inhibit] = {}
        self.alert_log: collections.deque = collections.deque(
            maxlen=self.MAX_LOG)
        self.actions: dict[str, Callable[[list], None]] = {
            "log": self.alert_log.extend,
        }
        # configured delivery actions (webhook/slack/email/pagerduty):
        # configs live here for CRUD + the actions subsystem; execution
        # runs on the dispatcher's worker thread (ref alert_act_thread,
        # gy_alertmgr.cc:3465) so evaluation never blocks on HTTP
        self.action_cfgs: dict[str, "deliver.ActionConfig"] = {}
        self._dispatcher = None
        self._state: dict[tuple, _EntityState] = {}
        self._trees: dict[str, object] = {}     # parsed filter cache
        # def name → canonical filter: the criteria-group identity
        # (defs sharing it share one predicate pass per check)
        self._canon: dict[str, str] = {}
        self._groups: dict[str, list] = {}      # name → [deadline, alerts]
        self._next_db: dict[str, float] = {}    # db-def → next eval time
        self._last_db: dict[str, float] = {}    # db-def → last eval time
        self._clock = clock or time.time
        self.stats = {"nchecks": 0, "nfired": 0, "nsilenced": 0,
                      "ninhibited": 0, "nresolved": 0, "ndbchecks": 0,
                      "ngroups_flushed": 0,
                      # windowed defs checked before the first history
                      # window exists skip COUNTED (check() bumps this;
                      # it must pre-exist or the += KeyErrors)
                      "nwindow_skipped": 0,
                      # criteria-group predicate passes per check():
                      # defs sharing a canonical filter share one pass,
                      # so this stays ≤ the enabled realtime def count
                      "ncq_group_evals": 0}

    # ------------------------------------------------------------- CRUD
    def add_def(self, d: dict | AlertDef) -> AlertDef:
        # BOTH paths validate at definition time: a typo'd subsys (or a
        # filter whose criteria target another subsystem) fails the
        # CRUD request with the valid-subsystem list instead of
        # erroring on every subsequent fold-time check
        ad = (d.validate() if isinstance(d, AlertDef)
              else AlertDef.from_json(d))
        self.defs[ad.name] = ad
        self._trees[f"def:{ad.name}"] = criteria.parse(ad.filter)
        self._canon[ad.name] = canonical_filter(ad.filter)
        return ad

    def delete_def(self, name: str) -> bool:
        self._state = {k: v for k, v in self._state.items()
                       if k[0] != name}
        self._trees.pop(f"def:{name}", None)
        self._canon.pop(name, None)
        return self.defs.pop(name, None) is not None

    # defs the runtimes must evaluate this pass — the zero-def (or
    # zero-REALTIME-def) short-circuit happens at the CALLER, before
    # any column/render work, counted ``alert_eval_skipped``
    def wants_realtime(self) -> bool:
        return any(ad.enabled and ad.mode == "realtime"
                   for ad in self.defs.values())

    def wants_db(self) -> bool:
        return any(ad.enabled and ad.mode == "db"
                   for ad in self.defs.values())

    def pending_groups(self) -> bool:
        return bool(self._groups)

    def add_silence(self, d: dict | Silence) -> Silence:
        s = d if isinstance(d, Silence) else Silence.from_json(d)
        self.silences[s.name] = s
        if s.filter:
            self._trees[f"sil:{s.name}"] = criteria.parse(s.filter)
        return s

    def add_inhibit(self, d: dict | Inhibit) -> Inhibit:
        i = d if isinstance(d, Inhibit) else Inhibit.from_json(d)
        self.inhibits[i.name] = i
        return i

    def register_action(self, name: str, fn: Callable[[list], None]):
        self.actions[name] = fn

    @property
    def dispatcher(self):
        if self._dispatcher is None:
            from gyeeta_tpu.alerts.deliver import ActionDispatcher
            self._dispatcher = ActionDispatcher()
        return self._dispatcher

    def add_action(self, d: dict):
        """CRUD: configure a delivery action (ref actiondef CRUD →
        routed by alertdef.actions names)."""
        from gyeeta_tpu.alerts import deliver
        cfg = d if isinstance(d, deliver.ActionConfig) \
            else deliver.ActionConfig.from_json(d)
        if cfg.name == "log":
            raise ValueError("'log' is built in")
        self.action_cfgs[cfg.name] = cfg
        self.actions[cfg.name] = \
            lambda group, _c=cfg: self.dispatcher.enqueue(_c, group)
        return cfg

    def delete_action(self, name: str) -> bool:
        if name == "log":
            return False
        self.action_cfgs.pop(name, None)
        return self.actions.pop(name, None) is not None

    def close(self) -> None:
        if self._dispatcher is not None:
            self._dispatcher.close()
            self._dispatcher = None

    # ------------------------------------------------------------ check
    def firing(self) -> list[tuple]:
        return [k for k, v in self._state.items() if v.firing]

    def _silenced(self, ad: AlertDef, cols, i, now) -> bool:
        for s in self.silences.values():
            if not (s.tstart <= now <= s.tend):
                continue
            if s.alertnames and ad.name not in s.alertnames:
                continue
            if s.filter:
                tree = self._trees.get(f"sil:{s.name}") \
                    or criteria.parse(s.filter)
                one = {k: np.asarray(v[i:i + 1]) for k, v in cols.items()}
                if not bool(criteria.evaluate(tree, one, ad.subsys)[0]):
                    continue
            return True
        return False

    def _inhibited(self, ad: AlertDef) -> bool:
        firing_names = {k[0] for k in self.firing()}
        for inh in self.inhibits.values():
            if ad.name in inh.target_alertnames and \
                    firing_names & set(inh.src_alertnames):
                return True
        return False

    def check(self, st, columns_fn=None) -> list[Alert]:
        """Evaluate all defs against live engine state → newly-notified
        alerts (grouped per def, routed to actions).

        Each def is a CONTINUOUS QUERY: its predicate evaluates once
        per ``(column-source, canonical-filter)`` group — N defs asking
        an equivalent question share one vectorized pass (the mask
        cache below; ``ncq_group_evals`` counts passes) — and the
        entity lifecycle advances on membership enter/stay/leave
        (``cq.advance_entities``): fire on enter after ``numcheckfor``
        consecutive membership checks, resolve on leave. Column
        sources render lazily per TARGETED subsystem only.

        ``columns_fn(subsys) -> (cols, mask)`` overrides the column source
        (the sharded runtime evaluates alerts on gathered readbacks)."""
        now = self._clock()
        self.stats["nchecks"] += 1
        notified: list[Alert] = []
        cols_cache: dict[str, tuple] = {}
        mask_cache: dict[tuple, object] = {}

        for ad in self.defs.values():
            if not ad.enabled or ad.mode != "realtime":
                continue
            # a windowed def evaluates against the time-travel tier's
            # aggregate: the column source is addressed "subsys@window"
            # (both runtimes route the suffix to timeview); before the
            # first window exists the check skips, counted
            ckey = f"{ad.subsys}@{ad.window}" if ad.window \
                else ad.subsys
            if ckey not in cols_cache:
                try:
                    cols_cache[ckey] = (
                        columns_fn(ckey) if columns_fn is not None
                        else api._COLUMNS_OF[ad.subsys](self.cfg, st))
                except ValueError:
                    if not ad.window:
                        raise
                    self.stats["nwindow_skipped"] += 1
                    cols_cache[ckey] = None
            if cols_cache[ckey] is None:
                continue
            cols, base = cols_cache[ckey]
            # shared-predicate index: one mask per (column source,
            # canonical criteria) group per check — the group key
            # embeds ckey so live and windowed defs never share
            gkey = (ckey, self._canon.get(ad.name, ad.filter))
            if gkey not in mask_cache:
                tree = self._trees.get(f"def:{ad.name}") \
                    or criteria.parse(ad.filter)
                try:
                    mask_cache[gkey] = \
                        base & criteria.evaluate(tree, cols, ad.subsys)
                    self.stats["ncq_group_evals"] += 1
                except KeyError:
                    if not ad.window:
                        raise
                    # a windowed QUANTILE criterion over shards without
                    # delta panels: the field was omitted from the
                    # window columns (never approximated) — the GROUP
                    # skips; each def standing on it counts below,
                    # exactly like a not-yet-existing window, instead
                    # of one stale store breaking the whole alert pass
                    mask_cache[gkey] = None
            mask = mask_cache[gkey]
            if mask is None:
                self.stats["nwindow_skipped"] += 1
                continue
            hits = set(np.nonzero(mask)[0].tolist())

            inhibited = self._inhibited(ad)
            group: list[Alert] = []
            # the def's held membership (entity keys with state):
            # enter/stay advance nhits below, leave resolves after
            held = {k for k in self._state if k[0] == ad.name}
            seen_keys = set()
            for i in sorted(hits):
                ent = _entity_key_of(ad.subsys, cols, i)
                key = (ad.name, ent)
                seen_keys.add(key)
                es = self._state.get(key, _EntityState())
                nhits = es.nhits + 1
                firing = nhits >= ad.numcheckfor
                notify = (firing
                          and now - es.tlast_notify >= ad.repeataftersec)
                if notify and self._silenced(ad, cols, i, now):
                    self.stats["nsilenced"] += 1
                    notify = False
                if notify and inhibited:
                    self.stats["ninhibited"] += 1
                    notify = False
                if notify:
                    row = {k: cols[k][i] for k in cols}
                    group.append(Alert(
                        alertname=ad.name, severity=ad.severity,
                        subsys=ad.subsys, entity=ent, tfired=now,
                        labels=dict(ad.labels),
                        annotations=dict(ad.annotations),
                        row={k: (v.item() if hasattr(v, "item") else v)
                             for k, v in row.items()}))
                    es = es._replace(tlast_notify=now)
                self._state[key] = es._replace(nhits=nhits, firing=firing)

            # LEAVE resolves (and drops state — the dict must not grow
            # with entity churn); enter/stay already advanced above
            _enter, _stay, leave = cq.advance_entities(held, seen_keys)
            for key in leave:
                if self._state[key].firing:
                    self.stats["nresolved"] += 1
                del self._state[key]

            self._emit(ad, group, now, notified)
        notified.extend(self.flush_groups(now))
        return notified

    # -------------------------------------------------- grouping/routing
    def _route(self, ad: AlertDef, group: list) -> None:
        for act in ad.actions:
            fn = self.actions.get(act)
            if fn is not None:
                fn(group)

    def _emit(self, ad: AlertDef, group: list, now: float,
              notified: list) -> None:
        if not group:
            return
        self.stats["nfired"] += len(group)
        if ad.groupwaitsec > 0:
            g = self._groups.get(ad.name)
            if g is None:
                # group opens with its first alert; the wait clock starts
                self._groups[ad.name] = [now + ad.groupwaitsec,
                                         list(group)]
            else:
                g[1].extend(group)
            return
        notified.extend(group)
        self._route(ad, group)

    def flush_groups(self, now: Optional[float] = None) -> list:
        """Emit groups whose wait window expired → flushed alerts."""
        now = self._clock() if now is None else now
        out: list = []
        for name in list(self._groups):
            deadline, alerts = self._groups[name]
            if now < deadline:
                continue
            del self._groups[name]
            ad = self.defs.get(name)
            if ad is None:
                continue
            self.stats["ngroups_flushed"] += 1
            out.extend(alerts)
            self._route(ad, alerts)
        return out

    # ---------------------------------------------------- db-mode check
    def check_db(self, history, now: Optional[float] = None) -> list:
        """Evaluate due db-mode defs as criteria-SQL over the history
        store (the MDB_ALERTDEF periodic path, ``server/gy_malerts.cc``):
        each def runs every ``querysec`` over its own lookback window;
        matched rows advance the same entity lifecycle as realtime defs.
        """
        now = self._clock() if now is None else now
        notified: list = []
        for ad in self.defs.values():
            if not ad.enabled or ad.mode != "db":
                continue
            due = self._next_db.get(ad.name, 0.0)
            if now < due:
                continue
            # window starts at the PREVIOUS eval time, not now-querysec:
            # tick-grain scheduling slip would otherwise leave a sliver
            # of history no eval ever covers
            tstart = self._last_db.get(ad.name, now - ad.querysec)
            self._next_db[ad.name] = now + ad.querysec
            self._last_db[ad.name] = now
            self.stats["ndbchecks"] += 1
            rows = history.query(ad.subsys, tstart, now, ad.filter)
            inhibited = self._inhibited(ad)
            group: list = []
            seen_keys = set()
            seen_entities = set()
            for row in rows:
                ent = _entity_key_of_row(row)
                if ent in seen_entities:
                    continue           # one alert per entity per eval
                seen_entities.add(ent)
                key = (ad.name, ent)
                seen_keys.add(key)
                es = self._state.get(key, _EntityState())
                nhits = es.nhits + 1
                firing = nhits >= ad.numcheckfor
                notify = (firing
                          and now - es.tlast_notify >= ad.repeataftersec)
                if notify and self._silenced_db(ad, now):
                    self.stats["nsilenced"] += 1
                    notify = False
                if notify and inhibited:
                    self.stats["ninhibited"] += 1
                    notify = False
                if notify:
                    group.append(Alert(
                        alertname=ad.name, severity=ad.severity,
                        subsys=ad.subsys, entity=ent, tfired=now,
                        labels=dict(ad.labels),
                        annotations=dict(ad.annotations),
                        row=dict(row)))
                    es = es._replace(tlast_notify=now)
                self._state[key] = es._replace(nhits=nhits, firing=firing)
            for key in [k for k in self._state
                        if k[0] == ad.name and k not in seen_keys]:
                if self._state[key].firing:
                    self.stats["nresolved"] += 1
                del self._state[key]
            self._emit(ad, group, now, notified)
        notified.extend(self.flush_groups(now))
        return notified

    def _silenced_db(self, ad: AlertDef, now: float) -> bool:
        """db-row silencing: alertname + time window only (history rows
        are presentation-domain; filter silences apply to realtime)."""
        for s in self.silences.values():
            if not (s.tstart <= now <= s.tend):
                continue
            if s.alertnames and ad.name not in s.alertnames:
                continue
            if s.filter:
                continue
            return True
        return False
