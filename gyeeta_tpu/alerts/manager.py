"""Alert manager: columnar realtime evaluation + lifecycle + routing.

One ``check()`` per 5s engine pass evaluates every enabled alertdef as a
criteria mask over its subsystem snapshot (the whole fleet in a handful of
vector ops — the tensor form of the reference's per-event RT_ALERT_VECS
walk, ``server/gy_malerts.cc:1869``), then advances per-entity lifecycle:

    pending (consecutive hits < numcheckfor) → firing → resolved

Silences and inhibits gate *notification*, not detection (matching the
reference: a silenced alert still tracks state, ``gy_alertmgr.cc:5117``).
Grouping batches notifications per (alertname, severity) within a check —
the degenerate group-wait window of the reference's ALERT_GROUP (:574)
under batch semantics. Actions are pluggable callables; "log" is built in
(EMAIL/SLACK/PAGERDUTY/WEBHOOK of ``gy_alertmgr.h:50`` register the same
way; network egress is deployment-specific).
"""

from __future__ import annotations

import collections
import time
from typing import Callable, NamedTuple, Optional

import numpy as np

from gyeeta_tpu.alerts.defs import AlertDef, Inhibit, Silence
from gyeeta_tpu.query import api, criteria


class Alert(NamedTuple):
    alertname: str
    severity: str
    subsys: str
    entity: str                  # svcid / hostid / flow key
    tfired: float
    labels: dict
    annotations: dict
    row: dict                    # snapshot row at fire time


class _EntityState(NamedTuple):
    nhits: int = 0
    firing: bool = False
    tlast_notify: float = -1e18


def _entity_key_of(subsys: str, cols: dict, i: int) -> str:
    for k in ("svcid", "hostid", "flowid"):
        if k in cols:
            return f"{k}={cols[k][i]}"
    return f"row={i}"


class AlertManager:
    MAX_LOG = 10_000     # bounded notification history (oldest dropped)

    def __init__(self, cfg, clock: Optional[Callable[[], float]] = None):
        self.cfg = cfg
        self.defs: dict[str, AlertDef] = {}
        self.silences: dict[str, Silence] = {}
        self.inhibits: dict[str, Inhibit] = {}
        self.alert_log: collections.deque = collections.deque(
            maxlen=self.MAX_LOG)
        self.actions: dict[str, Callable[[list], None]] = {
            "log": self.alert_log.extend,
        }
        self._state: dict[tuple, _EntityState] = {}
        self._trees: dict[str, object] = {}     # parsed filter cache
        self._clock = clock or time.time
        self.stats = {"nchecks": 0, "nfired": 0, "nsilenced": 0,
                      "ninhibited": 0, "nresolved": 0}

    # ------------------------------------------------------------- CRUD
    def add_def(self, d: dict | AlertDef) -> AlertDef:
        ad = d if isinstance(d, AlertDef) else AlertDef.from_json(d)
        self.defs[ad.name] = ad
        self._trees[f"def:{ad.name}"] = criteria.parse(ad.filter)
        return ad

    def delete_def(self, name: str) -> bool:
        self._state = {k: v for k, v in self._state.items()
                       if k[0] != name}
        self._trees.pop(f"def:{name}", None)
        return self.defs.pop(name, None) is not None

    def add_silence(self, d: dict | Silence) -> Silence:
        s = d if isinstance(d, Silence) else Silence.from_json(d)
        self.silences[s.name] = s
        if s.filter:
            self._trees[f"sil:{s.name}"] = criteria.parse(s.filter)
        return s

    def add_inhibit(self, d: dict | Inhibit) -> Inhibit:
        i = d if isinstance(d, Inhibit) else Inhibit.from_json(d)
        self.inhibits[i.name] = i
        return i

    def register_action(self, name: str, fn: Callable[[list], None]):
        self.actions[name] = fn

    # ------------------------------------------------------------ check
    def firing(self) -> list[tuple]:
        return [k for k, v in self._state.items() if v.firing]

    def _silenced(self, ad: AlertDef, cols, i, now) -> bool:
        for s in self.silences.values():
            if not (s.tstart <= now <= s.tend):
                continue
            if s.alertnames and ad.name not in s.alertnames:
                continue
            if s.filter:
                tree = self._trees.get(f"sil:{s.name}") \
                    or criteria.parse(s.filter)
                one = {k: np.asarray(v[i:i + 1]) for k, v in cols.items()}
                if not bool(criteria.evaluate(tree, one, ad.subsys)[0]):
                    continue
            return True
        return False

    def _inhibited(self, ad: AlertDef) -> bool:
        firing_names = {k[0] for k in self.firing()}
        for inh in self.inhibits.values():
            if ad.name in inh.target_alertnames and \
                    firing_names & set(inh.src_alertnames):
                return True
        return False

    def check(self, st, columns_fn=None) -> list[Alert]:
        """Evaluate all defs against live engine state → newly-notified
        alerts (grouped per def, routed to actions).

        ``columns_fn(subsys) -> (cols, mask)`` overrides the column source
        (the sharded runtime evaluates alerts on gathered readbacks)."""
        now = self._clock()
        self.stats["nchecks"] += 1
        notified: list[Alert] = []
        cols_cache: dict[str, tuple] = {}

        for ad in self.defs.values():
            if not ad.enabled:
                continue
            if ad.subsys not in cols_cache:
                cols_cache[ad.subsys] = (
                    columns_fn(ad.subsys) if columns_fn is not None
                    else api._COLUMNS_OF[ad.subsys](self.cfg, st))
            cols, base = cols_cache[ad.subsys]
            tree = self._trees.get(f"def:{ad.name}") \
                or criteria.parse(ad.filter)
            mask = base & criteria.evaluate(tree, cols, ad.subsys)
            hits = set(np.nonzero(mask)[0].tolist())

            inhibited = self._inhibited(ad)
            group: list[Alert] = []
            seen_keys = set()
            for i in sorted(hits):
                ent = _entity_key_of(ad.subsys, cols, i)
                key = (ad.name, ent)
                seen_keys.add(key)
                es = self._state.get(key, _EntityState())
                nhits = es.nhits + 1
                firing = nhits >= ad.numcheckfor
                notify = (firing
                          and now - es.tlast_notify >= ad.repeataftersec)
                if notify and self._silenced(ad, cols, i, now):
                    self.stats["nsilenced"] += 1
                    notify = False
                if notify and inhibited:
                    self.stats["ninhibited"] += 1
                    notify = False
                if notify:
                    row = {k: cols[k][i] for k in cols}
                    group.append(Alert(
                        alertname=ad.name, severity=ad.severity,
                        subsys=ad.subsys, entity=ent, tfired=now,
                        labels=dict(ad.labels),
                        annotations=dict(ad.annotations),
                        row={k: (v.item() if hasattr(v, "item") else v)
                             for k, v in row.items()}))
                    es = es._replace(tlast_notify=now)
                self._state[key] = es._replace(nhits=nhits, firing=firing)

            # entities that stopped matching resolve (and are dropped —
            # the state dict must not grow with entity churn)
            for key in [k for k in self._state
                        if k[0] == ad.name and k not in seen_keys]:
                if self._state[key].firing:
                    self.stats["nresolved"] += 1
                del self._state[key]

            if group:
                self.stats["nfired"] += len(group)
                notified.extend(group)
                for act in ad.actions:
                    fn = self.actions.get(act)
                    if fn is not None:
                        fn(group)
        return notified
