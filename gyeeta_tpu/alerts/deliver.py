"""Alert action delivery: webhook executor + preset payload shapes.

The reference's alertmgr routes grouped alerts to action agents —
EMAIL / SLACK / PAGERDUTY / WEBHOOK (``server/gy_alertmgr.h:50-58``) —
executed off the evaluation path by a dedicated action thread
(``alert_act_thread``, ``server/gy_alertmgr.cc:3465``). Same split
here: :class:`ActionDispatcher` owns ONE worker thread and a bounded
queue; alert evaluation only enqueues (never blocks on the network),
the worker does HTTP POST with retry/backoff, and overflow drops the
oldest batch (counted) rather than stalling ingest.

Everything is a webhook underneath: ``slack``, ``email`` and
``pagerduty`` are payload presets over the same executor (the
reference's EMAIL/SLACK agents are likewise thin shapers over a
delivery channel). Templates are ``str.format`` over the group's
fields — no engine dependency.
"""

from __future__ import annotations

import json
import queue
import threading
import time
import urllib.error
import urllib.request
from typing import Optional

_DEF_TIMEOUT = 5.0
_DEF_RETRIES = 2
_DEF_BACKOFF = 0.5
_MAX_QUEUE = 256

ACTION_TYPES = ("webhook", "slack", "email", "pagerduty")


class ActionConfig:
    """One configured action (CRUD objtype "action")."""

    def __init__(self, name: str, atype: str = "webhook",
                 url: str = "", method: str = "POST",
                 timeout_s: float = _DEF_TIMEOUT,
                 retries: int = _DEF_RETRIES,
                 backoff_s: float = _DEF_BACKOFF,
                 headers: Optional[dict] = None,
                 template: str = ""):
        if atype not in ACTION_TYPES:
            raise ValueError(f"action type must be one of {ACTION_TYPES}")
        if not url:
            raise ValueError("action needs a url")
        self.name = name
        self.atype = atype
        self.url = url
        self.method = method
        self.timeout_s = float(timeout_s)
        self.retries = int(retries)
        self.backoff_s = float(backoff_s)
        self.headers = dict(headers or {})
        self.template = template

    @classmethod
    def from_json(cls, d: dict) -> "ActionConfig":
        return cls(name=d["name"], atype=d.get("type", "webhook"),
                   url=d.get("url", ""), method=d.get("method", "POST"),
                   timeout_s=d.get("timeout_s", _DEF_TIMEOUT),
                   retries=d.get("retries", _DEF_RETRIES),
                   backoff_s=d.get("backoff_s", _DEF_BACKOFF),
                   headers=d.get("headers"),
                   template=d.get("template", ""))


def _group_summary(group: list) -> dict:
    first = group[0]
    return {
        "alertname": first.alertname,
        "severity": first.severity,
        "subsys": first.subsys,
        "nalerts": len(group),
        "entities": [a.entity for a in group[:16]],
    }


def _render(template: str, group: list) -> str:
    s = _group_summary(group)
    default = (f"[{s['severity']}] {s['alertname']}: {s['nalerts']} "
               f"alert(s) on {s['subsys']}")
    if not template:
        return default
    try:
        return template.format(**s)
    except Exception:     # noqa: BLE001 — template is operator input;
        return default    # any format failure falls back, never raises


def build_payload(cfg: ActionConfig, group: list) -> bytes:
    """Grouped alerts → the action type's wire shape."""
    alerts = [{
        "alertname": a.alertname, "severity": a.severity,
        "subsys": a.subsys, "entity": a.entity, "tfired": a.tfired,
        "labels": a.labels, "annotations": a.annotations,
        "row": {k: (v if isinstance(v, (int, float, str, bool))
                    or v is None else str(v))
                for k, v in a.row.items()},
    } for a in group]
    if cfg.atype == "slack":
        obj = {"text": _render(cfg.template, group),
               "attachments": [{"fields": alerts}]}
    elif cfg.atype == "email":
        s = _group_summary(group)
        obj = {"subject": f"[{s['severity']}] {s['alertname']} "
                          f"({s['nalerts']} alerts)",
               "body": _render(cfg.template, group),
               "alerts": alerts}
    elif cfg.atype == "pagerduty":
        s = _group_summary(group)
        obj = {"event_action": "trigger",
               "payload": {"summary": _render(cfg.template, group),
                           "severity": s["severity"],
                           "source": s["subsys"],
                           "custom_details": {"alerts": alerts}}}
    else:
        obj = {"status": "firing",
               "groupSummary": _group_summary(group),
               "alerts": alerts}
    return json.dumps(obj).encode()


class ActionDispatcher:
    """One worker thread delivering queued (config, group) batches."""

    def __init__(self):
        self._q: queue.Queue = queue.Queue(maxsize=_MAX_QUEUE)
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.stats = {"delivered": 0, "failed": 0, "retries": 0,
                      "dropped": 0}
        # in-flight accounting (enqueue→finished) for a race-free
        # drain(): an Event set on queue-empty can fire between a
        # worker's get() timeout and a concurrent enqueue
        self._pending = 0
        self._cv = threading.Condition()

    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="gyt-alert-actions", daemon=True)
            self._thread.start()

    def enqueue(self, cfg: ActionConfig, group: list) -> None:
        """Never blocks evaluation: on overflow the OLDEST batch drops
        (freshest alerts win — the reference likewise sheds when its
        action queue backs up)."""
        self._ensure_thread()
        with self._cv:
            self._pending += 1
        try:
            self._q.put_nowait((cfg, group))
            return
        except queue.Full:
            pass
        removed = 0
        try:
            self._q.get_nowait()      # shed the OLDEST batch
            removed = 1
        except queue.Empty:
            pass
        added = True
        try:
            self._q.put_nowait((cfg, group))
        except queue.Full:
            added = False
        lost = removed + (0 if added else 1)
        with self._cv:
            self.stats["dropped"] += lost
            self._pending -= lost
            self._cv.notify_all()

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                cfg, group = self._q.get(timeout=0.25)
            except queue.Empty:
                continue
            try:
                self._deliver(cfg, group)
            except Exception:  # noqa: BLE001 — a poison batch (bad
                # config/payload) must not kill the worker; count it
                # as failed and keep draining
                self.stats["failed"] += 1
            finally:
                with self._cv:
                    self._pending -= 1
                    self._cv.notify_all()

    def _deliver(self, cfg: ActionConfig, group: list) -> None:
        body = build_payload(cfg, group)
        headers = {"Content-Type": "application/json", **cfg.headers}
        for attempt in range(cfg.retries + 1):
            try:
                req = urllib.request.Request(
                    cfg.url, data=body, headers=headers,
                    method=cfg.method)
                with urllib.request.urlopen(
                        req, timeout=cfg.timeout_s) as resp:
                    if 200 <= resp.status < 300:
                        self.stats["delivered"] += 1
                        return
            except (urllib.error.URLError, OSError, ValueError):
                pass
            if attempt < cfg.retries:
                self.stats["retries"] += 1
                time.sleep(cfg.backoff_s * (2 ** attempt))
        self.stats["failed"] += 1

    def drain(self, timeout: float = 5.0) -> bool:
        """Wait until every enqueued batch has finished delivering
        (tests / orderly shutdown)."""
        deadline = time.monotonic() + timeout
        with self._cv:
            while self._pending > 0:
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._cv.wait(left)
        return True

    def close(self, timeout: float = 5.0) -> None:
        """Drain queued deliveries (bounded), then stop the worker —
        SIGTERM with alerts in flight must not silently lose them (the
        compose stop_grace_period exists for exactly this drain)."""
        if self._thread is not None and self._thread.is_alive():
            self.drain(timeout)
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
