"""Query-column providers over AlertManager state.

The reference serves alerts/alertdef/silences/inhibits as first-class
query subsystems from ALERTMGR's in-memory maps + shyama DB tables
(``server/gy_alertmgr.cc`` CRUD + ``gy_json_field_maps.h``
SUBSYS_ALERTS..SUBSYS_INHIBITS). Here the same four subsystems read the
AlertManager directly: the fired-alert log (bounded deque), the def
map, silences, and inhibit rules — filtered/sorted/projected by the
ordinary query engine once expressed as numpy columns.
"""

from __future__ import annotations

import json

import numpy as np


def _obj(vals) -> np.ndarray:
    out = np.empty(len(vals), object)
    out[:] = vals
    return out


def alerts_columns(mgr, names=None):
    """Fired-alert log, newest first (SUBSYS_ALERTS)."""
    log = list(mgr.alert_log)[::-1]
    cols = {
        "tfired": np.array([a.tfired for a in log], np.float64),
        "alertname": _obj([a.alertname for a in log]),
        "severity": _obj([a.severity for a in log]),
        "subsys": _obj([a.subsys for a in log]),
        "entity": _obj([a.entity for a in log]),
        "labels": _obj([json.dumps(dict(a.labels)) for a in log]),
        "annotations": _obj([json.dumps(dict(a.annotations))
                             for a in log]),
    }
    return cols, np.ones(len(log), bool)


def alertdef_columns(mgr, names=None):
    defs = sorted(mgr.defs.values(), key=lambda d: d.name)
    firing = mgr.firing()
    nfiring = {d.name: 0 for d in defs}
    for key in firing:
        if key[0] in nfiring:
            nfiring[key[0]] += 1
    cols = {
        "alertname": _obj([d.name for d in defs]),
        "subsys": _obj([d.subsys for d in defs]),
        "filter": _obj([d.filter for d in defs]),
        "severity": _obj([d.severity for d in defs]),
        "mode": _obj([d.mode for d in defs]),
        "numcheckfor": np.array([d.numcheckfor for d in defs], np.float64),
        "repeataftersec": np.array([d.repeataftersec for d in defs],
                                   np.float64),
        "querysec": np.array([d.querysec for d in defs], np.float64),
        "groupwaitsec": np.array([d.groupwaitsec for d in defs],
                                 np.float64),
        "enabled": np.array([d.enabled for d in defs], bool),
        "nfiring": np.array([nfiring[d.name] for d in defs], np.float64),
    }
    return cols, np.ones(len(defs), bool)


def silences_columns(mgr, names=None, now=None):
    now = mgr._clock() if now is None else now
    sils = sorted(mgr.silences.values(), key=lambda s: s.name)
    cols = {
        "name": _obj([s.name for s in sils]),
        "filter": _obj([s.filter or "" for s in sils]),
        "alertnames": _obj([",".join(s.alertnames) for s in sils]),
        "tstart": np.array([s.tstart for s in sils], np.float64),
        "tend": np.array([min(s.tend, 1e18) for s in sils], np.float64),
        "active": np.array([s.tstart <= now <= s.tend for s in sils],
                           bool),
    }
    return cols, np.ones(len(sils), bool)


def actions_columns(mgr, names=None):
    """Registered alert actions + how many defs route to each
    (SUBSYS_ACTIONS; ref actionstbl + NODE_ACTION_SOCK routing)."""
    acts = sorted(mgr.actions)
    ndefs = {a: 0 for a in acts}
    for d in mgr.defs.values():
        for a in d.actions:
            if a in ndefs:
                ndefs[a] += 1
    cfgs = mgr.action_cfgs

    def redact(url: str) -> str:
        """scheme+host only: webhook paths ARE bearer secrets (Slack /
        PagerDuty incoming-webhook URLs) and the actions subsystem is
        readable by any query client."""
        from urllib.parse import urlsplit
        try:
            p = urlsplit(url)
            # no parseable host ⇒ show NOTHING (a schemeless
            # "host/path-secret" string would leak whole)
            return f"{p.scheme}://{p.netloc}/…" if p.netloc else ""
        except ValueError:
            return ""

    cols = {"name": _obj(acts),
            "type": _obj(["builtin" if a not in cfgs
                          else cfgs[a].atype for a in acts]),
            "target": _obj(["" if a not in cfgs
                            else redact(cfgs[a].url) for a in acts]),
            "ndefs": np.array([float(ndefs[a]) for a in acts])}
    return cols, np.ones(len(acts), bool)


def inhibits_columns(mgr, names=None):
    inhs = sorted(mgr.inhibits.values(), key=lambda i: i.name)
    firing_names = {k[0] for k in mgr.firing()}
    cols = {
        "name": _obj([i.name for i in inhs]),
        "srcalerts": _obj([",".join(i.src_alertnames) for i in inhs]),
        "targetalerts": _obj([",".join(i.target_alertnames)
                              for i in inhs]),
        "active": np.array(
            [bool(firing_names & set(i.src_alertnames)) for i in inhs],
            bool),
    }
    return cols, np.ones(len(inhs), bool)
