"""Alert/silence/inhibit definitions (the CRUD payloads).

Field names follow the reference's alertdef JSON (``common/gy_alerts.cc``
parse; shyama CRUD ``CRUD_ALERT_JSON`` path): ``alertname``, ``subsys``,
``filter`` (criteria string), ``severity``, ``numcheckfor`` (consecutive
5s checks before firing), ``repeataftersec`` (re-notification holdoff),
``action`` names, ``annotations``/``labels`` templates.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

from gyeeta_tpu.query import criteria, fieldmaps

SEVERITIES = ("info", "warning", "critical")


ALERT_MODES = ("realtime", "db")


class AlertDef(NamedTuple):
    name: str
    subsys: str
    filter: str
    severity: str = "warning"
    numcheckfor: int = 1          # consecutive matching checks to fire
    repeataftersec: float = 300.0  # holdoff before re-notifying an entity
    actions: tuple = ("log",)
    labels: tuple = ()             # ((key, value), ...) — immutable
    annotations: tuple = ()
    enabled: bool = True
    # mode "realtime": evaluated on the live snapshot every 5s check
    # mode "db": evaluated as periodic criteria-SQL over the history
    # store (ref MDB_ALERTDEF periodic queries, server/gy_malerts.cc) —
    # ``querysec`` is both the evaluation period and the lookback window
    mode: str = "realtime"
    querysec: float = 300.0
    # notification group-wait: alerts buffer for this many seconds after
    # the group opens, then emit as one batch (ref ALERT_GROUP
    # group-wait windows, server/gy_alertmgr.h:574). 0 = immediate.
    groupwaitsec: float = 0.0
    # realtime defs only: evaluate against the time-travel tier's
    # WINDOWED per-entity aggregate over this duration ("15m", "1h",
    # seconds) instead of the live snapshot — "alert when the 15m mean
    # error rate exceeds X". Needs history shards (hist_shard_dir);
    # checks are skipped (counted) until the first window exists.
    window: str = ""

    def validate(self) -> "AlertDef":
        """Definition-time checks shared by the JSON and direct-
        instance paths (``AlertManager.add_def`` runs this for BOTH):
        a typo'd subsys fails here with the valid-subsystem list, and
        a filter whose criteria target a different subsystem fails
        here too — at evaluation such criteria are skipped (all-pass),
        so the def would otherwise match every row, surfacing only at
        the first fold-time check."""
        fieldmaps.check_subsys(self.subsys)
        tree = criteria.parse(self.filter)
        if tree is None:
            raise ValueError("alertdef filter must be non-empty")
        criteria.check_filter_subsys(tree, self.subsys,
                                     what=f"alertdef {self.name!r}")
        if self.window:
            from gyeeta_tpu.history.timeview import parse_dur
            try:
                dur = parse_dur(self.window)
            except ValueError:
                raise ValueError(
                    f"alertdef {self.name!r}: bad window "
                    f"{self.window!r} (use seconds or 15m/2h/1d)")
            if dur <= 0:
                raise ValueError(
                    f"alertdef {self.name!r}: window must be positive")
            if self.mode != "realtime":
                raise ValueError(
                    f"alertdef {self.name!r}: window applies to "
                    "realtime defs (db defs window via querysec)")
        return self

    @classmethod
    def from_json(cls, d: dict) -> "AlertDef":
        if "alertname" not in d or "subsys" not in d or "filter" not in d:
            raise ValueError("alertdef needs alertname/subsys/filter")
        sev = d.get("severity", "warning")
        if sev not in SEVERITIES:
            raise ValueError(f"severity must be one of {SEVERITIES}")
        mode = d.get("mode", "realtime")
        if mode not in ALERT_MODES:
            raise ValueError(f"mode must be one of {ALERT_MODES}")
        return cls(
            name=d["alertname"], subsys=d["subsys"], filter=d["filter"],
            severity=sev,
            numcheckfor=max(1, int(d.get("numcheckfor", 1))),
            repeataftersec=float(d.get("repeataftersec", 300.0)),
            actions=cls._actions_of_json(d),
            labels=tuple(sorted(dict(d.get("labels", {})).items())),
            annotations=tuple(sorted(dict(d.get("annotations", {}))
                                     .items())),
            enabled=bool(d.get("enabled", True)),
            mode=mode,
            querysec=max(1.0, float(d.get("querysec", 300.0))),
            groupwaitsec=max(0.0, float(d.get("groupwaitsec", 0.0))),
            window=str(d.get("window", "") or ""),
        ).validate()

    @staticmethod
    def _actions_of_json(d: dict) -> tuple:
        # 'action'/'actions', string or list — a bare string must wrap,
        # never iterate into per-character "names"
        acts = d.get("action", d.get("actions", ("log",)))
        return (acts,) if isinstance(acts, str) else tuple(acts)


class Silence(NamedTuple):
    """Mute alerts matching ``filter`` between tstart and tend
    (ref silences: ``server/gy_alertmgr.cc:5117`` is_alert_silenced)."""
    name: str
    filter: Optional[str] = None       # None = match all
    alertnames: tuple = ()             # () = any alert
    tstart: float = 0.0
    tend: float = float("inf")

    @classmethod
    def from_json(cls, d: dict) -> "Silence":
        return cls(name=d["name"], filter=d.get("filter"),
                   alertnames=tuple(d.get("alertnames", ())),
                   tstart=float(d.get("tstart", 0.0)),
                   tend=float(d.get("tend", float("inf"))))


class Inhibit(NamedTuple):
    """While any alert matching ``src_alertnames`` fires, suppress alerts
    in ``target_alertnames`` (ref: ``gy_alertmgr.cc:5200``)."""
    name: str
    src_alertnames: tuple
    target_alertnames: tuple

    @classmethod
    def from_json(cls, d: dict) -> "Inhibit":
        return cls(name=d["name"],
                   src_alertnames=tuple(d["src_alertnames"]),
                   target_alertnames=tuple(d["target_alertnames"]))
