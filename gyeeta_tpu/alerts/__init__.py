"""Alerting: definitions, realtime evaluation, silences/inhibits/grouping.

Mirrors the reference's two-tier alert architecture — per-madhava realtime
evaluation of alert definitions against live state (``server/gy_malerts.cc``
MRT_ALERTDEF + RT_ALERT_VECS) and the central shyama ALERTMGR
(``server/gy_alertmgr.cc``: silences :5117, inhibits :5200, grouping :574,
actions :50) — collapsed into one manager: criteria masks evaluate
columnar over whole snapshots (every service in one vector op), and the
alert lifecycle (consecutive-hit counts, firing, notification routing)
runs host-side as control plane.
"""

from gyeeta_tpu.alerts.defs import AlertDef, Silence, Inhibit
from gyeeta_tpu.alerts.manager import AlertManager, Alert

__all__ = ["AlertDef", "Silence", "Inhibit", "AlertManager", "Alert"]
