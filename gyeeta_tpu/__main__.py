from gyeeta_tpu.cli import main

main()
