from gyeeta_tpu.cli import main

# the __name__ guard matters: the GYT_QUERY_PROCS render pool uses a
# spawn-context ProcessPoolExecutor, and spawn re-imports the parent's
# main module in the child (as "__mp_main__") — an unguarded main()
# would re-run the CLI inside every pool worker
if __name__ == "__main__":
    main()
