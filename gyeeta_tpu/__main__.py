from gyeeta_tpu.server_main import main

main()
