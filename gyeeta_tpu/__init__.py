"""gyeeta_tpu — TPU-native observability aggregation framework.

A brand-new JAX/XLA implementation of the capabilities of Gyeeta
(https://github.com/Gyeeta/gyeeta): per-host agents stream flow/service/process
telemetry over a length-prefixed binary wire format; the aggregation tiers
(reference: madhava ``server/gy_mconnhdlr.cc`` and shyama
``server/gy_shconnhdlr.cc`` CPU loops) are replaced by device-resident
streaming-sketch state — Count-Min, HyperLogLog, log-bucketed histograms,
t-digest, top-K — updated in jitted microbatches and rolled up across a
``jax.sharding.Mesh`` with XLA collectives (``psum``/``pmax``/``all_to_all``).

Layout:
    utils/     hashing, time windows, field maps        (ref: common/ L1)
    sketch/    device sketch kernels + exact CPU refs   (ref: gy_statistics.h)
    ingest/    wire format, C++ deframer, columnar decode (ref: gy_comm_proto)
    sim/       synthetic partha agent simulator          (ref: test_multi_partha)
    engine/    AggState pytree + jitted update step      (ref: MCONN_HANDLER L2)
    parallel/  mesh, psum roll-ups, all_to_all routing   (ref: SHCONN_HANDLER)
    semantic/  service/host health classifiers           (ref: get_curr_state)
    query/     criteria filters + JSON query API         (ref: gy_query_common)
    alerts/    alert defs, manager, silences/grouping    (ref: gy_alertmgr)
"""

from gyeeta_tpu.version import __version__

__all__ = ["__version__"]
