"""Runtime: the aggregation-server process loop (the ``madhava`` analogue).

Owns the engine state and composes every tier: byte streams in (native
deframe), columnar folds onto the device, the 5s cadence (window tick +
semantic classify + alert check), history snapshots, checkpointing, and
table compaction — the role of madhava's L1/L2 thread architecture and
scheduler domains (``server/gy_mconnhdlr.h:53-75``,
``common/gy_scheduler.h:220``), but single-controller and event-driven:
``feed()`` ingests bytes; ``run_tick()`` closes a 5s window. No thread
pool — the device pipeline is the concurrency.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from gyeeta_tpu.alerts import AlertManager
from gyeeta_tpu.engine import aggstate, compact, step
from gyeeta_tpu.engine.aggstate import EngineCfg
from gyeeta_tpu.history import open_store
from gyeeta_tpu.obs import health as obs_health
from gyeeta_tpu.obs.spans import FoldProfiler, SpanTracer
from gyeeta_tpu.parallel import depgraph as dg
from gyeeta_tpu.ingest import decode, native, wire
from gyeeta_tpu.query import api
from gyeeta_tpu.semantic import derive
from gyeeta_tpu.utils import checkpoint as ckpt
from gyeeta_tpu.utils import dnsmap as _dnsmap
from gyeeta_tpu.utils.config import RuntimeOpts
from gyeeta_tpu.utils.intern import InternTable
from gyeeta_tpu.utils.selfstats import Stats


# a native resp stream is "live" for bridge-suppression purposes if it
# reported within this many base ticks (2 min at 5s)
_RESP_FRESH_TICKS = 24


_JIT_MEMO: dict = {}


def _memo_jit(key: tuple, make):
    """Process-wide compiled-function memo. Every Runtime used to
    build its own ``jax.jit`` wrappers (fresh lambdas → zero cache
    reuse), so each construction re-traced AND re-compiled the whole
    fold family — seconds per instance, minutes across a test suite
    that builds dozens of runtimes with identical geometry. The
    compiled functions are pure (donation included — they never hold
    instance state), so instances with the same key share them. Every
    value the jitted closure captures MUST be part of the key (the
    EngineCfg tuple, relevant RuntimeOpts fields, section-presence
    names)."""
    fn = _JIT_MEMO.get(key)
    if fn is None:
        fn = make()
        _JIT_MEMO[key] = fn
    return fn


def snap_pingpong_enabled(env=None) -> bool:
    """Snapshot ping-pong prototype (ROADMAP query item (a)): donate
    the retired (N-2) snapshot's buffers back as the next tree-copy's
    destination. Measured ~12x cheaper publish at the 32k geometry on
    the 0.4.37 CPU backend (bench.py ``snap_pingpong`` row — the plain
    copy pays full-state alloc+free every publish). Default OFF
    because the win has a sharp edge: on CPU the merged-column renders
    are ZERO-COPY numpy views of snapshot buffers, so an off-tick
    consumer (history writer queue, alert delivery) more than two
    ticks behind could still hold views of the N-2 snapshot when its
    buffers are donated — reading reused memory SILENTLY. The refcount
    guard in :func:`snapshot_copy` protects the snapshot OBJECT only,
    not loose views. Enable when those consumers provably drain within
    the tick (OPERATIONS.md "Fleet-scale deployment")."""
    env = os.environ if env is None else env
    return str(env.get("GYT_SNAP_PINGPONG", "0")).strip().lower() \
        in ("1", "true", "yes")


def make_pingpong_copy():
    """The donating tree-copy: output buffers may alias the retired
    snapshot's leaves (same shapes/dtypes every publish).
    ``keep_unused`` keeps the donated pytree in the compiled signature
    — jax would otherwise prune the unused arg and donation could
    never alias."""
    return jax.jit(lambda old, t: jax.tree.map(jnp.copy, t),
                   donate_argnums=(0,), keep_unused=True)


def snapshot_copy(rt, tree):
    """(state, dep) copy for snapshot publication. With ping-pong on,
    the N-2 snapshot — retired at the LAST publish and provably
    unreferenced now (refcount guard: queries in flight still hold the
    object if any are reading it) — donates its buffers as the copy's
    destination. Counted either way (``gyt_snapshot_pingpong_*``) so
    the hit rate is observable."""
    import sys as _sys

    old = getattr(rt, "_snap_old", None)
    rt._snap_old = None
    pp = getattr(rt, "_snap_copy_pp", None)
    if pp is None:
        return rt._snap_copy(tree)
    if old is not None and _sys.getrefcount(old) == 2:
        try:
            out = pp((old.state, old.dep), tree)
            rt.stats.bump("snapshot_pingpong_donations")
            return out
        except Exception:              # noqa: BLE001 — prototype guard
            rt.stats.bump("snapshot_pingpong_errors")
            return rt._snap_copy(tree)
    rt.stats.bump("snapshot_pingpong_fallbacks")
    return rt._snap_copy(tree)


def fused_fold_enabled(env=None) -> bool:
    """The fused ``fold_all`` megakernel is the default fold path;
    ``GYT_FUSED_FOLD=0`` selects the legacy per-subsystem dispatch
    sequence (the escape hatch — kept selectable and parity-tested,
    tests/test_fusedfold.py)."""
    env = os.environ if env is None else env
    return str(env.get("GYT_FUSED_FOLD", "1")).strip().lower() \
        not in ("0", "false", "no")


def _slab_lanes(env=None) -> dict:
    """Per-subsystem staging-slab lane capacities of the fused fold
    slab (fixed → one compiled shape per presence combination). Sized
    at 1-2 wire-max batches per section: sweep subsystems arrive at 5s
    cadence, so a deeper slab only adds padding cost to the fused
    dispatch. ``GYT_SLAB_<KIND>_LANES`` overrides (OPERATIONS.md
    "Fold-path tuning")."""
    env = os.environ if env is None else env
    base = {
        "listener": 2 * wire.MAX_LISTENERS_PER_BATCH,
        "host": wire.MAX_HOSTS_PER_BATCH,
        "task": 2 * wire.MAX_TASKS_PER_BATCH,
        "cpumem": wire.MAX_CPUMEM_PER_BATCH,
        "trace": wire.MAX_TRACE_PER_BATCH,
        "ping": wire.MAX_PINGS_PER_BATCH,
        # SKETCH_DELTA records per dispatch (each expands into its
        # per-family payload lanes host-side); must stay >= the
        # drain_chunks chunk size (decode.DELTA_LANES_DEFAULT)
        "delta": decode.DELTA_LANES_DEFAULT,
    }
    lanes = {k: int(env.get(f"GYT_SLAB_{k.upper()}_LANES", v))
             for k, v in base.items()}
    lanes["delta"] = max(lanes["delta"], decode.DELTA_LANES_DEFAULT)
    return lanes


# fused-slab section plumbing: selfstats counter, wire subtype (for the
# raw-backlog concat dtype) and columnar builder per device-fold kind
_SECTION_COUNTERS = {
    "listener": "listener_records", "host": "host_records",
    "task": "task_records", "ping": "task_pings",
    "cpumem": "cpumem_records", "trace": "trace_records",
    "delta": "preagg_delta_records",
}
_SECTION_SUBTYPES = {
    "listener": wire.NOTIFY_LISTENER_STATE, "host": wire.NOTIFY_HOST_STATE,
    "task": wire.NOTIFY_AGGR_TASK_STATE, "ping": wire.NOTIFY_TASK_PING,
    "cpumem": wire.NOTIFY_CPU_MEM_STATE, "trace": wire.NOTIFY_REQ_TRACE,
    "delta": wire.NOTIFY_SKETCH_DELTA,
}
_SECTION_BUILDERS = {
    "listener": lambda r, sz, st: decode.listener_batch_fast(r, sz,
                                                             stats=st),
    "host": lambda r, sz, st: decode.host_batch_fast(r, sz, stats=st),
    "task": lambda r, sz, st: decode.task_batch_fast(r, sz, stats=st),
    "ping": lambda r, sz, st: decode.ping_batch(r, sz, stats=st),
    "cpumem": lambda r, sz, st: decode.cpumem_batch_fast(r, sz, stats=st),
    "trace": lambda r, sz, st: decode.trace_batch(r, sz),
}


class Runtime:
    def __init__(self, cfg: Optional[EngineCfg] = None,
                 opts: Optional[RuntimeOpts] = None,
                 clock=None):
        self.cfg = cfg or EngineCfg()
        self.opts = opts or RuntimeOpts()
        self.state = aggstate.init(self.cfg)
        self.stats = Stats()
        # pipeline span ring + opt-in device-trace bracket (obs tier)
        self.spans = SpanTracer()
        self._profiler = FoldProfiler()
        self.alerts = AlertManager(self.cfg, clock=clock)
        self.history = (open_store(self.opts.history_db)
                        if self.opts.history_db else None)
        # batched single-writer thread: run_tick renders snapshot rows
        # (device readbacks stay on the fold thread) and ENQUEUES; a
        # slow sqlite/pg write can no longer stall the tick loop.
        # Read paths that need read-your-writes (db-mode alertdefs,
        # historical SQL queries) call barrier() first.
        self._histwriter = None
        if self.history is not None:
            from gyeeta_tpu.history.histwriter import HistoryWriter
            self._histwriter = HistoryWriter(
                self.history, stats=self.stats,
                max_queue=self.opts.history_queue_max)
        self._clock = clock or time.time
        # write-ahead event journal (utils/journal.py): every accepted
        # event-stream chunk appends post-validation/pre-fold; recovery
        # re-folds from the checkpoint's recorded position (bounds data
        # loss to the last group fsync, not the last checkpoint)
        self.journal = None
        if self.opts.journal_dir:
            from gyeeta_tpu.utils.journal import Journal
            self.journal = Journal(
                self.opts.journal_dir,
                segment_max_bytes=self.opts.journal_segment_mb << 20,
                fsync_bytes=self.opts.journal_fsync_kb << 10,
                fsync_ms=self.opts.journal_fsync_ms,
                backlog_max_bytes=self.opts.journal_backlog_mb << 20,
                stats=self.stats, clock=clock)
        self._journal_replaying = False
        # time-travel query tier (history/timeview.py): at=/window=
        # requests materialize compaction shards into transient engine
        # snapshots served through the unchanged query path. The
        # journal truncate floor starts at the compactor's durable
        # position so checkpoints never delete unconsumed segments.
        self.timeview = None
        if self.opts.hist_shard_dir:
            from gyeeta_tpu.history.shards import open_shard_store
            from gyeeta_tpu.history.timeview import TimeView
            store = open_shard_store(self.opts.hist_shard_dir,
                                     stats=self.stats)
            self.timeview = TimeView(self, store, clock=clock)
            if self.journal is not None:
                pos = store.position()
                if pos:
                    from gyeeta_tpu.utils.journal import floors_of
                    fl = floors_of(pos)
                    if isinstance(fl, list) \
                            and not hasattr(self.journal, "shards"):
                        # per-shard floors against a flat journal
                        # (layout drift): hold back at the lowest
                        fl = min(fl) if fl else 0
                    self.journal.set_truncate_floor(fl)
                else:
                    self.journal.set_truncate_floor(0)
        # per-host sweep-seq high-water marks (NOTIFY_SWEEP_SEQ): the
        # WAL dedup state — checkpointed, rebuilt by replay, echoed to
        # reconnecting agents so resend + replay never double-counts
        self._sweep_last_seq: dict[int, int] = {}
        self._tick_no = 0             # host-side mirror of the window tick
        self._pending = b""           # partial-frame resume buffer
        # conn/resp hot path stages RAW record arrays; decode happens
        # once per K-slab (one native columnar pass, free reshape into
        # the stacked layout) instead of per chunk + np.stack
        self._conn_raw: list = []
        self._resp_raw: list = []
        self._n_conn_raw = 0
        self._n_resp_raw = 0
        # last tick each host sent a native RESP_SAMPLE: the trace→resp
        # bridge skips hosts with a RECENT native stream (per-host
        # precedence — no steady-state double counting when a host
        # sends both; a dead resp stream un-suppresses after
        # _RESP_FRESH_TICKS). Startup transient: trace frames arriving
        # before the host's first resp frame are bridged and may
        # overlap the first native window — bounded by one window.
        self._host_resp_tick = np.full(self.cfg.n_hosts, -(10 ** 9),
                                       np.int64)
        self._td_dirty = False        # digest stage may be non-empty
        from gyeeta_tpu.utils.colcache import ColumnCache
        self._cols = ColumnCache()    # version-keyed snapshot memo
        # every state→state jit donates its input: without donation XLA
        # copies the whole AggState per call — 3 GiB ≈ 2 s/dispatch at
        # north-star geometry (the r4 listener-sweep cost was exactly
        # this). self.state is always rebound to the result, so the
        # donated buffers are never read again.
        cfg = self.cfg
        mj = lambda tag, make, *extra: _memo_jit(  # noqa: E731
            (tag, cfg, *extra), make)
        self._fold = mj("fold", lambda: step.jit_fold_step(cfg))
        self._fold_lst = mj("lst", lambda: jax.jit(
            lambda s, b: step.ingest_listener(cfg, s, b),
            donate_argnums=(0,)))
        self._fold_host = mj("host", lambda: jax.jit(
            lambda s, b: step.ingest_host(cfg, s, b),
            donate_argnums=(0,)))
        self._fold_task = mj("task", lambda: jax.jit(
            lambda s, b: step.ingest_task(cfg, s, b),
            donate_argnums=(0,)))
        self._fold_ping = mj("ping", lambda: jax.jit(
            lambda s, b: step.ping_tasks(cfg, s, b),
            donate_argnums=(0,)))
        self._fold_cm = mj("cm", lambda: jax.jit(
            lambda s, b: step.ingest_cpumem(cfg, s, b),
            donate_argnums=(0,)))
        self._fold_trace = mj("trace", lambda: jax.jit(
            lambda s, b: step.ingest_trace(cfg, s, b),
            donate_argnums=(0,)))
        _api_age = self.opts.api_max_age_ticks
        self._age_apis = mj("age_apis", lambda: jax.jit(
            lambda s: step.age_apis(cfg, s, _api_age),
            donate_argnums=(0,)), _api_age)
        _task_age = self.opts.task_max_age_ticks
        self._age_tasks = mj("age_tasks", lambda: jax.jit(
            lambda s: step.age_tasks(cfg, s, _task_age),
            donate_argnums=(0,)), _task_age)
        self._compact_tasks = mj("compact_tasks", lambda: jax.jit(
            lambda s: step.compact_tasks(cfg, s),
            donate_argnums=(0,)))
        self._tick = mj("tick", lambda: jax.jit(
            lambda s: step.tick_5s(cfg, s), donate_argnums=(0,)))
        # device-health readback: every health scalar packed into ONE
        # small vector (no donation — it only reads), transferred once
        # per report cadence (tick / metrics scrape), never per event
        self._engine_health = mj("health", lambda: jax.jit(
            lambda s, d: step.engine_health_vec(cfg, s, d)))
        # digest flush: host-side pressure trigger + O(m) partial flush.
        # An in-graph lax.cond flush cost 110 ms/dispatch UNTAKEN at 65k
        # capacity (whole-stage copies at the cond boundary); the full
        # O(capacity) flush cost 6.2 s there. The pressure scalar from
        # dispatch N is checked (already materialized) before dispatch
        # N+1 — no pipeline sync on the hot path.
        self._td_flush_partial = mj("td_flush_partial", lambda: jax.jit(
            lambda s: step.td_flush_partial(cfg, s),
            donate_argnums=(0,)))
        self._stage_pressure = mj("stage_pressure", lambda: jax.jit(
            step.stage_pressure))
        # heavy-hitter recovery: decode the invertible buckets + exact
        # top-K lanes in ONE read-only dispatch (no donation — the
        # readback must not invalidate live state); memoized like every
        # other compiled program
        self._hh_recover = mj("hh_recover", lambda: jax.jit(
            lambda s: step.heavy_recover(cfg, s)))
        # snapshot publication (query/snapshot.py): ONE non-donating
        # jitted copy of (state, dep) per publish — jit outputs never
        # alias non-donated inputs, so the snapshot's buffers survive
        # every later donating fold (the double buffer: queries read
        # snapshot N on worker threads while the fold builds N+1)
        self._snap_copy = mj("snap_copy", lambda: jax.jit(
            lambda t: jax.tree.map(jnp.copy, t)))
        # GYT_SNAP_PINGPONG=1: donate the RETIRED snapshot's buffers as
        # the next copy's destination (ROADMAP query item (a) — halves
        # HBM churn per publish where the backend implements donation;
        # see snapshot_copy for the refcount guard and the 0.4.x/CPU
        # caveats, measured by bench.py's snap_pingpong phase)
        self._snap_pingpong = snap_pingpong_enabled()
        self._snap_copy_pp = mj("snap_copy_pp", make_pingpong_copy) \
            if self._snap_pingpong else None
        self._snap_old = None         # retired-snapshot donation pool
        self.snapshot = None          # last published EngineSnapshot
        self._snap_version = 0
        # host-side registry renders (snapshot aux views) run on query
        # worker threads; registry UPDATES stay on the serving loop —
        # this lock keeps dict/deque iteration away from concurrent
        # structural mutation (cheap: uncontended except at render)
        self._reg_lock = threading.RLock()
        # recovered-hot key set from the previous recovery: promotions
        # count keys NEWLY recovered at/above the hot threshold, so the
        # counter tracks churn into the top view, not steady residency
        self._hh_prev_hot: set = set()
        from collections import deque
        # pressure scalars from recent dispatches: checked at lag 2 so
        # the int() readback never blocks on an in-flight fold (lag 1
        # would serialize dispatch N+1's launch on N's completion)
        self._pressures: deque = deque()
        # dependency graph (single-shard slice; the sharded tier keeps its
        # own stacked DepGraph — see parallel/depgraph.py)
        self.dep = dg.init(self.opts.dep_pair_capacity,
                           self.opts.dep_edge_capacity)
        self._dep_step = mj("dep_step", lambda: jax.jit(
            dg.dep_step, donate_argnums=(0,)))
        # slab hot path: engine fold + dep fold in ONE dispatch — one
        # host→device transfer of the slab tree, one jit-call overhead,
        # and XLA can schedule the two independent folds together
        self._fold_many_dep = mj("fold_many_dep", lambda: jax.jit(
            lambda st, dep, cbs, rbs, tick: (
                step.fold_many(cfg, st, cbs, rbs),
                dg.dep_fold_many(dep, cbs, tick)),
            donate_argnums=(0, 1)))
        _pttl = self.opts.dep_pair_ttl_ticks
        _ettl = self.opts.dep_edge_ttl_ticks
        self._dep_age = mj("dep_age", lambda: jax.jit(
            lambda d, t: dg.age(d, t, _pttl, _ettl),
            donate_argnums=(0,)), _pttl, _ettl)
        # edge pre-aggregation fold (NOTIFY_SKETCH_DELTA): one donated
        # dispatch folding a DeltaBatch into state AND dep (legacy
        # path; the fused path folds deltas inside fold_all)
        self._fold_delta = mj("delta", lambda: jax.jit(
            lambda s, d, b, t: step.ingest_delta(cfg, s, d, b, t),
            donate_argnums=(0, 1)))
        # delta decode geometry: payload indices outside it are
        # dropped + counted at decode, never scattered out of range
        self._delta_dims = dict(
            resp_nbuckets=cfg.resp_spec.nbuckets,
            hll_m_svc=1 << cfg.hll_p_svc,
            hll_m_glob=1 << cfg.hll_p_global)
        # ---- fused fold path (the default; GYT_FUSED_FOLD=0 keeps the
        # legacy per-subsystem dispatch sequence above selectable) ----
        self._fused = fused_fold_enabled()
        self._slab_lanes_cfg = _slab_lanes()
        self._sect_builders = dict(_SECTION_BUILDERS)
        self._sect_builders["delta"] = \
            lambda r, sz, st: decode.delta_batch(r, sz, stats=st,
                                                 **self._delta_dims)
        # per-subsystem staging sections: raw record-array backlogs that
        # ride the NEXT fold_all dispatch (drained at the end of every
        # ingest_records call, so they never outlive a feed batch)
        self._stage_recs = {k: [] for k in self._slab_lanes_cfg}
        self._stage_n = {k: 0 for k in self._slab_lanes_cfg}
        # double-buffered conn/resp decode slabs: the idle buffer is
        # decoded into while the in-flight fold still owns (device
        # copies of) the other — host decode of batch N+1 overlaps
        # device fold of batch N (async dispatch + buffer flip)
        K = self.cfg.fold_k
        self._slab_bufs = [
            {"conn": decode.alloc_conn_cols(K * self.cfg.conn_batch),
             "resp": decode.alloc_resp_cols(K * self.cfg.resp_batch),
             "hw_conn": 0, "hw_resp": 0}
            for _ in range(2)]
        self._slab_active = 0
        # fold_all jit cache: one compiled variant per section-presence
        # combination (hot path = connresp-only; a 5s sweep batch adds
        # one "everything" variant)
        self._fold_all_jits: dict = {}
        self.names = InternTable()
        from gyeeta_tpu.utils.svcreg import SvcInfoRegistry
        from gyeeta_tpu.utils.hostreg import CgroupRegistry, \
            HostInfoRegistry, MountRegistry, NetIfRegistry
        from gyeeta_tpu.utils.natreg import NatClusterRegistry
        self.svcreg = SvcInfoRegistry()
        self.hostinfo = HostInfoRegistry()
        self.cgroups = CgroupRegistry()
        self.mounts = MountRegistry()
        self.netifs = NetIfRegistry()
        self.natclusters = NatClusterRegistry()
        from gyeeta_tpu.utils.traceconnreg import TraceConnRegistry
        self.traceconns = TraceConnRegistry()
        from gyeeta_tpu.utils.tagreg import TagRegistry
        self.tags = TagRegistry()
        from gyeeta_tpu.utils.dnsmap import DnsCache
        self.dns = DnsCache()
        from gyeeta_tpu.alerts import columns as AC
        from gyeeta_tpu.trace.defs import TraceDefs
        from gyeeta_tpu.utils.notifylog import NotifyLog
        self.notifylog = NotifyLog(clock=clock)
        self.tracedefs = TraceDefs(clock=clock)
        self._t_started = self._clock()
        self._aux = {
            "topk": self._topk_columns,
            "tracedef": lambda: self.tracedefs.columns(),
            "tracestatus": lambda: self.tracedefs.columns(),
            "traceuniq": self._traceuniq_columns,
            "traceconn": lambda: self.traceconns.columns(
                self.names, svc_task_ids=self._svc_task_ids()),
            "extactiveconn": lambda: self._ext_join("activeconn"),
            "extclientconn": lambda: self._ext_join("clientconn",
                                                    idcol="cliid"),
            "exttracereq": lambda: self._ext_join("tracereq"),
            "hostinfo": lambda: self.hostinfo.columns(self.names),
            "cgroupstate": lambda: self.cgroups.columns(self.names),
            "mountstate": lambda: self.mounts.columns(self.names),
            "netif": lambda: self.netifs.columns(self.names),
            "alerts": lambda: AC.alerts_columns(self.alerts),
            "alertdef": lambda: AC.alertdef_columns(self.alerts),
            "silences": lambda: AC.silences_columns(self.alerts),
            "inhibits": lambda: AC.inhibits_columns(self.alerts),
            "actions": lambda: AC.actions_columns(self.alerts),
            "notifymsg": lambda: self.notifylog.columns(self.names),
            "hostlist": self._hostlist_columns,
            "serverstatus": self._serverstatus_columns,
            "svcipclust": lambda: _dnsmap.annotate_vip_cols(
                self.natclusters.columns(self.names), self.dns),
            "tags": lambda: self.tags.columns(),
        }
        self._classify = derive.jit_classify_pass(self.cfg)

    # ------------------------------------------------------------- ingest
    def feed(self, buf: bytes, hid: int = 0, conn_id: int = 0) -> int:
        """Ingest a byte stream (any number of frames, any mix of types).

        Returns records accepted. Trailing partial frames are buffered for
        the next call (epoll partial-read resume semantics). ``hid`` /
        ``conn_id`` attribute the bytes in the write-ahead journal (the
        serving edge passes them; direct feeds default to 0).

        Hot-path discipline (the DB_WRITE_ARR batching of the reference,
        ``server/gy_mconnhdlr.h:350``): raw conn/resp record arrays are
        STAGED host-side as-is and, once ``cfg.fold_k`` microbatches'
        worth accumulate, decoded in one flat native columnar pass and
        dispatched through ``_fold_many_dep`` (engine fold + dep fold,
        flattened to a single (K·B,)-lane batch — no ``lax.scan``) —
        no device readbacks anywhere in this path. Partial backlogs stay
        staged until the next ``feed``/``flush()``; ``run_tick``/
        ``query`` flush first, so staged events are never invisible at a
        cadence or query boundary."""
        # no resume bytes pending (the common case): skip the big-buffer
        # bytes concat — at slab geometry it copies ~9MB per feed
        data = (self._pending + buf) if self._pending else buf
        try:
            with self.stats.timeit("deframe"), \
                    self.spans.span("deframe", nrec=len(data),
                                    path="native" if native.available()
                                    else "python"):
                recs, consumed, unknown = native.drain2(data)
        except wire.FrameError:
            self.stats.bump("frames_bad")
            self._pending = b""       # poison frame: drop buffer, resync
            raise
        self._pending = data[consumed:]
        # WAL append AFTER validation, BEFORE the fold: exactly the
        # bytes drain2 accepted (a pending partial frame journals in
        # the call that completes it — each byte exactly once). Replay
        # suppresses the append (chunks are already in the WAL).
        if (consumed and self.journal is not None
                and not self._journal_replaying):
            self.journal.append(data[:consumed], hid=hid,
                                conn_id=conn_id, tick=self._tick_no)
        if unknown:
            # skipped unknown-subtype frames (version skew / corrupted
            # subtype byte): accounted loss, never silent loss
            self.stats.bump("records_unknown_subtype", unknown)
        return self.ingest_records(recs)

    def ingest_records(self, recs: dict) -> int:
        """Fold a drained {subtype: record array} dict (the post-
        deframe half of :meth:`feed` — the feed pipeline's decode
        worker hands these over, ``ingest/pipeline.py``)."""
        n = 0
        # sweep-seq marks: advance the per-host high-water mark (max is
        # order-insensitive, so the concatenated drain order is fine)
        sw = recs.pop(wire.NOTIFY_SWEEP_SEQ, None)
        if sw is not None and len(sw):
            for h, s in zip(sw["host_id"].tolist(), sw["seq"].tolist()):
                if s > self._sweep_last_seq.get(h, 0):
                    self._sweep_last_seq[h] = s
            self.stats.bump("sweep_marks", len(sw))
            n += len(sw)
        # conn/resp hot path: stage the raw record arrays as-is — the
        # per-slab decode in _dispatch_slab is the only decode they get
        conn = recs.pop(wire.NOTIFY_TCP_CONN, None)
        if conn is not None and len(conn):
            with self._reg_lock:
                self.natclusters.observe_conns(conn)
            self._conn_raw.append(conn)
            self._n_conn_raw += len(conn)
            self.stats.bump("conn_events", len(conn))
            n += len(conn)
        resp = recs.pop(wire.NOTIFY_RESP_SAMPLE, None)
        if resp is not None and len(resp):
            hid = resp["host_id"]
            self._host_resp_tick[hid[hid < self.cfg.n_hosts]] = \
                self._tick_no
            self._resp_raw.append(resp)
            self._n_resp_raw += len(resp)
            self.stats.bump("resp_events", len(resp))
            n += len(resp)
        for kind, *chunks in decode.drain_chunks(
                recs, self.cfg.conn_batch, self.cfg.resp_batch,
                self.cfg.listener_batch):
            if self._fused and kind in _SECTION_COUNTERS:
                n += self._stage_section(kind, chunks[0])
            elif kind == "listener":
                lb = decode.listener_batch_fast(chunks[0],
                                                self.cfg.listener_batch,
                                                stats=self.stats)
                self.state = self._fold_lst(self.state, lb)
                n += len(chunks[0])
                self.stats.bump("listener_records", len(chunks[0]))
            elif kind == "host":
                hb = decode.host_batch_fast(chunks[0], stats=self.stats)
                self.state = self._fold_host(self.state, hb)
                n += len(chunks[0])
                self.stats.bump("host_records", len(chunks[0]))
            elif kind == "task":
                tb = decode.task_batch_fast(chunks[0], stats=self.stats)
                self.state = self._fold_task(self.state, tb)
                n += len(chunks[0])
                self.stats.bump("task_records", len(chunks[0]))
            elif kind == "ping":
                pb = decode.ping_batch(chunks[0], stats=self.stats)
                self.state = self._fold_ping(self.state, pb)
                n += len(chunks[0])
                self.stats.bump("task_pings", len(chunks[0]))
            elif kind == "delta":
                db = decode.delta_batch(
                    chunks[0], self._slab_lanes_cfg["delta"],
                    stats=self.stats, **self._delta_dims)
                self.state, self.dep = self._fold_delta(
                    self.state, self.dep, db,
                    np.int32(self._tick_no))
                n += len(chunks[0])
                self.stats.bump("preagg_delta_records",
                                len(chunks[0]))
            elif kind == "cpumem":
                cmb = decode.cpumem_batch_fast(chunks[0],
                                               stats=self.stats)
                self.state = self._fold_cm(self.state, cmb)
                n += len(chunks[0])
                self.stats.bump("cpumem_records", len(chunks[0]))
            elif kind == "trace":
                self._observe_trace(chunks[0])
                trb = decode.trace_batch(chunks[0])
                self.state = self._fold_trace(self.state, trb)
                n += len(chunks[0])
                self.stats.bump("trace_records", len(chunks[0]))
            elif kind == "listener_info":
                # registry updates run under the registry lock: their
                # columns render on query worker threads in snapshot
                # mode (query/snapshot.py) and dict iteration must not
                # race a structural mutation
                with self._reg_lock:
                    self.stats.bump("listener_infos",
                                    self.svcreg.update(chunks[0]))
                n += len(chunks[0])
            elif kind == "host_info":
                with self._reg_lock:
                    self.stats.bump("host_infos",
                                    self.hostinfo.update(chunks[0]))
                n += len(chunks[0])
            elif kind == "cgroup":
                with self._reg_lock:
                    self.stats.bump("cgroup_records",
                                    self.cgroups.update(chunks[0]))
                n += len(chunks[0])
            elif kind == "mount":
                with self._reg_lock:
                    self.stats.bump("mount_records",
                                    self.mounts.update(chunks[0]))
                n += len(chunks[0])
            elif kind == "netif":
                with self._reg_lock:
                    self.stats.bump("netif_records",
                                    self.netifs.update(chunks[0]))
                n += len(chunks[0])
            elif kind == "agent_stats":
                # agent delivery-continuity deltas → server counters
                # (the only process that can see a spool drop is the
                # agent; the server is where /metrics renders)
                a = chunks[0]
                for fld, ctr in (
                        ("spool_dropped", "spool_dropped"),
                        ("spool_dropped_records",
                         "spool_dropped_records"),
                        ("spool_resent", "spool_resent"),
                        ("connect_timeouts", "agent_connect_timeouts")):
                    tot = int(a[fld].sum())
                    if tot:
                        self.stats.bump(ctr, tot)
            elif kind == "names":
                # names don't count into n (not telemetry events) but
                # DO invalidate cached columns: resolved name strings
                # are part of every snapshot view
                with self._reg_lock:
                    self.stats.bump("names_interned",
                                    self.names.update(chunks[0]))
                self._cols.bump()
        if self._fused:
            self._dispatch_fused_pending()
        else:
            self._dispatch_full_slabs()
        if n:
            self._cols.bump()
        return n

    def _observe_trace(self, recs) -> None:
        """Host-side half of the trace fold (registry observe + the
        trace→resp bridge with per-host native-stream precedence) —
        shared by the fused staging path and the legacy dispatch."""
        with self._reg_lock:
            self.traceconns.observe(recs)
        if self.opts.trace_resp_bridge:
            rs = decode.resp_from_trace(recs)
            # per-host precedence: hosts with a RECENT native resp
            # stream are not bridged (no double counting; a dead
            # native stream un-suppresses)
            hid = rs["host_id"]
            fresh = (self._tick_no - self._host_resp_tick[
                np.minimum(hid, self.cfg.n_hosts - 1)]
                <= _RESP_FRESH_TICKS)
            rs = rs[(hid >= self.cfg.n_hosts) | ~fresh]
            if len(rs):
                self._resp_raw.append(rs)
                self._n_resp_raw += len(rs)
                self.stats.bump("resp_from_trace", len(rs))

    # ------------------------------------------------- fused fold path
    def _stage_section(self, kind: str, recs) -> int:
        """Stage one drained subsystem chunk into the fused-fold slab
        section; dispatches the pending slab first when the section
        would overflow its fixed lane capacity."""
        if kind == "trace":
            self._observe_trace(recs)
        if self._stage_n[kind] + len(recs) > self._slab_lanes_cfg[kind]:
            self._dispatch_fused()
        self._stage_recs[kind].append(recs)
        self._stage_n[kind] += len(recs)
        self.stats.bump(_SECTION_COUNTERS[kind], len(recs))
        return len(recs)

    def _dispatch_fused_pending(self) -> None:
        """End-of-ingest fold boundary: one fused dispatch folds every
        staged subsystem section plus (when full) the conn/resp K-slab;
        extra full K-slabs drain in follow-up connresp-only dispatches.
        Same fold boundaries as the legacy sequence — grouped into one
        device dispatch per boundary instead of one per subsystem."""
        K = self.cfg.fold_k
        nc, nr = K * self.cfg.conn_batch, K * self.cfg.resp_batch
        while (any(self._stage_n.values())
               or self._n_conn_raw >= nc or self._n_resp_raw >= nr):
            self._dispatch_fused(
                connresp="slab" if (self._n_conn_raw >= nc
                                    or self._n_resp_raw >= nr)
                else None)

    def _get_fold_all(self, names: tuple):
        """Compiled fold_all variant for one section-presence tuple
        (process-wide memo — every Runtime with the same geometry
        shares the compiled variants)."""
        jitted = self._fold_all_jits.get(names)
        if jitted is None:
            cfg = self.cfg

            def make():
                def fn(st, dep, tick, *secs, _names=names):
                    return step.fold_all(cfg, st, dep, tick,
                                         **dict(zip(_names, secs)))
                return jax.jit(fn, donate_argnums=(0, 1))

            jitted = _memo_jit(("fold_all", cfg, names), make)
            self._fold_all_jits[names] = jitted
        return jitted

    def _dispatch_fused(self, connresp=None) -> None:
        """ONE fused device dispatch: staged subsystem sections (in the
        legacy drain order) + optionally the conn/resp slab + the dep
        fold + the digest-stage pressure scalar, with full state
        donation. ``connresp``: None (sections only), "slab" (a (K, B)
        double-buffered slab take) or "single" (one (1, B) microbatch —
        the flush/boundary shape).

        The per-batch device dispatch count of the hot path is exactly
        ONE (plus the occasional ``td_flush_partial``): the pressure
        scalar rides the fold's own outputs, so no second dispatch ever
        runs just to observe it."""
        sections = {}
        for kind in self._slab_lanes_cfg:
            if self._stage_n[kind]:
                recs = decode._concat_chunks(
                    self._stage_recs[kind],
                    wire.DTYPE_OF_SUBTYPE[_SECTION_SUBTYPES[kind]])
                sections[kind] = self._sect_builders[kind](
                    recs, self._slab_lanes_cfg[kind], self.stats)
                self._stage_recs[kind] = []
                self._stage_n[kind] = 0
        nrec = 0
        if connresp == "slab":
            K = self.cfg.fold_k
            buf = self._slab_bufs[self._slab_active]
            self._slab_active ^= 1          # flip: next decode goes to
            self.stats.bump("stage_slab_flips")  # the idle buffer
            crecs, nc = decode.take_raw_chunks(
                self._conn_raw, K * self.cfg.conn_batch)
            rrecs, nr = decode.take_raw_chunks(
                self._resp_raw, K * self.cfg.resp_batch)
            self._n_conn_raw -= nc
            self._n_resp_raw -= nr
            nrec = nc + nr
            # host-side staging gauges (no device readback): slab fill
            # at dispatch + the buffer flip counter; the engine_ prefix
            # rides the `health {...}` cadence line and /metrics
            self.stats.gauge("engine_stage_slab_conn_occupancy",
                             round(nc / (K * self.cfg.conn_batch), 4))
            self.stats.gauge("engine_stage_slab_resp_occupancy",
                             round(nr / (K * self.cfg.resp_batch), 4))
            cbs = decode.conn_slab(crecs, K, self.cfg.conn_batch,
                                   stats=self.stats, out=buf["conn"],
                                   clear_to=buf["hw_conn"])
            rbs = decode.resp_slab(rrecs, K, self.cfg.resp_batch,
                                   stats=self.stats, out=buf["resp"],
                                   clear_to=buf["hw_resp"])
            buf["hw_conn"], buf["hw_resp"] = nc, nr
            sections["connresp"] = (cbs, rbs)
            self.stats.bump("slab_dispatches")
        elif connresp == "single":
            crecs, nc = decode.take_raw_chunks(self._conn_raw,
                                               self.cfg.conn_batch)
            rrecs, nr = decode.take_raw_chunks(self._resp_raw,
                                               self.cfg.resp_batch)
            self._n_conn_raw -= nc
            self._n_resp_raw -= nr
            nrec = nc + nr
            cbs = decode.conn_slab(crecs, 1, self.cfg.conn_batch,
                                   stats=self.stats)
            rbs = decode.resp_slab(rrecs, 1, self.cfg.resp_batch,
                                   stats=self.stats)
            sections["connresp"] = (cbs, rbs)
        if not sections:
            return
        # lag-2 pressure scalar (a fold_all OUTPUT — materialized by
        # now): flush the fullest digest stages BEFORE this dispatch
        # when headroom is low
        if (len(self._pressures) >= 2
                and int(self._pressures.popleft())
                > self.cfg.td_stage_cap // 2):
            self.state = self._td_flush_partial(self.state)
            self.stats.bump("td_partial_flushes")
        names = tuple(k for k in step.FOLD_ALL_ORDER if k in sections)
        with self.stats.timeit("fold_dispatch"), \
                self.spans.span("decode_fold", nrec=nrec,
                                path="native" if native.available()
                                else "python"):
            # the staged (idle-buffer) columns transfer while the
            # previous fold may still be in flight; the jit call below
            # never blocks on it (async dispatch)
            secs = jax.device_put(tuple(sections[k] for k in names))
            self.state, self.dep, pressure = self._get_fold_all(names)(
                self.state, self.dep, np.int32(self._tick_no), *secs)
        self._profiler.on_fold()      # GYT_JAX_PROFILE bracket (opt-in)
        self._pressures.append(pressure)
        if "connresp" in sections:
            self._td_dirty = True
        self.stats.bump("fold_dispatches")

    def _dispatch_full_slabs(self) -> None:
        """Fold every full K-slab of staged raw records. JAX dispatch is
        async — the device computes slab N while the host decodes slab
        N+1, so the feed loop never blocks between slabs."""
        K = self.cfg.fold_k
        nc, nr = K * self.cfg.conn_batch, K * self.cfg.resp_batch
        while self._n_conn_raw >= nc or self._n_resp_raw >= nr:
            self._dispatch_slab()


    def _dispatch_slab(self) -> None:
        """One K-deep device dispatch: flat native columnar decode of up
        to K·B staged records straight into the stacked (K, B) layout
        (reshape, no copy), then the scan'd fold — no per-chunk decode,
        no np.stack (VERDICT r3 #2). Staged chunks decode into the slab
        buffers at their lane offsets — no staging concatenate either."""
        K = self.cfg.fold_k
        crecs, nc = decode.take_raw_chunks(self._conn_raw,
                                           K * self.cfg.conn_batch)
        rrecs, nr = decode.take_raw_chunks(self._resp_raw,
                                           K * self.cfg.resp_batch)
        self._n_conn_raw -= nc
        self._n_resp_raw -= nr
        # the lag-2 pressure scalar is materialized by now: flush the
        # fullest stages BEFORE this dispatch if headroom is low
        if (len(self._pressures) >= 2
                and int(self._pressures.popleft())
                > self.cfg.td_stage_cap // 2):
            self.state = self._td_flush_partial(self.state)
            self.stats.bump("td_partial_flushes")
        with self.stats.timeit("fold_dispatch"), \
                self.spans.span("decode_fold", nrec=nc + nr,
                                path="native" if native.available()
                                else "python"):
            cbs = decode.conn_slab(crecs, K, self.cfg.conn_batch,
                                   stats=self.stats)
            rbs = decode.resp_slab(rrecs, K, self.cfg.resp_batch,
                                   stats=self.stats)
            self.state, self.dep = self._fold_many_dep(
                self.state, self.dep, cbs, rbs, self._tick_no)
        self._profiler.on_fold()      # GYT_JAX_PROFILE bracket (opt-in)
        self._pressures.append(self._stage_pressure(self.state))
        self._td_dirty = True
        self.stats.bump("slab_dispatches")

    def flush(self) -> int:
        """Fold all staged raw records (single-microbatch path when they
        fit one, padded partial slab otherwise). Called at every
        cadence/query boundary — after it, every QUERY view is current
        (no query subsystem reads the all-time digest; its stage drains
        on tick cadence / ``td_drain``, off the <1s query path).
        Returns records folded."""
        n = self._n_conn_raw + self._n_resp_raw
        while (self._n_conn_raw or self._n_resp_raw
               or (self._fused and any(self._stage_n.values()))):
            if not self._fused:
                if (self._n_conn_raw <= self.cfg.conn_batch
                        and self._n_resp_raw <= self.cfg.resp_batch):
                    crecs, _ = decode.take_raw_chunks(
                        self._conn_raw, self.cfg.conn_batch)
                    rrecs, _ = decode.take_raw_chunks(
                        self._resp_raw, self.cfg.resp_batch)
                    self._n_conn_raw = self._n_resp_raw = 0
                    cb = decode.conn_batch_parts(
                        crecs, self.cfg.conn_batch, stats=self.stats)
                    rb = decode.resp_batch_parts(
                        rrecs, self.cfg.resp_batch, stats=self.stats)
                    self.state = self._fold(self.state, cb, rb)
                    self.dep = self._dep_step(self.dep, cb,
                                              self._tick_no)
                    self._td_dirty = True     # resp samples staged
                else:
                    self._dispatch_slab()
            elif (self._n_conn_raw <= self.cfg.conn_batch
                    and self._n_resp_raw <= self.cfg.resp_batch):
                # boundary leftovers: one fused (1, B) dispatch — the
                # same single-microbatch shape the legacy flush uses,
                # with dep fold + pressure riding the same graph
                self._dispatch_fused(
                    connresp="single"
                    if (self._n_conn_raw or self._n_resp_raw) else None)
            else:
                self._dispatch_fused(connresp="slab")
        if n:
            self._cols.bump()
        return n

    def td_drain(self, max_iters: int | None = None) -> int:
        """Drain the digest stage with O(m) partial flushes.

        Iteration count scales with the number of ACTIVE stages (entities
        holding samples), not capacity — the toy/test case drains in one
        pass. Unbounded by default (direct ``svc_snapshot`` consumers
        want exact digests); ``run_tick`` passes a bound to amortize the
        north-star worst case (every entity active) across ticks —
        overflowing stages drop + count, and the loghist remains the
        lossless estimator, mirroring the reference's ~50% response
        sampling (``common/gy_ebpf.h:29``). Returns flushes run."""
        self.flush()
        # the flushes below donate state: evict cached column closures
        # capturing the current state object (a cache hit after the
        # donation would dereference deleted device buffers)
        self._cols.bump()
        i = 0
        while max_iters is None or i < max_iters:
            if int(self._stage_pressure(self.state)) <= 0:
                self._td_dirty = False
                self._pressures.clear()
                break
            self.state = self._td_flush_partial(self.state)
            self.stats.bump("td_partial_flushes")
            i += 1
        return i

    # ------------------------------------------------------------ health
    def engine_health(self) -> dict:
        """Device-state health gauges from ONE batched readback
        (``engine/step.py:engine_health_vec``): slab occupancy %,
        probe-failure and eviction counters, dep-graph pair/edge fill,
        digest-stage pressure. Folded into ``self.stats`` gauges so
        the same numbers ride selfstats, /metrics and the cadence
        log."""
        vec = np.asarray(self._engine_health(self.state, self.dep))
        gauges = obs_health.gauges_from_vec(
            vec, obs_health.capacities(self.cfg, self.opts))
        # decode-path state gauge: a degraded native extension is a
        # scrape-level signal, not just a growing fallback counter
        gauges["native_decode_available"] = \
            1.0 if native.available() else 0.0
        # WAL health rides the same one-readback report path: fsync lag
        # (the RPO bound), pending bytes, segment footprint
        if self.journal is not None:
            gauges.update(self.journal.gauges())
        for k, v in gauges.items():
            self.stats.gauge(k, v)
        return gauges

    # -------------------------------------------------- heavy hitters
    def heavy_recover(self) -> dict:
        """Per-tick heavy-hitter key recovery: ONE read-only device
        dispatch decodes the invertible buckets (fingerprint + bucket-
        position verification), point-queries the CMS for every
        candidate and reads the exact top-K lanes alongside; the host
        merges them into the bound-annotated heavy-flow view the
        ``topk`` subsystem serves. Counted in /metrics
        (``gyt_topk_recover_readbacks_total``) — the fold path itself
        never pays an op for recovery."""
        from gyeeta_tpu.sketch import invertible

        self.flush()
        with self.stats.timeit("topk_recover"):
            out = {k: np.asarray(v) for k, v in
                   self._hh_recover(self.state).items()}
        self.stats.bump("topk_recover_readbacks")
        evicted = float(out["evicted"])
        total = float(out["total_mass"])
        err_term = invertible.cms_error_term(total, self.cfg.cms_width)
        hot_thresh = (self.cfg.hh_hot_frac * total
                      if self.cfg.hh_hot_frac > 0 else 0.0)
        flows, recovered, hot = invertible.merge_recovered_np(
            out, err_term, hot_thresh)
        # promotions: recovered-hot keys that were NOT hot at the
        # previous recovery — the "new flow entered the top view" edge
        new_hot = hot - self._hh_prev_hot
        if new_hot:
            self.stats.bump("topk_hot_promotions", len(new_hot))
        self._hh_prev_hot = hot
        self.stats.gauge("topk_recovered_keys", float(len(recovered)))
        self.stats.gauge("topk_evicted_mass", evicted)
        return {"flows": flows, "recovered_keys": len(recovered),
                "evicted": evicted, "err_term": err_term,
                "total_mass": total, "new_hot": len(new_hot)}

    def _topk_columns(self):
        """topk subsystem columns: heavy flows (exact ∪ recovered) +
        dense svc/api rankings. Recovery memoizes per state version —
        between folds every query (and the alert check) reuses one
        readback."""
        rec = self._cols.get("__hh_recover", self.heavy_recover)
        return api.heavy_topk_columns(
            rec["flows"], svc=self._cached_columns("svcstate"),
            trace=self._cached_columns("tracereq"))

    # ----------------------------------------------------- snapshot tier
    def publish_snapshot(self):
        """Freeze the current engine view into an immutable
        :class:`~gyeeta_tpu.query.snapshot.EngineSnapshot` and swap it
        in (plain attribute store — atomic under the GIL). One
        non-donating device copy of (state, dep) per publish; queries
        on worker threads keep reading the PREVIOUS snapshot until the
        swap, and the old snapshot's buffers free when its last reader
        drops it. Called once per tick (post-classify, pre-window-roll)
        and on restore; ``run_tick`` routes alert evaluation and the
        history sweep through the fresh snapshot so tick-time work
        PRE-WARMS the columns dashboards then reuse."""
        from gyeeta_tpu.query.snapshot import EngineSnapshot
        with self.stats.timeit("snapshot_publish"):
            state, dep = snapshot_copy(self, (self.state, self.dep))
        self._snap_version += 1
        snap = EngineSnapshot(
            self, state, dep, tick=self._tick_no,
            published_at=self._clock(), version=self._snap_version,
            result_cache_max=int(os.environ.get(
                "GYT_QUERY_CACHE_MAX", "1024")))
        # the snapshot being replaced becomes the NEXT publish's
        # donation candidate — retained ONLY in ping-pong mode (with
        # the flag off it would just pin an extra full copy in memory)
        self._snap_old = self.snapshot if self._snap_pingpong else None
        self.snapshot = snap
        self.stats.bump("snapshots_published")
        self.stats.gauge("snapshot_tick", float(self._tick_no))
        self.stats.gauge("snapshot_age_seconds", 0.0)
        return snap

    # ------------------------------------------------------------ cadence
    def run_tick(self) -> dict:
        with self.stats.timeit("tick"), self.spans.span(
                "tick", nrec=self._tick_no):
            return self._run_tick()

    def _run_tick(self) -> dict:
        """Close one 5s window: classify → alerts → windows tick →
        maintenance cadences. Returns a tick report."""
        self.flush()
        if self._td_dirty:    # tick-cadence digest compression (bounded)
            self.td_drain(max_iters=self.opts.td_drain_iters_per_tick)
        report = {}
        self.state = self._classify(self.state)
        self._cols.bump()             # classify + tick mutate views
        # publish the post-classify view: the snapshot dashboards read
        # for the next 5s window. Everything below that reads columns
        # (alert eval, the history sweep) goes THROUGH it — tick-time
        # work pre-warms the snapshot's column cache.
        snap = self.publish_snapshot()
        # per-tick heavy-hitter recovery (one read-only readback,
        # memoized per state version — an alertdef on `topk` and every
        # query until the next fold reuse it). 0 disables the cadence;
        # queries still recover on demand.
        ev = self.opts.hh_recover_every_ticks
        if ev and self.cfg.hh_width > 0 \
                and (self._tick_no + 1) % ev == 0:
            report["topk_recovered"] = self._cols.get(
                "__hh_recover", self.heavy_recover)["recovered_keys"]
        # alert eval short-circuits BEFORE any column render when no
        # realtime def is enabled (counted; pending group-wait batches
        # still flush on schedule)
        if self.alerts.wants_realtime():
            fired = self.alerts.check(self.state,
                                      columns_fn=snap.columns)
        else:
            self.stats.bump("alert_eval_skipped")
            fired = self.alerts.flush_groups()
        # history snapshots BEFORE the window tick: the closing 5s slab is
        # still readable (tick zeroes it)
        tick = int(np.asarray(self.state.resp_win.tick)) + 1
        report["tick"] = tick
        self._tick_no = tick
        self.stats.gauge("tick", tick)
        self.dep = self._dep_age(self.dep, tick)
        with self._reg_lock:      # ageing structurally mutates the
            self.cgroups.age()    # registries snapshot aux renders
            self.mounts.age()     # iterate on worker threads
            self.netifs.age()
            self.natclusters.age()
            self.traceconns.age()

        if self.history and tick % self.opts.history_every_ticks == 0:
            now = self._clock()
            # render on the fold thread from the JUST-published
            # snapshot (pre-warming its column cache for dashboards),
            # WRITE on the history writer thread (bounded queue,
            # drop-oldest counted) — a slow sqlite/pg write can no
            # longer stall run_tick (it used to be synchronous SQL in
            # this loop)
            out = api.execute(self.cfg, None, api.QueryOptions(
                subsys="svcstate", maxrecs=self.cfg.svc_capacity),
                names=self.names, columns_fn=snap.columns)
            hout = api.execute(self.cfg, None, api.QueryOptions(
                subsys="hoststate", maxrecs=self.cfg.n_hosts),
                names=self.names, columns_fn=snap.columns)
            cout = api.execute(self.cfg, None, api.QueryOptions(
                subsys="clusterstate"), columns_fn=snap.columns)
            tout = api.execute(self.cfg, None, api.QueryOptions(
                subsys="taskstate", maxrecs=self.cfg.task_capacity),
                names=self.names, columns_fn=snap.columns)
            mout = api.execute(self.cfg, None, api.QueryOptions(
                subsys="cpumem", maxrecs=self.cfg.n_hosts),
                names=self.names, columns_fn=snap.columns)
            trout = api.execute(self.cfg, None, api.QueryOptions(
                subsys="tracereq", maxrecs=self.cfg.api_capacity),
                names=self.names, columns_fn=snap.columns)
            sweep = [("svcstate", now, out["recs"]),
                     ("hoststate", now, hout["recs"]),
                     ("clusterstate", now, cout["recs"]),
                     ("taskstate", now, tout["recs"]),
                     ("cpumem", now, mout["recs"]),
                     ("tracereq", now, trout["recs"])]
            ncg = 0
            if len(self.cgroups):
                cgout = api.execute(self.cfg, None, api.QueryOptions(
                    subsys="cgroupstate", maxrecs=100_000),
                    names=self.names, columns_fn=snap.columns)
                sweep.append(("cgroupstate", now, cgout["recs"]))
                ncg = cgout["nrecs"]
            self._histwriter.write_sweep(sweep)
            report["history_rows"] = (
                out["nrecs"] + hout["nrecs"] + tout["nrecs"]
                + mout["nrecs"] + trout["nrecs"] + ncg + 1)

        # db-mode alertdefs run AFTER the history write so a due def sees
        # the snapshot from this very tick (ref: MDB alerts query the DB
        # the madhava just wrote, server/gy_malerts.cc). Only defs that
        # actually read the store pay the writer-queue barrier.
        if self.history and self.alerts.wants_db():
            self._histwriter.barrier()
            fired += self.alerts.check_db(self.history)
        report["alerts_fired"] = len(fired)
        for a in fired:
            self.notifylog.add_alert(a)

        # device-health readback (obs tier): slab occupancy, probe
        # failures, dep fill, stage pressure — ONE batched transfer,
        # folded into the stats gauges for /metrics + the cadence log.
        # The drop-pressure signal (VERDICT r4 #10) feeds off the same
        # vector (growing drops → notifymsg entries + gauges).
        from gyeeta_tpu.utils import droppressure
        health = self.engine_health()
        self._last_drops = droppressure.check(
            obs_health.drops_for_pressure(health),
            {"svc": self.cfg.svc_capacity,
             "task": self.cfg.task_capacity,
             "api": self.cfg.api_capacity,
             "dep": self.opts.dep_pair_capacity},
            getattr(self, "_last_drops", {}),
            self.notifylog, self.stats)

        self.state = self._tick(self.state)
        if tick % self.opts.task_age_every_ticks == 0:
            self.state = self._age_tasks(self.state)
            self.state = self._age_apis(self.state)
        n_tomb = int(np.asarray(self.state.tbl.n_tomb))
        if n_tomb > self.cfg.svc_capacity * self.opts.compact_tomb_frac:
            self.state = compact.compact_state(self.cfg, self.state)
            self.stats.bump("compactions")
            report["compacted"] = True
        nt_tomb = int(np.asarray(self.state.task_tbl.n_tomb))
        if nt_tomb > self.cfg.task_capacity * self.opts.compact_tomb_frac:
            self.state = self._compact_tasks(self.state)
            self.stats.bump("task_compactions")
            report["task_compacted"] = True

        # journal fsync cadence backstop: appends check the ms budget
        # themselves, but a quiet wire must not hold bytes unsynced
        # past a tick
        if self.journal is not None:
            self.journal.poll()
        if (self.opts.checkpoint_dir
                and tick % self.opts.checkpoint_every_ticks == 0):
            from gyeeta_tpu.utils import journal as J
            extra = J.checkpoint_extra(self, tick)
            path = ckpt.save(
                f"{self.opts.checkpoint_dir}/gyt_ckpt_{tick:08d}.npz",
                self.cfg, self.state, extra=extra)
            # the checkpoint supersedes older WAL segments: drop them
            # (bounds journal disk to ~one checkpoint interval)
            J.post_checkpoint_truncate(self, extra)
            report["checkpoint"] = str(path)
            self.stats.bump("checkpoints")
        # the window tick / aging / compaction above changed every view
        self._cols.bump()
        return report

    def _hostlist_columns(self):
        """hostlist subsystem (ref parthalist): hosts that have ever
        reported, with liveness from the last-report tick."""
        last = np.asarray(self.state.host_last_tick)
        seen = np.nonzero(last >= 0)[0]
        age = self._tick_no - last[seen]
        hostids, hostnames = api._host_name_cols(self.cfg.n_hosts,
                                                 self.names)
        cols = {
            "hostid": seen.astype(np.float64),
            "hostname": np.asarray(hostnames, object)[seen],
            "up": age <= api.DOWN_AFTER_TICKS,
            "lastseen": age.astype(np.float64),
        }
        return cols, np.ones(len(seen), bool)

    def _serverstatus_columns(self):
        """serverstatus subsystem (ref madhavastatus): one-row self
        status from the live counters."""
        from gyeeta_tpu import version as V

        c = self.stats.counters
        obj = lambda v: np.array([v], object)  # noqa: E731
        num = lambda v: np.array([float(v)], np.float64)  # noqa: E731
        cols = {
            "uptime": num(self._clock() - self._t_started),
            "tick": num(self._tick_no),
            "nhosts": num(int((np.asarray(self.state.host_last_tick)
                               >= 0).sum())),
            "nsvc": num(int(np.asarray(self.state.tbl.n_live))),
            # exact host-side int counters (the () f32 device scalars
            # lose increments past ~2^24 events); the sharded runtime
            # bumps the same counters in its feed path
            "connevents": num(c.get("conn_events", 0)),
            "respevents": num(c.get("resp_events", 0)),
            "queries": num(c.get("queries", 0)),
            "alertsfired": num(self.alerts.stats.get("nfired", 0)),
            "wirever": num(V.CURR_WIRE_VERSION),
            "version": obj(V.__version__),
        }
        return cols, np.ones(1, bool)

    def _alert_columns(self, subsys: str):
        """Column source for realtime alertdef evaluation — the same
        dispatch as api.execute so defs can target ANY live subsystem
        (device slabs, dep graph, or host-side registries). Routed
        through the snapshot cache: alert evaluation at tick time
        PRE-WARMS the columns queries then reuse. A ``subsys@window``
        name (an alertdef with a ``window`` field) evaluates against
        the time-travel tier's windowed aggregate instead of the live
        snapshot."""
        if "@" in subsys:
            base, _, win = subsys.partition("@")
            if self.timeview is None:
                raise ValueError(
                    "windowed alertdef needs history shards "
                    "(hist_shard_dir)")
            return self.timeview.window_columns_for(base, win)
        return self._cached_columns(subsys)

    def _cached_columns(self, subsys: str):
        """Version-keyed snapshot cache (query freshness, VERDICT r3
        weak #4): device readbacks recompute only after state actually
        changed (feed/tick/flush/restore bump the cache version);
        between ticks every query serves from the cached columns — the
        reference likewise queries incrementally-maintained in-memory
        tables, not per-request recomputation. Registry/CRUD-backed aux
        views are NEVER cached (they mutate without a version bump)."""
        if subsys in self._aux:
            return self._aux[subsys]()
        def compute():
            try:
                return api.columns_for(self.cfg, self.state, subsys,
                                       names=self.names, dep=self.dep,
                                       svcreg=self.svcreg,
                                       aux=self._aux)
            except KeyError:
                # a subsystem with fields but no single-node provider
                # (e.g. shardlist) must fail like execute() without a
                # columns_fn would — clean error, not a bare KeyError
                raise ValueError(
                    f"unknown subsystem {subsys!r}") from None
        out = self._cols.get(subsys, compute)
        if subsys == "procinfo":
            # joined OUTSIDE the cache: tags mutate via CRUD without a
            # state version bump
            out = self.tags.with_tags(out)
        return out

    def _ext_join(self, base_subsys: str, idcol: str = "svcid"):
        """ext* subsystems: base columns ⋈ svcinfo metadata."""
        cols, live = self._alert_columns(base_subsys)
        info_cols, _ = self.svcreg.columns(self.names)
        return api.info_join(cols, live, info_cols, idcol=idcol)

    def _svc_task_ids(self):
        """Hex process-group ids that serve a listener (taskstate rows
        with a nonzero relsvcid) — the traceconn ``csvc`` source."""
        cols, live = self._cached_columns("taskstate")
        zero = "0" * 16
        return {t for t, r, ok in zip(cols["taskid"], cols["relsvcid"],
                                      live) if ok and r != zero}

    def _traceuniq_columns(self):
        """traceuniq: distinct API signatures per service, derived by
        grouping the per-(svc, api) slab (ref traceuniqtbl)."""
        tcols, tlive = api.trace_columns(self.cfg, self.state,
                                         names=self.names)
        return api.traceuniq_from_trace(tcols, tlive)

    # ------------------------------------------------------- trace control
    def trace_control_diff(self, hosts=None):
        """Evaluate tracedefs against live svcinfo → per-host
        enable/disable diffs for the network edge to push (the
        REQ_TRACE_SET distribution step). ``hosts`` restricts to
        reachable agents so unreachable diffs aren't consumed."""
        targets = self.tracedefs.target_svcids(self._alert_columns)
        return self.tracedefs.diff_for_hosts(targets, hosts=hosts)

    # ---------------------------------------------------------------- CRUD
    def crud(self, req: dict) -> dict:
        from gyeeta_tpu.query import crud as CR
        with self._reg_lock:
            out = CR.crud(self, req)
        # CRUD mutates aux views mid-snapshot: invalidate the published
        # snapshot's result + column caches so the next query re-renders
        snap = self.snapshot
        if snap is not None:
            snap.on_mutation()
        return out

    # -------------------------------------------------------------- query
    def query(self, req: dict) -> dict:
        """Point-in-time (live) or historical (time-ranged) JSON query;
        requests with an "op" field route to the CRUD channel; a
        "multiquery" list runs several queries in one round trip (the
        reference's multiquery batches, ``gy_query_common.h:24``).

        ``consistency`` selects the live-query path: ``"strong"`` (the
        default for direct callers — flush staged events, read the live
        engine) or ``"snapshot"`` (read the last published per-tick
        :class:`~gyeeta_tpu.query.snapshot.EngineSnapshot`; never
        touches the fold — the serving edges default to this)."""
        if req.get("op"):
            return self.crud(req)
        if "multiquery" in req:
            from gyeeta_tpu.query import crud as CR
            return CR.multiquery(self.query, req)
        if req.get("consistency") == "snapshot":
            return self.query_snapshot(req)
        if "consistency" in req:
            req = dict(req)
            if req.pop("consistency") != "strong":
                raise ValueError(
                    "consistency must be 'snapshot' or 'strong'")
        # process-local subsystems (selfstats readback + Prometheus
        # metrics exposition) — shared routing with ShardedRuntime
        out = api.local_response(self, req)
        if out is not None:
            return out
        with self.stats.timeit("query"):
            return self._query(req)

    def query_snapshot(self, req: dict) -> dict:
        """Serve a live query from the last published snapshot — no
        ``flush()``, no fold-path device dispatch, safe from worker
        threads (the off-loop executor's path, ``net/qexec.py``).
        Historical ``at=``/``window=`` requests route to the shard tier
        (file-backed — also fold-free); relational ``tstart/tend`` SQL
        runs against the live history handle and must use
        ``consistency=strong`` (the serving edge routes it inline)."""
        req = {k: v for k, v in req.items() if k != "consistency"}
        snap = self.snapshot
        if snap is None:
            # bootstrap publish (single-threaded callers); the serving
            # edge publishes at start() so worker threads always find
            # a snapshot here
            snap = self.publish_snapshot()
        if req.get("subsys") in api.LOCAL_SUBSYS:
            return api.local_response(self, req, snapshot=snap)
        if ("tstart" in req or "tend" in req) and "at" not in req \
                and "window" not in req and self.history:
            raise ValueError(
                "relational history queries need consistency=strong")
        from gyeeta_tpu.history.timeview import route_historical
        out = route_historical(self, req)
        if out is not None:
            return out
        self.stats.bump("queries")
        with self.stats.timeit("query"):
            return snap.query(req)

    def _query(self, req: dict) -> dict:
        # time-travel tier: at=/window= materialize snapshot shards
        # (tstart/tend also route there when no relational store is
        # configured) — shared three-edge routing, so GYT binary, REST
        # and stock NM requests land on identical code paths
        from gyeeta_tpu.history.timeview import route_historical
        out = route_historical(self, req)
        if out is not None:
            return out
        if "tstart" in req or "tend" in req:
            if not self.history:
                raise ValueError("no history store configured")
            if self._histwriter is not None:
                self._histwriter.barrier()   # read-your-writes
            now = self._clock()
            if req.get("aggr"):
                recs = self.history.aggr_query(
                    req["subsys"], float(req.get("tstart", 0)),
                    float(req.get("tend", now)), req["aggr"],
                    groupby=req.get("groupby"), filter=req.get("filter"),
                    step=float(req["step"]) if req.get("step") else None,
                    maxrecs=int(req.get("maxrecs", 10000)))
                return {"recs": recs, "nrecs": len(recs)}
            return {"recs": self.history.query(
                req["subsys"], float(req.get("tstart", 0)),
                float(req.get("tend", now)), req.get("filter"),
                int(req.get("maxrecs", 10000)))}
        self.flush()                  # live queries see all staged events
        self.stats.bump("queries")
        return api.execute(self.cfg, self.state,
                           api.QueryOptions.from_json(req),
                           names=self.names,
                           columns_fn=self._cached_columns)

    def close(self) -> None:
        """Release background resources (alert delivery worker, DNS
        resolver, history db handle). Idempotent; the server calls it
        on stop."""
        self._profiler.close()        # flush a short-lived jax trace
        self.alerts.close()
        self.dns.close()
        if self.journal is not None:
            self.journal.close()      # fsync + close (idempotent)
        if self._histwriter is not None:
            self._histwriter.close()  # drain queued sweeps first
        if self.history is not None:
            try:
                self.history.db.close()
            except Exception:  # noqa: BLE001 — already closed is fine
                pass

    def restore(self, path) -> dict:
        # drop staged records and partial-frame bytes from before the
        # restore: folding them into checkpointed state would double-count
        self._conn_raw, self._resp_raw = [], []
        self._n_conn_raw = self._n_resp_raw = 0
        self._stage_recs = {k: [] for k in self._slab_lanes_cfg}
        self._stage_n = {k: 0 for k in self._slab_lanes_cfg}
        self._pending = b""
        self._cols.bump()
        self._cols.clear()
        # the checkpoint may carry a non-empty digest stage (per-tick
        # drains are bounded): mark dirty so the tick cadence drains it
        self._td_dirty = True
        self._pressures.clear()
        self.state, extra = ckpt.restore(path, self.cfg, self.state)
        # the dep graph is not checkpointed: reset it (edges rebuild from
        # live traffic) and realign the host tick mirror so TTL deltas
        # never go negative
        self.dep = dg.init(self.opts.dep_pair_capacity,
                           self.opts.dep_edge_capacity)
        self._tick_no = int(extra.get("tick", 0))
        # sweep-seq high-water marks through checkpoint time; WAL
        # replay advances them for the post-checkpoint window
        self._sweep_last_seq = {
            int(k): int(v)
            for k, v in extra.get("sweep_seq", {}).items()}
        # snapshot serving must not keep answering from pre-restore
        # state: republish over the restored view (only when a snapshot
        # was ever published — bare runtimes pay nothing)
        if self.snapshot is not None:
            self.publish_snapshot()
        return extra

    def replay_journal(self, pos=None) -> dict:
        """Re-fold WAL chunks from ``pos`` (a checkpoint's recorded
        position; None = journal start) through the normal decode/fold
        path — the recovery phase of ``--restore-latest``."""
        from gyeeta_tpu.utils import journal as J
        return J.replay_journal(self, pos)
