"""String-intern service: 64-bit id ↔ name, per kind namespace.

The reference ships strings inline in wire records (comm_[16] in
``TASK_AGGR_NOTIFY`` ``common/gy_comm_proto.h:1290``, trailing cmdlines
:1708, listener names in listeninfo tables) and carries them end-to-end.
The TPU wire format is fixed-width, so strings travel once as
``NAME_INTERN`` announcements (``ingest/wire.py``) and thereafter as
64-bit ids inside hot records. This table is the id→name resolver used by
the query layer — and the ``intern()`` half is what agents/simulators use
to produce ids (fnv-style ``hash_bytes_np``, stable across processes).
"""

from __future__ import annotations

import numpy as np

from gyeeta_tpu.ingest import wire
from gyeeta_tpu.utils import hashing as H


class InternTable:
    def __init__(self):
        self._names: dict[tuple[int, int], str] = {}
        self.version = 0    # bumped per update; caches key on this

    def __len__(self) -> int:
        return len(self._names)

    # ------------------------------------------------------------- update
    def update(self, recs: np.ndarray) -> int:
        """Fold a NAME_INTERN record array; returns names added/refreshed."""
        n = 0
        for r in recs:
            nlen = min(int(r["nlen"]), wire.MAX_NAME_BYTES)
            name = bytes(r["name"][:nlen]).decode("utf-8", "replace")
            self._names[(int(r["kind"]), int(r["name_id"]))] = name
            n += 1
        if n:
            self.version += 1
        return n

    # ------------------------------------------------------------- lookup
    def lookup(self, kind: int, name_id: int):
        """id → name, or None when the announcement hasn't arrived."""
        return self._names.get((kind, int(name_id)))

    def resolve_array(self, kind: int, ids: np.ndarray,
                      fallback_hex: bool = True) -> np.ndarray:
        """Vector id→name resolution for query columns. Unknown ids render
        as the hex id (queries must never fail on a missing name)."""
        out = np.empty(len(ids), object)
        for i, v in enumerate(np.asarray(ids, np.uint64)):
            name = self._names.get((kind, int(v)))
            if name is None:
                name = format(int(v), "016x") if fallback_hex else ""
            out[i] = name
        return out

    # ----------------------------------------------------- producer side
    @staticmethod
    def intern(name: str, kind: int = wire.NAME_KIND_COMM,
               name_id=None) -> int:
        """Name → stable 64-bit id (or use the given id, e.g. a glob_id)."""
        if name_id is None:
            name_id = H.hash_bytes_np(name.encode("utf-8"), salt=kind)
        return int(name_id)

    @staticmethod
    def records(entries) -> np.ndarray:
        """[(kind, name_id, name)] → NAME_INTERN record array."""
        out = np.zeros(len(entries), wire.NAME_INTERN_DT)
        for i, (kind, name_id, name) in enumerate(entries):
            raw = name.encode("utf-8")[: wire.MAX_NAME_BYTES]
            out[i]["name_id"] = np.uint64(name_id)
            out[i]["kind"] = kind
            out[i]["nlen"] = len(raw)
            out[i]["name"][: len(raw)] = np.frombuffer(raw, np.uint8)
        return out
