"""User tag registry: process-group id → tag text.

The reference keeps a per-aggr-task tag buffer set from the web tier
(``server/gy_msocket.h:960`` MAGGR_TASK tagbuf_, surfaced as
``procinfo.tag``, FIELD_TAG ``gy_json_field_maps.h:1814``; its
SUBSYS_TAGS enum has no field map of its own). Here: a bounded
host-side registry, CRUD objtype "tag", joined into procinfo rows at
query time (OUTSIDE the snapshot cache — tags mutate without a state
version bump) and listable as the ``tags`` subsystem.
"""

from __future__ import annotations

import numpy as np

MAX_TAG_LEN = 128                  # ref MAX_TOTAL_TAG_LEN discipline
MAX_TAGS = 65536


class TagRegistry:
    def __init__(self):
        self._tags: dict[str, str] = {}     # taskid hex → tag

    def set(self, taskid: str, tag: str) -> None:
        taskid = taskid.lower()
        if len(taskid) != 16 or not all(
                c in "0123456789abcdef" for c in taskid):
            raise ValueError("taskid must be a 16-hex-digit id")
        if not tag:
            raise ValueError("tag must be non-empty (delete to clear)")
        if len(self._tags) >= MAX_TAGS and taskid not in self._tags:
            raise ValueError(f"tag registry full ({MAX_TAGS})")
        self._tags[taskid] = str(tag)[:MAX_TAG_LEN]

    def delete(self, taskid: str) -> bool:
        return self._tags.pop(taskid.lower(), None) is not None

    def __len__(self) -> int:
        return len(self._tags)

    def of(self, taskids: np.ndarray) -> np.ndarray:
        """(N,) object array of tags ('' untagged) for hex taskids."""
        return np.array([self._tags.get(t, "") for t in taskids],
                        object)

    def with_tags(self, colmask):
        """procinfo (cols, mask) → same with the tag column joined."""
        cols, mask = colmask
        out = dict(cols)
        out["tag"] = self.of(cols["taskid"])
        return out, mask

    def columns(self):
        """(cols, mask) for the ``tags`` subsystem listing."""
        items = sorted(self._tags.items())
        return ({"taskid": np.array([k for k, _ in items], object),
                 "tag": np.array([v for _, v in items], object)},
                np.ones(len(items), bool))
