"""Host-side listener-metadata registry (the svcinfo backing store).

The reference keeps per-listener static metadata (bind address, command
line, start time) in madhava's listener tables and serves the ``svcinfo``
subsystem from them. Metadata is announce-rate (once per listener +
reconnect resends), so it stays host-side here — only hot-path columns
live on device. Records arrive as NOTIFY_LISTENER_INFO.
"""

from __future__ import annotations

import ipaddress

import numpy as np


def format_ip(ip16: np.ndarray) -> str:
    """16 raw bytes → presentation address (v4-mapped → dotted quad)."""
    b = bytes(ip16.tolist() if hasattr(ip16, "tolist") else ip16)
    addr = ipaddress.IPv6Address(b)
    v4 = addr.ipv4_mapped
    return str(v4) if v4 is not None else str(addr)


class SvcInfoRegistry:
    def __init__(self):
        self._by_id: dict[int, dict] = {}
        self._cols_cache = None     # built columns; invalidated on update

    def update(self, recs: np.ndarray) -> int:
        if len(recs):
            self._cols_cache = None
        for r in recs:
            gid = int(r["glob_id"])
            self._by_id[gid] = {
                "ip": format_ip(r["addr"]["ip"]),
                "port": int(r["addr"]["port"]),
                "tstart_usec": int(r["tusec_start"]),
                "cmdline_id": int(r["cmdline_id"]),
                "comm_id": int(r["comm_id"]),
                "relsvcid": int(r["related_listen_id"]),
                "pid": int(r["pid"]),
                "is_any_ip": bool(r["is_any_ip"]),
                "is_http": bool(r["is_http"]),
                "hostid": int(r["host_id"]),
            }
        return len(recs)

    def get(self, glob_id: int) -> dict | None:
        return self._by_id.get(glob_id)

    def __len__(self) -> int:
        return len(self._by_id)

    def columns(self, names=None):
        """Dense presentation columns for the svcinfo subsystem.

        Built columns are cached until the next ``update`` — metadata is
        announce-rate while queries are interactive-rate, so per-query
        Python row loops would stall the ingest loop at 65k listeners.
        (Cache keys on the names registry identity: resolved names can
        change when late NAME_INTERN announcements land, which bumps
        ``names.version``.)"""
        from gyeeta_tpu.ingest import wire

        ver = getattr(names, "version", None)
        if self._cols_cache is not None and self._cols_cache[0] == ver:
            return self._cols_cache[1]

        ids = sorted(self._by_id)
        rows = [self._by_id[i] for i in ids]
        n = len(ids)

        def resolve(kind, vals):
            vals = np.asarray(vals, np.uint64)
            if names is None:
                return np.array([format(int(v), "016x") for v in vals],
                                object)
            return names.resolve_array(kind, vals)

        def num(key):
            return np.array([r[key] for r in rows], np.float64)

        cols = {
            "svcid": np.array([format(i, "016x") for i in ids], object),
            "svcname": resolve(wire.NAME_KIND_SVC, ids),
            "ip": np.array([r["ip"] for r in rows], object),
            "port": num("port"),
            "tstart": np.array([r["tstart_usec"] / 1e6 for r in rows],
                               np.float64),
            "comm": resolve(wire.NAME_KIND_COMM,
                            [r["comm_id"] for r in rows]),
            "cmdline": resolve(wire.NAME_KIND_COMM,
                               [r["cmdline_id"] for r in rows]),
            "pid": num("pid"),
            "relsvcid": np.array([format(r["relsvcid"], "016x")
                                  for r in rows], object),
            "anyip": np.array([r["is_any_ip"] for r in rows], bool),
            "ishttp": np.array([r["is_http"] for r in rows], bool),
            "hostid": num("hostid"),
        }
        out = (cols, np.ones(n, bool))
        self._cols_cache = (ver, out)
        return out
