"""Server notification log — the ``notifymsg`` subsystem backing store.

The reference surfaces operational messages to the UI via
notificationtbl rows (``server/gy_mdb_schema.cc:101`` — agent
connects/disconnects, alert lifecycle, config events) queryable as
SUBSYS_NOTIFYMSG. Here: a bounded in-memory ring the runtime and the
network edge append to; queryable live like every other subsystem.
"""

from __future__ import annotations

import collections
import time
from typing import NamedTuple, Optional

import numpy as np

NOTIFY_INFO = "info"
NOTIFY_WARN = "warn"
NOTIFY_ERROR = "error"


class Notification(NamedTuple):
    tusec: float
    ntype: str          # info | warn | error
    source: str         # agent | alert | server | config
    msg: str


class NotifyLog:
    def __init__(self, maxlen: int = 10_000,
                 clock: Optional[callable] = None):
        self._ring: collections.deque = collections.deque(maxlen=maxlen)
        self._clock = clock or time.time
        # producers include non-loop threads (the crashguard watchdog,
        # alert delivery callbacks): a lock keeps columns()'s snapshot
        # iteration safe against cross-thread appends
        import threading
        self._lock = threading.Lock()

    def add(self, msg: str, ntype: str = NOTIFY_INFO,
            source: str = "server") -> None:
        with self._lock:
            self._ring.append(
                Notification(self._clock(), ntype, source, msg))

    def add_alert(self, alert) -> None:
        """One fired :class:`~gyeeta_tpu.alerts.manager.Alert` → entry
        (shared by both runtimes so the format/severity mapping can't
        diverge)."""
        self.add(f"alert {alert.alertname} [{alert.severity}] "
                 f"{alert.entity}",
                 ntype=NOTIFY_WARN if alert.severity in ("warning", "info")
                 else NOTIFY_ERROR, source="alert")

    def __len__(self) -> int:
        return len(self._ring)

    def columns(self, names=None):
        """Newest first."""
        with self._lock:
            rows = list(self._ring)[::-1]
        n = len(rows)

        def obj(vals):
            out = np.empty(n, object)
            out[:] = vals
            return out

        cols = {
            "time": np.array([r.tusec for r in rows], np.float64),
            "type": obj([r.ntype for r in rows]),
            "source": obj([r.source for r in rows]),
            "msg": obj([r.msg for r in rows]),
        }
        return cols, np.ones(n, bool)
