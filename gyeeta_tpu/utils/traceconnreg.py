"""TRACECONN registry: traced connections per service, host-side.

The reference keeps per-connection grouping for traced requests next
to the per-API aggregation (SUBSYS_TRACECONN,
``gy_json_field_maps.h:2670``: svcid, service comm, connid, client
process group, client comm, client-is-service). Connection identity is
announce-rate metadata — it belongs in a bounded host-side registry
(like svcinfo/hostinfo), not a device slab; the per-API latency slab
stays the device half.

Fed from RAW REQ_TRACE records before columnar decode (the same
pattern as ``natreg``/``svcreg``): conn_id → identity + request
tallies, bounded with oldest-idle eviction.
"""

from __future__ import annotations

import numpy as np

from gyeeta_tpu.ingest import wire


class TraceConnRegistry:
    def __init__(self, capacity: int = 65536):
        self.capacity = capacity
        # conn_id -> [svc_glob_id, cli_task, cli_comm_id, host_id,
        #             nreq, last_tick]
        self._conns: dict[int, list] = {}
        self._tick = 0

    def observe(self, recs: np.ndarray) -> int:
        """Fold one raw REQ_TRACE chunk; returns records folded.

        Vectorized tally: one ``np.unique`` collapses the chunk to its
        distinct conn_ids (usually ≪ records — conns are persistent),
        so the Python dict work is per-CONN, not per-record (the hot
        ingest path stays vectorized)."""
        if not len(recs):
            return 0
        cids = recs["conn_id"].astype(np.uint64)
        uniq, first, counts = np.unique(cids, return_index=True,
                                        return_counts=True)
        for cid, fi, cnt in zip(uniq.tolist(), first.tolist(),
                                counts.tolist()):
            if not cid:
                continue
            ent = self._conns.get(cid)
            if ent is None:
                if len(self._conns) >= self.capacity:
                    self._evict()
                r = recs[fi]
                self._conns[cid] = [int(r["svc_glob_id"]),
                                    int(r["cli_task_aggr_id"]),
                                    int(r["cli_comm_id"]),
                                    int(r["host_id"]), cnt, self._tick]
            else:
                ent[4] += cnt
                ent[5] = self._tick
        return len(recs)

    def _evict(self) -> None:
        """Drop the oldest-idle eighth (amortized, bounded walk)."""
        items = sorted(self._conns.items(), key=lambda kv: kv[1][5])
        for cid, _ in items[: max(1, len(items) // 8)]:
            del self._conns[cid]

    def age(self, max_idle_ticks: int = 720) -> int:
        self._tick += 1
        stale = [cid for cid, e in self._conns.items()
                 if self._tick - e[5] > max_idle_ticks]
        for cid in stale:
            del self._conns[cid]
        return len(stale)

    def __len__(self) -> int:
        return len(self._conns)

    def columns(self, names=None, svc_task_ids=None):
        """(cols, mask) for SUBSYS_TRACECONN. ``svc_task_ids`` is the
        set of process-group ids (hex) that serve a listener — rows
        whose client group is in it get ``csvc`` (client is itself a
        service, the mesh-edge flag of the reference's traceconn)."""
        n = len(self._conns)
        hx = lambda v: format(v & (2**64 - 1), "016x")  # noqa: E731
        svcid = np.empty(n, object)
        connid = np.empty(n, object)
        cprocid = np.empty(n, object)
        cname = np.empty(n, object)
        svcname = np.empty(n, object)
        csvc = np.zeros(n, bool)
        nreq = np.zeros(n, np.float64)
        hostid = np.zeros(n, np.float64)
        idle = np.zeros(n, np.float64)
        task_ids = svc_task_ids or set()
        for i, (cid, e) in enumerate(sorted(self._conns.items())):
            svcid[i] = hx(e[0])
            connid[i] = hx(cid)
            cprocid[i] = hx(e[1])
            comm = ""
            if names is not None:
                comm = names.lookup(wire.NAME_KIND_COMM, e[2]) or ""
                svcname[i] = names.lookup(wire.NAME_KIND_SVC, e[0]) \
                    or ""
            else:
                svcname[i] = ""
            cname[i] = comm
            csvc[i] = cprocid[i] in task_ids
            nreq[i] = e[4]
            hostid[i] = e[3]
            idle[i] = self._tick - e[5]
        cols = {"svcid": svcid, "name": svcname, "connid": connid,
                "cprocid": cprocid, "cname": cname, "csvc": csvc,
                "nreq": nreq, "hostid": hostid, "idleticks": idle}
        return cols, np.ones(n, bool)
