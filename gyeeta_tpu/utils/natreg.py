"""VIP / NAT-IP cluster registry — the ``svcipclust`` backing store.

The reference's shyama groups listeners that are reached through a
shared NAT/virtual IP into load-balancer clusters
(``check_svc_nat_ip_clusters``, ``server/gy_shconnhdlr.h:1301``;
``SvcNatIPOne`` entities, ``server/gy_shsocket.h:98``): two services
observed behind the same DNAT tuple are replicas behind one VIP.

Here the signal is extracted host-side from the raw TCP_CONN records as
they stream through ``feed`` (the nat_ser tuple is pre-device data the
engine's flow key folds away): each (vip, service) observation bumps a
bounded map with sweep-based ageing, and ``columns()`` renders one row
per pairing with the VIP's member count — the queryable cluster view.
"""

from __future__ import annotations

import numpy as np

from gyeeta_tpu.ingest import wire
from gyeeta_tpu.utils.svcreg import format_ip


class NatClusterRegistry:
    """(vip_key → {svc_glob_id: last_sweep}); vip_key packs the folded
    DNAT address and port."""

    def __init__(self, max_vips: int = 4096, max_age: int = 720):
        self._vips: dict[tuple, dict[int, int]] = {}
        self._vip_disp: dict[tuple, str] = {}
        # split-half resolution: backend tuple → (vip_key, last_sweep),
        # learned from client halves whose callee id is still unknown
        self._pending: dict[tuple, tuple] = {}
        self._version = 0           # bumped ONLY on membership change
        self._sweep = 0
        self.max_vips = max_vips
        self.max_age = max_age      # sweeps (ticks) without observation
        self._cache = None

    def observe_conns(self, recs: np.ndarray) -> int:
        """Fold raw TCP_CONN records. A DNAT-translated row
        (nat_ser set) dialed a VIP — the ORIGINAL ``ser`` address:

        - locally-resolved rows (ser_glob_id known) register
          (vip → backend) directly;
        - cross-host client halves (ser_glob_id == 0) remember
          (backend tuple → vip); the backend's own accept half, whose
          ``ser`` IS that tuple, later resolves the backend id — the
          host-side miniature of the pairing join.

        Work is bounded by DISTINCT (tuple, svc) pairs per chunk, not
        traffic volume (np.unique pre-dedup): VIP-heavy fleets translate
        nearly every connection."""
        nat = recs["nat_ser"]["ip"].any(axis=1)
        known = recs["ser_glob_id"] != 0
        n = 0

        def uniq(rows, with_nat):
            cols = [recs["ser"]["ip"][rows].reshape(len(rows), -1),
                    recs["ser"]["port"][rows, None].astype(np.uint32),
                    recs["ser_glob_id"][rows, None].astype(np.uint64)]
            if with_nat:
                cols.append(
                    recs["nat_ser"]["ip"][rows].reshape(len(rows), -1))
                cols.append(recs["nat_ser"]["port"][rows, None]
                            .astype(np.uint32))
            packed = np.concatenate(
                [np.ascontiguousarray(c).view(np.uint8).reshape(
                    len(rows), -1) for c in cols], axis=1)
            return rows[np.unique(packed, axis=0, return_index=True)[1]]

        # direct registrations (merged records)
        rows = np.nonzero(nat & known)[0]
        for i in uniq(rows, False) if len(rows) else ():
            n += self._register(
                (recs["ser"]["ip"][i].tobytes(),
                 int(recs["ser"]["port"][i])),
                recs["ser"]["ip"][i], int(recs["ser"]["port"][i]),
                int(recs["ser_glob_id"][i]))
        # client halves: learn backend-tuple → vip
        rows = np.nonzero(nat & ~known)[0]
        for i in uniq(rows, True) if len(rows) else ():
            bkey = (recs["nat_ser"]["ip"][i].tobytes(),
                    int(recs["nat_ser"]["port"][i]))
            vkey = (recs["ser"]["ip"][i].tobytes(),
                    int(recs["ser"]["port"][i]))
            if len(self._pending) < 4 * self.max_vips:
                self._pending[bkey] = (
                    vkey, recs["ser"]["ip"][i].copy(),
                    int(recs["ser"]["port"][i]), self._sweep)
        # accept halves resolve pending vips by their own ser tuple
        if self._pending:
            rows = np.nonzero(known)[0]
            for i in uniq(rows, False) if len(rows) else ():
                bkey = (recs["ser"]["ip"][i].tobytes(),
                        int(recs["ser"]["port"][i]))
                hit = self._pending.get(bkey)
                if hit is not None:
                    vkey, vip_ip, vip_port, _ = hit
                    n += self._register(vkey, vip_ip, vip_port,
                                        int(recs["ser_glob_id"][i]))
        return n

    def _register(self, key, ip16, port: int, svc: int) -> int:
        ent = self._vips.get(key)
        if ent is None:
            if len(self._vips) >= self.max_vips:
                return 0
            ent = self._vips[key] = {}
            self._vip_disp[key] = f"{format_ip(ip16)}:{port}"
        if svc not in ent:
            self._version += 1      # refreshes don't invalidate caches
        ent[svc] = self._sweep
        return 1

    def age(self) -> int:
        """Advance the sweep clock; drop members (and empty VIPs) not
        observed within ``max_age`` sweeps; expire unresolved pending
        halves fast (they resolve within a sweep or never)."""
        self._sweep += 1
        dropped = 0
        for key in list(self._vips):
            ent = self._vips[key]
            for svc in [s for s, t in ent.items()
                        if self._sweep - t > self.max_age]:
                del ent[svc]
                dropped += 1
            if not ent:
                del self._vips[key]
                self._vip_disp.pop(key, None)
        for key in [k for k, v in self._pending.items()
                    if self._sweep - v[3] > 2]:
            del self._pending[key]
        if dropped:
            self._version += 1
        return dropped

    def __len__(self) -> int:
        return len(self._vips)

    def columns(self, names=None):
        """One row per (vip, service) pairing; nsvc = replicas behind
        the VIP (rows with nsvc > 1 are the actual clusters)."""
        ver = (getattr(names, "version", None), self._version)
        if self._cache is not None and self._cache[0] == ver:
            return self._cache[1]
        vips, svcids, svcnames, nsvc = [], [], [], []
        for key in sorted(self._vips):
            ent = self._vips[key]
            disp = self._vip_disp[key]
            for svc in sorted(ent):
                vips.append(disp)
                svcids.append(format(svc, "016x"))
                if names is not None:
                    nm = names.lookup(wire.NAME_KIND_SVC, svc)
                    svcnames.append(nm if nm is not None
                                    else format(svc, "016x"))
                else:
                    svcnames.append(format(svc, "016x"))
                nsvc.append(float(len(ent)))
        n = len(vips)

        def obj(vals):
            out = np.empty(n, object)
            out[:] = vals
            return out

        cols = {"vip": obj(vips), "svcid": obj(svcids),
                "svcname": obj(svcnames),
                "nsvc": np.array(nsvc, np.float64)}
        out = (cols, np.ones(n, bool))
        self._cache = (ver, out)
        return out
