"""Checkpoint/restore of engine state (sketch snapshots).

The reference has no process checkpointing — durable state is Postgres and
agents resend inventory on reconnect (SURVEY §5). The TPU tier adds real
checkpoints: AggState is one pytree of arrays, so a snapshot is an
``npz`` with the flattened leaves plus a config fingerprint; restore
refuses a mismatched geometry instead of silently mis-slicing HBM.
Recovery composes both: restore the sketch snapshot, then replay from
agents/history for the gap.
"""

from __future__ import annotations

import hashlib
import json
import pathlib

import jax
import numpy as np


def _cfg_fingerprint(cfg) -> str:
    # repr-text equality: any geometry field change invalidates restores
    return hashlib.sha256(repr(cfg).encode()).hexdigest()[:16]


def save(path, cfg, state, extra: dict | None = None) -> pathlib.Path:
    """Write state pytree → ``<path>`` (npz). Atomic AND durable:
    tmp + fsync(file) + rename + fsync(dir). Without the fsyncs a
    crash (or power loss) shortly after the rename can leave the
    NEWEST checkpoint torn on disk — exactly the file a supervised
    ``--restore-latest`` restart reaches for first (the walk-back in
    ``server_main.checkpoint_candidates`` then lands on the next-older
    one, but a torn newest should be the rare case, not the norm)."""
    import os

    path = pathlib.Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    leaves, treedef = jax.tree_util.tree_flatten(state)
    payload = {f"leaf_{i}": np.asarray(x) for i, x in enumerate(leaves)}
    payload["__meta__"] = np.frombuffer(json.dumps({
        "nleaves": len(leaves),
        "cfg": _cfg_fingerprint(cfg),
        "extra": extra or {},
    }).encode(), dtype=np.uint8)
    tmp = path.with_suffix(".tmp.npz")
    with open(tmp, "wb") as f:
        np.savez_compressed(f, **payload)
        f.flush()
        os.fsync(f.fileno())      # file contents durable BEFORE rename
    tmp.rename(path)
    try:                          # …and the rename itself durable
        dfd = os.open(path.parent, os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except OSError:               # pragma: no cover — exotic fs
        pass
    # a crash between open(tmp) and the rename strands a .tmp.npz; left
    # alone they accumulate forever in the checkpoint dir. Each
    # SUCCESSFUL save sweeps siblings (its own tmp was just renamed
    # away, so anything still matching is a previous crash's orphan).
    sweep_stale_tmp(path.parent)
    return path


def sweep_stale_tmp(ckpt_dir) -> int:
    """Delete ``*.tmp.npz`` staging orphans left by a crash
    mid-:func:`save`. Called on daemon start and after each successful
    save; never touches completed checkpoints (the
    ``checkpoint_candidates`` walk already excludes tmp files, so this
    is disk hygiene, not correctness). Returns files removed."""
    import os as _os

    n = 0
    d = pathlib.Path(ckpt_dir)
    if not d.is_dir():
        return 0
    for p in d.glob("*.tmp.npz"):
        try:
            _os.unlink(p)
            n += 1
        except OSError:           # pragma: no cover — already gone
            pass
    return n


def restore(path, cfg, like):
    """Read a checkpoint into the structure of ``like`` (same treedef).

    Raises ValueError on config-fingerprint or leaf-shape mismatch.
    Returns (state, extra_dict).
    """
    path = pathlib.Path(path)
    with np.load(path) as z:
        meta = json.loads(bytes(z["__meta__"]).decode())
        if meta["cfg"] != _cfg_fingerprint(cfg):
            raise ValueError(
                f"checkpoint config fingerprint {meta['cfg']} does not "
                f"match engine config {_cfg_fingerprint(cfg)}")
        leaves, treedef = jax.tree_util.tree_flatten(like)
        if meta["nleaves"] != len(leaves):
            raise ValueError(
                f"checkpoint has {meta['nleaves']} leaves, engine state "
                f"has {len(leaves)} — incompatible versions")
        new_leaves = []
        for i, ref in enumerate(leaves):
            arr = z[f"leaf_{i}"]
            if arr.shape != ref.shape:
                raise ValueError(
                    f"leaf {i}: checkpoint shape {arr.shape} != "
                    f"state shape {ref.shape}")
            new_leaves.append(arr.astype(ref.dtype))
        return (jax.tree_util.tree_unflatten(treedef, new_leaves),
                meta["extra"])
