"""Drop-pressure monitoring: turn silent insert/overflow drops into
operator signals (VERDICT r4 #10).

Device tables drop inserts at probe exhaustion (``engine/table.py``
``n_drop``), the dep graph and the a2a pairing tier drop on dispatch
overflow (``parallel/depgraph.py``/``pairing.py`` ``n_dropped``).
Every drop is counted, but a counter an operator must poll is not a
signal — the reference prints pool/capture-stats pressure on cadence
(``common/gy_svc_net_capture.h:191`` print_stats) and raises
notifications for resource pressure. This helper diffs the counters
each tick and emits a notifymsg (warn; error when the growth rate
says the table is badly undersized) + selfstats gauges.
"""

from __future__ import annotations

# growth per tick above this fraction of capacity = sizing failure
_ERROR_FRAC = 0.01


# sentinel key carried in the returned ``last`` dict: whether the
# previous check saw growth (the enter/exit edge detector)
_ACTIVE = "_pressure_active"


def check(drops: dict, caps: dict, last: dict, notifylog, stats) -> dict:
    """Compare cumulative drop counters against the previous tick.

    ``drops``: {name: cumulative count}; ``caps``: {name: capacity};
    ``last``: previous tick's ``drops`` (mutated copy returned).
    Emits one notifymsg per tick listing every growing counter.

    Counter surface (all visible in ``Stats.delta()`` and /metrics):
    ``dropped_records_<name>`` attributes drops per subsystem per
    cadence; ``drop_pressure_enter``/``drop_pressure_exit`` count the
    pressure-state edges; the ``engine_drop_pressure`` gauge holds the
    current state (1 = drops grew this tick).
    """
    grew = {}
    for name, v in drops.items():
        stats.gauge(f"drops_{name}", v)
        d = v - last.get(name, 0)
        if d > 0:
            grew[name] = d
            stats.bump(f"dropped_records_{name}", int(d))
    was_active = bool(last.get(_ACTIVE))
    if grew:
        severe = any(d >= max(_ERROR_FRAC * caps.get(n, 1 << 30), 1.0)
                     for n, d in grew.items())
        detail = ", ".join(f"{n}+{int(d)} (total {int(drops[n])})"
                           for n, d in sorted(grew.items()))
        notifylog.add(
            f"insert drops growing: {detail} — table under-sized or "
            f"overload; raise capacity or shed load",
            ntype="error" if severe else "warn", source="selfmon")
        stats.bump("drop_pressure_events")
        if not was_active:
            stats.bump("drop_pressure_enter")
    elif was_active:
        stats.bump("drop_pressure_exit")
    stats.gauge("engine_drop_pressure", 1.0 if grew else 0.0)
    out = dict(drops)
    out[_ACTIVE] = bool(grew)
    return out
