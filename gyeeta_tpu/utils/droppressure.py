"""Drop-pressure monitoring: turn silent insert/overflow drops into
operator signals (VERDICT r4 #10).

Device tables drop inserts at probe exhaustion (``engine/table.py``
``n_drop``), the dep graph and the a2a pairing tier drop on dispatch
overflow (``parallel/depgraph.py``/``pairing.py`` ``n_dropped``).
Every drop is counted, but a counter an operator must poll is not a
signal — the reference prints pool/capture-stats pressure on cadence
(``common/gy_svc_net_capture.h:191`` print_stats) and raises
notifications for resource pressure. This helper diffs the counters
each tick and emits a notifymsg (warn; error when the growth rate
says the table is badly undersized) + selfstats gauges.
"""

from __future__ import annotations

# growth per tick above this fraction of capacity = sizing failure
_ERROR_FRAC = 0.01


def check(drops: dict, caps: dict, last: dict, notifylog, stats) -> dict:
    """Compare cumulative drop counters against the previous tick.

    ``drops``: {name: cumulative count}; ``caps``: {name: capacity};
    ``last``: previous tick's ``drops`` (mutated copy returned).
    Emits one notifymsg per tick listing every growing counter.
    """
    grew = {}
    for name, v in drops.items():
        stats.gauge(f"drops_{name}", v)
        d = v - last.get(name, 0)
        if d > 0:
            grew[name] = d
    if grew:
        severe = any(d >= max(_ERROR_FRAC * caps.get(n, 1 << 30), 1.0)
                     for n, d in grew.items())
        detail = ", ".join(f"{n}+{int(d)} (total {int(drops[n])})"
                           for n, d in sorted(grew.items()))
        notifylog.add(
            f"insert drops growing: {detail} — table under-sized or "
            f"overload; raise capacity or shed load",
            ntype="error" if severe else "warn", source="selfmon")
        stats.bump("drop_pressure_events")
    return dict(drops)
