"""Layered configuration: defaults ≺ JSON file ≺ env ≺ explicit overrides.

The reference's settings system (``PA_SETTINGS_C`` etc.,
``partha/gypartha.cc:456``) layers cfg JSON files, ``CFG_*`` env vars and
``--cfg_*`` CLI flags (which just setenv, :1813). Same model here with the
``GYT_`` prefix, plus the hot-reload runtime file (mtime-polled
``*_runtime.json``, :1965) for knobs that may change while running.
"""

from __future__ import annotations

import json
import os
import pathlib
from typing import Any, NamedTuple, Optional

from gyeeta_tpu.engine.aggstate import EngineCfg
from gyeeta_tpu.sketch import loghist

ENV_PREFIX = "GYT_"

# EngineCfg ints settable via cfg file/env; loghist specs via *_vmin etc.
_INT_FIELDS = {"svc_capacity", "n_hosts", "hll_p_svc", "hll_p_global",
               "cms_depth", "cms_width", "topk_capacity", "td_capacity",
               "conn_batch", "resp_batch",
               "listener_batch", "fold_k", "task_capacity",
               # fold-path tuning knobs (OPERATIONS.md "Fold-path
               # tuning"): digest duty cycle + staging geometry
               "td_sample_stride", "td_stage_cap", "td_flush_m",
               "topk_budget",
               # heavy-hitter tier geometry (sketch/invertible.py)
               "hh_depth", "hh_width"}

# EngineCfg floats settable via cfg file/env (hot-admission floor)
_FLOAT_FIELDS = {"hh_hot_frac"}


class RuntimeOpts(NamedTuple):
    """Process-level knobs outside engine geometry."""
    checkpoint_dir: Optional[str] = None
    checkpoint_every_ticks: int = 720       # 1 hour of 5s ticks
    history_db: Optional[str] = None
    history_every_ticks: int = 12           # 1 min
    compact_tomb_frac: float = 0.25         # compact when tombs exceed
    task_age_every_ticks: int = 12          # ageing sweep cadence (1 min)
    task_max_age_ticks: int = 36            # evict groups unseen for 3 min
    api_max_age_ticks: int = 360            # evict idle (svc,api) rows 30m
    debug_level: int = 0                    # hot-reloadable
    resp_sample_pct: float = 100.0          # hot-reloadable duty cycle
    trace_resp_bridge: bool = True          # parsed transactions also
    #                                         feed the per-svc response
    #                                         sketches (real latencies —
    #                                         the eBPF xmit-probe resp
    #                                         stream analogue, ref
    #                                         common/gy_socket_stat.cc:1554).
    #                                         Per-host precedence: a host
    #                                         with a native RESP_SAMPLE
    #                                         stream is never bridged, so
    #                                         dual-stream hosts don't
    #                                         double-count transactions.
    hh_recover_every_ticks: int = 1         # heavy-hitter key-recovery
    #                                         cadence (one read-only
    #                                         readback per N ticks,
    #                                         memoized per state
    #                                         version; 0 = on-demand
    #                                         only — `topk` queries and
    #                                         alertdefs still recover)
    td_drain_iters_per_tick: int = 2        # bounded digest compression
    #                                         per tick (O(td_flush_m)
    #                                         each); overflow drops are
    #                                         counted, loghist stays the
    #                                         lossless percentile path
    # dependency graph (parallel/depgraph.py): slab sizes + TTLs
    # in-flight unpaired halves: sized so one flattened fold_k-deep
    # dispatch of one-sided halves (fold_k × conn_batch = 32768 by
    # default) fits at <70% load even before intra-dispatch pairing
    # reclaims rows (ref: ~100k unresolved-conn cap per madhava,
    # server/gy_mconnhdlr.h:94)
    dep_pair_capacity: int = 65536
    dep_edge_capacity: int = 16384          # dependency edges tracked
    dep_pair_ttl_ticks: int = 24            # unpaired halves expire (2 min)
    dep_edge_ttl_ticks: int = 720           # idle edges expire (1 h)
    # write-ahead event journal (utils/journal.py): bounds data loss to
    # the last group fsync instead of the last checkpoint. None = off.
    journal_dir: Optional[str] = None
    journal_segment_mb: int = 64            # segment rotation size
    journal_fsync_kb: int = 1024            # group-fsync byte cadence
    journal_fsync_ms: float = 50.0          # …or ms cadence (first wins);
    #                                         RPO ≈ max pending bytes age
    #                                         — see OPERATIONS.md
    #                                         "Durability & recovery"
    journal_backlog_mb: int = 64            # writer-thread backlog bound:
    #                                         past it the oldest queued
    #                                         chunks drop COUNTED (the
    #                                         wire outran the disk; the
    #                                         admission controller
    #                                         throttles before this)
    # ---- history tier: relational-writer offload + time-travel shards
    # (OPERATIONS.md "History & time travel"; env knobs GYT_HIST_*)
    history_queue_max: int = 64             # bounded sweep queue of the
    #                                         single-writer history
    #                                         thread; overflow drops the
    #                                         OLDEST sweep, counted —
    #                                         a slow DB can no longer
    #                                         stall run_tick
    hist_shard_dir: Optional[str] = None    # snapshot-shard directory:
    #                                         enables the time-travel
    #                                         query tier (at=/window=
    #                                         on every edge). None=off.
    hist_window_ticks: int = 12             # raw shard window (1m at 5s
    #                                         ticks) — the time-travel
    #                                         resolution of the raw tier
    hist_mid_every: int = 15                # raws per mid shard (15m)
    hist_hour_every: int = 4                # mids per hour shard (1h)
    hist_retain_raw: int = 60               # raw shards kept before
    #                                         downsampling to mid (1h
    #                                         of 1m windows by default)
    hist_retain_mid: int = 96               # mid shards kept (24h)
    hist_retain_hour: int = 168             # hour shards kept (7d),
    #                                         older DROP
    hist_compact_interval_s: float = 30.0   # compaction daemon cadence


def _coerce(key: str, v: Any):
    if key in _INT_FIELDS:
        return int(v)
    if key in _FLOAT_FIELDS:
        return float(v)
    return v


def load_engine_cfg(cfg_file: Optional[str] = None,
                    env: Optional[dict] = None,
                    **overrides) -> EngineCfg:
    """defaults ≺ JSON file ≺ GYT_<FIELD> env ≺ kwargs."""
    env = os.environ if env is None else env
    spec_keys = {f"{n}_{p}" for n in ("resp", "qps", "active", "taskcpu")
                 for p in ("vmin", "vmax", "nbuckets")}
    known = set(EngineCfg._fields) | spec_keys
    vals: dict = {}
    if cfg_file:
        with open(cfg_file) as f:
            data = json.load(f)
        for k, v in data.get("engine", data).items():
            if k in known:
                vals[k] = _coerce(k, v)
    for k in known:
        ev = env.get(ENV_PREFIX + k.upper())
        if ev is not None:
            vals[k] = _coerce(k, ev)
    vals.update({k: _coerce(k, v) for k, v in overrides.items()})
    specs = {}
    for name in ("resp", "qps", "active", "taskcpu"):
        base = getattr(EngineCfg(), f"{name}_spec")
        parts = {}
        for p in ("vmin", "vmax", "nbuckets"):
            key = f"{name}_{p}"
            if key in vals:
                parts[p] = float(vals.pop(key)) if p != "nbuckets" \
                    else int(vals.pop(key))
        if parts:
            specs[f"{name}_spec"] = base._replace(**parts)
    unknown = set(vals) - set(EngineCfg._fields)
    if unknown:
        raise ValueError(f"unknown engine config keys: {sorted(unknown)}")
    return EngineCfg(**{**vals, **specs})


def load_runtime_opts(cfg_file: Optional[str] = None,
                      env: Optional[dict] = None,
                      **overrides) -> RuntimeOpts:
    env = os.environ if env is None else env
    vals: dict = {}
    if cfg_file:
        with open(cfg_file) as f:
            data = json.load(f)
        for k, v in data.get("runtime", {}).items():
            if k in RuntimeOpts._fields:
                vals[k] = v
    for k in RuntimeOpts._fields:
        ev = env.get(ENV_PREFIX + k.upper())
        if ev is not None:
            d = getattr(RuntimeOpts(), k)
            vals[k] = type(d)(ev) if d is not None else ev
    vals.update(overrides)
    unknown = set(vals) - set(RuntimeOpts._fields)
    if unknown:
        raise ValueError(f"unknown runtime config keys: {sorted(unknown)}")
    return RuntimeOpts(**vals)


class HotReload:
    """mtime-polled runtime knob file (``tmp/*_runtime.json`` analogue).

    ``poll()`` re-reads the file when its mtime changed and returns the
    updated RuntimeOpts (only hot-reloadable fields are applied)."""

    HOT_FIELDS = ("debug_level", "resp_sample_pct")

    def __init__(self, path, opts: RuntimeOpts):
        self.path = pathlib.Path(path)
        self.opts = opts
        self._mtime = 0.0

    def poll(self) -> RuntimeOpts:
        try:
            mtime = self.path.stat().st_mtime
        except FileNotFoundError:
            return self.opts
        if mtime == self._mtime:
            return self.opts
        self._mtime = mtime
        try:
            data = json.loads(self.path.read_text())
        except (json.JSONDecodeError, OSError):
            return self.opts          # malformed hot file is ignored
        # knobs live under "runtime" in the daemon config shape
        # ({engine:…, runtime:…}); accept top-level too for bare knob files
        src = data.get("runtime", data) if isinstance(data, dict) else {}
        hot = {k: type(getattr(self.opts, k))(v)
               for k, v in src.items()
               if k in self.HOT_FIELDS and
               type(getattr(self.opts, k))(v) != getattr(self.opts, k)}
        if hot:   # unchanged file content must keep object identity
            self.opts = self.opts._replace(**hot)
        return self.opts
