"""Version-keyed column-snapshot memo shared by both runtimes.

One mechanism to audit (VERDICT r3 weak #4 fix): queries between state
changes serve cached (cols, mask) snapshots; every mutation path calls
``bump()``. The invalidation RULES stay per-runtime (what counts as a
mutation differs — single-node folds staged backlogs, the mesh folds
per feed), but the memo mechanics live here once.
"""

from __future__ import annotations


class ColumnCache:
    def __init__(self):
        self.version = 0
        self._cache: dict = {}

    def bump(self) -> None:
        """Invalidate AND evict. Entries can hold LazyCols whose group
        loaders close over the full device AggState — keeping stale
        entries until their subsys is re-queried would pin a second
        multi-GB state on device (and defeat fold donation, which
        silently copies when another live reference exists)."""
        self.version += 1
        self._cache.clear()

    def clear(self) -> None:
        self._cache.clear()

    def peek(self, subsys: str):
        """Current-version cached entry or None — the lock-free fast
        path of the snapshot tier's single-flight column compute."""
        ent = self._cache.get(subsys)
        if ent is not None and ent[0] == self.version:
            return ent[1]
        return None

    def get(self, subsys: str, compute):
        """Cached (cols, mask) for ``subsys``; ``compute()`` runs only
        when the cached entry predates the current version."""
        ent = self._cache.get(subsys)
        if ent is not None and ent[0] == self.version:
            return ent[1]
        out = compute()
        self._cache[subsys] = (self.version, out)
        return out
