from gyeeta_tpu.utils import hashing

__all__ = ["hashing"]
