"""In-process crash forensics + liveness watchdog (component row 8).

The reference's init tier installs fatal-signal handlers that dump a
backtrace before dying and runs scheduler watchdogs that detect stuck
threads (``common/gy_init_proc.cc`` signal setup; scheduler liveness
checks). The Python-runtime equivalents:

- :func:`enable_crash_dumps` — ``faulthandler`` on SIGSEGV/FPE/ABRT/
  BUS writes every thread's stack to a crash file before the process
  dies (the post-mortem the reference's handler prints), plus
  SIGQUIT-on-demand dumps for live debugging.
- :class:`TickWatchdog` — a daemon thread watching a heartbeat the
  serving loop beats each tick; a silent gap beyond the threshold
  dumps all-thread tracebacks to the crash file and logs loudly
  (a wedged asyncio loop or a blocked device call is otherwise
  invisible until an operator notices stale data).
"""

from __future__ import annotations

import faulthandler
import logging
import threading
import time
from typing import Optional

log = logging.getLogger("gyeeta_tpu.crashguard")

_crash_file = None


def enable_crash_dumps(path: str) -> None:
    """Fatal-signal + on-demand (SIGQUIT) stack dumps into ``path``."""
    global _crash_file
    f = open(path, "a")                    # noqa: SIM115 — lives until
    _crash_file = f                        # process death by design
    faulthandler.enable(file=f, all_threads=True)
    try:
        import signal
        faulthandler.register(signal.SIGQUIT, file=f, all_threads=True,
                              chain=False)
    except (ImportError, AttributeError, ValueError):
        pass                               # non-main thread / platform


class TickWatchdog:
    """Detects a stalled serving loop; dumps stacks once per stall."""

    def __init__(self, stall_after_s: float = 60.0, clock=None,
                 on_stall=None):
        self.stall_after_s = stall_after_s
        self._clock = clock or time.monotonic
        self._last_beat = self._clock()
        self._on_stall = on_stall          # test seam / notify hook
        self._stalled = False
        self.n_stalls = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def beat(self) -> None:
        """Called by the serving loop each tick."""
        self._last_beat = self._clock()
        self._stalled = False

    def start(self) -> None:
        self._stop.clear()                 # restartable after stop()
        self._thread = threading.Thread(target=self._run,
                                        name="gyt-watchdog", daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(min(self.stall_after_s / 4, 5.0)):
            gap = self._clock() - self._last_beat
            if gap > self.stall_after_s and not self._stalled:
                self._stalled = True       # one dump per stall episode
                self.n_stalls += 1
                log.error("serving loop stalled: no tick for %.0fs — "
                          "dumping all thread stacks", gap)
                try:
                    faulthandler.dump_traceback(
                        file=_crash_file or None, all_threads=True)
                except Exception:          # noqa: BLE001 — best effort
                    pass
                if self._on_stall is not None:
                    try:
                        self._on_stall(gap)
                    except Exception:      # noqa: BLE001
                        pass

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
