"""Reverse-DNS annotation cache: ip → domain, bounded + async.

The reference snoops DNS responses off the wire and keeps an ip→domain
map that makes connection views human-readable
(``common/gy_dns_mapping.h:46``). A userspace server can't snoop, but
it can REVERSE-resolve the addresses it serves in views — same
annotation, resolver-driven. Discipline:

- lookups NEVER block the query path: unknown ips return '' and are
  queued for one background worker (``socket.getnameinfo`` with
  NI_NAMEREQD so unresolvable addresses don't echo back as numeric
  strings);
- positive AND negative results cache with TTLs (negative shorter —
  DNS appears for freshly-deployed endpoints);
- bounded: oldest entries evict past ``capacity``.
"""

from __future__ import annotations

import queue
import socket
import threading
import time
from typing import Optional

_POS_TTL = 3600.0
_NEG_TTL = 300.0


class DnsCache:
    def __init__(self, capacity: int = 8192, clock=None):
        self._cache: dict[str, tuple] = {}   # ip → (domain, expiry)
        self._capacity = capacity
        self._clock = clock or time.monotonic
        self._q: queue.Queue = queue.Queue(maxsize=1024)
        self._queued: set = set()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # ----------------------------------------------------------- query
    def get(self, ip: str) -> str:
        """Cached domain for ip ('' unknown/unresolvable); schedules a
        background resolution on miss. Never blocks."""
        now = self._clock()
        ent = self._cache.get(ip)
        if ent is not None and ent[1] > now:
            return ent[0]
        self._schedule(ip)
        return ent[0] if ent is not None else ""

    def annotate(self, ips) -> list:
        return [self.get(ip) for ip in ips]

    def prime(self, ip: str, domain: str, ttl: float = 3600.0) -> None:
        """Insert a PASSIVELY-LEARNED mapping (port-53 snoop,
        ``trace/dnssnoop.py``) — what the IP was resolved AS, which
        beats reverse lookups for CDN/VIP addresses. Same
        oldest-expiry eviction as the resolver path: a full cache
        keeps LEARNING (expired/negative entries go first)."""
        if len(self._cache) >= self._capacity and ip not in self._cache:
            for k in sorted(self._cache,
                            key=lambda k: self._cache[k][1])[
                    : max(1, self._capacity // 8)]:
                del self._cache[k]
        self._cache[ip] = (domain, self._clock() + ttl)

    # ------------------------------------------------------ background
    def _schedule(self, ip: str) -> None:
        if ip in self._queued:
            return
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="gyt-dnsmap", daemon=True)
            self._thread.start()
        try:
            self._queued.add(ip)
            self._q.put_nowait(ip)
        except queue.Full:
            self._queued.discard(ip)

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                ip = self._q.get(timeout=0.25)
            except queue.Empty:
                continue
            domain, ttl = "", _NEG_TTL
            try:
                host, _ = socket.getnameinfo(
                    (ip, 0), socket.NI_NAMEREQD)
                domain, ttl = host, _POS_TTL
            except OSError:
                pass
            now = self._clock()
            if len(self._cache) >= self._capacity:
                # oldest-expiry eviction, amortized
                for k in sorted(self._cache,
                                key=lambda k: self._cache[k][1])[
                        : max(1, self._capacity // 8)]:
                    del self._cache[k]
            self._cache[ip] = (domain, now + ttl)
            self._queued.discard(ip)

    def set(self, ip: str, domain: str,
            ttl: float = _POS_TTL) -> None:
        """Direct insert (tests / future wire-snoop sources)."""
        self._cache[ip] = (domain, self._clock() + ttl)

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=1.0)
            self._thread = None

    def __len__(self) -> int:
        return len(self._cache)


def annotate_vip_cols(colmask, cache: DnsCache):
    """svcipclust (cols, mask) → same + a ``dns`` column for the VIP
    (applied OUTSIDE the registry's column cache — resolutions land
    asynchronously and must surface on the next query)."""
    import numpy as np

    cols, mask = colmask
    out = dict(cols)
    out["dns"] = np.array(
        [cache.get(str(v).rsplit(":", 1)[0]) for v in cols["vip"]],
        object)
    return out, mask
