"""Machine identity + digest/encoding helpers.

The reference derives a stable 128-bit machine id from the OS
(``common/gy_sys_hardware.h`` SYS_HARDWARE: /etc/machine-id with DMI /
boot-id fallbacks) and carries SHA/base64 utilities for tokens and
payload digests (``common/gy_misc.h``). Agents register with this id;
the server's machine-id → host-id placement map keys on it.
"""

from __future__ import annotations

import base64
import hashlib
import pathlib
import socket
import uuid

_MACHINE_ID_PATHS = ("/etc/machine-id", "/var/lib/dbus/machine-id")


def machine_id() -> int:
    """Stable 128-bit machine identity.

    /etc/machine-id (systemd) first; DMI product UUID next; last resort
    a hash of hostname+MAC (stable per boot environment, weaker)."""
    for p in _MACHINE_ID_PATHS:
        try:
            text = pathlib.Path(p).read_text().strip()
            if text:
                return int(text, 16)
        except (OSError, ValueError):
            continue
    try:
        text = pathlib.Path(
            "/sys/class/dmi/id/product_uuid").read_text().strip()
        return uuid.UUID(text).int
    except (OSError, ValueError):
        pass
    seed = f"{socket.gethostname()}:{uuid.getnode():012x}".encode()
    return int.from_bytes(hashlib.sha256(seed).digest()[:16], "big")


def sha256_hex(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def b64_encode(data: bytes) -> str:
    return base64.b64encode(data).decode("ascii")


def b64_decode(text: str) -> bytes:
    return base64.b64decode(text)
