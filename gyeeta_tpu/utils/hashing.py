"""Vectorized 32/64-bit hashing for device-side sketch indexing.

The reference hashes flow 5-tuples and entity ids with cityhash/fnv1
(``common/gy_common_inc.h`` — cityhash; ``common/jhash.h``) on the CPU, one
key at a time. Here every hash is a vectorized uint32 mix evaluated on-device
over whole microbatches, because TPUs have no native 64-bit integer ALU path
worth using: 64-bit keys travel as ``(hi, lo)`` uint32 pairs and all mixing is
modular uint32 arithmetic (murmur3-style finalizers), which XLA maps directly
onto the VPU.

Every function has identical semantics in JAX (device) and numpy (host), so
host-side decoders and tests can reproduce device indices bit-exactly.
"""

from __future__ import annotations

import numpy as np


class _LazyJnp:
    """Deferred ``jax.numpy`` import: this module is on the import path
    of thin clients (query CLI, agents) that never touch a device —
    pulling in jax (and its backend init) there costs seconds and can
    block on an unreachable accelerator. First device-path use swaps
    the real module into the global."""

    def __getattr__(self, name):
        import jax.numpy as jnp
        globals()["jnp"] = jnp
        return getattr(jnp, name)


jnp = _LazyJnp()

# Murmur3 / splitmix constants.
_C1 = 0x85EBCA6B
_C2 = 0xC2B2AE35
_GOLDEN = 0x9E3779B9  # 2^32 / phi — per-salt stream separator


def _is_np(x) -> bool:
    return isinstance(x, (np.ndarray, np.generic, int))


def fmix32(h):
    """Murmur3 32-bit finalizer: bijective avalanche mix of a uint32 array.

    Works on either jnp or np uint32 arrays (wrapping multiply).
    """
    if _is_np(h):
        h = np.asarray(h, dtype=np.uint32)
        with np.errstate(over="ignore"):
            h = h ^ (h >> np.uint32(16))
            h = h * np.uint32(_C1)
            h = h ^ (h >> np.uint32(13))
            h = h * np.uint32(_C2)
            h = h ^ (h >> np.uint32(16))
        return h
    h = h.astype(jnp.uint32)
    h = h ^ (h >> 16)
    h = h * jnp.uint32(_C1)
    h = h ^ (h >> 13)
    h = h * jnp.uint32(_C2)
    h = h ^ (h >> 16)
    return h


def mix64(hi, lo, salt: int = 0):
    """Mix a 64-bit key given as (hi, lo) uint32 halves into one uint32.

    ``salt`` selects an independent hash stream (e.g. one per Count-Min row).
    """
    if _is_np(hi):
        hi = np.asarray(hi, dtype=np.uint32)
        lo = np.asarray(lo, dtype=np.uint32)
        with np.errstate(over="ignore"):
            s = np.uint32((salt + 1) & 0xFFFFFFFF) * np.uint32(_GOLDEN)
            h = fmix32(lo ^ s)
            h = fmix32(hi ^ h ^ np.uint32(salt & 0xFFFFFFFF))
        return h
    hi = hi.astype(jnp.uint32)
    lo = lo.astype(jnp.uint32)
    s = jnp.uint32(((salt + 1) & 0xFFFFFFFF)) * jnp.uint32(_GOLDEN)
    h = fmix32(lo ^ s)
    h = fmix32(hi ^ h ^ jnp.uint32(salt & 0xFFFFFFFF))
    return h


def bucket_indices_km(hi, lo, depth: int, nbuckets: int) -> list:
    """Per-row buckets for a ``depth``-row sketch via Kirsch-Mitzenmacher
    double hashing: ``bucket_r = range_map(h1 + r·h2)`` from TWO key
    mixes instead of one per row (*Less Hashing, Same Performance* —
    the derived streams preserve Count-Min/Bloom error bounds). ``h2``
    is forced odd so consecutive streams never collapse onto each other
    even for adversarial h2 = 0. Identical semantics in numpy and jax
    (same wrap-around uint32 arithmetic)."""
    h1 = mix64(hi, lo, 0xC035)
    h2 = mix64(hi, lo, 0x51ED)
    if _is_np(h1):
        with np.errstate(over="ignore"):
            h2 = h2 | np.uint32(1)
            return [_range_map(h1 + np.uint32(r) * h2, nbuckets)
                    for r in range(depth)]
    h2 = h2 | jnp.uint32(1)
    return [_range_map(h1 + jnp.uint32(r) * h2, nbuckets)
            for r in range(depth)]


def _range_map(h, nbuckets: int):
    """Uniform u32 → [0, nbuckets) (Lemire high-multiply; np + jnp)."""
    if _is_np(h):
        return ((h.astype(np.uint64) * np.uint64(nbuckets))
                >> np.uint64(32)).astype(np.int32)
    n = jnp.uint32(nbuckets)
    a_hi, a_lo = h >> 16, h & jnp.uint32(0xFFFF)
    b_hi, b_lo = n >> 16, n & jnp.uint32(0xFFFF)
    lo_lo = a_lo * b_lo
    t = a_hi * b_lo + (lo_lo >> 16)
    w1 = (t & jnp.uint32(0xFFFF)) + a_lo * b_hi
    res = a_hi * b_hi + (t >> 16) + (w1 >> 16)
    return res.astype(jnp.int32)


def bucket_index(hi, lo, salt: int, nbuckets: int):
    """Map a 64-bit key to a bucket in [0, nbuckets) for hash stream ``salt``.

    nbuckets need not be a power of two; uses the high-multiply range trick
    (Lemire) to avoid modulo bias and the slow integer divide on TPU.
    """
    h = mix64(hi, lo, salt)
    if _is_np(h):
        return ((h.astype(np.uint64) * np.uint64(nbuckets)) >> np.uint64(32)).astype(
            np.int32
        )
    # TPU path: mulhi32(h, n) via four 16x16 partial products (no 64-bit mul).
    n = jnp.uint32(nbuckets)
    a_hi, a_lo = h >> 16, h & jnp.uint32(0xFFFF)
    b_hi, b_lo = n >> 16, n & jnp.uint32(0xFFFF)
    lo_lo = a_lo * b_lo
    t = a_hi * b_lo + (lo_lo >> 16)
    w1 = (t & jnp.uint32(0xFFFF)) + a_lo * b_hi
    res = a_hi * b_hi + (t >> 16) + (w1 >> 16)
    return res.astype(jnp.int32)


def leading_zeros32(x):
    """Count leading zeros of each uint32 (for HyperLogLog rank).

    Returns int32 in [0, 32]. Branch-free binary search, identical on both
    backends.
    """
    if _is_np(x):
        x = np.asarray(x, dtype=np.uint32)
        n = np.zeros(x.shape, dtype=np.int32)
        y = x
        for shift in (16, 8, 4, 2, 1):
            mask = y > np.uint32((1 << shift) - 1)
            n = np.where(mask, n, n + shift)
            y = np.where(mask, y >> np.uint32(shift), y)
        return np.where(x == 0, np.int32(32), n).astype(np.int32)
    x = x.astype(jnp.uint32)
    n = jnp.zeros(x.shape, dtype=jnp.int32)
    y = x
    for shift in (16, 8, 4, 2, 1):
        mask = y > jnp.uint32((1 << shift) - 1)
        n = jnp.where(mask, n, n + shift)
        y = jnp.where(mask, y >> shift, y)
    return jnp.where(x == 0, jnp.int32(32), n)


def flow_key(saddr_hi, saddr_lo, daddr_hi, daddr_lo, sport, dport, proto):
    """Collapse a flow 5-tuple into a 64-bit (hi, lo) key.

    Reference analogue: ``PAIR_IP_PORT`` hashing in ``common/gy_inet_inc.h``
    (the 5-tuple flow key of the sketch tier, SURVEY §2.1). All inputs uint32
    arrays (IPv6 addresses pre-folded to two uint32 words by the decoder).
    """
    ports = (sport.astype(jnp.uint32) << 16) | (dport.astype(jnp.uint32) & 0xFFFF) \
        if not _is_np(sport) else (
            (np.asarray(sport, np.uint32) << np.uint32(16))
            | (np.asarray(dport, np.uint32) & np.uint32(0xFFFF)))
    a = mix64(saddr_hi, saddr_lo, 1)
    b = mix64(daddr_hi, daddr_lo, 2)
    if _is_np(a):
        with np.errstate(over="ignore"):
            lo = fmix32(a ^ (ports * np.uint32(_C1)))
            hi = fmix32(b ^ (np.asarray(proto, np.uint32) * np.uint32(_C2)) ^ lo)
        return hi, lo
    lo = fmix32(a ^ (ports * jnp.uint32(_C1)))
    hi = fmix32(b ^ (proto.astype(jnp.uint32) * jnp.uint32(_C2)) ^ lo)
    return hi, lo


def hash_bytes_np(data: bytes, salt: int = 0) -> int:
    """Host-only: hash arbitrary bytes to a 64-bit int (string interning ids,
    machine ids — ref: SHA256-derived host id, partha/gypartha.cc:64; we use a
    fast non-crypto mix since ids are internal)."""
    h = np.uint32(0x811C9DC5 ^ (salt & 0xFFFFFFFF))
    g = np.uint32(0x01000193)
    with np.errstate(over="ignore"):
        # FNV over 4-byte words, tail handled by padding.
        pad = (-len(data)) % 4
        w = np.frombuffer(data + b"\x00" * pad, dtype=np.uint32)
        h1 = h
        h2 = h ^ np.uint32(_GOLDEN)
        for word in w:
            h1 = (h1 ^ word) * g
            h2 = fmix32(h2 + word)
        h1 = fmix32(h1 ^ np.uint32(len(data)))
        h2 = fmix32(h2 ^ h1)
    return (int(h2) << 32) | int(h1)


def split64(x: int):
    """Split a python/np 64-bit int into (hi, lo) uint32."""
    x = int(x) & 0xFFFFFFFFFFFFFFFF
    return np.uint32(x >> 32), np.uint32(x & 0xFFFFFFFF)
