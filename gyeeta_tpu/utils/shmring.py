"""Shared-memory staging rings: the ingest-worker → fold-process wire.

The million-agent control plane splits the socket edge out of the fold
process (``net/ingestproc.py``): N ingest worker processes own wire
validation, native deframe/decode and the WAL append, and publish
DECODED columnar record batches — not raw bytes — into fixed-slot
rings living in one ``multiprocessing.shared_memory`` segment per
worker. The fold process drains the rings straight into its per-shard
staging slabs, so the fused fold dispatch path is unchanged.

Layout of one worker segment (all offsets fixed at creation)::

    [ worker header 512B | shard-0 ring | shard-1 ring | ... ]
    ring   = [ ring header 64B | slot 0 | slot 1 | ... ]
    slot   = [ slot header 32B | payload (packed record sections) ]

Concurrency contract — SPSC per ring, crash-tolerant:

- Exactly ONE producer (the worker) writes a ring, exactly ONE
  consumer (the fold process) reads it. The producer writes the slot
  payload, then the slot's ``seq`` word, then advances the ring-header
  ``head``; the consumer only reads slots below ``head``, so a worker
  killed mid-write never exposes a torn slot (head was not advanced —
  the respawned worker resumes at ``head`` and overwrites it).
- Drop-oldest COUNTED: the producer never blocks — when the consumer
  lags a full ring behind, the oldest unread slot is overwritten. The
  consumer detects the lap from the slot ``seq`` (every slot carries
  the producer's cumulative published-record count, so skipped slots
  are accounted in RECORDS, not just slots — the cross-process half of
  the no-silent-loss ledger).
- The worker header carries heartbeat words (monotone ``hb_seq``, a
  wall-clock stamp, the worker pid/epoch) plus the worker-side ledger
  counters; the supervisor reads them per poll cadence to detect a
  hung worker (process alive, loop wedged) and to render the
  ``gyt_ingest_proc_*`` metric rows.

Knobs (read by the supervisor at ring creation):

- ``GYT_SHM_RING_SLOTS``    — slots per shard ring (default 64)
- ``GYT_SHM_RING_SLOT_KB``  — payload bytes per slot (default 128)

Sizing: one worker segment is ``nshards * slots * (32 + slot_kb*1024)``
bytes — at the defaults, ~8 MiB per worker on an 8-shard mesh.
"""

from __future__ import annotations

import os
import struct
import time
from multiprocessing import shared_memory
from typing import Iterator, Optional

import numpy as np

MAGIC = 0x47595452494E4731          # "GYTRING1"

# ---- worker header (512 bytes) ----------------------------------------
# fixed scalar words first, then the named counter block
_WH = struct.Struct("<QIIQQ")        # magic, nshards, nslots, slot_bytes,
#                                      epoch
_WH_COUNTERS_OFF = 64
# Ledger + liveness counters, one u64 each, in this exact order. The
# supervisor folds deltas of these into the fold-process Stats registry
# (rendered as gyt_ingest_proc_* rows in /metrics).
COUNTER_NAMES = (
    "pid", "hb_seq", "hb_time_us", "done",
    "accepted_records",      # records drain2 built from accepted chunks
    "accepted_chunks",       # validated complete-frame chunks
    "accepted_bytes",        # wire bytes of those chunks
    "published_records",     # records committed into ring slots
    "published_slots",
    "frames_bad",            # poison frames (conn closed, counted)
    "unknown_records",       # unknown-subtype records (version skew)
    "wal_appended_chunks",   # chunks enqueued to the worker's WAL
    "wal_backlog_dropped",   # worker WAL backlog drops (counted loss)
    "conns_open",            # live event conns owned by the worker
    "conns_closed",
    "sweep_frames",          # reserved / roll-up convenience
)
WORKER_HEADER_BYTES = 512
assert _WH_COUNTERS_OFF + 8 * len(COUNTER_NAMES) <= WORKER_HEADER_BYTES

# ---- ring header (64 bytes): head only (tail is consumer-local) -------
_RH = struct.Struct("<Q")
RING_HEADER_BYTES = 64

# ---- slot header (32 bytes) -------------------------------------------
# seq, nbytes, nrec, cum_records (producer's published_records AFTER
# this slot — the drop-accounting anchor)
_SH = struct.Struct("<QIIQ")
SLOT_HEADER_BYTES = 32

# ---- packed payload: repeated record sections -------------------------
# [subtype u16 | reserved u16 | nrec u32 | nbytes u64 | raw bytes]
_SEC = struct.Struct("<HHIQ")


def ring_slots(env=None) -> int:
    env = os.environ if env is None else env
    return max(4, int(env.get("GYT_SHM_RING_SLOTS", "64")))


def ring_slot_bytes(env=None) -> int:
    env = os.environ if env is None else env
    return max(4096,
               int(env.get("GYT_SHM_RING_SLOT_KB", "128")) * 1024)


def pack_sections(recs: dict) -> bytes:
    """{subtype: structured record array} → one packed payload. The
    arrays must be C-contiguous structured arrays of the wire dtypes
    (``wire.DTYPE_OF_SUBTYPE``) — exactly what ``native.drain2``
    builds."""
    parts = []
    for subtype, arr in recs.items():
        if arr is None or len(arr) == 0:
            continue
        raw = np.ascontiguousarray(arr).tobytes()
        parts.append(_SEC.pack(int(subtype), 0, len(arr), len(raw)))
        parts.append(raw)
    return b"".join(parts)


def unpack_sections(buf, dtype_of_subtype: dict) -> tuple[dict, int]:
    """Packed payload → ({subtype: record array COPY}, nrec). Arrays
    are copied out of the (reused) ring slot buffer. Unknown subtypes
    are skipped and counted in the second return slot of the caller's
    ledger (they can only appear on version skew between worker and
    fold builds — same image in practice)."""
    out: dict = {}
    n = 0
    off = 0
    end = len(buf)
    while off + _SEC.size <= end:
        subtype, _r, nrec, nbytes = _SEC.unpack_from(buf, off)
        off += _SEC.size
        if off + nbytes > end:
            break                      # torn section: stop cleanly
        dt = dtype_of_subtype.get(subtype)
        if dt is not None and nrec:
            arr = np.frombuffer(buf, dtype=dt, count=nrec,
                                offset=off).copy()
            prev = out.get(subtype)
            out[subtype] = arr if prev is None \
                else np.concatenate([prev, arr])
            n += nrec
        off += nbytes
    return out, n


def split_records(recs: dict, max_payload: int) -> Iterator[tuple]:
    """Split a {subtype: array} dict into (payload, nrec) pieces that
    each fit ``max_payload`` bytes once packed. Record arrays split on
    record boundaries; a single record always fits (wire record dtypes
    are hundreds of bytes, slots are tens of KiB)."""
    cur: dict = {}
    cur_bytes = 0
    cur_n = 0
    for subtype, arr in recs.items():
        if arr is None or len(arr) == 0:
            continue
        itemsize = arr.dtype.itemsize
        i = 0
        while i < len(arr):
            budget = max_payload - cur_bytes - _SEC.size
            take = min(len(arr) - i, max(0, budget // itemsize))
            if take <= 0:
                if not cur_n:
                    # even an empty batch can't fit one record: a wide
                    # dtype vs a tiny slot. Fail loud — continuing here
                    # would spin forever and wedge the ingest worker.
                    raise ValueError(
                        f"record itemsize {itemsize}B (subtype "
                        f"{subtype}) exceeds slot payload budget "
                        f"{max_payload - _SEC.size}B; raise "
                        "GYT_SHM_RING_SLOT_KB")
                yield pack_sections(cur), cur_n
                cur, cur_bytes, cur_n = {}, 0, 0
                continue
            piece = arr[i:i + take]
            cur[subtype] = piece if subtype not in cur \
                else np.concatenate([cur[subtype], piece])
            cur_bytes += _SEC.size + take * itemsize
            cur_n += take
            i += take
    if cur_n:
        yield pack_sections(cur), cur_n


class WorkerShm:
    """One worker's shared segment: header + ``nshards`` rings.

    The supervisor creates it (``create=True``) and keeps the handle
    for draining; the worker attaches by name. Both sides compute the
    same fixed offsets from the header geometry."""

    def __init__(self, name: str, nshards: int = 0,
                 slots: Optional[int] = None,
                 slot_bytes: Optional[int] = None,
                 create: bool = False):
        self.name = name
        if create:
            self.slots = slots if slots is not None else ring_slots()
            self.slot_payload = (slot_bytes if slot_bytes is not None
                                 else ring_slot_bytes())
            self.nshards = int(nshards)
            self.slot_bytes = SLOT_HEADER_BYTES + self.slot_payload
            total = WORKER_HEADER_BYTES + self.nshards * (
                RING_HEADER_BYTES
                + self.slots * (SLOT_HEADER_BYTES + self.slot_payload))
            self.shm = shared_memory.SharedMemory(
                name=name, create=True, size=total)
            self.buf = self.shm.buf
            self.buf[:WORKER_HEADER_BYTES] = b"\0" * WORKER_HEADER_BYTES
            _WH.pack_into(self.buf, 0, MAGIC, self.nshards, self.slots,
                          self.slot_payload, 0)
            for s in range(self.nshards):
                roff = self._ring_off(s)
                self.buf[roff:roff + RING_HEADER_BYTES] = \
                    b"\0" * RING_HEADER_BYTES
        else:
            self.shm = shared_memory.SharedMemory(name=name)
            # a non-multiprocessing child attaching by name must not
            # let the resource tracker unlink the segment at exit (the
            # supervisor owns the lifecycle) — Python < 3.13 has no
            # track=False, so worker processes unregister explicitly
            # (gated: a same-process attach, e.g. in tests, keeps the
            # creator's registration intact)
            if os.environ.get("GYT_SHMRING_NOTRACK") == "1":
                try:
                    from multiprocessing import resource_tracker
                    resource_tracker.unregister(
                        self.shm._name, "shared_memory")  # noqa: SLF001
                except Exception:           # pragma: no cover
                    pass
            self.buf = self.shm.buf
            magic, nsh, slots_, slot_b, _epoch = _WH.unpack_from(
                self.buf, 0)
            if magic != MAGIC:
                raise ValueError(f"{name}: not a GYTRING1 segment")
            self.nshards, self.slots, self.slot_payload = \
                int(nsh), int(slots_), int(slot_b)
            self.slot_bytes = SLOT_HEADER_BYTES + self.slot_payload
        # producer-side mirrors (resumed from shm on attach, so a
        # respawned worker continues each ring's seq/cum chain — the
        # cum_records chain is PER SHARD: a global chain would make the
        # consumer count another ring's merely-undrained slots as drops)
        self._head = [self._read_head(s) for s in range(self.nshards)]
        self._cum_shard = [self._resume_cum(s)
                           for s in range(self.nshards)]
        # consumer-side state (fold-process local — a fold restart is a
        # full-system restart, so no need to persist it)
        self._tail = list(self._head)
        self._consumed_recs = [0] * self.nshards
        self._consumed_base = list(self._cum_shard)

    # ------------------------------------------------------------ offsets
    def _ring_off(self, shard: int) -> int:
        return WORKER_HEADER_BYTES + shard * (
            RING_HEADER_BYTES + self.slots * self.slot_bytes)

    def _slot_off(self, shard: int, idx: int) -> int:
        return self._ring_off(shard) + RING_HEADER_BYTES \
            + idx * self.slot_bytes

    def _read_head(self, shard: int) -> int:
        return _RH.unpack_from(self.buf, self._ring_off(shard))[0]

    def _resume_cum(self, shard: int) -> int:
        """Producer resume: per-shard cumulative record count from the
        most recently committed slot (never overwritten until the NEXT
        publish, so a respawned worker reads it reliably)."""
        head = self._head[shard]
        if head <= 0:
            return 0
        off = self._slot_off(shard, (head - 1) % self.slots)
        seq, _nb, _nr, cum = _SH.unpack_from(self.buf, off)
        return int(cum) if seq == head - 1 else 0

    def _write_head(self, shard: int, head: int) -> None:
        _RH.pack_into(self.buf, self._ring_off(shard), head)

    # ----------------------------------------------------------- counters
    def counter(self, name: str) -> int:
        i = COUNTER_NAMES.index(name)
        return struct.unpack_from(
            "<Q", self.buf, _WH_COUNTERS_OFF + 8 * i)[0]

    def set_counter(self, name: str, value: int) -> None:
        i = COUNTER_NAMES.index(name)
        struct.pack_into("<Q", self.buf, _WH_COUNTERS_OFF + 8 * i,
                         int(value) & (2 ** 64 - 1))

    def add_counter(self, name: str, n: int = 1) -> None:
        self.set_counter(name, self.counter(name) + int(n))

    def counters(self) -> dict:
        vals = struct.unpack_from(
            f"<{len(COUNTER_NAMES)}Q", self.buf, _WH_COUNTERS_OFF)
        return dict(zip(COUNTER_NAMES, vals))

    def heartbeat(self) -> None:
        """Producer liveness: bump hb_seq + wall stamp (the supervisor
        reaps a worker whose process is alive but whose hb_seq stops —
        a wedged loop is as dead as a SIGKILL)."""
        self.set_counter("pid", os.getpid())
        self.add_counter("hb_seq")
        self.set_counter("hb_time_us", int(time.time() * 1e6))

    def hb_age_s(self, now: Optional[float] = None) -> float:
        t = self.counter("hb_time_us") / 1e6
        if t <= 0:
            return float("inf")
        return max(0.0, (now if now is not None else time.time()) - t)

    def bump_epoch(self) -> int:
        magic, nsh, slots_, slot_b, epoch = _WH.unpack_from(self.buf, 0)
        _WH.pack_into(self.buf, 0, magic, nsh, slots_, slot_b,
                      epoch + 1)
        return epoch + 1

    def epoch(self) -> int:
        return _WH.unpack_from(self.buf, 0)[4]

    # ----------------------------------------------------------- producer
    def publish(self, shard: int, payload: bytes, nrec: int) -> None:
        """Commit one packed payload into shard ``shard``'s ring.
        Payload must fit ``slot_payload`` (callers split with
        :func:`split_records`). Write order: payload → slot header
        (with seq) → ring head. Never blocks; the oldest unread slot
        is overwritten when the consumer lags (the consumer counts the
        lap from cum_records)."""
        if len(payload) > self.slot_payload:
            raise ValueError(
                f"payload {len(payload)}B > slot {self.slot_payload}B")
        head = self._head[shard]
        off = self._slot_off(shard, head % self.slots)
        self.buf[off + SLOT_HEADER_BYTES:
                 off + SLOT_HEADER_BYTES + len(payload)] = payload
        self._cum_shard[shard] += int(nrec)
        _SH.pack_into(self.buf, off, head, len(payload), int(nrec),
                      self._cum_shard[shard])
        self._head[shard] = head + 1
        self._write_head(shard, head + 1)
        self.set_counter("published_records",
                         sum(self._cum_shard))
        self.add_counter("published_slots")

    # ----------------------------------------------------------- consumer
    def drain(self, shard: int, max_slots: int = 0) -> tuple:
        """Read committed slots for ``shard`` → (payload-bytes list,
        nrec_total, dropped_slots, dropped_records). Dropped = slots
        the producer overwrote before we read them (drop-oldest lap),
        with the record count recovered from the cum_records chain —
        counted loss, never silent."""
        head = self._read_head(shard)
        tail = self._tail[shard]
        if head <= tail:
            return [], 0, 0, 0
        dropped_slots = 0
        dropped_records = 0
        if head - tail > self.slots:
            # producer lapped us: the oldest unread slots are gone
            new_tail = head - self.slots
            dropped_slots = new_tail - tail
            tail = new_tail
        out = []
        nrec_total = 0
        while tail < head and (not max_slots or len(out) < max_slots):
            off = self._slot_off(shard, tail % self.slots)
            seq, nbytes, nrec, cum = _SH.unpack_from(self.buf, off)
            if seq != tail:
                # overwritten between the head read and ours (another
                # lap) — resync forward; the skipped RECORDS are
                # recovered by the cum-chain gap check at the next
                # valid slot read (possibly in a LATER drain call: the
                # stale head may end this one before another read)
                head2 = self._read_head(shard)
                new_tail = max(tail, head2 - self.slots)
                if new_tail == tail:        # torn/unexpected: bail out
                    break
                dropped_slots += new_tail - tail
                tail = new_tail
                continue
            payload = bytes(self.buf[off + SLOT_HEADER_BYTES:
                                     off + SLOT_HEADER_BYTES + nbytes])
            # validate the slot was not overwritten mid-copy
            seq2 = _SH.unpack_from(self.buf, off)[0]
            if seq2 != tail:
                continue                    # retry resyncs via seq path
            # cum-chain gap check, on EVERY slot: cum(after) - nrec is
            # the producer's ring total BEFORE this slot; anything this
            # consumer has not yet accounted — prior calls
            # (consumed_recs folds prior drops in), this call's
            # consumption, this call's earlier gaps — was overwritten
            # unread. Accumulated (+=), since the producer can lap us
            # more than once per drain; zero in steady state, and
            # negative (a cum reset after a failed producer resume)
            # never counts.
            gap = ((cum - nrec) - self._consumed_base[shard]
                   - self._consumed_recs[shard] - nrec_total
                   - dropped_records)
            if gap > 0:
                dropped_records += gap
            out.append(payload)
            nrec_total += nrec
            tail += 1
        self._tail[shard] = tail
        # the cursor covers consumed AND dropped records — both are
        # accounted, so the next lap's gap math starts clean
        self._consumed_recs[shard] += nrec_total + dropped_records
        return out, nrec_total, dropped_slots, dropped_records

    def backlog(self, shard: Optional[int] = None) -> int:
        """Committed-but-unconsumed slots (consumer side)."""
        if shard is not None:
            return max(0, self._read_head(shard) - self._tail[shard])
        return sum(self.backlog(s) for s in range(self.nshards))

    def heads(self) -> list:
        return [self._read_head(s) for s in range(self.nshards)]

    def tails(self) -> list:
        return list(self._tail)

    # ---------------------------------------------------------- lifecycle
    def close(self) -> None:
        try:
            self.shm.close()
        except Exception:                   # pragma: no cover
            pass

    def unlink(self) -> None:
        try:
            self.shm.unlink()
        except Exception:                   # pragma: no cover
            pass
