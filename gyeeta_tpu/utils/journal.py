"""Write-ahead event journal: bounded-RPO durability for the ingest edge.

The reference pairs madhava's in-memory state with a Postgres history
tier so a restart doesn't amnesia the window; this framework's engine
state lives in device HBM and checkpoints on a cadence — which left an
RPO of one full checkpoint interval (a crash between ``gyt_ckpt_*.npz``
saves silently discarded every event folded since the last one). The
journal closes that gap at the WIRE boundary: every accepted
event-stream chunk (post ``wire.read_frame``/deframe validation,
pre-fold) appends to tick-stamped, size-rotated segment files, and
recovery re-folds the journal from the checkpoint's recorded position
through the normal decode/fold path.

File format (little-endian), one segment = ``gyt_wal_<seq:08d>.gytwal``:
8-byte magic ``GYTWAL01``, then chunks of
``{t_usec u8, nbytes u4, host_id u4, tick u8, conn_id u8}`` + bytes —
the ``GYTREC01`` capture-chunk shape (``utils/replay.py``) widened with
the attribution fields replay needs (``hid`` routes per-shard on a
mesh; ``conn_id`` attributes torn tails; ``tick`` bounds the window).

Durability contract:
- the ingest thread only ENQUEUES chunks (microseconds); one WAL
  writer thread owns the file — it drains the backlog, writes, and
  group-fsyncs on a byte/ms cadence (``fsync_bytes`` / ``fsync_ms``).
  RPO is bounded by the last fsync, not the last checkpoint; the lag
  and the backlog ride gauges (``gyt_journal_fsync_lag_seconds``,
  ``gyt_journal_backlog_bytes``). The feed path therefore pays ~zero
  journal cost while the disk keeps up;
- when the WIRE outruns the DISK, the backlog saturates at
  ``backlog_max_bytes`` and drops whole oldest chunks — COUNTED
  (``wal_backlog_dropped``/``_bytes``), never silent, and the growing
  lag/backlog gauges are exactly what the server's admission
  controller watches to THROTTLE agents before that point (PSketch's
  priority-aware shedding, not blind drops);
- :meth:`fsync` is the BLOCKING form (checkpoint positions, close):
  it drains the backlog and syncs before returning, so a position
  recorded in checkpoint metadata is durable — checkpoint + replay
  never double-folds;
- a torn tail (SIGKILL / power loss mid-write) is truncated on open,
  counted (``wal_torn_tail``), and appends continue from the cut;
- segments wholly older than the newest durable checkpoint are
  deleted after each successful save (disk is bounded by roughly one
  checkpoint interval of wire traffic plus one segment).
"""

from __future__ import annotations

import collections
import os
import pathlib
import struct
import threading
import time
from typing import Iterator, Optional

MAGIC = b"GYTWAL01"
# {t_usec u8, nbytes u4, host_id u4, tick u8, conn_id u8}
_WHDR = struct.Struct("<QIIQQ")
_SEG_FMT = "gyt_wal_{:08d}.gytwal"
_SEG_GLOB = "gyt_wal_*.gytwal"


class _NullStats:
    """Stats shim so the journal works without a registry attached."""

    def bump(self, name, n=1):
        pass

    def gauge(self, name, v):
        pass

    def timeit(self, name):
        import contextlib
        return contextlib.nullcontext()


class Journal:
    """Append-only segmented WAL: lock-cheap enqueue on the ingest
    thread, one writer thread doing write/rotate/group-fsync, torn-tail
    repair on open."""

    def __init__(self, path, *, segment_max_bytes: int = 64 << 20,
                 fsync_bytes: int = 1 << 20, fsync_ms: float = 50.0,
                 backlog_max_bytes: int = 64 << 20,
                 stats=None, clock=None):
        self.dir = pathlib.Path(path)
        self.dir.mkdir(parents=True, exist_ok=True)
        self.segment_max_bytes = max(int(segment_max_bytes), 1 << 16)
        self.fsync_bytes = max(int(fsync_bytes), 1)
        self.fsync_ms = float(fsync_ms)
        self.backlog_max_bytes = max(int(backlog_max_bytes), 1 << 16)
        self.stats = stats if stats is not None else _NullStats()
        self._clock = clock or time.time
        self._f = None
        self._seq = 0
        self._off = len(MAGIC)            # logical end incl. backlog
        segs = self.segments()
        if segs:
            # resume the newest segment; a torn tail (crash mid-write)
            # is physically truncated so new appends never interleave
            # with a half-written chunk
            self._seq = segs[-1]
            self._off = self._recover_tail(self._segpath(self._seq))
            self._f = open(self._segpath(self._seq), "r+b")
            self._f.seek(self._off)
        else:
            self._open_segment(0)
        # ---- writer thread state (all under _cv's lock)
        self._cv = threading.Condition()
        self._q: collections.deque = collections.deque()
        self._q_bytes = 0
        self._unsynced_bytes = 0          # written but not yet fsynced
        self._oldest_unsynced_t: Optional[float] = None
        self._closing = False
        self._sync_req = False            # a blocking fsync() waits on it
        self._seal_req = False            # a blocking seal_active() waits
        # consumer handoff: segments >= a registered floor are NEVER
        # truncated even when a checkpoint supersedes them. NAMED floors
        # (one per consumer: the history compactor, the remote-ship
        # tier, ...) each advance monotonically; the effective bound is
        # the MIN over every registered floor, so no consumer can lose
        # a segment another consumer has already released. Empty = no
        # consumer registered (the pre-history behavior).
        self._floors: dict = {}
        self._worker = threading.Thread(
            target=self._writer_loop, name="gyt-wal-writer", daemon=True)
        self._worker.start()

    # ----------------------------------------------------------- segments
    def _segpath(self, seq: int) -> pathlib.Path:
        return self.dir / _SEG_FMT.format(seq)

    def segments(self) -> list[int]:
        """Existing segment sequence numbers, ascending."""
        out = []
        for p in self.dir.glob(_SEG_GLOB):
            try:
                out.append(int(p.stem.split("_")[-1]))
            except ValueError:              # foreign file — not ours
                continue
        return sorted(out)

    def _open_segment(self, seq: int) -> None:
        self._seq = seq
        self._f = open(self._segpath(seq), "ab")
        if self._f.tell() == 0:
            self._f.write(MAGIC)
            # the header must be ON DISK immediately: a reader (or a
            # crash) between open and the first cadence sync would
            # otherwise see a 0-byte "journal" and reject it
            self._f.flush()
        self._off = self._f.tell()

    def _recover_tail(self, path: pathlib.Path) -> int:
        """Walk ``path``'s chunks; truncate anything after the last
        complete one (the SIGKILL-mid-write repair). Returns the byte
        offset appends resume from."""
        size = path.stat().st_size
        with open(path, "rb") as f:
            head = f.read(len(MAGIC))
            if len(head) < len(MAGIC):
                # torn during creation: rewrite as empty
                self.stats.bump("wal_torn_tail")
                with open(path, "wb") as w:
                    w.write(MAGIC)
                return len(MAGIC)
            if head != MAGIC:
                raise ValueError(f"{path}: not a GYTWAL01 journal")
            off = len(MAGIC)
            torn = False
            while True:
                hdr = f.read(_WHDR.size)
                if len(hdr) < _WHDR.size:
                    torn = len(hdr) > 0
                    break
                _t, n, _hid, _tick, _cid = _WHDR.unpack(hdr)
                if off + _WHDR.size + n > size:
                    torn = True
                    break
                f.seek(n, 1)
                off += _WHDR.size + n
        if off < size:
            torn = True
        if torn:
            self.stats.bump("wal_torn_tail")
            os.truncate(path, off)
        return off

    # ------------------------------------------------------------- append
    def append(self, buf: bytes, hid: int = 0, conn_id: int = 0,
               tick: int = 0) -> None:
        """Enqueue one validated chunk for the writer thread — the
        ingest path never blocks on the disk. Past
        ``backlog_max_bytes`` the OLDEST queued chunks drop, counted
        (the admission controller's throttle exists to keep the fleet
        away from this point)."""
        if not buf:
            return
        if self._f is None:
            raise ValueError("journal is closed")
        now = self._clock()
        entry = (now, int(hid) & 0xFFFFFFFF, int(tick),
                 int(conn_id) & (2 ** 64 - 1), buf)
        # journal_append times what the FEED PATH pays (the enqueue —
        # microseconds); the physical write/fsync cost shows up as
        # journal_write / journal_fsync on the writer thread
        with self.stats.timeit("journal_append"), self._cv:
            self._q.append(entry)
            self._q_bytes += len(buf)
            while self._q_bytes > self.backlog_max_bytes \
                    and len(self._q) > 1:
                old = self._q.popleft()
                self._q_bytes -= len(old[4])
                self.stats.bump("wal_backlog_dropped")
                self.stats.bump("wal_backlog_dropped_bytes",
                                len(old[4]))
            self._cv.notify_all()
        self.stats.bump("wal_appended_chunks")
        self.stats.bump("wal_appended_bytes", _WHDR.size + len(buf))

    # ------------------------------------------------------ writer thread
    # The worker OWNS the file object: writes, rotation and every
    # os.fsync happen on this thread only. The ingest thread enqueues;
    # blocking fsync() raises _sync_req and waits for the worker to
    # drain + sync (single-writer discipline — no cross-thread flushes
    # on one BufferedWriter).
    def _writer_loop(self) -> None:
        while True:
            with self._cv:
                timeout = 0.5
                if self._unsynced_bytes:
                    # sleep only until the ms budget of the oldest
                    # unsynced byte expires
                    timeout = max(0.0, self.fsync_ms / 1e3
                                  - (self._clock()
                                     - (self._oldest_unsynced_t or 0)))
                if not self._q and not self._closing \
                        and not self._sync_req and not self._seal_req \
                        and not self._sync_due():
                    self._cv.wait(timeout=timeout)
                batch = list(self._q)
                self._q.clear()
                self._q_bytes = 0
                closing = self._closing
                sync_req = self._sync_req
                seal_req = self._seal_req
            for t, hid, tick, cid, buf in batch:
                self._write_one(t, hid, tick, cid, buf)
            if seal_req and self._off > len(MAGIC):
                # compaction handoff: rotate so the current segment
                # becomes sealed (immutable) and readable by the
                # compactor; an empty active segment needs no rotation
                self._rotate()
            if (sync_req or closing or self._sync_due()) \
                    and self._unsynced_bytes:
                self._sync_now()
            with self._cv:
                if seal_req and not self._q:
                    self._seal_req = False
                    self._cv.notify_all()
                if sync_req and not self._q:
                    self._sync_req = False
                    self._cv.notify_all()
                if closing and not self._q:
                    self._cv.notify_all()
                    return

    def _sync_due(self) -> bool:
        if not self._unsynced_bytes:
            return False
        if self._unsynced_bytes >= self.fsync_bytes:
            return True
        return (self._clock() - (self._oldest_unsynced_t or 0)) * 1e3 \
            >= self.fsync_ms

    def _write_one(self, t: float, hid: int, tick: int, cid: int,
                   buf: bytes) -> None:
        with self.stats.timeit("journal_write"):
            if (self._off + _WHDR.size + len(buf) > self.segment_max_bytes
                    and self._off > len(MAGIC)):
                self._rotate()
            self._f.write(_WHDR.pack(int(t * 1e6), len(buf), hid,
                                     tick, cid))
            self._f.write(buf)
            self._off += _WHDR.size + len(buf)
        self._unsynced_bytes += _WHDR.size + len(buf)
        if self._oldest_unsynced_t is None:
            self._oldest_unsynced_t = t

    def _rotate(self) -> None:
        self._sync_now()
        self._f.close()
        self.stats.bump("wal_rotations")
        self._open_segment(self._seq + 1)

    def _sync_now(self) -> None:
        lag = (self._clock() - self._oldest_unsynced_t) \
            if self._oldest_unsynced_t is not None else 0.0
        with self.stats.timeit("journal_fsync"):
            self._f.flush()
            os.fsync(self._f.fileno())
        self.stats.bump("wal_fsyncs")
        self.stats.gauge("journal_fsync_lag_seconds", round(lag, 4))
        self._unsynced_bytes = 0
        self._oldest_unsynced_t = None

    # --------------------------------------------------------- barriers
    def poll(self) -> None:
        """Cadence hook (tick loop): nudge the writer so a quiet wire
        still syncs within the ms budget."""
        with self._cv:
            self._cv.notify_all()

    def fsync(self) -> None:
        """Make every appended byte durable BEFORE returning (the
        blocking form: checkpoint positions, close). Idempotent; safe
        after close (no-op)."""
        if self._f is None:
            return
        if threading.current_thread() is self._worker:
            self._sync_now()          # writer-side call (rotation)
            return
        with self._cv:
            if not self._worker.is_alive():       # pragma: no cover
                return
            self._sync_req = True
            self._cv.notify_all()
            while self._sync_req and self._worker.is_alive():
                self._cv.wait(timeout=0.05)

    def seal_active(self) -> int:
        """Rotate the active segment so every byte appended so far sits
        in a SEALED (immutable) segment the history compactor can
        consume (``history/compactor.py``). Blocking, like
        :meth:`fsync`. No-op on an empty active segment or a closed
        journal. Returns the first sealed-segment bound afterwards
        (the new active seq — sealed segments are all ``< seq``)."""
        if self._f is None:
            return self._seq
        with self._cv:
            if not self._worker.is_alive():       # pragma: no cover
                return self._seq
            self._seal_req = True
            self._cv.notify_all()
            while self._seal_req and self._worker.is_alive():
                self._cv.wait(timeout=0.05)
        return self._seq

    def sealed_upto(self) -> int:
        """Exclusive upper bound of sealed segments (the active seq);
        the compactor never reads at/after it while the writer lives."""
        return self._seq

    @property
    def _truncate_floor(self) -> Optional[int]:
        """Effective truncation floor: the MIN over every named
        consumer floor (None when no consumer has registered)."""
        return min(self._floors.values()) if self._floors else None

    def set_truncate_floor(self, seq: int, name: str = "compact") -> None:
        """Register a consumer's position under ``name``: segments >=
        ``seq`` are held back from checkpoint truncation until that
        consumer has processed them (the compactor rolling them into
        snapshot shards; the segment shipper landing them in the remote
        compaction region). Each named floor is monotone — it never
        moves backwards — and truncation bounds at the MIN across all
        names, so e.g. a sealed-but-unshipped segment stays on disk no
        matter how far ahead checkpoints and local compaction run."""
        cur = self._floors.get(name)
        self._floors[name] = int(seq) if cur is None \
            else max(cur, int(seq))

    # ----------------------------------------------------------- position
    def position(self) -> tuple[int, int]:
        """(segment_seq, byte_offset) of the DURABLE end. Call
        :meth:`fsync` first (checkpoint metadata does) — after it the
        backlog is empty and every byte below the offset is synced."""
        return (self._seq, self._off)

    def gauges(self) -> dict:
        """Operator gauges, refreshed per report cadence (they ride the
        same one-readback path as the engine-health vector)."""
        now = self._clock()
        with self._cv:
            backlog = self._q_bytes
        lag = (now - self._oldest_unsynced_t) \
            if self._oldest_unsynced_t is not None else 0.0
        total = 0
        nseg = 0
        for s in self.segments():
            try:
                total += self._segpath(s).stat().st_size
                nseg += 1
            except OSError:
                pass
        return {
            "journal_backlog_bytes": float(backlog),
            "journal_pending_bytes": float(backlog
                                           + self._unsynced_bytes),
            "journal_fsync_lag_seconds": round(max(lag, 0.0), 4),
            "journal_segments": float(nseg),
            "journal_bytes": float(total),
        }

    # ----------------------------------------------------------- truncate
    def truncate_upto(self, seg_seq: int) -> int:
        """Delete segments wholly older than ``seg_seq`` (the newest
        durable checkpoint's segment). When a history compactor has
        registered a truncate floor, segments it has not consumed yet
        are held back regardless of checkpoint position (otherwise a
        checkpoint cadence faster than the compaction cadence would
        silently punch holes in the history). Returns segments
        deleted."""
        bound = int(seg_seq)
        if self._truncate_floor is not None:
            bound = min(bound, self._truncate_floor)
        n = 0
        for s in self.segments():
            if s >= bound or s == self._seq:
                continue
            try:
                self._segpath(s).unlink()
                n += 1
            except OSError:
                pass
        if n:
            self.stats.bump("wal_segments_deleted", n)
        return n

    # --------------------------------------------------------------- read
    def read_from(self, pos: Optional[tuple] = None
                  ) -> Iterator[tuple[int, int, int, bytes]]:
        """Yield ``(hid, tick, conn_id, chunk)`` from ``pos`` (a
        ``position()`` tuple; None = the very beginning) through the
        end. Drains + syncs first when the writer is live (same-process
        reads see everything appended). A torn tail ends the walk
        cleanly (counted, never a struct error)."""
        if self._f is not None:
            self.fsync()
        segs = self.segments()
        if not segs:
            return
        if pos is None:
            start_seq, start_off = segs[0], len(MAGIC)
        else:
            start_seq, start_off = int(pos[0]), int(pos[1])
        if start_seq not in segs and segs and segs[0] > start_seq:
            # the position's segment is gone (over-eager truncation /
            # foreign cleanup): replay what exists, loudly
            self.stats.bump("wal_position_gap")
            start_seq, start_off = segs[0], len(MAGIC)
        for s in segs:
            if s < start_seq:
                continue
            off = start_off if s == start_seq else len(MAGIC)
            yield from self._read_segment(self._segpath(s), off)

    def _read_segment(self, path: pathlib.Path, off: int
                      ) -> Iterator[tuple[int, int, int, bytes]]:
        for _nxt, _t, hid, tick, cid, chunk in read_entries(
                path, off, self.stats):
            yield hid, tick, cid, chunk

    # -------------------------------------------------------------- close
    def close(self) -> None:
        """Drain + fsync + close (the graceful-shutdown path).
        Idempotent."""
        if self._f is None:
            return
        self.fsync()
        with self._cv:
            self._closing = True
            self._cv.notify_all()
        self._worker.join(timeout=10.0)
        self._f.close()
        self._f = None

    def abort(self) -> None:
        """Close WITHOUT draining or fsync — the chaos/test hook
        emulating a SIGKILL'd writer (queued chunks vanish exactly like
        unsynced page-cache bytes would)."""
        if self._f is None:
            return
        with self._cv:
            self._q.clear()
            self._q_bytes = 0
            self._closing = True
            self._cv.notify_all()
        self._worker.join(timeout=10.0)
        self._f.close()
        self._f = None


class ShardedJournal:
    """Per-shard WAL: one :class:`Journal` per ``shard_NN/`` subdir,
    placed by the SAME sticky hid→shard hash the fold routes by
    (``parallel/partition.py:ShardLayout``). Journaling therefore
    shards with the fold: a chunk journaled for host h replays into
    exactly the shard that folded it live (stable across reconnect and
    ``--restore-latest``), and a future multi-controller split hands
    each controller its subdirs unchanged.

    Duck-type compatible with :class:`Journal` where the runtimes and
    the checkpoint/replay helpers touch it; positions are PER SHARD
    (a list of ``[seg_seq, byte_off]`` pairs, shard-indexed)."""

    def __init__(self, path, n_shards: int, *,
                 subdir_fmt: str = "shard_{:02d}",
                 segment_max_bytes: int = 64 << 20,
                 fsync_bytes: int = 1 << 20, fsync_ms: float = 50.0,
                 backlog_max_bytes: int = 64 << 20,
                 stats=None, clock=None):
        self.dir = pathlib.Path(path)
        self.n = int(n_shards)
        self.subdir_fmt = subdir_fmt
        self.stats = stats if stats is not None else _NullStats()
        self._clock = clock or time.time
        # counters accumulate correctly across sub-journals (shared
        # registry); the per-sync lag gauge is last-writer-wins noise —
        # gauges() computes the honest merge on demand
        self.shards = [
            Journal(self.dir / subdir_fmt.format(s),
                    segment_max_bytes=segment_max_bytes,
                    fsync_bytes=fsync_bytes, fsync_ms=fsync_ms,
                    backlog_max_bytes=backlog_max_bytes,
                    stats=self.stats, clock=clock)
            for s in range(self.n)]

    def shard_of(self, hid: int) -> int:
        return int(hid) % self.n

    # ------------------------------------------------------------- append
    def append(self, buf: bytes, hid: int = 0, conn_id: int = 0,
               tick: int = 0) -> None:
        self.shards[self.shard_of(hid)].append(
            buf, hid=hid, conn_id=conn_id, tick=tick)

    # ----------------------------------------------------------- barriers
    def poll(self) -> None:
        for j in self.shards:
            j.poll()

    def fsync(self) -> None:
        for j in self.shards:
            j.fsync()

    def seal_active(self) -> list:
        return [j.seal_active() for j in self.shards]

    def sealed_upto(self) -> list:
        return [j.sealed_upto() for j in self.shards]

    def set_truncate_floor(self, seq, name: str = "compact") -> None:
        """Per-shard floors (a list), or one floor broadcast; ``name``
        scopes the floor to one consumer (see :meth:`Journal
        .set_truncate_floor`)."""
        if isinstance(seq, (list, tuple)):
            for j, s in zip(self.shards, seq):
                j.set_truncate_floor(int(s), name=name)
        else:
            for j in self.shards:
                j.set_truncate_floor(int(seq), name=name)

    # ----------------------------------------------------------- position
    def position(self) -> list:
        """Per-shard ``[seg_seq, byte_off]`` durable ends (call
        :meth:`fsync` first, as checkpoint metadata does)."""
        return [list(j.position()) for j in self.shards]

    def gauges(self) -> dict:
        out: dict = {}
        for j in self.shards:
            for k, v in j.gauges().items():
                if k == "journal_fsync_lag_seconds":
                    out[k] = max(out.get(k, 0.0), v)    # worst shard
                else:
                    out[k] = out.get(k, 0.0) + v
        return out

    def truncate_upto(self, bounds) -> int:
        """Per-shard checkpoint truncation (``bounds``: shard-indexed
        segment floors, the checkpoint's recorded per-shard positions)."""
        n = 0
        if isinstance(bounds, (list, tuple)):
            for j, b in zip(self.shards, bounds):
                n += j.truncate_upto(
                    int(b[0]) if isinstance(b, (list, tuple)) else int(b))
        else:
            for j in self.shards:
                n += j.truncate_upto(int(bounds))
        return n

    # --------------------------------------------------------------- read
    def read_from(self, pos=None
                  ) -> Iterator[tuple[int, int, int, bytes]]:
        """Yield ``(hid, tick, conn_id, chunk)`` across every shard's
        journal from per-shard positions, k-way-merged by window tick
        (each shard's stream is tick-monotone, so the merged replay
        folds windows in order — the cross-shard interleave within a
        tick is irrelevant: records are host-disjoint by construction).
        ``pos``: shard-indexed pairs from :meth:`position`, or None."""
        import heapq

        if pos is not None:
            pos = list(pos)
            if not pos or not isinstance(pos[0], (list, tuple)):
                # a flat (seg, off) from a pre-shard checkpoint cannot
                # be mapped onto subdirs — replay everything, loudly
                self.stats.bump("wal_position_gap")
                pos = None

        def stream(s):
            p = tuple(pos[s]) if pos is not None and s < len(pos) \
                else None
            for hid, tick, cid, chunk in self.shards[s].read_from(p):
                yield (tick, s, hid, cid, chunk)

        for tick, _s, hid, cid, chunk in heapq.merge(
                *(stream(s) for s in range(self.n)),
                key=lambda e: e[0]):
            yield hid, tick, cid, chunk

    # -------------------------------------------------------------- close
    def close(self) -> None:
        for j in self.shards:
            j.close()

    def abort(self) -> None:
        for j in self.shards:
            j.abort()


# ---------------------------------------------------- sealed-segment read
# Position-yielding walkers over WAL segment FILES, usable without a
# live Journal instance (the history compactor reads sealed segments of
# the serving process's journal dir, and the offline `gyeeta_tpu
# compact` CLI reads a dir no process owns). Sealed segments are
# immutable, so no locking against the writer thread is needed.

def floors_of(pos):
    """Per-shard segment floors from a stored WAL position: a flat
    ``(seg, off)`` pair → its segment int; ``[shard, seg, off]``
    triples (sharded WAL) → a shard-indexed floor list (gaps 0)."""
    if pos and isinstance(pos[0], (list, tuple)):
        m = {int(e[0]): int(e[1]) for e in pos}
        return [m.get(s, 0) for s in range(max(m) + 1)]
    return int(pos[0])


def sharded_subdirs(path) -> list:
    """``shard_NN`` subdirectories of a sharded WAL root, shard-index
    order; empty for a flat (single-journal) dir. The compactor and
    the offline ``gyeeta_tpu compact`` CLI use this to detect the
    layout without a live journal object."""
    d = pathlib.Path(path)
    if not d.is_dir():
        return []
    out = []
    for p in sorted(d.glob("shard_*")):
        if p.is_dir():
            try:
                out.append((int(p.name.split("_")[-1]), p))
            except ValueError:
                continue
    return [p for _i, p in sorted(out)]


def read_sealed_sharded(subdirs, pos_map=None, uptos=None, stats=None
                        ) -> Iterator[tuple]:
    """Walk every shard subdir's sealed segments, k-way-merged by
    window tick (each shard's stream is tick-monotone), yielding
    ``(shard, seg_seq, next_off, t_epoch, hid, tick, conn_id, chunk)``
    — the sharded twin of :func:`read_sealed`, with the shard index
    prepended so the caller can keep per-shard resume positions.
    ``pos_map``: {shard: (seg, off)}; ``uptos``: per-shard exclusive
    segment bounds (a live ``ShardedJournal.sealed_upto()`` list), or
    None for offline dirs."""
    import heapq

    def stream(s, d):
        p = (pos_map or {}).get(s)
        u = uptos[s] if uptos is not None else None
        for seq, nxt, t, hid, tick, cid, chunk in read_sealed(
                d, p, u, stats=stats):
            yield (tick, s, seq, nxt, t, hid, cid, chunk)

    for tick, s, seq, nxt, t, hid, cid, chunk in heapq.merge(
            *(stream(s, d) for s, d in enumerate(subdirs)),
            key=lambda e: e[0]):
        yield s, seq, nxt, t, hid, tick, cid, chunk


def dir_segments(path) -> list[int]:
    """Segment sequence numbers in a journal dir, ascending."""
    out = []
    for p in pathlib.Path(path).glob(_SEG_GLOB):
        try:
            out.append(int(p.stem.split("_")[-1]))
        except ValueError:
            continue
    return sorted(out)


def read_entries(path, off: int = len(MAGIC), stats=None
                 ) -> Iterator[tuple[int, float, int, int, int, bytes]]:
    """Walk one segment file from byte ``off``, yielding
    ``(next_off, t_epoch, hid, tick, conn_id, chunk)`` — the
    position-carrying form the compactor needs to record a resumable
    manifest position (and the append timestamps that become shard
    wall-time ranges). A torn tail ends the walk cleanly (counted when
    ``stats``)."""
    path = pathlib.Path(path)
    with open(path, "rb") as f:
        if f.read(len(MAGIC)) != MAGIC:
            raise ValueError(f"{path}: not a GYTWAL01 journal")
        f.seek(off)
        while True:
            hdr = f.read(_WHDR.size)
            if len(hdr) < _WHDR.size:
                if hdr and stats is not None:
                    stats.bump("wal_torn_tail_read")
                return
            t, n, hid, tick, cid = _WHDR.unpack(hdr)
            chunk = f.read(n)
            if len(chunk) < n:          # torn mid-payload
                if stats is not None:
                    stats.bump("wal_torn_tail_read")
                return
            off += _WHDR.size + n
            yield off, t / 1e6, hid, tick, cid, chunk


def read_sealed(path, pos: Optional[tuple] = None,
                upto_seq: Optional[int] = None, stats=None
                ) -> Iterator[tuple]:
    """Walk a journal dir's SEALED segments from ``pos``
    (``(seg_seq, byte_off)``; None = the very beginning), yielding
    ``(seg_seq, next_off, t_epoch, hid, tick, conn_id, chunk)``.

    ``upto_seq`` excludes the live writer's active segment (pass
    ``journal.sealed_upto()``); None reads every segment — only safe
    when no writer owns the dir (offline compaction / closed journal).
    A position whose segment was truncated away resumes at the oldest
    surviving segment, counted (``wal_position_gap``)."""
    segs = dir_segments(path)
    if upto_seq is not None:
        segs = [s for s in segs if s < int(upto_seq)]
    if not segs:
        return
    if pos is None:
        start_seq, start_off = segs[0], len(MAGIC)
    else:
        start_seq, start_off = int(pos[0]), int(pos[1])
    if start_seq not in segs and segs[0] > start_seq:
        if stats is not None:
            stats.bump("wal_position_gap")
        start_seq, start_off = segs[0], len(MAGIC)
    d = pathlib.Path(path)
    for s in segs:
        if s < start_seq:
            continue
        off = start_off if s == start_seq else len(MAGIC)
        seg = d / _SEG_FMT.format(s)
        for nxt, t, hid, tick, cid, chunk in read_entries(seg, off,
                                                          stats):
            yield s, nxt, t, hid, tick, cid, chunk


# ------------------------------------------------------- runtime helpers
# Shared by Runtime and ShardedRuntime (duck-typed: rt.journal, rt.feed,
# rt.flush, rt.stats, rt._sweep_last_seq, rt._journal_replaying) so the
# durability contract lives in exactly one place.

def checkpoint_extra(rt, tick: int) -> dict:
    """Checkpoint metadata: window tick, the per-host sweep-seq
    high-water marks (the dedup state), and — when a journal is
    attached — its fsynced position, so replay starts exactly where
    the checkpointed state ends."""
    extra: dict = {"tick": int(tick)}
    seqs = getattr(rt, "_sweep_last_seq", None)
    if seqs:
        extra["sweep_seq"] = {str(k): int(v) for k, v in seqs.items()}
    j = getattr(rt, "journal", None)
    if j is not None:
        j.fsync()                    # the position must be durable
        extra["wal"] = list(j.position())
    return extra


def post_checkpoint_truncate(rt, extra: dict) -> int:
    """After a successful checkpoint save: drop journal segments the
    checkpoint supersedes (bounds WAL disk to ~one interval). Handles
    both position shapes: flat ``(seg, off)`` and the sharded journal's
    per-shard pair list."""
    j = getattr(rt, "journal", None)
    if j is None or "wal" not in extra:
        return 0
    wal = extra["wal"]
    if wal and isinstance(wal[0], (list, tuple)):
        return j.truncate_upto(wal)
    return j.truncate_upto(int(wal[0]))


def replay_journal(rt, pos: Optional[tuple] = None) -> dict:
    """Re-fold journal chunks from ``pos`` through the normal
    decode/fold path (``rt.feed``). Appends are suppressed while
    replaying (the chunks are already in the WAL). Tolerates a torn
    tail (the journal open already truncated it; reads stop cleanly).
    Returns {"chunks": n, "records": n}."""
    j = getattr(rt, "journal", None)
    if j is None:
        return {"chunks": 0, "records": 0}
    nch = nrec = 0
    rt._journal_replaying = True
    try:
        with rt.stats.timeit("wal_replay"):
            for hid, _tick, conn_id, chunk in j.read_from(
                    tuple(pos) if pos else None):
                nrec += rt.feed(chunk, hid=hid, conn_id=conn_id)
                nch += 1
        rt.flush()
    finally:
        rt._journal_replaying = False
        # a partial frame at the WAL cut must not splice into live
        # conn bytes fed after recovery
        rt._pending = b""
    rt.stats.bump("wal_replayed_chunks", nch)
    rt.stats.bump("wal_replayed_records", nrec)
    return {"chunks": nch, "records": nrec}
