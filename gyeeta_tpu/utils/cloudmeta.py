"""Cloud instance-metadata (IMDS) collector — config-gated.

The reference probes each cloud's metadata endpoint at startup for
instance id / region / zone (``common/gy_cloud_metadata.cc:27-67``:
AWS IMDSv2 token flow, GCP metadata-flavor header, Azure api-version
query). This build defaults to the NO-EGRESS stance — nothing is
queried unless ``GYT_CLOUD_META=1`` (the descope is a flag, not an
absence) — and the endpoint is injectable so tests run against a
local fake IMDS.

Returns ``None`` cleanly when disabled, unreachable, or on any
non-cloud box (the 169.254.169.254 link-local address answers only
inside cloud VMs; the probe uses short timeouts).
"""

from __future__ import annotations

import json
import os
import urllib.error
import urllib.request
from typing import Optional

CLOUD_NONE, CLOUD_AWS, CLOUD_GCP, CLOUD_AZURE = 0, 1, 2, 3

_DEFAULT_BASE = "http://169.254.169.254"


def _get(url: str, headers: dict, timeout: float,
         method: str = "GET") -> Optional[str]:
    req = urllib.request.Request(url, headers=headers, method=method)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.read().decode("utf-8", "replace")
    except (urllib.error.URLError, OSError, ValueError):
        return None


_cached: dict = {}


def detect(base: Optional[str] = None,
           timeout: float = 0.5) -> Optional[dict]:
    """→ {"cloud_type", "instance_id", "region", "zone"} or None.

    Gated: returns None unless ``GYT_CLOUD_META=1`` (or an explicit
    ``base`` is passed — tests and operators opting in). Probes AWS
    (IMDSv2 with v1 fallback), GCP, then Azure. The result is cached
    per endpoint — instance metadata is immutable for the VM's
    lifetime, and the probes are blocking HTTP calls that must not
    re-run inside the agent's reconnect path."""
    if base is None:
        if os.environ.get("GYT_CLOUD_META") != "1":
            return None
        base = os.environ.get("GYT_CLOUD_META_URL", _DEFAULT_BASE)
    if base in _cached:
        return _cached[base]
    out = _probe(base, timeout)
    _cached[base] = out
    return out


def _probe(base: str, timeout: float) -> Optional[dict]:

    # ---- AWS: IMDSv2 token, fall back to v1-style plain GET
    tok = _get(f"{base}/latest/api/token",
               {"X-aws-ec2-metadata-token-ttl-seconds": "60"},
               timeout, method="PUT")
    hdr = {"X-aws-ec2-metadata-token": tok} if tok else {}
    iid = _get(f"{base}/latest/meta-data/instance-id", hdr, timeout)
    if iid:
        az = _get(f"{base}/latest/meta-data/placement/"
                  f"availability-zone", hdr, timeout) or ""
        return {"cloud_type": CLOUD_AWS, "instance_id": iid.strip(),
                "region": az.strip()[:-1] if az.strip() else "",
                "zone": az.strip()}

    # ---- GCP: requires the Metadata-Flavor header
    g = _get(f"{base}/computeMetadata/v1/instance/id",
             {"Metadata-Flavor": "Google"}, timeout)
    if g:
        z = _get(f"{base}/computeMetadata/v1/instance/zone",
                 {"Metadata-Flavor": "Google"}, timeout) or ""
        zone = z.strip().rsplit("/", 1)[-1]
        return {"cloud_type": CLOUD_GCP, "instance_id": g.strip(),
                "region": zone.rsplit("-", 1)[0] if zone else "",
                "zone": zone}

    # ---- Azure: api-version query + Metadata header, JSON body
    a = _get(f"{base}/metadata/instance/compute"
             f"?api-version=2021-02-01", {"Metadata": "true"}, timeout)
    if a:
        try:
            c = json.loads(a)
            return {"cloud_type": CLOUD_AZURE,
                    "instance_id": str(c.get("vmId", "")),
                    "region": str(c.get("location", "")),
                    "zone": str(c.get("zone", ""))}
        except ValueError:
            pass
    return None
