"""Framework self-metrics: counters/gauges + per-stage timing histograms.

The reference instruments itself with per-subsystem ``STATS_STR_MAP``
counters printed on a cadence (``server/gy_mconnhdlr.h:46``,
``print_stats()`` on pools/captures), per-stage latency histograms
(``GY_HISTOGRAM`` wrappers around the hot paths) and a deferred
print-offload thread. Here: a process-wide registry with O(1) bumps on
the ingest path, geometric-bucket timing histograms recorded via a
``timeit`` context manager, and a ``snapshot()``/``delta()``/
``timing_rows()`` readback surfaced by the ``selfstats`` query subsystem.
"""

from __future__ import annotations

import collections
import contextlib
import math
import threading
import time

import numpy as np

# timing buckets: 10us .. ~1000s, ×1.35 geometric (64 buckets)
_T_VMIN_MS = 0.01
_T_GAMMA = 1.35
_T_NB = 64
_T_LOG_GAMMA = math.log(_T_GAMMA)


class Stats:
    def __init__(self):
        self.counters: collections.Counter = collections.Counter()
        self.gauges: dict = {}
        self._last: dict = {}
        self._timings: dict[str, np.ndarray] = {}
        self._t_sum_ms: collections.Counter = collections.Counter()
        self.t_start = time.time()
        # queries run on worker threads since the snapshot tier
        # (net/qexec.py): Counter += and histogram increments are
        # read-modify-write, so the registry takes a lock — uncontended
        # cost is ~100ns against per-BATCH (not per-event) bumps
        self._mu = threading.Lock()

    def bump(self, name: str, n=1):
        with self._mu:
            self.counters[name] += n

    def gauge(self, name: str, v):
        with self._mu:
            self.gauges[name] = v

    # ------------------------------------------------------------ timing
    def observe_ms(self, name: str, ms: float) -> None:
        with self._mu:
            h = self._timings.get(name)
            if h is None:
                h = self._timings[name] = np.zeros(_T_NB, np.int64)
            b = 0 if ms <= _T_VMIN_MS else min(
                _T_NB - 1,
                int(math.log(ms / _T_VMIN_MS) / _T_LOG_GAMMA) + 1)
            h[b] += 1
            self._t_sum_ms[name] += ms

    @contextlib.contextmanager
    def timeit(self, name: str):
        """Per-stage wall-time histogram (the GY_HISTOGRAM analogue)."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.observe_ms(name, (time.perf_counter() - t0) * 1e3)

    @staticmethod
    def _bucket_ms(b: int) -> float:
        return _T_VMIN_MS * _T_GAMMA ** max(0, b - 1)

    def export(self) -> tuple[dict, dict]:
        """Consistent (counters, gauges) copies for renderers that
        iterate off-thread (the Prometheus exposition)."""
        with self._mu:
            return dict(self.counters), dict(self.gauges)

    def timing_rows(self) -> list[dict]:
        """One row per timed stage: count + p50/p95/p99 + total."""
        out = []
        for name, h, tot in self.timing_hists():
            n = int(h.sum())
            if n == 0:
                continue
            cum = np.cumsum(h)
            row = {"stage": name, "count": n,
                   "totalms": round(tot, 3)}
            for q, col in ((0.5, "p50ms"), (0.95, "p95ms"),
                           (0.99, "p99ms")):
                # rank semantics: the q-quantile sample is the
                # ceil(q*n)-th smallest, and the float product must not
                # skip an exact-boundary bucket (0.99*100 is
                # 99.000…0001 in binary; searchsorted on it walked past
                # a bucket whose cumulative count is exactly 99)
                r = min(n, max(1, math.ceil(q * n - 1e-9)))
                b = int(np.searchsorted(cum, r, side="left"))
                row[col] = round(self._bucket_ms(b), 4)
            out.append(row)
        return out

    def timing_hists(self) -> list[tuple[str, np.ndarray, float]]:
        """Raw geometric buckets per stage: (name, counts, total_ms) —
        the exposition source (``obs/prom.py`` maps these to cumulative
        ``le`` buckets)."""
        with self._mu:
            return [(name, self._timings[name].copy(),
                     float(self._t_sum_ms[name]))
                    for name in sorted(self._timings)]

    def snapshot(self) -> dict:
        with self._mu:
            out = dict(self.counters)
            out.update(self.gauges)
        out["uptime_sec"] = round(time.time() - self.t_start, 1)
        return out

    def delta(self) -> dict:
        """Counters since the previous delta() call (rate reporting)."""
        with self._mu:
            cur = dict(self.counters)
        out = {k: v - self._last.get(k, 0) for k, v in cur.items()}
        self._last = cur
        return {k: v for k, v in out.items() if v}


def selfstats_response(stats: Stats, alerts=None, spans=None) -> dict:
    """The ``selfstats`` query-subsystem payload (shared by both
    runtimes so the surface cannot drift). ``spans`` is the optional
    pipeline span ring (``obs/spans.SpanTracer``) — its newest entries
    ride the payload as ``selfstats.spans``."""
    out = {"counters": stats.snapshot(),
           "timings": stats.timing_rows(),
           "alerts": dict(alerts.stats) if alerts is not None else {}}
    if spans is not None:
        out["spans"] = spans.rows()
    return out


# exposition helpers (obs/prom.py): geometric bucket b covers
# (upper(b-1), upper(b)] with upper(0) = vmin — the cumulative-`le`
# mapping needs the upper edges
def bucket_upper_ms(b: int) -> float:
    """Upper edge (ms) of timing bucket ``b``; the last bucket is
    +Inf (it absorbs everything past vmin·γ^(NB-1))."""
    if b >= _T_NB - 1:
        return math.inf
    return _T_VMIN_MS * _T_GAMMA ** b
