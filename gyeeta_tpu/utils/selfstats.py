"""Framework self-metrics: named counters/gauges + periodic snapshots.

The reference instruments itself with per-subsystem ``STATS_STR_MAP``
counters printed on a cadence (``server/gy_mconnhdlr.h:46``,
``print_stats()`` on pools/captures) and a deferred print-offload thread.
Here: a process-wide registry with O(1) bumps on the ingest path and a
``snapshot()``/``delta()`` readback the runtime logs each minute.
"""

from __future__ import annotations

import collections
import time


class Stats:
    def __init__(self):
        self.counters: collections.Counter = collections.Counter()
        self.gauges: dict = {}
        self._last: dict = {}
        self.t_start = time.time()

    def bump(self, name: str, n=1):
        self.counters[name] += n

    def gauge(self, name: str, v):
        self.gauges[name] = v

    def snapshot(self) -> dict:
        out = dict(self.counters)
        out.update(self.gauges)
        out["uptime_sec"] = round(time.time() - self.t_start, 1)
        return out

    def delta(self) -> dict:
        """Counters since the previous delta() call (rate reporting)."""
        cur = dict(self.counters)
        out = {k: v - self._last.get(k, 0) for k, v in cur.items()}
        self._last = cur
        return {k: v for k, v in out.items() if v}
