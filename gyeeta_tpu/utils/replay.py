"""Stream capture + replay — the pseudo-pcap test harness analogue.

The reference replays pcap files through its live parser with IP/netns
translation (``partha/gy_pseudo_pcap_cap.cc``, driven by runtime-config
``pcaptrace`` blocks) as its offline integration fixture. The TPU
framework's capture boundary is the WIRE, not packets: this module
records timestamped event-stream chunks to a file and replays them —
into a Runtime directly, or over a socket as a registered agent — with
optional time compression and host-id translation (the analogue of the
reference's IP/port translation, so one capture can simulate many
hosts).

File format (little-endian): 8-byte magic ``GYTREC01``, then chunks of
``{t_usec u8, nbytes u4, pad u4}`` + bytes. Chunks are whatever byte
runs the recorder saw — frame boundaries inside are the decoder's
business, exactly like a live socket.
"""

from __future__ import annotations

import pathlib
import struct
import time
from typing import Iterator, Optional

import numpy as np

from gyeeta_tpu.ingest import wire

MAGIC = b"GYTREC01"
_CHDR = struct.Struct("<QII")


class StreamRecorder:
    """Append-only capture file; one ``write`` per byte run.

    ``fsync=True`` makes every chunk durable before ``write`` returns
    (power-loss-proof captures — the flush alone only survives a
    process crash, not a host crash)."""

    def __init__(self, path, clock=None, fsync: bool = False):
        import os as _os
        self.path = pathlib.Path(path)
        self._clock = clock or time.time
        self._fsync = fsync
        self._os_fsync = _os.fsync
        self._f = open(self.path, "ab")
        if self._f.tell() == 0:
            self._f.write(MAGIC)

    def write(self, buf: bytes) -> None:
        if not buf:
            return
        self._f.write(_CHDR.pack(int(self._clock() * 1e6),
                                 len(buf), 0))
        self._f.write(buf)
        # writes are already batched (one per complete-frame run): flush
        # each so a server crash loses at most the OS buffer, and never
        # a chunk header without its payload
        self._f.flush()
        if self._fsync:
            self._os_fsync(self._f.fileno())

    def flush(self) -> None:
        self._f.flush()

    def close(self) -> None:
        self._f.close()


def read_chunks(path, stats=None) -> Iterator[tuple[int, bytes]]:
    """Yield (t_usec, chunk_bytes); validates the magic. Streams —
    captures can reach many GB at product ingest rates.

    A byte-chopped final chunk (crash mid-write / torn copy) ends the
    walk CLEANLY: counted on ``stats`` as ``replay_torn_tail`` when a
    registry is passed, never a struct error or a partial-payload
    yield."""
    with open(path, "rb") as f:
        if f.read(len(MAGIC)) != MAGIC:
            raise ValueError(f"{path}: not a GYTREC capture")
        while True:
            hdr = f.read(_CHDR.size)
            if len(hdr) < _CHDR.size:
                if hdr and stats is not None:
                    stats.bump("replay_torn_tail")
                return
            tus, n, _pad = _CHDR.unpack(hdr)
            chunk = f.read(n)
            if len(chunk) < n:
                # truncated tail (crash mid-write): counted, clean stop
                if stats is not None:
                    stats.bump("replay_torn_tail")
                return
            yield tus, chunk


def remap_host_ids(buf: bytes, offset: int) -> bytes:
    """Re-encode every known frame with host_id += offset — the
    host-translation knob (the reference's pcap IP/port translation
    analogue). Entity glob-ids are NOT translated: a remapped replay
    RELOCATES the captured fleet to new host ids (service rows follow
    their keys); true fleet multiplication uses distinct simulated
    agents, whose ids derive from their host index. Unknown subtypes
    and non-event frames pass through untouched."""
    out = []
    view = memoryview(buf)
    off = 0
    hsz = wire.HEADER_DT.itemsize
    esz = wire.EVENT_NOTIFY_DT.itemsize
    while off + hsz <= len(buf):
        hdr = np.frombuffer(view, wire.HEADER_DT, 1, off)[0]
        total = int(hdr["total_sz"])
        if total < hsz or off + total > len(buf):
            break
        frame = bytes(view[off: off + total])
        if int(hdr["data_type"]) == wire.COMM_EVENT_NOTIFY:
            ev = np.frombuffer(view, wire.EVENT_NOTIFY_DT, 1, off + hsz)[0]
            dt = wire.DTYPE_OF_SUBTYPE.get(int(ev["subtype"]))
            nev = int(ev["nevents"])
            if dt is not None and "host_id" in (dt.names or ()):
                if hsz + esz + nev * dt.itemsize > total:
                    raise wire.FrameError(
                        f"nevents {nev} overflows frame at {off}")
                recs = np.frombuffer(view, dt, nev, off + hsz + esz).copy()
                with np.errstate(over="ignore"):
                    recs["host_id"] = (
                        recs["host_id"].astype(np.int64)
                        + np.int64(offset)).astype(np.uint32)
                frame = (frame[: hsz + esz] + recs.tobytes()
                         + frame[hsz + esz + recs.nbytes:])
        out.append(frame)
        off += total
    out.append(bytes(view[off:]))
    return b"".join(out)


def paced_chunks(path, speed: float = 0.0, host_id_offset: int = 0,
                 stats=None) -> Iterator[tuple[float, bytes]]:
    """Yield (delay_seconds, ready-to-feed bytes) for a capture — the
    ONE implementation of pacing, partial-frame reassembly, and host-id
    remapping, shared by the sync :func:`play` and the async CLI (which
    must interleave awaits). ``delay`` is how long the consumer should
    sleep before feeding this chunk (0 when running flat out)."""
    t0: Optional[int] = None
    w0 = time.monotonic()
    pending = b""
    for tus, chunk in read_chunks(path, stats=stats):
        delay = 0.0
        if speed > 0:
            if t0 is None:
                t0 = tus
            delay = max(0.0, w0 + (tus - t0) / 1e6 / speed
                        - time.monotonic())
        if host_id_offset:
            data = pending + chunk
            k = wire.complete_prefix(data)
            pending = data[k:]
            chunk = remap_host_ids(data[:k], host_id_offset)
        if chunk or delay:
            yield delay, chunk
    if pending:
        yield 0.0, pending             # trailing partial, unremappable


def play(path, feed_fn, speed: float = 0.0,
         host_id_offset: int = 0, sleep=time.sleep, stats=None) -> int:
    """Replay a capture through ``feed_fn(bytes)``.

    ``speed``: 0 = as fast as possible; N = N× recorded pace (1 = real
    time). Returns bytes fed. With ``host_id_offset``, frames that span
    chunk boundaries reassemble before remapping (the file format
    permits arbitrary chunking even though the server records
    complete-frame runs). A torn capture tail stops cleanly (counted on
    ``stats`` as ``replay_torn_tail``)."""
    n = 0
    for delay, chunk in paced_chunks(path, speed, host_id_offset, stats):
        if delay > 0:
            sleep(delay)
        feed_fn(chunk)
        n += len(chunk)
    return n
