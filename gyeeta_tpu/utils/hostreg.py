"""Host-side registries for announce-rate inventory records.

Two registries backing query subsystems that the reference serves from
madhava's in-memory host tables + Postgres info tables:

- :class:`HostInfoRegistry` — static host inventory (``hostinfo``
  subsystem; reference ``HOST_INFO_NOTIFY`` → hostinfotbl,
  ``common/gy_sys_hardware.h`` SYS_HARDWARE + cloud IMDS metadata,
  ``common/gy_cloud_metadata.h``);
- :class:`CgroupRegistry` — 5s per-cgroup stats (``cgroupstate``
  subsystem; reference ``common/gy_cgroup_stat.h`` CGROUP_HANDLE).

Both follow the SvcInfoRegistry pattern: dict keyed by entity id,
columns() builds dense numpy presentation columns cached until the next
update. Cgroups age out when a host stops reporting them (deleted
cgroups simply vanish from sweeps — there is no delete message).
"""

from __future__ import annotations

import numpy as np

VIRT_NAMES = ("none", "vm", "container")
CLOUD_NAMES = ("none", "aws", "gcp", "azure")


class HostInfoRegistry:
    def __init__(self):
        self._by_id: dict[int, dict] = {}
        self._cache = None

    def update(self, recs: np.ndarray) -> int:
        if len(recs):
            self._cache = None
        for r in recs:
            self._by_id[int(r["host_id"])] = {
                "ncpus": int(r["ncpus"]),
                "nnuma": int(r["nnuma"]),
                "ram_mb": int(r["ram_mb"]),
                "swap_mb": int(r["swap_mb"]),
                "boot_tusec": int(r["boot_tusec"]),
                "kern_ver_id": int(r["kern_ver_id"]),
                "distro_id": int(r["distro_id"]),
                "cputype_id": int(r["cputype_id"]),
                "instance_id": int(r["instance_id"]),
                "region_id": int(r["region_id"]),
                "zone_id": int(r["zone_id"]),
                "virt_type": int(r["virt_type"]),
                "cloud_type": int(r["cloud_type"]),
                "is_k8s": bool(r["is_k8s"]),
            }
        return len(recs)

    def get(self, host_id: int) -> dict | None:
        return self._by_id.get(host_id)

    def __len__(self) -> int:
        return len(self._by_id)

    def columns(self, names=None):
        from gyeeta_tpu.ingest import wire

        ver = getattr(names, "version", None)
        if self._cache is not None and self._cache[0] == ver:
            return self._cache[1]
        ids = sorted(self._by_id)
        rows = [self._by_id[i] for i in ids]
        n = len(ids)

        def resolve(kind, vals):
            vals = np.asarray(vals, np.uint64)
            if names is None:
                return np.array([format(int(v), "016x") for v in vals],
                                object)
            return names.resolve_array(kind, vals)

        def num(key):
            return np.array([r[key] for r in rows], np.float64)

        def enum_name(key, table):
            return np.array(
                [table[r[key]] if 0 <= r[key] < len(table) else "?"
                 for r in rows], object)

        cols = {
            "hostid": np.array(ids, np.float64),
            "host": resolve(wire.NAME_KIND_HOST, ids),
            "ncpus": num("ncpus"),
            "nnuma": num("nnuma"),
            "rammb": num("ram_mb"),
            "swapmb": num("swap_mb"),
            "boot": np.array([r["boot_tusec"] / 1e6 for r in rows],
                             np.float64),
            "kernverstr": resolve(wire.NAME_KIND_MISC,
                                  [r["kern_ver_id"] for r in rows]),
            "dist": resolve(wire.NAME_KIND_MISC,
                            [r["distro_id"] for r in rows]),
            "cputype": resolve(wire.NAME_KIND_MISC,
                               [r["cputype_id"] for r in rows]),
            "instanceid": resolve(wire.NAME_KIND_MISC,
                                  [r["instance_id"] for r in rows]),
            "region": resolve(wire.NAME_KIND_MISC,
                              [r["region_id"] for r in rows]),
            "zone": resolve(wire.NAME_KIND_MISC,
                            [r["zone_id"] for r in rows]),
            "virt": enum_name("virt_type", VIRT_NAMES),
            "cloud": enum_name("cloud_type", CLOUD_NAMES),
            "isk8s": np.array([r["is_k8s"] for r in rows], bool),
        }
        out = (cols, np.ones(n, bool))
        self._cache = (ver, out)
        return out


class CgroupRegistry:
    """Keyed by (host_id, cg_id); rows age out after ``max_age`` sweeps
    without an update (the agent resends every live cgroup each 5s)."""

    def __init__(self, max_age: int = 24):
        self._by_key: dict[tuple[int, int], dict] = {}
        self._cache = None
        self._sweep = 0
        self.max_age = max_age

    def update(self, recs: np.ndarray) -> int:
        if len(recs):
            self._cache = None
        for r in recs:
            self._by_key[(int(r["host_id"]), int(r["cg_id"]))] = {
                "dir_id": int(r["dir_id"]),
                "cpu_pct": float(r["cpu_pct"]),
                "cpu_limit_pct": float(r["cpu_limit_pct"]),
                "cpu_throttled_pct": float(r["cpu_throttled_pct"]),
                "rss_mb": float(r["rss_mb"]),
                "memory_limit_mb": float(r["memory_limit_mb"]),
                "pgmajfault_sec": float(r["pgmajfault_sec"]),
                "nprocs": int(r["nprocs"]),
                "is_v2": bool(r["is_v2"]),
                "state": int(r["state"]),
                "sweep": self._sweep,
            }
        return len(recs)

    def age(self) -> int:
        """Advance the sweep clock and drop rows unseen for max_age
        sweeps. Call once per server tick."""
        self._sweep += 1
        dead = [k for k, v in self._by_key.items()
                if self._sweep - v["sweep"] > self.max_age]
        for k in dead:
            del self._by_key[k]
        if dead:
            self._cache = None
        return len(dead)

    def __len__(self) -> int:
        return len(self._by_key)

    def columns(self, names=None):
        from gyeeta_tpu.ingest import wire
        from gyeeta_tpu.semantic.states import STATE_NAMES

        ver = getattr(names, "version", None)
        if self._cache is not None and self._cache[0] == (ver, self._sweep):
            return self._cache[1]
        keys = sorted(self._by_key)
        rows = [self._by_key[k] for k in keys]
        n = len(keys)

        def num(key):
            return np.array([r[key] for r in rows], np.float64)

        if names is None:
            dirs = np.array(
                [format(r["dir_id"], "016x") for r in rows], object)
        else:
            dirs = names.resolve_array(
                wire.NAME_KIND_MISC,
                np.array([r["dir_id"] for r in rows], np.uint64))
        cols = {
            "cgid": np.array([format(c, "016x") for _, c in keys], object),
            "dir": dirs,
            "hostid": np.array([h for h, _ in keys], np.float64),
            "cpupct": num("cpu_pct"),
            "cpulimpct": num("cpu_limit_pct"),
            "throttlepct": num("cpu_throttled_pct"),
            "rssmb": num("rss_mb"),
            "memlimmb": num("memory_limit_mb"),
            "pgmajfps": num("pgmajfault_sec"),
            "nprocs": num("nprocs"),
            "isv2": np.array([r["is_v2"] for r in rows], bool),
            "state": np.array([r["state"] for r in rows], np.int32),
            "statestr": np.array(
                [STATE_NAMES[r["state"]]
                 if 0 <= r["state"] < len(STATE_NAMES) else "?"
                 for r in rows], object),
        }
        out = (cols, np.ones(n, bool))
        self._cache = ((ver, self._sweep), out)
        return out


class MountRegistry:
    """Keyed by (host_id, mnt_id); same sweep-ageing discipline as
    :class:`CgroupRegistry` (MOUNT_HDLR capability server-side)."""

    def __init__(self, max_age: int = 24):
        self._by_key: dict[tuple[int, int], dict] = {}
        self._cache = None
        self._sweep = 0
        self.max_age = max_age

    def update(self, recs: np.ndarray) -> int:
        if len(recs):
            self._cache = None
        for r in recs:
            self._by_key[(int(r["host_id"]), int(r["mnt_id"]))] = {
                "dir_id": int(r["dir_id"]),
                "fstype_id": int(r["fstype_id"]),
                "size_mb": float(r["size_mb"]),
                "free_mb": float(r["free_mb"]),
                "used_pct": float(r["used_pct"]),
                "inodes_used_pct": float(r["inodes_used_pct"]),
                "is_network_fs": bool(r["is_network_fs"]),
                "sweep": self._sweep,
            }
        return len(recs)

    def age(self) -> int:
        self._sweep += 1
        dead = [k for k, v in self._by_key.items()
                if self._sweep - v["sweep"] > self.max_age]
        for k in dead:
            del self._by_key[k]
        if dead:
            self._cache = None
        return len(dead)

    def __len__(self) -> int:
        return len(self._by_key)

    def columns(self, names=None):
        from gyeeta_tpu.ingest import wire

        ver = getattr(names, "version", None)
        if self._cache is not None and self._cache[0] == (ver,
                                                          self._sweep):
            return self._cache[1]
        keys = sorted(self._by_key)
        rows = [self._by_key[k] for k in keys]
        n = len(keys)

        def num(key):
            return np.array([r[key] for r in rows], np.float64)

        def resolve(idkey):
            ids = np.array([r[idkey] for r in rows], np.uint64)
            if names is None:
                return np.array([format(i, "016x") for i in ids],
                                object)
            return names.resolve_array(wire.NAME_KIND_MISC, ids)

        cols = {
            "hostid": np.array([h for h, _ in keys], np.float64),
            "mnt": resolve("dir_id"),
            "fstype": resolve("fstype_id"),
            "sizemb": num("size_mb"),
            "freemb": num("free_mb"),
            "usedpct": num("used_pct"),
            "inodepct": num("inodes_used_pct"),
            "netfs": np.array([r["is_network_fs"] for r in rows],
                              bool),
        }
        out = (cols, np.ones(n, bool))
        self._cache = ((ver, self._sweep), out)
        return out


class NetIfRegistry:
    """Keyed by (host_id, if_id); NET_IF_HDLR capability server-side."""

    def __init__(self, max_age: int = 24):
        self._by_key: dict[tuple[int, int], dict] = {}
        self._cache = None
        self._sweep = 0
        self.max_age = max_age

    def update(self, recs: np.ndarray) -> int:
        if len(recs):
            self._cache = None
        for r in recs:
            self._by_key[(int(r["host_id"]), int(r["if_id"]))] = {
                "name_id": int(r["name_id"]),
                "speed_mbps": float(r["speed_mbps"]),
                "rx_mb_sec": float(r["rx_mb_sec"]),
                "tx_mb_sec": float(r["tx_mb_sec"]),
                "rx_errs_sec": float(r["rx_errs_sec"]),
                "tx_errs_sec": float(r["tx_errs_sec"]),
                "is_up": bool(r["is_up"]),
                "sweep": self._sweep,
            }
        return len(recs)

    def age(self) -> int:
        self._sweep += 1
        dead = [k for k, v in self._by_key.items()
                if self._sweep - v["sweep"] > self.max_age]
        for k in dead:
            del self._by_key[k]
        if dead:
            self._cache = None
        return len(dead)

    def __len__(self) -> int:
        return len(self._by_key)

    def columns(self, names=None):
        from gyeeta_tpu.ingest import wire

        ver = getattr(names, "version", None)
        if self._cache is not None and self._cache[0] == (ver,
                                                          self._sweep):
            return self._cache[1]
        keys = sorted(self._by_key)
        rows = [self._by_key[k] for k in keys]
        n = len(keys)

        def num(key):
            return np.array([r[key] for r in rows], np.float64)

        ids = np.array([r["name_id"] for r in rows], np.uint64)
        if names is None:
            ifnames = np.array([format(i, "016x") for i in ids], object)
        else:
            ifnames = names.resolve_array(wire.NAME_KIND_MISC, ids)
        cols = {
            "hostid": np.array([h for h, _ in keys], np.float64),
            "name": ifnames,
            "speedmbps": num("speed_mbps"),
            "rxmbsec": num("rx_mb_sec"),
            "txmbsec": num("tx_mb_sec"),
            "rxerrsec": num("rx_errs_sec"),
            "txerrsec": num("tx_errs_sec"),
            "up": np.array([r["is_up"] for r in rows], bool),
        }
        out = (cols, np.ones(n, bool))
        self._cache = ((ver, self._sweep), out)
        return out
