"""Jitted microbatch update steps — the hot loop of the framework.

Replaces the reference's per-event handler chain (madhava L1 dispatch →
L2 ``partha_*`` RCU walks, ``server/gy_mconnhdlr.cc:2521-3490,4700``) with
four batched tensor folds, each one traced once and fused by XLA:

- ``ingest_conn``   — TCP_CONN flow records → per-svc counters, per-svc
  distinct-client HLL, global HLL, CMS bytes, heavy-hitter top-K
  (the ``partha_tcp_conn_info``/``add_tcp_conn_cli`` analogue)
- ``ingest_resp``   — raw response samples → per-svc windowed loghist +
  per-svc t-digest (replacing agent-side ``resp_hist_`` updates,
  ``common/gy_socket_stat.cc:1554``)
- ``ingest_listener`` / ``ingest_host`` — 5s state sweeps → gauge panels
  (the ``partha_listener_state`` hot loop, ``gy_mconnhdlr.cc:10993``)
- ``tick_5s``       — closes the 5s window slab (scheduler cadence,
  ``common/gy_scheduler.h`` 5s domain)

All functions are pure ``state, batch → state`` and donate-friendly. Batches
are the columnar pytrees from ``ingest/decode.py`` (device arrays inside
jit). `fold_step` is the fused flagship step used by bench + __graft_entry__.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp

from gyeeta_tpu.engine import table
from gyeeta_tpu.engine.aggstate import (
    AggState, EngineCfg, CTR_BYTES_SENT, CTR_BYTES_RCVD, CTR_NCONN_CLOSED,
    CTR_DUR_SUM_US,
)
from gyeeta_tpu.sketch import countmin, hyperloglog as hll, invertible, \
    loghist, tdigest, topk, windows


# Bench-only ablation switch: GYT_BENCH_ABLATE="topk,tdigest" compiles the
# fold WITHOUT those components so per-component device cost can be
# attributed on real hardware. Read ONCE at module import — set it in the
# environment before the process starts (the _ablate.py driver spawns
# subprocesses for exactly this reason). Never set in production.
_ABLATE = frozenset(
    os.environ.get("GYT_BENCH_ABLATE", "").split(",")) - {""}


def ingest_conn(cfg: EngineCfg, st: AggState, cb) -> AggState:
    """Fold a ConnBatch. cb fields are (B,) device arrays.

    Only accept-observed (server-side) lanes touch the per-service slab
    — a client-observed record names a REMOTE service and must not
    materialize (or re-home) its row; the reference likewise keeps
    client-half conns in remote/unknown maps, not the listener table
    (``server/gy_mconnhdlr.h:614-632``). The global HLL sees every
    valid lane (it dedups by flow key, so dual observation is safe);
    the additive CMS / flow top-K fold accept-observed lanes only, so a
    dual-observed flow's bytes are never counted twice. The dep graph
    dedups its halves via scatter-max.
    """
    valid = cb.valid
    svc_side = valid & cb.is_accept
    if "upsert" in _ABLATE:
        tbl, rows = st.tbl, table.lookup(st.tbl, cb.svc_hi, cb.svc_lo,
                                         svc_side)
        any_new = jnp.any(svc_side & (rows < 0))
    else:
        tbl, rows, any_new = table.upsert_fast2(
            st.tbl, cb.svc_hi, cb.svc_lo, svc_side)
    ok = svc_side & (rows >= 0)
    rowz = jnp.where(ok, rows, 0)
    S = cfg.svc_capacity

    # per-svc windowed counters: ONE row scatter-add of a (B, NCTR)
    # update block (columns in CTR_* order). Four per-column scatters
    # cost 4x the index-resolution work on both CPU and TPU (measured
    # 6.3 ms → 1.9 ms per 32k-lane dispatch on one core); per-slot
    # accumulation order per column is still lane order, so the result
    # is bit-identical to the per-column form.
    ctr_win = st.ctr_win
    lanes = jnp.where(ok, rowz, S)  # S = dropped (mode=drop)
    if "ctr" not in _ABLATE:
        upd = jnp.stack(
            [cb.bytes_sent, cb.bytes_rcvd,
             cb.is_close.astype(jnp.float32), cb.duration_us], axis=1)
        cur = st.ctr_win.cur.at[lanes].add(upd, mode="drop")
        ctr_win = st.ctr_win._replace(cur=cur)

    # the service→host homing column only changes when a NEW row is
    # claimed (existing rows re-write the value they already hold;
    # rehoming re-announces through the listener sweep, which upserts)
    # — so the scatter-set rides the upsert's own miss signal and the
    # all-hit steady state pays nothing for it
    svc_host = jax.lax.cond(
        any_new,
        lambda col: col.at[lanes].set(cb.host_id, mode="drop"),
        lambda col: col, st.svc_host)
    svc_hll = st.svc_hll if "svchll" in _ABLATE else hll.update_entities(
        st.svc_hll, rowz, cb.cli_hi, cb.cli_lo, valid=ok)
    glob_hll = st.glob_hll if "globhll" in _ABLATE else hll.update(
        st.glob_hll, cb.flow_hi, cb.flow_lo, valid=valid)
    # byte accounting takes the ACCEPT side only (valid=svc_side below
    # already masks client-observed lanes): a dual-observed flow would
    # otherwise count twice into the additive CMS/top-K. Server-side
    # listener accounting is also where the reference attaches traffic
    # stats.
    tot_bytes = cb.bytes_sent + cb.bytes_rcvd
    cms = st.cms if "cms" in _ABLATE else countmin.update(
        st.cms, cb.flow_hi, cb.flow_lo, tot_bytes, valid=svc_side)
    # sketch-assisted candidate compaction (CMS+heap, the shape of
    # the FPGA sketch-acceleration papers): the CMS — queried AFTER
    # this batch folded into it — upper-bounds every flow's
    # cumulative mass, so only the topk_budget best lanes enter the
    # grouping sort. One hash row is enough for a safe-side
    # ranking signal (sketch/countmin.py:upper_bound).
    est = None
    if "cms" not in _ABLATE and 0 < cfg.topk_budget:
        est = countmin.upper_bound(cms, cb.flow_hi, cb.flow_lo)
    # priority-aware hot admission (PSketch): on top of the budget's
    # relative ranking, a lane enters the exact top-K merge only when
    # its estimate clears an absolute floor of the total folded mass —
    # colder lanes keep their mass in the CMS and their excluded mass
    # lands in ``evicted`` (the bound stays honest because a floored
    # lane scores −1, same as padding, and unselected valid mass is
    # always accounted).
    hot = None
    if est is not None and cfg.hh_hot_frac > 0:
        thresh = jnp.float32(cfg.hh_hot_frac) * countmin.total(cms)
        hot = est >= thresh
    n = cb.flow_hi.shape[0]
    sel = None
    if est is not None and 0 < cfg.topk_budget < n:
        # ONE shared candidate selection feeds BOTH heavy-hitter
        # structures (the exact merge's grouping sort and the
        # invertible bucket-ownership writes): score = estimate on
        # admitted lanes, −1 on padding/cold lanes. Mass excluded by
        # the selection is charged to ``evicted`` here, so the
        # undercount bound stays exactly as honest as the in-update
        # compaction it replaces.
        score = jnp.where(svc_side, est.astype(jnp.float32), -1.0)
        if hot is not None:
            score = jnp.where(hot, score, -1.0)
        _, sel = jax.lax.top_k(score, cfg.topk_budget)
        sel_ok = score[sel] >= 0.0
        c_hi, c_lo = cb.flow_hi[sel], cb.flow_lo[sel]
        c_vals = jnp.where(sel_ok, tot_bytes[sel].astype(jnp.float32),
                           0.0)
        c_prio = jnp.where(sel_ok, est[sel].astype(jnp.float32), 0.0)
        extra_evicted = (jnp.sum(jnp.where(svc_side, tot_bytes, 0.0))
                         - jnp.sum(c_vals))
    if "topk" in _ABLATE:
        flow_topk = st.flow_topk
    elif sel is not None:
        ftk = st.flow_topk._replace(
            evicted=st.flow_topk.evicted + extra_evicted)
        flow_topk = topk.update(ftk, c_hi, c_lo, c_vals, valid=sel_ok)
    else:
        flow_topk = topk.update(
            st.flow_topk, cb.flow_hi, cb.flow_lo, tot_bytes,
            valid=svc_side, est=est, budget=cfg.topk_budget)
    if "hh" in _ABLATE or cfg.hh_width <= 0:
        inv = st.inv
    else:
        # invertible candidate buckets (sketch/invertible.py): the
        # selected (admitted) lanes compete for bucket ownership with
        # their estimate as priority — per-tick decoding recovers
        # heavy keys straight from this state, no candidate list.
        # Falls back to every accept-side lane with its own mass as
        # priority when the CMS is ablated.
        if sel is not None:
            inv = invertible.update(st.inv, c_hi, c_lo, c_prio,
                                    valid=sel_ok)
        else:
            inv_prio = est if est is not None else tot_bytes
            inv = invertible.update(st.inv, cb.flow_hi, cb.flow_lo,
                                    inv_prio, valid=svc_side,
                                    budget=cfg.topk_budget)
        if hot is not None:
            inv = inv._replace(n_hot=inv.n_hot + jnp.sum(
                svc_side & hot).astype(jnp.float32))
    return st._replace(
        tbl=tbl, ctr_win=ctr_win, svc_host=svc_host, svc_hll=svc_hll,
        glob_hll=glob_hll, cms=cms, flow_topk=flow_topk, inv=inv,
        n_conn=st.n_conn + jnp.sum(valid).astype(jnp.float32),
    )


def ingest_resp(cfg: EngineCfg, st: AggState, rb) -> AggState:
    """Fold one RespBatch of raw (glob_id, resp_us) samples — the
    single-microbatch path (partial slabs at cadence/query boundaries,
    sharded per-batch folds). Identical semantics to the hot loop
    (``ingest_resp_bulk``): digest samples STAGE; compression happens
    via the pressure-triggered ``td_flush_partial``/``td_drain``. An
    earlier inline route-and-compress here vmapped the compression
    sort over every entity per call — O(capacity), 1.1 s per
    microbatch at the 65k north-star geometry (the r4 fold collapse).

    Lookup-only: a response sample never CREATES a service row —
    services enter the table via conn/listener streams (the reference
    resolves resp events against listener_tbl_ and drops misses,
    ``gy_socket_stat.cc`` handle_tcp_resp_event). Unknowns are counted,
    not folded, so all paths agree regardless of batching.
    """
    return ingest_resp_flat(cfg, st, rb)


def td_flush(cfg: EngineCfg, st: AggState) -> AggState:
    """Compress the staged digest samples into the per-svc digests (one
    vmapped pass) and clear the stage."""
    if "tdigest" in _ABLATE:
        return st
    svc_td, stage, stage_n = tdigest.flush_staged(
        st.svc_td, st.td_stage, st.td_stage_n)
    return st._replace(svc_td=svc_td, td_stage=stage, td_stage_n=stage_n)


def td_flush_partial(cfg: EngineCfg, st: AggState) -> AggState:
    """Compress the ``cfg.td_flush_m`` fullest digest stages and clear
    them — the hot-loop flush. O(m) per call regardless of capacity;
    the runtime triggers it from a host-side pressure check instead of
    an in-graph ``lax.cond`` (a cond carrying the 128 MB stage forced
    whole-buffer copies every dispatch — measured 110 ms/dispatch at
    65k capacity even when the branch was NOT taken)."""
    if "tdigest" in _ABLATE:
        return st
    svc_td, stage, stage_n = tdigest.flush_staged_topm(
        st.svc_td, st.td_stage, st.td_stage_n, cfg.td_flush_m)
    return st._replace(svc_td=svc_td, td_stage=stage, td_stage_n=stage_n)


def stage_pressure(st: AggState):
    """Max staged-sample count over entities — the host-side flush
    trigger signal (a () int32; readback is one scalar)."""
    return jnp.max(st.td_stage_n)


def ingest_resp_bulk(cfg: EngineCfg, st: AggState, rbs) -> AggState:
    """Flatten a (K, B) stacked resp batch and fold it in one pass."""
    flat = jax.tree.map(lambda x: x.reshape((-1,) + x.shape[2:]), rbs)
    return ingest_resp_flat(cfg, st, flat)


def ingest_resp_flat(cfg: EngineCfg, st: AggState, flat) -> AggState:
    """Process response samples in ONE vectorized pass over flat lanes
    — the fold_many epilogue and the sharded per-shard fold.

    Replaces per-microbatch ``ingest_resp`` calls: one table lookup,
    one loghist scatter-add, one digest staging route (compression
    amortizes via pressure-triggered ``td_flush_partial``). Unknown
    services (never
    announced by conn/listener streams) drop and are counted — the
    reference likewise only folds response stats into *known* listeners
    (``gy_socket_stat.cc`` resp events resolve against listener_tbl_).
    """
    valid = flat.valid
    rows = table.lookup(st.tbl, flat.svc_hi, flat.svc_lo, valid)
    ok = valid & (rows >= 0)
    n_unknown = jnp.sum(valid & (rows < 0)).astype(jnp.float32)
    rowz = jnp.where(ok, rows, 0)
    resp_win = st.resp_win
    if "loghist" not in _ABLATE:
        cur = loghist.update_entities(
            st.resp_win.cur, cfg.resp_spec, rowz, flat.resp_us, valid=ok)
        resp_win = st.resp_win._replace(cur=cur)
    stage, stage_n = st.td_stage, st.td_stage_n
    n_over = jnp.int32(0)
    if "tdigest" not in _ABLATE:
        # duty-cycled digest sampling (the reference samples response
        # events at the source, RESP_SAMPLING ~50%, common/gy_ebpf.h:29):
        # the loghist above folds EVERY sample (lossless counts); the
        # digest — a tail-quantile estimator — takes a strided 1-in-N
        # subsample, shrinking the routing sort and flush cadence N×.
        # Static stride keeps shapes fixed; lane order is arrival order,
        # uncorrelated with service identity.
        k = max(1, cfg.td_sample_stride)
        stage, stage_n, n_over = tdigest.stage_samples(
            stage, stage_n, jnp.where(ok, rows, -1)[::k],
            flat.resp_us[::k])
    return st._replace(
        resp_win=resp_win, td_stage=stage, td_stage_n=stage_n,
        n_resp=st.n_resp + jnp.sum(valid).astype(jnp.float32),
        n_resp_unknown=st.n_resp_unknown + n_unknown,
        n_td_overflow=st.n_td_overflow + n_over.astype(jnp.float32),
    )


def ingest_listener(cfg: EngineCfg, st: AggState, lb) -> AggState:
    """Fold a ListenerBatch: gauges + learned QPS/active-conn baselines.

    The baseline histograms are the self-learning signal of the reference
    classifier (qps_hist_/active_conn_hist_, common/gy_socket_stat.h:365):
    every 5s sweep contributes one QPS and one active-conn sample per
    service; the classifier later compares current values against the
    p95/p25 of these histograms.
    """
    from gyeeta_tpu.ingest import decode as D

    valid = lb.valid
    tbl, rows = table.upsert(st.tbl, lb.svc_hi, lb.svc_lo, valid)
    ok = valid & (rows >= 0)
    rowz = jnp.where(ok, rows, 0)
    lanes = jnp.where(ok, rows, cfg.svc_capacity)
    svc_stats = st.svc_stats.at[lanes].set(lb.stats, mode="drop")
    svc_host = st.svc_host.at[lanes].set(lb.host_id, mode="drop")
    qps = lb.stats[:, D.STAT_NQRYS] / 5.0
    qps_hist = loghist.update_entities(
        st.qps_hist, cfg.qps_spec, rowz, qps, valid=ok)
    active_hist = loghist.update_entities(
        st.active_hist, cfg.active_spec, rowz,
        lb.stats[:, D.STAT_NCONNS_ACTIVE], valid=ok)
    return st._replace(tbl=tbl, svc_stats=svc_stats, svc_host=svc_host,
                       qps_hist=qps_hist, active_hist=active_hist)


def ingest_task(cfg: EngineCfg, st: AggState, tb) -> AggState:
    """Fold a TaskBatch (5s process-group sweep, ref MAGGR_TASK updates in
    ``partha_aggr_task_state``): gauges + agent state + learned CPU%%
    baseline + last-seen tick for ageing."""
    valid = tb.valid
    tbl, rows = table.upsert(st.task_tbl, tb.key_hi, tb.key_lo, valid)
    ok = valid & (rows >= 0)
    rowz = jnp.where(ok, rows, 0)
    lanes = jnp.where(ok, rows, cfg.task_capacity)
    stats = st.task_stats.at[lanes].set(tb.stats, mode="drop")
    state = st.task_state.at[lanes].set(tb.state, mode="drop")
    issue = st.task_issue.at[lanes].set(tb.issue, mode="drop")
    host = st.task_host.at[lanes].set(tb.host_id, mode="drop")
    c_hi = st.task_comm_hi.at[lanes].set(
        tb.comm_hi.astype(jnp.uint32), mode="drop")
    c_lo = st.task_comm_lo.at[lanes].set(
        tb.comm_lo.astype(jnp.uint32), mode="drop")
    r_hi = st.task_rel_hi.at[lanes].set(
        tb.rel_hi.astype(jnp.uint32), mode="drop")
    r_lo = st.task_rel_lo.at[lanes].set(
        tb.rel_lo.astype(jnp.uint32), mode="drop")
    from gyeeta_tpu.ingest import decode as D
    cpu_hist = loghist.update_entities(
        st.task_cpu_hist, cfg.taskcpu_spec, rowz,
        tb.stats[:, D.TASK_CPU_PCT], valid=ok)
    last = st.task_last_tick.at[lanes].set(st.resp_win.tick, mode="drop")
    return st._replace(
        task_tbl=tbl, task_stats=stats, task_state=state, task_issue=issue,
        task_host=host, task_comm_hi=c_hi, task_comm_lo=c_lo,
        task_rel_hi=r_hi, task_rel_lo=r_lo, task_cpu_hist=cpu_hist,
        task_last_tick=last)


# api_ctr column indices
APIC_NREQ = 0
APIC_NERR = 1
APIC_BYTES_IN = 2
APIC_BYTES_OUT = 3


def ingest_trace(cfg: EngineCfg, st: AggState, tb) -> AggState:
    """Fold a TraceBatch into the per-(svc, api) slab: counters +
    response-time loghist (the REQ_TRACE_TRAN fan-in aggregation,
    ``gy_comm_proto.h:3288`` — per-API latency sketches, north-star
    config #5).

    Also upserts the SERVICE row: a parsed server-side transaction is
    direct evidence of a live listener (stronger than a resp sample,
    which stays lookup-only) — so trace-only sources (pcap files,
    traced conns without a listener stream) still materialize svcstate
    rows for the trace→resp bridge to land on."""
    svc_tbl, svc_rows = table.upsert_fast(st.tbl, tb.svc_hi, tb.svc_lo,
                                          tb.valid)
    svc_ok = tb.valid & (svc_rows >= 0)
    svc_lanes = jnp.where(svc_ok, svc_rows, cfg.svc_capacity)
    svc_host = st.svc_host.at[svc_lanes].set(tb.host_id, mode="drop")
    # parsed server-side errors accumulate into the svc ser_errors
    # gauge — REAL error counts for trace-observed services (the
    # err-HTTP cheap tier's destination, gy_svc_net_capture.h:286).
    # Hosts with a listener stream overwrite the gauge each 5s sweep
    # (the agent's own count wins); trace-only sources keep the sum.
    from gyeeta_tpu.ingest.decode import STAT_SER_ERRORS
    svc_stats = st.svc_stats.at[svc_lanes, STAT_SER_ERRORS].add(
        jnp.where(svc_ok & tb.is_err, 1.0, 0.0), mode="drop")
    st = st._replace(tbl=svc_tbl, svc_host=svc_host,
                     svc_stats=svc_stats)
    valid = tb.valid
    tbl, rows = table.upsert(st.api_tbl, tb.key_hi, tb.key_lo, valid)
    ok = valid & (rows >= 0)
    rowz = jnp.where(ok, rows, 0)
    A = cfg.api_capacity
    lanes = jnp.where(ok, rows, A)
    set_ = lambda col, v: col.at[lanes].set(v, mode="drop")  # noqa: E731
    ctr = st.api_ctr
    ctr = ctr.at[lanes, APIC_NREQ].add(jnp.where(ok, 1.0, 0.0),
                                       mode="drop")
    ctr = ctr.at[lanes, APIC_NERR].add(
        jnp.where(ok & tb.is_err, 1.0, 0.0), mode="drop")
    ctr = ctr.at[lanes, APIC_BYTES_IN].add(jnp.where(ok, tb.byin, 0.0),
                                           mode="drop")
    ctr = ctr.at[lanes, APIC_BYTES_OUT].add(jnp.where(ok, tb.byout, 0.0),
                                            mode="drop")
    hist = loghist.update_entities(st.api_resp_hist, cfg.apiresp_spec,
                                   rowz, tb.resp_us, valid=ok)
    return st._replace(
        api_tbl=tbl,
        api_svc_hi=set_(st.api_svc_hi, tb.svc_hi.astype(jnp.uint32)),
        api_svc_lo=set_(st.api_svc_lo, tb.svc_lo.astype(jnp.uint32)),
        api_id_hi=set_(st.api_id_hi, tb.api_hi.astype(jnp.uint32)),
        api_id_lo=set_(st.api_id_lo, tb.api_lo.astype(jnp.uint32)),
        api_proto=set_(st.api_proto, tb.proto),
        api_resp_hist=hist, api_ctr=ctr,
        api_host=set_(st.api_host, tb.host_id),
        api_last_tick=set_(st.api_last_tick, st.resp_win.tick),
    )


def age_apis(cfg: EngineCfg, st: AggState, max_age_ticks: int) -> AggState:
    """Tombstone (svc, api) rows unseen for ``max_age_ticks`` ticks."""
    seen = st.api_last_tick >= 0
    stale = seen & (st.resp_win.tick - st.api_last_tick
                    > jnp.int32(max_age_ticks))
    tbl, killed = table.tombstone_rows(st.api_tbl, stale)
    z32 = lambda col: jnp.where(killed, jnp.uint32(0), col)  # noqa: E731
    return st._replace(
        api_tbl=tbl,
        api_svc_hi=z32(st.api_svc_hi), api_svc_lo=z32(st.api_svc_lo),
        api_id_hi=z32(st.api_id_hi), api_id_lo=z32(st.api_id_lo),
        api_proto=jnp.where(killed, 0, st.api_proto),
        api_resp_hist=jnp.where(killed[:, None], 0.0, st.api_resp_hist),
        api_ctr=jnp.where(killed[:, None], 0.0, st.api_ctr),
        api_host=jnp.where(killed, -1, st.api_host),
        api_last_tick=jnp.where(killed, -1, st.api_last_tick),
    )


def ping_tasks(cfg: EngineCfg, st: AggState, pb) -> AggState:
    """Fold a PingBatch (process-group keepalives, the ref
    PING_TASK_AGGR ``gy_comm_proto.h:1384``): refresh ``task_last_tick``
    for rows that EXIST — lookup, never upsert. A quiet long-lived group
    keeps its slot (and its learned CPU baseline) without a stats sweep;
    pings for unknown groups are dropped (the reference asks the partha
    to re-announce instead of fabricating empty rows)."""
    rows = table.lookup(st.task_tbl, pb.key_hi, pb.key_lo, pb.valid)
    lanes = jnp.where(rows >= 0, rows, cfg.task_capacity)
    last = st.task_last_tick.at[lanes].set(st.resp_win.tick, mode="drop")
    return st._replace(task_last_tick=last)


def ingest_delta(cfg: EngineCfg, st: AggState, dep, db, tick):
    """Fold a DeltaBatch (``ingest/decode.py:delta_batch``) — the edge
    pre-aggregation path: agents fold their own conn/resp streams
    locally (``sketch/edgefold.py``) and the wire carries mergeable
    partials instead of raw tuples. Every merge here is the SAME
    monotone operation the raw fold (and the history downsampler)
    applies, so a delta-fed engine reaches the same state the raw-fed
    fold would, up to float-addition order and the declared truncation
    bounds:

    - counters / loghist buckets / CMS mass / dep edges: scatter-add
      of per-sweep sums (counts are exact; float byte sums differ only
      in addition order);
    - HLL registers: scatter-max of the agent's register maxes —
      BIT-IDENTICAL to folding the raw keys;
    - flows: aggregated (key, bytes) lanes feed CMS/top-K/invertible
      exactly like raw lanes, with the agent's truncated residual mass
      charged to the top-K ``evicted`` undercount bound — bound
      honesty survives the edge fold.

    One table upsert per dispatch (the unique-svc section); every
    family then row-resolves with lookups against the updated table.
    Returns ``(state, dep)``.
    """
    from gyeeta_tpu.parallel import depgraph as dg

    S = cfg.svc_capacity
    # ---- ONE upsert over the unique svc keys of the whole dispatch
    tbl, urows, any_new = table.upsert_fast2(
        st.tbl, db.svc_hi, db.svc_lo, db.svc_valid)
    ok_u = db.svc_valid & (urows >= 0)
    lanes_u = jnp.where(ok_u, urows, S)
    # owning-host column rides the upsert's own miss signal (see
    # ingest_conn: existing rows re-write the value they already hold)
    svc_host = jax.lax.cond(
        any_new,
        lambda col: col.at[lanes_u].set(db.svc_host, mode="drop"),
        lambda col: col, st.svc_host)

    # ---- per-svc exact counters (ctr_win order) + event counts
    rc = table.lookup(tbl, db.ctr_hi, db.ctr_lo, db.ctr_valid)
    ok_c = db.ctr_valid & (rc >= 0)
    lanes_c = jnp.where(ok_c, rc, S)
    upd = jnp.where(ok_c[:, None], db.ctr_vals[:, :4],
                    jnp.float32(0.0))
    ctr_win = st.ctr_win._replace(
        cur=st.ctr_win.cur.at[lanes_c].add(upd, mode="drop"))
    n_conn_add = jnp.sum(jnp.where(ok_c, db.ctr_vals[:, 4], 0.0))
    n_resp_add = jnp.sum(jnp.where(ok_c, db.ctr_vals[:, 5], 0.0))

    # ---- per-svc resp loghist bucket counts (exact scatter-add)
    rh = table.lookup(tbl, db.hist_hi, db.hist_lo, db.hist_valid)
    ok_h = db.hist_valid & (rh >= 0)
    roww = jnp.where(ok_h, rh, 0)
    w = jnp.where(ok_h, db.hist_w, 0.0)
    resp_win = st.resp_win._replace(
        cur=st.resp_win.cur.at[roww, db.hist_bucket].add(w))

    # ---- per-svc distinct-client HLL register maxes (scatter-max)
    rs = table.lookup(tbl, db.shll_hi, db.shll_lo, db.shll_valid)
    ok_s = db.shll_valid & (rs >= 0)
    rank_s = jnp.where(ok_s, db.shll_rank, 0)
    svc_hll = st.svc_hll._replace(
        regs=st.svc_hll.regs.at[jnp.where(ok_s, rs, 0),
                                db.shll_reg].max(rank_s))

    # ---- global flow HLL register maxes
    rank_g = jnp.where(db.ghll_valid, db.ghll_rank, 0)
    glob_hll = st.glob_hll._replace(
        regs=st.glob_hll.regs.at[db.ghll_reg].max(rank_g))

    # ---- t-digest stage (pre-strided at the agent — the same duty
    # cycle the raw fold applies; compression stays pressure-driven)
    rt_ = table.lookup(tbl, db.td_hi, db.td_lo, db.td_valid)
    ok_t = db.td_valid & (rt_ >= 0)
    stage, stage_n, n_over = tdigest.stage_samples(
        st.td_stage, st.td_stage_n, jnp.where(ok_t, rt_, -1),
        db.td_val)

    # ---- flow aggregates → CMS, top-K, invertible buckets (with the
    # agent-side truncation residual charged to the undercount bound)
    fv = db.flow_valid
    cms = countmin.update(st.cms, db.flow_hi, db.flow_lo, db.flow_val,
                          valid=fv)
    est = countmin.upper_bound(cms, db.flow_hi, db.flow_lo)
    ftk = st.flow_topk._replace(
        evicted=st.flow_topk.evicted + db.evicted_add[0])
    hot = None
    vhot = fv
    if cfg.hh_hot_frac > 0:
        thresh = jnp.float32(cfg.hh_hot_frac) * countmin.total(cms)
        hot = est >= thresh
        vhot = fv & hot
        # cold valid mass never reaches the exact merge — accounted
        # (the PSketch floor, same semantics as ingest_conn)
        ftk = ftk._replace(evicted=ftk.evicted + jnp.sum(
            jnp.where(fv & ~hot, db.flow_val, 0.0)))
    flow_topk = topk.update(ftk, db.flow_hi, db.flow_lo, db.flow_val,
                            valid=vhot, est=est,
                            budget=cfg.topk_budget)
    if "hh" in _ABLATE or cfg.hh_width <= 0:
        inv = st.inv
    else:
        inv = invertible.update(st.inv, db.flow_hi, db.flow_lo,
                                jnp.where(vhot, est, 0.0), valid=vhot,
                                budget=cfg.topk_budget)
        if hot is not None:
            inv = inv._replace(n_hot=inv.n_hot + jnp.sum(
                fv & hot).astype(jnp.float32))

    # ---- dependency edges (pre-aggregated direct edges)
    dep = dg.fold_edges(dep, db.dep_cli_hi, db.dep_cli_lo,
                        db.dep_cli_svc, db.dep_ser_hi, db.dep_ser_lo,
                        db.dep_bytes, db.dep_valid, tick,
                        nconn=db.dep_nconn)

    st = st._replace(
        tbl=tbl, ctr_win=ctr_win, resp_win=resp_win, svc_host=svc_host,
        svc_hll=svc_hll, glob_hll=glob_hll, td_stage=stage,
        td_stage_n=stage_n, cms=cms, flow_topk=flow_topk, inv=inv,
        n_conn=st.n_conn + n_conn_add,
        n_resp=st.n_resp + n_resp_add,
        n_td_overflow=st.n_td_overflow + n_over.astype(jnp.float32),
    )
    return st, dep


def age_tasks(cfg: EngineCfg, st: AggState, max_age_ticks: int) -> AggState:
    """Tombstone process groups not seen for ``max_age_ticks`` base ticks
    (the reference ages MAGGR_TASK entries via ping/delete msgs,
    ``gy_comm_proto.h:1384-1399``; we age by last-sweep tick)."""
    seen = st.task_last_tick >= 0
    stale = seen & (st.resp_win.tick - st.task_last_tick
                    > jnp.int32(max_age_ticks))
    tbl, killed = table.tombstone_rows(st.task_tbl, stale)
    return st._replace(
        task_tbl=tbl,
        task_stats=jnp.where(killed[:, None], 0.0, st.task_stats),
        task_state=jnp.where(killed, 0, st.task_state),
        task_issue=jnp.where(killed, 0, st.task_issue),
        task_host=jnp.where(killed, -1, st.task_host),
        # cpu_hist is scatter-added, never overwritten: zero it here or a
        # reclaimed slot inherits the dead group's learned baseline
        task_cpu_hist=jnp.where(killed[:, None], 0.0, st.task_cpu_hist),
        task_last_tick=jnp.where(killed, -1, st.task_last_tick),
    )


def compact_tasks(cfg: EngineCfg, st: AggState) -> AggState:
    """Rebuild the task slab without tombstones (cf. compact_state)."""
    cols = {
        "stats": st.task_stats, "state": st.task_state,
        "issue": st.task_issue, "host": st.task_host,
        "comm_hi": st.task_comm_hi, "comm_lo": st.task_comm_lo,
        "rel_hi": st.task_rel_hi, "rel_lo": st.task_rel_lo,
        "cpu_hist": st.task_cpu_hist, "last": st.task_last_tick,
    }
    tbl, c = table.compact(st.task_tbl, cols)
    live = table.live_mask(tbl)
    return st._replace(
        task_tbl=tbl, task_stats=c["stats"], task_state=c["state"],
        task_issue=c["issue"],
        task_host=jnp.where(live, c["host"], -1),
        task_comm_hi=c["comm_hi"], task_comm_lo=c["comm_lo"],
        task_rel_hi=c["rel_hi"], task_rel_lo=c["rel_lo"],
        task_cpu_hist=c["cpu_hist"],
        task_last_tick=jnp.where(live, c["last"], -1))


def ingest_host(cfg: EngineCfg, st: AggState, hb) -> AggState:
    """Fold a HostBatch (decode.host_batch): dense panel write by host_id."""
    hid = jnp.where(hb.valid, hb.host_id, cfg.n_hosts)
    panel = st.host_panel.at[hid].set(
        hb.panel.astype(jnp.float32), mode="drop")
    last = st.host_last_tick.at[hid].set(st.resp_win.tick, mode="drop")
    return st._replace(host_panel=panel, host_last_tick=last)


def ingest_cpumem(cfg: EngineCfg, st: AggState, cm) -> AggState:
    """Fold a CpuMemBatch (the 2s path): panel write + fleet-wide
    server-side classification (``semantic/cpumem.py`` — the SYS_CPU/
    SYS_MEM issue scans, ``common/gy_sys_stat.h:131``)."""
    from gyeeta_tpu.semantic import cpumem as CM

    hid = jnp.where(cm.valid, cm.host_id, cfg.n_hosts)
    vals = st.host_cm.at[hid].set(cm.vals.astype(jnp.float32),
                                  mode="drop")
    cpu_state, cpu_issue = CM.classify_cpu(vals)
    mem_state, mem_issue = CM.classify_mem(vals)
    last = st.cm_last_tick.at[hid].set(st.resp_win.tick, mode="drop")
    return st._replace(
        host_cm=vals, cm_cpu_state=cpu_state, cm_cpu_issue=cpu_issue,
        cm_mem_state=mem_state, cm_mem_issue=mem_issue,
        cm_last_tick=last)


def tick_5s(cfg: EngineCfg, st: AggState) -> AggState:
    """Close the 5s base slab on all windowed state."""
    return st._replace(
        resp_win=windows.tick(st.resp_win, cfg.levels),
        ctr_win=windows.tick(st.ctr_win, cfg.levels),
    )


# ------------------------------------------------------- health readback
# engine_health_vec layout: one f32 scalar per key, packed so the WHOLE
# device-health surface reads back in a single small transfer per report
# cadence (never per event). Reductions are sum over shards for counts
# (stacked (n,) leaves on a mesh) and max for the stage-pressure signal.
HEALTH_KEYS = (
    "svc_live", "svc_tomb", "svc_drop",
    "task_live", "task_tomb", "task_drop",
    "api_live", "api_tomb", "api_drop",
    "td_stage_max",
    "n_conn", "n_resp", "n_resp_unknown", "n_td_overflow",
    "dep_half_live", "dep_edge_live", "dep_edge_drop",
    "dep_paired", "dep_expired", "dep_dropped",
    # heavy-hitter tier: the top-K undercount bound (mass truncation
    # ever dropped — the per-key error bar every flow row reports),
    # invertible-bucket fill, and hot-admission lane count
    "topk_evicted", "hh_occupied", "hh_hot_lanes",
)


def engine_health_vec(cfg: EngineCfg, st: AggState, dep) -> jnp.ndarray:
    """Device-state health as ONE (len(HEALTH_KEYS),) f32 vector.

    The PSketch lesson (PAPERS.md): sketch/slab occupancy and eviction
    pressure are first-class monitored signals, and accelerator-side
    aggregation structures fail silently (probe exhaustion, stage
    saturation) unless their state is read back and exported. This is
    the batched readback: slab fills + tombstones + probe-failure drop
    counters for every keyed table, digest-stage pressure, dep-graph
    pair/edge fill and drop counters, and the device event counters —
    folded to scalars ON DEVICE so the host does one small transfer.
    Works on single-chip state (() scalars) and stacked sharded state
    ((n,) leaves) alike: ``sum`` reduces over shards, ``max`` keeps the
    worst shard's pressure.
    """
    s = lambda v: jnp.sum(v).astype(jnp.float32)       # noqa: E731
    vals = (
        s(st.tbl.n_live), s(st.tbl.n_tomb), s(st.tbl.n_drop),
        s(st.task_tbl.n_live), s(st.task_tbl.n_tomb),
        s(st.task_tbl.n_drop),
        s(st.api_tbl.n_live), s(st.api_tbl.n_tomb), s(st.api_tbl.n_drop),
        jnp.max(st.td_stage_n).astype(jnp.float32),
        s(st.n_conn), s(st.n_resp), s(st.n_resp_unknown),
        s(st.n_td_overflow),
        s(dep.half_tbl.n_live), s(dep.edge_tbl.n_live),
        s(dep.edge_tbl.n_drop),
        s(dep.n_paired), s(dep.n_expired), s(dep.n_dropped),
        s(st.flow_topk.evicted), s(st.inv.prio > 0), s(st.inv.n_hot),
    )
    return jnp.stack(vals)


def heavy_recover(cfg: EngineCfg, st: AggState) -> dict:
    """Per-tick heavy-hitter recovery: decode the invertible buckets
    (verify fingerprints + bucket positions, point-query the CMS for
    every candidate) and read the exact top-K lanes alongside — ONE
    read-only dispatch whose outputs are the whole recovery readback
    (the acceptance contract: recovery adds at most one readback per
    tick; the fold path itself never pays a single op for it)."""
    out = invertible.decode(st.inv, st.cms)
    k = cfg.topk_capacity
    t_hi, t_lo, t_counts = topk.query(st.flow_topk, k)
    # CMS estimate for the exact lanes too: truth ∈ [count, est], so
    # the merge reports est (never undercounts) with errbound est−count
    # — the exact lane's job is TIGHTENING the bound, and the window
    # shrinks the longer a key stays admitted
    t_est = countmin.query(st.cms, t_hi, t_lo).astype(jnp.float32)
    out.update({
        "topk_hi": t_hi, "topk_lo": t_lo, "topk_counts": t_counts,
        "topk_est": jnp.where(t_counts > 0, t_est, 0.0),
        "evicted": st.flow_topk.evicted,
        "total_mass": countmin.total(st.cms),
        "n_hot": st.inv.n_hot,
    })
    return out


def fold_step(cfg: EngineCfg, st: AggState, cb, rb) -> AggState:
    """The flagship fused step: one conn batch + one resp batch."""
    st = ingest_conn(cfg, st, cb)
    st = ingest_resp(cfg, st, rb)
    return st


def jit_fold_step(cfg: EngineCfg):
    """Compiled fold_step with state donation (in-place HBM update)."""
    return jax.jit(
        lambda st, cb, rb: fold_step(cfg, st, cb, rb), donate_argnums=(0,))


def fold_many(cfg: EngineCfg, st: AggState, cbs, rbs) -> AggState:
    """Fold K stacked microbatches in one flattened device dispatch.

    cbs/rbs leaves have leading axis K. The microbatch framing is a
    WIRE artifact (≤2048-conn messages, ``gy_comm_proto.h:1711``), not
    a compute boundary: every fold op is shape-generic and
    order-independent (scatter-add counters, scatter-max HLL registers,
    dup-safe table upsert), so the whole dispatch folds as ONE
    (K*B,)-lane batch — one table upsert instead of K, one top-K
    combine instead of K, no ``lax.scan`` sequencing at all. This is
    the TPU-first shape: maximal batch, minimal op count (vs the
    reference amortizing syscalls per 2048-element DB_WRITE_ARR,
    ``server/gy_mconnhdlr.h:350``).

    Response-side work (lookup + loghist + digest staging) is likewise
    one vectorized pass (``ingest_resp_bulk``); digest compression
    amortizes across dispatches via the persistent stage. The flush
    itself is NOT in this graph: the runtime watches ``stage_pressure``
    host-side and dispatches ``td_flush_partial`` when the stage runs
    out of headroom — an in-graph ``lax.cond`` here cost 110 ms per
    dispatch at 65k capacity (untaken!) from whole-buffer copies at the
    cond boundary.
    """
    flatc = jax.tree.map(lambda x: x.reshape((-1,) + x.shape[2:]), cbs)
    st = ingest_conn(cfg, st, flatc)
    return ingest_resp_bulk(cfg, st, rbs)


def jit_fold_many(cfg: EngineCfg):
    return jax.jit(
        lambda st, cbs, rbs: fold_many(cfg, st, cbs, rbs),
        donate_argnums=(0,))


# --------------------------------------------------------- fused megakernel
# Canonical sub-fold order inside fold_all — the SAME order the legacy
# per-subsystem dispatch sequence applies (decode.drain_chunks yields
# device kinds in this order, and the runtimes fold conn/resp slabs
# after the chunk loop), so a fused dispatch is bit-identical to the
# dispatch sequence it replaces (tests/test_fusedfold.py fuzzes this).
FOLD_ALL_ORDER = ("listener", "host", "task", "cpumem", "trace", "ping",
                  "delta", "connresp")


def fold_all(cfg: EngineCfg, st: AggState, dep, tick, *, listener=None,
             host=None, task=None, cpumem=None, trace=None, ping=None,
             delta=None, connresp=None):
    """The fused per-batch megakernel: every staged subsystem section +
    the conn/resp K-slab + the dependency-graph fold + the digest-stage
    pressure scalar, in ONE compiled dispatch with full state donation.

    Sections are Python-``None`` when absent, so each distinct presence
    combination traces its own lean variant (the hot feed path — conn/
    resp only — never pays a single op for listener/task/trace lanes;
    a 5s sweep batch compiles one "everything" variant). The runtimes
    key their jit cache on the presence tuple; in practice two or three
    variants exist per process.

    Replaces 6+ separate donated dispatches per feed batch (one per
    subsystem + ``_fold_many_dep`` + the ``stage_pressure`` readback
    dispatch) with one jit-call overhead and one host→device transfer,
    and returns the pressure scalar as a graph OUTPUT so the hot loop
    never issues a second dispatch just to observe it (the lagged
    host-side flush trigger reads a scalar that is already
    materialized).

    Returns ``(state, dep, pressure)``.
    """
    from gyeeta_tpu.parallel import depgraph as dg

    if listener is not None:
        st = ingest_listener(cfg, st, listener)
    if host is not None:
        st = ingest_host(cfg, st, host)
    if task is not None:
        st = ingest_task(cfg, st, task)
    if cpumem is not None:
        st = ingest_cpumem(cfg, st, cpumem)
    if trace is not None:
        st = ingest_trace(cfg, st, trace)
    if ping is not None:
        st = ping_tasks(cfg, st, ping)
    if delta is not None:
        st, dep = ingest_delta(cfg, st, dep, delta, tick)
    if connresp is not None:
        cbs, rbs = connresp
        st = fold_many(cfg, st, cbs, rbs)
        dep = dg.dep_fold_many(dep, cbs, tick)
    return st, dep, stage_pressure(st)
