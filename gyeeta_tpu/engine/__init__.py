"""Aggregation engine: device-resident entity tables + jitted sketch update.

TPU-native replacement for the madhava in-memory aggregation core
(``server/gy_mconnhdlr.cc`` L1/L2 loops + RCU entity tables): instead of
per-event pointer-chasing threads, the engine folds whole columnar
microbatches into per-entity sketch tensors with one jitted step.
"""

from gyeeta_tpu.engine import table  # noqa: F401
from gyeeta_tpu.engine import aggstate  # noqa: F401
from gyeeta_tpu.engine import step  # noqa: F401
