"""Whole-engine slab compaction: rebuild the service table, permute every
row-indexed column tensor, and reset non-additive per-row state.

The device analogue of an RCU grace-period sweep after deletions
(``common/gy_rcu_inc.h:487``; delete flow ``server/gy_mconnhdlr.cc:11195``):
runs entirely on device in one jitted call — no host round-trip, no pause
in ingest (call between microbatches).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from gyeeta_tpu.engine import table
from gyeeta_tpu.engine.aggstate import AggState, EngineCfg


def _rows_leading(st: AggState) -> dict:
    """Collect row-indexed arrays, moving the row axis to the front.

    Window rings are (nslots, S, ...) — moveaxis to (S, nslots, ...)."""
    cols = {
        "resp_cur": st.resp_win.cur,
        "resp_alltime": st.resp_win.alltime,
        "ctr_cur": st.ctr_win.cur,
        "ctr_alltime": st.ctr_win.alltime,
        "svc_hll": st.svc_hll.regs,
        "td_means": st.svc_td.means,
        "td_weights": st.svc_td.weights,
        "td_vmin": st.svc_td.vmin,
        "td_vmax": st.svc_td.vmax,
        "td_stage": st.td_stage,
        "td_stage_n": st.td_stage_n,
        "svc_stats": st.svc_stats,
        "qps_hist": st.qps_hist,
        "active_hist": st.active_hist,
        "svc_host": st.svc_host,
        "svc_state": st.svc_state,
        "svc_issue": st.svc_issue,
        "resp_hi_bits": st.resp_hi_bits,
    }
    for i, (ring, tot) in enumerate(zip(st.resp_win.rings,
                                        st.resp_win.totals)):
        cols[f"resp_ring{i}"] = jnp.moveaxis(ring, 0, 1)
        cols[f"resp_tot{i}"] = tot
    for i, (ring, tot) in enumerate(zip(st.ctr_win.rings,
                                        st.ctr_win.totals)):
        cols[f"ctr_ring{i}"] = jnp.moveaxis(ring, 0, 1)
        cols[f"ctr_tot{i}"] = tot
    return cols


@partial(jax.jit, static_argnums=(0,), donate_argnums=(1,))
def compact_state(cfg: EngineCfg, st: AggState) -> AggState:
    """Rebuild the slab without tombstones; all per-row state follows."""
    cols = _rows_leading(st)
    new_tbl, new_cols = table.compact(st.tbl, cols)
    live = table.live_mask(new_tbl)

    # non-additive resets for rows that are now empty
    new_cols["td_vmin"] = jnp.where(live, new_cols["td_vmin"], jnp.inf)
    new_cols["td_vmax"] = jnp.where(live, new_cols["td_vmax"], -jnp.inf)
    new_cols["svc_host"] = jnp.where(live, new_cols["svc_host"], -1)

    resp_rings = tuple(
        jnp.moveaxis(new_cols[f"resp_ring{i}"], 1, 0)
        for i in range(len(st.resp_win.rings)))
    ctr_rings = tuple(
        jnp.moveaxis(new_cols[f"ctr_ring{i}"], 1, 0)
        for i in range(len(st.ctr_win.rings)))
    return st._replace(
        tbl=new_tbl,
        resp_win=st.resp_win._replace(
            cur=new_cols["resp_cur"], alltime=new_cols["resp_alltime"],
            rings=resp_rings,
            totals=tuple(new_cols[f"resp_tot{i}"]
                         for i in range(len(st.resp_win.totals)))),
        ctr_win=st.ctr_win._replace(
            cur=new_cols["ctr_cur"], alltime=new_cols["ctr_alltime"],
            rings=ctr_rings,
            totals=tuple(new_cols[f"ctr_tot{i}"]
                         for i in range(len(st.ctr_win.totals)))),
        svc_hll=st.svc_hll._replace(regs=new_cols["svc_hll"]),
        svc_td=st.svc_td._replace(
            means=new_cols["td_means"], weights=new_cols["td_weights"],
            vmin=new_cols["td_vmin"], vmax=new_cols["td_vmax"]),
        td_stage=new_cols["td_stage"],
        td_stage_n=new_cols["td_stage_n"],
        svc_stats=new_cols["svc_stats"],
        qps_hist=new_cols["qps_hist"],
        active_hist=new_cols["active_hist"],
        svc_host=new_cols["svc_host"],
        svc_state=new_cols["svc_state"],
        svc_issue=new_cols["svc_issue"],
        resp_hi_bits=new_cols["resp_hi_bits"],
    )


def delete_services(cfg: EngineCfg, st: AggState, khi, klo):
    """Tombstone services + zero their gauges (LISTEN_FLAG_DELETE path).

    Sketch/window state is left for ``compact_state`` to sweep."""
    tbl, rows = table.delete(st.tbl, khi, klo)
    S = cfg.svc_capacity
    tgt = jnp.where(rows >= 0, rows, S)
    stats = st.svc_stats.at[tgt].set(0.0, mode="drop")
    state = st.svc_state.at[tgt].set(0, mode="drop")
    issue = st.svc_issue.at[tgt].set(0, mode="drop")
    return st._replace(tbl=tbl, svc_stats=stats, svc_state=state,
                       svc_issue=issue), rows
