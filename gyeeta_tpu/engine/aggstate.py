"""AggState: the full device-resident aggregation state as one pytree.

This is the TPU replacement for a madhava's in-memory model
(``server/gy_msocket.h`` MTCP_LISTENER/MAGGR_TASK rows + per-listener
histograms): one keyed entity slab for services, struct-of-arrays sketch
columns per service, global flow sketches, and a dense per-host stat panel.
A single jitted step (see ``engine/step.py``) folds whole columnar
microbatches into this state; queries are pure readbacks (``query/``).

Memory (defaults, f32): per-service loghist windows dominate —
(S=1024 rows × 256 buckets) × (1 cur + 12 + 24 ring slabs + 2 totals + 1
alltime) ≈ 40 MB. Scale S/buckets per deployment; HBM is the budget.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

from gyeeta_tpu.engine import table
from gyeeta_tpu.ingest import decode
from gyeeta_tpu.sketch import countmin, hyperloglog as hll, invertible, \
    loghist, tdigest, topk, windows

# conn-counter columns (windowed, per service)
CTR_BYTES_SENT = 0
CTR_BYTES_RCVD = 1
CTR_NCONN_CLOSED = 2
CTR_DUR_SUM_US = 3
NCTR = 4

# host panel columns (canonical order defined by the decode layer)
from gyeeta_tpu.ingest.decode import (  # noqa: E402,F401
    HOST_NTASKS, HOST_NTASKS_ISSUE, HOST_NTASKS_SEVERE, HOST_NLISTEN,
    HOST_NLISTEN_ISSUE, HOST_NLISTEN_SEVERE, HOST_CPU_ISSUE, HOST_MEM_ISSUE,
    HOST_SEVERE_CPU, HOST_SEVERE_MEM, HOST_STATE, NHOSTCOL,
)


class EngineCfg(NamedTuple):
    """Static engine geometry (all sizes are compile-time constants)."""
    svc_capacity: int = 1024          # service slab rows (power of two)
    n_hosts: int = 64                 # dense host panel rows
    resp_spec: loghist.LogHistSpec = loghist.LogHistSpec(
        vmin=1.0, vmax=1e8, nbuckets=256)   # usec: 1us..100s, <2% error
    # learned per-svc baselines (ref: qps_hist_/active_conn_hist_,
    # common/gy_socket_stat.h:365): QPS 1..1M, active conns 1..100k
    qps_spec: loghist.LogHistSpec = loghist.LogHistSpec(
        vmin=1.0, vmax=1e6, nbuckets=64)
    active_spec: loghist.LogHistSpec = loghist.LogHistSpec(
        vmin=1.0, vmax=1e5, nbuckets=32)
    levels: tuple = windows.LEVELS_DEFAULT
    task_capacity: int = 2048         # process-group slab rows (power of 2)
    api_capacity: int = 4096          # (svc, api) trace slab rows (pow 2)
    # per-API response-time loghist (north-star config #5): 1us..100s,
    # 128 γ-buckets → ~±7% quantile error
    apiresp_spec: loghist.LogHistSpec = loghist.LogHistSpec(
        vmin=1.0, vmax=1e8, nbuckets=128)
    # learned per-group CPU%% baseline (ref AGGR_TASK_HIST_STATS cpu pct
    # histogram, gy_comm_proto.h:2966): 0.1%..10k% (100 cores)
    taskcpu_spec: loghist.LogHistSpec = loghist.LogHistSpec(
        vmin=0.1, vmax=1e4, nbuckets=32)
    hll_p_svc: int = 10               # per-svc distinct clients (±3.2%)
    hll_p_global: int = 14            # global distinct endpoints (±0.8%)
    cms_depth: int = 2                # fold cost is depth-linear (one
    #                                   scatter lane per row per event —
    #                                   the 2nd-largest fold op); depth 2
    #                                   at DOUBLE width spends the same
    #                                   memory on halved per-row
    #                                   collision rates. Estimates stay
    #                                   strict upper bounds (the top-K
    #                                   candidate filter depends on
    #                                   that); the weaker tail bound
    #                                   (err ≤ e·N/width w.p. 1-e⁻²) is
    #                                   a documented CPU-geometry
    #                                   tradeoff — raise GYT_CMS_DEPTH
    #                                   back on accelerators with
    #                                   scatter headroom (OPERATIONS.md
    #                                   "Fold-path tuning")
    cms_width: int = 1 << 17
    topk_capacity: int = 512
    topk_budget: int = 2048           # sketch-assisted top-K candidate
    #                                   compaction: only the budget
    #                                   highest-CMS-estimate lanes of a
    #                                   fold dispatch enter the O(n
    #                                   log n) grouping sort (the
    #                                   dominant fold op at slab width;
    #                                   33k→2.6k lanes ≈ 11.6→2 ms per
    #                                   dispatch on one core; 4x the
    #                                   top-K capacity). 0 = every
    #                                   lane (exact truncation). Mass
    #                                   excluded by the budget is
    #                                   accounted in ``evicted`` —
    #                                   see sketch/topk.py:update
    hh_depth: int = 2                 # invertible heavy-hitter tier
    #                                   (sketch/invertible.py): rows of
    #                                   candidate buckets; a heavy key
    #                                   is missed only if it loses its
    #                                   bucket argmax in EVERY row
    hh_width: int = 4096              # buckets per row; d·w candidate
    #                                   slots ≈ 8k (160 KB of state, a
    #                                   ~128 KB readback per tick). 0
    #                                   disables the tier entirely.
    hh_hot_frac: float = 1e-5         # PSketch hot-admission floor: a
    #                                   lane enters the exact top-K
    #                                   merge only when its CMS
    #                                   estimate ≥ hh_hot_frac × total
    #                                   folded mass (on TOP of the
    #                                   topk_budget relative ranking);
    #                                   colder lanes stay in the
    #                                   invertible array + CMS, their
    #                                   mass lands in ``evicted``. 0
    #                                   disables the absolute floor
    #                                   (budget-only admission).
    td_capacity: int = 64             # per-svc t-digest centroids
    # staged-digest buffer: samples accumulate here across a fold_many
    # dispatch (K microbatches) and compress ONCE at its end — the
    # vmapped compression sort is ~80% of the naive fold cost
    td_stage_cap: int = 512           # per-svc staged samples (flush at
    #                                   half-full: size ≥4× the expected
    #                                   per-svc fill per dispatch)
    td_sample_stride: int = 16        # digest duty-cycle: stage 1-in-N
    #                                   resp samples. The loghist folds
    #                                   EVERY sample and stays the
    #                                   lossless estimator behind the
    #                                   windowed resp_p* columns; the
    #                                   digest is the ALL-TIME tail
    #                                   refinement (td_p*), where the
    #                                   duty cycle only slows
    #                                   convergence (samples accumulate
    #                                   unboundedly). Its staging sort +
    #                                   flush compression scale ~1/N:
    #                                   16 vs the old 2 is ~45% of the
    #                                   whole toy fold cost (r07). The
    #                                   reference samples resp events
    #                                   ~50% at the SOURCE (gy_ebpf.h:29)
    #                                   — here the full stream still
    #                                   reaches the loghist. GYT_TD_
    #                                   SAMPLE_STRIDE tunes it; see
    #                                   OPERATIONS.md "Fold-path tuning"
    td_flush_m: int = 256             # entities compressed per partial
    #                                   flush — flush cost is O(m), not
    #                                   O(capacity); the runtime drains
    #                                   iteratively under pressure.
    #                                   Small m beats m≈S under skewed
    #                                   load: pressure is driven by the
    #                                   few HOT stages, and sorting the
    #                                   mostly-empty rest was ~2/3 of
    #                                   the flush cost (107→27 ms per
    #                                   flush on the toy geometry, r07)
    conn_batch: int = 2048            # static microbatch lanes
    resp_batch: int = 4096
    listener_batch: int = 512
    fold_k: int = 16                  # microbatches per fold_many dispatch


class AggState(NamedTuple):
    tbl: table.Table                  # service key slab (glob_id → row)
    resp_win: windows.MultiWindow     # (S, B) resp-time loghist, windowed
    ctr_win: windows.MultiWindow      # (S, NCTR) conn counters, windowed
    svc_hll: hll.HLL                  # (S, m) distinct client endpoints
    svc_td: tdigest.TDigest           # (S, C) per-svc resp digest
    td_stage: jnp.ndarray             # (S, cap) staged raw samples
    td_stage_n: jnp.ndarray           # (S,) int32 staged fill counts
    svc_stats: jnp.ndarray            # (S, NSTAT) last listener-state gauges
    qps_hist: jnp.ndarray             # (S, Bq) learned QPS baseline hist
    active_hist: jnp.ndarray          # (S, Ba) learned active-conn baseline
    svc_host: jnp.ndarray             # (S,) int32 owning host id (-1 unset)
    svc_state: jnp.ndarray            # (S,) int32 semantic.STATE_*
    svc_issue: jnp.ndarray            # (S,) int32 semantic.ISSUE_*
    resp_hi_bits: jnp.ndarray         # (S,) int32 8-tick high-resp history
    #                                   (ref high_resp_bit_hist_,
    #                                    gy_comm_proto.h:2212)
    host_panel: jnp.ndarray           # (H, NHOSTCOL) last host state
    host_last_tick: jnp.ndarray       # (H,) int32 tick of last host report
    #                                   (-1 = never; staleness → Down)
    # --- 2s cpu/mem path (ref CPU_MEM_STATE_NOTIFY gy_comm_proto.h:2024,
    #     classified server-side by semantic/cpumem.py) ---
    host_cm: jnp.ndarray              # (H, NCM) last raw 2s gauges
    cm_cpu_state: jnp.ndarray         # (H,) int32 STATE_*
    cm_cpu_issue: jnp.ndarray         # (H,) int32 CISSUE_*
    cm_mem_state: jnp.ndarray         # (H,) int32 STATE_*
    cm_mem_issue: jnp.ndarray         # (H,) int32 MISSUE_*
    cm_last_tick: jnp.ndarray         # (H,) int32
    # --- task tier (process groups, ref MAGGR_TASK server/gy_msocket.h) ---
    task_tbl: table.Table             # aggr_task_id → row
    task_stats: jnp.ndarray           # (T, NTASKSTAT) last 5s sweep gauges
    task_state: jnp.ndarray           # (T,) int32 agent-classified state
    task_issue: jnp.ndarray           # (T,) int32 issue source
    task_host: jnp.ndarray            # (T,) int32 owning host (-1 unset)
    task_comm_hi: jnp.ndarray         # (T,) interned comm id halves
    task_comm_lo: jnp.ndarray
    task_rel_hi: jnp.ndarray          # (T,) related listener id halves
    task_rel_lo: jnp.ndarray
    task_cpu_hist: jnp.ndarray        # (T, Bc) learned CPU%% baseline
    task_last_tick: jnp.ndarray       # (T,) int32 tick of last sweep
    # --- request-trace tier (per-(svc, api) aggregates, ref
    #     REQ_TRACE_TRAN fan-in gy_comm_proto.h:3288) ---
    api_tbl: table.Table              # mix(svc, api) → row
    api_svc_hi: jnp.ndarray           # (A,) service glob id halves
    api_svc_lo: jnp.ndarray
    api_id_hi: jnp.ndarray            # (A,) interned api signature halves
    api_id_lo: jnp.ndarray
    api_proto: jnp.ndarray            # (A,) int32 trace.PROTO_*
    api_resp_hist: jnp.ndarray        # (A, Ba) response-time loghist
    api_ctr: jnp.ndarray              # (A, 4) nreq/nerr/bytes_in/bytes_out
    api_host: jnp.ndarray             # (A,) int32 last reporting host
    api_last_tick: jnp.ndarray        # (A,) int32
    glob_hll: hll.HLL                 # distinct flow endpoints global
    cms: countmin.CMS                 # flow-key → bytes
    flow_topk: topk.TopK              # heavy-hitter flows by bytes
    inv: invertible.InvSketch         # invertible candidate buckets —
    #                                   per-tick key recovery decodes
    #                                   heavy keys straight from here
    n_conn: jnp.ndarray               # () f32 counters
    n_resp: jnp.ndarray
    n_td_overflow: jnp.ndarray        # samples that missed the digest path
    n_resp_unknown: jnp.ndarray       # resp samples for unannounced svcs


def init(cfg: EngineCfg) -> AggState:
    S = cfg.svc_capacity
    B = cfg.resp_spec.nbuckets
    return AggState(
        tbl=table.init(S),
        resp_win=windows.init((S, B), cfg.levels),
        ctr_win=windows.init((S, NCTR), cfg.levels),
        svc_hll=hll.init(p=cfg.hll_p_svc, entities=(S,)),
        svc_td=tdigest.init(capacity=cfg.td_capacity, entities=(S,)),
        td_stage=jnp.zeros((S, cfg.td_stage_cap), jnp.float32),
        td_stage_n=jnp.zeros((S,), jnp.int32),
        svc_stats=jnp.zeros((S, decode.NSTAT), jnp.float32),
        qps_hist=jnp.zeros((S, cfg.qps_spec.nbuckets), jnp.float32),
        active_hist=jnp.zeros((S, cfg.active_spec.nbuckets), jnp.float32),
        svc_host=jnp.full((S,), -1, jnp.int32),
        svc_state=jnp.zeros((S,), jnp.int32),
        svc_issue=jnp.zeros((S,), jnp.int32),
        resp_hi_bits=jnp.zeros((S,), jnp.int32),
        host_panel=jnp.zeros((cfg.n_hosts, NHOSTCOL), jnp.float32),
        host_last_tick=jnp.full((cfg.n_hosts,), -1, jnp.int32),
        host_cm=jnp.zeros((cfg.n_hosts, decode.NCM), jnp.float32),
        cm_cpu_state=jnp.zeros((cfg.n_hosts,), jnp.int32),
        cm_cpu_issue=jnp.zeros((cfg.n_hosts,), jnp.int32),
        cm_mem_state=jnp.zeros((cfg.n_hosts,), jnp.int32),
        cm_mem_issue=jnp.zeros((cfg.n_hosts,), jnp.int32),
        cm_last_tick=jnp.full((cfg.n_hosts,), -1, jnp.int32),
        task_tbl=table.init(cfg.task_capacity),
        task_stats=jnp.zeros((cfg.task_capacity, decode.NTASKSTAT),
                             jnp.float32),
        task_state=jnp.zeros((cfg.task_capacity,), jnp.int32),
        task_issue=jnp.zeros((cfg.task_capacity,), jnp.int32),
        task_host=jnp.full((cfg.task_capacity,), -1, jnp.int32),
        task_comm_hi=jnp.zeros((cfg.task_capacity,), jnp.uint32),
        task_comm_lo=jnp.zeros((cfg.task_capacity,), jnp.uint32),
        task_rel_hi=jnp.zeros((cfg.task_capacity,), jnp.uint32),
        task_rel_lo=jnp.zeros((cfg.task_capacity,), jnp.uint32),
        task_cpu_hist=jnp.zeros(
            (cfg.task_capacity, cfg.taskcpu_spec.nbuckets), jnp.float32),
        task_last_tick=jnp.full((cfg.task_capacity,), -1, jnp.int32),
        api_tbl=table.init(cfg.api_capacity),
        api_svc_hi=jnp.zeros((cfg.api_capacity,), jnp.uint32),
        api_svc_lo=jnp.zeros((cfg.api_capacity,), jnp.uint32),
        api_id_hi=jnp.zeros((cfg.api_capacity,), jnp.uint32),
        api_id_lo=jnp.zeros((cfg.api_capacity,), jnp.uint32),
        api_proto=jnp.zeros((cfg.api_capacity,), jnp.int32),
        api_resp_hist=jnp.zeros(
            (cfg.api_capacity, cfg.apiresp_spec.nbuckets), jnp.float32),
        api_ctr=jnp.zeros((cfg.api_capacity, 4), jnp.float32),
        api_host=jnp.full((cfg.api_capacity,), -1, jnp.int32),
        api_last_tick=jnp.full((cfg.api_capacity,), -1, jnp.int32),
        glob_hll=hll.init(p=cfg.hll_p_global),
        cms=countmin.init(cfg.cms_depth, cfg.cms_width),
        flow_topk=topk.init(cfg.topk_capacity),
        inv=invertible.init(cfg.hh_depth, max(cfg.hh_width, 1)),
        n_conn=jnp.zeros((), jnp.float32),
        n_resp=jnp.zeros((), jnp.float32),
        n_td_overflow=jnp.zeros((), jnp.float32),
        n_resp_unknown=jnp.zeros((), jnp.float32),
    )
