"""Device-resident keyed entity table (the RCU-hash-table replacement).

The reference keeps every keyed entity (listener by ``glob_id_``, task by
``aggr_task_id_``, conn by tuple hash) in liburcu lock-free hash tables
(``common/gy_rcu_inc.h:1664`` ``RCU_HASH_TABLE``), mutated one pointer at a
time by many threads. On TPU the equivalent is a fixed-capacity open-addressing
hash slab living in HBM:

- keys are 64-bit ids carried as ``(hi, lo)`` uint32 pairs (TPUs have no
  useful 64-bit integer path),
- lookup/insert is a *batched* vectorized probe: every lane of a microbatch
  resolves its row in ``PROBES`` unrolled gather/scatter rounds,
- per-entity state lives in separate ``(capacity, ...)`` column tensors
  indexed by the returned row ids (struct-of-arrays),
- delete writes a tombstone key; ``compact`` rebuilds the slab and permutes
  the state columns (the analogue of RCU grace-period reclamation
  (``gy_rcu_inc.h:487``) without any host round-trip).

Intra-batch insert races (two lanes claiming the same empty slot) are resolved
deterministically with a scatter-min "winner lane" pass, so the same batch
always produces the same table — a property the threaded original cannot give.

Everything is fixed-shape and branch-free → jits, shards (each mesh shard owns
an independent slab), and runs entirely on the VPU.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from gyeeta_tpu.utils import hashing as H

# Key sentinels. Real ids of ~0 are astronomically unlikely (ids are hashes);
# colliding with one merely loses that id, never corrupts others.
EMPTY = np.uint32(0xFFFFFFFF)
TOMB = np.uint32(0xFFFFFFFE)

PROBES = 16  # unrolled double-hash probe rounds
# Load guidance: a key whose probe positions are ALL occupied can never
# insert — it drops on every retry and permanently defeats the
# ``upsert_fast`` all-hit fast path (one such key forces the 16-round
# insert machinery on every dispatch). The permanent-failure odds are
# ~load^PROBES per key: at 8 probes, 0.5^8 ≈ 0.4% of keys at 50% load
# (observed in the bench: a stuck key cost ~2.5ms/µbatch forever);
# at 16 probes it is 0.0015% at 50% and 0.3% at 70%. The lookup cost
# is one (B, PROBES) gather — doubling probes costs ~1.5% of the fold,
# the cheapest insurance available. Size slabs for ≤70% steady-state
# occupancy; drops are counted in ``n_drop`` and re-sent keys retry
# next sweep.


class Table(NamedTuple):
    key_hi: jnp.ndarray   # (S,) uint32
    key_lo: jnp.ndarray   # (S,) uint32
    n_live: jnp.ndarray   # () int32 — live keys
    n_tomb: jnp.ndarray   # () int32 — tombstones awaiting compaction
    n_drop: jnp.ndarray   # () int32 — inserts dropped (probe exhaustion)


def init(capacity: int) -> Table:
    assert capacity & (capacity - 1) == 0, "capacity must be a power of two"
    return Table(
        key_hi=jnp.full((capacity,), EMPTY, jnp.uint32),
        key_lo=jnp.full((capacity,), EMPTY, jnp.uint32),
        n_live=jnp.zeros((), jnp.int32),
        n_tomb=jnp.zeros((), jnp.int32),
        n_drop=jnp.zeros((), jnp.int32),
    )


def _probe_slots(khi, klo, capacity: int):
    """(B, PROBES) candidate slots via double hashing (odd step)."""
    h1 = H.mix64(khi, klo, 0x7AB1E5)
    h2 = H.mix64(khi, klo, 0x57E9) | jnp.uint32(1)
    p = jnp.arange(PROBES, dtype=jnp.uint32)
    slots = (h1[:, None] + p[None, :] * h2[:, None]) & jnp.uint32(capacity - 1)
    return slots.astype(jnp.int32)


def _is_empty(hi, lo):
    return (hi == EMPTY) & (lo == EMPTY)


def _is_tomb(hi, lo):
    return (hi == TOMB) & (lo == TOMB)


def upsert(tbl: Table, khi, klo, valid=None):
    """Resolve (or insert) a batch of keys → (new_table, rows).

    rows: (B,) int32 — slab row per lane, or -1 for invalid lanes and for
    inserts dropped after probe exhaustion (counted in ``n_drop``).
    """
    capacity = tbl.key_hi.shape[0]
    khi = khi.astype(jnp.uint32)
    klo = klo.astype(jnp.uint32)
    B = khi.shape[0]
    if valid is None:
        valid = jnp.ones((B,), bool)
    # never insert sentinel-valued keys
    valid = valid & ~_is_empty(khi, klo) & ~_is_tomb(khi, klo)
    lane = jnp.arange(B, dtype=jnp.int32)
    slots = _probe_slots(khi, klo, capacity)            # (B, P)
    rows = jnp.full((B,), -1, jnp.int32)
    key_hi, key_lo = tbl.key_hi, tbl.key_lo
    inserted = jnp.zeros((), jnp.int32)

    def match_rows(key_hi, key_lo, rows):
        cur_hi = key_hi[slots]
        cur_lo = key_lo[slots]
        m = (cur_hi == khi[:, None]) & (cur_lo == klo[:, None])   # (B, P)
        pos = jnp.argmax(m, axis=1)
        found = jnp.any(m, axis=1) & valid
        mrow = slots[lane, pos]
        return jnp.where((rows < 0) & found, mrow, rows)

    for _ in range(PROBES):
        rows = match_rows(key_hi, key_lo, rows)
        unresolved = valid & (rows < 0)
        cur_hi = key_hi[slots]
        cur_lo = key_lo[slots]
        claimable = _is_empty(cur_hi, cur_lo) | _is_tomb(cur_hi, cur_lo)
        has_claim = jnp.any(claimable, axis=1)
        pos = jnp.argmax(claimable, axis=1)
        target = slots[lane, pos]
        want = unresolved & has_claim
        # deterministic winner per contested slot: lowest lane index
        winner = jnp.full((capacity,), B, jnp.int32)
        winner = winner.at[jnp.where(want, target, capacity)].min(
            lane, mode="drop")
        win = want & (winner[target] == lane)
        wtarget = jnp.where(win, target, capacity)
        was_tomb = _is_tomb(key_hi[target], key_lo[target])
        key_hi = key_hi.at[wtarget].set(khi, mode="drop")
        key_lo = key_lo.at[wtarget].set(klo, mode="drop")
        rows = jnp.where(win, target, rows)
        inserted = inserted + jnp.sum(win).astype(jnp.int32)
        tomb_reclaimed = jnp.sum(win & was_tomb).astype(jnp.int32)
        tbl = tbl._replace(n_tomb=tbl.n_tomb - tomb_reclaimed)
    # duplicates of a round-(P-1) winner resolve in this final pass
    rows = match_rows(key_hi, key_lo, rows)
    dropped = jnp.sum(valid & (rows < 0)).astype(jnp.int32)
    new_tbl = Table(
        key_hi=key_hi,
        key_lo=key_lo,
        n_live=tbl.n_live + inserted,
        n_tomb=tbl.n_tomb,
        n_drop=tbl.n_drop + dropped,
    )
    return new_tbl, rows


def upsert_fast(tbl: Table, khi, klo, valid=None):
    """Upsert that skips the insert machinery when every key already
    resolves — the steady state of the ingest hot loop (service keys
    are long-lived; inserts happen at announce/churn rate, not event
    rate). One probe-match pass decides; ``lax.cond`` executes only the
    taken branch on TPU, so the PROBES unrolled claim rounds (gather +
    scatter-min winner election per round) cost nothing once the
    working set is resident — the moral equivalent of the reference's
    RCU read-mostly fast path vs its insert slow path
    (``gy_rcu_inc.h:1664``)."""
    khi = khi.astype(jnp.uint32)
    klo = klo.astype(jnp.uint32)
    if valid is None:
        valid = jnp.ones((khi.shape[0],), bool)
    tbl, rows, _ = upsert_fast2(tbl, khi, klo, valid)
    return tbl, rows


def upsert_fast2(tbl: Table, khi, klo, valid=None):
    """:func:`upsert_fast` that also returns the ``any_miss`` () bool —
    True when this batch carried at least one key that was not already
    resolvable (i.e. the insert machinery ran). Callers use it to
    cond-skip work that only matters for NEW rows (e.g. the dep-graph
    edge identity columns, which existing rows already hold)."""
    khi = khi.astype(jnp.uint32)
    klo = klo.astype(jnp.uint32)
    if valid is None:
        valid = jnp.ones((khi.shape[0],), bool)
    rows0 = lookup(tbl, khi, klo, valid)
    any_miss = jnp.any(valid & (rows0 < 0)
                       & ~_is_empty(khi, klo) & ~_is_tomb(khi, klo))
    tbl, rows = jax.lax.cond(
        any_miss,
        lambda t: upsert(t, khi, klo, valid),
        lambda t: (t, rows0),
        tbl)
    return tbl, rows, any_miss


def lookup(tbl: Table, khi, klo, valid=None):
    """Find rows for a batch of keys without inserting. -1 = absent.

    The two (B, PROBES) key-half gathers share one index array, so XLA
    fuses them into a single gather loop — a measured attempt to halve
    them via a derived-fingerprint probe (one fp gather + per-lane
    verify) was NOT faster on CPU and cost an extra ~2.5 ms per 65k
    lanes in verify/cond overhead. Don't re-split this."""
    capacity = tbl.key_hi.shape[0]
    khi = khi.astype(jnp.uint32)
    klo = klo.astype(jnp.uint32)
    B = khi.shape[0]
    if valid is None:
        valid = jnp.ones((B,), bool)
    slots = _probe_slots(khi, klo, capacity)
    cur_hi = tbl.key_hi[slots]
    cur_lo = tbl.key_lo[slots]
    m = (cur_hi == khi[:, None]) & (cur_lo == klo[:, None])
    pos = jnp.argmax(m, axis=1)
    found = jnp.any(m, axis=1) & valid
    rows = slots[jnp.arange(B), pos]
    return jnp.where(found, rows, -1)


def delete(tbl: Table, khi, klo, valid=None):
    """Tombstone a batch of keys → (new_table, rows_deleted).

    Callers must clear state columns at the returned rows (>=0). The row
    stays unusable until ``compact`` or until an insert reclaims the
    tombstone.
    """
    capacity = tbl.key_hi.shape[0]
    rows = lookup(tbl, khi, klo, valid)
    tgt = jnp.where(rows >= 0, rows, capacity)
    key_hi = tbl.key_hi.at[tgt].set(TOMB, mode="drop")
    key_lo = tbl.key_lo.at[tgt].set(TOMB, mode="drop")
    # count distinct rows: duplicate lanes of one key must not double-count
    hit = jnp.zeros((capacity + 1,), bool).at[tgt].set(True)
    ndel = jnp.sum(hit[:capacity]).astype(jnp.int32)
    return Table(
        key_hi=key_hi,
        key_lo=key_lo,
        n_live=tbl.n_live - ndel,
        n_tomb=tbl.n_tomb + ndel,
        n_drop=tbl.n_drop,
    ), rows


def live_mask(tbl: Table):
    return ~_is_empty(tbl.key_hi, tbl.key_lo) & \
        ~_is_tomb(tbl.key_hi, tbl.key_lo)


def tombstone_rows(tbl: Table, row_mask):
    """Tombstone every live row where ``row_mask`` is True.

    The batched ageing primitive (the reference evicts idle entities via
    per-entry timestamps walked by scheduler jobs, e.g. MAGGR_TASK
    ageing): callers build the mask from a last-seen-tick column. Returns
    (new_table, killed_mask); state columns at killed rows should be
    zeroed by the caller (or left — compact zeroes them)."""
    kill = live_mask(tbl) & row_mask
    n = jnp.sum(kill).astype(jnp.int32)
    return tbl._replace(
        key_hi=jnp.where(kill, TOMB, tbl.key_hi),
        key_lo=jnp.where(kill, TOMB, tbl.key_lo),
        n_live=tbl.n_live - n,
        n_tomb=tbl.n_tomb + n,
    ), kill


def compact(tbl: Table, state_cols):
    """Reclaim tombstones and zero dead state columns — in place.

    In this probe design a tombstone is *operationally identical* to an
    empty slot: ``match_rows``/``lookup`` scan all probe positions with
    no early termination, and inserts claim either. So compaction never
    needs to relocate keys — it reclassifies TOMB → EMPTY and zeroes the
    dead rows' state, O(S) with zero insert failures. (An earlier rebuild
    that re-upserted every key into a fresh slab dropped ~1.7% of live
    entities at 77% load when probe chains exhausted — the scale test
    caught it; in-place reclamation cannot lose rows. Rows also keep
    their ids across compaction.) The analogue of an RCU grace-period
    sweep (``gy_rcu_inc.h:487``), minus the relocation the pointer world
    requires.

    state_cols: pytree of ``(S, ...)`` arrays indexed by row. Returns
    (new_table, new_state_cols). Runs fully on device (jit-able).
    """
    tomb = _is_tomb(tbl.key_hi, tbl.key_lo)
    live = live_mask(tbl)
    new_tbl = Table(
        key_hi=jnp.where(tomb, EMPTY, tbl.key_hi),
        key_lo=jnp.where(tomb, EMPTY, tbl.key_lo),
        n_live=tbl.n_live,
        n_tomb=jnp.zeros((), jnp.int32),
        n_drop=tbl.n_drop,
    )

    def zero_dead(col):
        keep = live.reshape((-1,) + (1,) * (col.ndim - 1))
        return jnp.where(keep, col, jnp.zeros_like(col))

    return new_tbl, jax.tree_util.tree_map(zero_dead, state_cols)
