"""Mesh/collective tier: the madhava→shyama aggregation tree as SPMD.

The reference scales by a server tree (≤512 agents per madhava, ≤1024
madhavas per shyama) connected by TCP RPCs. Here the same roles map onto a
``jax.sharding.Mesh``:

- hosts are data-parallel: each mesh shard owns the full engine state for
  its slice of the host-id space (``mesh.py``, ``sharded.py``),
- the shyama roll-up (``server/gy_shconnhdlr.cc:4583`` cluster aggregation)
  is ``psum``/``pmax`` of sketch tensors over the mesh axis (``rollup.py``),
- global conn pairing (``server/gy_shconnhdlr.h:1136`` glob_tcp_conn_tbl_)
  is an ``all_to_all`` reshard of conn halves to their flow-key owner shard
  plus a device pair table (``pairing.py``).
"""

from gyeeta_tpu.parallel.mesh import HOST_AXIS, make_mesh, shard_of_host
from gyeeta_tpu.parallel import sharded, rollup, pairing, depgraph, \
    partition

__all__ = ["HOST_AXIS", "make_mesh", "shard_of_host", "sharded", "rollup",
           "pairing", "depgraph", "partition", "ShardedRuntime"]


def __getattr__(name):
    # lazy: shardedrt pulls in the query/alerts tiers; keep base imports light
    if name == "ShardedRuntime":
        from gyeeta_tpu.parallel.shardedrt import ShardedRuntime
        return ShardedRuntime
    raise AttributeError(name)
