"""Service dependency graph: paired flows → svc→svc edge slab → clusters.

This is the product feature the pairing collective exists for. The
reference builds it in three stages: madhava records per-listener
``DEPENDS_LISTENER`` maps from locally-resolved conns
(``common/gy_socket_stat.h:721``), shyama pairs the cross-madhava halves in
``glob_tcp_conn_tbl_`` and notifies both sides
(``server/gy_shconnhdlr.cc:3790-3854``), and a periodic job coalesces
listeners that talk to each other into service-mesh clusters
(``coalesce_svc_mesh_clusters``, ``server/gy_shconnhdlr.cc:5198``).

TPU-native redesign — three fixed-shape device structures per shard:

- **half table**: flow-key-addressed slab holding unpaired conn halves
  *with payloads* (client entity id, server glob id, bytes). Halves arrive
  pre-routed to the flow-owner shard by the ``lax.all_to_all`` capacity
  dispatch (``pairing._dispatch``); a row whose both halves have landed is
  *drained the same step*: its edge is folded and the row tombstoned, so
  the table holds only in-flight halves (the reference's unresolved-conn
  cap, ``server/gy_mconnhdlr.h:94``, becomes the slab capacity + TTL).
- **edge slab**: (cli_entity, ser_listener)-keyed table accumulating
  nconn/bytes per dependency edge. The client entity is the caller's
  related-listener id when it has one (svc→svc edge — the mesh), else its
  process-group id (task→svc edge). Conn records that already carry both
  sides (local / same-agent flows, the non-shyama path of the reference)
  fold straight into the edge slab and skip pairing.
- **cluster labels**: vectorized min-label propagation over the svc→svc
  edges — the coalesce pass as a fixed-iteration jitted loop instead of
  shyama's pointer-chasing set merge. Runs on the merged (rolled-up) edge
  set, so every shard computes the same clusters ("every shard is shyama").

Shard-merge of edge slabs is an ``all_gather`` + re-upsert (edges for one
(cli,ser) key may accumulate on several shards; counts are additive).
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from gyeeta_tpu.engine import table
from gyeeta_tpu.parallel.mesh import HOST_AXIS
from gyeeta_tpu.parallel.pairing import owner_shard
from gyeeta_tpu.utils import hashing as H

_EDGE_SALT = 0x5E1FD0


class DepGraph(NamedTuple):
    # ---- unpaired halves, keyed by flow key (per-shard slice) ----
    half_tbl: table.Table
    h_cli_hi: jnp.ndarray    # (P,) client entity id (payload of cli half)
    h_cli_lo: jnp.ndarray
    h_cli_svc: jnp.ndarray   # (P,) bool — client entity is a listener
    h_ser_hi: jnp.ndarray    # (P,) server glob id (payload of ser half)
    h_ser_lo: jnp.ndarray
    h_bytes: jnp.ndarray     # (P,) f32 — flow bytes (max of the two halves)
    h_cli_seen: jnp.ndarray  # (P,) bool
    h_ser_seen: jnp.ndarray  # (P,) bool
    h_last_tick: jnp.ndarray  # (P,) i32 — for TTL eviction
    # ---- dependency edges, keyed by mix(cli, ser) ----
    edge_tbl: table.Table
    e_cli_hi: jnp.ndarray    # (E,) endpoint ids (actual, not the hash key)
    e_cli_lo: jnp.ndarray
    e_cli_svc: jnp.ndarray   # (E,) bool — svc→svc edge (mesh member)
    e_ser_hi: jnp.ndarray
    e_ser_lo: jnp.ndarray
    e_ctr: jnp.ndarray       # (E, 2) f32 — [:, 0] nconn (flows folded
    #                           into this edge), [:, 1] bytes. ONE
    #                           column block so the per-dispatch
    #                           accumulate is ONE row scatter-add (two
    #                           per-column scatters pay the 32k-lane
    #                           index resolution twice — the ctr_win
    #                           lesson, engine/step.py:ingest_conn)
    e_last_tick: jnp.ndarray  # (E,) i32
    # ---- counters ----
    n_paired: jnp.ndarray    # () f32 — halves joined into an edge
    n_expired: jnp.ndarray   # () f32 — halves evicted unpaired (TTL)
    n_dropped: jnp.ndarray   # () f32 — dispatch/table overflow drops

    @property
    def e_nconn(self):
        """(E,) flows-per-edge view of ``e_ctr`` (read path)."""
        return self.e_ctr[:, 0]

    @property
    def e_bytes(self):
        """(E,) bytes-per-edge view of ``e_ctr`` (read path)."""
        return self.e_ctr[:, 1]


def init(pair_capacity: int = 4096, edge_capacity: int = 2048) -> DepGraph:
    Pc, E = pair_capacity, edge_capacity
    z32 = lambda n: jnp.zeros((n,), jnp.uint32)        # noqa: E731
    return DepGraph(
        half_tbl=table.init(Pc),
        h_cli_hi=z32(Pc), h_cli_lo=z32(Pc),
        h_cli_svc=jnp.zeros((Pc,), bool),
        h_ser_hi=z32(Pc), h_ser_lo=z32(Pc),
        h_bytes=jnp.zeros((Pc,), jnp.float32),
        h_cli_seen=jnp.zeros((Pc,), bool),
        h_ser_seen=jnp.zeros((Pc,), bool),
        h_last_tick=jnp.full((Pc,), -1, jnp.int32),
        edge_tbl=table.init(E),
        e_cli_hi=z32(E), e_cli_lo=z32(E),
        e_cli_svc=jnp.zeros((E,), bool),
        e_ser_hi=z32(E), e_ser_lo=z32(E),
        e_ctr=jnp.zeros((E, 2), jnp.float32),
        e_last_tick=jnp.full((E,), -1, jnp.int32),
        n_paired=jnp.zeros((), jnp.float32),
        n_expired=jnp.zeros((), jnp.float32),
        n_dropped=jnp.zeros((), jnp.float32),
    )


# ------------------------------------------------------------------ edges
def edge_key(cli_hi, cli_lo, ser_hi, ser_lo):
    """(cli, ser) → 64-bit edge table key as (hi, lo) u32 pair."""
    khi = H.mix64(cli_hi, cli_lo, _EDGE_SALT) ^ ser_hi
    klo = H.mix64(ser_hi, ser_lo, _EDGE_SALT) ^ cli_lo
    return khi, klo


def fold_edges(dep: DepGraph, cli_hi, cli_lo, cli_svc, ser_hi, ser_lo,
               byts, valid, tick, nconn=None) -> DepGraph:
    """Accumulate (cli→ser) flows into the edge slab (batched upsert).

    ``upsert_fast``: the edge working set is small and long-lived (one
    row per cli→ser dependency), so after warmup every batch is all-hit
    and the insert rounds are skipped entirely (``lax.cond``).

    ``nconn``: per-lane flow count (default 1 per lane — the raw-record
    path). Edge-folding agents ship PRE-AGGREGATED edges, so a lane may
    represent many flows (``engine/step.py:ingest_delta``)."""
    khi, klo = edge_key(cli_hi, cli_lo, ser_hi, ser_lo)
    tbl, rows, any_new = table.upsert_fast2(dep.edge_tbl, khi, klo,
                                            valid=valid)
    ok = valid & (rows >= 0)
    E = dep.e_nconn.shape[0]
    lanes = jnp.where(ok, rows, E)
    set_ = lambda col, v: col.at[lanes].set(v, mode="drop")  # noqa: E731

    # Identity columns only change when a NEW row is claimed — an
    # existing row already holds its (cli, ser) endpoint ids, and every
    # lane of a resolved key writes the values the row already has. In
    # steady state (all-hit, the hot loop) the five scatter-sets below
    # are pure redundancy at ~2 ms each per 32k-lane dispatch on one
    # core, so they ride the SAME miss signal the upsert's insert
    # machinery keys on. The carried operands are the five small (E,)
    # identity columns — nothing slab-sized crosses the cond boundary.
    def _write_ids(cols):
        chi, clo, csvc, shi, slo = cols
        return (set_(chi, cli_hi.astype(jnp.uint32)),
                set_(clo, cli_lo.astype(jnp.uint32)),
                set_(csvc, cli_svc),
                set_(shi, ser_hi.astype(jnp.uint32)),
                set_(slo, ser_lo.astype(jnp.uint32)))

    e_cli_hi, e_cli_lo, e_cli_svc, e_ser_hi, e_ser_lo = lax.cond(
        any_new, _write_ids, lambda cols: cols,
        (dep.e_cli_hi, dep.e_cli_lo, dep.e_cli_svc, dep.e_ser_hi,
         dep.e_ser_lo))
    return dep._replace(
        edge_tbl=tbl,
        e_cli_hi=e_cli_hi, e_cli_lo=e_cli_lo, e_cli_svc=e_cli_svc,
        e_ser_hi=e_ser_hi, e_ser_lo=e_ser_lo,
        e_ctr=dep.e_ctr.at[lanes].add(
            jnp.stack([jnp.where(ok, jnp.float32(1.0) if nconn is None
                                 else nconn.astype(jnp.float32), 0.0),
                       jnp.where(ok, byts, 0.0)], axis=1),
            mode="drop"),
        e_last_tick=set_(dep.e_last_tick, jnp.int32(tick)),
        n_dropped=dep.n_dropped
        + jnp.sum(valid & (rows < 0)).astype(jnp.float32),
    )


# ------------------------------------------------------------------ halves
class Halves(NamedTuple):
    """Dispatch lanes for cross-shard pairing (all shape (B,))."""
    flow_hi: jnp.ndarray
    flow_lo: jnp.ndarray
    is_cli: jnp.ndarray     # bool — this lane is the client-side half
    pay_hi: jnp.ndarray     # payload: cli entity id / ser glob id
    pay_lo: jnp.ndarray
    pay_svc: jnp.ndarray    # bool — (cli halves) entity is a listener
    byts: jnp.ndarray       # f32
    valid: jnp.ndarray


def halves_from_conn(cb):
    """Split a ConnBatch into direct-edge lanes and pairing halves.

    A conn record may know both sides (local flow / single-agent sim —
    the reference resolves those without shyama), only its client side
    (connect-observed, remote server), or only its server side
    (accept-observed, remote client). Returns
    ``(direct_lanes, halves)`` where direct_lanes is the tuple for
    ``fold_edges`` and halves is a :class:`Halves` for pairing.
    """
    cli_id_hi = jnp.where(cb.cli_rel_hi | cb.cli_rel_lo,
                          cb.cli_rel_hi, cb.cli_task_hi)
    cli_id_lo = jnp.where(cb.cli_rel_hi | cb.cli_rel_lo,
                          cb.cli_rel_lo, cb.cli_task_lo)
    cli_svc = (cb.cli_rel_hi | cb.cli_rel_lo) != 0
    know_cli = (cli_id_hi | cli_id_lo) != 0
    know_ser = (cb.svc_hi | cb.svc_lo) != 0
    byts = cb.bytes_sent + cb.bytes_rcvd
    direct = (cli_id_hi, cli_id_lo, cli_svc, cb.svc_hi, cb.svc_lo,
              byts, cb.valid & know_cli & know_ser)
    one_sided = cb.valid & (know_cli ^ know_ser)
    is_cli = know_cli
    halves = Halves(
        flow_hi=cb.flow_hi, flow_lo=cb.flow_lo, is_cli=is_cli,
        pay_hi=jnp.where(is_cli, cli_id_hi, cb.svc_hi),
        pay_lo=jnp.where(is_cli, cli_id_lo, cb.svc_lo),
        pay_svc=cli_svc & is_cli,
        byts=byts, valid=one_sided)
    return direct, halves


def pair_halves(dep: DepGraph, hv: Halves, tick) -> DepGraph:
    """Land halves in the half table; drain rows that just completed."""
    tbl, rows = table.upsert(dep.half_tbl, hv.flow_hi, hv.flow_lo,
                             valid=hv.valid)
    ok = hv.valid & (rows >= 0)
    Pc = dep.h_bytes.shape[0]
    cl = jnp.where(ok & hv.is_cli, rows, Pc)     # client-half lanes
    sl = jnp.where(ok & ~hv.is_cli, rows, Pc)    # server-half lanes
    cli_hi = dep.h_cli_hi.at[cl].set(hv.pay_hi.astype(jnp.uint32),
                                     mode="drop")
    cli_lo = dep.h_cli_lo.at[cl].set(hv.pay_lo.astype(jnp.uint32),
                                     mode="drop")
    cli_svc = dep.h_cli_svc.at[cl].set(hv.pay_svc, mode="drop")
    ser_hi = dep.h_ser_hi.at[sl].set(hv.pay_hi.astype(jnp.uint32),
                                     mode="drop")
    ser_lo = dep.h_ser_lo.at[sl].set(hv.pay_lo.astype(jnp.uint32),
                                     mode="drop")
    lanes = jnp.where(ok, rows, Pc)
    h_bytes = dep.h_bytes.at[lanes].max(jnp.where(ok, hv.byts, 0.0),
                                        mode="drop")
    cli_seen = dep.h_cli_seen.at[cl].set(True, mode="drop")
    ser_seen = dep.h_ser_seen.at[sl].set(True, mode="drop")
    last = dep.h_last_tick.at[lanes].set(jnp.int32(tick), mode="drop")

    done = cli_seen & ser_seen            # rows now holding both halves
    dep = dep._replace(
        half_tbl=tbl, h_cli_hi=cli_hi, h_cli_lo=cli_lo, h_cli_svc=cli_svc,
        h_ser_hi=ser_hi, h_ser_lo=ser_lo, h_bytes=h_bytes,
        h_cli_seen=cli_seen, h_ser_seen=ser_seen, h_last_tick=last,
        n_paired=dep.n_paired + jnp.sum(done).astype(jnp.float32),
        n_dropped=dep.n_dropped
        + jnp.sum(hv.valid & (rows < 0)).astype(jnp.float32),
    )
    # fold the completed rows' edges, then tombstone + clear them (drain —
    # the table only ever holds in-flight halves). A row can only become
    # done when a lane of THIS batch landed its second half, and every
    # done row is cleared the same step, so newly-done ≤ B — a bounded
    # nonzero gather covers all of them. (Folding edges with a P-lane
    # valid mask over the whole table was the dominant dep-fold cost:
    # a PROBES-round upsert at 65k lanes per step at the default capacity.)
    D = hv.valid.shape[0]
    idx = jnp.nonzero(done, size=D, fill_value=Pc)[0]
    get = lambda col: col.at[idx].get(mode="fill", fill_value=0)  # noqa: E731
    dep = fold_edges(dep, get(dep.h_cli_hi), get(dep.h_cli_lo),
                     get(dep.h_cli_svc), get(dep.h_ser_hi),
                     get(dep.h_ser_lo), get(dep.h_bytes),
                     idx < Pc, tick)
    return _clear_half_rows(dep, done)


def pair_halves_cond(dep: DepGraph, hv: Halves, tick) -> DepGraph:
    """``pair_halves`` skipped entirely (``lax.cond``) when the batch
    carries no one-sided halves — local/two-sided traffic (every flow
    whose agent observed both ends, the reference's non-shyama path)
    pays zero pairing cost. Identical semantics: with no valid lanes
    pair_halves inserts nothing and completes no rows, and done rows
    never persist across steps (drained the same step they complete)."""
    return lax.cond(jnp.any(hv.valid),
                    lambda d: pair_halves(d, hv, tick),
                    lambda d: d, dep)


def _clear_half_rows(dep: DepGraph, kill) -> DepGraph:
    tbl, killed = table.tombstone_rows(dep.half_tbl, kill)
    z = jnp.uint32(0)
    return dep._replace(
        half_tbl=tbl,
        h_cli_hi=jnp.where(killed, z, dep.h_cli_hi),
        h_cli_lo=jnp.where(killed, z, dep.h_cli_lo),
        h_cli_svc=jnp.where(killed, False, dep.h_cli_svc),
        h_ser_hi=jnp.where(killed, z, dep.h_ser_hi),
        h_ser_lo=jnp.where(killed, z, dep.h_ser_lo),
        h_bytes=jnp.where(killed, 0.0, dep.h_bytes),
        h_cli_seen=jnp.where(killed, False, dep.h_cli_seen),
        h_ser_seen=jnp.where(killed, False, dep.h_ser_seen),
        h_last_tick=jnp.where(killed, -1, dep.h_last_tick),
    )


def age(dep: DepGraph, tick, pair_ttl_ticks: int,
        edge_ttl_ticks: int) -> DepGraph:
    """TTL eviction: unpaired halves expire fast (the reference diag-dumps
    and drops unresolved conns); edges linger for the query horizon."""
    seen = dep.h_last_tick >= 0
    stale_h = seen & (jnp.int32(tick) - dep.h_last_tick
                      > jnp.int32(pair_ttl_ticks))
    dep = dep._replace(
        n_expired=dep.n_expired + jnp.sum(stale_h).astype(jnp.float32))
    dep = _clear_half_rows(dep, stale_h)
    e_seen = dep.e_last_tick >= 0
    stale_e = e_seen & (jnp.int32(tick) - dep.e_last_tick
                        > jnp.int32(edge_ttl_ticks))
    etbl, ekilled = table.tombstone_rows(dep.edge_tbl, stale_e)
    z = jnp.uint32(0)
    return dep._replace(
        edge_tbl=etbl,
        e_cli_hi=jnp.where(ekilled, z, dep.e_cli_hi),
        e_cli_lo=jnp.where(ekilled, z, dep.e_cli_lo),
        e_cli_svc=jnp.where(ekilled, False, dep.e_cli_svc),
        e_ser_hi=jnp.where(ekilled, z, dep.e_ser_hi),
        e_ser_lo=jnp.where(ekilled, z, dep.e_ser_lo),
        e_ctr=jnp.where(ekilled[:, None], 0.0, dep.e_ctr),
        e_last_tick=jnp.where(ekilled, -1, dep.e_last_tick),
    )


# ------------------------------------------------------- single-shard step
def dep_step(dep: DepGraph, cb, tick) -> DepGraph:
    """One conn batch → edges (single shard: no dispatch, halves pair
    locally — the n_shards=1 degenerate of the sharded step)."""
    direct, hv = halves_from_conn(cb)
    dep = fold_edges(dep, *direct, tick)
    return pair_halves_cond(dep, hv, tick)


def dep_fold_many(dep: DepGraph, cbs, tick) -> DepGraph:
    """K stacked conn batches → one flat direct-edge fold + chunked
    pairing.

    Direct (both-sides-known) lanes don't recycle table rows, so the
    whole K×B slab folds in ONE ``upsert_fast`` — all-hit in steady
    state, a single probe-match pass. Pairing DOES recycle rows (a
    matched half frees its slot for the next insert), so its one-sided
    lanes run in bounded chunks: each chunk's worst-case inserts stay
    under a quarter of the pair table (even on top of a steady-state
    unpaired backlog, an all-one-sided burst stays under the ~78%
    probe-exhaustion load documented in engine/table.py). Each chunk
    cond-skips entirely when it carries no one-sided lanes — the
    common case for local/two-sided traffic."""
    K, B = cbs.valid.shape[:2]
    n = K * B
    flat = jax.tree.map(lambda x: x.reshape((-1,) + x.shape[2:]), cbs)
    direct, hv = halves_from_conn(flat)
    dep = fold_edges(dep, *direct, tick)
    capacity = dep.h_last_tick.shape[0]
    chunk = max(1, min(n, capacity // 4))

    def body(carry, hvn):
        return pair_halves_cond(carry, hvn, tick), None

    nfull = n // chunk
    if nfull == 1 and n % chunk == 0:
        return pair_halves_cond(dep, hv, tick)

    def _pair_all(dep):
        if nfull:
            grouped = jax.tree.map(
                lambda x: x[: nfull * chunk].reshape(
                    (nfull, chunk) + x.shape[1:]), hv)
            dep, _ = lax.scan(body, dep, grouped)
        rem = n % chunk
        if rem:      # remainder lanes get their own bounded chunk
            tail = jax.tree.map(lambda x: x[nfull * chunk:], hv)
            dep = pair_halves_cond(dep, tail, tick)
        return dep

    # local/two-sided traffic (no one-sided half anywhere in the slab —
    # the common hot-path case) skips the whole chunked pairing scan
    # with ONE cond instead of paying K per-chunk cond evaluations; the
    # per-chunk conds still bound insert load when the outer is taken
    return lax.cond(jnp.any(hv.valid), _pair_all, lambda d: d, dep)


# ------------------------------------------------------------ sharded step
def dep_step_fn(mesh, cap_per_dest: int):
    """Compiled sharded step: (dep_stacked, conn_stacked, tick) → dep.

    Direct (both-sides-known) lanes fold into the local shard's edge slab.
    One-sided halves ride the capacity-disciplined staged ``all_to_all``
    to the flow-owner shard (payload columns travel with the key; on a
    multi-slice mesh the DCN axis is crossed at most once) and pair there.
    """
    from gyeeta_tpu.parallel.mesh import axes_of

    n = mesh.devices.size
    axes = axes_of(mesh)
    sizes = tuple(mesh.shape[a] for a in axes)
    spec = P(axes)

    @partial(jax.shard_map, mesh=mesh, in_specs=(spec, spec, P()),
             out_specs=spec, check_vma=False)
    def _step(dep, cb, tick):
        local = jax.tree.map(lambda x: x[0], dep)
        cb = jax.tree.map(lambda x: x[0], cb)
        direct, hv = halves_from_conn(cb)
        local = fold_edges(local, *direct, tick)
        routed, o_drop = _dispatch_halves(hv, axes, sizes, n,
                                          cap_per_dest)
        local = local._replace(n_dropped=local.n_dropped + o_drop)
        local = pair_halves_cond(local, routed, tick)
        return jax.tree.map(lambda x: x[None], local)

    return jax.jit(_step, donate_argnums=(0,))


def _dispatch_halves(hv: Halves, axes, sizes, n: int, cap: int):
    """Staged all_to_all capacity dispatch of Halves → received Halves."""
    from gyeeta_tpu.parallel.pairing import dispatch_fields

    owner = owner_shard(hv.flow_hi, hv.flow_lo, n)
    routed, r_val, dropped = dispatch_fields(
        {"fhi": (hv.flow_hi.astype(jnp.uint32), 0),
         "flo": (hv.flow_lo.astype(jnp.uint32), 0),
         "cli": (hv.is_cli, False),
         "phi": (hv.pay_hi.astype(jnp.uint32), 0),
         "plo": (hv.pay_lo.astype(jnp.uint32), 0),
         "psvc": (hv.pay_svc, False),
         "byts": (hv.byts, 0.0)},
        hv.valid, owner, axes, sizes, cap)
    return Halves(
        flow_hi=routed["fhi"], flow_lo=routed["flo"],
        is_cli=routed["cli"], pay_hi=routed["phi"],
        pay_lo=routed["plo"], pay_svc=routed["psvc"],
        byts=routed["byts"], valid=r_val), dropped


# ------------------------------------------------------------ edge rollup
class EdgeSet(NamedTuple):
    """A dense merged edge view (replicated after rollup)."""
    tbl: table.Table
    cli_hi: jnp.ndarray
    cli_lo: jnp.ndarray
    cli_svc: jnp.ndarray
    ser_hi: jnp.ndarray
    ser_lo: jnp.ndarray
    nconn: jnp.ndarray
    byts: jnp.ndarray


def _edge_merge(cap: int, cli_hi, cli_lo, cli_svc, ser_hi, ser_lo,
                nconn, byts, valid) -> EdgeSet:
    """Merge flat edge lanes (counts additive) into a fresh dense slab."""
    khi, klo = edge_key(cli_hi, cli_lo, ser_hi, ser_lo)
    tbl, rows = table.upsert(table.init(cap), khi, klo, valid=valid)
    ok = valid & (rows >= 0)
    lanes = jnp.where(ok, rows, cap)
    set_ = lambda z, v: z.at[lanes].set(v, mode="drop")      # noqa: E731
    zero32 = jnp.zeros((cap,), jnp.uint32)
    return EdgeSet(
        tbl=tbl,
        cli_hi=set_(zero32, cli_hi.astype(jnp.uint32)),
        cli_lo=set_(zero32, cli_lo.astype(jnp.uint32)),
        cli_svc=set_(jnp.zeros((cap,), bool), cli_svc),
        ser_hi=set_(zero32, ser_hi.astype(jnp.uint32)),
        ser_lo=set_(zero32, ser_lo.astype(jnp.uint32)),
        nconn=jnp.zeros((cap,), jnp.float32).at[lanes].add(
            jnp.where(ok, nconn, 0.0), mode="drop"),
        byts=jnp.zeros((cap,), jnp.float32).at[lanes].add(
            jnp.where(ok, byts, 0.0), mode="drop"),
    )


def edges_local(dep: DepGraph) -> EdgeSet:
    """Single-shard edge view (no collective) as an EdgeSet."""
    live = table.live_mask(dep.edge_tbl)
    cap = dep.e_nconn.shape[0]
    return _edge_merge(cap, dep.e_cli_hi, dep.e_cli_lo, dep.e_cli_svc,
                       dep.e_ser_hi, dep.e_ser_lo, dep.e_nconn,
                       dep.e_bytes, live)


def edge_rollup_fn(mesh, out_capacity: int):
    """Compiled sharded DepGraph → replicated merged EdgeSet."""
    from gyeeta_tpu.parallel.mesh import axes_of

    axes = axes_of(mesh)

    from gyeeta_tpu.parallel.mesh import gather_all

    @partial(jax.shard_map, mesh=mesh, in_specs=P(axes), out_specs=P(),
             check_vma=False)
    def _roll(dep):
        local = jax.tree.map(lambda x: x[0], dep)
        live = table.live_mask(local.edge_tbl)
        g = lambda x: gather_all(x, axes)       # noqa: E731
        return _edge_merge(
            out_capacity, g(local.e_cli_hi), g(local.e_cli_lo),
            g(local.e_cli_svc), g(local.e_ser_hi), g(local.e_ser_lo),
            g(local.e_nconn), g(local.e_bytes), g(live))

    return jax.jit(_roll)


# --------------------------------------------------------- mesh clustering
def mesh_clusters(es: EdgeSet, node_capacity: int, n_iters: int = 16):
    """Svc-mesh coalescing: connected components of the svc→svc edges.

    Returns ``(node_tbl, labels, sizes)``: a node table keyed by listener
    id, a per-row cluster label (the min node row reachable — stable,
    deterministic), and per-row member count of the row's cluster.
    Vectorized min-label propagation, ``n_iters`` fixed sweeps ≥ graph
    diameter (monitoring meshes are shallow; 16 covers 64k-node chains of
    fanout ≥2). The coalesce analogue of ``server/gy_shconnhdlr.cc:5198``.
    """
    use = table.live_mask(es.tbl) & es.cli_svc
    ntbl = table.init(node_capacity)
    ntbl, cli_rows = table.upsert(ntbl, es.cli_hi, es.cli_lo, valid=use)
    ntbl, ser_rows = table.upsert(ntbl, es.ser_hi, es.ser_lo, valid=use)
    ok = use & (cli_rows >= 0) & (ser_rows >= 0)
    cr = jnp.where(ok, cli_rows, node_capacity)
    sr = jnp.where(ok, ser_rows, node_capacity)
    labels = jnp.arange(node_capacity, dtype=jnp.int32)

    def body(labels, _):
        m = jnp.minimum(labels[jnp.where(ok, cli_rows, 0)],
                        labels[jnp.where(ok, ser_rows, 0)])
        m = jnp.where(ok, m, jnp.int32(node_capacity))
        labels = labels.at[cr].min(m, mode="drop")
        labels = labels.at[sr].min(m, mode="drop")
        return labels, None

    labels, _ = lax.scan(body, labels, None, length=n_iters)
    live = table.live_mask(ntbl)
    labels = jnp.where(live, labels, -1)
    counts = jnp.zeros((node_capacity + 1,), jnp.int32).at[
        jnp.where(live, labels, node_capacity)].add(1, mode="drop")
    sizes = jnp.where(live, counts[jnp.where(live, labels, 0)], 0)
    return ntbl, labels, sizes


# Process-wide compiled-builder memo (see sharded.memo_sharded: also a
# 0.4.x persistent-cache-reload correctness fix — the dep-graph a2a
# programs were exactly the ones that came back with broken layouts).
from gyeeta_tpu.parallel.sharded import memoize_builder as _memoize  # noqa: E402

dep_step_fn = _memoize(dep_step_fn)
edge_rollup_fn = _memoize(edge_rollup_fn)
