"""Device mesh construction + host→shard placement.

The placement rule replaces shyama's ``assign_partha_madhava``
(``server/gy_shconnhdlr.cc:5876``): instead of a capacity/affinity-aware
central assignment with DB-backed stickiness, hosts map to mesh shards by a
stable modulus of host id — deterministic, stateless, and uniform. Region/
zone affinity returns at the multi-slice level (DCN axis) where it matters
for TPUs; within a slice every shard is equidistant over ICI.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

HOST_AXIS = "hosts"
SLICE_AXIS = "slices"    # DCN axis of a multi-slice mesh (outer)

if not hasattr(jax, "shard_map"):
    # Older jax (<0.6) only ships shard_map under jax.experimental and
    # spells the replication check ``check_rep`` (renamed ``check_vma``
    # later). Every sharded tier entry point imports this module to
    # build its Mesh, so installing the translated alias here keeps
    # the call sites on the one current spelling.
    from jax.experimental.shard_map import shard_map as _exp_shard_map

    def _shard_map_compat(f, **kw):
        if "check_vma" in kw:
            kw["check_rep"] = kw.pop("check_vma")
        return _exp_shard_map(f, **kw)

    jax.shard_map = _shard_map_compat


def make_mesh(n_devices: int | None = None) -> Mesh:
    """1-D mesh over the first ``n_devices`` local devices (default: all)."""
    devs = jax.devices()
    if n_devices is not None:
        if len(devs) < n_devices:
            raise RuntimeError(
                f"need {n_devices} devices, have {len(devs)}")
        devs = devs[:n_devices]
    return Mesh(np.asarray(devs), (HOST_AXIS,))


def make_mesh2d(n_slices: int, per_slice: int) -> Mesh:
    """Multi-slice mesh: (slices × hosts) — the DCN tier (SURVEY §2.6
    multi-slice; the madhava-per-DC / shyama-across-DCs hierarchy).

    The outer ``slices`` axis rides DCN between slices; the inner
    ``hosts`` axis rides ICI within a slice. Collectives written against
    ``axes_of(mesh)`` reduce over both; the pairing dispatch routes in
    two stages so each flow crosses DCN at most once.
    """
    devs = jax.devices()
    need = n_slices * per_slice
    if len(devs) < need:
        raise RuntimeError(f"need {need} devices, have {len(devs)}")
    grid = np.asarray(devs[:need]).reshape(n_slices, per_slice)
    return Mesh(grid, (SLICE_AXIS, HOST_AXIS))


def axes_of(mesh: Mesh) -> tuple:
    """The mesh's shard axes, outermost first (collectives reduce over
    all of them; the stacked state's leading dim shards over the tuple)."""
    return tuple(mesh.axis_names)


def gather_all(x, axes):
    """all_gather over every mesh axis, innermost first (tiled) — the
    multi-axis gather used by every rollup path."""
    from jax import lax

    for ax in reversed(axes):
        x = lax.all_gather(x, ax, tiled=True)
    return x


def shard_of_host(host_id, n_shards: int):
    """Stable host→shard placement (works on np or jnp arrays)."""
    return host_id % n_shards


def leading_sharding(mesh: Mesh) -> NamedSharding:
    """NamedSharding that splits leaves on their leading (shard) axis
    over every mesh axis (1-D and multi-slice meshes alike)."""
    return NamedSharding(mesh, P(axes_of(mesh)))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
