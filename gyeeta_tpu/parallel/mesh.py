"""Device mesh construction + host→shard placement.

The placement rule replaces shyama's ``assign_partha_madhava``
(``server/gy_shconnhdlr.cc:5876``): instead of a capacity/affinity-aware
central assignment with DB-backed stickiness, hosts map to mesh shards by a
stable modulus of host id — deterministic, stateless, and uniform. Region/
zone affinity returns at the multi-slice level (DCN axis) where it matters
for TPUs; within a slice every shard is equidistant over ICI.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

HOST_AXIS = "hosts"


def make_mesh(n_devices: int | None = None) -> Mesh:
    """1-D mesh over the first ``n_devices`` local devices (default: all)."""
    devs = jax.devices()
    if n_devices is not None:
        if len(devs) < n_devices:
            raise RuntimeError(
                f"need {n_devices} devices, have {len(devs)}")
        devs = devs[:n_devices]
    return Mesh(np.asarray(devs), (HOST_AXIS,))


def shard_of_host(host_id, n_shards: int):
    """Stable host→shard placement (works on np or jnp arrays)."""
    return host_id % n_shards


def leading_sharding(mesh: Mesh) -> NamedSharding:
    """NamedSharding that splits leaves on their leading (shard) axis."""
    return NamedSharding(mesh, P(HOST_AXIS))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
