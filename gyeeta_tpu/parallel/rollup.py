"""Cluster roll-up: shard sketches → one global view, via ICI collectives.

This replaces the madhava→shyama aggregation RPCs — cluster state
aggregation (``server/gy_shconnhdlr.cc:4583`` aggregate_cluster_state) and
the per-madhava summary pushes (``MS_CLUSTER_STATE``) — with one jitted
collective program:

- Count-Min counters and windowed counters are additive → ``psum``,
- HLL registers merge by elementwise max → ``pmax``,
- top-K and t-digest need their survivor sets side by side → ``all_gather``
  (tiled) then one combine/compress on every shard (result replicated —
  every shard *is* shyama; there is no central server to fail).

Everything rides ICI inside a slice; on a multi-slice mesh the same program
spans the DCN axis unchanged.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from gyeeta_tpu.engine import aggstate, table
from gyeeta_tpu.parallel.mesh import HOST_AXIS
from gyeeta_tpu.sketch import countmin, hyperloglog as hll, invertible, \
    topk


class GlobalRollup(NamedTuple):
    """The shyama-level merged view (replicated on every shard)."""
    glob_hll: hll.HLL          # distinct flow endpoints, cluster-wide
    cms: countmin.CMS          # flow-key → bytes, cluster-wide
    flow_topk: topk.TopK       # heavy hitters across all shards
    n_conn: jnp.ndarray        # () totals
    n_resp: jnp.ndarray
    n_svc_live: jnp.ndarray    # () live service rows cluster-wide
    host_totals: jnp.ndarray   # (NHOSTCOL,) summed host panel (ntasks,
    #                             nlisten, issue counts — cluster state)
    n_hosts_up: jnp.ndarray    # () hosts that have reported
    # invertible heavy-hitter recovery, cluster-wide: every shard
    # decodes its own buckets (fingerprint + position verification is
    # local geometry), the candidates gather across shards, and each
    # one is point-queried against the GLOBALLY-merged CMS — the
    # madhava→shyama candidate pull as one collective program
    hh_hi: jnp.ndarray         # (n·d·w,) uint32 candidate key halves
    hh_lo: jnp.ndarray
    hh_ok: jnp.ndarray         # (n·d·w,) bool decode verification
    hh_est: jnp.ndarray        # (n·d·w,) f32 global CMS estimate
    hh_topk_est: jnp.ndarray   # (cap,) f32 global CMS estimate of the
    #                             merged exact lanes (bound tightening)
    hh_n_hot: jnp.ndarray      # () hot-admission lanes, summed
    hh_total_mass: jnp.ndarray  # () total folded flow mass (global)


from gyeeta_tpu.parallel.mesh import gather_all as _gather_all  # noqa: E402


def _rollup_local(st: aggstate.AggState,
                  axes=(HOST_AXIS,)) -> GlobalRollup:
    """Collective merge of one shard's state (runs inside shard_map).
    ``axes`` covers every mesh axis: on a multi-slice mesh the psum/pmax
    ride ICI within a slice first, then DCN across slices — XLA routes
    the named-axis reduction hierarchically."""
    regs = lax.pmax(st.glob_hll.regs, axes)
    cms_counts = lax.psum(st.cms.counts, axes)

    hi = _gather_all(st.flow_topk.key_hi, axes)
    lo = _gather_all(st.flow_topk.key_lo, axes)
    cnt = _gather_all(st.flow_topk.counts, axes)
    evicted = lax.psum(st.flow_topk.evicted, axes)
    cap = st.flow_topk.counts.shape[0]
    merged_topk = topk._combine(hi, lo, cnt, cap, evicted)

    # invertible-tier recovery: decode locally (bucket-position checks
    # are per-shard geometry), gather candidates, estimate against the
    # merged CMS so recovered counts are CLUSTER totals
    khi, klo, ok = invertible.decode_keys(st.inv)
    hh_hi = _gather_all(khi.reshape(-1), axes)
    hh_lo = _gather_all(klo.reshape(-1), axes)
    hh_ok = _gather_all(ok.reshape(-1), axes)
    gcms = countmin.CMS(counts=cms_counts)
    hh_est = jnp.where(hh_ok,
                       countmin.query(gcms, hh_hi, hh_lo)
                       .astype(jnp.float32), 0.0)
    hh_topk_est = jnp.where(
        merged_topk.counts > 0,
        countmin.query(gcms, merged_topk.key_hi, merged_topk.key_lo)
        .astype(jnp.float32), 0.0)

    live = jnp.sum(table.live_mask(st.tbl)).astype(jnp.float32)
    reported = st.host_panel[:, aggstate.HOST_NTASKS] > 0
    return GlobalRollup(
        glob_hll=hll.HLL(regs=regs),
        cms=gcms,
        flow_topk=merged_topk,
        hh_hi=hh_hi, hh_lo=hh_lo, hh_ok=hh_ok, hh_est=hh_est,
        hh_topk_est=hh_topk_est,
        hh_n_hot=lax.psum(st.inv.n_hot, axes),
        hh_total_mass=countmin.total(gcms),
        n_conn=lax.psum(st.n_conn, axes),
        n_resp=lax.psum(st.n_resp, axes),
        n_svc_live=lax.psum(live, axes),
        host_totals=lax.psum(
            jnp.sum(jnp.where(reported[:, None], st.host_panel, 0.0),
                    axis=0), axes),
        n_hosts_up=lax.psum(jnp.sum(reported).astype(jnp.float32),
                            axes),
    )


def rollup_fn(cfg: aggstate.EngineCfg, mesh):
    """Compiled sharded-state → replicated GlobalRollup."""
    from gyeeta_tpu.parallel.mesh import axes_of

    axes = axes_of(mesh)

    @partial(jax.shard_map, mesh=mesh, in_specs=P(axes),
             out_specs=P(), check_vma=False)
    def _roll(st):
        return _rollup_local(jax.tree.map(lambda x: x[0], st), axes)

    return jax.jit(_roll)


class FleetView(NamedTuple):
    """The whole once-per-tick cross-shard fleet view, from ONE
    collective program: cluster aggregates + heavy-hitter candidates
    (:class:`GlobalRollup`), the merged service dependency graph
    (``depgraph.EdgeSet``) and the engine-health vector. This is the
    madhava→shyama push cycle as a single mesh dispatch — everything a
    dashboard, an alertdef or the ops cadence reads about the FLEET in
    a tick comes off this one program's outputs."""
    rollup: GlobalRollup
    edges: object                  # depgraph.EdgeSet
    health: jnp.ndarray            # (len(HEALTH_KEYS),) f32, merged


def fleet_rollup_fn(cfg: aggstate.EngineCfg, mesh, edge_capacity: int):
    """Compiled (state, dep) → replicated :class:`FleetView`.

    One shard_map program per tick instead of three (rollup + edge
    rollup + health readback): the psum/pmax/all_gather traffic for all
    three shares one dispatch, and the host does one readback. The
    health vector merges per HEALTH_KEYS semantics — sums across
    shards, max for stage pressure (index of ``td_stage_max``)."""
    from gyeeta_tpu.engine import step as _step
    from gyeeta_tpu.parallel import depgraph as dg
    from gyeeta_tpu.parallel.mesh import axes_of

    axes = axes_of(mesh)
    max_idx = _step.HEALTH_KEYS.index("td_stage_max")
    is_max = jnp.zeros(len(_step.HEALTH_KEYS), bool).at[max_idx].set(True)

    @partial(jax.shard_map, mesh=mesh, in_specs=(P(axes), P(axes)),
             out_specs=P(), check_vma=False)
    def _roll(st, dep):
        sloc = jax.tree.map(lambda x: x[0], st)
        dloc = jax.tree.map(lambda x: x[0], dep)
        ru = _rollup_local(sloc, axes)
        live = table.live_mask(dloc.edge_tbl)
        g = lambda x: _gather_all(x, axes)       # noqa: E731
        es = dg._edge_merge(
            edge_capacity, g(dloc.e_cli_hi), g(dloc.e_cli_lo),
            g(dloc.e_cli_svc), g(dloc.e_ser_hi), g(dloc.e_ser_lo),
            g(dloc.e_nconn), g(dloc.e_bytes), g(live))
        vec = _step.engine_health_vec(cfg, sloc, dloc)
        vsum, vmax = vec, vec
        for ax in axes:
            vsum = lax.psum(vsum, ax)
            vmax = lax.pmax(vmax, ax)
        return FleetView(rollup=ru, edges=es,
                         health=jnp.where(is_max, vmax, vsum))

    return jax.jit(_roll)


# Process-wide compiled-builder memo (see sharded.memo_sharded).
from gyeeta_tpu.parallel.sharded import memoize_builder as _memoize  # noqa: E402

rollup_fn = _memoize(rollup_fn)
fleet_rollup_fn = _memoize(fleet_rollup_fn)
