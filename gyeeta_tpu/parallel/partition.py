"""Shard-layout declaration: partition rules named once, reused everywhere.

Before this module every consumer of the mesh re-derived its own layout
ad hoc: ``init_sharded`` broadcast + leading-sharded, the snapshot copy
inherited input shardings implicitly, the dep graph replicated by hand,
and the WAL/history tiers had no layout notion at all. The fleet-scale
tier makes the layout a FIRST-CLASS declaration (the
``match_partition_rules`` idiom of large-model training codebases): a
:class:`ShardLayout` holds the mesh plus an ordered list of
``(leaf-path regex, PartitionSpec)`` rules, and fold, roll-up, snapshot
publication, checkpoint restore and the per-shard WAL all ask IT where
data lives instead of encoding the answer locally.

The default rules say exactly what the sharded tier has always meant:

- stacked engine/dep leaves split on their LEADING axis over every mesh
  axis (each shard owns the full-geometry slab for its slice of the
  host space — data parallelism over ``HOST_AXIS``),
- scalars and rollup outputs replicate.

``pjit_with_cpu_fallback`` keeps single-device hosts (a laptop, the
1-device bench leg) on plain ``jax.jit`` — sharding constraints over a
1-element mesh only cost compile time — while mesh hosts get explicit
in/out shardings.
"""

from __future__ import annotations

import re
from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from gyeeta_tpu.parallel.mesh import SLICE_AXIS, axes_of, make_mesh, \
    make_mesh2d, shard_of_host


def named_tree_paths(tree, sep: str = "/"):
    """Flatten ``tree`` to ``[(path, leaf)]`` with ``sep``-joined path
    names (NamedTuple fields and dict keys become path components —
    e.g. ``state/tbl/key_hi``). The name side of the partition-rule
    match."""
    out = []

    def walk(prefix, node):
        if hasattr(node, "_fields"):          # NamedTuple
            for f in node._fields:
                walk(prefix + [f], getattr(node, f))
        elif isinstance(node, dict):
            for k in sorted(node):
                walk(prefix + [str(k)], node[k])
        elif isinstance(node, (list, tuple)):
            for i, v in enumerate(node):
                walk(prefix + [str(i)], v)
        else:
            out.append((sep.join(prefix), node))

    walk([], tree)
    return out


def match_partition_rules(rules, tree, sep: str = "/"):
    """Pytree of PartitionSpec chosen by the first rule whose regex
    matches each leaf's path name (scalars never partition). Raises on
    an unmatched non-scalar leaf so a new engine field cannot silently
    fall through the layout declaration."""
    def spec_of(name, leaf):
        shape = getattr(leaf, "shape", ())
        if len(shape) == 0 or int(np.prod(shape)) <= 1:
            return P()
        for rule, ps in rules:
            if re.search(rule, name) is not None:
                return ps
        raise ValueError(f"partition rule not found for leaf: {name}")

    leaves = named_tree_paths(tree, sep=sep)
    specs = [spec_of(name, leaf) for name, leaf in leaves]
    treedef = jax.tree_util.tree_structure(tree)
    return jax.tree_util.tree_unflatten(treedef, specs)


def pjit_with_cpu_fallback(fun, in_shardings=None, out_shardings=None,
                           static_argnums=(), donate_argnums=(),
                           mesh: Optional[Mesh] = None):
    """``jax.jit`` with explicit shardings on a real mesh; plain jit on
    a 1-device mesh (the CPU/laptop fallback — constraints over a
    single device add compile cost and nothing else)."""
    if mesh is not None and mesh.devices.size <= 1:
        return jax.jit(fun, static_argnums=static_argnums,
                       donate_argnums=donate_argnums)
    kw = {}
    if in_shardings is not None:
        kw["in_shardings"] = in_shardings
    if out_shardings is not None:
        kw["out_shardings"] = out_shardings
    return jax.jit(fun, static_argnums=static_argnums,
                   donate_argnums=donate_argnums, **kw)


def make_hybrid_mesh(n_slices: int, per_slice: int) -> Mesh:
    """(slices × hosts) mesh via ``create_hybrid_device_mesh`` when the
    backend exposes multi-granularity devices (real multi-slice TPU),
    else the local reshape (``make_mesh2d`` — the simulated-mesh and
    single-slice path). Same axis names either way, so every collective
    written against ``axes_of(mesh)`` is layout-agnostic."""
    try:
        from jax.experimental import mesh_utils
        devs = mesh_utils.create_hybrid_device_mesh(
            (per_slice,), (n_slices,), devices=jax.devices())
        return Mesh(devs.reshape(n_slices, per_slice),
                    (SLICE_AXIS, "hosts"))
    except Exception:
        # no DCN granularity on this backend (CPU sim, one slice)
        return make_mesh2d(n_slices, per_slice)


# The sharded tier's layout in one place. Order matters: first match
# wins. Leaves are named by pytree path (AggState/DepGraph field names).
DEFAULT_RULES: tuple = (
    # every stacked engine / dep-graph slab: split the leading shard
    # axis over the whole mesh (1-D and multi-slice alike)
    (r".*", "leading"),
)


class ShardLayout:
    """The one declaration of where sharded data lives.

    ``spec(tree)`` resolves the partition rules against a STACKED
    ``(n_shards, ...)`` pytree; ``sharding(tree)`` turns the specs into
    NamedShardings ready for ``jax.device_put`` / jit out_shardings.
    ``shard_of_host`` / ``wal_subdir`` are the host-facing half: the
    ingest edge, the WAL and replay all place by the same stable rule
    the fold uses, so a chunk journaled for host h replays into the
    shard that folded it (stable across reconnect AND restore)."""

    WAL_SUBDIR_FMT = "shard_{:02d}"

    def __init__(self, mesh: Optional[Mesh] = None,
                 rules: tuple = DEFAULT_RULES):
        self.mesh = mesh if mesh is not None else make_mesh()
        self.rules = tuple(
            (pat, self._leading_spec() if ps == "leading" else ps)
            for pat, ps in rules)
        self.n = int(self.mesh.devices.size)
        self._shd_memo: dict = {}     # (treedef, scalar flags) → shardings

    def _leading_spec(self) -> P:
        return P(axes_of(self.mesh))

    # ------------------------------------------------------------- specs
    def spec(self, tree):
        """Pytree of PartitionSpec for a stacked pytree."""
        return match_partition_rules(self.rules, tree)

    def sharding(self, tree):
        """Pytree of NamedSharding (device placement) for ``tree``."""
        return jax.tree_util.tree_map(
            lambda ps: NamedSharding(self.mesh, ps), self.spec(tree),
            is_leaf=lambda x: isinstance(x, P))

    @property
    def leading(self) -> NamedSharding:
        return NamedSharding(self.mesh, self._leading_spec())

    @property
    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    # -------------------------------------------------- host-side placement
    def shard_of_host(self, host_id):
        """The stable ingest-edge hash: host → shard (works on scalars
        and arrays; the same modulus the stacked fold routes by)."""
        return shard_of_host(host_id, self.n)

    def wal_subdir(self, shard: int) -> str:
        """Per-shard WAL subdirectory name (journaling shards with the
        fold — ``utils/journal.py:ShardedJournal``)."""
        return self.WAL_SUBDIR_FMT.format(int(shard))

    # ------------------------------------------------------------ plumbing
    def put(self, tree):
        """Place a stacked host-side pytree onto the mesh per the
        rules (the ``put_sharded`` role, layout-declared). The resolved
        sharding list is memoized per tree shape — rule matching never
        rides the per-dispatch hot path."""
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        key = (treedef, tuple(
            len(getattr(x, "shape", ())) == 0
            or int(np.prod(x.shape)) <= 1 for x in leaves))
        shds = self._shd_memo.get(key)
        if shds is None:
            shds = self._shd_memo[key] = jax.tree_util.tree_leaves(
                self.sharding(tree),
                is_leaf=lambda x: isinstance(x, NamedSharding))
        return jax.tree_util.tree_unflatten(
            treedef, [jax.device_put(x, s)
                      for x, s in zip(leaves, shds)])

    def jit(self, fun, donate_argnums=(), static_argnums=(),
            out_shardings=None):
        """Layout-aware jit with the 1-device fallback."""
        return pjit_with_cpu_fallback(
            fun, out_shardings=out_shardings, mesh=self.mesh,
            donate_argnums=donate_argnums, static_argnums=static_argnums)
