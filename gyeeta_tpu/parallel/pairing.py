"""Global conn-half pairing: all_to_all reshard by flow key + device table.

The reference pairs the client half and server half of every cross-madhava
TCP connection in shyama's central ``glob_tcp_conn_tbl_`` hash table
(``server/gy_shconnhdlr.h:1136``, match loop ``gy_shconnhdlr.cc:3790-3854``):
each madhava sends unresolved halves upward; shyama joins on ``PAIR_IP_PORT``
and notifies both sides.

TPU-native version: there is no central table. The flow-key space is
hash-sharded over the mesh; every shard routes its locally-observed halves
to the owner shard with one ``lax.all_to_all`` (an EP/MoE-style capacity
dispatch), and the owner upserts them into its slice of a device pair table.
A pair completes when both halves have landed on the same row. Exact join —
this path is deliberately not sketched (SURVEY §7 "exactness boundaries").

Capacity discipline: each shard sends at most ``cap`` lanes to each owner
per step; overflow lanes are dropped and counted (the analogue of the
reference's ~100k unresolved-conn cap, ``server/gy_mconnhdlr.h:94``).
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from gyeeta_tpu.engine import table
from gyeeta_tpu.parallel.mesh import HOST_AXIS
from gyeeta_tpu.utils import hashing as H

_OWNER_SALT = 0x9A1C


class PairTable(NamedTuple):
    """Per-shard slice of the global pairing table."""
    tbl: table.Table
    cli_seen: jnp.ndarray   # (S,) bool — client half landed
    ser_seen: jnp.ndarray   # (S,) bool — server half landed
    n_paired: jnp.ndarray   # () f32 — completed pairs (monotonic)
    n_dropped: jnp.ndarray  # () f32 — dispatch overflow + table drops


def pair_init(capacity: int) -> PairTable:
    return PairTable(
        tbl=table.init(capacity),
        cli_seen=jnp.zeros((capacity,), bool),
        ser_seen=jnp.zeros((capacity,), bool),
        n_paired=jnp.zeros((), jnp.float32),
        n_dropped=jnp.zeros((), jnp.float32),
    )


def owner_shard(flow_hi, flow_lo, n_shards: int):
    """Deterministic flow-key → owner shard (the sharding of the global
    pair table). Works on np or jnp inputs."""
    return H.mix64(flow_hi, flow_lo, _OWNER_SALT) % n_shards


def dispatch_fields(fields: dict, valid, owner, axes: tuple,
                    sizes: tuple, cap: int):
    """Route lanes to their owner device: one capacity-bucketed
    ``all_to_all`` per mesh axis, outermost (DCN) first.

    ``fields``: {name: ((B,) array, fill)}; ``owner``: (B,) global owner
    device index (row-major over ``sizes``). On a 1-D mesh this is the
    single-stage EP-style dispatch; on a multi-slice mesh each lane
    crosses the DCN axis at most once (to its owner slice) and then hops
    ICI to the owner lane — the hierarchical madhava→shyama routing.
    Stage k's per-destination cap is ``cap × (owners downstream)`` so an
    outer stage never throttles below the final per-owner capacity.
    Returns (routed_fields, routed_valid, dropped_count).
    """
    names = list(fields)
    arrs = {k: fields[k][0] for k in names}
    fills = {k: fields[k][1] for k in names}
    owner = owner.astype(jnp.int32)
    dropped = jnp.zeros((), jnp.float32)
    stride = 1
    for s in sizes[1:]:
        stride *= s
    for k, (ax, m) in enumerate(zip(axes, sizes)):
        B = valid.shape[0]
        cap_k = cap * stride
        dest = jnp.where(valid, (owner // stride) % m, m)
        order = jnp.argsort(dest)                      # stable
        d_s = dest[order]
        counts = jnp.bincount(d_s, length=m + 1)
        offsets = jnp.cumsum(counts) - counts          # exclusive prefix
        pos = jnp.arange(B, dtype=jnp.int32) - offsets[d_s]
        keep = (d_s < m) & (pos < cap_k)
        slot = jnp.where(keep, d_s * cap_k + pos, m * cap_k)

        def scatter(x, fill):
            buf = jnp.full((m * cap_k,) + x.shape[1:], fill, x.dtype)
            return buf.at[slot].set(x[order], mode="drop")

        def a2a(x):
            return lax.all_to_all(
                x.reshape((m, cap_k) + x.shape[1:]), ax,
                split_axis=0, concat_axis=0).reshape(
                    (m * cap_k,) + x.shape[1:])

        dropped = dropped + (jnp.sum(valid)
                             - jnp.sum(keep)).astype(jnp.float32)
        new_valid = jnp.zeros((m * cap_k,), bool).at[slot].set(
            keep, mode="drop")
        arrs = {kk: a2a(scatter(arrs[kk], fills[kk])) for kk in names}
        valid = a2a(new_valid)
        if k + 1 < len(sizes):
            # owner only rides along while later stages still route by it
            owner = a2a(scatter(owner, 0))
            stride //= sizes[k + 1]
    return arrs, valid, dropped


def _dispatch(flow_hi, flow_lo, is_cli, valid, axes, sizes, cap: int):
    """Pairing-lane dispatch (see :func:`dispatch_fields`)."""
    n = 1
    for s in sizes:
        n *= s
    owner = owner_shard(flow_hi, flow_lo, n)
    routed, r_val, dropped = dispatch_fields(
        {"hi": (flow_hi.astype(jnp.uint32), 0),
         "lo": (flow_lo.astype(jnp.uint32), 0),
         "cli": (is_cli, False)},
        valid, owner, axes, sizes, cap)
    return routed["hi"], routed["lo"], routed["cli"], r_val, dropped


def _pair_local(pt: PairTable, r_hi, r_lo, r_cli, r_valid) -> PairTable:
    """Upsert received halves into the local pair-table slice."""
    tbl, rows = table.upsert(pt.tbl, r_hi, r_lo, valid=r_valid)
    ok = r_valid & (rows >= 0)
    S = pt.cli_seen.shape[0]
    lanes = jnp.where(ok, rows, S)
    cli = pt.cli_seen.at[jnp.where(ok & r_cli, lanes, S)].set(
        True, mode="drop")
    ser = pt.ser_seen.at[jnp.where(ok & ~r_cli, lanes, S)].set(
        True, mode="drop")
    new_pairs = jnp.sum((cli & ser) & ~(pt.cli_seen & pt.ser_seen))
    tab_dropped = jnp.sum(r_valid & (rows < 0)).astype(jnp.float32)
    return pt._replace(
        tbl=tbl, cli_seen=cli, ser_seen=ser,
        n_paired=pt.n_paired + new_pairs.astype(jnp.float32),
        n_dropped=pt.n_dropped + tab_dropped,
    )


def pair_init_sharded(mesh, capacity: int) -> PairTable:
    """Stacked (n_shards, ...) pair table laid out over the mesh axes."""
    from gyeeta_tpu.parallel.mesh import leading_sharding
    n = mesh.devices.size
    shd = leading_sharding(mesh)

    @partial(jax.jit, out_shardings=shd)
    def _init():
        one = pair_init(capacity)
        return jax.tree.map(
            lambda x: jnp.broadcast_to(x[None], (n,) + x.shape), one)

    return _init()


def pairing_fn(mesh, cap_per_dest: int):
    """Compiled (pair_state, halves) → (pair_state, stats).

    ``halves`` leaves are (n_shards, B) stacked: flow_hi, flow_lo, is_cli,
    valid. ``stats`` is replicated: total pairs completed, total dropped.
    Works on 1-D and multi-slice meshes (staged dispatch).
    """
    from gyeeta_tpu.parallel.mesh import axes_of

    axes = axes_of(mesh)
    sizes = tuple(mesh.shape[a] for a in axes)
    spec = P(axes)

    @partial(jax.shard_map, mesh=mesh,
             in_specs=(spec,) * 5, out_specs=(spec, P()),
             check_vma=False)
    def _step(pt, fhi, flo, is_cli, valid):
        local = jax.tree.map(lambda x: x[0], pt)
        r_hi, r_lo, r_cli, r_val, o_drop = _dispatch(
            fhi[0], flo[0], is_cli[0], valid[0], axes, sizes,
            cap_per_dest)
        local = local._replace(n_dropped=local.n_dropped + o_drop)
        local = _pair_local(local, r_hi, r_lo, r_cli, r_val)
        stats = {
            "n_paired": lax.psum(local.n_paired, axes),
            "n_dropped": lax.psum(local.n_dropped, axes),
            "n_table_live": lax.psum(
                local.tbl.n_live.astype(jnp.float32), axes),
        }
        return jax.tree.map(lambda x: x[None], local), stats

    return jax.jit(_step, donate_argnums=(0,))
