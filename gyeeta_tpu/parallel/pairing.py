"""Global conn-half pairing: all_to_all reshard by flow key + device table.

The reference pairs the client half and server half of every cross-madhava
TCP connection in shyama's central ``glob_tcp_conn_tbl_`` hash table
(``server/gy_shconnhdlr.h:1136``, match loop ``gy_shconnhdlr.cc:3790-3854``):
each madhava sends unresolved halves upward; shyama joins on ``PAIR_IP_PORT``
and notifies both sides.

TPU-native version: there is no central table. The flow-key space is
hash-sharded over the mesh; every shard routes its locally-observed halves
to the owner shard with one ``lax.all_to_all`` (an EP/MoE-style capacity
dispatch), and the owner upserts them into its slice of a device pair table.
A pair completes when both halves have landed on the same row. Exact join —
this path is deliberately not sketched (SURVEY §7 "exactness boundaries").

Capacity discipline: each shard sends at most ``cap`` lanes to each owner
per step; overflow lanes are dropped and counted (the analogue of the
reference's ~100k unresolved-conn cap, ``server/gy_mconnhdlr.h:94``).
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from gyeeta_tpu.engine import table
from gyeeta_tpu.parallel.mesh import HOST_AXIS
from gyeeta_tpu.utils import hashing as H

_OWNER_SALT = 0x9A1C


class PairTable(NamedTuple):
    """Per-shard slice of the global pairing table."""
    tbl: table.Table
    cli_seen: jnp.ndarray   # (S,) bool — client half landed
    ser_seen: jnp.ndarray   # (S,) bool — server half landed
    n_paired: jnp.ndarray   # () f32 — completed pairs (monotonic)
    n_dropped: jnp.ndarray  # () f32 — dispatch overflow + table drops


def pair_init(capacity: int) -> PairTable:
    return PairTable(
        tbl=table.init(capacity),
        cli_seen=jnp.zeros((capacity,), bool),
        ser_seen=jnp.zeros((capacity,), bool),
        n_paired=jnp.zeros((), jnp.float32),
        n_dropped=jnp.zeros((), jnp.float32),
    )


def owner_shard(flow_hi, flow_lo, n_shards: int):
    """Deterministic flow-key → owner shard (the sharding of the global
    pair table). Works on np or jnp inputs."""
    return H.mix64(flow_hi, flow_lo, _OWNER_SALT) % n_shards


def _dispatch(flow_hi, flow_lo, is_cli, valid, n: int, cap: int):
    """Capacity-limited all_to_all dispatch of (B,) lanes → received lanes.

    Returns (r_hi, r_lo, r_cli, r_valid) of shape (n*cap,) on each shard,
    plus the local count of overflow-dropped lanes.
    """
    B = flow_hi.shape[0]
    dest = owner_shard(flow_hi, flow_lo, n).astype(jnp.int32)
    dest = jnp.where(valid, dest, n)                   # invalid → trash bin
    order = jnp.argsort(dest)                          # stable
    d_s = dest[order]
    counts = jnp.bincount(d_s, length=n + 1)
    offsets = jnp.cumsum(counts) - counts              # exclusive prefix
    pos = jnp.arange(B, dtype=jnp.int32) - offsets[d_s]
    keep = (d_s < n) & (pos < cap)
    slot = jnp.where(keep, d_s * cap + pos, n * cap)

    def scatter(x, fill):
        buf = jnp.full((n * cap,) + x.shape[1:], fill, x.dtype)
        return buf.at[slot].set(x[order], mode="drop")

    b_hi = scatter(flow_hi.astype(jnp.uint32), 0)
    b_lo = scatter(flow_lo.astype(jnp.uint32), 0)
    b_cli = scatter(is_cli, False)
    b_val = jnp.zeros((n * cap,), bool).at[slot].set(keep, mode="drop")

    def a2a(x):
        return lax.all_to_all(x.reshape((n, cap) + x.shape[1:]), HOST_AXIS,
                              split_axis=0, concat_axis=0).reshape(
                                  (n * cap,) + x.shape[1:])

    dropped = (jnp.sum(valid) - jnp.sum(keep)).astype(jnp.float32)
    return a2a(b_hi), a2a(b_lo), a2a(b_cli), a2a(b_val), dropped


def _pair_local(pt: PairTable, r_hi, r_lo, r_cli, r_valid) -> PairTable:
    """Upsert received halves into the local pair-table slice."""
    tbl, rows = table.upsert(pt.tbl, r_hi, r_lo, valid=r_valid)
    ok = r_valid & (rows >= 0)
    S = pt.cli_seen.shape[0]
    lanes = jnp.where(ok, rows, S)
    cli = pt.cli_seen.at[jnp.where(ok & r_cli, lanes, S)].set(
        True, mode="drop")
    ser = pt.ser_seen.at[jnp.where(ok & ~r_cli, lanes, S)].set(
        True, mode="drop")
    new_pairs = jnp.sum((cli & ser) & ~(pt.cli_seen & pt.ser_seen))
    tab_dropped = jnp.sum(r_valid & (rows < 0)).astype(jnp.float32)
    return pt._replace(
        tbl=tbl, cli_seen=cli, ser_seen=ser,
        n_paired=pt.n_paired + new_pairs.astype(jnp.float32),
        n_dropped=pt.n_dropped + tab_dropped,
    )


def pair_init_sharded(mesh, capacity: int) -> PairTable:
    """Stacked (n_shards, ...) pair table laid out over the mesh axis."""
    from jax.sharding import NamedSharding
    n = mesh.devices.size
    shd = NamedSharding(mesh, P(HOST_AXIS))

    @partial(jax.jit, out_shardings=shd)
    def _init():
        one = pair_init(capacity)
        return jax.tree.map(
            lambda x: jnp.broadcast_to(x[None], (n,) + x.shape), one)

    return _init()


def pairing_fn(mesh, cap_per_dest: int):
    """Compiled (pair_state, halves) → (pair_state, stats).

    ``halves`` leaves are (n_shards, B) stacked: flow_hi, flow_lo, is_cli,
    valid. ``stats`` is replicated: total pairs completed, total dropped.
    """
    n = mesh.devices.size

    @partial(jax.shard_map, mesh=mesh,
             in_specs=(P(HOST_AXIS),) * 5, out_specs=(P(HOST_AXIS), P()),
             check_vma=False)
    def _step(pt, fhi, flo, is_cli, valid):
        local = jax.tree.map(lambda x: x[0], pt)
        r_hi, r_lo, r_cli, r_val, o_drop = _dispatch(
            fhi[0], flo[0], is_cli[0], valid[0], n, cap_per_dest)
        local = local._replace(n_dropped=local.n_dropped + o_drop)
        local = _pair_local(local, r_hi, r_lo, r_cli, r_val)
        stats = {
            "n_paired": lax.psum(local.n_paired, HOST_AXIS),
            "n_dropped": lax.psum(local.n_dropped, HOST_AXIS),
            "n_table_live": lax.psum(
                local.tbl.n_live.astype(jnp.float32), HOST_AXIS),
        }
        return jax.tree.map(lambda x: x[None], local), stats

    return jax.jit(_step, donate_argnums=(0,))
