"""Sharded engine: per-shard AggState slabs + shard_map'd fold steps.

Each mesh shard owns an independent ``AggState`` (its own service slab and
sketches) for its slice of the host-id space — exactly a madhava's role
(per-host RCU tables, ``server/gy_mconnhdlr.h:1107``), but as one stacked
pytree with a leading shard axis laid out over the mesh. Ingest batches
arrive pre-routed ``(n_shards, B, ...)`` (see ``shard_batches``); the fold
runs embarrassingly parallel under ``shard_map`` with zero collectives —
collectives appear only in ``rollup.py``/``pairing.py``.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from gyeeta_tpu.engine import aggstate, step
from gyeeta_tpu.parallel.mesh import HOST_AXIS, axes_of, \
    leading_sharding, shard_of_host


_MESH_MEMO: dict = {}


def mesh_key(mesh) -> tuple:
    """Hashable identity of a mesh's geometry (axis names + shape +
    device ids): two Mesh objects over the same devices compile the
    same programs, so they share memoized executables."""
    return (tuple(mesh.axis_names), tuple(mesh.devices.shape),
            tuple(int(d.id) for d in mesh.devices.flat))


def memo_sharded(key: tuple, make):
    """Process-wide compiled-function memo for the mesh tier (the
    sharded twin of ``runtime._memo_jit``). Beyond the compile-time
    win, this is a CORRECTNESS fix on the 0.4.x jaxlib line: a second
    ShardedRuntime with identical geometry used to re-trace the same
    shard_map program, HIT the persistent XLA cache entry written
    minutes earlier by the first instance, and the reloaded executable
    came back with broken layouts — the long-standing "a2a rollup"
    garbage-value failure (negative collective sums, NaN health
    counters) that only reproduced when two mesh runtimes shared a
    process. Sharing the in-memory executable means the program is
    never re-traced, so the broken reload path is never taken."""
    fn = _MESH_MEMO.get(key)
    if fn is None:
        fn = _MESH_MEMO[key] = make()
    return fn


def _local(tree):
    """Strip the singleton shard axis inside shard_map."""
    return jax.tree.map(lambda x: x[0], tree)


def _relocal(tree):
    return jax.tree.map(lambda x: x[None], tree)


def init_sharded(cfg: aggstate.EngineCfg, mesh):
    """Stacked (n_shards, ...) AggState laid out over the mesh axis."""
    n = mesh.devices.size
    shd = leading_sharding(mesh)

    @partial(jax.jit, out_shardings=shd)
    def _init():
        one = aggstate.init(cfg)
        return jax.tree.map(
            lambda x: jnp.broadcast_to(x[None], (n,) + x.shape), one)

    return _init()


def stack_prerouted(batch_fns, per_shard_records):
    """Stacked batches from records ALREADY routed per shard — the
    ingest edge hashes hosts to shards once at staging time
    (``ShardedRuntime._stage_raw``), so the dispatch path just builds
    each shard's lanes from its own bucket. Returns host-side numpy
    leaves ``(n_shards, lanes, ...)`` ready for ``put_sharded``."""
    builder, lanes = batch_fns
    return jax.tree.map(
        lambda *xs: np.stack(xs),
        *[builder(recs, lanes) for recs in per_shard_records])


def shard_batches(cfg: aggstate.EngineCfg, mesh, batch_fns, records,
                  host_ids):
    """Route host-side records to shards and build stacked batches.

    ``records``: structured record array; ``host_ids``: (N,) source host of
    each record; ``batch_fns``: (builder, lane_size) — e.g.
    ``(decode.conn_batch, cfg.conn_batch)``. Returns a batch pytree whose
    leaves are (n_shards, lane_size, ...) numpy arrays (ready for
    ``jax.device_put`` with the leading sharding).

    This is the host-side L1 role (validate + batch + route,
    ``server/gy_mconnhdlr.cc:2430``): pure numpy, no device work.
    """
    builder, lanes = batch_fns
    n = mesh.devices.size
    dest = shard_of_host(np.asarray(host_ids), n)
    shards = []
    for s in range(n):
        shards.append(builder(records[dest == s], lanes))
    return jax.tree.map(lambda *xs: np.stack(xs), *shards)


def put_sharded(mesh, batch):
    """Transfer a stacked host batch to devices, split on the shard axis."""
    shd = leading_sharding(mesh)
    return jax.tree.map(lambda x: jax.device_put(x, shd), batch)


def fold_step_sharded(cfg: aggstate.EngineCfg, mesh):
    """Compiled sharded flagship step: (state, conn, resp) → state.

    Uses the same staged-digest hot path as the single-chip
    ``fold_many``: conn fold + one flat resp pass + amortized digest
    compression per shard. Callers must apply ``td_flush_sharded``
    before reading digest quantiles (the sharded runtime does, at tick
    and query boundaries)."""

    @partial(jax.shard_map, mesh=mesh, in_specs=(P(axes_of(mesh)),) * 3,
             out_specs=P(axes_of(mesh)), check_vma=False)
    def _step(st, cb, rb):
        local = step.ingest_conn(cfg, _local(st), _local(cb))
        local = step.ingest_resp_flat(cfg, local, _local(rb))
        return _relocal(local)

    return jax.jit(_step, donate_argnums=(0,))


def fold_step_dep_sharded(cfg: aggstate.EngineCfg, mesh,
                          cap_per_dest: int):
    """The sharded fused slab dispatch: engine fold + dependency-graph
    fold (incl. the cross-shard pairing ``all_to_all``) + the global
    digest-stage pressure scalar in ONE shard_map'd jit with state AND
    dep donation — replacing the legacy three-dispatch sequence
    (``fold_step_sharded`` + ``td_pressure_sharded`` + ``dep_step_fn``)
    with one jit-call overhead per slab. The pressure scalar is a graph
    OUTPUT (replicated ()), so the hot loop never issues a dispatch
    just to observe it. ``cap_per_dest`` is the pairing dispatch
    capacity — instantiate once per slab width (chunk vs fold_k-deep),
    like the legacy ``dep_step_fn`` pair."""
    from gyeeta_tpu.parallel import depgraph as dg

    n = mesh.devices.size
    axes = axes_of(mesh)
    sizes = tuple(mesh.shape[a] for a in axes)
    spec = P(axes)

    @partial(jax.shard_map, mesh=mesh,
             in_specs=(spec, spec, spec, spec, P()),
             out_specs=(spec, spec, P()), check_vma=False)
    def _step(st, dep, cb, rb, tick):
        local = step.ingest_conn(cfg, _local(st), _local(cb))
        local = step.ingest_resp_flat(cfg, local, _local(rb))
        dloc = _local(dep)
        cbl = _local(cb)
        direct, hv = dg.halves_from_conn(cbl)
        dloc = dg.fold_edges(dloc, *direct, tick)
        routed, o_drop = dg._dispatch_halves(hv, axes, sizes, n,
                                             cap_per_dest)
        dloc = dloc._replace(n_dropped=dloc.n_dropped + o_drop)
        dloc = dg.pair_halves_cond(dloc, routed, tick)
        press = jnp.max(local.td_stage_n)
        for ax in axes:
            press = jax.lax.pmax(press, ax)
        return _relocal(local), _relocal(dloc), press

    return jax.jit(_step, donate_argnums=(0, 1))


def td_flush_sharded(cfg: aggstate.EngineCfg, mesh):
    """Per-shard partial digest-stage flush (query/tick readiness).

    Each shard compresses its ``td_flush_m`` fullest stages per call —
    O(m), not O(per-shard capacity); when m ≥ the per-shard slab this
    is exactly the full flush. The sharded runtime drains iteratively
    against ``td_pressure_sharded`` (same host-trigger design as the
    single-chip runtime; an in-graph cond flush cost 110 ms/dispatch
    untaken at 65k capacity)."""

    @partial(jax.shard_map, mesh=mesh, in_specs=P(axes_of(mesh)),
             out_specs=P(axes_of(mesh)), check_vma=False)
    def _flush(st):
        return _relocal(step.td_flush_partial(cfg, _local(st)))

    return jax.jit(_flush, donate_argnums=(0,))


def td_pressure_sharded(mesh):
    """Global max staged-sample count across shards — one () scalar."""

    @partial(jax.shard_map, mesh=mesh, in_specs=P(axes_of(mesh)),
             out_specs=P(), check_vma=False)
    def _pressure(st):
        local = jnp.max(_local(st).td_stage_n)
        for ax in axes_of(mesh):
            local = jax.lax.pmax(local, ax)
        return local

    return jax.jit(_pressure)


def tick_5s_sharded(cfg: aggstate.EngineCfg, mesh):
    @partial(jax.shard_map, mesh=mesh, in_specs=P(axes_of(mesh)),
             out_specs=P(axes_of(mesh)), check_vma=False)
    def _tick(st):
        return _relocal(step.tick_5s(cfg, _local(st)))

    return jax.jit(_tick, donate_argnums=(0,))


def ingest_listener_sharded(cfg: aggstate.EngineCfg, mesh):
    @partial(jax.shard_map, mesh=mesh, in_specs=(P(axes_of(mesh)),) * 2,
             out_specs=P(axes_of(mesh)), check_vma=False)
    def _fold(st, lb):
        return _relocal(step.ingest_listener(cfg, _local(st), _local(lb)))

    return jax.jit(_fold, donate_argnums=(0,))


def ingest_host_sharded(cfg: aggstate.EngineCfg, mesh):
    @partial(jax.shard_map, mesh=mesh, in_specs=(P(axes_of(mesh)),) * 2,
             out_specs=P(axes_of(mesh)), check_vma=False)
    def _fold(st, hb):
        return _relocal(step.ingest_host(cfg, _local(st), _local(hb)))

    return jax.jit(_fold, donate_argnums=(0,))


def ingest_cpumem_sharded(cfg: aggstate.EngineCfg, mesh):
    @partial(jax.shard_map, mesh=mesh, in_specs=(P(axes_of(mesh)),) * 2,
             out_specs=P(axes_of(mesh)), check_vma=False)
    def _fold(st, cm):
        return _relocal(step.ingest_cpumem(cfg, _local(st), _local(cm)))

    return jax.jit(_fold, donate_argnums=(0,))


def ingest_trace_sharded(cfg: aggstate.EngineCfg, mesh):
    @partial(jax.shard_map, mesh=mesh, in_specs=(P(axes_of(mesh)),) * 2,
             out_specs=P(axes_of(mesh)), check_vma=False)
    def _fold(st, tb):
        return _relocal(step.ingest_trace(cfg, _local(st), _local(tb)))

    return jax.jit(_fold, donate_argnums=(0,))


def ingest_task_sharded(cfg: aggstate.EngineCfg, mesh):
    @partial(jax.shard_map, mesh=mesh, in_specs=(P(axes_of(mesh)),) * 2,
             out_specs=P(axes_of(mesh)), check_vma=False)
    def _fold(st, tb):
        return _relocal(step.ingest_task(cfg, _local(st), _local(tb)))

    return jax.jit(_fold, donate_argnums=(0,))


def ingest_delta_sharded(cfg: aggstate.EngineCfg, mesh):
    """Sharded edge pre-aggregation fold: each shard folds the delta
    lanes of ITS hosts (records were routed by the layout's hid hash at
    staging time, like every raw stream) into its own state AND dep
    slice — pre-aggregated dep edges are direct edges (both endpoints
    known at the agent), so no pairing collective is needed."""

    @partial(jax.shard_map, mesh=mesh,
             in_specs=(P(axes_of(mesh)),) * 3 + (P(),),
             out_specs=(P(axes_of(mesh)),) * 2, check_vma=False)
    def _fold(st, dep, db, tick):
        lst, ldep = step.ingest_delta(cfg, _local(st), _local(dep),
                                      _local(db), tick)
        return _relocal(lst), _relocal(ldep)

    return jax.jit(_fold, donate_argnums=(0, 1))


def ping_tasks_sharded(cfg: aggstate.EngineCfg, mesh):
    @partial(jax.shard_map, mesh=mesh, in_specs=(P(axes_of(mesh)),) * 2,
             out_specs=P(axes_of(mesh)), check_vma=False)
    def _fold(st, pb):
        return _relocal(step.ping_tasks(cfg, _local(st), _local(pb)))

    return jax.jit(_fold, donate_argnums=(0,))


def classify_sharded(cfg: aggstate.EngineCfg, mesh):
    """Per-shard 5s classify pass (embarrassingly parallel: each shard
    classifies its own services/hosts — the per-madhava sweep)."""
    from gyeeta_tpu.semantic import derive

    @partial(jax.shard_map, mesh=mesh, in_specs=P(axes_of(mesh)),
             out_specs=P(axes_of(mesh)), check_vma=False)
    def _cls(st):
        return _relocal(derive.classify_pass(cfg, _local(st)))

    return jax.jit(_cls, donate_argnums=(0,))


def age_tasks_sharded(cfg: aggstate.EngineCfg, mesh, max_age_ticks: int):
    @partial(jax.shard_map, mesh=mesh, in_specs=P(axes_of(mesh)),
             out_specs=P(axes_of(mesh)), check_vma=False)
    def _age(st):
        return _relocal(step.age_tasks(cfg, _local(st), max_age_ticks))

    return jax.jit(_age, donate_argnums=(0,))


def age_apis_sharded(cfg: aggstate.EngineCfg, mesh, max_age_ticks: int):
    @partial(jax.shard_map, mesh=mesh, in_specs=P(axes_of(mesh)),
             out_specs=P(axes_of(mesh)), check_vma=False)
    def _age(st):
        return _relocal(step.age_apis(cfg, _local(st), max_age_ticks))

    return jax.jit(_age, donate_argnums=(0,))


def memoize_builder(builder):
    """Route a compiled-program builder ``f(cfg?, mesh, extras...)``
    through the process-wide memo (every arg must be hashable; Mesh
    args key by geometry). Used below and by ``depgraph``/``rollup`` —
    see :func:`memo_sharded` for why this is also a 0.4.x correctness
    fix, not just a compile-time saving."""
    from jax.sharding import Mesh

    def wrapper(*args, **kwargs):
        key = (builder.__module__, builder.__name__) + tuple(
            mesh_key(a) if isinstance(a, Mesh) else a for a in args) \
            + tuple(sorted(kwargs.items()))
        return memo_sharded(key, lambda: builder(*args, **kwargs))

    wrapper.__name__ = builder.__name__
    wrapper.__doc__ = builder.__doc__
    wrapper.__wrapped__ = builder
    return wrapper


# Memoize every pure compiled-program builder in this module (NOT
# init_sharded — it returns live state buffers that are later donated,
# so instances must never share them).
for _n in ("fold_step_sharded", "fold_step_dep_sharded",
           "td_flush_sharded", "td_pressure_sharded", "tick_5s_sharded",
           "ingest_listener_sharded", "ingest_host_sharded",
           "ingest_cpumem_sharded", "ingest_trace_sharded",
           "ingest_task_sharded", "ping_tasks_sharded",
           "ingest_delta_sharded",
           "classify_sharded", "age_tasks_sharded", "age_apis_sharded"):
    globals()[_n] = memoize_builder(globals()[_n])
del _n
