"""ShardedRuntime: the full product loop on an n-device mesh.

The single-node :class:`~gyeeta_tpu.runtime.Runtime` is one madhava. This
is the whole tier: every mesh shard owns the engine state for its slice of
the host space (DP over ``HOST_AXIS``), and the subsystems that the
reference runs as madhava→shyama RPCs become collectives:

- **ingest**: host-side routing of decoded records by ``host_id % n``
  (shyama's ``assign_partha_madhava`` placement, stateless) + shard_map'd
  folds — zero collectives in the hot path;
- **tick**: per-shard classify (each madhava classifies its own
  listeners), per-shard window tick/ageing, dep-graph TTL;
- **pairing / dep graph**: ``all_to_all`` to flow owners
  (``parallel/depgraph.py``);
- **queries & alerts**: gather per-shard snapshot columns and run the
  SAME filter/sort/aggregation pipeline on the merged columns — the
  multi-madhava scatter the reference's Node webserver performs
  (``server/gy_mnodehandle.cc:203``), done once here so alertdefs, JSON
  queries and history writes all see a cluster-wide view.

Everything stacked ``(n_shards, ...)`` with a leading-axis sharding, so
the same program runs on one chip (n=1), a v5e-8 slice, or a multi-slice
DCN mesh.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from gyeeta_tpu.alerts import AlertManager
from gyeeta_tpu.engine.aggstate import EngineCfg
from gyeeta_tpu.ingest import decode, native, wire
from gyeeta_tpu.obs import health as obs_health
from gyeeta_tpu.obs.spans import FoldProfiler, SpanTracer
from gyeeta_tpu.parallel import depgraph as dg
from gyeeta_tpu.parallel import pairing, rollup, sharded
from gyeeta_tpu.parallel.mesh import shard_of_host  # noqa: F401 — re-export
from gyeeta_tpu.query import api, fieldmaps, readback
from gyeeta_tpu.query.api import QueryOptions
from gyeeta_tpu.sketch import topk
from gyeeta_tpu.utils import dnsmap as _dnsmap
from gyeeta_tpu.utils.config import RuntimeOpts
from gyeeta_tpu.utils.intern import InternTable
from gyeeta_tpu.utils.selfstats import Stats


class ShardedRuntime:
    def __init__(self, cfg: Optional[EngineCfg] = None, mesh=None,
                 opts: Optional[RuntimeOpts] = None, clock=None):
        from gyeeta_tpu.parallel.mesh import make_mesh

        self.cfg = cfg or EngineCfg()
        self.mesh = mesh if mesh is not None else make_mesh()
        self.n = self.mesh.devices.size
        # the ONE shard-layout declaration (parallel/partition.py):
        # fold, roll-up, snapshot placement, the ingest-edge host hash
        # and the per-shard WAL subdirs all ask the layout instead of
        # re-deriving placement locally
        from gyeeta_tpu.parallel.partition import ShardLayout
        self.layout = ShardLayout(self.mesh)
        self.opts = opts or RuntimeOpts()
        self.stats = Stats()
        # pipeline span ring + opt-in device-trace bracket (obs tier)
        self.spans = SpanTracer()
        self._profiler = FoldProfiler()
        from gyeeta_tpu.utils.colcache import ColumnCache
        self._cols = ColumnCache()    # version-keyed snapshot memo
        self.names = InternTable()
        from gyeeta_tpu.utils.svcreg import SvcInfoRegistry
        from gyeeta_tpu.utils.hostreg import CgroupRegistry, \
            HostInfoRegistry, MountRegistry, NetIfRegistry
        from gyeeta_tpu.utils.notifylog import NotifyLog
        from gyeeta_tpu.trace.defs import TraceDefs
        self.tracedefs = TraceDefs(clock=clock)
        from gyeeta_tpu.utils.natreg import NatClusterRegistry
        self.svcreg = SvcInfoRegistry()
        self.hostinfo = HostInfoRegistry()
        self.cgroups = CgroupRegistry()
        self.mounts = MountRegistry()
        self.netifs = NetIfRegistry()
        self.natclusters = NatClusterRegistry()
        from gyeeta_tpu.utils.traceconnreg import TraceConnRegistry
        self.traceconns = TraceConnRegistry()
        from gyeeta_tpu.utils.tagreg import TagRegistry
        self.tags = TagRegistry()
        from gyeeta_tpu.utils.dnsmap import DnsCache
        self.dns = DnsCache()
        self.notifylog = NotifyLog(clock=clock)
        self.alerts = AlertManager(self.cfg, clock=clock)
        self._clock = clock or time.time
        self._t_started = self._clock()
        self._tick_no = 0
        self._pending = b""
        # write-ahead event journal (utils/journal.py): the mesh tier
        # journals PER SHARD — chunks land in ``shard_NN/`` subdirs by
        # the layout's sticky hid→shard hash, so journaling, replay and
        # compaction all shard with the fold (a replayed chunk re-folds
        # into exactly the shard that folded it live; see the routing-
        # stability tests). A 1-device mesh keeps the flat layout.
        self.journal = None
        if self.opts.journal_dir:
            from gyeeta_tpu.utils.journal import Journal, ShardedJournal
            jkw = dict(
                segment_max_bytes=self.opts.journal_segment_mb << 20,
                fsync_bytes=self.opts.journal_fsync_kb << 10,
                fsync_ms=self.opts.journal_fsync_ms,
                backlog_max_bytes=self.opts.journal_backlog_mb << 20,
                stats=self.stats, clock=clock)
            if self.n > 1:
                self.journal = ShardedJournal(
                    self.opts.journal_dir, self.n,
                    subdir_fmt=self.layout.WAL_SUBDIR_FMT, **jkw)
            else:
                self.journal = Journal(self.opts.journal_dir, **jkw)
        self._journal_replaying = False
        # time-travel query tier (history/timeview.py): shard-
        # materialized snapshots re-enter the stacked pytree shape and
        # are served by the SAME merged-columns pipeline (see
        # _merged_columns_state), so the mesh tier gets at=/window=
        # queries on every edge with zero edge-specific code
        self.timeview = None
        if self.opts.hist_shard_dir:
            from gyeeta_tpu.history.shards import open_shard_store
            from gyeeta_tpu.history.timeview import TimeView
            store = open_shard_store(self.opts.hist_shard_dir,
                                     stats=self.stats)
            self.timeview = TimeView(self, store, clock=clock)
            if self.journal is not None:
                pos = store.position()
                if pos:
                    from gyeeta_tpu.utils.journal import floors_of
                    fl = floors_of(pos)
                    if isinstance(fl, list) \
                            and not hasattr(self.journal, "shards"):
                        fl = min(fl) if fl else 0
                    self.journal.set_truncate_floor(fl)
                else:
                    self.journal.set_truncate_floor(0)
        # per-host sweep-seq high-water marks (the WAL dedup state)
        self._sweep_last_seq: dict = {}
        # conn/resp slab staging, PER SHARD: the ingest edge hashes
        # each record's host to its shard ONCE at staging time
        # (``_stage_raw``), so a dispatch builds every shard's lanes
        # from its own bucket — lane width is the actual slab width,
        # not the worst-case routing skew, and the per-record routing
        # cost leaves the dispatch path. ``_n_conn_raw``/``_n_resp_raw``
        # stay the TOTALS (the admission controller reads them).
        self._conn_raw: list = [[] for _ in range(self.n)]
        self._resp_raw: list = [[] for _ in range(self.n)]
        self._conn_staged = [0] * self.n
        self._resp_staged = [0] * self.n
        self._n_conn_raw = 0
        self._n_resp_raw = 0
        # per-shard folded-event counters → gyt_shard_fold_ev_per_sec
        # gauges at tick cadence (host-side ints, no readback)
        self._shard_events = np.zeros(self.n, np.int64)
        self._shard_rate_mark = np.zeros(self.n, np.int64)
        self._shard_rate_t: float = self._clock()
        # last tick each host sent a native RESP_SAMPLE (trace→resp
        # bridge precedence, see Runtime)
        self._host_resp_tick = np.full(self.cfg.n_hosts, -(10 ** 9),
                                       np.int64)

        self.state = sharded.init_sharded(self.cfg, self.mesh)
        self.dep = self.layout.put(
            jax.tree.map(
                lambda x: np.broadcast_to(
                    np.asarray(x)[None], (self.n,) + np.asarray(x).shape),
                dg.init(self.opts.dep_pair_capacity,
                        self.opts.dep_edge_capacity)))

        self._fold = sharded.fold_step_sharded(self.cfg, self.mesh)
        self._td_flush = sharded.td_flush_sharded(self.cfg, self.mesh)
        self._td_pressure = sharded.td_pressure_sharded(self.mesh)
        # fused slab dispatch (default): engine fold + dep fold +
        # pressure scalar in ONE shard_map'd jit — the legacy three-
        # dispatch sequence stays selectable via GYT_FUSED_FOLD=0
        from gyeeta_tpu.runtime import fused_fold_enabled
        self._fused = fused_fold_enabled()
        self._fold_dep_slab = sharded.fold_step_dep_sharded(
            self.cfg, self.mesh,
            cap_per_dest=self.cfg.conn_batch * self.cfg.fold_k)
        self._fold_dep_chunk = sharded.fold_step_dep_sharded(
            self.cfg, self.mesh, cap_per_dest=self.cfg.conn_batch)
        self._td_dirty = False
        self._pressure = None         # device scalar from last dispatch
        self._fold_lst = sharded.ingest_listener_sharded(self.cfg,
                                                         self.mesh)
        # edge pre-aggregation fold (state + dep donated; delta records
        # route per shard by host_id like every raw stream)
        self._fold_delta = sharded.ingest_delta_sharded(self.cfg,
                                                        self.mesh)
        self._delta_dims = dict(
            resp_nbuckets=self.cfg.resp_spec.nbuckets,
            hll_m_svc=1 << self.cfg.hll_p_svc,
            hll_m_glob=1 << self.cfg.hll_p_global)
        self._fold_host = sharded.ingest_host_sharded(self.cfg, self.mesh)
        self._fold_task = sharded.ingest_task_sharded(self.cfg, self.mesh)
        self._fold_ping = sharded.ping_tasks_sharded(self.cfg, self.mesh)
        self._fold_cm = sharded.ingest_cpumem_sharded(self.cfg, self.mesh)
        self._fold_trace = sharded.ingest_trace_sharded(self.cfg,
                                                        self.mesh)
        self._classify = sharded.classify_sharded(self.cfg, self.mesh)
        self._tick = sharded.tick_5s_sharded(self.cfg, self.mesh)
        self._age_tasks = sharded.age_tasks_sharded(
            self.cfg, self.mesh, self.opts.task_max_age_ticks)
        self._age_apis = sharded.age_apis_sharded(
            self.cfg, self.mesh, self.opts.api_max_age_ticks)
        self._dep_step = dg.dep_step_fn(
            self.mesh, cap_per_dest=self.cfg.conn_batch)
        # slab-width dep step: the a2a capacity scales with the wider
        # dispatch so a burst of one-sided halves isn't dropped
        self._dep_slab = dg.dep_step_fn(
            self.mesh,
            cap_per_dest=self.cfg.conn_batch * self.cfg.fold_k)
        self._rollup = rollup.rollup_fn(self.cfg, self.mesh)
        self._edge_roll = dg.edge_rollup_fn(
            self.mesh, out_capacity=self.opts.dep_edge_capacity)
        # the once-per-tick fleet-view collective: cluster rollup +
        # merged dep edges + health vector in ONE shard_map program
        # (the in-device madhava→shyama push cycle). run_tick seeds the
        # snapshot/live column caches from its outputs, so dashboard
        # queries and alertdefs reuse the tick's collective instead of
        # re-dispatching their own.
        self._fleet_roll = rollup.fleet_rollup_fn(
            self.cfg, self.mesh, self.opts.dep_edge_capacity)

        from functools import partial
        from jax.sharding import PartitionSpec as P

        from gyeeta_tpu.parallel.mesh import axes_of
        pttl, ettl = (self.opts.dep_pair_ttl_ticks,
                      self.opts.dep_edge_ttl_ticks)
        _axes = axes_of(self.mesh)
        mkey = sharded.mesh_key(self.mesh)

        def _make_dep_age():
            @partial(jax.shard_map, mesh=self.mesh,
                     in_specs=(P(_axes), P()), out_specs=P(_axes),
                     check_vma=False)
            def _dep_age(dep, tick):
                local = jax.tree.map(lambda x: x[0], dep)
                return jax.tree.map(lambda x: x[None],
                                    dg.age(local, tick, pttl, ettl))

            return jax.jit(_dep_age, donate_argnums=(0,))

        # instance-local jits route through the process memo too (the
        # sharded.memo_sharded correctness note: re-traced twins of
        # these programs reload broken from the 0.4.x persistent cache)
        self._dep_age = sharded.memo_sharded(
            ("dep_age", mkey, pttl, ettl), _make_dep_age)
        self._mesh_clusters = sharded.memo_sharded(
            ("mesh_clusters",),
            lambda: jax.jit(dg.mesh_clusters, static_argnums=(1,)))
        # device-health readback: sums over stacked shard leaves (max
        # for stage pressure) → ONE replicated vector, one small
        # transfer per report cadence (no donation — read-only)
        from gyeeta_tpu.engine import step as _step
        self._engine_health = sharded.memo_sharded(
            ("engine_health", self.cfg, mkey),
            lambda: jax.jit(
                lambda s, d: _step.engine_health_vec(self.cfg, s, d)))

        # recovered-hot key set from the previous recovery (promotion
        # edge detection — see Runtime.heavy_recover)
        self._hh_prev_hot: set = set()

        # snapshot publication (query/snapshot.py): one non-donating
        # jitted copy of the stacked (state, dep) per publish — output
        # shardings follow the inputs, so collectives (rollup, edge
        # rollup) run on the frozen copy unchanged. See Runtime.
        # GYT_SNAP_PINGPONG=1 donates the RETIRED snapshot's buffers as
        # the copy's destination (runtime.snap_pingpong_enabled — the
        # ROADMAP item (a) prototype, refcount-guarded).
        self._snap_copy = sharded.memo_sharded(
            ("snap_copy",),
            lambda: jax.jit(lambda t: jax.tree.map(jnp.copy, t)))
        from gyeeta_tpu.runtime import make_pingpong_copy, \
            snap_pingpong_enabled
        self._snap_pingpong = snap_pingpong_enabled()
        self._snap_copy_pp = sharded.memo_sharded(
            ("snap_copy_pp",), make_pingpong_copy) \
            if self._snap_pingpong else None
        self._snap_old = None     # the retired (N-2) snapshot candidate
        self.snapshot = None
        self._snap_version = 0
        # registry renders on query worker threads vs updates on the
        # serving loop (see Runtime._reg_lock)
        self._reg_lock = threading.RLock()

        from gyeeta_tpu.alerts import columns as AC
        self._aux = {
            "topk": self._topk_columns,
            "hostinfo": lambda: self.hostinfo.columns(self.names),
            "cgroupstate": lambda: self.cgroups.columns(self.names),
            "mountstate": lambda: self.mounts.columns(self.names),
            "netif": lambda: self.netifs.columns(self.names),
            "alerts": lambda: AC.alerts_columns(self.alerts),
            "alertdef": lambda: AC.alertdef_columns(self.alerts),
            "silences": lambda: AC.silences_columns(self.alerts),
            "inhibits": lambda: AC.inhibits_columns(self.alerts),
            "actions": lambda: AC.actions_columns(self.alerts),
            "notifymsg": lambda: self.notifylog.columns(self.names),
            "serverstatus": self._serverstatus_columns,
            "hostlist": self._hostlist_columns,
            "shardlist": self._shardlist_columns,
            "svcipclust": lambda: _dnsmap.annotate_vip_cols(
                self.natclusters.columns(self.names), self.dns),
            "tags": lambda: self.tags.columns(),
            "tracedef": lambda: self.tracedefs.columns(),
            "tracestatus": lambda: self.tracedefs.columns(),
            "traceuniq": self._traceuniq_columns,
            "traceconn": lambda: self.traceconns.columns(
                self.names, svc_task_ids=self._svc_task_ids()),
            "extactiveconn": lambda: self._ext_join("activeconn"),
            "extclientconn": lambda: self._ext_join("clientconn",
                                                    idcol="cliid"),
            "exttracereq": lambda: self._ext_join("tracereq"),
        }

    # ------------------------------------------------------------- ingest
    def _stack(self, builder, recs, lanes, count_path: bool = True):
        # the *_fast builders take a stats kwarg for the native-vs-
        # fallback decode counters; trace_batch (python-only) does not
        b = (lambda r, sz: builder(r, sz, stats=self.stats)) \
            if count_path else builder
        return sharded.put_sharded(self.mesh, sharded.shard_batches(
            self.cfg, self.mesh, (b, lanes), recs, recs["host_id"]))

    def feed(self, buf: bytes, hid: int = 0, conn_id: int = 0) -> int:
        """Byte stream → routed stacked batches → sharded folds."""
        data = (self._pending + buf) if self._pending else buf
        try:
            with self.stats.timeit("deframe"), \
                    self.spans.span("deframe", nrec=len(data),
                                    path="native" if native.available()
                                    else "python"):
                recs, consumed, unknown = native.drain2(data)
        except wire.FrameError:
            self.stats.bump("frames_bad")
            self._pending = b""
            raise
        self._pending = data[consumed:]
        # WAL append post-validation / pre-fold (see Runtime.feed)
        if (consumed and self.journal is not None
                and not self._journal_replaying):
            self.journal.append(data[:consumed], hid=hid,
                                conn_id=conn_id, tick=self._tick_no)
        if unknown:
            self.stats.bump("records_unknown_subtype", unknown)
        return self.ingest_records(recs)

    def ingest_records(self, recs: dict, shard=None) -> int:
        """Fold a drained ``{subtype: record array}`` dict — the
        post-deframe half of :meth:`feed`. The multi-process ingest
        supervisor (``net/ingestproc.py``) drains shared-memory ring
        slots through here with ``shard=`` set: the worker already
        routed the records by the layout's host hash, so conn/resp
        arrays go STRAIGHT into that shard's staging bucket (no
        re-hash, no argsort — the pre-routed fast path the per-shard
        rings exist for)."""
        n = 0
        self._cols.bump()
        # sweep-seq marks → per-host high-water mark (WAL dedup)
        sw = recs.pop(wire.NOTIFY_SWEEP_SEQ, None)
        if sw is not None and len(sw):
            for h, s in zip(sw["host_id"].tolist(), sw["seq"].tolist()):
                if s > self._sweep_last_seq.get(h, 0):
                    self._sweep_last_seq[h] = s
            self.stats.bump("sweep_marks", len(sw))
            n += len(sw)
        # conn/resp hot path: hash each record's host to its shard ONCE
        # and stage into per-shard buckets; a shard whose bucket fills a
        # slab (fold_k microbatches' worth) triggers ONE stacked
        # dispatch where every shard's lanes come from its own bucket
        conn = recs.pop(wire.NOTIFY_TCP_CONN, None)
        if conn is not None and len(conn):
            with self._reg_lock:
                self.natclusters.observe_conns(conn)
            if shard is None:
                self._stage_raw(self._conn_raw, self._conn_staged, conn)
            else:
                self._conn_raw[shard].append(conn)
                self._conn_staged[shard] += len(conn)
            self._n_conn_raw += len(conn)
            self.stats.bump("conn_events", len(conn))
            n += len(conn)
        resp = recs.pop(wire.NOTIFY_RESP_SAMPLE, None)
        if resp is not None and len(resp):
            hid = resp["host_id"]
            self._host_resp_tick[hid[hid < self.cfg.n_hosts]] = \
                self._tick_no
            if shard is None:
                self._stage_raw(self._resp_raw, self._resp_staged, resp)
            else:
                self._resp_raw[shard].append(resp)
                self._resp_staged[shard] += len(resp)
            self._n_resp_raw += len(resp)
            self.stats.bump("resp_events", len(resp))
            n += len(resp)
        slab_c = self.cfg.fold_k * self.cfg.conn_batch
        slab_r = self.cfg.fold_k * self.cfg.resp_batch
        while (max(self._conn_staged) >= slab_c
               or max(self._resp_staged) >= slab_r):
            self._dispatch_slab(slab_c, slab_r)
        for kind, *chunks in decode.drain_chunks(
                recs, self.cfg.conn_batch, self.cfg.resp_batch,
                self.cfg.listener_batch):
            if kind == "listener":
                self.state = self._fold_lst(self.state, self._stack(
                    decode.listener_batch_fast, chunks[0],
                    self.cfg.listener_batch))
                n += len(chunks[0])
            elif kind == "host":
                self.state = self._fold_host(self.state, self._stack(
                    decode.host_batch_fast, chunks[0],
                    wire.MAX_HOSTS_PER_BATCH))
                n += len(chunks[0])
            elif kind == "task":
                self.state = self._fold_task(self.state, self._stack(
                    decode.task_batch_fast, chunks[0],
                    wire.MAX_TASKS_PER_BATCH))
                n += len(chunks[0])
            elif kind == "ping":
                self.state = self._fold_ping(self.state, self._stack(
                    decode.ping_batch, chunks[0],
                    wire.MAX_PINGS_PER_BATCH))
                n += len(chunks[0])
                self.stats.bump("task_pings", len(chunks[0]))
            elif kind == "delta":
                bd = lambda r, sz: decode.delta_batch(  # noqa: E731
                    r, sz, stats=self.stats, **self._delta_dims)
                db = self._stack(bd, chunks[0],
                                 decode.DELTA_LANES_DEFAULT,
                                 count_path=False)
                self.state, self.dep = self._fold_delta(
                    self.state, self.dep, db,
                    np.int32(self._tick_no))
                n += len(chunks[0])
                self.stats.bump("preagg_delta_records",
                                len(chunks[0]))
            elif kind == "cpumem":
                self.state = self._fold_cm(self.state, self._stack(
                    decode.cpumem_batch_fast, chunks[0],
                    wire.MAX_CPUMEM_PER_BATCH))
                n += len(chunks[0])
            elif kind == "trace":
                with self._reg_lock:
                    self.traceconns.observe(chunks[0])
                self.state = self._fold_trace(self.state, self._stack(
                    decode.trace_batch, chunks[0],
                    wire.MAX_TRACE_PER_BATCH, count_path=False))
                n += len(chunks[0])
                if self.opts.trace_resp_bridge:
                    rs = decode.resp_from_trace(chunks[0])
                    # per-host precedence (see Runtime.feed): RECENT
                    # native resp streams win; the bridge fills gaps
                    from gyeeta_tpu.runtime import _RESP_FRESH_TICKS
                    hid = rs["host_id"]
                    fresh = (self._tick_no - self._host_resp_tick[
                        np.minimum(hid, self.cfg.n_hosts - 1)]
                        <= _RESP_FRESH_TICKS)
                    rs = rs[(hid >= self.cfg.n_hosts) | ~fresh]
                    if len(rs):
                        self._stage_raw(self._resp_raw,
                                        self._resp_staged, rs)
                        self._n_resp_raw += len(rs)
                        self.stats.bump("resp_from_trace", len(rs))
            elif kind == "listener_info":
                # registry updates under the registry lock — their
                # columns render on query worker threads in snapshot
                # mode (see Runtime.ingest_records)
                with self._reg_lock:
                    self.stats.bump("listener_infos",
                                    self.svcreg.update(chunks[0]))
                n += len(chunks[0])
            elif kind == "host_info":
                with self._reg_lock:
                    self.stats.bump("host_infos",
                                    self.hostinfo.update(chunks[0]))
                n += len(chunks[0])
            elif kind == "mount":
                with self._reg_lock:
                    self.stats.bump("mount_records",
                                    self.mounts.update(chunks[0]))
                n += len(chunks[0])
            elif kind == "netif":
                with self._reg_lock:
                    self.stats.bump("netif_records",
                                    self.netifs.update(chunks[0]))
                n += len(chunks[0])
            elif kind == "cgroup":
                with self._reg_lock:
                    self.stats.bump("cgroup_records",
                                    self.cgroups.update(chunks[0]))
                n += len(chunks[0])
            elif kind == "agent_stats":
                # agent delivery-continuity deltas → server counters
                # (same fold as Runtime.ingest_records)
                a = chunks[0]
                for fld, ctr in (
                        ("spool_dropped", "spool_dropped"),
                        ("spool_dropped_records",
                         "spool_dropped_records"),
                        ("spool_resent", "spool_resent"),
                        ("connect_timeouts", "agent_connect_timeouts")):
                    tot = int(a[fld].sum())
                    if tot:
                        self.stats.bump(ctr, tot)
            elif kind == "names":
                with self._reg_lock:
                    self.stats.bump("names_interned",
                                    self.names.update(chunks[0]))
        return n

    def _stage_raw(self, buckets: list, counts: list, recs) -> None:
        """Hash each record's host to its shard (the layout's stable
        ingest-edge rule) and append the per-shard slices — one stable
        argsort per record array, so within-shard arrival order is
        exactly what the pre-routed fold sees (bit-parity with the
        route-at-dispatch path)."""
        if self.n == 1:
            buckets[0].append(recs)
            counts[0] += len(recs)
            return
        dest = np.asarray(
            self.layout.shard_of_host(recs["host_id"].astype(np.int64)))
        order = np.argsort(dest, kind="stable")
        recs = recs[order]
        bounds = np.searchsorted(dest[order], np.arange(self.n + 1))
        for s in range(self.n):
            a, b = int(bounds[s]), int(bounds[s + 1])
            if b > a:
                buckets[s].append(recs[a:b])
                counts[s] += b - a

    def _take_shard_raw(self, buckets: list, counts: list, lanes: int,
                        dtype) -> list:
        """Pop up to ``lanes`` records off EVERY shard's bucket."""
        out = []
        for s in range(self.n):
            got = decode.take_raw(buckets[s], lanes, dtype)
            counts[s] -= len(got)
            out.append(got)
        return out

    def _dispatch_slab(self, lanes_c: int, lanes_r: int) -> None:
        """Decode + fold up to a slab of staged raw records PER SHARD
        in one stacked dispatch. Records were routed at staging time,
        so each shard's lanes build straight from its own bucket."""
        crecs = self._take_shard_raw(self._conn_raw, self._conn_staged,
                                     lanes_c, wire.TCP_CONN_DT)
        rrecs = self._take_shard_raw(self._resp_raw, self._resp_staged,
                                     lanes_r, wire.RESP_SAMPLE_DT)
        nc = sum(len(x) for x in crecs)
        nr = sum(len(x) for x in rrecs)
        self._n_conn_raw -= nc
        self._n_resp_raw -= nr
        for s in range(self.n):
            self._shard_events[s] += len(crecs[s]) + len(rrecs[s])
        with self.stats.timeit("fold_dispatch"), \
                self.spans.span("decode_fold",
                                nrec=nc + nr,
                                path="native" if native.available()
                                else "python"):
            b = lambda r, sz: decode.conn_batch_fast(  # noqa: E731
                r, sz, stats=self.stats)
            cbs = self.layout.put(
                sharded.stack_prerouted((b, lanes_c), crecs))
            b = lambda r, sz: decode.resp_batch_fast(  # noqa: E731
                r, sz, stats=self.stats)
            rbs = self.layout.put(
                sharded.stack_prerouted((b, lanes_r), rrecs))
            # previous dispatch's pressure scalar is ready by now:
            # flush the fullest per-shard stages before folding if
            # headroom is low
            if (self._pressure is not None
                    and int(self._pressure) > self.cfg.td_stage_cap // 2):
                self.state = self._td_flush(self.state)
                self.stats.bump("td_partial_flushes")
            if self._fused:
                # ONE fused dispatch: fold + dep (a2a pairing) +
                # pressure output — no observation dispatch
                fn = self._fold_dep_slab if lanes_c > self.cfg.conn_batch \
                    else self._fold_dep_chunk
                self.state, self.dep, self._pressure = fn(
                    self.state, self.dep, cbs, rbs,
                    np.int32(self._tick_no))
                self.stats.bump("fold_dispatches")
            else:
                self.state = self._fold(self.state, cbs, rbs)
        self._profiler.on_fold()      # GYT_JAX_PROFILE bracket (opt-in)
        self._td_dirty = True
        if not self._fused:
            self._pressure = self._td_pressure(self.state)
            dep_fn = self._dep_slab if lanes_c > self.cfg.conn_batch \
                else self._dep_step
            self.dep = dep_fn(self.dep, cbs, np.int32(self._tick_no))

    def flush(self) -> int:
        """Fold staged raw leftovers (chunk-width dispatches) — state
        is fully query-ready afterwards. Called at every tick/query
        boundary."""
        folded = self._n_conn_raw + self._n_resp_raw
        if folded:
            # evict BEFORE the donating dispatches: cached zero-copy
            # shard views must never alias a donated buffer. (The
            # single-node twin bumps AFTER its folds — safe there
            # because its closures hold jax arrays that error loudly
            # if ever read post-donation, and the single thread has no
            # read window mid-flush; numpy views would read reused
            # memory SILENTLY, so this path evicts up front.)
            self._cols.bump()
        while self._n_conn_raw or self._n_resp_raw:
            self._dispatch_slab(self.cfg.conn_batch,
                                self.cfg.resp_batch)
        return folded

    # ---------------------------------------------------- merged columns
    @staticmethod
    def _shard_leaf(x, s: int):
        """Leaf slice for shard s, read from its addressable buffer
        directly — no cross-device XLA gather, no host transfer."""
        if hasattr(x, "addressable_shards"):
            for sh in x.addressable_shards:
                idx = sh.index[0] if sh.index else None
                if (isinstance(idx, slice) and idx.start is not None
                        and idx.stop is not None
                        and idx.start <= s < idx.stop):
                    if sh.data.platform() == "cpu":
                        # zero-copy host view (see _shard_state for
                        # the lifetime discipline)
                        return np.asarray(sh.data)[s - idx.start]
                    # accelerator: slice stays on-device
                    return sh.data[s - idx.start]
        return np.asarray(x)[s]

    def _shard_state(self, s: int, state=None, cache=None):
        """Shard s's full state slice for the per-shard column
        providers (``state``/``cache`` default to the LIVE state and
        column memo; the time-travel tier passes a shard-materialized
        state and its snapshot-scoped cache).

        On the CPU platform the slice is a zero-copy NUMPY VIEW of the
        shard's buffer (measured: eager jnp slicing costs ~26-430 ms
        PER LEAF in dispatch overhead — ~10 s per merge at the 51k
        geometry, the r5 post-tick cold-query profile; the view is
        0.01 ms). Views alias device buffers, so they must never
        outlive a donating fold: ColumnCache holds them (here and in
        the providers' LazyCols closures) and ``feed`` bumps/evicts at
        entry, BEFORE any donating dispatch — queries and feeds share
        one thread, so no view survives into a fold. On accelerators
        the device-side slice path keeps data on-chip."""
        state = self.state if state is None else state
        cache = self._cols if cache is None else cache
        return cache.get(
            f"__shard_state_{s}",
            lambda: jax.tree.map(lambda x: self._shard_leaf(x, s),
                                 state))

    def _hosts_ever_reported(self, s: int) -> np.ndarray:
        """Shard s's ``host_last_tick`` as a host array — the single
        definition of "has ever reported" (last tick >= 0), shared by
        hostlist and serverstatus so the two can't diverge."""
        return np.asarray(self._shard_leaf(self.state.host_last_tick, s))

    def _merged_columns(self, subsys: str):
        """Cluster-wide (cols, mask), version-cached: the per-shard
        snapshot gather recomputes only after state actually changed
        (feed/tick/td-flush bump the cache version) — between ticks
        queries serve from the cached merge (query freshness, VERDICT
        r3 weak #4). Registry/CRUD-backed aux views are never cached
        (they mutate without a version bump)."""
        if "@" in subsys:
            # subsys@window: an alertdef with a window field evaluates
            # against the time-travel tier's windowed aggregate
            base, _, win = subsys.partition("@")
            if self.timeview is None:
                raise ValueError(
                    "windowed alertdef needs history shards "
                    "(hist_shard_dir)")
            return self.timeview.window_columns_for(base, win)
        if subsys in self._aux:
            return self._aux[subsys]()
        out = self._cols.get(
            subsys, lambda: self._merged_columns_uncached(subsys))
        if subsys == fieldmaps.SUBSYS_PROCINFO:
            # joined OUTSIDE the cache: tags mutate via CRUD without a
            # state version bump
            out = self.tags.with_tags(out)
        return out

    def _merged_columns_uncached(self, subsys: str):
        return self._merged_columns_state(subsys, self.state, self.dep,
                                          self._cols, live=True)

    def _merged_columns_state(self, subsys: str, state, dep, cache,
                              live: bool = False, reg: bool = False):
        """Per-shard provider outputs concatenated, or collective-
        rollup-backed for global subsystems — parameterized on
        (state, dep, cache) so the SAME pipeline serves the live mesh
        AND shard-materialized historical snapshots
        (``history/timeview.py``) AND the per-tick published snapshot
        (``query/snapshot.py``). ``live`` routes recursive lookups
        through the top-level cached path and keeps registry-backed
        joins (which have no historical source) available; ``reg``
        keeps the registry joins available over a NON-live state (the
        published snapshot: engine columns frozen, registries live)."""
        if live:
            def get(s):
                return self._merged_columns(s)
        else:
            def get(s):
                return cache.get(
                    s, lambda: self._merged_columns_state(
                        s, state, dep, cache, reg=reg))
        if subsys == fieldmaps.SUBSYS_SVCINFO:
            if not (live or reg):
                raise ValueError(
                    "svcinfo is registry-backed — not available "
                    "historically")
            return self.svcreg.columns(self.names)
        if subsys == fieldmaps.SUBSYS_SVCSUMM:
            # group AFTER merging: one host's services span shards
            cols, live_m = get(fieldmaps.SUBSYS_SVCSTATE)
            return api.svcsumm_from_svc(cols, live_m, self.names)
        if subsys == fieldmaps.SUBSYS_EXTSVCSTATE:
            if not (live or reg):
                raise ValueError(
                    "extsvcstate joins the live registry — not "
                    "available historically")
            cols, live_m = get(fieldmaps.SUBSYS_SVCSTATE)
            info_cols, _ = self.svcreg.columns(self.names)
            return api.extsvc_join(cols, live_m, info_cols)
        if subsys == fieldmaps.SUBSYS_SVCPROCMAP:
            if not (live or reg):
                raise ValueError(
                    "svcprocmap joins the live registry — not "
                    "available historically")
            tcols, tlive = get(fieldmaps.SUBSYS_TASKSTATE)
            info_cols, _ = self.svcreg.columns(self.names)
            return api.svcprocmap_join(tcols, tlive, info_cols)
        if subsys in (fieldmaps.SUBSYS_SVCDEP, fieldmaps.SUBSYS_SVCMESH,
                      fieldmaps.SUBSYS_ACTIVECONN,
                      fieldmaps.SUBSYS_CLIENTCONN):
            # run_tick seeds __edgeset from the fleet-rollup collective;
            # a miss (between-tick mutation, historical state) pays the
            # standalone edge-rollup dispatch
            es = cache.get("__edgeset", lambda: self._edge_roll(dep))
            return self._dep_cols_from_edgeset(subsys, es,
                                               state=state, cache=cache)
        if subsys == fieldmaps.SUBSYS_FLOWSTATE:
            ru = cache.get("__rollup", lambda: self._rollup(state))
            k = min(128, int(ru.flow_topk.counts.shape[0]))
            f_hi, f_lo, f_bytes = topk.query(ru.flow_topk, k)
            f_hi, f_lo = np.asarray(f_hi), np.asarray(f_lo)
            f_bytes = np.asarray(f_bytes)
            cols = {
                "flowid": api._hex_id(f_hi, f_lo),
                "bytes": f_bytes,
                "evictedbytes": np.full(len(f_bytes),
                                        float(ru.flow_topk.evicted)),
            }
            return cols, f_bytes > 0
        if subsys == fieldmaps.SUBSYS_CLUSTERSTATE:
            from gyeeta_tpu.semantic import hoststate as HS
            hcols, reported = get(fieldmaps.SUBSYS_HOSTSTATE)
            c = HS.cluster_state(np.asarray(hcols["state"]),
                                 valid=reported)
            return ({k: np.array([float(v)]) for k, v in c.items()},
                    np.ones(1, bool))
        provider = api._COLUMNS_OF[subsys]
        parts = [provider(self.cfg,
                          self._shard_state(s, state, cache),
                          names=self.names)
                 for s in range(self.n)]
        from gyeeta_tpu.query.lazycols import LazyCols, merge_lazy
        if all(isinstance(p[0], LazyCols) for p in parts):
            # lazy groups concatenate on first reference — a sharded
            # query reads only the groups its filter/sort names
            cols = merge_lazy([p[0] for p in parts],
                              widths=[len(p[1]) for p in parts])
        else:
            cols = {k: np.concatenate([p[0][k] for p in parts])
                    for k in parts[0][0]}
        mask = np.concatenate([p[1] for p in parts])
        return cols, mask

    def _gathered_task_names(self, hi, lo, state=None, cache=None):
        """Resolve task-group callers via the gathered task slabs."""
        keys, comms, lives = [], [], []
        for s in range(self.n):
            k, c, lv = api._task_slab_arrays(
                self._shard_state(s, state, cache))
            keys.append(k)
            comms.append(c)
            lives.append(lv)
        return api.task_comm_names_from(
            self.names, np.concatenate(keys), np.concatenate(comms),
            np.concatenate(lives), hi, lo)

    def _dep_cols_from_edgeset(self, subsys: str, es, state=None,
                               cache=None):
        from gyeeta_tpu.engine import table

        if subsys in (fieldmaps.SUBSYS_ACTIVECONN,
                      fieldmaps.SUBSYS_CLIENTCONN):
            snap = {
                "e_live": np.asarray(table.live_mask(es.tbl)),
                "e_cli_hi": np.asarray(es.cli_hi),
                "e_cli_lo": np.asarray(es.cli_lo),
                "e_ser_hi": np.asarray(es.ser_hi),
                "e_ser_lo": np.asarray(es.ser_lo),
                "e_nconn": np.asarray(es.nconn),
                "e_bytes": np.asarray(es.byts),
                "e_cli_svc": np.asarray(es.cli_svc),
            }
            if subsys == fieldmaps.SUBSYS_CLIENTCONN:
                return api.clientconn_from_edges(
                    snap, self.names,
                    lambda hi, lo: self._gathered_task_names(
                        hi, lo, state, cache))
            return api.activeconn_from_edges(snap, self.names)
        if subsys == fieldmaps.SUBSYS_SVCMESH:
            cap = 2 * es.nconn.shape[0]
            ntbl, labels, sizes = self._mesh_clusters(es, cap)
            n_hi, n_lo = np.asarray(ntbl.key_hi), np.asarray(ntbl.key_lo)
            cols = {
                "svcid": api._hex_id(n_hi, n_lo),
                "svcname": api._names_of(self.names, wire.NAME_KIND_SVC,
                                         n_hi, n_lo),
                "clusterid": np.asarray(labels),
                "clustersize": np.asarray(sizes),
            }
            return cols, np.asarray(table.live_mask(ntbl))
        live = np.asarray(table.live_mask(es.tbl))
        cli_hi, cli_lo = np.asarray(es.cli_hi), np.asarray(es.cli_lo)
        ser_hi, ser_lo = np.asarray(es.ser_hi), np.asarray(es.ser_lo)
        cli_svc = np.asarray(es.cli_svc)
        svc_names = api._names_of(self.names, wire.NAME_KIND_SVC,
                                  cli_hi, cli_lo)
        # task→svc callers resolve via the gathered task slabs (comm join)
        task_names = self._gathered_task_names(cli_hi, cli_lo, state,
                                               cache)
        cols = {
            "cliid": api._hex_id(cli_hi, cli_lo),
            "cliname": np.where(cli_svc, svc_names, task_names),
            "clisvc": cli_svc,
            "serid": api._hex_id(ser_hi, ser_lo),
            "sername": api._names_of(self.names, wire.NAME_KIND_SVC,
                                     ser_hi, ser_lo),
            "nconn": np.asarray(es.nconn),
            "bytes": np.asarray(es.byts),
        }
        return cols, live

    # -------------------------------------------------- heavy hitters
    def heavy_recover(self) -> dict:
        """Cluster-wide heavy-hitter recovery: the rollup collective
        decodes every shard's invertible buckets, gathers the
        candidates across shards (`all_gather`, the madhava→shyama
        candidate pull) and estimates each against the globally-merged
        CMS; the host merges with the merged exact top-K lanes. One
        collective dispatch + one small readback per tick."""
        from gyeeta_tpu.sketch import invertible

        self.flush()
        with self.stats.timeit("topk_recover"):
            ru = self._cols.get("__rollup",
                                lambda: self._rollup(self.state))
            rec = {
                "topk_hi": np.asarray(ru.flow_topk.key_hi),
                "topk_lo": np.asarray(ru.flow_topk.key_lo),
                "topk_counts": np.asarray(ru.flow_topk.counts),
                "topk_est": np.asarray(ru.hh_topk_est),
                "hh_hi": np.asarray(ru.hh_hi),
                "hh_lo": np.asarray(ru.hh_lo),
                "hh_ok": np.asarray(ru.hh_ok),
                "hh_est": np.asarray(ru.hh_est),
            }
            evicted = float(np.asarray(ru.flow_topk.evicted))
            total = float(np.asarray(ru.hh_total_mass))
        self.stats.bump("topk_recover_readbacks")
        err_term = invertible.cms_error_term(total, self.cfg.cms_width)
        hot_thresh = (self.cfg.hh_hot_frac * total
                      if self.cfg.hh_hot_frac > 0 else 0.0)
        flows, recovered, hot = invertible.merge_recovered_np(
            rec, err_term, hot_thresh)
        new_hot = hot - self._hh_prev_hot
        if new_hot:
            self.stats.bump("topk_hot_promotions", len(new_hot))
        self._hh_prev_hot = hot
        self.stats.gauge("topk_recovered_keys", float(len(recovered)))
        self.stats.gauge("topk_evicted_mass", evicted)
        return {"flows": flows, "recovered_keys": len(recovered),
                "evicted": evicted, "err_term": err_term,
                "total_mass": total, "new_hot": len(new_hot)}

    def _topk_columns(self):
        """topk subsystem over the mesh: cluster-wide heavy flows
        (rollup recovery) + dense rankings over the MERGED svc/api
        columns — the same union builder as the single-node runtime."""
        rec = self._cols.get("__hh_recover", self.heavy_recover)
        return api.heavy_topk_columns(
            rec["flows"],
            svc=self._merged_columns(fieldmaps.SUBSYS_SVCSTATE),
            trace=self._merged_columns(fieldmaps.SUBSYS_TRACEREQ))

    def _hostlist_columns(self):
        """hostlist over the mesh: each shard's host panel holds only
        its routed hosts (global ids), so concatenating the seen rows
        of every shard yields the cluster host list."""
        parts_id, parts_age = [], []
        for s in range(self.n):
            last = self._hosts_ever_reported(s)
            seen = np.nonzero(last >= 0)[0]
            parts_id.append(seen)
            parts_age.append(self._tick_no - last[seen])
        ids = np.concatenate(parts_id)
        age = np.concatenate(parts_age)
        order = np.argsort(ids, kind="stable")
        ids, age = ids[order], age[order]
        from gyeeta_tpu.ingest import wire as W
        names = self.names.resolve_array(W.NAME_KIND_HOST,
                                         ids.astype(np.uint64))
        cols = {
            "hostid": ids.astype(np.float64),
            "hostname": names,
            "up": age <= api.DOWN_AFTER_TICKS,
            "lastseen": age.astype(np.float64),
        }
        return cols, np.ones(len(ids), bool)

    def _ext_join(self, base_subsys: str, idcol: str = "svcid"):
        cols, live = self._merged_columns(base_subsys)
        info_cols, _ = self.svcreg.columns(self.names)
        return api.info_join(cols, live, info_cols, idcol=idcol)

    def _svc_task_ids(self):
        """Hex process-group ids serving a listener (traceconn csvc)."""
        cols, live = self._merged_columns(fieldmaps.SUBSYS_TASKSTATE)
        zero = "0" * 16
        return {t for t, r, ok in zip(cols["taskid"], cols["relsvcid"],
                                      live) if ok and r != zero}

    def _traceuniq_columns(self):
        tcols, tlive = self._merged_columns(fieldmaps.SUBSYS_TRACEREQ)
        return api.traceuniq_from_trace(tcols, tlive)

    def trace_control_diff(self, hosts=None):
        """Mesh analogue of Runtime.trace_control_diff: evaluate
        tracedefs against the (registry-backed) svcinfo inventory."""
        targets = self.tracedefs.target_svcids(self._merged_columns)
        return self.tracedefs.diff_for_hosts(targets, hosts=hosts)

    def _shardlist_columns(self):
        """One row per mesh shard (the madhavalist analogue): live
        rows, hosts, fold counters, and drop diagnostics per shard."""
        rows = []
        for sidx in range(self.n):
            st = self._shard_state(sidx)
            rows.append({
                "shard": float(sidx),
                "nsvc": float(np.asarray(st.tbl.n_live)),
                "nhosts": float((np.asarray(st.host_last_tick) >= 0)
                                .sum()),
                "nconn": float(np.asarray(st.n_conn)),
                "nresp": float(np.asarray(st.n_resp)),
                "ntaskrows": float(np.asarray(st.task_tbl.n_live)),
                "ndropped": float(np.asarray(st.tbl.n_drop)
                                  + np.asarray(st.task_tbl.n_drop)),
            })
        cols = {k: np.array([r[k] for r in rows], np.float64)
                for k in rows[0]}
        return cols, np.ones(self.n, bool)

    def _serverstatus_columns(self):
        from gyeeta_tpu import version as V

        ru = self._cols.get("__rollup",
                            lambda: self._rollup(self.state))
        c = self.stats.counters
        obj = lambda v: np.array([v], object)  # noqa: E731
        num = lambda v: np.array([float(v)], np.float64)  # noqa: E731
        # "hosts that have EVER reported" (same quantity the single-node
        # runtime reports) — each shard's host panel holds only its own
        # routed hosts, so the per-shard counts are disjoint and sum
        nhosts = sum(int((self._hosts_ever_reported(s) >= 0).sum())
                     for s in range(self.n))
        cols = {
            "uptime": num(self._clock() - self._t_started),
            "tick": num(self._tick_no),
            "nhosts": num(float(nhosts)),
            "nsvc": num(float(ru.n_svc_live)),
            # exact host-side int counters, same as the single-node path
            "connevents": num(c.get("conn_events", 0)),
            "respevents": num(c.get("resp_events", 0)),
            "queries": num(c.get("queries", 0)),
            "alertsfired": num(self.alerts.stats.get("nfired", 0)),
            "wirever": num(V.CURR_WIRE_VERSION),
            "version": obj(V.__version__),
        }
        return cols, np.ones(1, bool)

    # ----------------------------------------------------- snapshot tier
    def publish_snapshot(self):
        """Freeze the stacked mesh state into an immutable
        :class:`~gyeeta_tpu.query.snapshot.EngineSnapshot` (see
        ``Runtime.publish_snapshot`` — same double-buffer contract; the
        copied leaves keep their shardings, so the merged-columns
        pipeline and the rollup collectives serve the frozen view
        unchanged)."""
        from gyeeta_tpu.query.snapshot import EngineSnapshot
        from gyeeta_tpu.runtime import snapshot_copy
        with self.stats.timeit("snapshot_publish"):
            state, dep = snapshot_copy(self, (self.state, self.dep))
        self._snap_version += 1
        snap = EngineSnapshot(
            self, state, dep, tick=self._tick_no,
            published_at=self._clock(), version=self._snap_version,
            result_cache_max=int(os.environ.get(
                "GYT_QUERY_CACHE_MAX", "1024")))
        # ping-pong donation candidate (see Runtime.publish_snapshot —
        # only retained when the flag is on)
        self._snap_old = self.snapshot if self._snap_pingpong else None
        self.snapshot = snap
        self.stats.bump("snapshots_published")
        self.stats.gauge("snapshot_tick", float(self._tick_no))
        self.stats.gauge("snapshot_age_seconds", 0.0)
        return snap

    # ------------------------------------------------------------ cadence
    def td_drain(self, max_iters: int | None = None) -> int:
        """Drain per-shard digest stages with O(m) partial flushes
        against the global pressure scalar — same host-trigger design
        as the single-chip runtime (no in-graph cond; see
        ``Runtime.td_drain``). Unbounded by default; ``run_tick``
        bounds it to amortize a fully-active slab across ticks. No
        query subsystem reads the digest, so this is off the <1s
        query path."""
        self.flush()
        # the flushes below DONATE state: cached zero-copy shard views
        # (and LazyCols closures) from the current version must be
        # evicted BEFORE the first donating dispatch, or a later
        # cache-hit query would read reused buffers
        self._cols.bump()
        i = 0
        while max_iters is None or i < max_iters:
            if int(self._td_pressure(self.state)) <= 0:
                self._td_dirty = False
                self._pressure = None
                break
            self.state = self._td_flush(self.state)
            self.stats.bump("td_partial_flushes")
            i += 1
        return i

    def _shard_rate_gauges(self) -> None:
        """Per-shard fold rates + staged-slab occupancy at tick cadence
        (host-side counters only — no device readback). Rendered as
        ``gyt_shard_fold_ev_per_sec{shard=...}`` and
        ``gyt_shard_stage_occupancy{shard=...}``."""
        now = self._clock()
        dt = max(now - self._shard_rate_t, 1e-9)
        delta = self._shard_events - self._shard_rate_mark
        for s in range(self.n):
            self.stats.gauge(f"shard_fold_ev_per_sec|shard={s}",
                             round(float(delta[s]) / dt, 1))
        cap = max(1, self.cfg.fold_k
                  * (self.cfg.conn_batch + self.cfg.resp_batch))
        for s in range(self.n):
            occ = (self._conn_staged[s] + self._resp_staged[s]) / cap
            self.stats.gauge(f"shard_stage_occupancy|shard={s}",
                             round(occ, 4))
        self._shard_rate_t = now
        self._shard_rate_mark = self._shard_events.copy()

    def engine_health(self, vec=None) -> dict:
        """Cluster-wide device-health gauges (sums over every shard's
        slabs; max stage pressure) — the sharded twin of
        ``Runtime.engine_health``, folded into the same ``Stats`` gauge
        names so /metrics parity holds across runtimes. ``run_tick``
        passes the fleet-rollup collective's health vector; standalone
        callers (scrapes between ticks) pay one batched readback."""
        if vec is None:
            vec = np.asarray(self._engine_health(self.state, self.dep))
        gauges = obs_health.gauges_from_vec(
            vec, obs_health.capacities(self.cfg, self.opts,
                                       n_shards=self.n))
        gauges["native_decode_available"] = \
            1.0 if native.available() else 0.0
        if self.journal is not None:
            gauges.update(self.journal.gauges())
        for k, v in gauges.items():
            self.stats.gauge(k, v)
        return gauges

    def run_tick(self) -> dict:
        with self.stats.timeit("tick"), self.spans.span(
                "tick", nrec=self._tick_no):
            return self._run_tick()

    def _run_tick(self) -> dict:
        """Sharded 5s pass: classify → alerts on merged columns → window
        tick → ageing."""
        report = {}
        self.flush()
        if self._td_dirty:    # tick-cadence digest compression (bounded)
            self.td_drain(max_iters=self.opts.td_drain_iters_per_tick)
        self.state = self._classify(self.state)
        self._cols.bump()
        # publish the post-classify view and route alert evaluation
        # through it — tick-time work pre-warms the snapshot's merged
        # columns for the dashboards (see Runtime._run_tick)
        snap = self.publish_snapshot()
        # ---- the once-per-tick cross-shard roll-up: cluster rollup +
        # merged dep edges + health vector in ONE collective program
        # over the FROZEN snapshot leaves. Both the snapshot's and the
        # live column cache are seeded from its outputs, so svcdep/
        # flowstate/serverstatus/topk queries and alertdefs this window
        # reuse the tick's collective instead of re-dispatching.
        t_ru = self._clock()
        with self.stats.timeit("rollup"):
            fv = self._fleet_roll(snap.state, snap.dep)
            health_vec = np.asarray(fv.health)
        self.stats.gauge("rollup_seconds",
                         round(self._clock() - t_ru, 6))
        for cache in (snap._cols, self._cols):
            cache.get("__rollup", lambda: fv.rollup)
            cache.get("__edgeset", lambda: fv.edges)
        # per-tick heavy-hitter recovery (memoized — an alertdef on
        # `topk` and queries until the next feed reuse the readback)
        ev = self.opts.hh_recover_every_ticks
        if ev and self.cfg.hh_width > 0 \
                and (self._tick_no + 1) % ev == 0:
            report["topk_recovered"] = self._cols.get(
                "__hh_recover", self.heavy_recover)["recovered_keys"]
        # alert eval short-circuits BEFORE any column render when no
        # realtime def is enabled (counted; pending group-wait batches
        # still flush on schedule)
        if self.alerts.wants_realtime():
            fired = self.alerts.check(None, columns_fn=snap.columns)
        else:
            self.stats.bump("alert_eval_skipped")
            fired = self.alerts.flush_groups()
        report["alerts_fired"] = len(fired)
        for a in fired:
            self.notifylog.add_alert(a)
        self._tick_no += 1
        report["tick"] = self._tick_no
        # device health from the SAME collective (no extra readback);
        # the drop-pressure signal (VERDICT r4 #10) feeds off the vector
        from gyeeta_tpu.utils import droppressure
        health = self.engine_health(vec=health_vec)
        self._shard_rate_gauges()
        self._last_drops = droppressure.check(
            obs_health.drops_for_pressure(health),
            {"svc": self.cfg.svc_capacity,
             "task": self.cfg.task_capacity,
             "api": self.cfg.api_capacity,
             "dep": self.opts.dep_pair_capacity},
            getattr(self, "_last_drops", {}),
            self.notifylog, self.stats)
        self.state = self._tick(self.state)
        if self._tick_no % self.opts.task_age_every_ticks == 0:
            self.state = self._age_tasks(self.state)
            self.state = self._age_apis(self.state)
        self.dep = self._dep_age(self.dep, np.int32(self._tick_no))
        with self._reg_lock:      # ageing structurally mutates the
            self.cgroups.age()    # registries snapshot aux renders
            self.mounts.age()     # iterate on worker threads
            self.netifs.age()
            self.natclusters.age()
            self.traceconns.age()
        # journal fsync cadence backstop + checkpoint-with-WAL-position
        # (same durability contract as the single-node Runtime: the
        # checkpoint records the fsynced journal position and
        # supersedes older segments)
        if self.journal is not None:
            self.journal.poll()
        if (self.opts.checkpoint_dir
                and self._tick_no % self.opts.checkpoint_every_ticks
                == 0):
            from gyeeta_tpu.utils import checkpoint as ckpt
            from gyeeta_tpu.utils import journal as J
            extra = J.checkpoint_extra(self, self._tick_no)
            path = ckpt.save(
                f"{self.opts.checkpoint_dir}/"
                f"gyt_ckpt_{self._tick_no:08d}.npz",
                self.cfg, self.state, extra=extra)
            J.post_checkpoint_truncate(self, extra)
            report["checkpoint"] = str(path)
            self.stats.bump("checkpoints")
        # the window tick / ageing above changed every view
        self._cols.bump()
        return report

    # -------------------------------------------------------------- query
    def crud(self, req: dict) -> dict:
        from gyeeta_tpu.query import crud as CR
        with self._reg_lock:
            out = CR.crud(self, req)
        snap = self.snapshot          # CRUD invalidates aux views
        if snap is not None:
            snap.on_mutation()
        return out

    def query(self, req: dict) -> dict:
        if req.get("op"):
            return self.crud(req)
        if "multiquery" in req:
            from gyeeta_tpu.query import crud as CR
            return CR.multiquery(self.query, req)
        if req.get("consistency") == "snapshot":
            return self.query_snapshot(req)
        if "consistency" in req:
            req = dict(req)
            if req.pop("consistency") != "strong":
                raise ValueError(
                    "consistency must be 'snapshot' or 'strong'")
        # process-local subsystems (selfstats + metrics exposition) —
        # shared routing with the single-node Runtime (api.py)
        out = api.local_response(self, req)
        if out is not None:
            return out
        # time-travel tier: at=/window=/tstart/tend materialize
        # compaction shards (the mesh has no relational store, so every
        # historical request routes here)
        from gyeeta_tpu.history.timeview import route_historical
        out = route_historical(self, req)
        if out is not None:
            return out
        self.stats.bump("queries")
        self.flush()          # live queries see all staged records
        with self.stats.timeit("query"):
            return api.execute(self.cfg, None, QueryOptions.from_json(req),
                               names=self.names,
                               columns_fn=self._merged_columns)

    def query_snapshot(self, req: dict) -> dict:
        """Serve a live query from the last published snapshot (no
        flush, no fold-path dispatch; safe from worker threads) — the
        mesh twin of ``Runtime.query_snapshot``."""
        req = {k: v for k, v in req.items() if k != "consistency"}
        snap = self.snapshot
        if snap is None:
            snap = self.publish_snapshot()
        if req.get("subsys") in api.LOCAL_SUBSYS:
            return api.local_response(self, req, snapshot=snap)
        from gyeeta_tpu.history.timeview import route_historical
        out = route_historical(self, req)
        if out is not None:
            return out
        self.stats.bump("queries")
        with self.stats.timeit("query"):
            return snap.query(req)

    def close(self) -> None:
        """Release background workers (alert delivery, DNS resolver).
        Idempotent — mirrors Runtime.close()."""
        self._profiler.close()
        self.alerts.close()
        self.dns.close()
        if self.journal is not None:
            self.journal.close()      # fsync + close (idempotent)

    # -------------------------------------------------- restore/recovery
    def restore(self, path) -> dict:
        """Restore a checkpoint saved by a SAME-GEOMETRY mesh run (the
        stacked ``(n_shards, …)`` leaves re-shard onto this mesh).
        Mirrors ``Runtime.restore``: staged records and partial-frame
        bytes from before the restore are dropped (folding them into
        checkpointed state would double-count)."""
        from gyeeta_tpu.utils import checkpoint as ckpt

        self._conn_raw = [[] for _ in range(self.n)]
        self._resp_raw = [[] for _ in range(self.n)]
        self._conn_staged = [0] * self.n
        self._resp_staged = [0] * self.n
        self._n_conn_raw = self._n_resp_raw = 0
        self._pending = b""
        self._cols.bump()
        self._cols.clear()
        self._td_dirty = True
        self._pressure = None
        state_np, extra = ckpt.restore(path, self.cfg, self.state)
        # re-shard every leaf with its live counterpart's sharding (the
        # checkpoint stores gathered host arrays; shapes were already
        # validated against this mesh's stacked geometry)
        self.state = jax.tree_util.tree_map(
            lambda a, ref: jax.device_put(a, ref.sharding),
            state_np, self.state)
        # the dep graph is not checkpointed: reset (edges rebuild from
        # live traffic), placed per the layout like __init__
        self.dep = self.layout.put(
            jax.tree.map(
                lambda x: np.broadcast_to(
                    np.asarray(x)[None], (self.n,) + np.asarray(x).shape),
                dg.init(self.opts.dep_pair_capacity,
                        self.opts.dep_edge_capacity)))
        self._tick_no = int(extra.get("tick", 0))
        self._sweep_last_seq = {
            int(k): int(v)
            for k, v in extra.get("sweep_seq", {}).items()}
        # republish over the restored view (see Runtime.restore)
        if self.snapshot is not None:
            self.publish_snapshot()
        return extra

    def replay_journal(self, pos=None) -> dict:
        """Re-fold WAL chunks from ``pos`` through the normal
        decode/fold path (chunks journal once at the mesh's single
        ingest edge; ``feed`` routes records per-shard by host_id, so
        replay is per-shard by construction)."""
        from gyeeta_tpu.utils import journal as J
        return J.replay_journal(self, pos)

    def rollup_stats(self) -> dict:
        """Replicated cluster totals (the MS_CLUSTER_STATE analogue)."""
        self.flush()          # staged slab records must count
        ru = self._rollup(self.state)
        return {
            "n_conn": float(ru.n_conn), "n_resp": float(ru.n_resp),
            "n_svc_live": float(ru.n_svc_live),
            "n_hosts_up": float(ru.n_hosts_up),
        }
