"""Top-K heavy hitters over unbounded 64-bit key spaces, as tensors.

Replaces the reference's ``BOUNDED_PRIO_QUEUE`` top-K rankings
(``common/gy_statistics.h:29``; used for top-CPU/QPS/net listeners,
``gy_task_handler.cc:655-756``) in the unbounded-key regime (flow tuples,
remote endpoints). For *dense* tracked entities (service rows) use
``dense_topk`` — a plain ``lax.top_k`` over the stat column.

Algorithm (Misra-Gries-style truncation, fully vectorized):
  1. concat candidate table with the microbatch's (key, value) lanes,
  2. group equal 64-bit keys adjacently with a two-pass stable radix
     sort (argsort by lo, then stable argsort by hi) — two single-key
     sorts are the TPU-fast path; a measured multi-key ``lax.sort`` on
     u32 pairs lowered ~200× slower. Exact lexicographic grouping, no
     hash-collision caveats,
  3. segment-sum duplicate keys (boundary detection + segment ids),
  4. keep the top `capacity` segment totals via ``lax.top_k``.
Evicted keys lose their history (undercount bound = mass evicted); pair with
a CMS estimate at query time when exact-ish counts matter.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np


# Reserved sentinel marking empty/invalid slots. A real key of all-ones is
# astronomically unlikely for hashed flow keys (and merely loses one slot if
# it occurs); a real all-zero key is NOT special, unlike the previous design.
SENTINEL = jnp.uint32(0xFFFFFFFF)


class TopK(NamedTuple):
    key_hi: jnp.ndarray   # (cap,) uint32 (SENTINEL = empty slot)
    key_lo: jnp.ndarray   # (cap,) uint32
    counts: jnp.ndarray   # (cap,) float32 (<=0 with SENTINEL key = empty)
    evicted: jnp.ndarray  # () float32 — total mass dropped by truncation;
    #                        per-key undercount is bounded by this.


def init(capacity: int = 256) -> TopK:
    return TopK(
        key_hi=jnp.full((capacity,), SENTINEL, jnp.uint32),
        key_lo=jnp.full((capacity,), SENTINEL, jnp.uint32),
        counts=jnp.zeros((capacity,), jnp.float32),
        evicted=jnp.zeros((), jnp.float32),
    )


def _combine(hi, lo, vals, capacity: int, evicted) -> TopK:
    """Radix-group by 64-bit key, merge dups, keep heaviest ``capacity``.

    On CPU the grouping sort is ONE variadic ``lax.sort`` carrying the
    value column as payload (exact lexicographic (hi, lo) order;
    measured 8.9 ms vs 12.6 ms for the two-argsort+gathers form at 33k
    lanes — the sort is the dominant fold-path op on one core). On
    accelerators the two stable single-key argsorts remain (LSD radix
    over the u32 halves; a measured multi-key ``lax.sort`` lowered
    ~200× slower on TPU). Both sorts are stable and group equal 64-bit
    keys adjacently with lanes in arrival order, so segment merging is
    exact on either path (the i32 bitcast flips the ORDER of segments,
    never their contents — only cross-platform tie-break order can
    differ, within one platform results are deterministic).
    """
    if jax.default_backend() == "cpu":
        hi_s, lo_s, v_s = jax.lax.sort((hi, lo, vals), num_keys=2)
    else:
        lo_i = jax.lax.bitcast_convert_type(lo, jnp.int32)
        hi_i = jax.lax.bitcast_convert_type(hi, jnp.int32)
        o1 = jnp.argsort(lo_i, stable=True)
        o2 = jnp.argsort(hi_i[o1], stable=True)
        order = o1[o2]
        hi_s = hi[order]
        lo_s = lo[order]
        v_s = vals[order]
    first = jnp.concatenate([
        jnp.ones((1,), bool),
        (hi_s[1:] != hi_s[:-1]) | (lo_s[1:] != lo_s[:-1]),
    ])
    seg = jnp.cumsum(first.astype(jnp.int32)) - 1
    n = hi_s.shape[0]
    seg_tot = jax.ops.segment_sum(v_s, seg, num_segments=n)
    # route each segment's total onto its first lane; non-first lanes get 0
    # mass AND sentinel keys, so top_k can never surface a duplicate key.
    lane_tot = jnp.where(first, seg_tot[seg], 0.0)
    sentinel_lane = (hi_s == SENTINEL) & (lo_s == SENTINEL)
    lane_tot = jnp.where(sentinel_lane, 0.0, lane_tot)
    keep_key = first & ~sentinel_lane
    hi_k = jnp.where(keep_key, hi_s, SENTINEL)
    lo_k = jnp.where(keep_key, lo_s, SENTINEL)
    top_v, top_i = jax.lax.top_k(lane_tot, capacity)
    out_hi = hi_k[top_i]
    out_lo = lo_k[top_i]
    # slots that got a zero-mass lane are empty → sentinel them explicitly
    empty = top_v <= 0.0
    out_hi = jnp.where(empty, SENTINEL, out_hi)
    out_lo = jnp.where(empty, SENTINEL, out_lo)
    out_v = jnp.where(empty, 0.0, top_v)
    new_evicted = evicted + (jnp.sum(lane_tot) - jnp.sum(out_v))
    return TopK(key_hi=out_hi, key_lo=out_lo, counts=out_v,
                evicted=new_evicted)


def update(sk: TopK, key_hi, key_lo, values, valid=None, est=None,
           budget: int = 0) -> TopK:
    """Fold a batch of (key, value) lanes into the top-K table.

    ``est``/``budget``: optional sketch-assisted candidate compaction
    (the CMS+heap shape of the FPGA sketch-acceleration literature —
    the sketch upper-bounds each flow's cumulative mass, the expensive
    exact merge only sees plausible candidates). When ``est`` carries a
    per-lane upper-bound estimate of that lane's FLOW total (e.g. a CMS
    point query issued after this batch's CMS update) and ``budget`` is
    a static lane count < n, only the ``budget`` highest-estimate lanes
    enter the O(n log n) grouping sort — on the hot fold path this cuts
    the dominant 33k-lane sort to a ~4.6k-lane one (11.6 → ~3 ms per
    dispatch on one CPU core). Duplicate lanes of one flow share its
    flow-level estimate, so a flow heavy in aggregate but light per
    lane is selected flow-wise, never split by per-lane mass ranking
    (ties at the budget boundary can still split one flow's lanes —
    the excluded mass lands in ``evicted`` like any truncation). Mass
    excluded by the budget is added to ``evicted``, so the per-key
    undercount bound stays honest. ``est`` requires ``valid``; lanes
    with ``valid`` False never enter (score −1). With ``est=None`` or
    ``budget >= n`` the exact legacy path runs (every lane enters the
    grouping sort)."""
    capacity = sk.counts.shape[0]
    vals = values.astype(jnp.float32)
    key_hi = key_hi.astype(jnp.uint32)
    key_lo = key_lo.astype(jnp.uint32)
    if valid is not None:
        vals = jnp.where(valid, vals, 0.0)
        # invalid lanes get the sentinel key → merged into the dead segment
        key_hi = jnp.where(valid, key_hi, SENTINEL)
        key_lo = jnp.where(valid, key_lo, SENTINEL)
    n = key_hi.shape[0]
    evicted = sk.evicted
    if est is not None and 0 < budget < n:
        assert valid is not None, "est-compacted update requires valid"
        score = jnp.where(valid, est.astype(jnp.float32), -1.0)
        _, idx = jax.lax.top_k(score, budget)
        hi_c, lo_c, v_c = key_hi[idx], key_lo[idx], vals[idx]
        # mass that never reaches the merge is evicted mass (undercount
        # bound): total valid mass minus the selected lanes' mass
        evicted = evicted + jnp.sum(vals) - jnp.sum(v_c)
        key_hi, key_lo, vals = hi_c, lo_c, v_c
    hi = jnp.concatenate([sk.key_hi, key_hi])
    lo = jnp.concatenate([sk.key_lo, key_lo])
    v = jnp.concatenate([sk.counts, vals])
    return _combine(hi, lo, v, capacity, evicted)


def merge(a: TopK, b: TopK) -> TopK:
    capacity = a.counts.shape[0]
    return _combine(
        jnp.concatenate([a.key_hi, b.key_hi]),
        jnp.concatenate([a.key_lo, b.key_lo]),
        jnp.concatenate([a.counts, b.counts]),
        capacity,
        a.evicted + b.evicted,
    )


def query(sk: TopK, k: int):
    """Return (key_hi, key_lo, counts) of the top k entries (count desc).

    Slots with SENTINEL keys / zero counts are empty; callers should filter
    ``counts > 0``. ``sk.evicted`` bounds the per-key undercount.
    """
    k = min(k, sk.counts.shape[0])
    v, i = jax.lax.top_k(sk.counts, k)
    return sk.key_hi[i], sk.key_lo[i], v


def dense_topk(stats, k: int):
    """Top-k rows of a dense per-entity stat column: (values, row_indices).

    The tensor form of the reference's per-subsystem BOUNDED_PRIO_QUEUE walks
    (top issue/QPS/net listeners, server/gy_mconnhdlr.cc partha_listener_state).
    """
    return jax.lax.top_k(stats, k)


# ---------------------------------------------------------------- numpy ref
def np_exact_topk(keys: np.ndarray, values: np.ndarray, k: int):
    """Exact top-k: keys int64 array, values float; returns (keys, totals)."""
    import collections
    acc = collections.defaultdict(float)
    for key, v in zip(keys.tolist(), values.tolist()):
        acc[key] += v
    items = sorted(acc.items(), key=lambda kv: -kv[1])[:k]
    return (np.array([key for key, _ in items], dtype=np.int64),
            np.array([v for _, v in items], dtype=np.float64))
