"""t-digest as fixed-capacity centroid tensors.

The high-accuracy quantile sketch for readback paths (north-star config #1:
single-stream RTT p50/p95/p99 vs exact). Complements ``loghist`` (the bulk
per-entity path): t-digest gives sub-percent tail accuracy independent of the
value range.

Design is the *merging* t-digest (Dunning), but compression uses k-bin
clustering instead of the sequential greedy pass: sort centroids+samples by
mean, compute midpoint quantiles q, assign cluster id = floor(k1(q)) with the
arcsine scale k1(q) = δ/2π·asin(2q−1), and segment-sum into the fixed C slots.
Everything is fixed-shape (sort + scatter), so it jits, vmaps over entity
axes, and runs on the VPU — no data-dependent loop like the CPU original.

State merge is concat+recompress → shard roll-up uses gathered concat
(all_gather of (C,2) tensors is tiny) rather than psum.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np


class TDigest(NamedTuple):
    means: jnp.ndarray    # (..., C) float32, sorted ascending among occupied
    weights: jnp.ndarray  # (..., C) float32, 0 = empty slot
    vmin: jnp.ndarray     # (...,) float32 observed min (inf if empty)
    vmax: jnp.ndarray     # (...,) float32 observed max (-inf if empty)


def init(capacity: int = 128, entities: tuple = ()) -> TDigest:
    return TDigest(
        means=jnp.zeros(entities + (capacity,), jnp.float32),
        weights=jnp.zeros(entities + (capacity,), jnp.float32),
        vmin=jnp.full(entities, jnp.inf, jnp.float32),
        vmax=jnp.full(entities, -jnp.inf, jnp.float32),
    )


def _k1(q, delta):
    # arcsine scale: dense bins at the tails → tail quantile accuracy
    return (delta / (2.0 * jnp.pi)) * jnp.arcsin(
        jnp.clip(2.0 * q - 1.0, -1.0, 1.0)
    )


def _compress(means, weights, capacity: int):
    """Cluster (means, weights) rows into ≤capacity centroids. 1-D inputs."""
    delta = 2.0 * (capacity - 1)
    # empty slots sort to the end
    sort_key = jnp.where(weights > 0, means, jnp.inf)
    order = jnp.argsort(sort_key)
    m = means[order]
    w = weights[order]
    tot = jnp.sum(w)
    cum = jnp.cumsum(w)
    q_mid = (cum - 0.5 * w) / jnp.maximum(tot, 1e-30)
    k = _k1(q_mid, delta) - _k1(jnp.float32(0.0), delta)
    cid = jnp.clip(jnp.floor(k).astype(jnp.int32), 0, capacity - 1)
    cid = jnp.where(w > 0, cid, capacity - 1)
    new_w = jax.ops.segment_sum(w, cid, num_segments=capacity)
    new_s = jax.ops.segment_sum(w * m, cid, num_segments=capacity)
    new_m = jnp.where(new_w > 0, new_s / jnp.maximum(new_w, 1e-30), 0.0)
    return new_m, new_w


def update(sk: TDigest, values, valid=None) -> TDigest:
    """Fold a batch of unit-weight samples into a (single-entity) digest."""
    capacity = sk.means.shape[-1]
    w_in = jnp.ones_like(values, jnp.float32)
    if valid is not None:
        w_in = jnp.where(valid, w_in, 0.0)
    vals = values.astype(jnp.float32)
    all_m = jnp.concatenate([sk.means, vals])
    all_w = jnp.concatenate([sk.weights, w_in])
    new_m, new_w = _compress(all_m, all_w, capacity)
    vmasked_min = jnp.where(w_in > 0, vals, jnp.inf)
    vmasked_max = jnp.where(w_in > 0, vals, -jnp.inf)
    return TDigest(
        means=new_m,
        weights=new_w,
        vmin=jnp.minimum(sk.vmin, vmasked_min.min()),
        vmax=jnp.maximum(sk.vmax, vmasked_max.max()),
    )


def stage_samples(stage_v, stage_n, rows, values, valid=None):
    """Append a batch of per-entity samples into a (S, cap) staging
    buffer WITHOUT compressing — the amortization half of the buffered
    merging t-digest (Dunning's merging variant buffers inserts and
    compresses when the buffer fills; here the fold loop stages every
    microbatch and compresses once per K-deep dispatch, because the
    vmapped sort in ``_compress`` is by far the most expensive op in
    the fold — measured 81%% of the full fold cost).

    stage_v: (S, cap) float32 values; stage_n: (S,) int32 fill counts.
    Returns (stage_v, stage_n, n_overflow). Overflowing samples (entity
    buffer full) are dropped and counted — the loghist path remains the
    lossless estimator.
    """
    S, cap = stage_v.shape
    B = rows.shape[0]
    vals = values.astype(jnp.float32)
    ok = rows >= 0
    if valid is not None:
        ok = ok & valid
    rows_ok = jnp.where(ok, rows, S)
    order = jnp.argsort(rows_ok)
    r_s = rows_ok[order]
    v_s = vals[order]
    lane = jnp.arange(B, dtype=jnp.int32)
    first = jnp.concatenate([jnp.ones((1,), bool), r_s[1:] != r_s[:-1]])
    seg_start = jax.lax.cummax(jnp.where(first, lane, 0))
    pos = lane - seg_start
    base = stage_n[jnp.clip(r_s, 0, S - 1)]
    slot = base + pos
    keep = (r_s < S) & (slot < cap)
    n_overflow = jnp.sum((r_s < S) & (slot >= cap)).astype(jnp.int32)
    tgt_row = jnp.where(keep, r_s, S)
    tgt_slot = jnp.where(keep, slot, 0)
    stage_v = stage_v.at[tgt_row, tgt_slot].set(v_s, mode="drop")
    added = jnp.zeros((S + 1,), jnp.int32).at[tgt_row].add(
        keep.astype(jnp.int32), mode="drop")[:S]
    return stage_v, stage_n + added, n_overflow


def flush_staged(sk: TDigest, stage_v, stage_n):
    """Fold a staging buffer into the per-entity digest in ONE vmapped
    compression; returns (new_digest, zeroed stage_v, zeroed stage_n)."""
    S, C = sk.means.shape
    cap = stage_v.shape[1]
    occ = jnp.arange(cap)[None, :] < stage_n[:, None]       # (S, cap)
    w_st = occ.astype(jnp.float32)
    all_m = jnp.concatenate([sk.means, stage_v], axis=-1)
    all_w = jnp.concatenate([sk.weights, w_st], axis=-1)
    new_m, new_w = jax.vmap(_compress, in_axes=(0, 0, None))(all_m, all_w,
                                                             C)
    v_for_min = jnp.where(occ, stage_v, jnp.inf)
    v_for_max = jnp.where(occ, stage_v, -jnp.inf)
    return TDigest(
        means=new_m, weights=new_w,
        vmin=jnp.minimum(sk.vmin, v_for_min.min(axis=-1)),
        vmax=jnp.maximum(sk.vmax, v_for_max.max(axis=-1)),
    ), jnp.zeros_like(stage_v), jnp.zeros_like(stage_n)


def flush_staged_topm(sk: TDigest, stage_v, stage_n, m: int):
    """Partial flush: compress only the ``m`` entities with the fullest
    stages — cost O(m·(C+cap)·log) instead of O(S·(C+cap)·log).

    The full ``flush_staged`` vmaps the compression sort over EVERY
    entity row even when almost all stages are empty; at north-star
    geometry (S=65k) that is a ~38M-element sort per flush — measured
    6.2 s on one CPU core and the dominant term of the r4 fold collapse
    (VERDICT r4 weak #3). Entities outside the top-m keep their staged
    samples (nothing is lost); callers drain iteratively or let
    pressure re-trigger. Selection by ``lax.top_k`` over the fill
    counts; rows with zero staged samples pass through untouched.

    Returns (new_digest, stage_v, stage_n) with the flushed rows' stage
    cleared.
    """
    S, C = sk.means.shape
    cap = stage_v.shape[1]
    m = min(m, S)
    nsel, idx = jax.lax.top_k(stage_n, m)              # (m,)
    occ = jnp.arange(cap)[None, :] < nsel[:, None]     # (m, cap)
    sel_means = sk.means[idx]
    sel_weights = sk.weights[idx]
    sel_stage = stage_v[idx]
    all_m = jnp.concatenate([sel_means, sel_stage], axis=-1)
    all_w = jnp.concatenate([sel_weights, occ.astype(jnp.float32)],
                            axis=-1)
    new_m, new_w = jax.vmap(_compress, in_axes=(0, 0, None))(all_m, all_w,
                                                             C)
    # empty-stage rows: recompression is a no-op in value but not in
    # centroid layout — keep the original row bit-for-bit instead
    has = nsel > 0
    new_m = jnp.where(has[:, None], new_m, sel_means)
    new_w = jnp.where(has[:, None], new_w, sel_weights)
    v_for_min = jnp.where(occ, sel_stage, jnp.inf)
    v_for_max = jnp.where(occ, sel_stage, -jnp.inf)
    return TDigest(
        means=sk.means.at[idx].set(new_m),
        weights=sk.weights.at[idx].set(new_w),
        vmin=sk.vmin.at[idx].min(v_for_min.min(axis=-1)),
        vmax=sk.vmax.at[idx].max(v_for_max.max(axis=-1)),
    ), stage_v.at[idx].set(0.0), stage_n.at[idx].set(0)


def merge(a: TDigest, b: TDigest) -> TDigest:
    capacity = a.means.shape[-1]
    all_m = jnp.concatenate([a.means, b.means], axis=-1)
    all_w = jnp.concatenate([a.weights, b.weights], axis=-1)
    if a.means.ndim == 1:
        new_m, new_w = _compress(all_m, all_w, capacity)
    else:
        flat_m = all_m.reshape(-1, all_m.shape[-1])
        flat_w = all_w.reshape(-1, all_w.shape[-1])
        new_m, new_w = jax.vmap(_compress, in_axes=(0, 0, None))(
            flat_m, flat_w, capacity
        )
        new_m = new_m.reshape(a.means.shape)
        new_w = new_w.reshape(a.weights.shape)
    return TDigest(
        means=new_m,
        weights=new_w,
        vmin=jnp.minimum(a.vmin, b.vmin),
        vmax=jnp.maximum(a.vmax, b.vmax),
    )


def quantiles(sk: TDigest, qs):
    """Quantile estimates for a single-entity digest. qs: (Q,) → (Q,)."""
    qs = jnp.asarray(qs, jnp.float32)
    w = sk.weights
    m = sk.means
    # occupied centroids are already in ascending-mean order except empty
    # slots (weight 0) interleaved at the tail of value 0 — resort defensively.
    sort_key = jnp.where(w > 0, m, jnp.inf)
    order = jnp.argsort(sort_key)
    m = m[order]
    w = w[order]
    # Empty slots sort to the tail with weight 0 and mean 0; their midpoint
    # mass equals the total, so a tail quantile whose target exceeds the last
    # occupied centroid's midpoint would otherwise interpolate toward 0.
    # Substitute vmax so that region interpolates last-midpoint → observed max
    # (mirror of the `below` branch toward vmin).
    m = jnp.where(w > 0, m, sk.vmax)
    tot = jnp.sum(w)
    cum = jnp.cumsum(w)
    left = cum - 0.5 * w                      # midpoint mass of each centroid
    target = qs * tot                         # (Q,)
    # find the pair of adjacent centroid midpoints bracketing target
    ge = left[None, :] >= target[:, None]     # (Q, C)
    hi_idx = jnp.argmax(ge, axis=-1)
    any_ge = jnp.any(ge, axis=-1)
    hi_idx = jnp.where(any_ge, hi_idx, m.shape[-1] - 1)
    lo_idx = jnp.maximum(hi_idx - 1, 0)
    x0 = left[lo_idx]
    x1 = left[hi_idx]
    y0 = m[lo_idx]
    y1 = m[hi_idx]
    t = jnp.where(x1 > x0, (target - x0) / jnp.maximum(x1 - x0, 1e-30), 0.0)
    est = y0 + t * (y1 - y0)
    # clamp into observed range; below-first-midpoint → interp from vmin
    below = target < left[0]
    est = jnp.where(below, sk.vmin + (m[0] - sk.vmin) *
                    (target / jnp.maximum(left[0], 1e-30)), est)
    est = jnp.clip(est, sk.vmin, sk.vmax)
    return jnp.where(tot > 0, est, 0.0)


def quantiles_entities(sk: TDigest, qs):
    """Vmapped quantiles over a (S, C) entity-axis digest → (S, Q)."""
    return jax.vmap(
        lambda m, w, vn, vx: quantiles(TDigest(m, w, vn, vx), qs),
        in_axes=(0, 0, 0, 0))(sk.means, sk.weights, sk.vmin, sk.vmax)


def count(sk: TDigest):
    return sk.weights.sum(axis=-1)


# ---------------------------------------------------------------- numpy ref
def np_quantiles_exact(values: np.ndarray, qs) -> np.ndarray:
    return np.quantile(np.asarray(values, np.float64), qs)
