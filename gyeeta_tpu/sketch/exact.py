"""Exact CPU (numpy) reference implementations for sketch-accuracy tests.

Mirrors the reference's test strategy (SURVEY §4): each device sketch is
diffed against an exact host computation with explicit error bounds, the way
``test_histogram.cc``/``test_quantiles.cc`` assert on
``GY_HISTOGRAM``/``TIME_HISTOGRAM`` outputs.
"""

from __future__ import annotations

import collections

import numpy as np


def distinct(keys_hi: np.ndarray, keys_lo: np.ndarray) -> int:
    k = (keys_hi.astype(np.uint64) << np.uint64(32)) | keys_lo.astype(np.uint64)
    return len(np.unique(k))


def quantiles(values: np.ndarray, qs) -> np.ndarray:
    return np.quantile(np.asarray(values, np.float64), qs)


def key_totals(keys_hi, keys_lo, values) -> dict:
    acc = collections.defaultdict(float)
    keys = (np.asarray(keys_hi, np.uint64) << np.uint64(32)) | np.asarray(
        keys_lo, np.uint64
    )
    for k, v in zip(keys.tolist(), np.asarray(values).tolist()):
        acc[k] += v
    return dict(acc)


def topk(keys_hi, keys_lo, values, k: int):
    acc = key_totals(keys_hi, keys_lo, values)
    items = sorted(acc.items(), key=lambda kv: -kv[1])[:k]
    return items
