"""Exact CPU (numpy) reference implementations for sketch-accuracy tests.

Mirrors the reference's test strategy (SURVEY §4): each device sketch is
diffed against an exact host computation with explicit error bounds, the way
``test_histogram.cc``/``test_quantiles.cc`` assert on
``GY_HISTOGRAM``/``TIME_HISTOGRAM`` outputs.
"""

from __future__ import annotations

import collections

import numpy as np


def distinct(keys_hi: np.ndarray, keys_lo: np.ndarray) -> int:
    k = (keys_hi.astype(np.uint64) << np.uint64(32)) | keys_lo.astype(np.uint64)
    return len(np.unique(k))


def quantiles(values: np.ndarray, qs) -> np.ndarray:
    return np.quantile(np.asarray(values, np.float64), qs)


def key_totals(keys_hi, keys_lo, values) -> dict:
    acc = collections.defaultdict(float)
    keys = (np.asarray(keys_hi, np.uint64) << np.uint64(32)) | np.asarray(
        keys_lo, np.uint64
    )
    for k, v in zip(keys.tolist(), np.asarray(values).tolist()):
        acc[k] += v
    return dict(acc)


def topk(keys_hi, keys_lo, values, k: int):
    acc = key_totals(keys_hi, keys_lo, values)
    items = sorted(acc.items(), key=lambda kv: -kv[1])[:k]
    return items


class StreamTopK:
    """Exact offline heavy-hitter reference over an event stream.

    Dict-based accumulation of (64-bit key → total weight) across any
    number of batches — the ground truth the device heavy-hitter tier
    (exact top-K lanes + invertible-sketch recovery) is measured
    against in tests and ``bench.py``'s ``topk_recover`` phase. Masks
    mirror the engine's admission rule so both sides count the same
    lanes (accept-observed flows only; see ``engine/step.py:
    ingest_conn``).
    """

    def __init__(self):
        self.acc: dict[int, float] = collections.defaultdict(float)

    def add(self, keys_hi, keys_lo, values, mask=None) -> None:
        hi = np.asarray(keys_hi, np.uint64)
        lo = np.asarray(keys_lo, np.uint64)
        v = np.asarray(values, np.float64)
        if mask is not None:
            m = np.asarray(mask, bool)
            hi, lo, v = hi[m], lo[m], v[m]
        keys = (hi << np.uint64(32)) | lo
        for k, w in zip(keys.tolist(), v.tolist()):
            self.acc[k] += w

    def add_conn_batch(self, cb) -> None:
        """Fold a decoded ConnBatch exactly the way the engine does:
        accept-observed lanes only, weight = bytes both ways."""
        self.add(cb.flow_hi, cb.flow_lo,
                 np.asarray(cb.bytes_sent, np.float64)
                 + np.asarray(cb.bytes_rcvd, np.float64),
                 mask=np.asarray(cb.valid) & np.asarray(cb.is_accept))

    def total(self) -> float:
        return float(sum(self.acc.values()))

    def __len__(self) -> int:
        return len(self.acc)

    def topk(self, k: int) -> list:
        """→ [(key64, exact_total)] heaviest first (key asc on ties —
        the same determinism rule as the recovered view)."""
        return sorted(self.acc.items(), key=lambda kv: (-kv[1], kv[0]))[:k]

    def topk_hex(self, k: int) -> list:
        return [(format(key, "016x"), v) for key, v in self.topk(k)]
