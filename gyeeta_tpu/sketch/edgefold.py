"""Agent-side edge fold: raw conn/resp sweeps → mergeable delta records.

The sPIN move (PAPERS.md, arXiv:1709.05483) applied to the agent tier:
process the stream *where it flows* and ship only reductions. The
reference's partha already classifies locally; this module makes it
*aggregate* locally too — per sweep it folds the agent's own TCP_CONN /
RESP_SAMPLE streams into the exact per-service counter columns the
server fold would have produced, plus tiny sketch partials (loghist
bucket counts, HLL register maxes, capped flow aggregates, dep-graph
edge sums), and emits ONE ``NOTIFY_SKETCH_DELTA`` record stream
(``wire.DELTA_DT``) instead of N raw tuples. The per-event update is
one hash→bucket→max/add numpy pass (the FPGA sketch-acceleration shape,
arXiv:2504.16896) — cheap enough for an agent CPU.

Merge contract (the engine half is ``engine/step.py:ingest_delta``):

- **counters / loghist buckets / CMS mass / dep edges** are per-sweep
  SUMS — the server scatter-adds them, so splitting a sweep across
  records, frames, or retransmitted spool entries never changes totals
  (at-least-once duplicates double-add exactly like duplicated raw
  sweeps; the SWEEP_SEQ ack dedup applies unchanged).
- **HLL registers** are monotone maxes — the agent keeps a CUMULATIVE
  local register file (a few KB) and ships only registers that ROSE
  this sweep, so steady-state deltas shrink as the sketch converges;
  a periodic full refresh (``hll_refresh_every``) re-ships the whole
  register file as insurance against a server that lost un-replayed
  state (idempotent: merge is max).
- **flows** are capped at ``flow_max`` aggregates per sweep (heaviest
  first); truncated mass ships as a DK_RESID bound the server folds
  into the top-K ``evicted`` undercount annotation — the bound stays
  honest end to end.

The sketch geometry (loghist spec, HLL precisions, digest stride) is
serve-negotiated: the server adverts its engine-cfg constants in the
REGISTER_RESP v5 tail (``wire.PREAGG_DT``) and the agent folds with
exactly those, so agent partials land in exactly the buckets the raw
fold would have hit — bucket counts and HLL registers are
bit-identical to raw mode, not merely close.
"""

from __future__ import annotations

import os

import numpy as np

from gyeeta_tpu.ingest import wire


def preagg_enabled(env=None) -> bool:
    """Server-side opt-in: ``GYT_PREAGG=1`` makes the serve tier advert
    edge pre-aggregation in every REGISTER_RESP; agents that understand
    the tail switch their conn/resp streams to delta sweeps. Default
    OFF — the raw wire stays the default contract."""
    env = os.environ if env is None else env
    return str(env.get("GYT_PREAGG", "0")).strip().lower() \
        in ("1", "true", "yes")


def params_of_cfg(cfg, td_stride: int | None = None,
                  flow_max: int | None = None,
                  env=None) -> dict:
    """The preagg advert for one engine geometry (the dict
    ``wire.encode_preagg`` serializes). ``flow_max`` defaults to the
    top-K candidate budget scale (``GYT_PREAGG_FLOW_MAX`` overrides):
    per-sweep flow aggregates past it ship as a residual bound."""
    env = os.environ if env is None else env
    if flow_max is None:
        flow_max = int(env.get("GYT_PREAGG_FLOW_MAX",
                               max(64, cfg.topk_capacity // 2)))
    if td_stride is None:
        # edge duty cycle: 4× the engine's own digest stride by
        # default (GYT_PREAGG_TD_STRIDE overrides). The digest is the
        # all-time tail refinement — a deeper duty cycle only slows
        # convergence, and shipped samples are the one delta family
        # whose lane count scales with event rate instead of entity
        # cardinality
        td_stride = int(env.get("GYT_PREAGG_TD_STRIDE",
                                4 * cfg.td_sample_stride))
    return {
        "hll_p_svc": cfg.hll_p_svc,
        "hll_p_global": cfg.hll_p_global,
        "td_stride": max(1, int(td_stride)),
        "resp_nbuckets": cfg.resp_spec.nbuckets,
        "flow_max": int(flow_max),
        "resp_vmin": float(cfg.resp_spec.vmin),
        "resp_vmax": float(cfg.resp_spec.vmax),
    }


def default_params() -> dict:
    """Advert matching the default EngineCfg (tests / direct sims)."""
    from gyeeta_tpu.engine.aggstate import EngineCfg
    return params_of_cfg(EngineCfg(), env={})


def _key64(hi: np.ndarray, lo: np.ndarray) -> np.ndarray:
    return ((hi.astype(np.uint64) << np.uint64(32))
            | lo.astype(np.uint64))


class EdgeFold:
    """One agent's local fold state (per host in multi-host sims).

    ``fold_sweep(conn_recs, resp_recs)`` → a ``wire.DELTA_DT`` record
    array carrying the whole sweep. Cumulative state is ONLY the HLL
    register files (monotone; everything else is per-sweep)."""

    def __init__(self, params: dict, host_id: int = 0,
                 hll_refresh_every: int = 120):
        from gyeeta_tpu.sketch import loghist
        self.params = dict(params)
        self.host_id = int(host_id)
        self.resp_spec = loghist.LogHistSpec(
            vmin=float(params["resp_vmin"]),
            vmax=float(params["resp_vmax"]),
            nbuckets=int(params["resp_nbuckets"]))
        self.p_svc = int(params["hll_p_svc"])
        self.p_glob = int(params["hll_p_global"])
        self.td_stride = max(1, int(params["td_stride"]))
        self.flow_max = max(1, int(params["flow_max"]))
        # cumulative register files: {(host, svc64): uint8[m_svc]} and
        # {host: uint8[m_glob]} — a few KB per tracked entity
        self._svc_regs: dict = {}
        self._glob_regs: dict = {}
        self.hll_refresh_every = max(0, int(hll_refresh_every))
        self._sweeps = 0
        self.stats = {"records_in": 0, "delta_records": 0,
                      "resid_bytes": 0.0, "onesided_skipped": 0}
        # exact per-svc running totals (the smoke/parity oracle: what
        # the server's ctr_win columns must show, within float addition)
        self.totals: dict = {}

    # ------------------------------------------------------------ helpers
    def _rows(self, n: int) -> np.ndarray:
        r = np.zeros(n, wire.DELTA_DT)
        r["host_id"] = self.host_id
        return r

    @staticmethod
    def _pack_pairs(rows_out: list, kind: int, key64, host, idx, wt):
        """Chunk sparse (idx, weight) pairs for ONE key into ≤16-pair
        records (splitting is free: the merges are monotone)."""
        P = wire.DELTA_PAIRS
        for off in range(0, len(idx), P):
            n = min(P, len(idx) - off)
            r = np.zeros(1, wire.DELTA_DT)
            r["kind"] = kind
            r["key_hi"] = np.uint32(key64 >> np.uint64(32))
            r["key_lo"] = np.uint32(key64 & np.uint64(0xFFFFFFFF))
            r["nitem"] = n
            r["host_id"] = host
            pv = r["payload"].reshape(-1)[: n * 6].view(wire.DELTA_PAIR_DT)
            pv["idx"] = idx[off: off + n].astype(np.uint16)
            pv["wt"] = wt[off: off + n].astype(np.float32)
            rows_out.append(r)

    def _hll_delta(self, regs: np.ndarray, idx, rank, refresh: bool):
        """Fold (idx, rank) observations into the cumulative register
        file; return the (idx, rank) pairs to ship (risen this sweep,
        or ALL occupied on a refresh sweep)."""
        if len(idx):
            np.maximum.at(regs, idx, rank.astype(regs.dtype))
            if not refresh:
                # registers whose cumulative value ROSE this sweep:
                # ship the new max (dedup per register via unique)
                u = np.unique(idx)
                prev = self._prev_regs
                rose = u[regs[u] > prev[u]]
                return rose, regs[rose]
        if refresh:
            occ = np.nonzero(regs)[0]
            return occ, regs[occ]
        return np.empty(0, np.int64), np.empty(0, np.uint8)

    # --------------------------------------------------------------- fold
    def fold_sweep(self, conn_recs: np.ndarray,
                   resp_recs: np.ndarray) -> np.ndarray:
        """One sweep's raw records → DELTA_DT records (possibly empty).

        Multi-host record arrays are supported (the fleet-harness sim):
        every family groups by the record's own host_id, so sharded
        servers route each row to the shard that owns its host."""
        from gyeeta_tpu.ingest import decode
        from gyeeta_tpu.sketch import hyperloglog as hll, loghist

        self._sweeps += 1
        refresh = bool(self.hll_refresh_every
                       and self._sweeps % self.hll_refresh_every == 1
                       and self._sweeps > 1)
        nc = 0 if conn_recs is None else len(conn_recs)
        nr = 0 if resp_recs is None else len(resp_recs)
        self.stats["records_in"] += nc + nr
        rows: list = []
        if nc:
            cb = decode.conn_batch(conn_recs, size=nc)
            self._fold_conn(cb, conn_recs["host_id"], rows, hll,
                            refresh)
        if nr:
            self._fold_resp(resp_recs, rows, loghist)
        if not rows:
            return np.empty(0, wire.DELTA_DT)
        out = np.concatenate(rows)
        self.stats["delta_records"] += len(out)
        return out

    def _fold_conn(self, cb, rec_host, rows, hll, refresh: bool):
        from gyeeta_tpu.utils import hashing as H  # noqa: F401

        valid = cb.valid
        acc = valid & cb.is_accept
        svc64 = _key64(cb.svc_hi, cb.svc_lo)
        flow64 = _key64(cb.flow_hi, cb.flow_lo)
        hosts = rec_host.astype(np.uint32)
        tot_bytes = cb.bytes_sent + cb.bytes_rcvd
        for h in np.unique(hosts):
            hm = hosts == h
            a = acc & hm
            v = valid & hm
            # ---- per-svc exact counters (the raw ctr_win fold)
            if a.any():
                uk, inv = np.unique(svc64[a], return_inverse=True)
                ctr = np.zeros((len(uk), 6), np.float64)
                np.add.at(ctr[:, 0], inv, cb.bytes_sent[a])
                np.add.at(ctr[:, 1], inv, cb.bytes_rcvd[a])
                np.add.at(ctr[:, 2], inv, cb.is_close[a].astype(float))
                np.add.at(ctr[:, 3], inv, cb.duration_us[a])
                np.add.at(ctr[:, 4], inv, 1.0)
                r = self._rows(len(uk))
                r["kind"] = wire.DK_SVC_CTR
                r["key_hi"] = (uk >> np.uint64(32)).astype(np.uint32)
                r["key_lo"] = uk.astype(np.uint32)
                r["nitem"] = 6
                r["host_id"] = h
                pv = r["payload"][:, :24].view("<f4")
                pv[:, :6] = ctr.astype(np.float32)
                rows.append(r)
                for k, c in zip(uk.tolist(), ctr):
                    t = self.totals.setdefault(
                        int(k), np.zeros(6, np.float64))
                    t += c
                # ---- per-svc distinct-client HLL (incremental maxes)
                ci, cr = hll._idx_rank(cb.cli_hi[a], cb.cli_lo[a],
                                       self.p_svc)
                for j, k in enumerate(uk.tolist()):
                    m = inv == j
                    regs = self._svc_regs.get((int(h), k))
                    if regs is None:
                        regs = np.zeros(1 << self.p_svc, np.uint8)
                        self._svc_regs[(int(h), k)] = regs
                    self._prev_regs = regs.copy()
                    idx, rank = self._hll_delta(regs, ci[m], cr[m],
                                                refresh)
                    if len(idx):
                        self._pack_pairs(rows, wire.DK_SVC_HLL,
                                         np.uint64(k), h, idx,
                                         rank.astype(np.float32))
            # ---- global flow HLL over every valid lane
            if v.any():
                gi, gr = hll._idx_rank(cb.flow_hi[v], cb.flow_lo[v],
                                       self.p_glob)
                regs = self._glob_regs.get(int(h))
                if regs is None:
                    regs = np.zeros(1 << self.p_glob, np.uint8)
                    self._glob_regs[int(h)] = regs
                self._prev_regs = regs.copy()
                idx, rank = self._hll_delta(regs, gi, gr, refresh)
                if len(idx):
                    self._pack_pairs(rows, wire.DK_GLOB_HLL,
                                     np.uint64(0), h, idx,
                                     rank.astype(np.float32))
            # ---- flow aggregates: heaviest flow_max ship, rest is a
            # counted residual bound (accept side only — the additive
            # CMS/top-K fold accept-observed lanes only, like the raw
            # fold; see engine/step.py:ingest_conn)
            if a.any():
                fu, finv = np.unique(flow64[a], return_inverse=True)
                fsum = np.zeros(len(fu), np.float64)
                np.add.at(fsum, finv, tot_bytes[a])
                order = np.argsort(-fsum, kind="stable")
                keep = order[: self.flow_max]
                resid = float(fsum[order[self.flow_max:]].sum()) \
                    if len(order) > self.flow_max else 0.0
                F = wire.DELTA_FLOWS
                kf, vf = fu[keep], fsum[keep]
                nrows = -(-len(kf) // F)
                r = self._rows(nrows)
                r["kind"] = wire.DK_FLOW
                r["host_id"] = h
                for i in range(nrows):
                    sl = slice(i * F, min((i + 1) * F, len(kf)))
                    n = sl.stop - sl.start
                    r[i]["nitem"] = n
                    pv = r[i]["payload"][: n * 12].view(
                        wire.DELTA_FLOW_DT)
                    pv["hi"] = (kf[sl] >> np.uint64(32)).astype(
                        np.uint32)
                    pv["lo"] = kf[sl].astype(np.uint32)
                    pv["val"] = vf[sl].astype(np.float32)
                rows.append(r)
                if resid > 0:
                    rr = self._rows(1)
                    rr["kind"] = wire.DK_RESID
                    rr["errb"] = np.float32(resid)
                    rr["host_id"] = h
                    rows.append(rr)
                    self.stats["resid_bytes"] += resid
            # ---- dependency edges (both-sides-known lanes, the
            # direct-edge path of depgraph.halves_from_conn; one-sided
            # halves cannot be locally resolved and are counted)
            cli_hi = np.where(cb.cli_rel_hi[hm] | cb.cli_rel_lo[hm],
                              cb.cli_rel_hi[hm], cb.cli_task_hi[hm])
            cli_lo = np.where(cb.cli_rel_hi[hm] | cb.cli_rel_lo[hm],
                              cb.cli_rel_lo[hm], cb.cli_task_lo[hm])
            cli_svc = (cb.cli_rel_hi[hm] | cb.cli_rel_lo[hm]) != 0
            know_cli = (cli_hi | cli_lo) != 0
            know_ser = (cb.svc_hi[hm] | cb.svc_lo[hm]) != 0
            vm = valid[hm]
            both = vm & know_cli & know_ser
            self.stats["onesided_skipped"] += int(
                (vm & (know_cli ^ know_ser)).sum())
            if both.any():
                c64 = _key64(cli_hi, cli_lo)[both]
                s64 = svc64[hm][both]
                csvc = cli_svc[both]
                eb = tot_bytes[hm][both]
                comp = np.stack([c64, s64,
                                 csvc.astype(np.uint64)], axis=1)
                ue, einv = np.unique(comp, axis=0,
                                     return_inverse=True)
                nconn = np.zeros(len(ue), np.float64)
                bsum = np.zeros(len(ue), np.float64)
                np.add.at(nconn, einv, 1.0)
                np.add.at(bsum, einv, eb)
                r = self._rows(len(ue))
                r["kind"] = wire.DK_DEP
                r["key_hi"] = (ue[:, 1] >> np.uint64(32)).astype(
                    np.uint32)
                r["key_lo"] = ue[:, 1].astype(np.uint32)
                r["aux_hi"] = (ue[:, 0] >> np.uint64(32)).astype(
                    np.uint32)
                r["aux_lo"] = ue[:, 0].astype(np.uint32)
                r["flags"] = ue[:, 2].astype(np.uint8)
                r["nitem"] = 2
                r["host_id"] = h
                pv = r["payload"][:, :8].view("<f4")
                pv[:, 0] = nconn.astype(np.float32)
                pv[:, 1] = bsum.astype(np.float32)
                rows.append(r)

    def _fold_resp(self, resp, rows, loghist):
        hosts = resp["host_id"].astype(np.uint32)
        gid = resp["glob_id"]
        vals = resp["resp_usec"].astype(np.float32)
        bucket = loghist.bucket_of(self.resp_spec, vals)
        for h in np.unique(hosts):
            hm = hosts == h
            uk, inv = np.unique(gid[hm], return_inverse=True)
            # ---- resp-count column of the per-svc counters
            cnt = np.zeros(len(uk), np.float64)
            np.add.at(cnt, inv, 1.0)
            r = self._rows(len(uk))
            r["kind"] = wire.DK_SVC_CTR
            r["key_hi"] = (uk >> np.uint64(32)).astype(np.uint32)
            r["key_lo"] = uk.astype(np.uint32)
            r["nitem"] = 6
            r["host_id"] = h
            pv = r["payload"][:, :24].view("<f4")
            pv[:, 5] = cnt.astype(np.float32)
            rows.append(r)
            for k, c in zip(uk.tolist(), cnt):
                t = self.totals.setdefault(int(k),
                                           np.zeros(6, np.float64))
                t[5] += c
            # ---- per-svc loghist bucket counts (exact)
            comp = inv.astype(np.int64) * self.resp_spec.nbuckets \
                + bucket[hm]
            uc, cinv = np.unique(comp, return_inverse=True)
            w = np.zeros(len(uc), np.float64)
            np.add.at(w, cinv, 1.0)
            for j in range(len(uk)):
                m = (uc // self.resp_spec.nbuckets) == j
                if m.any():
                    self._pack_pairs(
                        rows, wire.DK_SVC_HIST, np.uint64(uk[j]), h,
                        (uc[m] % self.resp_spec.nbuckets),
                        w[m].astype(np.float32))
            # ---- digest duty-cycle: the strided subsample the raw
            # fold would have staged (1-in-N of arrival order)
            sub = np.nonzero(hm)[0][:: self.td_stride]
            if len(sub):
                sgid = gid[sub]
                svals = vals[sub]
                su = np.unique(sgid)
                S = wire.DELTA_SAMPLES
                for k in su.tolist():
                    sv = svals[sgid == k]
                    for off in range(0, len(sv), S):
                        n = min(S, len(sv) - off)
                        rr = self._rows(1)
                        rr["kind"] = wire.DK_SVC_TD
                        rr["key_hi"] = np.uint32(k >> 32)
                        rr["key_lo"] = np.uint32(k & 0xFFFFFFFF)
                        rr["nitem"] = n
                        rr["host_id"] = h
                        pv = rr["payload"].reshape(-1)[: n * 4].view(
                            "<f4")
                        pv[:] = sv[off: off + n]
                        rows.append(rr)
