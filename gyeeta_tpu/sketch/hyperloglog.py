"""HyperLogLog distinct counting as a device tensor.

Replaces the reference's exact distinct-endpoint tracking (RCU entity tables +
``CONN_BITMAP``, ``common/gy_socket_stat.h:390``) with a fixed 2^p-register
sketch: cardinality of distinct peers/flows per service or per host with
~1.04/sqrt(2^p) standard error (p=14 → 0.8%).

Register update is a scatter-max; cross-shard merge is elementwise max →
roll-up over shards is ``lax.pmax``. Supports a leading entity axis so one
tensor holds a sketch per tracked service row.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
import numpy as np

from gyeeta_tpu.utils import hashing as H

_HLL_SALT = 0x1F123BB5


class HLL(NamedTuple):
    regs: jnp.ndarray  # (..., m) int32 registers (0..32-p+1)


def init(p: int = 14, entities: tuple = ()) -> HLL:
    m = 1 << p
    return HLL(regs=jnp.zeros(entities + (m,), dtype=jnp.int32))


def _idx_rank(key_hi, key_lo, p: int):
    h = H.mix64(key_hi, key_lo, _HLL_SALT)
    is_np = isinstance(h, np.ndarray)
    if is_np:
        idx = (h >> np.uint32(32 - p)).astype(np.int32)
        w = (h << np.uint32(p)).astype(np.uint32)
        rank = np.minimum(H.leading_zeros32(w), 32 - p) + 1
    else:
        idx = (h >> (32 - p)).astype(jnp.int32)
        w = (h << p).astype(jnp.uint32)
        rank = jnp.minimum(H.leading_zeros32(w), 32 - p) + 1
    return idx, rank


def update(sk: HLL, key_hi, key_lo, valid=None) -> HLL:
    """Global (no entity axis) register update via scatter-max.

    GYT_PALLAS=1 routes the register write through the hand-kernel
    prototype (``sketch/pallas_scatter.py``) — rank is pre-masked to 0
    on invalid lanes, so both paths see identical no-op updates."""
    p = int(np.log2(sk.regs.shape[-1]))
    idx, rank = _idx_rank(key_hi, key_lo, p)
    if valid is not None:
        rank = jnp.where(valid, rank, 0)
    from gyeeta_tpu.sketch import pallas_scatter as _ps
    if _ps.enabled():
        return HLL(regs=_ps.scatter_max(sk.regs, idx, rank))
    return HLL(regs=sk.regs.at[idx].max(rank))


def update_entities(sk: HLL, entity_row, key_hi, key_lo, valid=None) -> HLL:
    """Per-entity update: scatter-max at (entity_row, register)."""
    p = int(np.log2(sk.regs.shape[-1]))
    m = sk.regs.shape[-1]
    idx, rank = _idx_rank(key_hi, key_lo, p)
    if valid is not None:
        rank = jnp.where(valid, rank, 0)
        entity_row = jnp.where(valid, entity_row, 0)
    from gyeeta_tpu.sketch import pallas_scatter as _ps
    if _ps.enabled():
        flat_idx = entity_row.astype(jnp.int32) * m + idx
        return HLL(regs=_ps.scatter_max(sk.regs, flat_idx, rank))
    return HLL(regs=sk.regs.at[entity_row, idx].max(rank))


def estimate(sk: HLL):
    """Cardinality estimate per entity (HLL with small/large-range correction,
    Flajolet et al.; 32-bit hash variant)."""
    m = sk.regs.shape[-1]
    if m >= 128:
        alpha = 0.7213 / (1.0 + 1.079 / m)
    elif m == 64:
        alpha = 0.709
    elif m == 32:
        alpha = 0.697
    else:
        alpha = 0.673
    regs = sk.regs.astype(jnp.float32)
    inv_sum = jnp.sum(jnp.exp2(-regs), axis=-1)
    raw = alpha * m * m / inv_sum
    zeros = jnp.sum(sk.regs == 0, axis=-1).astype(jnp.float32)
    # small-range: linear counting when estimate <= 2.5m and empty regs exist
    lc = m * jnp.log(m / jnp.maximum(zeros, 1e-9))
    small = (raw <= 2.5 * m) & (zeros > 0)
    est = jnp.where(small, lc, raw)
    # large-range (32-bit hash space)
    two32 = jnp.float32(2.0**32)
    large = est > two32 / 30.0
    est = jnp.where(large, -two32 * jnp.log1p(-est / two32), est)
    return est


def merge(a: HLL, b: HLL) -> HLL:
    return HLL(regs=jnp.maximum(a.regs, b.regs))


# ---------------------------------------------------------------- numpy ref
def np_update(regs: np.ndarray, key_hi, key_lo):
    p = int(np.log2(regs.shape[-1]))
    idx, rank = _idx_rank(np.asarray(key_hi), np.asarray(key_lo), p)
    np.maximum.at(regs, idx, rank)
    return regs
