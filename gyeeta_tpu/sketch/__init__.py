"""Device-resident streaming sketches — the tensor replacement for the
reference's CPU sketch tier (``common/gy_statistics.h``:
``GY_HISTOGRAM``/``TIME_HISTOGRAM``/``BOUNDED_PRIO_QUEUE`` and
``thirdparty/TimeseriesSlabHistogram``).

Each sketch is a pure-functional module: ``init() -> state`` (a pytree of
arrays), ``update(state, batch) -> state``, ``merge(a, b) -> state`` (the
cross-shard roll-up primitive — always expressible as psum/pmax so it rides
ICI collectives), and ``query(state) -> stats``. Everything is fixed-shape and
jittable.
"""

from gyeeta_tpu.sketch import (
    countmin,
    exact,
    hyperloglog,
    loghist,
    tdigest,
    topk,
    windows,
)

__all__ = [
    "countmin",
    "exact",
    "hyperloglog",
    "loghist",
    "tdigest",
    "topk",
    "windows",
]
