"""Count-Min sketch as a device tensor.

Replaces the reference's per-flow exact counters kept in RCU hash tables
(``common/gy_socket_stat.h:999`` ``tcp_tbl_`` byte/packet counts) for the
unbounded-key regime: per-5-tuple bytes/sec, per-endpoint event counts.
Point-update pointer chasing becomes one batched scatter-add per microbatch.

State is ``(depth, width)``; row streams derive from TWO independent
hashes via Kirsch-Mitzenmacher double hashing (``bucket_r = h1 + r·h2``
— provably preserves the CMS error bounds, *Less Hashing, Same
Performance*, and costs 2 key mixes instead of ``depth``; the fold-path
hash work is ~depth/2 cheaper). Estimates are upper bounds; error ≤
e·N/width with prob 1-e^-depth. Merge is elementwise ``+`` → roll-up
over shards is a plain ``psum``.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
import numpy as np

from gyeeta_tpu.utils import hashing as H


class CMS(NamedTuple):
    counts: jnp.ndarray  # (depth, width) float32 (sums) or int32 (counts)


def init(depth: int = 4, width: int = 1 << 16, dtype=jnp.float32) -> CMS:
    return CMS(counts=jnp.zeros((depth, width), dtype=dtype))


def update(sk: CMS, key_hi, key_lo, values, valid=None) -> CMS:
    """Scatter-add ``values`` for 64-bit keys ``(key_hi, key_lo)``.

    ``valid``: optional bool mask (padding lanes contribute nothing).
    """
    depth, width = sk.counts.shape
    vals = values.astype(sk.counts.dtype)
    if valid is not None:
        vals = jnp.where(valid, vals, jnp.zeros_like(vals))
    # One fused scatter over all rows: flatten (row, bucket) into row*width+idx.
    buckets = H.bucket_indices_km(key_hi, key_lo, depth, width)
    rows = [b + r * width for r, b in enumerate(buckets)]
    flat_idx = jnp.concatenate(rows)
    flat_vals = jnp.tile(vals, depth)
    # GYT_PALLAS=1: the hash→bucket→add inner loop as a hand kernel
    # (sketch/pallas_scatter.py prototype); vals are pre-masked, so
    # both paths apply identical updates
    from gyeeta_tpu.sketch import pallas_scatter as _ps
    if _ps.enabled():
        return CMS(counts=_ps.scatter_add(sk.counts, flat_idx,
                                          flat_vals))
    counts = sk.counts.reshape(-1).at[flat_idx].add(flat_vals)
    return CMS(counts=counts.reshape(depth, width))


def query(sk: CMS, key_hi, key_lo):
    """Point estimate (min over rows) for a batch of keys."""
    depth, width = sk.counts.shape
    est = None
    for r, idx in enumerate(H.bucket_indices_km(key_hi, key_lo, depth,
                                                width)):
        v = sk.counts[r, idx]
        est = v if est is None else jnp.minimum(est, v)
    return est


def upper_bound(sk: CMS, key_hi, key_lo, rows: int = 1):
    """Looser point estimate using only the first ``rows`` hash rows —
    still a valid upper bound (every row receives all mass), at 1/depth
    the gather cost. Candidate filters (top-K compaction) want exactly
    this: cheap, safe-side, ranking quality degrades gracefully with
    collisions."""
    depth, width = sk.counts.shape
    rows = min(rows, depth)
    est = None
    for r, idx in enumerate(H.bucket_indices_km(key_hi, key_lo, rows,
                                                width)):
        v = sk.counts[r, idx]
        est = v if est is None else jnp.minimum(est, v)
    return est


def merge(a: CMS, b: CMS) -> CMS:
    return CMS(counts=a.counts + b.counts)


def total(sk: CMS):
    """Total inserted weight (any row sums to it)."""
    return sk.counts[0].sum()


# ---------------------------------------------------------------- numpy ref
def np_update(counts: np.ndarray, key_hi, key_lo, values):
    depth, width = counts.shape
    buckets = H.bucket_indices_km(np.asarray(key_hi), np.asarray(key_lo),
                                  depth, width)
    for r, idx in enumerate(buckets):
        np.add.at(counts[r], idx, values)
    return counts


def np_query(counts: np.ndarray, key_hi, key_lo):
    depth, width = counts.shape
    buckets = H.bucket_indices_km(np.asarray(key_hi), np.asarray(key_lo),
                                  depth, width)
    est = None
    for r, idx in enumerate(buckets):
        v = counts[r][idx]
        est = v if est is None else np.minimum(est, v)
    return est
