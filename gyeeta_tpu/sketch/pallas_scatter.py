"""Pallas prototype for the sketch-update inner loop (GYT_PALLAS=1).

The FPGA sketch-acceleration literature (PAPERS.md: "Memory-efficient
Sketch Acceleration for Large Network Flows", "HyperLogLog Sketch
Acceleration on FPGA") shows the per-event sketch update is a pure
``hash → bucket → max/add`` pattern that fuses into a single pipeline
pass. The XLA path expresses it as one scatter op per sketch; this
module is the hand-kernel prototype of the same inner loop as a Pallas
``pallas_call`` — a read-modify-write sweep over the batch lanes:

- :func:`scatter_max` — the HLL register update (per-entity and global
  registers flatten to one 1-D register file; lanes carry a
  pre-masked rank, so padding lanes are max-with-0 no-ops),
- :func:`scatter_add` — the CMS row update (the ``depth`` rows flatten
  to one buffer with per-row lane offsets, exactly like the XLA path;
  padding lanes add 0.0).

Status: PROTOTYPE, off by default. ``GYT_PALLAS=1`` routes
``hyperloglog.update/update_entities`` and ``countmin.update`` through
these kernels; on non-TPU backends the kernels run in Pallas
INTERPRET mode (correct, slow — CI exercises numeric equality with the
XLA scatters there), and any import/lowering failure falls back to the
XLA path with a one-time warning (never an error on the hot path).
``python -m gyeeta_tpu.sketch.pallas_scatter`` benchmarks both paths
and prints one JSON line — the honest comparison the flag is gated on.

The flag is read once per process (the fold graphs trace once); set it
before start, like GYT_BENCH_ABLATE.
"""

from __future__ import annotations

import logging
import os

import jax
import jax.numpy as jnp
import numpy as np

_log = logging.getLogger("gyeeta_tpu.sketch.pallas")
_warned = False


def enabled() -> bool:
    """True when GYT_PALLAS=1 and the Pallas import works. Read at
    trace time (once per compiled fold variant)."""
    if os.environ.get("GYT_PALLAS", "0").strip() not in ("1", "true"):
        return False
    return _import_ok()


def _import_ok() -> bool:
    global _warned
    try:
        from jax.experimental import pallas as pl  # noqa: F401
        return True
    except Exception as e:  # noqa: BLE001 — any import failure → XLA
        if not _warned:
            _warned = True
            _log.warning("GYT_PALLAS=1 but Pallas is unavailable "
                         "(%s) — XLA scatter path in use", e)
        return False


def _interpret() -> bool:
    """Interpret mode everywhere but real TPU backends — the CPU/GPU
    fallback contract of the prototype."""
    return jax.default_backend() != "tpu"


def _scatter_max_call(regs_flat, idx, val):
    from jax.experimental import pallas as pl

    def kernel(idx_ref, val_ref, regs_ref, out_ref):
        def body(i, carry):
            j = idx_ref[i]
            out_ref[j] = jnp.maximum(out_ref[j], val_ref[i])
            return carry
        jax.lax.fori_loop(0, idx_ref.shape[0], body, 0)

    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(regs_flat.shape, regs_flat.dtype),
        input_output_aliases={2: 0},
        interpret=_interpret(),
    )(idx, val, regs_flat)


def _scatter_add_call(counts_flat, idx, val):
    from jax.experimental import pallas as pl

    def kernel(idx_ref, val_ref, counts_ref, out_ref):
        def body(i, carry):
            j = idx_ref[i]
            out_ref[j] = out_ref[j] + val_ref[i]
            return carry
        jax.lax.fori_loop(0, idx_ref.shape[0], body, 0)

    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(counts_flat.shape,
                                       counts_flat.dtype),
        input_output_aliases={2: 0},
        interpret=_interpret(),
    )(idx, val, counts_flat)


def scatter_max(regs, flat_idx, val):
    """``regs.flat[idx] = max(regs.flat[idx], val)`` per lane, in lane
    order — the HLL register update. ``regs`` may carry leading entity
    axes (flattened and restored here); ``flat_idx`` indexes the
    flattened register file; ``val`` must be pre-masked (0 on padding
    lanes). Falls back to the XLA scatter on any kernel failure."""
    shape = regs.shape
    flat = regs.reshape(-1)
    try:
        out = _scatter_max_call(flat, flat_idx.astype(jnp.int32),
                                val.astype(regs.dtype))
    except Exception as e:  # noqa: BLE001 — lowering failure → XLA
        _fallback_warn(e)
        out = flat.at[flat_idx].max(val.astype(regs.dtype))
    return out.reshape(shape)


def scatter_add(counts, flat_idx, val):
    """``counts.flat[idx] += val`` per lane — the CMS row update (val
    pre-masked to 0 on padding lanes). Fallback: XLA scatter-add."""
    shape = counts.shape
    flat = counts.reshape(-1)
    try:
        out = _scatter_add_call(flat, flat_idx.astype(jnp.int32),
                                val.astype(counts.dtype))
    except Exception as e:  # noqa: BLE001 — lowering failure → XLA
        _fallback_warn(e)
        out = flat.at[flat_idx].add(val.astype(counts.dtype))
    return out.reshape(shape)


def _fallback_warn(e) -> None:
    global _warned
    if not _warned:
        _warned = True
        _log.warning("Pallas sketch kernel failed (%s) — XLA scatter "
                     "fallback in use", e)


# ------------------------------------------------------------- benchmark
def _bench(n_lanes: int = 4096, m: int = 1 << 14, iters: int = 20):
    """Pallas vs XLA scatter on one (idx, val) workload; asserts
    numeric equality, times both, returns a result dict."""
    import time

    rng = np.random.default_rng(7)
    idx = jnp.asarray(rng.integers(0, m, n_lanes), jnp.int32)
    rank = jnp.asarray(rng.integers(0, 23, n_lanes), jnp.int32)
    vals = jnp.asarray(rng.random(n_lanes), jnp.float32)
    regs = jnp.zeros((m,), jnp.int32)
    counts = jnp.zeros((m,), jnp.float32)

    xla_max = jax.jit(lambda r: r.at[idx].max(rank))
    xla_add = jax.jit(lambda c: c.at[idx].add(vals))
    pls_max = jax.jit(lambda r: _scatter_max_call(r, idx, rank))
    pls_add = jax.jit(lambda c: _scatter_add_call(c, idx, vals))

    def rate(f, x):
        out = f(x)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(iters):
            out = f(x)
        jax.block_until_ready(out)
        return n_lanes * iters / (time.perf_counter() - t0)

    res = {"backend": jax.default_backend(),
           "interpret": _interpret(), "n_lanes": n_lanes, "m": m}
    np.testing.assert_array_equal(np.asarray(xla_max(regs)),
                                  np.asarray(pls_max(regs)))
    np.testing.assert_allclose(np.asarray(xla_add(counts)),
                               np.asarray(pls_add(counts)), rtol=1e-6)
    res["equal"] = True
    res["xla_scatter_max_lanes_per_sec"] = round(rate(xla_max, regs), 1)
    res["pallas_scatter_max_lanes_per_sec"] = round(rate(pls_max, regs),
                                                    1)
    res["xla_scatter_add_lanes_per_sec"] = round(rate(xla_add, counts),
                                                 1)
    res["pallas_scatter_add_lanes_per_sec"] = round(rate(pls_add,
                                                         counts), 1)
    return res


def main() -> None:
    import json
    if not _import_ok():
        print(json.dumps({"pallas_available": False}))
        return
    print(json.dumps({"pallas_available": True, **_bench()}))


if __name__ == "__main__":
    main()
