"""Multi-resolution time windows over additive sketch state.

Tensor analogue of ``folly::MultiLevelTimeSeries`` as used by
``TIME_HISTOGRAM`` (``common/gy_statistics.h:1083``) with the reference's
canonical level set ``Level_5s_5min_5days_all`` (:1545): every statistic is
readable over the last 5 s, last 5 min, last 5 days, and process lifetime.

Design: the engine ticks at a fixed base cadence (default 5 s — the service
state cadence, ``gy_socket_stat.cc:152``). Each level above the base is a ring
of ``nslots`` sub-slabs plus a rolling ``total``; on tick the just-finished
base slab is added into every level's current sub-slab, and when a level's
stride boundary passes, its ring advances and the expired sub-slab is
subtracted from the rolling total. All branch-free (``jnp.where`` on tick
predicates) so the whole thing lives inside the jitted update step.

Works over any *additive* state array (loghist slabs, CMS tensors, packed
stat columns). Non-additive sketches (HLL max-merge) use the same ring but
``maximum`` recombine at query time instead of a rolling total.
"""

from __future__ import annotations

from typing import NamedTuple, Sequence

import jax.numpy as jnp
import numpy as np


class WindowSpec(NamedTuple):
    """One level: covers ``stride_ticks * nslots`` base ticks."""
    stride_ticks: int  # base ticks per sub-slab
    nslots: int        # ring length

    @property
    def span_ticks(self) -> int:
        return self.stride_ticks * self.nslots


# 5s base tick; "all" is a plain accumulator, handled separately. Coverage of
# a level oscillates in [span - stride + 1, span] base ticks: right after a
# stride boundary the just-expired sub-slab's stride-1 older ticks are gone.
# 5 min = 60 ticks; 5 days = 86400 ticks (matching Level_5s_5min_5days_all,
# common/gy_statistics.h:1545).
LEVELS_DEFAULT: tuple[WindowSpec, ...] = (
    WindowSpec(stride_ticks=5, nslots=12),     # 5 min span, 25 s resolution
    WindowSpec(stride_ticks=3600, nslots=24),  # 5 day span, 5 h resolution
)


class MultiWindow(NamedTuple):
    """Windowed view of one additive state array of shape ``shape``.

    cur:    (shape) slab being filled this base tick
    rings:  tuple of (nslots, *shape) per level
    totals: tuple of (shape) rolling per-level totals
    alltime:(shape) lifetime accumulator
    tick:   () int32 — base ticks since start
    """
    cur: jnp.ndarray
    rings: tuple
    totals: tuple
    alltime: jnp.ndarray
    tick: jnp.ndarray


def init(shape: tuple, levels: Sequence[WindowSpec] = LEVELS_DEFAULT,
         dtype=jnp.float32) -> MultiWindow:
    return MultiWindow(
        cur=jnp.zeros(shape, dtype),
        rings=tuple(jnp.zeros((lv.nslots,) + tuple(shape), dtype)
                    for lv in levels),
        totals=tuple(jnp.zeros(shape, dtype) for _ in levels),
        alltime=jnp.zeros(shape, dtype),
        tick=jnp.zeros((), jnp.int32),
    )


def add(win: MultiWindow, delta) -> MultiWindow:
    """Accumulate into the current base slab (called per microbatch)."""
    return win._replace(cur=win.cur + delta)


def tick(win: MultiWindow, levels: Sequence[WindowSpec] = LEVELS_DEFAULT
         ) -> MultiWindow:
    """Close the current base slab: fold into every level, advance rings."""
    t = win.tick
    new_rings = []
    new_totals = []
    for lv, ring, total in zip(levels, win.rings, win.totals):
        slot = (t // lv.stride_ticks) % lv.nslots
        boundary = (t % lv.stride_ticks) == 0
        # at a stride boundary the slab at `slot` expires and is replaced
        ring = ring.at[slot].set(
            jnp.where(boundary, win.cur, ring[slot] + win.cur))
        # resync the rolling total from the ring at each boundary: float32
        # add/subtract drift would otherwise accumulate over the 5-day
        # level's 86,400 ticks (ADVICE r1). Off-boundary: cheap increment.
        total = jnp.where(boundary, ring.sum(axis=0), total + win.cur)
        new_rings.append(ring)
        new_totals.append(total)
    return MultiWindow(
        cur=jnp.zeros_like(win.cur),
        rings=tuple(new_rings),
        totals=tuple(new_totals),
        alltime=win.alltime + win.cur,
        tick=t + 1,
    )


def read(win: MultiWindow, level: int):
    """Windowed sum for a level: -1 = current base slab, len(levels) = all."""
    if level == -1:
        return win.cur
    if level < len(win.totals):
        return win.totals[level] + win.cur
    return win.alltime + win.cur


# ---------------------------------------------------------------- numpy ref
class NpMultiWindow:
    """Exact sliding-window reference (stores every base slab)."""

    def __init__(self, shape, levels=LEVELS_DEFAULT):
        self.levels = levels
        self.slabs = []          # closed base slabs, oldest first
        self.cur = np.zeros(shape, np.float64)

    def add(self, delta):
        self.cur = self.cur + delta

    def tick(self):
        self.slabs.append(self.cur)
        self.cur = np.zeros_like(self.cur)

    def read(self, level: int):
        if level == -1:
            return self.cur
        if level < len(self.levels):
            lv = self.levels[level]
            # the device ring covers the slabs since the oldest *unexpired*
            # sub-slab boundary — coverage oscillates in
            # [span - stride + 1, span] base ticks (dips right after a
            # stride boundary expires a whole sub-slab at once).
            if not self.slabs:
                return self.cur.copy()
            # the ring's content is fixed by the LAST processed tick index:
            # slab i survives iff its slot wasn't overwritten since, i.e.
            # (t_last//stride - i//stride) < nslots  (replay reference).
            t_last = len(self.slabs) - 1
            keep = np.zeros_like(self.cur)
            for i, s in enumerate(self.slabs):
                age = (t_last // lv.stride_ticks) - (i // lv.stride_ticks)
                if age < lv.nslots:
                    keep = keep + s
            return keep + self.cur
        return sum(self.slabs, np.zeros_like(self.cur)) + self.cur


class NpTrueSlidingWindow:
    """Independent oracle: an exact trailing-span sliding window.

    Unlike ``NpMultiWindow`` (which replays device ring semantics), this is
    the spec-level answer: the sum of exactly the last ``span_ticks`` closed
    base slabs plus the open one. Device reads must match it within ±stride
    base ticks of slab mass (tests assert bracketing between the true sums
    over span-stride and span ticks).
    """

    def __init__(self, shape, levels=LEVELS_DEFAULT):
        self.levels = levels
        self.slabs = []
        self.cur = np.zeros(shape, np.float64)

    def add(self, delta):
        self.cur = self.cur + delta

    def tick(self):
        self.slabs.append(self.cur)
        self.cur = np.zeros_like(self.cur)

    def read_span(self, n_ticks: int):
        """Exact sum over the trailing ``n_ticks`` closed slabs + open slab."""
        tail = self.slabs[-n_ticks:] if n_ticks > 0 else []
        return sum(tail, np.zeros_like(self.cur)) + self.cur
