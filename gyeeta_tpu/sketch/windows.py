"""Multi-resolution time windows over additive sketch state.

Tensor analogue of ``folly::MultiLevelTimeSeries`` as used by
``TIME_HISTOGRAM`` (``common/gy_statistics.h:1083``) with the reference's
canonical level set ``Level_5s_5min_5days_all`` (:1545): every statistic is
readable over the last 5 s, last 5 min, last 5 days, and process lifetime.

Design: the engine ticks at a fixed base cadence (default 5 s — the service
state cadence, ``gy_socket_stat.cc:152``). Each level above the base is a ring
of ``nslots`` sub-slabs plus a rolling ``total``; on tick the just-finished
base slab is added into every level's current sub-slab, and when a level's
stride boundary passes, its ring advances and the expired sub-slab is
subtracted from the rolling total. All branch-free (``jnp.where`` on tick
predicates) so the whole thing lives inside the jitted update step.

Works over any *additive* state array (loghist slabs, CMS tensors, packed
stat columns). Non-additive sketches (HLL max-merge) use the same ring but
``maximum`` recombine at query time instead of a rolling total.
"""

from __future__ import annotations

from typing import NamedTuple, Sequence

import jax.numpy as jnp
import numpy as np


class WindowSpec(NamedTuple):
    """One level: covers ``stride_ticks * nslots`` base ticks."""
    stride_ticks: int  # base ticks per sub-slab
    nslots: int        # ring length

    @property
    def span_ticks(self) -> int:
        return self.stride_ticks * self.nslots


# 5s base tick; 5min = 60 ticks (12 slabs of 25s); 5day = 86400 ticks
# (24 slabs of 1h). "all" is a plain accumulator, handled separately.
LEVELS_5S_5MIN_5DAYS: tuple[WindowSpec, ...] = (
    WindowSpec(stride_ticks=5, nslots=12),      # 5 min, 25 s resolution
    WindowSpec(stride_ticks=3600, nslots=24),   # 1 day×5 ≈ 5d? no: 24h ring
)
# NOTE: 5-day coverage needs stride 18000 (25h) × 24; we pick 1-day ring for
# HBM economy and document the deviation; the historical path (Postgres tier)
# serves longer horizons, as in the reference (SURVEY §2.7 Postgres row).
LEVELS_DEFAULT: tuple[WindowSpec, ...] = (
    WindowSpec(stride_ticks=5, nslots=12),      # 5 min
    WindowSpec(stride_ticks=18000, nslots=24),  # 5 days, 25 h resolution
)


class MultiWindow(NamedTuple):
    """Windowed view of one additive state array of shape ``shape``.

    cur:    (shape) slab being filled this base tick
    rings:  tuple of (nslots, *shape) per level
    totals: tuple of (shape) rolling per-level totals
    alltime:(shape) lifetime accumulator
    tick:   () int32 — base ticks since start
    """
    cur: jnp.ndarray
    rings: tuple
    totals: tuple
    alltime: jnp.ndarray
    tick: jnp.ndarray


def init(shape: tuple, levels: Sequence[WindowSpec] = LEVELS_DEFAULT,
         dtype=jnp.float32) -> MultiWindow:
    return MultiWindow(
        cur=jnp.zeros(shape, dtype),
        rings=tuple(jnp.zeros((lv.nslots,) + tuple(shape), dtype)
                    for lv in levels),
        totals=tuple(jnp.zeros(shape, dtype) for _ in levels),
        alltime=jnp.zeros(shape, dtype),
        tick=jnp.zeros((), jnp.int32),
    )


def add(win: MultiWindow, delta) -> MultiWindow:
    """Accumulate into the current base slab (called per microbatch)."""
    return win._replace(cur=win.cur + delta)


def tick(win: MultiWindow, levels: Sequence[WindowSpec] = LEVELS_DEFAULT
         ) -> MultiWindow:
    """Close the current base slab: fold into every level, advance rings."""
    t = win.tick
    new_rings = []
    new_totals = []
    for lv, ring, total in zip(levels, win.rings, win.totals):
        slot = (t // lv.stride_ticks) % lv.nslots
        boundary = (t % lv.stride_ticks) == 0
        # at a stride boundary the slab at `slot` expires: subtract + clear
        expired = jnp.where(boundary, ring[slot], jnp.zeros_like(win.cur))
        ring = ring.at[slot].set(
            jnp.where(boundary, win.cur, ring[slot] + win.cur))
        total = total - expired + win.cur
        new_rings.append(ring)
        new_totals.append(total)
    return MultiWindow(
        cur=jnp.zeros_like(win.cur),
        rings=tuple(new_rings),
        totals=tuple(new_totals),
        alltime=win.alltime + win.cur,
        tick=t + 1,
    )


def read(win: MultiWindow, level: int):
    """Windowed sum for a level: -1 = current base slab, len(levels) = all."""
    if level == -1:
        return win.cur
    if level < len(win.totals):
        return win.totals[level] + win.cur
    return win.alltime + win.cur


# ---------------------------------------------------------------- numpy ref
class NpMultiWindow:
    """Exact sliding-window reference (stores every base slab)."""

    def __init__(self, shape, levels=LEVELS_DEFAULT):
        self.levels = levels
        self.slabs = []          # closed base slabs, oldest first
        self.cur = np.zeros(shape, np.float64)

    def add(self, delta):
        self.cur = self.cur + delta

    def tick(self):
        self.slabs.append(self.cur)
        self.cur = np.zeros_like(self.cur)

    def read(self, level: int):
        if level == -1:
            return self.cur
        if level < len(self.levels):
            lv = self.levels[level]
            # the device ring covers: slabs since the oldest *unexpired*
            # sub-slab boundary — between span and span+stride slabs.
            n = len(self.slabs)
            t = n  # current tick index
            # replicate device semantics exactly:
            keep = np.zeros_like(self.cur)
            for i, s in enumerate(self.slabs):
                slot_of_i = (i // lv.stride_ticks) % lv.nslots
                # slab i is retained iff its slot hasn't been overwritten:
                age_strides = (t // lv.stride_ticks) - (i // lv.stride_ticks)
                if age_strides < lv.nslots:
                    keep = keep + s
            return keep + self.cur
        return sum(self.slabs, np.zeros_like(self.cur)) + self.cur
