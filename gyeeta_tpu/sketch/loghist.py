"""Log-bucketed histogram — the workhorse quantile sketch.

This is the direct tensor analogue of the reference's bucketed histograms
(``GY_HISTOGRAM`` ``common/gy_statistics.h:553`` with fixed threshold tables
like ``RESP_TIME_HASH`` :1677 — 15 buckets, 1ms–15s — and percentile
interpolation), generalized to geometric buckets fine enough for <2% relative
quantile error (DDSketch-style guarantee: midpoint interpolation bounds the
relative error by (γ-1)/2).

State is ``(..., B)`` counts with arbitrary leading entity axes — one row per
tracked service/host — so a single scatter-add per microbatch updates
thousands of per-entity histograms at once (replacing per-listener
``resp_hist_`` pointer walks). Merge is ``+`` → psum roll-up.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
import numpy as np


class LogHistSpec(NamedTuple):
    vmin: float
    vmax: float
    nbuckets: int

    @property
    def gamma(self) -> float:
        return float((self.vmax / self.vmin) ** (1.0 / self.nbuckets))

    @property
    def rel_error(self) -> float:
        """Guaranteed max relative quantile error (midpoint interpolation)."""
        g = self.gamma
        return (g - 1.0) / (g + 1.0)


# Response-time spec: 10us .. 100s. gamma≈1.0328 → ≤1.7% error.
RESP_TIME_SPEC = LogHistSpec(vmin=1e-5, vmax=100.0, nbuckets=512)
# QPS / rate spec, mirrors HASH_10_5000 (gy_statistics.h:1908) but geometric.
RATE_SPEC = LogHistSpec(vmin=0.1, vmax=1e7, nbuckets=256)
# Generic percent 0..100 (PERCENT_HASH :1624) — linear is fine via log trick
PERCENT_SPEC = LogHistSpec(vmin=0.5, vmax=100.0, nbuckets=128)


def init(spec: LogHistSpec, entities: tuple = (), dtype=jnp.float32):
    return jnp.zeros(entities + (spec.nbuckets,), dtype=dtype)


def bucket_of(spec: LogHistSpec, values):
    """values -> bucket index [0, B). Values below vmin clamp to 0, above
    vmax clamp to B-1. Works for jnp and np arrays."""
    xp = np if isinstance(values, np.ndarray) else jnp
    v = xp.maximum(values.astype(xp.float32), spec.vmin)
    inv_log_gamma = 1.0 / np.log(spec.gamma)
    b = xp.floor(xp.log(v / spec.vmin) * inv_log_gamma).astype(xp.int32)
    return xp.clip(b, 0, spec.nbuckets - 1)


def bucket_mid(spec: LogHistSpec, bucket):
    """Geometric midpoint of each bucket (the <2%-error estimator)."""
    xp = np if isinstance(bucket, np.ndarray) else jnp
    g = spec.gamma
    return spec.vmin * xp.exp(
        (bucket.astype(xp.float32) + 0.5) * np.float32(np.log(g))
    )


def update(hist, spec: LogHistSpec, values, weights=None, valid=None):
    """Global histogram (no entity axis) scatter-add."""
    b = bucket_of(spec, values)
    w = jnp.ones_like(values, dtype=hist.dtype) if weights is None \
        else weights.astype(hist.dtype)
    if valid is not None:
        w = jnp.where(valid, w, jnp.zeros_like(w))
    return hist.at[b].add(w)


def update_entities(hist, spec: LogHistSpec, entity_row, values,
                    weights=None, valid=None):
    """Per-entity scatter-add at (row, bucket)."""
    b = bucket_of(spec, values)
    w = jnp.ones_like(values, dtype=hist.dtype) if weights is None \
        else weights.astype(hist.dtype)
    if valid is not None:
        w = jnp.where(valid, w, jnp.zeros_like(w))
        entity_row = jnp.where(valid, entity_row, 0)
    return hist.at[entity_row, b].add(w)


def quantiles(hist, spec: LogHistSpec, qs):
    """Quantile estimates per entity.

    hist: (..., B); qs: (Q,) in [0,1]. Returns (..., Q) float32.
    Mirrors the reference's percentile interpolation
    (``get_percentile_locked``, gy_statistics.h) but vectorized over all
    entities and quantiles at once. Empty histograms return 0.
    """
    qs = jnp.asarray(qs, dtype=jnp.float32)
    cdf = jnp.cumsum(hist.astype(jnp.float32), axis=-1)        # (..., B)
    tot = cdf[..., -1:]                                        # (..., 1)
    target = qs * tot                                          # (..., Q)
    # first bucket where cdf >= target
    ge = cdf[..., None, :] >= target[..., :, None] - 1e-6      # (..., Q, B)
    idx = jnp.argmax(ge, axis=-1).astype(jnp.int32)            # (..., Q)
    val = bucket_mid(spec, idx)
    return jnp.where(tot > 0, val, 0.0)


def merge(a, b):
    return a + b


def counts_total(hist):
    return hist.sum(axis=-1)


def mean(hist, spec: LogHistSpec):
    mids = bucket_mid(spec, jnp.arange(spec.nbuckets, dtype=jnp.int32))
    tot = hist.sum(axis=-1)
    s = (hist.astype(jnp.float32) * mids).sum(axis=-1)
    return jnp.where(tot > 0, s / jnp.maximum(tot, 1.0), 0.0)


# ---------------------------------------------------------------- numpy ref
def np_update(hist: np.ndarray, spec: LogHistSpec, values, weights=None):
    b = bucket_of(spec, np.asarray(values, dtype=np.float32))
    w = np.ones_like(values) if weights is None else weights
    np.add.at(hist, b, w)
    return hist
