"""Invertible heavy-flow sketch: recover heavy keys FROM device state.

The aggregation sketches (CMS / top-K) answer "how much did key k
move?" but cannot enumerate the heavy keys themselves — the top-K table
only knows keys that survived its per-dispatch admission. This module
is the invertible tier of the heavy-hitter subsystem (PAPERS.md:
*A Fast and Compact Invertible Sketch for Network-Wide Heavy Flow
Detection*, arXiv 1910.10441; priority-aware admission per *PSketch*,
arXiv 2509.07338): a ``(depth, width)`` bucket array where each bucket
remembers ONE candidate key — the key with the highest CMS-estimate
priority that ever hashed there — so per-tick decoding recovers the
heavy keys directly from the sketch, no candidate list.

Bucket contents (struct-of-arrays, all ``(depth, width)``):

- ``prio``    — the candidate's priority at its last write (its CMS
  upper-bound estimate; the PSketch angle: hot flows hold buckets,
  cold flows share them). Priorities only grow, so each bucket
  converges to the heaviest-by-estimate key among its colliders.
- ``enc_hi``/``enc_lo`` — the candidate key halves, XOR-folded with a
  fingerprint-derived mask (see :func:`encode_key`): decoding XORs the
  mask back and a corrupted/torn bucket fails the fingerprint check
  instead of yielding a plausible-looking garbage key.
- ``fp``      — the candidate's 32-bit key fingerprint (independent
  hash stream), verified at decode together with the bucket position
  re-hash (a decoded key must hash INTO its own bucket).

Update is pure scatter-max / masked scatter-set — it rides the fused
``fold_all`` dispatch with zero extra dispatches, and the ``prio``
scatter-max routes through the Pallas hand-kernel prototype when
``GYT_PALLAS=1`` (``sketch/pallas_scatter.py``), exactly like the
CMS/HLL updates. Bucket mass totals are deliberately NOT tracked: the
CMS next door already accounts every lane's mass, so a per-bucket
vsum would duplicate the most expensive scatter in the fold for a
signal the error bounds never read. The candidate-replacement write resolves a
unique winner per bucket via lexicographic (priority, key_hi, key_lo)
scatter-max rounds, so the result is order-insensitive within a batch
and bit-identical between the fused and legacy fold paths.

Decode (:func:`decode` / :func:`decode_keys`) is a read-only jitted
pass: un-fold the keys, verify fingerprint + bucket position, and
point-query the CMS for each candidate — one dispatch, one small
readback per tick. Recovered counts are CMS upper bounds; the honest
per-key error term is :func:`cms_error_term` (≤ 2·N/width with
probability 1−2^−depth per key — Markov per row, min over rows).
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
import numpy as np

from gyeeta_tpu.utils import hashing as H

# independent hash streams: per-row bucket salts and the fingerprint
# stream must not be correlated with the CMS rows (0xC035/0x51ED) or
# the flow-key mix — a shared stream would make CMS collisions and
# bucket collisions coincide, defeating the min-over-rows verification
_SALT_BUCKET = 0x1B5E12A7
_SALT_FP = 0x7F4A7C15
_MASK_HI = 0xA5A5A5A5
_MASK_LO = 0x5A5A5A5A


class InvSketch(NamedTuple):
    prio: jnp.ndarray     # (d, w) f32 candidate priority (CMS estimate)
    enc_hi: jnp.ndarray   # (d, w) uint32 XOR-folded key high half
    enc_lo: jnp.ndarray   # (d, w) uint32 XOR-folded key low half
    fp: jnp.ndarray       # (d, w) uint32 candidate fingerprint
    n_hot: jnp.ndarray    # () f32 lanes at/above the hot threshold


def init(depth: int = 2, width: int = 4096) -> InvSketch:
    return InvSketch(
        prio=jnp.zeros((depth, width), jnp.float32),
        enc_hi=jnp.zeros((depth, width), jnp.uint32),
        enc_lo=jnp.zeros((depth, width), jnp.uint32),
        fp=jnp.zeros((depth, width), jnp.uint32),
        n_hot=jnp.zeros((), jnp.float32),
    )


def fingerprint(key_hi, key_lo):
    """32-bit key fingerprint on its own hash stream (np + jnp)."""
    return H.mix64(key_hi, key_lo, _SALT_FP)


def buckets(key_hi, key_lo, depth: int, width: int) -> list:
    """Per-row bucket indices on the invertible tier's own salts."""
    return [H.bucket_index(key_hi, key_lo, _SALT_BUCKET + r, width)
            for r in range(depth)]


def encode_key(key_hi, key_lo, fp):
    """XOR-fold the key halves with fingerprint-derived masks. A bucket
    whose (enc, fp) fields ever disagree (corruption, torn write)
    decodes to a key whose fingerprint cannot match — decode drops it
    instead of surfacing garbage."""
    if isinstance(fp, np.ndarray):
        with np.errstate(over="ignore"):
            return (key_hi ^ H.fmix32(fp ^ np.uint32(_MASK_HI)),
                    key_lo ^ H.fmix32(fp ^ np.uint32(_MASK_LO)))
    return (key_hi ^ H.fmix32(fp ^ jnp.uint32(_MASK_HI)),
            key_lo ^ H.fmix32(fp ^ jnp.uint32(_MASK_LO)))


def decode_key(enc_hi, enc_lo, fp):
    """Inverse of :func:`encode_key` (XOR is its own inverse)."""
    return encode_key(enc_hi, enc_lo, fp)


def update(sk: InvSketch, key_hi, key_lo, prio, valid,
           hot=None, budget: int = 0) -> InvSketch:
    """Fold a batch of key lanes with per-lane ``prio``.

    ``prio`` is the lane's admission priority — the CMS upper-bound
    estimate of its flow's cumulative mass (``countmin.upper_bound``
    issued after the batch's CMS fold), so a bucket's candidate is
    always the estimated-heaviest collider, not the last writer.
    ``hot``: optional bool mask counting lanes at/above the hot
    admission threshold (pure accounting — surfaced as a health gauge).

    ``budget``: sketch-assisted candidate compaction (the same trick
    as ``topk.update``): only the ``budget`` highest-priority lanes
    enter the candidate-write scatters — a lane can only WIN a bucket
    while its estimate ranks high, and duplicate lanes of one flow
    share its flow-level estimate, so the selection is flow-wise. Hot
    counting always sees every lane. 0 = every lane competes.

    All ops are scatters over the flattened (d·w) buffers; candidate
    replacement resolves one unique winner per bucket per batch via
    lexicographic (prio, key_hi, key_lo) scatter-max rounds — ties
    between duplicate lanes of ONE key write identical values, so the
    result never depends on scatter application order.
    """
    import jax

    d, w = sk.prio.shape
    key_hi = key_hi.astype(jnp.uint32)
    key_lo = key_lo.astype(jnp.uint32)
    pr = jnp.where(valid, prio.astype(jnp.float32), 0.0)
    n = key_hi.shape[0]
    n_hot = sk.n_hot
    if hot is not None:
        # full-batch accounting — counted BEFORE candidate compaction
        n_hot = n_hot + jnp.sum(valid & hot).astype(jnp.float32)
    from gyeeta_tpu.sketch import pallas_scatter as _ps
    if 0 < budget < n:
        score = jnp.where(valid, pr, -1.0)
        _, sel = jax.lax.top_k(score, budget)
        key_hi, key_lo = key_hi[sel], key_lo[sel]
        pr = jnp.where(score[sel] >= 0, pr[sel], 0.0)
        valid = valid[sel] & (score[sel] >= 0)
    bks = buckets(key_hi, key_lo, d, w)
    flat_idx = jnp.concatenate([b + r * w for r, b in enumerate(bks)])
    if _ps.enabled():
        prio_new = _ps.scatter_max(sk.prio, flat_idx, jnp.tile(pr, d))
    else:
        prio_new = sk.prio.reshape(-1).at[flat_idx].max(
            jnp.tile(pr, d)).reshape(d, w)

    fp_l = fingerprint(key_hi, key_lo)
    e_hi, e_lo = encode_key(key_hi, key_lo, fp_l)
    enc_hi, enc_lo, fps = sk.enc_hi, sk.enc_lo, sk.fp
    rows_ehi, rows_elo, rows_fp = [], [], []
    for r, b in enumerate(bks):
        # winners: lanes that achieved the bucket's NEW max priority
        # AND strictly raised it (an unchallenged incumbent stays put)
        win = valid & (pr == prio_new[r, b]) & (pr > sk.prio[r, b])
        # lexicographic tie-break between distinct keys at equal
        # priority: scatter-max key_hi among winners, then key_lo —
        # surviving winner lanes of one bucket all carry the SAME key
        mh = jnp.zeros((w,), jnp.uint32).at[b].max(
            jnp.where(win, key_hi, jnp.uint32(0)))
        win = win & (key_hi == mh[b])
        ml = jnp.zeros((w,), jnp.uint32).at[b].max(
            jnp.where(win, key_lo, jnp.uint32(0)))
        win = win & (key_lo == ml[b])
        lanes = jnp.where(win, b, w)          # w = dropped lane
        rows_ehi.append(enc_hi[r].at[lanes].set(e_hi, mode="drop"))
        rows_elo.append(enc_lo[r].at[lanes].set(e_lo, mode="drop"))
        rows_fp.append(fps[r].at[lanes].set(fp_l, mode="drop"))
    return InvSketch(
        prio=prio_new, enc_hi=jnp.stack(rows_ehi),
        enc_lo=jnp.stack(rows_elo), fp=jnp.stack(rows_fp),
        n_hot=n_hot)


def decode_keys(sk: InvSketch):
    """Un-fold every bucket's candidate → (khi, klo, ok), all (d, w).

    ``ok`` is the invertibility verification: the bucket is occupied,
    its decoded key's fingerprint matches the stored one, and the key
    re-hashes INTO its own bucket position on that row's hash stream —
    a corrupted bucket can pass neither check by accident (~2^-44).
    """
    d, w = sk.prio.shape
    khi, klo = decode_key(sk.enc_hi, sk.enc_lo, sk.fp)
    ok = (sk.prio > 0) & (fingerprint(khi, klo) == sk.fp)
    pos = jnp.arange(w, dtype=jnp.int32)
    for r in range(d):
        ok = ok.at[r].set(
            ok[r] & (H.bucket_index(khi[r], klo[r], _SALT_BUCKET + r, w)
                     == pos))
    return khi, klo, ok


def decode(sk: InvSketch, cms):
    """Full recovery pass: decoded candidates + their CMS point
    estimates, flattened to (d·w,) host-ready arrays. One jitted
    dispatch; the caller reads back four small arrays per tick."""
    from gyeeta_tpu.sketch import countmin

    khi, klo, ok = decode_keys(sk)
    hi_f, lo_f = khi.reshape(-1), klo.reshape(-1)
    est = countmin.query(cms, hi_f, lo_f).astype(jnp.float32)
    est = jnp.where(ok.reshape(-1), est, 0.0)
    return {"hh_hi": hi_f, "hh_lo": lo_f, "hh_ok": ok.reshape(-1),
            "hh_est": est}


def merge(a: InvSketch, b: InvSketch) -> InvSketch:
    """Bucket-wise merge: the higher-priority candidate wins each
    bucket (same rule as the streaming update); n_hot adds."""
    take_b = b.prio > a.prio
    return InvSketch(
        prio=jnp.maximum(a.prio, b.prio),
        enc_hi=jnp.where(take_b, b.enc_hi, a.enc_hi),
        enc_lo=jnp.where(take_b, b.enc_lo, a.enc_lo),
        fp=jnp.where(take_b, b.fp, a.fp),
        n_hot=a.n_hot + b.n_hot)


def cms_error_term(total_mass, width: int):
    """Per-key CMS overestimate bound: err ≤ 2·N/width w.p. 1−2^−depth
    (Markov per row at the halving point, min over rows). This is the
    "invertible-array error term" every recovered topk row carries —
    recovered counts are upper bounds; exact top-K lanes carry the
    ``evicted`` undercount bound instead."""
    return 2.0 * total_mass / max(int(width), 1)


def merge_recovered_np(rec: dict, err_term: float,
                       hot_thresh: float = 0.0):
    """Host half of per-tick recovery: merge the exact top-K lanes with
    the decoded candidates → the heavy-flow view every query edge
    serves.

    ``rec``: the numpy readback of :func:`gyeeta_tpu.engine.step.
    heavy_recover` (topk_hi/lo/counts/est + hh_hi/lo/ok/est). Every
    row's value is an UPPER bound on the key's true total (it never
    undercounts, w.p. 1−2^−depth), with the overcount bounded by the
    row's own ``errbound``:

    - exact lanes: truth ∈ [count, est] — value = max(count, est) with
      errbound = value − count. The exact counter's job is TIGHTENING
      the bound: the longer a key stays admitted, the closer count
      tracks est and the smaller its error bar.
    - recovered-only candidates: value = est with errbound =
      ``err_term`` (the invertible-array term, :func:`cms_error_term`).

    Returns ``(flow_rows, recovered_ids, hot_ids)``: rows as
    ``(id_hex, value, errbound, source)`` heaviest-first (value desc,
    id asc on ties — deterministic across runs), the recovered key-id
    set, and the recovered ids at/above ``hot_thresh`` (the promotion
    candidates).
    """
    t_hi = np.asarray(rec["topk_hi"], np.uint64)
    t_lo = np.asarray(rec["topk_lo"], np.uint64)
    t_cnt = np.asarray(rec["topk_counts"], np.float64)
    t_est = np.asarray(rec["topk_est"], np.float64)
    m = t_cnt > 0
    exact_ids = (t_hi[m] << np.uint64(32)) | t_lo[m]
    rows = []
    for k, cnt, est in zip(exact_ids.tolist(), t_cnt[m].tolist(),
                           t_est[m].tolist()):
        val = max(cnt, est)
        rows.append((format(int(k), "016x"), float(val),
                     float(val - cnt), "exact"))
    exact_set = set(exact_ids.tolist())

    c_ok = np.asarray(rec["hh_ok"], bool)
    c_hi = np.asarray(rec["hh_hi"], np.uint64)[c_ok]
    c_lo = np.asarray(rec["hh_lo"], np.uint64)[c_ok]
    c_est = np.asarray(rec["hh_est"], np.float64)[c_ok]
    cand = {}
    for k, v in zip(((c_hi << np.uint64(32)) | c_lo).tolist(),
                    c_est.tolist()):
        if v > 0 and k not in exact_set:
            cand[k] = max(cand.get(k, 0.0), v)
    recovered_ids = set(cand)
    hot_ids = {k for k, v in cand.items() if v >= hot_thresh} \
        if hot_thresh > 0 else set(recovered_ids)
    rows.extend((format(k, "016x"), float(v), float(err_term),
                 "recovered") for k, v in cand.items())
    rows.sort(key=lambda r: (-r[1], r[0]))
    return rows, recovered_ids, hot_ids


# ---------------------------------------------------------------- numpy ref
def np_update(prio, enc_hi, enc_lo, fp, key_hi, key_lo, prios):
    """Host reference of one batch fold (tests): per bucket, the
    lexicographic-max (prio, key_hi, key_lo) lane wins, and replaces
    the incumbent only when it strictly raises the stored priority —
    the batch-level rule the vectorized scatters implement."""
    d, w = prio.shape
    key_hi = np.asarray(key_hi, np.uint32)
    key_lo = np.asarray(key_lo, np.uint32)
    bks = buckets(key_hi, key_lo, d, w)
    with np.errstate(over="ignore"):
        fps = np.asarray(fingerprint(key_hi, key_lo))
        e_hi, e_lo = encode_key(key_hi, key_lo, fps)
    for r in range(d):
        b = np.asarray(bks[r])
        per_bucket: dict = {}
        for i in range(len(key_hi)):
            j = int(b[i])
            cand = (float(prios[i]), int(key_hi[i]), int(key_lo[i]), i)
            if j not in per_bucket or cand[:3] > per_bucket[j][:3]:
                per_bucket[j] = cand
        for j, (p, _hi, _lo, i) in per_bucket.items():
            if p > prio[r, j]:
                prio[r, j] = p
                enc_hi[r, j] = e_hi[i]
                enc_lo[r, j] = e_lo[i]
                fp[r, j] = fps[i]
    return prio, enc_hi, enc_lo, fp
