"""State and issue-source enums (ref: ``common/gy_json_field_maps.h:242``
OBJ_STATE_E, :419 LISTENER_ISSUE_SRC)."""

STATE_IDLE = 0
STATE_GOOD = 1
STATE_OK = 2
STATE_BAD = 3
STATE_SEVERE = 4
STATE_DOWN = 5

STATE_NAMES = ("Idle", "Good", "OK", "Bad", "Severe", "Down")

ISSUE_NONE = 0
ISSUE_TASKS = 1           # ISSUE_LISTENER_TASKS
ISSUE_QPS_HIGH = 2
ISSUE_ACTIVE_CONN_HIGH = 3
ISSUE_SERVER_ERRORS = 4
ISSUE_OS_CPU = 5
ISSUE_OS_MEMORY = 6

ISSUE_NAMES = ("none", "listener_tasks", "qps_high", "active_conn_high",
               "server_errors", "os_cpu", "os_memory")

# process-group (aggregate task) issue sources
# (ref TASK_ISSUE_SOURCE, common/gy_json_field_maps.h:317)
TISSUE_NONE = 0
TISSUE_CPU_DELAY = 1
TISSUE_BLKIO_DELAY = 2
TISSUE_VM_DELAY = 3
TISSUE_HIGH_CPU = 4
TISSUE_HIGH_RSS = 5

TASK_ISSUE_NAMES = ("none", "cpu_delay", "blkio_delay", "vm_delay",
                    "high_cpu", "high_rss")

# host cpu/mem issue sources of the 2s path
# (ref CPU_ISSUE_SOURCE/MEM_ISSUE_SOURCE, common/gy_sys_stat.h:131)
CISSUE_NONE = 0
CISSUE_CPU_SATURATED = 1
CISSUE_CORE_SATURATED = 2
CISSUE_IOWAIT = 3
CISSUE_CONTEXT_SWITCH = 4
CISSUE_FORKS = 5
CISSUE_PROCS_RUNNING = 6

CPU_ISSUE_NAMES = ("none", "cpu_saturated", "core_saturated", "iowait",
                   "context_switch", "new_forks", "procs_running")

MISSUE_NONE = 0
MISSUE_RSS = 1
MISSUE_COMMIT = 2
MISSUE_SWAP_FULL = 3
MISSUE_SWAP_IO = 4
MISSUE_RECLAIM_STALLS = 5
MISSUE_PAGE_IO = 6
MISSUE_OOM_KILL = 7

MEM_ISSUE_NAMES = ("none", "rss_pct", "commit_pct", "swap_full",
                   "swap_io", "reclaim_stalls", "page_io", "oom_kill")
