"""Host and cluster state rollups.

Host: replicates ``TCP_SOCK_HANDLER::host_status_update``
(``common/gy_socket_stat.cc:4455``): combines host cpu/mem issue flags with
per-host counts of task/listener issues into one 6-state label — vectorized
over the whole host panel.

Cluster: the shyama aggregate (``server/gy_shconnhdlr.cc:4583``
aggregate_cluster_state) — counts of hosts per state plus totals — computed
from the same panel (optionally the ``psum``-merged panel of a mesh rollup).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from gyeeta_tpu.semantic.states import (
    STATE_IDLE, STATE_GOOD, STATE_OK, STATE_BAD, STATE_SEVERE,
)


def classify_hosts(ntask_issue, ntask_severe, nlisten_issue, nlisten_severe,
                   cpu_issue, mem_issue, severe_cpu, severe_mem,
                   cpu_idle=None):
    """→ (H,) int32 host states. Rule order mirrors the reference exactly."""
    xp = jnp if isinstance(ntask_issue, jnp.ndarray) else np
    H = ntask_issue.shape
    if cpu_idle is None:
        cpu_idle = xp.zeros(H, bool)
    any_cpu_mem = cpu_issue | mem_issue
    any_entity = (ntask_issue > 0) | (nlisten_issue > 0)

    state = xp.full(H, STATE_OK, np.int32)  # reference fallback (:4529)
    decided = xp.zeros(H, bool)

    def rule(cond, st):
        nonlocal state, decided
        take = cond & ~decided
        state = xp.where(take, st, state)
        decided = decided | take

    # severe everywhere (:4462)
    rule(((ntask_severe > 0) | (nlisten_severe > 0))
         & (severe_cpu | severe_mem), STATE_SEVERE)
    # totally clean (:4468)
    rule(~any_cpu_mem & ~any_entity & cpu_idle, STATE_IDLE)
    rule(~any_cpu_mem & ~any_entity, STATE_GOOD)
    # entity issues + host pressure (:4478)
    rule(any_entity & any_cpu_mem
         & ((ntask_issue > 5) | (nlisten_issue > 5)), STATE_SEVERE)
    rule(any_entity & any_cpu_mem, STATE_BAD)
    # host pressure only (:4488)
    rule(any_cpu_mem & (severe_cpu | severe_mem), STATE_BAD)
    rule(any_cpu_mem, STATE_OK)
    # listener issues only (:4498)
    rule((nlisten_issue > 0) & ((nlisten_severe > 0) | (ntask_issue > 0))
         & (nlisten_issue > 5), STATE_SEVERE)
    rule((nlisten_issue > 0) & ((nlisten_severe > 0) | (ntask_issue > 0)),
         STATE_BAD)
    rule(nlisten_issue > 2, STATE_BAD)
    rule(nlisten_issue > 0, STATE_OK)
    # task issues only (:4518)
    rule((ntask_issue > 0) & ((ntask_severe > 0) | (ntask_issue > 5)),
         STATE_BAD)
    rule(ntask_issue > 0, STATE_OK)
    return state


def cluster_state(host_states, valid=None):
    """Counts of hosts per state + issue ratio (the MS_CLUSTER_STATE
    payload, ``common/gy_comm_proto.h:3181``). → dict of () scalars."""
    xp = jnp if isinstance(host_states, jnp.ndarray) else np
    if valid is None:
        valid = xp.ones(host_states.shape, bool)
    counts = [xp.sum(valid & (host_states == st)).astype(np.int32)
              for st in range(6)]
    n_up = xp.sum(valid).astype(np.int32)
    n_issue = counts[STATE_BAD] + counts[STATE_SEVERE]
    return {
        "nhosts": n_up,
        "nidle": counts[STATE_IDLE],
        "ngood": counts[STATE_GOOD],
        "nok": counts[STATE_OK],
        "nbad": counts[STATE_BAD],
        "nsevere": counts[STATE_SEVERE],
        "ndown": counts[5],
        "issue_frac": n_issue / xp.maximum(n_up, 1),
    }
