"""Derive classifier signals from live AggState + run the 5s classify pass.

The tensor equivalent of the reference's 5-second ``listener_stats_update``
sweep (``common/gy_socket_stat.cc:3898``): for every service row at once,
read current/historical percentiles out of the sketch state, build
``SvcSignals``, run the rule cascade, and store the resulting state/issue
(and the 8-tick high-response bit history) back into the engine state.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from gyeeta_tpu.engine.aggstate import AggState, EngineCfg
from gyeeta_tpu.ingest import decode as D
from gyeeta_tpu.semantic import svcstate
from gyeeta_tpu.sketch import loghist, windows

_QS = (0.95, 0.99)


def _popcount8(x):
    return sum((x >> k) & 1 for k in range(8))


def signals(cfg: EngineCfg, st: AggState):
    """AggState → (SvcSignals, high_resp_now) over all service rows."""
    spec = cfg.resp_spec
    qs = jnp.asarray(_QS, jnp.float32)
    h5 = st.resp_win.cur                       # current 5s slab
    h300 = windows.read(st.resp_win, 0)        # 5 min
    h5day = windows.read(st.resp_win, 1)       # 5 days
    q5 = loghist.quantiles(h5, spec, qs)
    q300 = loghist.quantiles(h300, spec, qs)
    q5day = loghist.quantiles(h5day, spec, qs)

    b5 = loghist.bucket_of(spec, q5[:, 0])
    b300 = loghist.bucket_of(spec, q300[:, 0])
    b5day = loghist.bucket_of(spec, q5day[:, 0])
    # static bucket of 1ms (resp values are usec) — same formula as
    # loghist.bucket_of, computed in python at trace time
    import math
    b_1ms = int(min(spec.nbuckets - 1, max(0, math.floor(
        math.log(max(1000.0, spec.vmin) / spec.vmin)
        / math.log(spec.gamma)))))

    nqrys = loghist.counts_total(h5)
    gauges = st.svc_stats
    # engine-resident query count: prefer live resp samples; fall back to
    # the agent-reported gauge when the resp stream is sampled out
    nqrys = jnp.maximum(nqrys, gauges[:, D.STAT_NQRYS])
    curr_qps = nqrys / 5.0

    qps_q = loghist.quantiles(st.qps_hist, cfg.qps_spec,
                              jnp.asarray([0.95, 0.25], jnp.float32))
    act_q = loghist.quantiles(st.active_hist, cfg.active_spec,
                              jnp.asarray([0.95, 0.25], jnp.float32))

    ntasks = gauges[:, D.STAT_NTASKS]
    ntasks_issue = gauges[:, D.STAT_NTASKS_ISSUE]
    delay_ms = (gauges[:, D.STAT_TASKS_DELAY_US]
                + gauges[:, D.STAT_TASKS_CPUDELAY_US]
                + gauges[:, D.STAT_TASKS_BLKIODELAY_US]) / 1000.0

    # task-tier join: fold the process-group sweeps into per-service
    # signals via related_listen_id (the reference joins MAGGR_TASK →
    # MTCP_LISTENER through related_listen_id_ and feeds listener task
    # counts from it). Segment-sum over the svc slab; elementwise max with
    # the listener gauges (same underlying facts, different paths — the
    # fresher/stronger signal wins, never double-counts).
    from gyeeta_tpu.engine import table as _table
    task_live = _table.live_mask(st.task_tbl)
    rel_rows = _table.lookup(st.tbl, st.task_rel_hi, st.task_rel_lo,
                             valid=task_live)
    tgt = jnp.where(rel_rows >= 0, rel_rows, cfg.svc_capacity)
    tstats = st.task_stats
    t_issue_by_svc = jnp.zeros((cfg.svc_capacity,), jnp.float32).at[tgt].add(
        tstats[:, D.TASK_NTASKS_ISSUE], mode="drop")
    t_ntasks_by_svc = jnp.zeros((cfg.svc_capacity,), jnp.float32).at[tgt].add(
        tstats[:, D.TASK_NTASKS], mode="drop")
    t_delay_by_svc = jnp.zeros((cfg.svc_capacity,), jnp.float32).at[tgt].add(
        tstats[:, D.TASK_CPU_DELAY_MS] + tstats[:, D.TASK_VM_DELAY_MS]
        + tstats[:, D.TASK_BLKIO_DELAY_MS], mode="drop")
    ntasks = jnp.maximum(ntasks, t_ntasks_by_svc)
    ntasks_issue = jnp.maximum(ntasks_issue, t_issue_by_svc)
    delay_ms = jnp.maximum(delay_ms, t_delay_by_svc)
    # simplified is_task_issue (ref gy_socket_stat.h:699): any flagged task
    # is an issue; severe when every task is flagged or delays are heavy
    task_issue = ntasks_issue > 0
    task_severe = task_issue & ((ntasks_issue >= ntasks)
                                | (delay_ms >= 1000.0))
    task_delay = delay_ms > 0

    # host pressure flags looked up through the service→host mapping
    hostz = jnp.clip(st.svc_host, 0, cfg.n_hosts - 1)
    has_host = st.svc_host >= 0
    cpu_issue = has_host & (
        st.host_panel[hostz, D.HOST_CPU_ISSUE] > 0)
    mem_issue = has_host & (
        st.host_panel[hostz, D.HOST_MEM_ISSUE] > 0)

    mean5 = loghist.mean(h5, spec)
    mean5day = loghist.mean(h5day, spec)

    low = (b5 <= b_1ms) | (q5[:, 0] < q5day[:, 0])
    same = b5 == b5day
    high_now = ~low & ~same

    sig = svcstate.SvcSignals(
        b5=b5, b300=b300, b5day=b5day,
        r5p95=q5[:, 0], r5p99=q5[:, 1],
        r5dayp95=q5day[:, 0], r5dayp99=q5day[:, 1],
        mean5=mean5, mean5day=mean5day,
        nqrys_5s=nqrys, curr_qps=curr_qps,
        qps_p95=qps_q[:, 0], qps_p25=qps_q[:, 1],
        curr_active=gauges[:, D.STAT_NCONNS_ACTIVE],
        active_p95=act_q[:, 0], active_p25=act_q[:, 1],
        nconn=gauges[:, D.STAT_NCONNS],
        ser_errors=gauges[:, D.STAT_SER_ERRORS],
        task_issue=task_issue, task_severe=task_severe,
        task_delay=task_delay,
        ntasks_issue=ntasks_issue,
        ntasks_noissue=jnp.maximum(ntasks - ntasks_issue, 0.0),
        tasks_delay_msec=delay_ms,
        total_resp_msec=gauges[:, D.STAT_TOTAL_RESP_MS],
        cpu_issue=cpu_issue, mem_issue=mem_issue,
        high_resp_ticks=_popcount8(
            ((st.resp_hi_bits << 1)
             | high_now.astype(jnp.int32)) & 0xFF),
        b_1ms=b_1ms,
    )
    return sig, high_now


def classify_pass(cfg: EngineCfg, st: AggState):
    """One 5s classification sweep → updated AggState (state/issue/bits)."""
    sig, high_now = signals(cfg, st)
    state, issue = svcstate.classify(sig)
    from gyeeta_tpu.engine import table
    live = table.live_mask(st.tbl)
    state = jnp.where(live, state, 0)
    issue = jnp.where(live, issue, 0)
    bits = ((st.resp_hi_bits << 1) | high_now.astype(jnp.int32)) & 0xFF
    return st._replace(svc_state=state, svc_issue=issue, resp_hi_bits=bits)


def jit_classify_pass(cfg: EngineCfg):
    return jax.jit(partial(classify_pass, cfg), donate_argnums=(0,))
