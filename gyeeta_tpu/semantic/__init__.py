"""Semantic layer: self-learning health classification as tensor rules.

The product feature the sketches feed — the reference classifies every
service (``TCP_LISTENER::get_curr_state``, ``common/gy_socket_stat.cc:2020``)
and host (``host_status_update``, :4455) into six states
(Idle/Good/OK/Bad/Severe/Down, ``common/gy_json_field_maps.h:242``) by
comparing *current* percentiles against the service's own *historical*
percentile baselines. Here the whole fleet classifies in one jitted
first-match-wins rule cascade over (S,) columns.
"""

import importlib

from gyeeta_tpu.semantic.states import (
    STATE_IDLE, STATE_GOOD, STATE_OK, STATE_BAD, STATE_SEVERE, STATE_DOWN,
    ISSUE_NONE, ISSUE_TASKS, ISSUE_QPS_HIGH, ISSUE_ACTIVE_CONN_HIGH,
    ISSUE_SERVER_ERRORS, ISSUE_OS_CPU, ISSUE_OS_MEMORY, STATE_NAMES,
    ISSUE_NAMES,
)

__all__ = [
    "STATE_IDLE", "STATE_GOOD", "STATE_OK", "STATE_BAD", "STATE_SEVERE",
    "STATE_DOWN", "ISSUE_NONE", "ISSUE_TASKS", "ISSUE_QPS_HIGH",
    "ISSUE_ACTIVE_CONN_HIGH", "ISSUE_SERVER_ERRORS", "ISSUE_OS_CPU",
    "ISSUE_OS_MEMORY", "STATE_NAMES", "ISSUE_NAMES", "svcstate", "hoststate",
    "derive",
]


def __getattr__(name):
    # the classifier modules import jax; agents only need the state
    # constants above, so keep the jax side lazy (thin clients must
    # never initialize an accelerator backend)
    if name in ("svcstate", "hoststate", "derive", "cpumem"):
        return importlib.import_module(f"gyeeta_tpu.semantic.{name}")
    raise AttributeError(name)
