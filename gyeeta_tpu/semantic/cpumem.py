"""Host CPU/memory issue classification for the 2s path.

The tensor re-expression of the reference's ``SYS_CPU_STATS`` /
``SYS_MEM_STATS`` analyzers (``common/gy_sys_stat.h:131``,
``common/gy_sys_stat.cc`` cpu/mem issue scans): every 2s sweep, raw host
gauges are judged against saturation thresholds and each host gets a
(state, issue-source) pair per dimension. The reference walks per-host
ring buffers one CPU at a time; here the whole fleet classifies in one
branch-free pass — rules ordered most-severe-first exactly like the
service-state cascade.

Severity model (mirrors the reference's Bad/Severe split):
- **Severe**: hard saturation (cpu ≳ 98%, OOM kill, swap exhausted while
  swapping, reclaim stalls).
- **Bad**: sustained pressure (cpu ≳ 90%, iowait, hot core, fork/runq
  storms; rss/commit beyond watermark, heavy paging).
- **OK**: elevated but sub-threshold (≥ 70% cpu / ≥ 75% rss).
- **Good / Idle**: quiet.
"""

from __future__ import annotations

import jax.numpy as jnp

from gyeeta_tpu.ingest import decode as D
from gyeeta_tpu.semantic import states as S


def classify_cpu(vals):
    """(H, NCM) gauges → (state, issue) int32 per host (CPU dimension)."""
    cpu = vals[:, D.CM_CPU_PCT]
    core = vals[:, D.CM_MAX_CORE_CPU_PCT]
    iow = vals[:, D.CM_IOWAIT_PCT]
    cs = vals[:, D.CM_CS_SEC]
    forks = vals[:, D.CM_FORKS_SEC]
    runq = vals[:, D.CM_PROCS_RUNNING]
    ncpu = jnp.maximum(vals[:, D.CM_NCPUS], 1.0)

    sev_cpu = cpu >= 98.0
    bad_cpu = cpu >= 90.0
    ok_cpu = cpu >= 70.0
    bad_core = core >= 95.0
    bad_iow = iow >= 25.0
    sev_iow = iow >= 50.0
    bad_cs = cs >= 100_000.0 * ncpu
    bad_forks = forks >= 300.0
    bad_runq = runq >= 4.0 * ncpu

    issue = jnp.full(cpu.shape, S.CISSUE_NONE, jnp.int32)
    state = jnp.full(cpu.shape, S.STATE_GOOD, jnp.int32)
    state = jnp.where(cpu < 10.0, S.STATE_IDLE, state)
    state = jnp.where(ok_cpu, S.STATE_OK, state)

    def rule(cond, st, isrc, state, issue):
        hit = cond & (issue == S.CISSUE_NONE)
        return (jnp.where(hit, st, state), jnp.where(hit, isrc, issue))

    # most-severe-first; first hit wins the issue source
    state, issue = rule(sev_cpu, S.STATE_SEVERE, S.CISSUE_CPU_SATURATED,
                        state, issue)
    state, issue = rule(sev_iow, S.STATE_SEVERE, S.CISSUE_IOWAIT,
                        state, issue)
    state, issue = rule(bad_cpu, S.STATE_BAD, S.CISSUE_CPU_SATURATED,
                        state, issue)
    state, issue = rule(bad_iow, S.STATE_BAD, S.CISSUE_IOWAIT,
                        state, issue)
    state, issue = rule(bad_core, S.STATE_BAD, S.CISSUE_CORE_SATURATED,
                        state, issue)
    state, issue = rule(bad_cs, S.STATE_BAD, S.CISSUE_CONTEXT_SWITCH,
                        state, issue)
    state, issue = rule(bad_forks, S.STATE_BAD, S.CISSUE_FORKS,
                        state, issue)
    state, issue = rule(bad_runq, S.STATE_BAD, S.CISSUE_PROCS_RUNNING,
                        state, issue)
    return state, issue


def classify_mem(vals):
    """(H, NCM) gauges → (state, issue) int32 per host (memory)."""
    rss = vals[:, D.CM_RSS_PCT]
    commit = vals[:, D.CM_COMMIT_PCT]
    swap_free = vals[:, D.CM_SWAP_FREE_PCT]
    pgio = vals[:, D.CM_PG_INOUT_SEC]
    swapio = vals[:, D.CM_SWAP_INOUT_SEC]
    stalls = vals[:, D.CM_ALLOCSTALL_SEC]
    oom = vals[:, D.CM_OOM_KILLS]

    issue = jnp.full(rss.shape, S.MISSUE_NONE, jnp.int32)
    state = jnp.full(rss.shape, S.STATE_GOOD, jnp.int32)
    state = jnp.where(rss >= 75.0, S.STATE_OK, state)

    def rule(cond, st, isrc, state, issue):
        hit = cond & (issue == S.MISSUE_NONE)
        return (jnp.where(hit, st, state), jnp.where(hit, isrc, issue))

    state, issue = rule(oom > 0, S.STATE_SEVERE, S.MISSUE_OOM_KILL,
                        state, issue)
    state, issue = rule((swap_free <= 5.0) & (swapio > 0),
                        S.STATE_SEVERE, S.MISSUE_SWAP_FULL, state, issue)
    state, issue = rule(stalls >= 50.0, S.STATE_SEVERE,
                        S.MISSUE_RECLAIM_STALLS, state, issue)
    state, issue = rule(commit >= 95.0, S.STATE_BAD, S.MISSUE_COMMIT,
                        state, issue)
    state, issue = rule(rss >= 90.0, S.STATE_BAD, S.MISSUE_RSS,
                        state, issue)
    state, issue = rule(swapio >= 100.0, S.STATE_BAD, S.MISSUE_SWAP_IO,
                        state, issue)
    state, issue = rule(pgio >= 10_000.0, S.STATE_BAD, S.MISSUE_PAGE_IO,
                        state, issue)
    return state, issue
