"""Vectorized service health classifier.

Replicates the rule structure of ``TCP_LISTENER::get_curr_state``
(``common/gy_socket_stat.cc:2020-2780``) — the reference's self-learning
percentile heuristic — as one first-match-wins rule cascade over (S,)
columns, jitted for the whole service fleet at once.

The learning signal is identical: the service's *own* history is the
baseline (5s p95 vs 5-day p95 response buckets, current QPS vs p95/p25
historical QPS, current active conns vs their percentiles). Rules fire in
the reference's priority order; each rule's condition is the conjunction of
its branch path in the original tree.

Documented deviations (TPU-first simplifications, same spirit):
- bucket comparisons use the engine's geometric loghist bucket index
  (``sketch/loghist.bucket_of``) instead of RESP_TIME_HASH's 15 fixed
  thresholds — finer resolution, same "within N buckets" semantics;
- the reference's final per-bucket active-conn scan (nactive_conn_arr_,
  :2711) and the 8-tick high-resp persistence check (:2750) fold into one
  ``high_resp_ticks`` input (count of recent high-response ticks) supplied
  by the engine's issue bit history;
- one reference fall-through quirk (OK state labeled with the overwritten
  LISTENER_TASKS issue after a missing return, :2419) is emitted as the
  evidently-intended OK/SERVER_ERRORS.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
import numpy as np

from gyeeta_tpu.semantic.states import (
    STATE_IDLE, STATE_GOOD, STATE_OK, STATE_BAD, STATE_SEVERE,
    ISSUE_NONE, ISSUE_TASKS, ISSUE_QPS_HIGH, ISSUE_ACTIVE_CONN_HIGH,
    ISSUE_SERVER_ERRORS,
)


class SvcSignals(NamedTuple):
    """Per-service classifier inputs, all (S,) float32/bool arrays.

    Response percentiles are loghist *bucket indices* (resolution-free
    comparisons); qps/active percentiles are plain values.
    """
    b5: jnp.ndarray            # bucket of 5s-window p95 response
    b300: jnp.ndarray          # bucket of 5min-window p95
    b5day: jnp.ndarray        # bucket of 5day-window p95
    r5p95: jnp.ndarray         # raw p95 values (usec)
    r5p99: jnp.ndarray
    r5dayp95: jnp.ndarray
    r5dayp99: jnp.ndarray
    mean5: jnp.ndarray         # 5s-window mean response
    mean5day: jnp.ndarray
    nqrys_5s: jnp.ndarray      # queries in current 5s window
    curr_qps: jnp.ndarray
    qps_p95: jnp.ndarray       # historical qps percentiles (learned)
    qps_p25: jnp.ndarray
    curr_active: jnp.ndarray   # current active conns
    active_p95: jnp.ndarray
    active_p25: jnp.ndarray
    nconn: jnp.ndarray         # total conns
    ser_errors: jnp.ndarray    # server errors in window
    task_issue: jnp.ndarray    # bool — process-level issue (delays/cpu)
    task_severe: jnp.ndarray   # bool
    task_delay: jnp.ndarray    # bool — delay-type issue
    ntasks_issue: jnp.ndarray
    ntasks_noissue: jnp.ndarray
    tasks_delay_msec: jnp.ndarray
    total_resp_msec: jnp.ndarray
    cpu_issue: jnp.ndarray     # bool — host cpu issue
    mem_issue: jnp.ndarray     # bool
    high_resp_ticks: jnp.ndarray  # recent high-response tick count (0..8)
    b_1ms: int = 0             # bucket index of 1 ms (static threshold)


def classify(s: SvcSignals):
    """→ (state, issue): (S,) int32 each. First matching rule wins."""
    xp = jnp if isinstance(s.b5, jnp.ndarray) else np
    S = s.b5.shape
    state = xp.full(S, STATE_BAD, xp.int32 if xp is jnp else np.int32)
    issue = xp.full(S, ISSUE_NONE, xp.int32 if xp is jnp else np.int32)
    decided = xp.zeros(S, bool)

    rules = []

    def rule(cond, st, isrc):
        rules.append((cond, st, isrc))

    err = s.ser_errors
    nq = s.nqrys_5s
    many_err = err * 2 > nq
    some_err = err * 5 > nq
    has_err = err > 0
    ti = s.task_issue

    # ---- idle gate (:2125) -------------------------------------------------
    rule((s.curr_qps == 0) & ~(ti & s.task_severe & has_err),
         STATE_IDLE, ISSUE_NONE)

    # ---- branch A: low response (:2141) -----------------------------------
    low = (s.b5 <= s.b_1ms) | (s.r5p95 < s.r5dayp95)
    qps_low = (s.curr_qps <= s.qps_p25) & (s.qps_p25 < s.qps_p95)

    a1 = low & qps_low
    rule(a1 & ~ti & ~has_err, STATE_IDLE, ISSUE_NONE)
    rule(a1 & many_err, STATE_SEVERE, ISSUE_SERVER_ERRORS)
    rule(a1 & some_err, STATE_BAD, ISSUE_SERVER_ERRORS)
    rule(a1 & ~ti & has_err & (err < nq * 0.1), STATE_OK,
         ISSUE_SERVER_ERRORS)
    rule(a1 & ti & has_err, STATE_BAD, ISSUE_TASKS)
    rule(a1 & ti & s.task_severe & (s.ntasks_issue > 0)
         & (s.ntasks_noissue == 0), STATE_BAD, ISSUE_TASKS)
    rule(a1 & ti & (s.nconn > s.active_p25), STATE_OK, ISSUE_TASKS)

    rule(low & many_err, STATE_SEVERE, ISSUE_SERVER_ERRORS)
    rule(low & some_err, STATE_BAD, ISSUE_SERVER_ERRORS)
    rule(low & ti & s.task_severe & (s.ntasks_issue > 0)
         & (s.ntasks_noissue == 0), STATE_BAD, ISSUE_TASKS)
    rule(low & ~has_err & ((s.curr_qps <= s.qps_p95)
                           | (s.b5 + 2 <= s.b5day)), STATE_GOOD, ISSUE_NONE)
    rule(low & ~has_err, STATE_OK, ISSUE_QPS_HIGH)   # qps > p95
    rule(low, STATE_OK, ISSUE_SERVER_ERRORS)

    # ---- branch B: response equals the historical baseline (:2309) --------
    same = s.b5 == s.b5day
    rule(same & many_err, STATE_SEVERE, ISSUE_SERVER_ERRORS)
    rule(same & some_err, STATE_BAD, ISSUE_SERVER_ERRORS)

    b2 = same & (s.mean5 <= s.mean5day * 0.8)
    b2_qlow = b2 & (s.curr_qps <= s.qps_p25)
    rule(b2_qlow & has_err, STATE_BAD, ISSUE_SERVER_ERRORS)
    rule(b2_qlow & ~ti, STATE_IDLE, ISSUE_NONE)
    rule(b2_qlow & (s.ntasks_issue > 0) & (s.ntasks_noissue == 0),
         STATE_BAD, ISSUE_TASKS)
    rule(b2_qlow & (s.ntasks_issue > 0) & (s.tasks_delay_msec >= 1000),
         STATE_BAD, ISSUE_TASKS)
    rule(b2 & ~ti & ~has_err, STATE_GOOD, ISSUE_NONE)
    rule(b2 & has_err & ti, STATE_BAD, ISSUE_TASKS)
    rule(b2 & has_err, STATE_OK, ISSUE_SERVER_ERRORS)
    rule(b2, STATE_OK, ISSUE_TASKS)

    rule(same & (s.mean5 <= s.mean5day * 1.2), STATE_OK, ISSUE_NONE)

    # ---- high-response section (:2437) ------------------------------------
    rule(many_err, STATE_SEVERE, ISSUE_SERVER_ERRORS)
    rule(some_err, STATE_BAD, ISSUE_SERVER_ERRORS)

    much_higher = (s.b5 > s.b5day + 2) & (s.b5 > s.b300)
    qps_high = ((s.curr_qps > s.qps_p95)
                & (s.curr_qps - s.qps_p95 > 5)
                & (s.curr_qps > s.qps_p95 * 1.1))
    rule(qps_high & much_higher, STATE_SEVERE, ISSUE_QPS_HIGH)
    rule(qps_high, STATE_BAD, ISSUE_QPS_HIGH)

    task_like = ti | (s.task_delay
                      & (s.ntasks_issue + s.ntasks_noissue > 2)
                      & (s.tasks_delay_msec * 4 > s.total_resp_msec))
    rule(task_like & much_higher, STATE_SEVERE, ISSUE_TASKS)
    rule(task_like, STATE_BAD, ISSUE_TASKS)

    act_high = ((s.curr_active > s.active_p95)
                & (s.curr_active - s.active_p95 > 1))
    rule(act_high & much_higher & (s.curr_active > 10), STATE_SEVERE,
         ISSUE_ACTIVE_CONN_HIGH)
    rule(act_high, STATE_BAD, ISSUE_ACTIVE_CONN_HIGH)

    # outliers only: p95 same but p99 worse → a few slow queries (:2556)
    rule(same & (s.r5p99 > s.r5dayp99), STATE_OK, ISSUE_NONE)

    # low qps + low conns + bounded degradation (:2662)
    calm = ((s.curr_qps <= s.qps_p25) & (s.curr_active <= s.active_p25)
            & (s.b5 <= s.b5day + 1))
    rule(calm & s.task_delay & s.cpu_issue & s.mem_issue, STATE_BAD,
         ISSUE_TASKS)
    rule(calm & s.task_delay & (s.cpu_issue | s.mem_issue)
         & (s.tasks_delay_msec * 4 > s.total_resp_msec), STATE_BAD,
         ISSUE_TASKS)
    rule(calm & has_err, STATE_OK, ISSUE_SERVER_ERRORS)
    rule(calm, STATE_OK, ISSUE_NONE)

    # transient: 5s worse but 5min == 5day (:2685)
    transient = ((s.b5 <= s.b5day + 1) & (s.b300 == s.b5day)
                 & (s.mean5 > s.mean5day) & has_err)
    rule(transient, STATE_OK, ISSUE_SERVER_ERRORS)
    rule((s.b5 <= s.b5day + 1) & (s.b300 == s.b5day)
         & (s.mean5 > s.mean5day), STATE_OK, ISSUE_NONE)

    # not persistent: high resp for < 5 of the last 8 ticks (:2750)
    rule(s.high_resp_ticks < 5, STATE_OK, ISSUE_NONE)

    # final: genuinely degraded (:2774)
    rule(much_higher & (s.tasks_delay_msec * 4 > s.total_resp_msec),
         STATE_SEVERE, ISSUE_TASKS)
    rule(much_higher, STATE_SEVERE, ISSUE_NONE)
    rule((s.tasks_delay_msec * 4 > s.total_resp_msec), STATE_BAD,
         ISSUE_TASKS)

    for cond, st, isrc in rules:
        take = cond & ~decided
        state = xp.where(take, st, state)
        issue = xp.where(take, isrc, issue)
        decided = decided | take
    # anything undecided: Bad with no attributed source (reference default)
    return state, issue


def np_classify(s: SvcSignals):
    """Numpy twin of ``classify`` (same cascade, used as the test oracle
    for scalar-loop cross-checks)."""
    return classify(SvcSignals(*[np.asarray(x) if not isinstance(x, int)
                                 else x for x in s]))
