"""Deterministic multi-agent event generator.

Models a fleet of ``n_hosts`` agents, each exposing ``n_svcs`` listening
services (glob_ids) and a population of client endpoints. Emits the three
hot record streams of the reference protocol (SURVEY §3.2, §3.3):

- TCP_CONN close notifications (flow records, zipf-heavy flow keys —
  ref ``TCP_CONN_NOTIFY`` ``common/gy_comm_proto.h:1665``),
- raw response-time samples (lognormal per-service latency with per-service
  scale — the duty-cycled eBPF response stream,
  ref ``partha/gy_ebpf_kernel_struct.h`` tcp_ipv4_resp_event_t),
- 5s LISTENER_STATE / HOST_STATE summaries (ref :2183, :2289).

All draws are vectorized numpy with a fixed seed: the same (seed, sequence of
calls) produces bit-identical streams — the replayable fixture style of the
reference's test strategy (SURVEY §4), minus the kernel.
"""

from __future__ import annotations

import numpy as np

from gyeeta_tpu.ingest import wire


class ParthaSim:
    def __init__(self, n_hosts: int = 64, n_svcs: int = 16,
                 n_clients: int = 4096, seed: int = 42,
                 zipf_a: float = 1.3, n_groups: int = 8,
                 host_base: int = 0, cli_groups_per_svc: int = 8):
        self.n_hosts = n_hosts
        self.n_svcs = n_svcs
        self.n_clients = n_clients
        self.n_groups = n_groups     # process groups per host
        self.host_base = host_base   # global id of local host 0 (net agents
        #                              construct a 1-host sim at their
        #                              server-assigned host_id)
        self.rng = np.random.default_rng(seed)
        self.zipf_a = zipf_a
        # distinct client PROCESS GROUPS calling each service: bounded
        # per-svc fan-in (a service is called by a handful of
        # deployments) — the dependency-edge working set then scales
        # with the fleet (≈ n_svcs × this), matching the reference's
        # bounded per-listener DEPENDS maps. Client IPs (flow identity,
        # HLL diversity) stay zipf over the full n_clients pool.
        self.cli_groups_per_svc = cli_groups_per_svc
        # stable 64-bit glob_ids per (host, svc): mixed so ids look like the
        # reference's hashed listener ids, not small integers; derived from
        # the GLOBAL host id so sims on different agents never collide
        hs = np.arange(host_base, host_base + n_hosts,
                       dtype=np.uint64)[:, None]
        sv = np.arange(n_svcs, dtype=np.uint64)[None, :]
        raw = (hs << np.uint64(32)) | (sv + np.uint64(1))
        self.glob_ids = _splitmix64(raw)                    # (H, S)
        # per-service latency scale: log-spaced 200us..50ms across services
        scales = np.geomspace(200.0, 50_000.0, n_svcs)
        self.svc_latency_us = np.tile(scales, (n_hosts, 1))  # (H, S)
        # client IPv4 pool per host (10.x.y.z)
        self.cli_ips = self.rng.integers(
            0x0A000000, 0x0AFFFFFF, size=(n_clients,), dtype=np.uint32)
        self.tusec = np.uint64(1_700_000_000_000_000)
        # stable process-group ids per (host, group) + interned comm ids
        hs = np.arange(host_base, host_base + n_hosts,
                       dtype=np.uint64)[:, None]
        gr = np.arange(n_groups, dtype=np.uint64)[None, :]
        self.task_ids = _splitmix64(
            (hs << np.uint64(24)) | gr | np.uint64(0x7A5C << 48))
        from gyeeta_tpu.utils.intern import InternTable
        self.comm_ids = np.array(
            [InternTable.intern(f"proc-{g}") for g in range(n_groups)],
            np.uint64)

    # ------------------------------------------------------------ streams
    def resp_records(self, n: int) -> np.ndarray:
        """n response-time samples across all hosts/services."""
        r = self.rng
        host = r.integers(0, self.n_hosts, n)
        svc = r.integers(0, self.n_svcs, n)
        scale = self.svc_latency_us[host, svc]
        lat = r.lognormal(mean=0.0, sigma=0.7, size=n) * scale
        out = np.zeros(n, wire.RESP_SAMPLE_DT)
        out["glob_id"] = self.glob_ids[host, svc]
        out["resp_usec"] = np.minimum(lat, 4e9).astype(np.uint32)
        out["host_id"] = (host + self.host_base).astype(np.uint32)
        return out

    def conn_records(self, n: int) -> np.ndarray:
        """n TCP_CONN close notifications with zipf-heavy flow keys."""
        r = self.rng
        host = r.integers(0, self.n_hosts, n)
        svc = r.integers(0, self.n_svcs, n)
        # zipf rank → client index: few clients dominate (heavy hitters)
        rank = r.zipf(self.zipf_a, n)
        cli = (rank - 1) % self.n_clients
        cli_ip = self.cli_ips[cli]
        sport = (20000 + (rank % 20000)).astype(np.uint16)
        out = np.zeros(n, wire.TCP_CONN_DT)
        _put_ipv4(out["cli"], cli_ip, sport)
        ser_ip = (0xC0A80000
                  | ((host.astype(np.uint32) + self.host_base) & 0xFFFF))
        _put_ipv4(out["ser"], ser_ip.astype(np.uint32),
                  (8000 + svc).astype(np.uint16))
        dur = (r.lognormal(1.0, 1.0, n) * 50_000).astype(np.uint64)
        out["tusec_start"] = self.tusec
        out["tusec_close"] = self.tusec + dur
        # client group: one of the svc's bounded caller deployments
        # (zipf over the pool so one deployment dominates per svc)
        grp = (rank - 1) % self.cli_groups_per_svc
        out["cli_task_aggr_id"] = _splitmix64(
            (host.astype(np.uint64) * np.uint64(131071)
             + svc.astype(np.uint64)) * np.uint64(64)
            + grp.astype(np.uint64) + np.uint64(0xABCD))
        out["ser_glob_id"] = self.glob_ids[host, svc]
        out["ser_related_listen_id"] = out["ser_glob_id"]
        nbytes = (r.pareto(1.5, n) + 1.0) * 2000.0
        out["bytes_sent"] = np.minimum(nbytes, 2**40).astype(np.uint64)
        out["bytes_rcvd"] = np.minimum(nbytes * 9.0, 2**40).astype(np.uint64)
        out["cli_pid"] = cli.astype(np.int32) + 1000
        out["ser_pid"] = svc.astype(np.int32) + 300
        out["host_id"] = (host + self.host_base).astype(np.uint32)
        # accept-observed: these are the service host's own close
        # notifications (the server side owns the listener row)
        out["flags"] = 2
        self.tusec += np.uint64(5_000_000)
        return out

    def churn_records(self, phase: int, n_conn: int = 256,
                      n_resp: int = 512, duty: int = 3):
        """One tick of DETERMINISTICALLY ROTATING traffic → (conn,
        resp) record arrays: tick ``phase`` directs all traffic at
        services where ``(svc + phase) % duty != 0``, so every
        ``duty`` ticks each service swings between loaded and idle —
        a rate/latency threshold predicate's match set visibly gains
        and loses rows every tick. The churn source the continuous-
        query tests, smoke, and bench share (natural rng drift alone
        can leave thresholds unmoved for many ticks)."""
        allowed = np.array([s for s in range(self.n_svcs)
                            if (s + phase) % duty != 0], np.int64)
        if not len(allowed):
            allowed = np.arange(self.n_svcs, dtype=np.int64)
        conn = self.conn_records(n_conn)
        resp = self.resp_records(n_resp)
        r = self.rng
        for out, n in ((conn, n_conn), (resp, n_resp)):
            host = r.integers(0, self.n_hosts, n)
            svc = allowed[r.integers(0, len(allowed), n)]
            out["host_id"] = (host + self.host_base).astype(np.uint32)
            gid = self.glob_ids[host, svc]
            if "ser_glob_id" in out.dtype.names:
                out["ser_glob_id"] = gid
                out["ser_related_listen_id"] = gid
            else:
                out["glob_id"] = gid
        return conn, resp

    def svc_call_graph(self):
        """The fleet's deterministic service→service call topology.

        Each service (h, j) calls one downstream service: a fixed
        pseudo-random permutation-ish map so cross-host edges dominate.
        Returns (cal_h, cal_j, cee_h, cee_j) flat int arrays of length
        n_hosts*n_svcs.
        """
        h = np.repeat(np.arange(self.n_hosts), self.n_svcs)
        j = np.tile(np.arange(self.n_svcs), self.n_hosts)
        cee_h = (h * 31 + j * 7 + 1) % self.n_hosts
        cee_j = (j + 1) % self.n_svcs
        return h, j, cee_h, cee_j

    def listener_info_records(self) -> np.ndarray:
        """Static metadata announcements for every listener (ref
        NEW_LISTENER path, gy_comm_proto.h:2499)."""
        n = self.n_hosts * self.n_svcs
        host = np.repeat(np.arange(self.n_hosts, dtype=np.uint32),
                         self.n_svcs)
        svc = np.tile(np.arange(self.n_svcs, dtype=np.uint32),
                      self.n_hosts)
        out = np.zeros(n, wire.LISTENER_INFO_DT)
        out["glob_id"] = self.glob_ids.reshape(-1)
        ser_ip = (0xC0A80000
                  | ((host + np.uint32(self.host_base)) & 0xFFFF))
        _put_ipv4(out["addr"], ser_ip, (8000 + svc).astype(np.uint16))
        out["tusec_start"] = self.tusec - np.uint64(3_600_000_000)
        out["comm_id"] = self.comm_ids[svc % self.n_groups]
        out["cmdline_id"] = self.comm_ids[svc % self.n_groups]
        out["related_listen_id"] = out["glob_id"]
        out["pid"] = (300 + svc).astype(np.int32)
        out["is_any_ip"] = 1
        out["is_http"] = (svc % 2 == 0)
        out["host_id"] = host + self.host_base
        return out

    def svc_conn_records(self, n: int, split_halves: bool = False,
                         nat: bool = False):
        """n service→service flows drawn from the fleet call graph.

        ``split_halves=False`` emits one record per flow carrying both
        sides (the locally-resolved case — the reference's non-shyama
        path). ``split_halves=True`` emits TWO half records per flow with
        identical 5-tuples: a connect-observed record from the caller's
        host (``ser_glob_id`` 0 — remote callee unknown) and an
        accept-observed record from the callee's host (client identity 0),
        the inputs the pairing tier joins (ref cross-madhava halves,
        ``server/gy_shconnhdlr.cc:3790``). Returns one record array, or a
        ``(cli_side, ser_side)`` tuple when ``split_halves``.
        """
        r = self.rng
        cal_h, cal_j, cee_h, cee_j = self.svc_call_graph()
        pick = r.integers(0, len(cal_h), n)
        ch, cj = cal_h[pick], cal_j[pick]
        sh, sj = cee_h[pick], cee_j[pick]
        cli_ip = (0xC0A80000
                  | ((ch.astype(np.uint32) + self.host_base) & 0xFFFF))
        ser_ip = (0xC0A80000
                  | ((sh.astype(np.uint32) + self.host_base) & 0xFFFF))
        sport = (30000 + r.integers(0, 20000, n)).astype(np.uint16)
        dport = (8000 + sj).astype(np.uint16)
        # one byte draw per FLOW: both halves must report the same totals
        nbytes = (r.pareto(1.5, n) + 1.0) * 3000.0

        def base(hs) -> np.ndarray:
            out = np.zeros(n, wire.TCP_CONN_DT)
            _put_ipv4(out["cli"], cli_ip.astype(np.uint32), sport)
            _put_ipv4(out["ser"], ser_ip.astype(np.uint32), dport)
            out["tusec_start"] = self.tusec
            out["tusec_close"] = self.tusec + np.uint64(100_000)
            out["bytes_sent"] = np.minimum(nbytes, 2**40).astype(np.uint64)
            out["bytes_rcvd"] = np.minimum(nbytes * 4, 2**40).astype(
                np.uint64)
            out["host_id"] = (hs + self.host_base).astype(np.uint32)
            return out

        cli_side = base(ch)
        cli_side["cli_task_aggr_id"] = self.task_ids[
            ch, cj % self.n_groups]
        cli_side["cli_related_listen_id"] = self.glob_ids[ch, cj]
        cli_side["flags"] = 1                    # connect-observed
        if nat:
            # callee behind a VIP: the client dials the VIP but its
            # conntrack resolves the DNAT'd tuple — the flow key must
            # come from the post-NAT view both sides share
            vip = (0x0AFE0000 | sj.astype(np.uint32))
            _put_ipv4(cli_side["ser"], vip, (80 + sj).astype(np.uint16))
            _put_ipv4(cli_side["nat_cli"], cli_ip.astype(np.uint32),
                      sport)
            _put_ipv4(cli_side["nat_ser"], ser_ip.astype(np.uint32),
                      dport)
        if not split_halves:
            cli_side["ser_glob_id"] = self.glob_ids[sh, sj]
            cli_side["ser_related_listen_id"] = cli_side["ser_glob_id"]
            self.tusec += np.uint64(1_000_000)
            return cli_side
        ser_side = base(sh)
        ser_side["ser_glob_id"] = self.glob_ids[sh, sj]
        ser_side["ser_related_listen_id"] = ser_side["ser_glob_id"]
        ser_side["flags"] = 2                    # accept-observed
        self.tusec += np.uint64(1_000_000)
        return cli_side, ser_side

    def listener_state_records(self) -> np.ndarray:
        """One 5s LISTENER_STATE sweep over every (host, svc)."""
        r = self.rng
        n = self.n_hosts * self.n_svcs
        host = np.repeat(np.arange(self.n_hosts, dtype=np.uint32),
                         self.n_svcs)
        out = np.zeros(n, wire.LISTENER_STATE_DT)
        out["glob_id"] = self.glob_ids.reshape(-1)
        qps = r.poisson(200, n)
        out["nqrys_5s"] = qps
        out["total_resp_5sec"] = (
            qps * self.svc_latency_us.reshape(-1) / 1000.0).astype(np.uint32)
        out["nconns"] = r.poisson(50, n)
        out["nconns_active"] = np.minimum(out["nconns"], r.poisson(20, n))
        out["ntasks"] = 1 + r.integers(0, 4, n)
        out["p95_5s_resp_ms"] = (
            self.svc_latency_us.reshape(-1) * 2.5 / 1000.0).astype(np.uint32)
        out["curr_kbytes_inbound"] = r.poisson(500, n)
        out["curr_kbytes_outbound"] = r.poisson(4000, n)
        out["ser_errors"] = (r.random(n) < 0.02) * r.poisson(3, n)
        out["tasks_delay_usec"] = r.poisson(100, n)
        out["host_id"] = host + self.host_base
        return out

    def aggr_task_records(self) -> np.ndarray:
        """One 5s AGGR_TASK_STATE sweep: ``n_groups`` process groups per
        host (ref AGGR_TASK_STATE_NOTIFY, gy_comm_proto.h:2114)."""
        from gyeeta_tpu.semantic import states as S
        r = self.rng
        n = self.n_hosts * self.n_groups
        host = np.repeat(np.arange(self.n_hosts, dtype=np.uint32),
                         self.n_groups)
        grp = np.tile(np.arange(self.n_groups, dtype=np.uint64),
                      self.n_hosts)
        out = np.zeros(n, wire.AGGR_TASK_DT)
        out["aggr_task_id"] = self.task_ids.reshape(-1)
        out["comm_id"] = self.comm_ids[grp]
        # groups 0..n_svcs-1 serve the corresponding listener
        svc = np.minimum(grp, self.n_svcs - 1).astype(np.int64)
        serves = grp < self.n_svcs
        out["related_listen_id"] = np.where(
            serves, self.glob_ids[host, svc], 0)
        out["tcp_kbytes"] = r.poisson(800, n) * serves
        out["tcp_conns"] = r.poisson(30, n) * serves
        cpu = (r.pareto(2.0, n) + 0.2) * 8.0
        out["total_cpu_pct"] = np.minimum(cpu, 3200.0).astype(np.float32)
        out["rss_mb"] = 64 + r.integers(0, 4096, n)
        cpu_delay = (r.random(n) < 0.06) * r.integers(50, 2000, n)
        io_delay = (r.random(n) < 0.04) * r.integers(20, 1500, n)
        out["cpu_delay_msec"] = cpu_delay
        out["blkio_delay_msec"] = io_delay
        out["vm_delay_msec"] = (r.random(n) < 0.01) * r.integers(10, 500, n)
        out["ntasks_total"] = 1 + r.integers(0, 16, n)
        # fork churn: mostly quiet groups, a heavy-tailed few (the
        # TOPFORK signal — shell/cron-style groups fork constantly)
        out["forks_sec"] = np.where(
            r.random(n) < 0.15, r.pareto(1.5, n) * 5.0, 0.0
        ).astype(np.float32)
        issue = (cpu_delay > 500) | (io_delay > 300)
        out["ntasks_issue"] = issue * (1 + r.integers(
            0, out["ntasks_total"].astype(np.int64), n))
        out["curr_state"] = np.where(
            issue, np.where(cpu_delay > 1200, S.STATE_SEVERE, S.STATE_BAD),
            np.where(out["total_cpu_pct"] > 1.0, S.STATE_OK, S.STATE_IDLE)
        ).astype(np.uint8)
        out["curr_issue"] = np.where(
            cpu_delay > 500, S.TISSUE_CPU_DELAY,
            np.where(io_delay > 300, S.TISSUE_BLKIO_DELAY,
                     S.TISSUE_NONE)).astype(np.uint8)
        out["host_id"] = host + self.host_base
        return out

    # API signature pool for the trace stream (announced via
    # name_records; ids are content hashes like the agent would compute)
    API_SIGS = ("GET /v1/items/{}", "POST /v1/items",
                "GET /v1/search", "SELECT * FROM items WHERE id=$",
                "INSERT INTO events VALUES ($)")

    def trace_records(self, n: int, err_pct: float = 0.02) -> np.ndarray:
        """n REQ_TRACE transactions over the fleet's services (the
        volume path of request tracing; the parser path is exercised by
        trace/proto.py on real byte conversations)."""
        from gyeeta_tpu.trace import PROTO_HTTP1, PROTO_POSTGRES
        from gyeeta_tpu.utils import hashing as HH

        r = self.rng
        host = r.integers(0, self.n_hosts, n)
        svc = r.integers(0, self.n_svcs, n)
        api_i = r.integers(0, len(self.API_SIGS), n)
        out = np.zeros(n, wire.REQ_TRACE_DT)
        out["svc_glob_id"] = self.glob_ids[host, svc]
        api_ids = np.array([HH.hash_bytes_np(s.encode())
                            for s in self.API_SIGS], np.uint64)
        out["api_id"] = api_ids[api_i]
        out["tusec"] = self.tusec
        lat = self.svc_latency_us[host, svc]
        out["resp_usec"] = (r.lognormal(0.0, 0.8, n) * lat).astype(
            np.uint32)
        is_sql = api_i >= 3
        err = r.random(n) < err_pct
        out["status"] = np.where(is_sql, err.astype(np.uint16),
                                 np.where(err, 500, 200))
        out["proto"] = np.where(is_sql, PROTO_POSTGRES, PROTO_HTTP1)
        out["is_error"] = err
        out["bytes_in"] = r.integers(100, 2000, n)
        out["bytes_out"] = r.integers(200, 50_000, n)
        # traced-connection identity: a handful of persistent client
        # conns per (client group, service) pair — the TRACECONN axis
        ch = r.integers(0, self.n_hosts, n)
        cg = r.integers(0, self.n_groups, n)
        cli_task = self.task_ids[ch, cg]
        out["cli_task_aggr_id"] = cli_task
        out["cli_comm_id"] = self.comm_ids[cg]
        conn_no = r.integers(0, 4, n).astype(np.uint64)
        khi = (cli_task >> np.uint64(32)).astype(np.uint32)
        klo = cli_task.astype(np.uint32) \
            ^ out["svc_glob_id"].astype(np.uint32)
        chi = HH.mix64(khi, klo, 0xC0)
        clo = HH.mix64(khi, klo, 0xC1)
        out["conn_id"] = ((chi.astype(np.uint64) << np.uint64(32))
                          | clo.astype(np.uint64)) ^ conn_no
        out["host_id"] = (host + self.host_base).astype(np.uint32)
        return out

    def trace_frames(self, n: int, only_svcs=None) -> bytes:
        """``only_svcs``: an iterable of enabled svc glob ids — records
        for other services are filtered out (the agent captures only
        where a trace definition enabled it, ref REQ_TRACE_SET)."""
        recs = self.trace_records(n)
        if only_svcs is not None:
            keep = np.isin(recs["svc_glob_id"],
                           np.fromiter(only_svcs, np.uint64,
                                       len(only_svcs)))
            recs = recs[keep]
        return b"".join(
            wire.encode_frame(wire.NOTIFY_REQ_TRACE,
                              recs[i:i + wire.MAX_TRACE_PER_BATCH])
            for i in range(0, len(recs), wire.MAX_TRACE_PER_BATCH))

    def cpu_mem_records(self, hot_cpu=(), hot_mem=()) -> np.ndarray:
        """One 2s CPU_MEM_STATE sweep. ``hot_cpu``/``hot_mem`` are local
        host indices forced into saturation (pathological fixtures for
        the server-side classifier)."""
        r = self.rng
        n = self.n_hosts
        out = np.zeros(n, wire.CPU_MEM_DT)
        cpu = np.clip(r.normal(35.0, 15.0, n), 1.0, 85.0)
        out["cpu_pct"] = cpu
        out["usercpu_pct"] = cpu * 0.7
        out["syscpu_pct"] = cpu * 0.3
        out["iowait_pct"] = np.clip(r.exponential(2.0, n), 0.0, 15.0)
        out["max_core_cpu_pct"] = np.clip(cpu * 1.5, 0.0, 90.0)
        out["cs_sec"] = r.poisson(20_000, n)
        out["forks_sec"] = r.poisson(20, n)
        out["procs_running"] = r.poisson(3, n)
        out["rss_pct"] = np.clip(r.normal(50.0, 12.0, n), 5.0, 72.0)
        out["commit_pct"] = np.clip(r.normal(60.0, 10.0, n), 10.0, 90.0)
        out["swap_free_pct"] = np.clip(r.normal(90.0, 5.0, n), 50.0, 100.0)
        out["pg_inout_sec"] = r.poisson(200, n)
        out["ncpus"] = 16.0
        hot_cpu = np.asarray(list(hot_cpu), int)
        hot_mem = np.asarray(list(hot_mem), int)
        if len(hot_cpu):
            out["cpu_pct"][hot_cpu] = 99.0
            out["usercpu_pct"][hot_cpu] = 95.0
        if len(hot_mem):
            out["rss_pct"][hot_mem] = 96.0
            out["oom_kills"][hot_mem] = 1.0
        out["host_id"] = np.arange(n, dtype=np.uint32) + self.host_base
        return out

    # fixed inventory vocabulary (interned as NAME_KIND_MISC)
    DISTROS = ("Debian 12", "Ubuntu 22.04", "AlmaLinux 9")
    KERNELS = ("6.1.0-18-amd64", "5.15.0-105-generic")
    CPUTYPES = ("Xeon-8481C", "EPYC-9B14")
    REGIONS = ("us-east1", "eu-west4")
    CGPATHS = ("/sys/fs/cgroup/system.slice", "/sys/fs/cgroup/user.slice",
               "/sys/fs/cgroup/kubepods/burstable",
               "/sys/fs/cgroup/kubepods/besteffort")

    def name_records(self) -> np.ndarray:
        """Intern announcements for every name this agent fleet uses."""
        from gyeeta_tpu.utils import hashing as HH
        from gyeeta_tpu.utils.intern import InternTable
        entries = []
        for sig in self.API_SIGS:
            entries.append((wire.NAME_KIND_API,
                            HH.hash_bytes_np(sig.encode()), sig))
        for g in range(self.n_groups):
            entries.append((wire.NAME_KIND_COMM, self.comm_ids[g],
                            f"proc-{g}"))
        for h in range(self.n_hosts):
            for s in range(self.n_svcs):
                entries.append((wire.NAME_KIND_SVC, self.glob_ids[h, s],
                                f"svc-{s}.host-{h}"))
            entries.append((wire.NAME_KIND_HOST, h, f"host-{h}.sim"))
        misc = list(self.DISTROS + self.KERNELS + self.CPUTYPES
                    + self.CGPATHS)
        for r in self.REGIONS:
            misc += [r, f"{r}-a", f"{r}-b"]
        for h in range(self.n_hosts):
            misc.append(f"i-{h + self.host_base:016x}")
        for s in misc:
            entries.append((wire.NAME_KIND_MISC,
                            InternTable.intern(s, wire.NAME_KIND_MISC), s))
        return InternTable.records(entries)

    def host_info_records(self) -> np.ndarray:
        """Static host inventory (HOST_INFO announce): deterministic per
        host id so reconnect resends are idempotent."""
        from gyeeta_tpu.utils.intern import InternTable

        def mid(s):
            return InternTable.intern(s, wire.NAME_KIND_MISC)

        n = self.n_hosts
        hs = np.arange(n) + self.host_base
        out = np.zeros(n, wire.HOST_INFO_DT)
        out["host_id"] = hs
        out["ncpus"] = 8 << (hs % 3)
        out["nnuma"] = 1 + (hs % 2)
        out["ram_mb"] = 32768 << (hs % 3)
        out["swap_mb"] = 2048
        out["boot_tusec"] = self.tusec - np.uint64(86_400_000_000)
        out["kern_ver_id"] = [mid(self.KERNELS[h % 2]) for h in hs]
        out["distro_id"] = [mid(self.DISTROS[h % 3]) for h in hs]
        out["cputype_id"] = [mid(self.CPUTYPES[h % 2]) for h in hs]
        out["instance_id"] = [mid(f"i-{h:016x}") for h in hs]
        region = [self.REGIONS[h % 2] for h in hs]
        out["region_id"] = [mid(r) for r in region]
        out["zone_id"] = [mid(f"{r}-{'ab'[h % 2]}")
                          for r, h in zip(region, hs)]
        out["virt_type"] = 1
        out["cloud_type"] = 1 + (hs % 3)
        out["is_k8s"] = (hs % 4) == 0
        return out

    def cgroup_records(self) -> np.ndarray:
        """One 5s cgroup sweep: a few tracked cgroups per host with
        utilization jitter; kubepods throttle under load."""
        from gyeeta_tpu.utils import hashing as HH
        from gyeeta_tpu.utils.intern import InternTable

        r = self.rng
        npaths = len(self.CGPATHS)
        n = self.n_hosts * npaths
        host = np.repeat(np.arange(self.n_hosts) + self.host_base, npaths)
        path_i = np.tile(np.arange(npaths), self.n_hosts)
        out = np.zeros(n, wire.CGROUP_DT)
        dir_ids = np.array([InternTable.intern(p, wire.NAME_KIND_MISC)
                            for p in self.CGPATHS], np.uint64)
        out["dir_id"] = dir_ids[path_i]
        out["cg_id"] = _splitmix64(
            (host.astype(np.uint64) << np.uint64(8))
            | path_i.astype(np.uint64))
        out["host_id"] = host
        out["is_v2"] = True
        limited = path_i >= 2                 # kubepods have cpu limits
        out["cpu_pct"] = r.random(n) * 40.0
        out["cpu_limit_pct"] = np.where(limited, 50.0, -1.0)
        throttled = limited & (r.random(n) < 0.1)
        out["cpu_throttled_pct"] = np.where(throttled,
                                            r.random(n) * 30.0, 0.0)
        out["rss_mb"] = r.random(n) * 4096.0
        out["memory_limit_mb"] = np.where(limited, 8192.0, -1.0)
        out["pgmajfault_sec"] = r.random(n) * 2.0
        out["nprocs"] = r.integers(1, 64, n)
        out["state"] = np.where(throttled, 3, 1)   # Bad when throttled
        return out

    def host_state_records(self) -> np.ndarray:
        r = self.rng
        n = self.n_hosts
        out = np.zeros(n, wire.HOST_STATE_DT)
        out["curr_time_usec"] = self.tusec
        out["ntasks"] = 100 + r.integers(0, 50, n)
        out["ntasks_issue"] = (r.random(n) < 0.1) * r.integers(1, 5, n)
        out["nlisten"] = self.n_svcs
        out["nlisten_issue"] = (r.random(n) < 0.1) * r.integers(1, 3, n)
        out["cpu_issue"] = r.random(n) < 0.05
        out["mem_issue"] = r.random(n) < 0.03
        out["host_id"] = np.arange(n, dtype=np.uint32) + self.host_base
        return out

    # --------------------------------------------------------------- wire
    def conn_frames(self, n_events: int) -> bytes:
        """n_events conn records framed into ≤2048-record messages."""
        return wire.encode_frames_chunked(
            wire.NOTIFY_TCP_CONN, self.conn_records(n_events))

    def delta_frames(self, n_conn: int, n_resp: int,
                     params: dict | None = None) -> bytes:
        """Edge pre-aggregation form of one conn+resp sweep: fold the
        records locally (``sketch/edgefold.py`` — per-svc counters,
        loghist buckets, incremental HLL register maxes, capped flow
        aggregates + residual bound, dep edges) and frame the
        mergeable NOTIFY_SKETCH_DELTA stream instead of raw tuples.
        The fold state (cumulative HLL registers) persists on the sim,
        so successive sweeps ship shrinking register deltas — the
        fixture mirror of a preagg-negotiated ``NetAgent``."""
        from gyeeta_tpu.sketch import edgefold as EF
        if getattr(self, "_edgefold", None) is None:
            self._edgefold = EF.EdgeFold(
                params if params is not None else EF.default_params(),
                host_id=self.host_base)
        return wire.encode_frames_chunked(
            wire.NOTIFY_SKETCH_DELTA,
            self._edgefold.fold_sweep(self.conn_records(n_conn),
                                      self.resp_records(n_resp)))

    def resp_frames(self, n_events: int) -> bytes:
        return wire.encode_frames_chunked(
            wire.NOTIFY_RESP_SAMPLE, self.resp_records(n_events))

    def listener_frames(self) -> bytes:
        return wire.encode_frames_chunked(
            wire.NOTIFY_LISTENER_STATE, self.listener_state_records())

    def task_frames(self) -> bytes:
        return wire.encode_frames_chunked(
            wire.NOTIFY_AGGR_TASK_STATE, self.aggr_task_records())

    def name_frames(self) -> bytes:
        return wire.encode_frames_chunked(
            wire.NOTIFY_NAME_INTERN, self.name_records())

    def host_info_frames(self) -> bytes:
        return wire.encode_frames_chunked(
            wire.NOTIFY_HOST_INFO, self.host_info_records())

    def cgroup_frames(self) -> bytes:
        return wire.encode_frames_chunked(
            wire.NOTIFY_CGROUP_STATE, self.cgroup_records())


def _splitmix64(x: np.ndarray) -> np.ndarray:
    x = np.asarray(x, np.uint64)
    with np.errstate(over="ignore"):
        x = x + np.uint64(0x9E3779B97F4A7C15)
        z = x
        z = (z ^ (z >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
        z = (z ^ (z >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
        z = z ^ (z >> np.uint64(31))
    return z


def _put_ipv4(ip_port_view: np.ndarray, ipv4: np.ndarray,
              port: np.ndarray) -> None:
    """Write IPv4-mapped addresses (::ffff:a.b.c.d) + port into IP_PORT."""
    ip = ip_port_view["ip"]
    ip[:, 10] = 0xFF
    ip[:, 11] = 0xFF
    ip[:, 12] = (ipv4 >> 24).astype(np.uint8)
    ip[:, 13] = ((ipv4 >> 16) & 0xFF).astype(np.uint8)
    ip[:, 14] = ((ipv4 >> 8) & 0xFF).astype(np.uint8)
    ip[:, 15] = (ipv4 & 0xFF).astype(np.uint8)
    ip_port_view["port"] = port
